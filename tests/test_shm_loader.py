"""Shm coworker data loader tests (reference atorch data/shm_dataloader.py
parity): batches produced in sidecar processes arrive intact through
shared memory, slots recycle, shutdown is clean.
"""

import numpy as np
import pytest

from dlrover_wuqiong_tpu.data.shm_loader import ShmCoworkerLoader


def _produce(worker_id, step):
    rng = np.random.default_rng(step)
    return {
        "input_ids": rng.integers(0, 100, (4, 8)).astype(np.int32),
        "labels": np.full((4, 8), step, np.int64),
    }


class TestShmCoworkerLoader:
    def test_batches_arrive_intact(self):
        example = _produce(0, 0)
        loader = ShmCoworkerLoader(_produce, example, num_workers=2,
                                   depth=4, max_steps=8)
        seen = []
        try:
            for batch in loader:
                assert batch["input_ids"].shape == (4, 8)
                step = int(batch["labels"][0, 0])
                np.testing.assert_array_equal(
                    batch["input_ids"], _produce(0, step)["input_ids"])
                seen.append(step)
        finally:
            loader.close()
        # every step 0..7 arrives exactly once (order may interleave)
        assert sorted(seen) == list(range(8))

    def test_slot_recycling_beyond_depth(self):
        example = _produce(0, 0)
        loader = ShmCoworkerLoader(_produce, example, num_workers=1,
                                   depth=2, max_steps=10)
        count = 0
        try:
            for batch in loader:
                count += 1
        finally:
            loader.close()
        assert count == 10  # 10 batches through 2 slots

    def test_clean_shutdown_midstream(self):
        example = _produce(0, 0)
        loader = ShmCoworkerLoader(_produce, example, num_workers=2,
                                   depth=3, max_steps=-1)
        got = next(loader)
        assert got["input_ids"].shape == (4, 8)
        loader.close()  # must not hang with producers running

"""Checkpoint replica manager tests.

Mirrors reference `dlrover/trainer/tests/torch/checkpoint_backup_test.py`
(backup/gather) — the kill-node test proves restore from a peer without a
storage read.
"""

import numpy as np
import pytest

from dlrover_wuqiong_tpu.checkpoint.replica import (
    CkptReplicaManager,
    ReplicaServer,
)
from dlrover_wuqiong_tpu.checkpoint.shm_handler import SharedMemoryHandler


@pytest.fixture()
def two_nodes():
    servers = [ReplicaServer(), ReplicaServer()]
    for s in servers:
        s.start()
    peers = {r: f"127.0.0.1:{s.port}" for r, s in enumerate(servers)}
    managers = [
        CkptReplicaManager(rank=r, peers=peers, job_name=f"t-rep{r}",
                           replica_count=1)
        for r in range(2)
    ]
    yield servers, peers, managers
    for m in managers:
        m.close()
    for r in range(2):
        SharedMemoryHandler(0, f"t-rep{r}").unlink()
    for s in servers:
        s.stop()


class TestReplica:
    def test_ring_backup_and_peer_restore(self, two_nodes):
        servers, peers, (m0, m1) = two_nodes
        state = {"w": np.arange(64, dtype=np.float32).reshape(8, 8),
                 "b": np.ones(8, np.float32)}
        shm0 = SharedMemoryHandler(0, "t-rep0")
        shm0.save_state_dict(state, step=7)
        assert m0.backup() == 1  # shipped to rank 1's server

        # node 0 dies: wipe its shm, a replacement manager restores from
        # the peer WITHOUT any storage involved
        shm0.unlink()
        m0b = CkptReplicaManager(rank=0, peers=peers, job_name="t-rep0",
                                 replica_count=1)
        step = m0b.restore()
        assert step == 7
        restored = SharedMemoryHandler(0, "t-rep0").load_state_dict()
        assert restored is not None
        rstep, flat, _, _ = restored
        assert rstep == 7
        np.testing.assert_array_equal(flat["w"], state["w"])
        np.testing.assert_array_equal(flat["b"], state["b"])
        m0b.close()

    def test_restore_without_backup_returns_none(self, two_nodes):
        _, peers, (m0, _) = two_nodes
        assert m0.restore() is None

    def test_backup_skips_empty_shm(self, two_nodes):
        _, _, (m0, _) = two_nodes
        assert m0.backup() == 0

    def test_ring_successors(self):
        peers = {0: "a", 1: "b", 2: "c", 3: "d"}
        m = CkptReplicaManager(rank=1, peers=peers, job_name="t-succ",
                               replica_count=2)
        assert m._successors() == [2, 3]
        m2 = CkptReplicaManager(rank=3, peers=peers, job_name="t-succ2",
                                replica_count=1)
        assert m2._successors() == [0]
        m.close()
        m2.close()

    def test_newer_backup_replaces_older(self, two_nodes):
        _, peers, (m0, m1) = two_nodes
        shm0 = SharedMemoryHandler(0, "t-rep0")
        shm0.save_state_dict({"x": np.zeros(4, np.float32)}, step=1)
        m0.backup()
        shm0.save_state_dict({"x": np.full(4, 9.0, np.float32)}, step=2)
        m0.backup()
        shm0.unlink()
        m0b = CkptReplicaManager(rank=0, peers=peers, job_name="t-rep0",
                                 replica_count=1)
        assert m0b.restore() == 2
        _, flat, _, _ = SharedMemoryHandler(0, "t-rep0").load_state_dict()
        np.testing.assert_array_equal(flat["x"], np.full(4, 9.0))
        m0b.close()

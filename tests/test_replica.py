"""Checkpoint replica manager tests.

Mirrors reference `dlrover/trainer/tests/torch/checkpoint_backup_test.py`
(backup/gather) — the kill-node test proves restore from a peer without a
storage read.
"""

import numpy as np
import pytest

from dlrover_wuqiong_tpu.checkpoint.replica import (
    CkptReplicaManager,
    ReplicaServer,
)
from dlrover_wuqiong_tpu.checkpoint.shm_handler import SharedMemoryHandler


@pytest.fixture()
def three_nodes():
    servers = [ReplicaServer(), ReplicaServer(), ReplicaServer()]
    for s in servers:
        s.start()
    peers = {r: f"127.0.0.1:{s.port}" for r, s in enumerate(servers)}
    managers = [
        CkptReplicaManager(rank=r, peers=peers, job_name=f"t-3rep{r}",
                           replica_count=1, timeout=5.0)
        for r in range(3)
    ]
    yield servers, peers, managers
    for m in managers:
        m.close()
    for r in range(3):
        SharedMemoryHandler(0, f"t-3rep{r}").unlink()
    for s in servers:
        try:
            s.stop()
        except Exception:  # noqa: BLE001 — a test may stop one mid-run
            pass


@pytest.fixture()
def two_nodes():
    servers = [ReplicaServer(), ReplicaServer()]
    for s in servers:
        s.start()
    peers = {r: f"127.0.0.1:{s.port}" for r, s in enumerate(servers)}
    managers = [
        CkptReplicaManager(rank=r, peers=peers, job_name=f"t-rep{r}",
                           replica_count=1)
        for r in range(2)
    ]
    yield servers, peers, managers
    for m in managers:
        m.close()
    for r in range(2):
        SharedMemoryHandler(0, f"t-rep{r}").unlink()
    for s in servers:
        s.stop()


class TestReplica:
    def test_ring_backup_and_peer_restore(self, two_nodes):
        servers, peers, (m0, m1) = two_nodes
        state = {"w": np.arange(64, dtype=np.float32).reshape(8, 8),
                 "b": np.ones(8, np.float32)}
        shm0 = SharedMemoryHandler(0, "t-rep0")
        shm0.save_state_dict(state, step=7)
        assert m0.backup() == 1  # shipped to rank 1's server

        # node 0 dies: wipe its shm, a replacement manager restores from
        # the peer WITHOUT any storage involved
        shm0.unlink()
        m0b = CkptReplicaManager(rank=0, peers=peers, job_name="t-rep0",
                                 replica_count=1)
        step = m0b.restore()
        assert step == 7
        restored = SharedMemoryHandler(0, "t-rep0").load_state_dict()
        assert restored is not None
        rstep, flat, _, _ = restored
        assert rstep == 7
        np.testing.assert_array_equal(flat["w"], state["w"])
        np.testing.assert_array_equal(flat["b"], state["b"])
        m0b.close()

    def test_restore_without_backup_returns_none(self, two_nodes):
        _, peers, (m0, _) = two_nodes
        assert m0.restore() is None

    def test_backup_skips_empty_shm(self, two_nodes):
        _, _, (m0, _) = two_nodes
        assert m0.backup() == 0

    def test_ring_successors(self):
        peers = {0: "a", 1: "b", 2: "c", 3: "d"}
        m = CkptReplicaManager(rank=1, peers=peers, job_name="t-succ",
                               replica_count=2)
        assert m._successors() == [2, 3]
        m2 = CkptReplicaManager(rank=3, peers=peers, job_name="t-succ2",
                                replica_count=1)
        assert m2._successors() == [0]
        m.close()
        m2.close()

    def test_ring_successors_never_own_address(self):
        # two agents x two ranks: one ReplicaServer per agent, so ranks
        # 0/1 share address "a" and ranks 2/3 share "b".  A fan-out >=
        # len(peers) must NOT route a segment back to its creator's own
        # server (a "backup" that dies with the node) nor visit one
        # address twice.
        peers = {0: "a", 1: "a", 2: "b", 3: "b"}
        m = CkptReplicaManager(rank=0, peers=peers, job_name="t-shared",
                               replica_count=4)
        assert m._successors() == [2]
        assert m._successors(count=len(peers)) == [2]
        m.close()
        # 2-node ring, both ranks on ONE server: no eligible peer at all
        solo = {0: "a", 1: "a"}
        m2 = CkptReplicaManager(rank=0, peers=solo, job_name="t-solo",
                                replica_count=2)
        assert m2._successors() == []
        m2.close()

    def test_ring_successors_zero_count(self):
        m = CkptReplicaManager(rank=0, peers={0: "a", 1: "b"},
                               job_name="t-zero", replica_count=0)
        assert m._successors() == []
        m.close()

    def test_backup_never_ships_to_own_server(self):
        # both ranks resolve to rank 0's OWN server: backup() must send
        # nothing (pre-fix it stored a self-copy and reported success)
        server = ReplicaServer()
        server.start()
        peers = {0: f"127.0.0.1:{server.port}",
                 1: f"127.0.0.1:{server.port}"}
        m0 = CkptReplicaManager(rank=0, peers=peers, job_name="t-own",
                                replica_count=1)
        try:
            shm = SharedMemoryHandler(0, "t-own")
            shm.save_state_dict({"x": np.ones(4, np.float32)}, step=3)
            assert m0.backup() == 0
            assert server._get(0) is None
        finally:
            m0.close()
            SharedMemoryHandler(0, "t-own").unlink()
            server.stop()

    def test_restore_fails_over_corrupt_holder(self, three_nodes, tmp_path):
        # rank 0 ships to both ring successors; the NEAREST holder's
        # stored blob is then corrupted in place.  restore() must report
        # + quarantine that holder and fail over to the next one instead
        # of failing the whole replica tier.
        servers, peers, (m0, m1, m2) = three_nodes
        health = []
        m0.replica_count = 2
        shm0 = SharedMemoryHandler(0, "t-3rep0")
        state = {"w": np.arange(16, dtype=np.float32)}
        shm0.save_state_dict(state, step=5)
        assert m0.backup() == 2
        step, blob = servers[1]._store[0]
        servers[1]._store[0] = (step, blob[:-4] + b"\x00\x00\x00\x00")
        shm0.unlink()
        m0b = CkptReplicaManager(rank=0, peers=peers, job_name="t-3rep0",
                                 replica_count=2,
                                 health_hook=health.append,
                                 quarantine_dir=str(tmp_path))
        try:
            assert m0b.restore() == 5
            _, flat, _, _ = SharedMemoryHandler(
                0, "t-3rep0").load_state_dict()
            np.testing.assert_array_equal(flat["w"], state["w"])
            # the skipped holder was reported and its bytes kept as
            # evidence, never silently absorbed
            assert health and "holder rank 1" in health[0]
            blobs = list(tmp_path.glob("owner0-holder1.*.blob"))
            reasons = list(tmp_path.glob("owner0-holder1.*.reason"))
            assert blobs and reasons
        finally:
            m0b.close()

    def test_restore_fails_over_dead_holder(self, three_nodes):
        servers, peers, (m0, m1, m2) = three_nodes
        m0.replica_count = 2
        shm0 = SharedMemoryHandler(0, "t-3rep0")
        shm0.save_state_dict({"w": np.full(8, 2.0, np.float32)}, step=9)
        assert m0.backup() == 2
        servers[1].stop()  # nearest holder dies with its node
        shm0.unlink()
        m0b = CkptReplicaManager(rank=0, peers=peers, job_name="t-3rep0",
                                 replica_count=2)
        try:
            assert m0b.restore() == 9
        finally:
            m0b.close()

    def test_fetch_peer_returns_verified_blob(self, three_nodes):
        # a SURVIVOR pulls the dead rank's segment from its ring holders
        # without touching its own shm — the hot-swap hydration path
        from dlrover_wuqiong_tpu.checkpoint.shm_handler import \
            blob_state_dict

        servers, peers, (m0, m1, m2) = three_nodes
        state = {"w": np.arange(12, dtype=np.float32).reshape(3, 4)}
        shm0 = SharedMemoryHandler(0, "t-3rep0")
        shm0.save_state_dict(state, step=11)
        assert m0.backup() == 1  # held by rank 1
        # rank 2 (survivor, NOT a holder) hydrates rank 0's shards
        fetched = m2.fetch_peer(0)
        assert fetched is not None
        step, blob = fetched
        assert step == 11
        parsed = blob_state_dict(blob)
        assert parsed is not None
        pstep, flat, _ = parsed
        assert pstep == 11
        np.testing.assert_array_equal(flat["w"], state["w"])
        # survivor's own shm untouched
        assert not m2.has_local_segment()

    def test_newer_backup_replaces_older(self, two_nodes):
        _, peers, (m0, m1) = two_nodes
        shm0 = SharedMemoryHandler(0, "t-rep0")
        shm0.save_state_dict({"x": np.zeros(4, np.float32)}, step=1)
        m0.backup()
        shm0.save_state_dict({"x": np.full(4, 9.0, np.float32)}, step=2)
        m0.backup()
        shm0.unlink()
        m0b = CkptReplicaManager(rank=0, peers=peers, job_name="t-rep0",
                                 replica_count=1)
        assert m0b.restore() == 2
        _, flat, _, _ = SharedMemoryHandler(0, "t-rep0").load_state_dict()
        np.testing.assert_array_equal(flat["x"], np.full(4, 9.0))
        m0b.close()

"""Local SGD / DiLoCo tests (reference atorch/local_sgd parity).

Runs on the virtual 8-device CPU mesh: dp=2 replica groups x fsdp=4.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_wuqiong_tpu.auto.accelerate import auto_accelerate
from dlrover_wuqiong_tpu.models.gpt import GPT, GPTConfig
from dlrover_wuqiong_tpu.parallel.local_sgd import (
    DiLoCoState,
    LocalSGDConfig,
    _reduce_delta,
)


def _setup(sync_every=4, reduce="mean"):
    cfg = dataclasses.replace(GPTConfig.nano(), dtype=jnp.float32,
                              use_flash_attention=False, remat=False)
    res = auto_accelerate(
        GPT(cfg),
        optimizer=optax.adam(1e-2),
        strategy=[("local_sgd", {"sync_every": sync_every,
                                 "outer_lr": 0.7, "reduce": reduce}),
                  ("data_parallel", {"size": 2}),
                  ("fsdp", {})],
        devices=jax.devices())
    data = jax.random.randint(jax.random.PRNGKey(0), (8, 33), 0,
                              cfg.vocab_size)
    batch = res.place_batch({"input_ids": data[:, :-1],
                             "labels": data[:, 1:]})
    return res, batch


def _group_params(state, g):
    return jax.tree.map(lambda x: np.asarray(x[g]), state.inner_params)


class TestDiLoCo:
    def test_groups_diverge_then_sync(self):
        res, batch = _setup(sync_every=4)
        state = res.state
        assert isinstance(state, DiLoCoState)
        # inner steps 1-3: groups see different batch shards → diverge
        for _ in range(3):
            state, m = res.train_step(state, batch)
        g0 = _group_params(state, 0)
        g1 = _group_params(state, 1)
        diffs = [np.abs(a - b).max()
                 for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1))]
        assert max(diffs) > 0, "replica groups did not diverge"
        # step 4 is the sync step: groups re-align on the outer params
        state, m = res.train_step(state, batch)
        g0 = _group_params(state, 0)
        g1 = _group_params(state, 1)
        outer = jax.tree.map(np.asarray, state.outer_params)
        for a, b, w in zip(jax.tree.leaves(g0), jax.tree.leaves(g1),
                           jax.tree.leaves(outer)):
            np.testing.assert_allclose(a, b, atol=1e-6)
            np.testing.assert_allclose(a, w, atol=1e-6)

    def test_loss_decreases_across_rounds(self):
        res, batch = _setup(sync_every=2)
        state = res.state
        losses = []
        for _ in range(12):
            state, m = res.train_step(state, batch)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0]
        assert int(state.step) == 12

    def test_requires_dp_axis(self):
        cfg = dataclasses.replace(GPTConfig.nano(), dtype=jnp.float32,
                                  use_flash_attention=False, remat=False)
        with pytest.raises(ValueError, match="dp axis"):
            auto_accelerate(GPT(cfg),
                            strategy=[("local_sgd", {}), ("fsdp", {})],
                            devices=jax.devices())


class TestReduceMethods:
    def test_gta_gates_disagreement(self):
        """Components with opposite signs across replicas are zeroed."""
        from jax.sharding import Mesh

        mesh = Mesh(np.array(jax.devices()[:2]), ("dp",))
        cfg = LocalSGDConfig(reduce="gta", gta_threshold=0.0)

        def body(d):
            return _reduce_delta({"x": d}, cfg)["x"]

        from jax.sharding import PartitionSpec as P
        from jax import shard_map

        fn = shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
                       axis_names={"dp"}, check_vma=False)
        # replica 0: [+1, +1]; replica 1: [-1, +1] → first comp gated off
        d = jnp.array([[1.0, 1.0], [-1.0, 1.0]])
        out = np.asarray(fn(d))
        np.testing.assert_allclose(out[0], [0.0, 1.0], atol=1e-6)

    def test_mean_reduce(self):
        from jax.sharding import Mesh, PartitionSpec as P
        from jax import shard_map

        mesh = Mesh(np.array(jax.devices()[:2]), ("dp",))
        cfg = LocalSGDConfig(reduce="mean")

        def body(d):
            return _reduce_delta({"x": d}, cfg)["x"]

        fn = shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
                       axis_names={"dp"}, check_vma=False)
        d = jnp.array([[2.0], [4.0]])
        np.testing.assert_allclose(np.asarray(fn(d)), [[3.0], [3.0]])

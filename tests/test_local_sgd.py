"""Local SGD / DiLoCo tests (reference atorch/local_sgd parity).

Runs on the virtual 8-device CPU mesh: dp=2 replica groups x fsdp=4.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_wuqiong_tpu.auto.accelerate import auto_accelerate
from dlrover_wuqiong_tpu.models.gpt import GPT, GPTConfig
from version_gates import requires_pinned_host, requires_shard_map
from dlrover_wuqiong_tpu.parallel.local_sgd import (
    DiLoCoState,
    LocalSGDConfig,
    _reduce_delta,
)


def _setup(sync_every=4, reduce="mean"):
    cfg = dataclasses.replace(GPTConfig.nano(), dtype=jnp.float32,
                              use_flash_attention=False, remat=False)
    res = auto_accelerate(
        GPT(cfg),
        optimizer=optax.adam(1e-2),
        strategy=[("local_sgd", {"sync_every": sync_every,
                                 "outer_lr": 0.7, "reduce": reduce}),
                  ("data_parallel", {"size": 2}),
                  ("fsdp", {})],
        devices=jax.devices())
    data = jax.random.randint(jax.random.PRNGKey(0), (8, 33), 0,
                              cfg.vocab_size)
    batch = res.place_batch({"input_ids": data[:, :-1],
                             "labels": data[:, 1:]})
    return res, batch


def _group_params(state, g):
    return jax.tree.map(lambda x: np.asarray(x[g]), state.inner_params)


class TestDiLoCo:
    @requires_shard_map
    def test_groups_diverge_then_sync(self):
        res, batch = _setup(sync_every=4)
        state = res.state
        assert isinstance(state, DiLoCoState)
        # inner steps 1-3: groups see different batch shards → diverge
        for _ in range(3):
            state, m = res.train_step(state, batch)
        g0 = _group_params(state, 0)
        g1 = _group_params(state, 1)
        diffs = [np.abs(a - b).max()
                 for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1))]
        assert max(diffs) > 0, "replica groups did not diverge"
        # step 4 is the sync step: groups re-align on the outer params
        state, m = res.train_step(state, batch)
        g0 = _group_params(state, 0)
        g1 = _group_params(state, 1)
        outer = jax.tree.map(np.asarray, state.outer_params)
        for a, b, w in zip(jax.tree.leaves(g0), jax.tree.leaves(g1),
                           jax.tree.leaves(outer)):
            np.testing.assert_allclose(a, b, atol=1e-6)
            np.testing.assert_allclose(a, w, atol=1e-6)

    @requires_shard_map
    def test_loss_decreases_across_rounds(self):
        res, batch = _setup(sync_every=2)
        state = res.state
        losses = []
        for _ in range(12):
            state, m = res.train_step(state, batch)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0]
        assert int(state.step) == 12

    def test_requires_dp_axis(self):
        cfg = dataclasses.replace(GPTConfig.nano(), dtype=jnp.float32,
                                  use_flash_attention=False, remat=False)
        with pytest.raises(ValueError, match="dp axis"):
            auto_accelerate(GPT(cfg),
                            strategy=[("local_sgd", {}), ("fsdp", {})],
                            devices=jax.devices())


@requires_shard_map
class TestReduceMethods:
    def test_gta_gates_disagreement(self):
        """Components with opposite signs across replicas are zeroed."""
        from jax.sharding import Mesh

        mesh = Mesh(np.array(jax.devices()[:2]), ("dp",))
        cfg = LocalSGDConfig(reduce="gta", gta_threshold=0.0)

        def body(d):
            return _reduce_delta({"x": d}, cfg)["x"]

        from jax.sharding import PartitionSpec as P
        from jax import shard_map

        fn = shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
                       axis_names={"dp"}, check_vma=False)
        # replica 0: [+1, +1]; replica 1: [-1, +1] → first comp gated off
        d = jnp.array([[1.0, 1.0], [-1.0, 1.0]])
        out = np.asarray(fn(d))
        np.testing.assert_allclose(out[0], [0.0, 1.0], atol=1e-6)

    def test_mean_reduce(self):
        from jax.sharding import Mesh, PartitionSpec as P
        from jax import shard_map

        mesh = Mesh(np.array(jax.devices()[:2]), ("dp",))
        cfg = LocalSGDConfig(reduce="mean")

        def body(d):
            return _reduce_delta({"x": d}, cfg)["x"]

        fn = shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
                       axis_names={"dp"}, check_vma=False)
        d = jnp.array([[2.0], [4.0]])
        np.testing.assert_allclose(np.asarray(fn(d)), [[3.0], [3.0]])


@requires_shard_map
class TestDiLoCoGradAccum:
    """local_sgd x grad_accum (round-3 rejection, now closed): gradients
    accumulate inside each replica group's inner step, so the composition
    is purely local and must match a single big-batch inner step."""

    def _setup(self, accum):
        cfg = dataclasses.replace(GPTConfig.nano(), dtype=jnp.float32,
                                  use_flash_attention=False, remat=False)
        strat = [("local_sgd", {"sync_every": 2, "outer_lr": 0.7}),
                 ("data_parallel", {"size": 2}), ("fsdp", {})]
        if accum > 1:
            strat.append(("grad_accum", {"steps": accum}))
        res = auto_accelerate(GPT(cfg), optimizer=optax.sgd(1e-2),
                              strategy=strat, devices=jax.devices(),
                              rng=jax.random.PRNGKey(11))
        return cfg, res

    def test_accum_matches_big_batch_inner_step(self):
        cfg, res1 = self._setup(accum=1)
        _, res2 = self._setup(accum=2)
        data = np.asarray(jax.random.randint(
            jax.random.PRNGKey(0), (16, 33), 0, cfg.vocab_size))
        full = {"input_ids": data[:, :-1], "labels": data[:, 1:]}
        # microbatch split: dp group g sees full rows [8g, 8g+8); under
        # accum it must see the same rows across its two microbatches, and
        # each microbatch's dim 1 keeps the (dp, fsdp)-divisible layout
        def _split(v):
            out = np.zeros((2, 8) + v.shape[1:], v.dtype)
            for g in range(2):
                for mb in range(2):
                    out[mb, g * 4:(g + 1) * 4] = \
                        v[g * 8 + mb * 4:g * 8 + (mb + 1) * 4]
            return out

        micro = {k: _split(v) for k, v in full.items()}
        b1 = res1.place_batch(full)
        b2 = res2.place_batch(micro)
        s1, m1 = res1.train_step(res1.state, b1)
        s2, m2 = res2.train_step(res2.state, b2)
        # same rng → same init; sgd inner → grads average linearly, so the
        # accumulated step must match the big-batch step (CE normalizes per
        # microbatch; equal-size microbatches keep the mean identical)
        np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]),
                                   rtol=1e-5)
        for a, b in zip(jax.tree.leaves(
                jax.tree.map(np.asarray, s1.inner_params)),
                jax.tree.leaves(jax.tree.map(np.asarray, s2.inner_params))):
            np.testing.assert_allclose(a, b, rtol=2e-4, atol=1e-6)

    def test_accum_sync_round_still_aligns_groups(self):
        cfg, res = self._setup(accum=2)
        data = jax.random.randint(jax.random.PRNGKey(1), (2, 8, 33), 0,
                                  cfg.vocab_size)
        batch = res.place_batch({"input_ids": data[..., :-1],
                                 "labels": data[..., 1:]})
        state = res.state
        for _ in range(2):  # sync_every=2 → second step syncs
            state, m = res.train_step(state, batch)
        g0 = jax.tree.map(lambda x: np.asarray(x[0]), state.inner_params)
        g1 = jax.tree.map(lambda x: np.asarray(x[1]), state.inner_params)
        for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
            np.testing.assert_allclose(a, b, atol=1e-6)
        assert np.isfinite(float(m["loss"]))


@requires_shard_map
class TestDiLoCoStableBF16:
    """local_sgd x stable_bf16 (round-4 rejection, closed): bf16 inner
    params with Kahan/master precision, the outer sync re-anchoring the
    comp state (optimizers/bf16_stable.py reset_compensation)."""

    def _run(self, strategy, steps=8, lr=3e-3):
        cfg = dataclasses.replace(GPTConfig.nano(), dtype=jnp.float32,
                                  use_flash_attention=False, remat=False)
        res = auto_accelerate(GPT(cfg), optimizer=optax.adam(lr),
                              strategy=strategy, devices=jax.devices(),
                              rng=jax.random.PRNGKey(5))
        data = jax.random.randint(jax.random.PRNGKey(1), (8, 33), 0,
                                  cfg.vocab_size)
        batch = res.place_batch({"input_ids": data[:, :-1],
                                 "labels": data[:, 1:]})
        state, losses = res.state, []
        for _ in range(steps):
            state, m = res.train_step(state, batch)
            losses.append(float(m["loss"]))
        return state, losses

    BASE = [("local_sgd", {"sync_every": 4, "outer_lr": 0.7}),
            ("data_parallel", {"size": 2}), ("fsdp", {})]

    @pytest.mark.parametrize("master", [False, True])
    def test_trajectory_matches_f32(self, master):
        s32, l32 = self._run(self.BASE)
        sb, lb = self._run(self.BASE + [("stable_bf16",
                                         {"master": master})])
        # inner params became bf16
        assert all(l.dtype == jnp.bfloat16
                   for l in jax.tree.leaves(sb.inner_params))
        # loss trajectory tracks f32 within bf16 tolerance, incl. ACROSS
        # the sync step at 4 (comp-state re-anchor correctness)
        np.testing.assert_allclose(lb, l32, rtol=0.05)

    def test_sync_still_aligns_groups_bf16(self):
        sb, _ = self._run(self.BASE + [("stable_bf16", {"master": True})])
        g0 = jax.tree.map(lambda x: np.asarray(x[0], np.float32),
                          sb.inner_params)
        g1 = jax.tree.map(lambda x: np.asarray(x[1], np.float32),
                          sb.inner_params)
        for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
            np.testing.assert_allclose(a, b, atol=1e-6)


@requires_shard_map
@requires_pinned_host
class TestDiLoCoOptimizerOffload:
    """local_sgd x optimizer_offload (round-4 rejection, closed): stacked
    inner moments live in pinned_host between steps."""

    def _setup(self, offload):
        cfg = dataclasses.replace(GPTConfig.nano(), dtype=jnp.float32,
                                  use_flash_attention=False, remat=False)
        strat = [("local_sgd", {"sync_every": 2, "outer_lr": 0.7}),
                 ("data_parallel", {"size": 2}), ("fsdp", {})]
        if offload:
            strat.append(("optimizer_offload", {}))
        res = auto_accelerate(GPT(cfg), optimizer=optax.adam(1e-2),
                              strategy=strat, devices=jax.devices(),
                              rng=jax.random.PRNGKey(7))
        data = jax.random.randint(jax.random.PRNGKey(2), (8, 33), 0,
                                  cfg.vocab_size)
        batch = res.place_batch({"input_ids": data[:, :-1],
                                 "labels": data[:, 1:]})
        return res, batch

    def test_moments_in_pinned_host_and_trajectory_identical(self):
        res_d, batch = self._setup(offload=False)
        res_h, _ = self._setup(offload=True)
        # param-shaped moments stack to ndim >= 2; the stacked count
        # scalar is (dp,) and legitimately stays on device
        kinds = {l.sharding.memory_kind
                 for l in jax.tree.leaves(res_h.state.inner_opt_state)
                 if l.ndim > 1}
        assert kinds == {"pinned_host"}, kinds
        sd, sh = res_d.state, res_h.state
        for _ in range(5):  # crosses the sync at step 2 and 4
            sd, md = res_d.train_step(sd, batch)
            sh, mh = res_h.train_step(sh, batch)
            np.testing.assert_allclose(float(md["loss"]),
                                       float(mh["loss"]), rtol=1e-6)
        for a, b in zip(jax.tree.leaves(sd.inner_params),
                        jax.tree.leaves(sh.inner_params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6)

"""Tests for the core runtime layer (serialize, comm, node, storage, IPC).

Mirrors reference tests `dlrover/python/tests/test_multi_process.py`,
`test_servicer.py` style: in-process servers, no cluster.
"""

import multiprocessing as mp
import os
import threading
import time

import pytest

from dlrover_wuqiong_tpu.common import comm, serialize
from dlrover_wuqiong_tpu.common.constants import NodeStatus
from dlrover_wuqiong_tpu.common.messages import (
    HeartBeat,
    JoinRendezvousRequest,
    OkResponse,
    RendezvousState,
    Task,
    ShardConfig,
)
from dlrover_wuqiong_tpu.common.multi_process import (
    SharedDict,
    SharedLock,
    SharedMemoryBuffer,
    SharedQueue,
)
from dlrover_wuqiong_tpu.common.node import Node, NodeStateFlow
from dlrover_wuqiong_tpu.common.storage import PosixDiskStorage, get_checkpoint_storage


class TestSerialize:
    def test_roundtrip_nested(self):
        t = Task(task_id=3, task_type="training",
                 shard=ShardConfig(start=10, end=20), dataset_name="ds")
        data = serialize.dumps(t)
        back = serialize.loads(data)
        assert isinstance(back, Task)
        assert back.task_id == 3
        assert back.shard.start == 10 and back.shard.end == 20

    def test_bytes_roundtrip(self):
        from dlrover_wuqiong_tpu.common.messages import KVStoreSetRequest
        req = KVStoreSetRequest(key="a", value=b"\x00\xff\x01")
        back = serialize.loads(serialize.dumps(req))
        assert back.value == b"\x00\xff\x01"

    def test_plain_dict(self):
        obj = {"verb": "get", "payload": HeartBeat(node_id=1, timestamp=2.0)}
        back = serialize.loads(serialize.dumps(obj))
        assert back["verb"] == "get"
        assert isinstance(back["payload"], HeartBeat)


class TestRpc:
    def test_get_report_roundtrip(self):
        def handler(verb, node_id, node_type, payload):
            if verb == "get" and isinstance(payload, JoinRendezvousRequest):
                return RendezvousState(rdzv_round=1, complete=True)
            return OkResponse()

        server = comm.RpcServer(handler, host="127.0.0.1")
        server.start()
        try:
            client = comm.RpcClient(f"127.0.0.1:{server.port}", node_id=0)
            resp = client.get(JoinRendezvousRequest(node_id=0, node_rank=0))
            assert isinstance(resp, RendezvousState)
            assert resp.complete
            resp2 = client.report(HeartBeat(node_id=0))
            assert isinstance(resp2, OkResponse)
            client.close()
        finally:
            server.stop()

    def test_handler_error_propagates(self):
        def handler(verb, node_id, node_type, payload):
            raise ValueError("boom")

        server = comm.RpcServer(handler, host="127.0.0.1")
        server.start()
        try:
            client = comm.RpcClient(f"127.0.0.1:{server.port}")
            with pytest.raises(comm.RpcError, match="boom"):
                client.get(HeartBeat())
        finally:
            server.stop()

    def test_addr_connectable(self):
        server = comm.RpcServer(lambda *a: OkResponse(), host="127.0.0.1")
        server.start()
        assert comm.addr_connectable(f"127.0.0.1:{server.port}")
        server.stop()
        assert not comm.addr_connectable("127.0.0.1:1")


class TestNode:
    def test_status_flow(self):
        assert NodeStateFlow.can_transition(NodeStatus.PENDING,
                                            NodeStatus.RUNNING)
        assert not NodeStateFlow.can_transition(NodeStatus.SUCCEEDED,
                                                NodeStatus.RUNNING)
        assert NodeStateFlow.should_relaunch(NodeStatus.RUNNING,
                                             NodeStatus.FAILED)
        assert not NodeStateFlow.should_relaunch(NodeStatus.RUNNING,
                                                 NodeStatus.SUCCEEDED)

    def test_relaunch_info(self):
        n = Node("worker", 0, max_relaunch_count=2)
        n.update_status(NodeStatus.RUNNING)
        assert n.start_time is not None
        n2 = n.get_relaunch_node_info(new_id=7)
        assert n2.id == 7 and n2.rank_index == 0 and n2.relaunch_count == 1
        n.relaunch_count = 2
        assert n.is_unrecoverable_failure()


class TestStorage:
    def test_posix_roundtrip(self, tmp_path):
        s = PosixDiskStorage()
        p = str(tmp_path / "a" / "b.bin")
        s.write(b"hello", p)
        assert s.read(p) == b"hello"
        assert s.exists(p)
        s.safe_remove(p)
        assert not s.exists(p)

    def test_registry(self):
        s = get_checkpoint_storage({"class_name": "PosixDiskStorage",
                                    "kwargs": {}})
        assert isinstance(s, PosixDiskStorage)


def _queue_worker(in_name, out_name):
    q_in = SharedQueue(in_name, master=False)
    q_out = SharedQueue(out_name, master=False)
    item = q_in.get(timeout=10)
    q_out.put({"echo": item})


def _lock_and_die_worker(lock_name, out_name):
    # acquire and exit WITHOUT releasing — the cross-process shape of a
    # worker SIGKILLed inside its shm-staging critical section
    lock = SharedLock(lock_name, master=False)
    q_out = SharedQueue(out_name, master=False)
    assert lock.acquire(timeout=10)  # graftlint: disable=lock-leak -- the un-released acquire IS the scenario under test
    q_out.put("held")


class TestIpc:
    def test_shared_lock_same_process(self):
        lock = SharedLock("t1", master=True)
        assert lock.acquire()  # graftlint: disable=lock-leak -- single-process semantics test, released two lines down
        assert lock.locked()
        lock.release()
        assert not lock.locked()
        lock.close()

    def test_shared_lock_reaps_dead_holder(self):
        """A holder that hard-dies mid-critical-section must not wedge
        the next acquirer for the full timeout (the elastic relaunch
        path: gen N SIGKILLed while staging, gen N+1 blocks on its first
        save) — the lock notices the dead pid and is reacquirable."""
        lock = SharedLock("t1-reap", master=True)
        q = SharedQueue("t1-reap-out", master=True)
        proc = mp.get_context("spawn").Process(
            target=_lock_and_die_worker, args=("t1-reap", "t1-reap-out"))
        proc.start()
        assert q.get(timeout=15) == "held"
        proc.join(timeout=10)
        assert lock.locked()  # the dead holder left it held
        t0 = time.monotonic()
        assert lock.acquire(timeout=30)  # reaped, not waited out  # graftlint: disable=lock-leak -- reap-semantics test, released below
        assert time.monotonic() - t0 < 5.0
        lock.release()
        lock.close()
        q.close()

    def test_shared_lock_does_not_reap_live_holder(self):
        lock = SharedLock("t1-live", master=True)
        assert lock.acquire()  # holder: this (live) process  # graftlint: disable=lock-leak -- live-holder semantics test, released below
        assert not lock.acquire(blocking=False)  # graftlint: disable=lock-leak -- must FAIL to acquire; nothing to release
        assert lock.locked()
        lock.release()
        lock.close()

    def test_shared_queue_cross_process(self):
        q_in = SharedQueue("t2-in", master=True)
        q_out = SharedQueue("t2-out", master=True)
        proc = mp.get_context("spawn").Process(
            target=_queue_worker, args=("t2-in", "t2-out"))
        proc.start()
        q_in.put(42)
        got = q_out.get(timeout=15)
        proc.join(timeout=10)
        assert got == {"echo": 42}
        q_in.close()
        q_out.close()

    def test_shared_dict(self):
        d = SharedDict("t3", master=True)
        d.set({"a": 1, "b": [1, 2]})
        assert d.get() == {"a": 1, "b": [1, 2]}
        assert d.pop("a") == 1
        assert d.get() == {"b": [1, 2]}
        d.close()

    def test_shared_memory_buffer(self):
        buf = SharedMemoryBuffer("dwt-test-shm", create=True, size=1024)
        buf.buf[:5] = b"hello"
        other = SharedMemoryBuffer("dwt-test-shm")
        assert bytes(other.buf[:5]) == b"hello"
        other.close()
        buf.close()
        buf.unlink()

    def test_shared_memory_grow(self):
        buf = SharedMemoryBuffer("dwt-test-shm2", create=True, size=64)
        buf.close()
        big = SharedMemoryBuffer("dwt-test-shm2", create=True, size=4096)
        assert big.size >= 4096
        big.close()
        big.unlink()


class TestSyncTree:
    def test_sync_tree_touches_every_leaf(self):
        import jax.numpy as jnp

        from dlrover_wuqiong_tpu.common.util import sync_tree

        tree = {"a": jnp.ones((4, 4)), "b": [jnp.arange(3),
                jnp.zeros((0,))], "c": jnp.bool_(True)}
        total = sync_tree(tree)
        # 1.0 (a[0,0]) + 0.0 (arange[0]) + empty skipped + 1.0 (bool)
        assert total == 2.0

    def test_sync_tree_empty(self):
        from dlrover_wuqiong_tpu.common.util import sync_tree

        assert sync_tree({}) == 0.0

"""Master-layer tests: rendezvous state machine, dynamic sharding, servicer loop.

Mirrors reference tests `test_rdzv_manager.py`, `test_task_manager.py`,
`test_servicer.py`, `test_speed_monitor.py` — real master objects, no cluster.
"""

import time

import pytest

from dlrover_wuqiong_tpu.agent.master_client import MasterClient
from dlrover_wuqiong_tpu.agent.sharding_client import (
    IndexShardingClient,
    ShardingClient,
)
from dlrover_wuqiong_tpu.common.constants import NodeStatus, RendezvousName
from dlrover_wuqiong_tpu.master.dataset_splitter import (
    DatasetSplitter,
    StreamingDatasetSplitter,
    TableDatasetSplitter,
    TextDatasetSplitter,
)
from dlrover_wuqiong_tpu.master.master import JobMaster
from dlrover_wuqiong_tpu.master.rendezvous import (
    ElasticTrainingRendezvousManager,
    NetworkCheckRendezvousManager,
)
from dlrover_wuqiong_tpu.master.speed_monitor import SpeedMonitor
from dlrover_wuqiong_tpu.master.task_manager import TaskManager


class TestElasticRendezvous:
    def test_world_forms_at_min_nodes(self):
        rdzv = ElasticTrainingRendezvousManager()
        rdzv.update_rdzv_params(2, 4, waiting_timeout=0.0)
        rdzv.join_rendezvous(0, 0, 4, node_ip="10.0.0.1", free_port=1234)
        rnd, grp, world = rdzv.get_comm_world(0)
        assert world == {}  # only 1 node
        rdzv.join_rendezvous(1, 1, 4, node_ip="10.0.0.2", free_port=1235)
        time.sleep(0.01)
        rnd, grp, world = rdzv.get_comm_world(0)
        assert len(world) == 2
        assert world[0].node_id == 0 and world[1].node_id == 1
        assert rdzv.coordinator_addr() == "10.0.0.1:1234"
        # same world returned to the other member
        rnd2, _, world2 = rdzv.get_comm_world(1)
        assert rnd2 == rnd and len(world2) == 2

    def test_rejoin_advances_round(self):
        rdzv = ElasticTrainingRendezvousManager()
        rdzv.update_rdzv_params(2, 2, waiting_timeout=0.0)
        for nid in (0, 1):
            rdzv.join_rendezvous(nid, nid, 1)
        rnd, _, world = rdzv.get_comm_world(0)
        assert rnd == 1 and len(world) == 2
        # node 1 dies; replacement node 2 joins, node 0 rejoins
        rdzv.remove_alive_node(1)
        rdzv.join_rendezvous(2, 1, 1)
        assert rdzv.num_nodes_waiting() == 1
        rdzv.join_rendezvous(0, 0, 1)
        rnd, _, world = rdzv.get_comm_world(0)
        assert rnd == 2 and len(world) == 2
        ids = {s.node_id for s in world.values()}
        assert ids == {0, 2}

    def test_node_unit_truncates(self):
        rdzv = ElasticTrainingRendezvousManager()
        rdzv.update_rdzv_params(2, 8, waiting_timeout=0.0, node_unit=2)
        for nid in range(3):
            rdzv.join_rendezvous(nid, nid, 1)
        _, _, world = rdzv.get_comm_world(0)
        assert len(world) == 2  # truncated to multiple of node_unit


class TestNetworkCheckRendezvous:
    def _form(self, n):
        rdzv = NetworkCheckRendezvousManager()
        rdzv.update_rdzv_params(n, n, waiting_timeout=0.0)
        for nid in range(n):
            rdzv.join_rendezvous(nid, nid, 1)
        return rdzv

    def test_pair_groups_round0(self):
        rdzv = self._form(4)
        _, g0, w0 = rdzv.get_comm_world(0)
        _, g1, w1 = rdzv.get_comm_world(1)
        assert g0 == g1 and len(w0) == 2
        _, g2, _ = rdzv.get_comm_world(2)
        assert g2 != g0

    def test_fault_isolation_two_rounds(self):
        rdzv = self._form(4)
        # round 1: node 3 faulty → its pair group (2,3) both report failure
        for nid, ok in [(0, True), (1, True), (2, False), (3, False)]:
            rdzv.report_network_check_result(nid, ok, 1.0)
        success, _ = rdzv.network_check_success()
        assert not success
        faults, reason = rdzv.check_fault_node()
        assert set(faults) == {2, 3}
        # round 2: shifted grouping — 2 paired with a healthy node passes,
        # 3 still fails; status ORs across rounds → only 3 remains faulty
        for nid in range(4):
            rdzv.join_rendezvous(nid, nid, 1)
        rdzv.get_comm_world(0)
        for nid, ok in [(0, True), (1, True), (2, True), (3, False)]:
            rdzv.report_network_check_result(nid, ok, 1.0)
        faults, _ = rdzv.check_fault_node()
        assert faults == [3]

    def test_straggler_detection(self):
        rdzv = self._form(4)
        for nid, t in [(0, 1.0), (1, 1.1), (2, 0.9), (3, 5.0)]:
            rdzv.report_network_check_result(nid, True, t)
        stragglers, _ = rdzv.get_straggler(threshold=2.0)
        assert stragglers == [3]


class TestDatasetSplitters:
    def test_table_splitter(self):
        sp = TableDatasetSplitter("ds", 100, 30)
        sp.create_shards()
        shards = sp.get_shards()
        assert [s.start for s in shards] == [0, 30, 60, 90]
        assert shards[-1].end == 100

    def test_text_splitter_indices(self):
        sp = TextDatasetSplitter("ds", 10, 4, shuffle=True)
        sp.create_shards()
        all_indices = [i for s in sp.get_shards() for i in s.record_indices]
        assert sorted(all_indices) == list(range(10))

    def test_streaming_checkpoint_roundtrip(self):
        sp = StreamingDatasetSplitter("ds", 100, fetch_data_size=300)
        sp.create_shards()
        ckpt = sp.to_checkpoint()
        sp2 = DatasetSplitter.from_checkpoint(ckpt)
        assert sp2.partition_offset == 300
        assert len(sp2.get_shards()) == 3


class TestTaskManager:
    def test_dispatch_and_recover(self):
        tm = TaskManager()
        tm.new_dataset(batch_size=10, dataset_size=100, dataset_name="d",
                       num_minibatches_per_shard=2)
        t1 = tm.get_dataset_task(0, "d")
        t2 = tm.get_dataset_task(1, "d")
        assert t1.task_id != t2.task_id
        assert tm.report_dataset_task(0, "d", t1.task_id, True)
        # worker 1 dies: its shard is requeued at the front
        tm.recover_tasks(1)
        t3 = tm.get_dataset_task(2, "d")
        assert t3.shard.start == t2.shard.start
        assert not tm.finished("d")

    def test_finish_epoch(self):
        tm = TaskManager()
        tm.new_dataset(batch_size=10, dataset_size=20, dataset_name="d",
                       num_minibatches_per_shard=1)
        seen = 0
        while True:
            t = tm.get_dataset_task(0, "d")
            if t is None:
                break
            seen += 1
            tm.report_dataset_task(0, "d", t.task_id, True)
        assert seen == 2
        assert tm.finished("d")

    def test_checkpoint_roundtrip(self):
        tm = TaskManager()
        tm.new_dataset(batch_size=5, dataset_size=50, dataset_name="d")
        t = tm.get_dataset_task(0, "d")
        ckpt = tm.get_dataset_checkpoint("d")
        tm2 = TaskManager()
        assert tm2.restore_dataset_from_checkpoint(ckpt)
        # in-flight shard is back in todo
        starts = set()
        while True:
            task = tm2.get_dataset_task(0, "d")
            if task is None:
                break
            starts.add(task.shard.start)
        assert t.shard.start in starts


class TestSpeedMonitor:
    def test_running_speed(self):
        sm = SpeedMonitor()
        t0 = time.time()
        for i in range(10):
            sm.collect_global_step(i * 10, t0 + i)
        assert sm.completed_global_step == 90
        assert abs(sm.running_speed() - 10.0) < 0.01

    def test_target_worker_num_readable_before_set(self):
        # regression: _target_worker_num was only assigned by
        # set_target_worker_num — reading it first raised AttributeError
        sm = SpeedMonitor()
        assert sm.target_worker_num == 0
        assert not sm.all_worker_joined()  # 0 target = never joined
        sm.add_running_worker(0)
        assert not sm.all_worker_joined()

    def test_all_worker_joined_semantics(self):
        sm = SpeedMonitor()
        sm.set_target_worker_num(2)
        assert sm.target_worker_num == 2
        sm.add_running_worker(0)
        assert not sm.all_worker_joined()
        sm.add_running_worker(1)
        assert sm.all_worker_joined()
        sm.remove_running_worker(1)
        assert not sm.all_worker_joined()


class TestMasterEndToEnd:
    """In-process master + RPC clients (reference test_elastic_training_agent
    style)."""

    @pytest.fixture()
    def master(self):
        m = JobMaster(min_nodes=2, max_nodes=2)
        m.prepare()
        yield m
        m.stop()
        MasterClient.reset()

    def test_rendezvous_over_rpc(self, master):
        c0 = MasterClient(master.addr, node_id=0)
        c1 = MasterClient(master.addr, node_id=1)
        c0.register_node(0, accelerator_num=4)
        c1.register_node(1, accelerator_num=4)
        c0.join_rendezvous(0, 4, node_ip="127.0.0.1", free_port=4000)
        c1.join_rendezvous(1, 4, node_ip="127.0.0.1", free_port=4001)
        state = c0.get_comm_world()
        assert state.complete
        assert state.coordinator_addr == "127.0.0.1:4000"
        assert len(state.world) == 2
        # world maps str(rank) -> [node_id, local_world_size, ip, port]
        assert state.world["0"][0] == 0
        assert state.world["0"][1] == 4

    def test_sharding_over_rpc(self, master):
        c0 = MasterClient(master.addr, node_id=0)
        sc = ShardingClient(c0, "train", batch_size=4, dataset_size=40,
                            num_minibatches_per_shard=1)
        count = 0
        while True:
            task = sc.fetch_shard(wait=False)
            if task is None:
                break
            count += 1
            sc.report_shard_done()
        assert count == 10

    def test_index_sharding_client(self, master):
        c0 = MasterClient(master.addr, node_id=0)
        sc = IndexShardingClient(c0, "train2", batch_size=4, dataset_size=20,
                                 num_minibatches_per_shard=1)
        indices = []
        while True:
            idx = sc.fetch_sample_index()
            if idx is None:
                break
            indices.append(idx)
            sc.report_batch_done(1)
        assert sorted(indices) == list(range(20))

    def test_kv_store_and_heartbeat(self, master):
        c0 = MasterClient(master.addr, node_id=0)
        c0.register_node(0)
        c0.kv_store_set("k", b"v1")
        assert c0.kv_store_get("k") == b"v1"
        assert c0.kv_store_get("missing") is None
        assert c0.kv_store_add("cnt", 5) == 5
        assert c0.kv_store_add("cnt", 2) == 7
        action = c0.report_heart_beat(global_step=10)
        assert action == ""
        assert master.speed_monitor.completed_global_step == 10

    def test_failure_report_recovers_tasks(self, master):
        c0 = MasterClient(master.addr, node_id=0)
        c1 = MasterClient(master.addr, node_id=1)
        c0.register_node(0)
        c1.register_node(1)
        sc = ShardingClient(c1, "d3", batch_size=5, dataset_size=50)
        task = sc.fetch_shard()
        assert task is not None
        c1.report_failure("SIGKILL", level="node")
        node = master.job_manager.get_node(1)
        # the local manager relaunches in place: node is either still marked
        # FAILED (relaunch pending) or already reset to PENDING for restart
        assert node.status in (NodeStatus.FAILED, NodeStatus.PENDING)
        assert node.relaunch_count == 1
        # shard recovered: another worker can fetch the same start
        sc0 = ShardingClient(c0, "d3", batch_size=5, dataset_size=50)
        t2 = sc0.fetch_shard()
        assert t2.shard.start == task.shard.start


class TestNetTopology:
    def test_subnet_grouping(self):
        from dlrover_wuqiong_tpu.master.net_topology import (
            DpTopologySorter,
            NodeTopologyMeta,
        )

        metas = [
            NodeTopologyMeta(0, 0, ip="10.0.1.5"),
            NodeTopologyMeta(1, 1, ip="10.0.2.5"),
            NodeTopologyMeta(2, 2, ip="10.0.1.6"),
            NodeTopologyMeta(3, 3, ip="10.0.2.6"),
        ]
        out = DpTopologySorter().sort(metas)
        # same-/24 nodes contiguous: [0,2] then [1,3]
        assert [m.node_id for m in out] == [0, 2, 1, 3]

    def test_slice_id_beats_subnet(self):
        from dlrover_wuqiong_tpu.master.net_topology import (
            DpTopologySorter,
            NodeTopologyMeta,
        )

        metas = [
            NodeTopologyMeta(0, 0, ip="10.0.1.5", slice_id="s0"),
            NodeTopologyMeta(1, 1, ip="10.0.1.6", slice_id="s1"),
            NodeTopologyMeta(2, 2, ip="10.0.2.5", slice_id="s0"),
        ]
        out = DpTopologySorter().sort(metas)
        assert [m.node_id for m in out] == [0, 2, 1]

    def test_stable_without_locality(self):
        from dlrover_wuqiong_tpu.master.net_topology import (
            DpTopologySorter,
            NodeTopologyMeta,
        )

        metas = [NodeTopologyMeta(i, 3 - i) for i in range(4)]
        out = DpTopologySorter().sort(metas)
        assert [m.node_rank for m in out] == [0, 1, 2, 3]


class TestParalConfigTuner:
    def test_poll_writes_file_once_per_change(self, tmp_path):
        from dlrover_wuqiong_tpu.agent.config_tuner import (
            ParalConfigTuner,
            read_paral_config,
        )
        from dlrover_wuqiong_tpu.common import messages as msg

        class FakeMC:
            def __init__(self):
                self.cfg = msg.ParallelConfig(dataloader_batch_size=16)

            def get_paral_config(self):
                return self.cfg

        mc = FakeMC()
        path = str(tmp_path / "paral.json")
        tuner = ParalConfigTuner(mc, config_path=path)
        assert tuner.poll_once() is True
        assert read_paral_config(path)["dataloader_batch_size"] == 16
        assert tuner.poll_once() is False  # unchanged → no rewrite
        mc.cfg = msg.ParallelConfig(dataloader_batch_size=32)
        assert tuner.poll_once() is True
        assert read_paral_config(path)["dataloader_batch_size"] == 32

    def test_listener_reports_changes_once(self, tmp_path):
        import json

        from dlrover_wuqiong_tpu.agent.config_tuner import (
            ParalConfigListener,
        )

        path = tmp_path / "paral.json"
        listener = ParalConfigListener(path=str(path))
        assert listener.poll() is None            # no file yet
        path.write_text(json.dumps({"dataloader_batch_size": 8}))
        assert listener.poll()["dataloader_batch_size"] == 8
        assert listener.poll() is None            # unchanged
        path.write_text(json.dumps({"dataloader_batch_size": 16}))
        assert listener.poll()["dataloader_batch_size"] == 16

"""Flash-checkpoint tests: shm staging, async persistence, commit, restore.

Mirrors reference `dlrover/python/tests/test_ckpt_saver.py` and
`dlrover/trainer/tests/torch/checkpoint_egine_test.py` — real POSIX shm on a
single host, sharded arrays over the virtual 8-device CPU mesh.
"""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dlrover_wuqiong_tpu.checkpoint.ckpt_saver import (
    AsyncCheckpointSaver,
    read_last_step,
)
from dlrover_wuqiong_tpu.checkpoint.checkpointer import (
    FlashCheckpointer,
    StorageType,
)
from dlrover_wuqiong_tpu.checkpoint.engine import CheckpointEngine
from dlrover_wuqiong_tpu.checkpoint.shm_handler import (
    SharedMemoryHandler,
    flatten_state_dict,
)


@pytest.fixture(autouse=True)
def _fresh_saver():
    AsyncCheckpointSaver.reset()
    yield
    AsyncCheckpointSaver.reset()


def _mesh():
    return Mesh(np.array(jax.devices()).reshape(4, 2), ("data", "model"))


class TestShmHandler:
    def test_flatten(self):
        state = {"a": {"b": jnp.ones((2,)), "c": [jnp.zeros((3,))]}}
        flat = flatten_state_dict(state)
        assert set(flat) == {"a/b", "a/c/0"}

    def test_roundtrip_numpy(self):
        h = SharedMemoryHandler(0, "t-shm1")
        state = {"w": np.arange(12, dtype=np.float32).reshape(3, 4),
                 "b": np.array([1, 2], dtype=np.int32)}
        h.save_state_dict(state, step=7)
        step, flat, metas, extra = h.load_state_dict()
        assert step == 7
        np.testing.assert_array_equal(flat["w"], state["w"])
        np.testing.assert_array_equal(flat["b"], state["b"])
        h.unlink()

    def test_bfloat16_roundtrip(self):
        h = SharedMemoryHandler(0, "t-shm2")
        x = jnp.ones((8, 8), dtype=jnp.bfloat16) * 1.5
        h.save_state_dict({"x": x}, step=1)
        _, flat, _, _ = h.load_state_dict()
        assert flat["x"].dtype.name == "bfloat16"
        np.testing.assert_array_equal(np.asarray(flat["x"], np.float32), 1.5)
        h.unlink()

    def test_sharded_array_staging(self):
        mesh = _mesh()
        x = jax.device_put(
            jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
            NamedSharding(mesh, P("data", "model")))
        h = SharedMemoryHandler(0, "t-shm3")
        h.save_state_dict({"x": x}, step=2)
        _, flat, metas, _ = h.load_state_dict()
        # 8 unique shards staged with indices
        shard_names = [m.name for m in metas]
        assert len(shard_names) == 8
        assert all("#shard" in n for n in shard_names)
        # verify one shard content
        m0 = metas[0]
        slices = tuple(slice(s, e) for s, e in m0.index)
        np.testing.assert_array_equal(
            flat[m0.name], np.asarray(x)[slices])
        h.unlink()

    def test_replicated_array_staged_once(self):
        mesh = _mesh()
        x = jax.device_put(jnp.ones((4, 4)), NamedSharding(mesh, P()))
        h = SharedMemoryHandler(0, "t-shm4")
        h.save_state_dict({"x": x}, step=3)
        _, flat, metas, _ = h.load_state_dict()
        assert [m.name for m in metas] == ["x"]
        h.unlink()


class TestEngineEndToEnd:
    def test_save_load_storage(self, tmp_path):
        ckpt_dir = str(tmp_path / "ckpt")
        engine = CheckpointEngine(ckpt_dir, job_name="t-eng1",
                                  standalone=True)
        state = {"w": jnp.arange(16, dtype=jnp.float32).reshape(4, 4),
                 "step": np.int64(5)}
        blocked = engine.save_to_storage(5, state)
        assert blocked < 5.0
        assert engine.wait_saving_latest(timeout=30)
        assert read_last_step(ckpt_dir) == 5
        flat = engine.load_from_storage()
        np.testing.assert_array_equal(flat["w"],
                                      np.arange(16).reshape(4, 4))
        engine.close()

    def test_sharded_save_and_global_assembly(self, tmp_path):
        mesh = _mesh()
        ckpt_dir = str(tmp_path / "ckpt")
        engine = CheckpointEngine(ckpt_dir, job_name="t-eng2",
                                  standalone=True)
        x = jax.device_put(
            jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
            NamedSharding(mesh, P("data", None)))
        engine.save_to_storage(1, {"x": x})
        assert engine.wait_saving_latest(timeout=30)
        flat = engine.load_from_storage()
        np.testing.assert_array_equal(
            flat["x"], np.arange(64, dtype=np.float32).reshape(8, 8))
        engine.close()

    def test_memory_only_then_load_from_shm(self, tmp_path):
        engine = CheckpointEngine(str(tmp_path / "c"), job_name="t-eng3",
                                  standalone=True)
        state = {"v": jnp.ones((4,))}
        engine.save_to_memory(9, state)
        flat = engine.load()
        np.testing.assert_array_equal(flat["v"], np.ones(4))
        engine.close()


class TestFlashCheckpointer:
    def test_full_cycle_with_sharding_restore(self, tmp_path):
        mesh = _mesh()
        sharding = NamedSharding(mesh, P("data", "model"))
        ckpt_dir = str(tmp_path / "run")
        ckpt = FlashCheckpointer(ckpt_dir, job_name="t-fc1",
                                 standalone=True)
        params = {
            "dense": {"kernel": jax.device_put(
                jnp.arange(64, dtype=jnp.float32).reshape(8, 8), sharding)},
            "bias": jnp.zeros((8,)),
        }
        blocked = ckpt.save_checkpoint(10, params,
                                       storage_type=StorageType.DISK)
        assert blocked < 5.0
        assert ckpt.wait_latest_checkpoint(30)

        # fresh checkpointer (simulating restart) restores into template
        AsyncCheckpointSaver.reset()
        ckpt2 = FlashCheckpointer(ckpt_dir, job_name="t-fc2",
                                  standalone=True)
        template = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), params)
        # attach shardings to template leaves
        template["dense"]["kernel"] = jax.ShapeDtypeStruct(
            (8, 8), jnp.float32, sharding=sharding)
        restored = ckpt2.load_checkpoint(template)
        assert restored is not None
        np.testing.assert_array_equal(
            np.asarray(restored["dense"]["kernel"]),
            np.arange(64, dtype=np.float32).reshape(8, 8))
        assert restored["dense"]["kernel"].sharding == sharding
        ckpt.close()
        ckpt2.close()

    def test_save_speed_vs_direct_write(self, tmp_path):
        """Flash save must block far less than a full serialize+fsync write."""
        ckpt = FlashCheckpointer(str(tmp_path / "speed"), job_name="t-fc3",
                                 standalone=True)
        big = {"w": jnp.ones((512, 512), dtype=jnp.float32)}
        t0 = time.time()
        blocked = ckpt.save_checkpoint(1, big, storage_type=StorageType.MEMORY)
        assert blocked < 1.0
        ckpt.close()


class TestMultiNodeCommit:
    def test_tracker_waits_for_all_world_shards(self, tmp_path):
        """Node-0's agent must not publish the tracker until every rank's
        done-file lands (reference ckpt_saver.py:863) — a premature tracker
        is a torn checkpoint on any multi-node job."""
        import threading

        from dlrover_wuqiong_tpu.common.constants import CheckpointConstant

        path = str(tmp_path / "mn")
        saver0 = AsyncCheckpointSaver(job_name="t-mn0", local_shard_num=1,
                                      node_rank=0, world_shard_num=2)
        saver1 = AsyncCheckpointSaver(job_name="t-mn1", local_shard_num=1,
                                      node_rank=1, world_shard_num=2)
        try:
            h0 = SharedMemoryHandler(0, "t-mn0")
            h0.save_state_dict({"w": np.ones((4,), np.float32)}, step=3)
            h1 = SharedMemoryHandler(0, "t-mn1")
            h1.save_state_dict({"w": np.ones((4,), np.float32) * 2}, step=3)

            done0 = threading.Event()

            def _node0_save():
                saver0.save_step_checkpoint(3, path, commit_timeout=30)
                done0.set()

            t = threading.Thread(target=_node0_save, daemon=True)
            t.start()
            time.sleep(1.5)  # node 0 alone: commit must still be waiting
            tracker = os.path.join(path, CheckpointConstant.TRACKER_FILE)
            assert not done0.is_set()
            assert not os.path.exists(tracker), "premature tracker publish"

            saver1.save_step_checkpoint(3, path)  # rank!=0 never commits
            assert done0.wait(timeout=30)
            assert read_last_step(path) == 3
        finally:
            saver0._shm_handlers[0].unlink()
            saver1._shm_handlers[0].unlink()
            saver0._event_queue.close()
            saver1._event_queue.close()

    def test_node1_global_rank_offset(self, tmp_path):
        path = str(tmp_path / "gr")
        saver = AsyncCheckpointSaver(job_name="t-gr1", local_shard_num=1,
                                     node_rank=1, world_shard_num=2)
        try:
            h = SharedMemoryHandler(0, "t-gr1")
            h.save_state_dict({"w": np.zeros((2,), np.float32)}, step=1)
            saver.save_step_checkpoint(1, path)
            sdir = os.path.join(path, "checkpoint-1")
            assert os.path.exists(os.path.join(sdir, "meta_rank1.json"))
            assert os.path.exists(os.path.join(sdir, ".done", "rank1.done"))
        finally:
            saver._shm_handlers[0].unlink()
            saver._event_queue.close()


class TestTeardownFlush:
    def test_stop_persists_memory_only_checkpoint(self, tmp_path):
        """A MEMORY-only save newer than the last persisted step must be
        flushed to storage on clean teardown, not discarded with the shm
        segment (reference save_shm_to_storage on teardown, :634)."""
        ckpt_dir = str(tmp_path / "flush")
        ckpt = FlashCheckpointer(ckpt_dir, job_name="t-flush1",
                                 standalone=True)
        state = {"w": jnp.arange(8, dtype=jnp.float32)}
        ckpt.save_checkpoint(4, state, storage_type=StorageType.MEMORY)
        ckpt.close()
        AsyncCheckpointSaver.reset()  # triggers saver.stop() → flush
        assert read_last_step(ckpt_dir) == 4
        eng = CheckpointEngine(ckpt_dir, job_name="t-flush2",
                               standalone=True)
        flat = eng.load_from_storage()
        np.testing.assert_array_equal(flat["w"], np.arange(8))
        eng.close()


class TestObjectStoreStorage:
    def test_scheme_resolution(self):
        from dlrover_wuqiong_tpu.common.storage import (
            ObjectStoreStorage,
            PosixDiskStorage,
            get_checkpoint_storage,
        )

        assert isinstance(get_checkpoint_storage(path_hint="/tmp/x"),
                          PosixDiskStorage)
        assert isinstance(get_checkpoint_storage(path_hint="gs://b/x"),
                          ObjectStoreStorage)

    def test_epath_backend_roundtrip(self, tmp_path):
        """ObjectStoreStorage works over posix paths too (epath routing) —
        the full ckpt cycle runs through it end to end."""
        from dlrover_wuqiong_tpu.common.storage import ObjectStoreStorage

        storage = ObjectStoreStorage()
        ckpt_dir = str(tmp_path / "obj")
        engine = CheckpointEngine(ckpt_dir, job_name="t-obj1",
                                  standalone=True, storage=storage)
        state = {"w": jnp.arange(8, dtype=jnp.float32)}
        engine.save_to_storage(3, state)
        assert engine.wait_saving_latest(30)
        assert read_last_step(ckpt_dir, storage) == 3
        flat = engine.load_from_storage()
        np.testing.assert_array_equal(flat["w"], np.arange(8))
        engine.close()


@pytest.mark.slow  # tier-2: ~210s of orbax serialization; interop only —
# the flash engine's own save/restore integrity is tier-1 elsewhere
class TestOrbaxInterop:
    """Flash <-> Orbax layout adapters (SURVEY §7 item 3): checkpoints are
    not framework-locked — a sharded train state round-trips through
    orbax.checkpoint with values and shardings intact."""

    def _sharded_state(self):
        import optax

        from dlrover_wuqiong_tpu.auto.accelerate import auto_accelerate
        from dlrover_wuqiong_tpu.models.gpt import GPT, GPTConfig

        res = auto_accelerate(GPT(GPTConfig.nano()),
                              optimizer=optax.sgd(1e-2),
                              strategy=[("fsdp", {})])
        return res.state._asdict()

    def test_flash_to_orbax_roundtrip(self, tmp_path):
        from dlrover_wuqiong_tpu.checkpoint.checkpointer import (
            FlashCheckpointer,
            StorageType,
        )
        from dlrover_wuqiong_tpu.checkpoint.orbax_compat import (
            export_orbax,
            load_orbax,
        )

        state = self._sharded_state()
        flash_dir = str(tmp_path / "flash")
        ck = FlashCheckpointer(flash_dir, job_name=f"orbx{os.getpid()}")
        try:
            ck.save_checkpoint(7, state, storage_type=StorageType.DISK)
            assert ck.wait_latest_checkpoint(120)
        finally:
            ck.close()

        orbax_path = str(tmp_path / "orbax" / "step7")
        export_orbax(flash_dir, orbax_path, state)
        loaded = load_orbax(orbax_path, state)
        for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(loaded)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            assert b.sharding == a.sharding  # restored onto the mesh

    def test_orbax_to_flash_import(self, tmp_path):
        from dlrover_wuqiong_tpu.checkpoint.checkpointer import (
            FlashCheckpointer,
        )
        from dlrover_wuqiong_tpu.checkpoint.orbax_compat import (
            import_orbax,
            save_orbax,
        )

        state = self._sharded_state()
        orbax_path = str(tmp_path / "orbax" / "pretrained")
        save_orbax(orbax_path, state)

        flash_dir = str(tmp_path / "flash-import")
        import_orbax(orbax_path, flash_dir, state, step=3)
        ck = FlashCheckpointer(flash_dir, job_name=f"orbi{os.getpid()}")
        try:
            assert ck.last_step() == 3
            loaded = ck.load_checkpoint(state)
        finally:
            ck.close()
        for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(loaded)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestRollbackBeforeStep:
    def test_load_before_step_picks_pre_spike_commit(self, tmp_path):
        """ADVICE r4: rollback must restore the newest committed step that
        PRECEDES the spike, not the tracker's latest (which may postdate
        spike onset)."""
        ckpt_dir = str(tmp_path / "rb")
        ck = FlashCheckpointer(ckpt_dir, job_name="t-rb1", standalone=True)
        for step in (5, 10, 15):
            ck.save_checkpoint(step, {"w": jnp.full((4,), float(step))},
                               storage_type=StorageType.DISK)
            # each staged step must commit before the next save reuses the
            # shm segment (flash ckpt keeps ONE staged step at a time)
            assert ck.wait_latest_checkpoint(30)
        assert ck.engine.committed_steps() == [5, 10, 15]
        template = {"w": jnp.zeros((4,))}
        # spike detected at step 12 -> newest committed step < 12 is 10
        restored = ck.load_checkpoint(template, before_step=12)
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.full((4,), 10.0))
        # rollback durability: the post-spike step 15 is a poisoned
        # lineage — demoted so a later naive resume cannot pick it up
        assert ck.engine.committed_steps() == [5, 10]
        assert ck.last_step() == 10
        # no committed step precedes 5 -> falls back to latest (now 10)
        restored = ck.load_checkpoint(template, before_step=5)
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.full((4,), 10.0))
        ck.close()

    def test_partial_step_not_committed_and_not_assembled(self, tmp_path):
        """A step dir with done-files but NO commit marker (crash before
        every shard landed) must be invisible to rollback, and a
        shard-incomplete step must refuse to assemble."""
        import os
        import shutil

        ckpt_dir = str(tmp_path / "rbp")
        ck = FlashCheckpointer(ckpt_dir, job_name="t-rb2", standalone=True)
        for step in (5, 10):
            ck.save_checkpoint(step, {"w": jnp.full((4,), float(step))},
                               storage_type=StorageType.DISK)
            assert ck.wait_latest_checkpoint(30)
        # forge a partial step 8: copy step 5's dir, strip the marker
        src, dst = (os.path.join(ckpt_dir, f"checkpoint-{s}")
                    for s in (5, 8))
        shutil.copytree(src, dst)
        os.remove(os.path.join(dst, ".commit"))
        assert ck.engine.committed_steps() == [5, 10]  # 8 invisible
        restored = ck.load_checkpoint({"w": jnp.zeros((4,))},
                                      before_step=9)
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.full((4,), 5.0))
        ck.close()


class TestTrustBoundary:
    """Checkpoint trust boundary (checkpoint/integrity.py): digests at
    every tier, atomic manifest commit, quarantine-not-delete, verified
    fallback, self-heal."""

    def _commit(self, ck, step, value, shape=(8, 8)):
        ck.save_checkpoint(step, {"w": jnp.full(shape, value),
                                  "step": np.int64(step)},
                           storage_type=StorageType.DISK)
        assert ck.wait_latest_checkpoint(30)

    def test_manifest_roundtrip_across_dtypes_and_shardings(self, tmp_path):
        """Property test: a committed generation's manifest verifies
        per-leaf for every dtype/sharding combination the stack stages,
        and restore is exact for each."""
        from dlrover_wuqiong_tpu.checkpoint.integrity import (
            read_manifest,
            verify_storage_step,
        )
        from dlrover_wuqiong_tpu.common.storage import PosixDiskStorage

        mesh = _mesh()
        ckpt_dir = str(tmp_path / "prop")
        ck = FlashCheckpointer(ckpt_dir, job_name="t-tb-prop",
                               standalone=True)
        rng = np.random.default_rng(0)
        state = {
            "f32_2d": jax.device_put(
                jnp.asarray(rng.normal(size=(8, 8)), jnp.float32),
                NamedSharding(mesh, P("data", "model"))),
            "f32_rep": jax.device_put(jnp.asarray(
                rng.normal(size=(4, 4)), jnp.float32),
                NamedSharding(mesh, P())),
            "bf16_row": jax.device_put(jnp.asarray(
                rng.normal(size=(8, 2)), jnp.bfloat16),
                NamedSharding(mesh, P("data", None))),
            "i32": jnp.arange(16, dtype=jnp.int32),
            "u8": jnp.asarray(rng.integers(0, 255, (5,)), jnp.uint8),
            "scalar": np.int64(42),
        }
        ck.save_checkpoint(3, state, storage_type=StorageType.DISK)
        assert ck.wait_latest_checkpoint(30)
        storage = PosixDiskStorage()
        # deep (per-leaf) verification passes on healthy bytes
        v = verify_storage_step(storage, ckpt_dir, 3, per_leaf=True)
        assert v["ok"] and not v["bad_leaves"], v
        m = read_manifest(storage, str(tmp_path / "prop" / "checkpoint-3"))
        assert m["step"] == 3 and m["algo"] and m["ranks"], m
        # exact round trip for every leaf
        template = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=getattr(x, "sharding", None))
            if hasattr(x, "sharding") else x, state)
        ck.engine._shm_handler.mark_empty()  # force the storage tier
        restored = ck.load_checkpoint(template)
        assert ck.last_restore_report["tier"] == "storage"
        for name, a in flatten_state_dict(state).items():
            b = flatten_state_dict(restored)[name]
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        ck.close()

    def test_torn_manifest_falls_back_and_quarantines(self, tmp_path):
        ckpt_dir = str(tmp_path / "torn")
        ck = FlashCheckpointer(ckpt_dir, job_name="t-tb-torn",
                               standalone=True)
        for step, val in ((5, 5.0), (10, 10.0)):
            self._commit(ck, step, val)
        # tear the newest manifest mid-json (as a crashed rewrite would)
        mpath = os.path.join(ckpt_dir, "checkpoint-10", "manifest.json")
        raw = open(mpath).read()
        open(mpath, "w").write(raw[:len(raw) // 2])  # graftlint: disable=atomic-publish -- the torn manifest IS the fault under test
        ck.engine._shm_handler.mark_empty()
        restored = ck.load_checkpoint({"w": jnp.zeros((8, 8)),
                                       "step": np.int64(0)})
        rep = ck.last_restore_report
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.full((8, 8), 5.0))
        assert rep["tier"] == "storage" and rep["step"] == 5
        assert any(f["reason"] == "missing-manifest"
                   for f in rep["fallbacks"])  # torn == unreadable
        qdir = tmp_path / "torn" / ".quarantine" / "checkpoint-10"
        assert qdir.is_dir()  # evidence moved aside, not deleted
        assert (qdir / ".reason").exists()
        ck.close()

    def test_shm_flip_detected_heals_and_reverifies(self, tmp_path):
        ckpt_dir = str(tmp_path / "flip")
        ck = FlashCheckpointer(ckpt_dir, job_name="t-tb-flip",
                               standalone=True)
        self._commit(ck, 7, 7.0)
        h = ck.engine._shm_handler
        ok, _ = h.verify()
        assert ok
        buf = h._buf.buf
        buf[1 << 20] = (buf[1 << 20] + 1) % 256  # first payload byte
        ok, why = h.verify()
        assert not ok and "digest-mismatch" in why
        restored = ck.load_checkpoint({"w": jnp.zeros((8, 8)),
                                       "step": np.int64(0)})
        rep = ck.last_restore_report
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.full((8, 8), 7.0))
        assert rep["tier"] == "storage" and rep["healed"]
        assert any(f["tier"] == "shm" for f in rep["fallbacks"])
        # self-heal re-staged a verified copy: next load is the fast tier
        restored = ck.load_checkpoint({"w": jnp.zeros((8, 8)),
                                       "step": np.int64(0)})
        assert ck.last_restore_report["tier"] == "shm"
        ck.close()

    def test_corrupt_shm_never_persists(self, tmp_path):
        """The saver digest-checks while streaming shm → storage: a
        segment corrupted AFTER staging must abort the persist, never
        become a committed generation."""
        ckpt_dir = str(tmp_path / "nop")
        ck = FlashCheckpointer(ckpt_dir, job_name="t-tb-nop",
                               standalone=True)
        self._commit(ck, 1, 1.0)
        ck.save_checkpoint(2, {"w": jnp.full((8, 8), 2.0),
                               "step": np.int64(2)},
                           storage_type=StorageType.MEMORY)
        ck.wait_staging(30)
        h = ck.engine._shm_handler
        h._buf.buf[1 << 20] ^= 0xFF  # corrupt the staged step-2 payload
        saver = AsyncCheckpointSaver.get_ckpt_saver()
        saver.save_step_checkpoint(2, ckpt_dir, commit_timeout=3)
        assert read_last_step(ckpt_dir) == 1  # step 2 never committed
        marker = os.path.join(ckpt_dir, "checkpoint-2", ".commit")
        assert not os.path.exists(marker)
        ck.close()

    def test_replica_blob_verification(self):
        from dlrover_wuqiong_tpu.checkpoint.shm_handler import (
            verify_segment_blob,
        )

        h = SharedMemoryHandler(0, "t-tb-blob")
        try:
            h.save_state_dict(
                {"w": np.arange(32, dtype=np.float32)}, step=4)
            end = 1 << 20
            for m in h.load_header()["metas"]:
                end = max(end, m["offset"] + m["nbytes"])
            blob = bytes(h._buf.buf[:end])
            step, why = verify_segment_blob(blob)
            assert step == 4 and why == ""
            bad = bytearray(blob)
            bad[1 << 20] ^= 0x01
            step, why = verify_segment_blob(bytes(bad))
            assert step is None and "digest-mismatch" in why
            # torn header (truncated mid-json) is rejected too
            step, why = verify_segment_blob(blob[:100])
            assert step is None and why == "torn-header"
        finally:
            h.unlink()


_MID_PERSIST_SAVER = r"""
import os, sys
import numpy as np

from dlrover_wuqiong_tpu.checkpoint.checkpointer import (
    FlashCheckpointer, StorageType)

ckpt_dir = sys.argv[1]
ck = FlashCheckpointer(ckpt_dir, job_name=os.environ["DWT_JOB_NAME"],
                       standalone=True)
ck.save_checkpoint(1, {"w": np.full((8, 8), 1.0, np.float32),
                       "step": np.int64(1)},
                   storage_type=StorageType.DISK)
assert ck.wait_latest_checkpoint(60)
os.environ["DWT_CKPT_CRASH_POINT"] = sys.argv[2]
ck.save_checkpoint(2, {"w": np.full((8, 8), 2.0, np.float32),
                       "step": np.int64(2)},
                   storage_type=StorageType.DISK)
ck.wait_latest_checkpoint(60)
"""


class TestSigkillMidPersist:
    """The saver dies BETWEEN the shard-file write and the manifest
    publish (and, separately, between done-files and manifest): the torn
    generation is invisible-or-quarantined, restore serves N-1, and the
    dead run's shm segment is reaped by the next saver's sweeper."""

    @pytest.mark.parametrize("crash_point", ["after-bin",
                                             "before-manifest"])
    def test_restore_falls_back_to_previous_generation(
            self, tmp_path, crash_point):
        import subprocess
        import sys as _sys
        import tempfile

        ckpt_dir = str(tmp_path / "mp")
        job = f"mp{os.getpid()}{'a' if crash_point == 'after-bin' else 'b'}"
        script = tmp_path / "saver.py"
        script.write_text(_MID_PERSIST_SAVER)
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ, DWT_JOB_NAME=job,
                   # a short dir: AF_UNIX socket paths cap at ~108 chars
                   # and pytest tmp_path nests deep
                   DWT_SOCKET_DIR=tempfile.mkdtemp(prefix="dwt-mp-"),
                   JAX_PLATFORMS="cpu",
                   PYTHONPATH=repo + os.pathsep
                   + os.environ.get("PYTHONPATH", ""))
        proc = subprocess.run(
            [_sys.executable, str(script), ckpt_dir, crash_point],
            env=env, cwd=str(tmp_path), capture_output=True, text=True,
            timeout=120)
        assert proc.returncode == 137, proc.stdout + proc.stderr
        # generation 2 must be torn by construction: no manifest
        assert not os.path.exists(os.path.join(
            ckpt_dir, "checkpoint-2", "manifest.json"))

        AsyncCheckpointSaver.reset()
        ck = FlashCheckpointer(ckpt_dir, job_name=f"{job}-verify",
                               standalone=True)
        try:
            # sweeper reaped the dead saver's segment on startup
            assert not os.path.exists(f"/dev/shm/{job}_ckpt_shm_0")
            restored = ck.load_checkpoint({"w": jnp.zeros((8, 8)),
                                           "step": np.int64(0)})
            rep = ck.last_restore_report
            assert restored is not None and int(restored["step"]) == 1
            np.testing.assert_array_equal(np.asarray(restored["w"]),
                                          np.full((8, 8), 1.0))
            assert rep["step"] == 1 and rep["tier"] == "storage"
        finally:
            ck.close()


class TestCkptDoctor:
    def test_doctor_verifies_flags_and_repairs(self, tmp_path):
        import json as _json
        import subprocess
        import sys as _sys

        ckpt_dir = str(tmp_path / "doc")
        ck = FlashCheckpointer(ckpt_dir, job_name="t-doc1",
                               standalone=True)
        for step, val in ((2, 2.0), (4, 4.0)):
            ck.save_checkpoint(step, {"w": jnp.full((8, 8), val),
                                      "step": np.int64(step)},
                               storage_type=StorageType.DISK)
            assert ck.wait_latest_checkpoint(30)
        ck.close()
        AsyncCheckpointSaver.reset()
        doctor = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "ckpt_doctor.py")

        def run(*args):
            p = subprocess.run([_sys.executable, doctor, ckpt_dir, *args],
                               capture_output=True, text=True, timeout=60)
            return p.returncode, _json.loads(
                p.stdout.strip().splitlines()[-1])["ckpt_doctor"]

        rc, v = run("--deep")
        assert rc == 0 and v["ok"] and v["healthy_steps"] == [4, 2]
        # flip one byte in the newest shard file
        import glob

        bin4 = glob.glob(os.path.join(ckpt_dir, "checkpoint-4",
                                      "shards_rank*.bin"))[0]
        raw = bytearray(open(bin4, "rb").read())
        raw[10] ^= 0x02
        open(bin4, "wb").write(raw)
        rc, v = run()
        assert rc == 1 and not v["ok"]
        bad = [g for g in v["generations"] if not g["ok"]]
        assert [g["step"] for g in bad] == [4]
        # repair: quarantine + tracker repointed to the healthy gen
        rc, v = run("--repair")
        assert v["quarantined_now"] == [4]
        assert v["tracker_step"] == 2
        assert read_last_step(ckpt_dir) == 2
        rc, v = run()
        assert rc == 0 and v["ok"] and v["healthy_steps"] == [2]


class TestStaleSegmentSweeper:
    def test_dead_creator_reaped_live_spared(self, tmp_path):
        import subprocess
        import sys as _sys

        from dlrover_wuqiong_tpu.checkpoint.shm_handler import (
            sweep_stale_segments,
        )

        dead_job = f"t-sweep-dead-{os.getpid()}"
        live_job = f"t-sweep-live-{os.getpid()}"
        # a subprocess stages a segment and exits (its pid dies with it)
        code = (
            "import numpy as np, sys;"
            "from dlrover_wuqiong_tpu.checkpoint.shm_handler import "
            "SharedMemoryHandler;"
            f"h = SharedMemoryHandler(0, {dead_job!r});"
            "h.save_state_dict({'w': np.ones(4, np.float32)}, step=1);"
            "h.close()")
        subprocess.run([_sys.executable, "-c", code], check=True,
                       timeout=60, env=dict(os.environ,
                                            JAX_PLATFORMS="cpu"))
        assert os.path.exists(f"/dev/shm/{dead_job}_ckpt_shm_0")
        # this process stages one too (creator alive)
        h = SharedMemoryHandler(0, live_job)
        h.save_state_dict({"w": np.ones(4, np.float32)}, step=1)
        try:
            reaped = sweep_stale_segments("some-other-job")
            assert f"{dead_job}_ckpt_shm_0" in reaped
            assert not os.path.exists(f"/dev/shm/{dead_job}_ckpt_shm_0")
            # live creator: spared
            assert os.path.exists(f"/dev/shm/{live_job}_ckpt_shm_0")
            # segments of the current job are never touched either
            assert f"{live_job}_ckpt_shm_0" not in sweep_stale_segments(
                live_job)
        finally:
            h.unlink()


class TestWireDtype:
    """bf16 wire staging (r4 verdict next #3): halves bytes end to end.
    Exact-resume contract: f32 leaves come back bf16-quantized (documented
    lossy); bf16 and integer leaves round-trip bit-exactly."""

    def test_bf16_wire_contract(self, tmp_path):
        mesh = _mesh()
        sharding = NamedSharding(mesh, P("data", None))
        ckpt_dir = str(tmp_path / "wire")
        ck = FlashCheckpointer(ckpt_dir, job_name="t-wire1",
                               standalone=True, wire_dtype="bf16")
        f32 = jax.device_put(
            jnp.linspace(0.0, 1.0, 64, dtype=jnp.float32).reshape(8, 8),
            sharding)
        bf16 = jax.device_put(
            jnp.linspace(-1.0, 1.0, 64, dtype=jnp.bfloat16).reshape(8, 8),
            sharding)
        ints = jnp.arange(8, dtype=jnp.int32)
        state = {"f32": f32, "bf16": bf16, "ints": ints}
        ck.save_checkpoint(3, state, storage_type=StorageType.DISK)
        assert ck.wait_latest_checkpoint(30)

        # stored shards are bf16 for the f32 leaf: bytes halved on disk
        import json as _json

        meta_files = list((tmp_path / "wire" / "checkpoint-3").glob(
            "meta_rank*.json"))
        tensors = {t["name"].split("#shard")[0]: t["dtype"]
                   for mf in meta_files
                   for t in _json.loads(mf.read_text())["tensors"]}
        assert tensors["f32"] == "bfloat16", tensors
        assert tensors["ints"] == "int32"

        template = {"f32": jax.ShapeDtypeStruct((8, 8), jnp.float32,
                                                sharding=sharding),
                    "bf16": jax.ShapeDtypeStruct((8, 8), jnp.bfloat16,
                                                 sharding=sharding),
                    "ints": jnp.zeros(8, jnp.int32)}
        restored = ck.load_checkpoint(template)
        # template dtype honored; f32 values are bf16-quantized
        assert restored["f32"].dtype == jnp.float32
        np.testing.assert_array_equal(
            np.asarray(restored["f32"]),
            np.asarray(f32.astype(jnp.bfloat16).astype(jnp.float32)))
        # bf16 and int leaves: bit-exact
        np.testing.assert_array_equal(np.asarray(restored["bf16"]),
                                      np.asarray(bf16))
        np.testing.assert_array_equal(np.asarray(restored["ints"]),
                                      np.asarray(ints))
        ck.close()

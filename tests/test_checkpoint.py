"""Flash-checkpoint tests: shm staging, async persistence, commit, restore.

Mirrors reference `dlrover/python/tests/test_ckpt_saver.py` and
`dlrover/trainer/tests/torch/checkpoint_egine_test.py` — real POSIX shm on a
single host, sharded arrays over the virtual 8-device CPU mesh.
"""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dlrover_wuqiong_tpu.checkpoint.ckpt_saver import (
    AsyncCheckpointSaver,
    read_last_step,
)
from dlrover_wuqiong_tpu.checkpoint.checkpointer import (
    FlashCheckpointer,
    StorageType,
)
from dlrover_wuqiong_tpu.checkpoint.engine import CheckpointEngine
from dlrover_wuqiong_tpu.checkpoint.shm_handler import (
    SharedMemoryHandler,
    flatten_state_dict,
)


@pytest.fixture(autouse=True)
def _fresh_saver():
    AsyncCheckpointSaver.reset()
    yield
    AsyncCheckpointSaver.reset()


def _mesh():
    return Mesh(np.array(jax.devices()).reshape(4, 2), ("data", "model"))


class TestShmHandler:
    def test_flatten(self):
        state = {"a": {"b": jnp.ones((2,)), "c": [jnp.zeros((3,))]}}
        flat = flatten_state_dict(state)
        assert set(flat) == {"a/b", "a/c/0"}

    def test_roundtrip_numpy(self):
        h = SharedMemoryHandler(0, "t-shm1")
        state = {"w": np.arange(12, dtype=np.float32).reshape(3, 4),
                 "b": np.array([1, 2], dtype=np.int32)}
        h.save_state_dict(state, step=7)
        step, flat, metas, extra = h.load_state_dict()
        assert step == 7
        np.testing.assert_array_equal(flat["w"], state["w"])
        np.testing.assert_array_equal(flat["b"], state["b"])
        h.unlink()

    def test_bfloat16_roundtrip(self):
        h = SharedMemoryHandler(0, "t-shm2")
        x = jnp.ones((8, 8), dtype=jnp.bfloat16) * 1.5
        h.save_state_dict({"x": x}, step=1)
        _, flat, _, _ = h.load_state_dict()
        assert flat["x"].dtype.name == "bfloat16"
        np.testing.assert_array_equal(np.asarray(flat["x"], np.float32), 1.5)
        h.unlink()

    def test_sharded_array_staging(self):
        mesh = _mesh()
        x = jax.device_put(
            jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
            NamedSharding(mesh, P("data", "model")))
        h = SharedMemoryHandler(0, "t-shm3")
        h.save_state_dict({"x": x}, step=2)
        _, flat, metas, _ = h.load_state_dict()
        # 8 unique shards staged with indices
        shard_names = [m.name for m in metas]
        assert len(shard_names) == 8
        assert all("#shard" in n for n in shard_names)
        # verify one shard content
        m0 = metas[0]
        slices = tuple(slice(s, e) for s, e in m0.index)
        np.testing.assert_array_equal(
            flat[m0.name], np.asarray(x)[slices])
        h.unlink()

    def test_replicated_array_staged_once(self):
        mesh = _mesh()
        x = jax.device_put(jnp.ones((4, 4)), NamedSharding(mesh, P()))
        h = SharedMemoryHandler(0, "t-shm4")
        h.save_state_dict({"x": x}, step=3)
        _, flat, metas, _ = h.load_state_dict()
        assert [m.name for m in metas] == ["x"]
        h.unlink()


class TestEngineEndToEnd:
    def test_save_load_storage(self, tmp_path):
        ckpt_dir = str(tmp_path / "ckpt")
        engine = CheckpointEngine(ckpt_dir, job_name="t-eng1",
                                  standalone=True)
        state = {"w": jnp.arange(16, dtype=jnp.float32).reshape(4, 4),
                 "step": np.int64(5)}
        blocked = engine.save_to_storage(5, state)
        assert blocked < 5.0
        assert engine.wait_saving_latest(timeout=30)
        assert read_last_step(ckpt_dir) == 5
        flat = engine.load_from_storage()
        np.testing.assert_array_equal(flat["w"],
                                      np.arange(16).reshape(4, 4))
        engine.close()

    def test_sharded_save_and_global_assembly(self, tmp_path):
        mesh = _mesh()
        ckpt_dir = str(tmp_path / "ckpt")
        engine = CheckpointEngine(ckpt_dir, job_name="t-eng2",
                                  standalone=True)
        x = jax.device_put(
            jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
            NamedSharding(mesh, P("data", None)))
        engine.save_to_storage(1, {"x": x})
        assert engine.wait_saving_latest(timeout=30)
        flat = engine.load_from_storage()
        np.testing.assert_array_equal(
            flat["x"], np.arange(64, dtype=np.float32).reshape(8, 8))
        engine.close()

    def test_memory_only_then_load_from_shm(self, tmp_path):
        engine = CheckpointEngine(str(tmp_path / "c"), job_name="t-eng3",
                                  standalone=True)
        state = {"v": jnp.ones((4,))}
        engine.save_to_memory(9, state)
        flat = engine.load()
        np.testing.assert_array_equal(flat["v"], np.ones(4))
        engine.close()


class TestFlashCheckpointer:
    def test_full_cycle_with_sharding_restore(self, tmp_path):
        mesh = _mesh()
        sharding = NamedSharding(mesh, P("data", "model"))
        ckpt_dir = str(tmp_path / "run")
        ckpt = FlashCheckpointer(ckpt_dir, job_name="t-fc1",
                                 standalone=True)
        params = {
            "dense": {"kernel": jax.device_put(
                jnp.arange(64, dtype=jnp.float32).reshape(8, 8), sharding)},
            "bias": jnp.zeros((8,)),
        }
        blocked = ckpt.save_checkpoint(10, params,
                                       storage_type=StorageType.DISK)
        assert blocked < 5.0
        assert ckpt.wait_latest_checkpoint(30)

        # fresh checkpointer (simulating restart) restores into template
        AsyncCheckpointSaver.reset()
        ckpt2 = FlashCheckpointer(ckpt_dir, job_name="t-fc2",
                                  standalone=True)
        template = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), params)
        # attach shardings to template leaves
        template["dense"]["kernel"] = jax.ShapeDtypeStruct(
            (8, 8), jnp.float32, sharding=sharding)
        restored = ckpt2.load_checkpoint(template)
        assert restored is not None
        np.testing.assert_array_equal(
            np.asarray(restored["dense"]["kernel"]),
            np.arange(64, dtype=np.float32).reshape(8, 8))
        assert restored["dense"]["kernel"].sharding == sharding
        ckpt.close()
        ckpt2.close()

    def test_save_speed_vs_direct_write(self, tmp_path):
        """Flash save must block far less than a full serialize+fsync write."""
        ckpt = FlashCheckpointer(str(tmp_path / "speed"), job_name="t-fc3",
                                 standalone=True)
        big = {"w": jnp.ones((512, 512), dtype=jnp.float32)}
        t0 = time.time()
        blocked = ckpt.save_checkpoint(1, big, storage_type=StorageType.MEMORY)
        assert blocked < 1.0
        ckpt.close()


class TestMultiNodeCommit:
    def test_tracker_waits_for_all_world_shards(self, tmp_path):
        """Node-0's agent must not publish the tracker until every rank's
        done-file lands (reference ckpt_saver.py:863) — a premature tracker
        is a torn checkpoint on any multi-node job."""
        import threading

        from dlrover_wuqiong_tpu.common.constants import CheckpointConstant

        path = str(tmp_path / "mn")
        saver0 = AsyncCheckpointSaver(job_name="t-mn0", local_shard_num=1,
                                      node_rank=0, world_shard_num=2)
        saver1 = AsyncCheckpointSaver(job_name="t-mn1", local_shard_num=1,
                                      node_rank=1, world_shard_num=2)
        try:
            h0 = SharedMemoryHandler(0, "t-mn0")
            h0.save_state_dict({"w": np.ones((4,), np.float32)}, step=3)
            h1 = SharedMemoryHandler(0, "t-mn1")
            h1.save_state_dict({"w": np.ones((4,), np.float32) * 2}, step=3)

            done0 = threading.Event()

            def _node0_save():
                saver0.save_step_checkpoint(3, path, commit_timeout=30)
                done0.set()

            t = threading.Thread(target=_node0_save, daemon=True)
            t.start()
            time.sleep(1.5)  # node 0 alone: commit must still be waiting
            tracker = os.path.join(path, CheckpointConstant.TRACKER_FILE)
            assert not done0.is_set()
            assert not os.path.exists(tracker), "premature tracker publish"

            saver1.save_step_checkpoint(3, path)  # rank!=0 never commits
            assert done0.wait(timeout=30)
            assert read_last_step(path) == 3
        finally:
            saver0._shm_handlers[0].unlink()
            saver1._shm_handlers[0].unlink()
            saver0._event_queue.close()
            saver1._event_queue.close()

    def test_node1_global_rank_offset(self, tmp_path):
        path = str(tmp_path / "gr")
        saver = AsyncCheckpointSaver(job_name="t-gr1", local_shard_num=1,
                                     node_rank=1, world_shard_num=2)
        try:
            h = SharedMemoryHandler(0, "t-gr1")
            h.save_state_dict({"w": np.zeros((2,), np.float32)}, step=1)
            saver.save_step_checkpoint(1, path)
            sdir = os.path.join(path, "checkpoint-1")
            assert os.path.exists(os.path.join(sdir, "meta_rank1.json"))
            assert os.path.exists(os.path.join(sdir, ".done", "rank1.done"))
        finally:
            saver._shm_handlers[0].unlink()
            saver._event_queue.close()


class TestTeardownFlush:
    def test_stop_persists_memory_only_checkpoint(self, tmp_path):
        """A MEMORY-only save newer than the last persisted step must be
        flushed to storage on clean teardown, not discarded with the shm
        segment (reference save_shm_to_storage on teardown, :634)."""
        ckpt_dir = str(tmp_path / "flush")
        ckpt = FlashCheckpointer(ckpt_dir, job_name="t-flush1",
                                 standalone=True)
        state = {"w": jnp.arange(8, dtype=jnp.float32)}
        ckpt.save_checkpoint(4, state, storage_type=StorageType.MEMORY)
        ckpt.close()
        AsyncCheckpointSaver.reset()  # triggers saver.stop() → flush
        assert read_last_step(ckpt_dir) == 4
        eng = CheckpointEngine(ckpt_dir, job_name="t-flush2",
                               standalone=True)
        flat = eng.load_from_storage()
        np.testing.assert_array_equal(flat["w"], np.arange(8))
        eng.close()


class TestObjectStoreStorage:
    def test_scheme_resolution(self):
        from dlrover_wuqiong_tpu.common.storage import (
            ObjectStoreStorage,
            PosixDiskStorage,
            get_checkpoint_storage,
        )

        assert isinstance(get_checkpoint_storage(path_hint="/tmp/x"),
                          PosixDiskStorage)
        assert isinstance(get_checkpoint_storage(path_hint="gs://b/x"),
                          ObjectStoreStorage)

    def test_epath_backend_roundtrip(self, tmp_path):
        """ObjectStoreStorage works over posix paths too (epath routing) —
        the full ckpt cycle runs through it end to end."""
        from dlrover_wuqiong_tpu.common.storage import ObjectStoreStorage

        storage = ObjectStoreStorage()
        ckpt_dir = str(tmp_path / "obj")
        engine = CheckpointEngine(ckpt_dir, job_name="t-obj1",
                                  standalone=True, storage=storage)
        state = {"w": jnp.arange(8, dtype=jnp.float32)}
        engine.save_to_storage(3, state)
        assert engine.wait_saving_latest(30)
        assert read_last_step(ckpt_dir, storage) == 3
        flat = engine.load_from_storage()
        np.testing.assert_array_equal(flat["w"], np.arange(8))
        engine.close()


class TestOrbaxInterop:
    """Flash <-> Orbax layout adapters (SURVEY §7 item 3): checkpoints are
    not framework-locked — a sharded train state round-trips through
    orbax.checkpoint with values and shardings intact."""

    def _sharded_state(self):
        import optax

        from dlrover_wuqiong_tpu.auto.accelerate import auto_accelerate
        from dlrover_wuqiong_tpu.models.gpt import GPT, GPTConfig

        res = auto_accelerate(GPT(GPTConfig.nano()),
                              optimizer=optax.sgd(1e-2),
                              strategy=[("fsdp", {})])
        return res.state._asdict()

    def test_flash_to_orbax_roundtrip(self, tmp_path):
        from dlrover_wuqiong_tpu.checkpoint.checkpointer import (
            FlashCheckpointer,
            StorageType,
        )
        from dlrover_wuqiong_tpu.checkpoint.orbax_compat import (
            export_orbax,
            load_orbax,
        )

        state = self._sharded_state()
        flash_dir = str(tmp_path / "flash")
        ck = FlashCheckpointer(flash_dir, job_name=f"orbx{os.getpid()}")
        try:
            ck.save_checkpoint(7, state, storage_type=StorageType.DISK)
            assert ck.wait_latest_checkpoint(120)
        finally:
            ck.close()

        orbax_path = str(tmp_path / "orbax" / "step7")
        export_orbax(flash_dir, orbax_path, state)
        loaded = load_orbax(orbax_path, state)
        for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(loaded)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            assert b.sharding == a.sharding  # restored onto the mesh

    def test_orbax_to_flash_import(self, tmp_path):
        from dlrover_wuqiong_tpu.checkpoint.checkpointer import (
            FlashCheckpointer,
        )
        from dlrover_wuqiong_tpu.checkpoint.orbax_compat import (
            import_orbax,
            save_orbax,
        )

        state = self._sharded_state()
        orbax_path = str(tmp_path / "orbax" / "pretrained")
        save_orbax(orbax_path, state)

        flash_dir = str(tmp_path / "flash-import")
        import_orbax(orbax_path, flash_dir, state, step=3)
        ck = FlashCheckpointer(flash_dir, job_name=f"orbi{os.getpid()}")
        try:
            assert ck.last_step() == 3
            loaded = ck.load_checkpoint(state)
        finally:
            ck.close()
        for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(loaded)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestRollbackBeforeStep:
    def test_load_before_step_picks_pre_spike_commit(self, tmp_path):
        """ADVICE r4: rollback must restore the newest committed step that
        PRECEDES the spike, not the tracker's latest (which may postdate
        spike onset)."""
        ckpt_dir = str(tmp_path / "rb")
        ck = FlashCheckpointer(ckpt_dir, job_name="t-rb1", standalone=True)
        for step in (5, 10, 15):
            ck.save_checkpoint(step, {"w": jnp.full((4,), float(step))},
                               storage_type=StorageType.DISK)
            # each staged step must commit before the next save reuses the
            # shm segment (flash ckpt keeps ONE staged step at a time)
            assert ck.wait_latest_checkpoint(30)
        assert ck.engine.committed_steps() == [5, 10, 15]
        template = {"w": jnp.zeros((4,))}
        # spike detected at step 12 -> newest committed step < 12 is 10
        restored = ck.load_checkpoint(template, before_step=12)
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.full((4,), 10.0))
        # rollback durability: the post-spike step 15 is a poisoned
        # lineage — demoted so a later naive resume cannot pick it up
        assert ck.engine.committed_steps() == [5, 10]
        assert ck.last_step() == 10
        # no committed step precedes 5 -> falls back to latest (now 10)
        restored = ck.load_checkpoint(template, before_step=5)
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.full((4,), 10.0))
        ck.close()

    def test_partial_step_not_committed_and_not_assembled(self, tmp_path):
        """A step dir with done-files but NO commit marker (crash before
        every shard landed) must be invisible to rollback, and a
        shard-incomplete step must refuse to assemble."""
        import os
        import shutil

        ckpt_dir = str(tmp_path / "rbp")
        ck = FlashCheckpointer(ckpt_dir, job_name="t-rb2", standalone=True)
        for step in (5, 10):
            ck.save_checkpoint(step, {"w": jnp.full((4,), float(step))},
                               storage_type=StorageType.DISK)
            assert ck.wait_latest_checkpoint(30)
        # forge a partial step 8: copy step 5's dir, strip the marker
        src, dst = (os.path.join(ckpt_dir, f"checkpoint-{s}")
                    for s in (5, 8))
        shutil.copytree(src, dst)
        os.remove(os.path.join(dst, ".commit"))
        assert ck.engine.committed_steps() == [5, 10]  # 8 invisible
        restored = ck.load_checkpoint({"w": jnp.zeros((4,))},
                                      before_step=9)
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.full((4,), 5.0))
        ck.close()


class TestWireDtype:
    """bf16 wire staging (r4 verdict next #3): halves bytes end to end.
    Exact-resume contract: f32 leaves come back bf16-quantized (documented
    lossy); bf16 and integer leaves round-trip bit-exactly."""

    def test_bf16_wire_contract(self, tmp_path):
        mesh = _mesh()
        sharding = NamedSharding(mesh, P("data", None))
        ckpt_dir = str(tmp_path / "wire")
        ck = FlashCheckpointer(ckpt_dir, job_name="t-wire1",
                               standalone=True, wire_dtype="bf16")
        f32 = jax.device_put(
            jnp.linspace(0.0, 1.0, 64, dtype=jnp.float32).reshape(8, 8),
            sharding)
        bf16 = jax.device_put(
            jnp.linspace(-1.0, 1.0, 64, dtype=jnp.bfloat16).reshape(8, 8),
            sharding)
        ints = jnp.arange(8, dtype=jnp.int32)
        state = {"f32": f32, "bf16": bf16, "ints": ints}
        ck.save_checkpoint(3, state, storage_type=StorageType.DISK)
        assert ck.wait_latest_checkpoint(30)

        # stored shards are bf16 for the f32 leaf: bytes halved on disk
        import json as _json

        meta_files = list((tmp_path / "wire" / "checkpoint-3").glob(
            "meta_rank*.json"))
        tensors = {t["name"].split("#shard")[0]: t["dtype"]
                   for mf in meta_files
                   for t in _json.loads(mf.read_text())["tensors"]}
        assert tensors["f32"] == "bfloat16", tensors
        assert tensors["ints"] == "int32"

        template = {"f32": jax.ShapeDtypeStruct((8, 8), jnp.float32,
                                                sharding=sharding),
                    "bf16": jax.ShapeDtypeStruct((8, 8), jnp.bfloat16,
                                                 sharding=sharding),
                    "ints": jnp.zeros(8, jnp.int32)}
        restored = ck.load_checkpoint(template)
        # template dtype honored; f32 values are bf16-quantized
        assert restored["f32"].dtype == jnp.float32
        np.testing.assert_array_equal(
            np.asarray(restored["f32"]),
            np.asarray(f32.astype(jnp.bfloat16).astype(jnp.float32)))
        # bf16 and int leaves: bit-exact
        np.testing.assert_array_equal(np.asarray(restored["bf16"]),
                                      np.asarray(bf16))
        np.testing.assert_array_equal(np.asarray(restored["ints"]),
                                      np.asarray(ints))
        ck.close()

"""Warm-standby master (ISSUE 20): journal shipping + fenced failover.

Layers under test, bottom up:

- `MasterJournal.fetch_batch` / `ingest_snapshot` / `ingest_frames` —
  the shipping plane: durable-only frames, verbatim bytes (the mirror
  is a byte-prefix of the primary's log), snapshot+tail handoff when
  compaction outruns the ring, whole-frames-only ingest (torn batch
  tails and gaps stop, never corrupt).
- `StandbyTailer` — fetch→ingest→fold via the SAME `_apply_entry`
  replay path, lease clock armed only by adopted lease frames, final
  drain that DISARMS when a fresh lease proves the primary alive.
- The failover ladder end to end, in-process: promotion fenced at
  observed+2, exactly-once idem replay across the bump, the corpse
  self-fencing read-only via --peer, and the live-vs-offline merged
  incident timeline byte-equal with kind="failover".

The chaos `master-failover` drill runs the same ladder with real
processes and SIGKILL; these stay fast and deterministic.
"""

import json
import threading
import time

import pytest

from dlrover_wuqiong_tpu.agent.master_client import MasterClient
from dlrover_wuqiong_tpu.common import serialize
from dlrover_wuqiong_tpu.common.comm import RpcClient, RpcError
from dlrover_wuqiong_tpu.common.messages import (
    FetchJournalRequest,
    JournalStatsQuery,
    KVStoreAddRequest,
    KVStoreSetRequest,
)
from dlrover_wuqiong_tpu.master.journal import MasterJournal
from dlrover_wuqiong_tpu.master.master import JobMaster
from dlrover_wuqiong_tpu.master.standby import StandbyTailer
from dlrover_wuqiong_tpu.telemetry import timeline as tl


# ------------------------------------------------------- shipping plane


def _mkjournal(tmp_path, name):
    j = MasterJournal(str(tmp_path / name))
    j.load()
    return j


def _raw_lines(journal):
    with open(journal._path, "rb") as f:  # noqa: SLF001
        return [l for l in f.read().split(b"\n") if l.strip()]


class TestFetchBatch:
    def test_ring_serves_durable_frames_verbatim(self, tmp_path):
        j = _mkjournal(tmp_path, "src")
        for i in range(3):
            j.append("kv", {"key": f"k{i}"})
        snap, snap_seq, frames, durable = j.fetch_batch(0)
        assert (snap, snap_seq) == (b"", 0)
        assert durable == 3
        assert frames == _raw_lines(j)  # verbatim bytes, not re-encoded
        # caught-up pull: nothing to ship, watermark stays
        assert j.fetch_batch(3)[2] == []
        st = j.group_commit_stats()
        assert st["shipped_seq"] == 3
        assert st["standby_lag_frames"] == 0
        j.close()

    def test_unfetched_journal_reports_no_standby(self, tmp_path):
        j = _mkjournal(tmp_path, "src")
        j.append("kv", {})
        assert j.group_commit_stats()["standby_lag_frames"] == -1
        j.close()

    def test_max_frames_paginates(self, tmp_path):
        j = _mkjournal(tmp_path, "src")
        for i in range(5):
            j.append("kv", {"i": i})
        _, _, page1, durable = j.fetch_batch(0, max_frames=2)
        assert len(page1) == 2 and durable == 5
        next_seq = int(serialize.loads(page1[-1])["seq"])
        _, _, page2, _ = j.fetch_batch(next_seq, max_frames=10)
        assert len(page2) == 3

    def test_snapshot_tail_handoff_when_ring_outrun(self, tmp_path):
        j = _mkjournal(tmp_path, "src")
        for i in range(4):
            j.append("kv", {"i": i})
        j.snapshot({"kv": {"x": 1}})
        j.append("kv", {"i": 99})  # tail after compaction
        j._ship_ring.clear()  # noqa: SLF001 — emulate a long-dead standby
        snap, snap_seq, frames, durable = j.fetch_batch(0)
        assert snap and snap_seq > 0
        state = serialize.loads(snap).get("state")
        assert state["kv"] == {"x": 1}
        # tail resumes past the snapshot: compaction marker + the new kv
        seqs = [int(serialize.loads(f)["seq"]) for f in frames]
        assert seqs == list(range(snap_seq + 1, durable + 1))
        j.close()

    def test_handoff_skips_snapshot_standby_already_covers(self, tmp_path):
        j = _mkjournal(tmp_path, "src")
        for i in range(3):
            j.append("kv", {"i": i})
        j.snapshot({"kv": {}})
        j.append("kv", {"i": 3})
        j._ship_ring.clear()  # noqa: SLF001
        # the standby already holds past the snapshot seq: no handoff
        snap, snap_seq, frames, _ = j.fetch_batch(4)
        assert (snap, snap_seq) == (b"", 0)
        assert len(frames) >= 1
        j.close()


class TestIngest:
    def test_mirror_is_byte_prefix_of_primary(self, tmp_path):
        src = _mkjournal(tmp_path, "src")
        dst = _mkjournal(tmp_path, "dst")
        for i in range(4):
            src.append("kv", {"i": i})
        _, _, frames, _ = src.fetch_batch(0)
        adopted = dst.ingest_frames(frames)
        assert [f["seq"] for f in adopted] == [1, 2, 3, 4]
        assert _raw_lines(dst) == _raw_lines(src)
        assert dst.group_commit_stats()["durable_seq"] == 4
        src.close()
        dst.close()

    def test_torn_batch_tail_whole_frames_only(self, tmp_path):
        src = _mkjournal(tmp_path, "src")
        dst = _mkjournal(tmp_path, "dst")
        for i in range(3):
            src.append("kv", {"i": i})
        _, _, frames, _ = src.fetch_batch(0)
        torn = frames[:2] + [frames[2][:10]]  # mid-frame cut
        adopted = dst.ingest_frames(torn)
        assert [f["seq"] for f in adopted] == [1, 2]
        # the local log holds ONLY intact frames; a re-fetch from our
        # durable seq resumes cleanly (dup skipped upstream by from_seq)
        assert _raw_lines(dst) == frames[:2]
        adopted = dst.ingest_frames(frames[2:])
        assert [f["seq"] for f in adopted] == [3]
        assert _raw_lines(dst) == frames
        src.close()
        dst.close()

    def test_gap_stops_ingest_and_refetch_heals(self, tmp_path):
        src = _mkjournal(tmp_path, "src")
        dst = _mkjournal(tmp_path, "dst")
        for i in range(4):
            src.append("kv", {"i": i})
        _, _, frames, _ = src.fetch_batch(0)
        adopted = dst.ingest_frames([frames[0], frames[2], frames[3]])
        assert [f["seq"] for f in adopted] == [1]  # gap at 3: stop
        adopted = dst.ingest_frames(frames)  # re-fetch overlap
        assert [f["seq"] for f in adopted] == [2, 3, 4]
        src.close()
        dst.close()

    def test_ingest_snapshot_resets_log_and_primes_seq(self, tmp_path):
        src = _mkjournal(tmp_path, "src")
        dst = _mkjournal(tmp_path, "dst")
        dst.append("stale", {"old": True})  # pre-handoff garbage
        for i in range(3):
            src.append("kv", {"i": i})
        src.snapshot({"kv": {"a": 1}})
        src.append("kv", {"i": 9})
        src._ship_ring.clear()  # noqa: SLF001
        snap, snap_seq, frames, _ = src.fetch_batch(0)
        state, seq, _epoch = dst.ingest_snapshot(snap)
        assert state["kv"] == {"a": 1}
        assert seq == snap_seq
        assert _raw_lines(dst) == []  # local log reset
        adopted = dst.ingest_frames(frames)
        assert adopted and adopted[-1]["kind"] == "kv"
        assert dst.group_commit_stats()["durable_seq"] == \
            src.group_commit_stats()["durable_seq"]
        src.close()
        dst.close()


# ------------------------------------------------- tailer + failover e2e


def _hard_kill(master, client=None):
    """In-process stand-in for SIGKILL: stop the server, mark the
    leadership dead, and sever the client's persistent connection (a
    real process death resets the TCP stream; socketserver's stop only
    closes the accept loop)."""
    master._stopped.set()  # noqa: SLF001
    master._server.stop()  # noqa: SLF001
    master.is_leader = False
    if client is not None:
        client._client.close()  # noqa: SLF001


@pytest.fixture()
def ha_pair(tmp_path):
    """Primary (leased) + standby + armed tailer, torn down in order."""
    ttl = 0.5
    m1 = JobMaster(port=0, journal_dir=str(tmp_path / "j1"),
                   lease_ttl_s=ttl)
    m1.prepare()
    m1.start_lease_heartbeat()
    m2 = JobMaster(port=0, journal_dir=str(tmp_path / "j2"),
                   standby=True, lease_ttl_s=ttl)
    m2.prepare()
    tailer = StandbyTailer(m2, f"127.0.0.1:{m1.port}", lease_ttl_s=ttl,
                           poll_interval_s=0.05)
    yield m1, m2, tailer
    tailer.close()
    m2.stop()
    m1.stop()


def _mirror_until_leased(m1, m2, tailer):
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        tailer.poll_once()
        if tailer._last_lease_mono and \
                m2.journal_stats().durable_seq >= \
                m1.journal_stats().durable_seq:  # noqa: SLF001
            return
        time.sleep(0.02)
    raise AssertionError("mirror never caught up / lease never armed")


class TestStandbyTailer:
    def test_mirror_folds_state_and_reports_lag(self, ha_pair):
        m1, m2, tailer = ha_pair
        mc = MasterClient(f"127.0.0.1:{m1.port}", node_id=0)
        mc.kv_store_set("boot", b"coord")
        assert mc.kv_store_add("ctr", 2) == 2
        _mirror_until_leased(m1, m2, tailer)
        s1, s2 = m1.journal_stats(), m2.journal_stats()
        assert s1.standby_lag_frames == 0
        assert s1.shipped_seq == s1.durable_seq
        assert s2.epoch == s1.epoch  # mirrored, no spurious bump
        assert not s2.is_leader and s1.is_leader
        # folded through the SAME apply path: state queryable read-only
        mc_sb = MasterClient(f"127.0.0.1:{m2.port}", node_id=1)
        assert mc_sb.kv_store_get("boot") == b"coord"
        mc_sb.close()
        mc.close()

    def test_standby_refuses_mutations_until_promoted(self, ha_pair):
        m1, m2, tailer = ha_pair
        rc = RpcClient(f"127.0.0.1:{m2.port}", node_id=7, retries=1)
        with pytest.raises(RpcError, match="NotLeaderError"):
            rc.get(KVStoreSetRequest(key="nope", value=b"x"))
        # read-only verbs answer (a fenced master is still a reporter)
        assert rc.get(JournalStatsQuery()).is_leader is False
        rc.close()

    def test_fetch_journal_is_never_journaled(self, ha_pair):
        """The POLLING fetch verb must not feed the journal it ships —
        N idle polls leave the primary's seq exactly flat."""
        m1, m2, tailer = ha_pair
        _mirror_until_leased(m1, m2, tailer)
        before = m1.journal_stats().durable_seq
        for _ in range(5):
            assert tailer.poll_once() == 0
        assert m1.journal_stats().durable_seq == before

    def test_no_lease_primary_makes_pure_mirror(self, tmp_path):
        """fleet_bench's shape: primary never heartbeats a lease, so the
        standby mirrors forever and NEVER promotes (ttl clock unarmed)."""
        m1 = JobMaster(port=0, journal_dir=str(tmp_path / "j1"))
        m1.prepare()
        m2 = JobMaster(port=0, journal_dir=str(tmp_path / "j2"),
                       standby=True, lease_ttl_s=0.2)
        m2.prepare()
        tailer = StandbyTailer(m2, f"127.0.0.1:{m1.port}",
                               lease_ttl_s=0.2, poll_interval_s=0.02)
        try:
            mc = MasterClient(f"127.0.0.1:{m1.port}", node_id=0)
            mc.kv_store_set("k", b"v")
            mc.close()
            assert not tailer.run(threading.Event(), max_seconds=1.0)
            assert not m2.is_leader
            assert m2.journal_stats().durable_seq == \
                m1.journal_stats().durable_seq
        finally:
            tailer.close()
            m2.stop()
            m1.stop()

    def test_fresh_lease_mid_drain_disarms(self, ha_pair):
        """A stalled tailer whose clock reads expired must NOT promote
        while the primary still heartbeats — the final drain adopts a
        fresh lease frame and disarms."""
        m1, m2, tailer = ha_pair
        _mirror_until_leased(m1, m2, tailer)
        # forge expiry: pretend the last lease landed long ago
        tailer._last_lease_mono = (  # noqa: SLF001
            time.monotonic() - 10 * tailer.lease_ttl_s)
        time.sleep(tailer.lease_ttl_s)  # let the primary heartbeat
        assert not tailer.run(threading.Event(), max_seconds=1.5)
        assert not m2.is_leader
        assert m1.is_leader


class TestFailover:
    def test_promotion_fence_exactly_once_and_corpse(self, ha_pair,
                                                     tmp_path):
        m1, m2, tailer = ha_pair
        mc = MasterClient(f"127.0.0.1:{m1.port},127.0.0.1:{m2.port}",
                          node_id=0)
        mc.report_dataset_shard_params(
            batch_size=4, dataset_size=64, dataset_name="ds",
            num_minibatches_per_shard=2)
        t1 = mc.get_task("ds")
        mc.kv_store_set("boot", b"coord")
        idem = "node0:add:1"
        assert mc._client.get(  # noqa: SLF001 — fixed idem on purpose
            KVStoreAddRequest(key="ctr", amount=5), idem=idem).num == 5
        _mirror_until_leased(m1, m2, tailer)

        old_epoch = m1.epoch
        _hard_kill(m1, mc)
        assert tailer.run(threading.Event(), max_seconds=30)

        # fenced promotion: strictly above what a revived corpse's
        # naive restart bump (+1) could ever reach
        assert m2.is_leader
        assert m2.epoch == old_epoch + 2

        # client fails over on its next critical verb; state intact
        t2 = mc.get_task("ds")
        assert t2.task_id != t1.task_id  # dispatch cursor exact
        assert mc.kv_store_get("boot") == b"coord"
        assert mc.degraded_stats()["failovers"] >= 1
        # exactly-once: the original idem key replays the journaled
        # response instead of re-applying
        assert mc._client.get(  # noqa: SLF001
            KVStoreAddRequest(key="ctr", amount=5), idem=idem).num == 5
        assert mc.kv_store_add("ctr", 1) == 6

        # the corpse revives on its old journal with --peer: it must
        # observe the higher epoch and self-fence read-only
        m3 = JobMaster(port=0, journal_dir=str(tmp_path / "j1"),
                       peer=f"127.0.0.1:{m2.port}", lease_ttl_s=0.5)
        m3.prepare()
        rc = RpcClient(f"127.0.0.1:{m3.port}", node_id=9, retries=1)
        try:
            assert not m3.is_leader
            assert m3.epoch < m2.epoch
            with pytest.raises(RpcError, match="NotLeaderError"):
                rc.get(KVStoreAddRequest(key="q", amount=1))
        finally:
            rc.close()
            m3.stop()
            mc.close()

    def test_timeline_merges_both_journals_byte_equal(self, ha_pair,
                                                      tmp_path):
        m1, m2, tailer = ha_pair
        jd1, jd2 = str(tmp_path / "j1"), str(tmp_path / "j2")
        mc = MasterClient(f"127.0.0.1:{m1.port},127.0.0.1:{m2.port}",
                          node_id=0)
        mc.kv_store_set("k", b"v")
        _mirror_until_leased(m1, m2, tailer)
        _hard_kill(m1, mc)
        assert tailer.run(threading.Event(), max_seconds=30)
        mc.kv_store_set("after", b"failover")

        resp = mc.get_timeline(journal_dirs=[jd2, jd1])
        offline = tl.incident_json(tl.assemble_incident(
            journal_dir=jd2, ckpt_dir="", journal_dirs=[jd1]))
        assert resp.content == offline  # live == offline, byte-equal
        rep = json.loads(offline)
        kinds = [i["kind"] for i in rep["narrative"]["incidents"]]
        assert "failover" in kinds
        # the merge dedups shipped frames: (epoch, seq, kind) unique and
        # (epoch, seq)-ordered across both dirs
        keys = [(e["epoch"], e["seq"], e["kind"]) for e in rep["events"]
                if e["source"] == "journal"]
        assert keys == sorted(keys, key=lambda k: k[:2])
        assert len(keys) == len(set(keys))
        mc.close()

"""High-level Trainer tests (ATorchTrainer parity).

Runs the full stack on the virtual CPU mesh: strategy → sharded step →
flash ckpt save/resume → eval → callbacks.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_wuqiong_tpu.checkpoint.ckpt_saver import AsyncCheckpointSaver
from dlrover_wuqiong_tpu.models.gpt import GPT, GPTConfig
from dlrover_wuqiong_tpu.trainer.trainer import Trainer, TrainingArgs


@pytest.fixture(autouse=True)
def _fresh_saver():
    AsyncCheckpointSaver.reset()
    yield
    AsyncCheckpointSaver.reset()


def _model():
    return GPT(dataclasses.replace(GPTConfig.nano(), dtype=jnp.float32,
                                   use_flash_attention=False, remat=False))


def _data(step, batch=8, seq=32, vocab=512):
    rng = np.random.default_rng(step % 4)  # small cycling dataset
    x = rng.integers(0, vocab, (batch, seq + 1))
    return {"input_ids": x[:, :-1], "labels": x[:, 1:]}


class TestTrainer:
    def test_train_loss_decreases_and_saves(self, tmp_path):
        seen = []
        args = TrainingArgs(
            output_dir=str(tmp_path), max_steps=24, global_batch_size=8,
            seq_len=32, learning_rate=1e-2, warmup_steps=2,
            logging_steps=4, save_steps=10, strategy=[("fsdp", {})])
        tr = Trainer(_model(), args, _data,
                     callbacks=[lambda s, m: seen.append((s, m["loss"]))])
        out = tr.train()
        assert out["final_step"] == 24
        assert seen and seen[-1][1] < seen[0][1]  # loss decreased
        # checkpoints committed on the save cadence + exit
        tracker = (tmp_path / "checkpoints" /
                   "latest_checkpointed_iteration.txt")
        assert tracker.exists()
        assert int(tracker.read_text()) == 24
        tr.ckpt.close()

    def test_resume_continues_from_checkpoint(self, tmp_path):
        args = TrainingArgs(
            output_dir=str(tmp_path), max_steps=6, seq_len=32,
            global_batch_size=8, warmup_steps=1, save_steps=3,
            logging_steps=2, strategy=[("fsdp", {})])
        tr1 = Trainer(_model(), args, _data)
        tr1.train()
        tr1.ckpt.close()
        AsyncCheckpointSaver.reset()

        args2 = dataclasses.replace(args, max_steps=10)
        tr2 = Trainer(_model(), args2, _data)
        out = tr2.train()
        # resumed (step 6) rather than restarting from zero
        assert int(np.asarray(jax.tree.leaves(tr2.state.step)[0])) == 10
        tracker = (tmp_path / "checkpoints" /
                   "latest_checkpointed_iteration.txt")
        assert int(tracker.read_text()) == 10
        tr2.ckpt.close()

    def test_evaluate(self, tmp_path):
        args = TrainingArgs(
            output_dir=str(tmp_path), max_steps=4, seq_len=32,
            global_batch_size=8, warmup_steps=1, save_steps=0,
            eval_steps=2, max_eval_batches=2, logging_steps=0,
            strategy=[("fsdp", {})], save_on_exit=False)
        tr = Trainer(_model(), args, _data, eval_data=_data)
        tr.train()
        loss = tr.evaluate()
        assert np.isfinite(loss)
        tr.ckpt.close()

    def test_lr_schedules(self, tmp_path):
        import optax

        for kind in ("cosine", "linear", "constant"):
            args = TrainingArgs(output_dir=str(tmp_path), max_steps=10,
                                lr_schedule=kind, warmup_steps=2)
            tr = Trainer.__new__(Trainer)
            tr.args = args
            sched = tr._make_schedule(optax)
            assert float(sched(0)) <= args.learning_rate
            assert np.isfinite(float(sched(9)))


class TestTunedConfigLoop:
    """The closed auto-tuning loop: master → agent ParalConfigTuner → file
    → trainer ParalConfigListener → ElasticDataLoader/ckpt cadence.

    Parity: reference trainer/torch/elastic/dataloader.py:97-133."""

    def test_master_tunes_loader_mid_epoch(self, tmp_path):
        from dlrover_wuqiong_tpu.agent.config_tuner import ParalConfigTuner
        from dlrover_wuqiong_tpu.agent.master_client import MasterClient
        from dlrover_wuqiong_tpu.common import messages as msg
        from dlrover_wuqiong_tpu.data.elastic_dataset import (
            ElasticDataLoader,
            ElasticDistributedSampler,
        )
        from dlrover_wuqiong_tpu.master.master import JobMaster

        master = JobMaster(min_nodes=1, max_nodes=1)
        master.prepare()
        try:
            mc = MasterClient(master.addr, node_id=0)
            tuner = ParalConfigTuner(
                mc, config_path=str(tmp_path / "paral.json"))

            vocab, seq = 512, 32
            rng = np.random.default_rng(0)
            table = rng.integers(0, vocab, (4096, seq + 1))

            def read_sample(i):
                return {"input_ids": table[i, :-1], "labels": table[i, 1:]}

            batch_sizes = []

            def collate(buf):
                batch_sizes.append(len(buf))
                return jax.tree.map(lambda *xs: np.stack(xs), *buf)

            loader = ElasticDataLoader(
                read_sample, batch_size=8,
                sampler=ElasticDistributedSampler(dataset_size=4096),
                collate=collate)

            def push(step, metrics):
                if step == 2:  # mid-training: the master retunes
                    master.update_paral_config(msg.ParallelConfig(
                        dataloader_batch_size=16, ckpt_interval_steps=50))
                    tuner.poll_once()

            args = TrainingArgs(
                output_dir=str(tmp_path / "out"), max_steps=8,
                global_batch_size=8, seq_len=seq, warmup_steps=1,
                logging_steps=2, save_steps=0, save_on_exit=False,
                tune_config_steps=1, strategy=[("fsdp", {})])
            tr = Trainer(_model(), args, loader, callbacks=[push])
            tr.train()
            # the loader really emitted differently-sized batches mid-epoch
            assert 8 in batch_sizes and 16 in batch_sizes, batch_sizes
            assert batch_sizes[-1] == 16
            # ckpt cadence followed the master's tuning
            assert tr.args.save_steps == 50
            tr.ckpt.close()
        finally:
            import os

            from dlrover_wuqiong_tpu.common.constants import ConfigPath

            os.environ.pop(ConfigPath.ENV_PARAL_CONFIG, None)
            master.stop()
            MasterClient.reset()


class TestTrainerDepth:
    """Weak-spot coverage (VERDICT r2 #8): callbacks, profiler window,
    save-on-exit, eval cadence asserted tightly."""

    def test_callbacks_cadence_and_metrics(self, tmp_path):
        seen = []
        args = TrainingArgs(
            output_dir=str(tmp_path), max_steps=12, global_batch_size=8,
            seq_len=32, warmup_steps=1, logging_steps=3, save_steps=0,
            save_on_exit=False, strategy=[("fsdp", {})])
        Trainer(_model(), args, _data,
                callbacks=[lambda s, m: seen.append((s, m))]).train()
        assert [s for s, _ in seen] == [3, 6, 9, 12]
        for _, m in seen:
            assert {"loss", "tokens_per_sec"} <= set(m)
            assert np.isfinite(m["loss"]) and m["tokens_per_sec"] > 0

    def test_profiler_window_produces_op_profile(self, tmp_path):
        args = TrainingArgs(
            output_dir=str(tmp_path / "out"), max_steps=6,
            global_batch_size=8, seq_len=32, warmup_steps=1,
            logging_steps=0, save_steps=0, save_on_exit=False,
            profile_trace_dir=str(tmp_path / "trace"),
            profile_start_step=2, profile_end_step=4,
            strategy=[("fsdp", {})])
        tr = Trainer(_model(), args, _data)
        tr.train()
        assert tr.profiler.last_profile is not None
        cats = tr.profiler.last_profile.categories
        assert "matmul" in cats and cats["matmul"] > 0
        import glob

        assert glob.glob(str(tmp_path / "trace" / "plugins" / "profile" /
                             "*" / "*.xplane.pb"))

    def test_save_on_exit_persists_after_crash(self, tmp_path):
        """A mid-train exception must still leave a committed checkpoint
        at the crash step (the finally-block save)."""
        class Boom(RuntimeError):
            pass

        def exploding_cb(step, metrics):
            if step >= 4:
                raise Boom()

        args = TrainingArgs(
            output_dir=str(tmp_path), max_steps=20, global_batch_size=8,
            seq_len=32, warmup_steps=1, logging_steps=2, save_steps=0,
            save_on_exit=True, strategy=[("fsdp", {})])
        tr = Trainer(_model(), args, _data, callbacks=[exploding_cb])
        with pytest.raises(Boom):
            tr.train()
        tracker = (tmp_path / "checkpoints" /
                   "latest_checkpointed_iteration.txt")
        assert tracker.exists()
        assert int(tracker.read_text()) == 4
        tr.ckpt.close()

    def test_eval_cadence(self, tmp_path):
        eval_calls = []

        def eval_data(step):
            eval_calls.append(step)
            return _data(step)

        args = TrainingArgs(
            output_dir=str(tmp_path), max_steps=8, global_batch_size=8,
            seq_len=32, warmup_steps=1, logging_steps=0, save_steps=0,
            eval_steps=4, max_eval_batches=2, save_on_exit=False,
            strategy=[("fsdp", {})])
        Trainer(_model(), args, _data, eval_data=eval_data).train()
        # 8 steps / eval every 4 = 2 eval passes x 2 batches each
        assert len(eval_calls) == 4


class TestWireDtypeTrainer:
    def test_bf16_wire_train_save_resume(self, tmp_path):
        """ckpt_wire_dtype="bf16" plumbs through to the checkpointer:
        half-width shards on disk, resume still lands on the committed
        step (values bf16-quantized — the documented contract)."""
        import json as _json

        args = TrainingArgs(
            output_dir=str(tmp_path), max_steps=4, seq_len=32,
            global_batch_size=8, warmup_steps=1, save_steps=2,
            logging_steps=0, strategy=[("fsdp", {})],
            ckpt_wire_dtype="bf16")
        tr1 = Trainer(_model(), args, _data)
        tr1.train()
        tr1.ckpt.close()
        AsyncCheckpointSaver.reset()
        # f32 params were staged as bf16 on disk
        sdir = tmp_path / "checkpoints" / "checkpoint-4"
        metas = [t for mf in sdir.glob("meta_rank*.json")
                 for t in _json.loads(mf.read_text())["tensors"]]
        kinds = {t["dtype"] for t in metas
                 if "wte" in t["name"] or "kernel" in t["name"]}
        assert kinds == {"bfloat16"}, kinds

        args2 = dataclasses.replace(args, max_steps=6)
        tr2 = Trainer(_model(), args2, _data)
        out = tr2.train()
        assert out["final_step"] == 6
        assert int(np.asarray(jax.tree.leaves(tr2.state.step)[0])) == 6
        tr2.ckpt.close()

"""High-level Trainer tests (ATorchTrainer parity).

Runs the full stack on the virtual CPU mesh: strategy → sharded step →
flash ckpt save/resume → eval → callbacks.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_wuqiong_tpu.checkpoint.ckpt_saver import AsyncCheckpointSaver
from dlrover_wuqiong_tpu.models.gpt import GPT, GPTConfig
from dlrover_wuqiong_tpu.trainer.trainer import Trainer, TrainingArgs


@pytest.fixture(autouse=True)
def _fresh_saver():
    AsyncCheckpointSaver.reset()
    yield
    AsyncCheckpointSaver.reset()


def _model():
    return GPT(dataclasses.replace(GPTConfig.nano(), dtype=jnp.float32,
                                   use_flash_attention=False, remat=False))


def _data(step, batch=8, seq=32, vocab=512):
    rng = np.random.default_rng(step % 4)  # small cycling dataset
    x = rng.integers(0, vocab, (batch, seq + 1))
    return {"input_ids": x[:, :-1], "labels": x[:, 1:]}


class TestTrainer:
    def test_train_loss_decreases_and_saves(self, tmp_path):
        seen = []
        args = TrainingArgs(
            output_dir=str(tmp_path), max_steps=24, global_batch_size=8,
            seq_len=32, learning_rate=1e-2, warmup_steps=2,
            logging_steps=4, save_steps=10, strategy=[("fsdp", {})])
        tr = Trainer(_model(), args, _data,
                     callbacks=[lambda s, m: seen.append((s, m["loss"]))])
        out = tr.train()
        assert out["final_step"] == 24
        assert seen and seen[-1][1] < seen[0][1]  # loss decreased
        # checkpoints committed on the save cadence + exit
        tracker = (tmp_path / "checkpoints" /
                   "latest_checkpointed_iteration.txt")
        assert tracker.exists()
        assert int(tracker.read_text()) == 24
        tr.ckpt.close()

    def test_resume_continues_from_checkpoint(self, tmp_path):
        args = TrainingArgs(
            output_dir=str(tmp_path), max_steps=6, seq_len=32,
            global_batch_size=8, warmup_steps=1, save_steps=3,
            logging_steps=2, strategy=[("fsdp", {})])
        tr1 = Trainer(_model(), args, _data)
        tr1.train()
        tr1.ckpt.close()
        AsyncCheckpointSaver.reset()

        args2 = dataclasses.replace(args, max_steps=10)
        tr2 = Trainer(_model(), args2, _data)
        out = tr2.train()
        # resumed (step 6) rather than restarting from zero
        assert int(np.asarray(jax.tree.leaves(tr2.state.step)[0])) == 10
        tracker = (tmp_path / "checkpoints" /
                   "latest_checkpointed_iteration.txt")
        assert int(tracker.read_text()) == 10
        tr2.ckpt.close()

    def test_evaluate(self, tmp_path):
        args = TrainingArgs(
            output_dir=str(tmp_path), max_steps=4, seq_len=32,
            global_batch_size=8, warmup_steps=1, save_steps=0,
            eval_steps=2, max_eval_batches=2, logging_steps=0,
            strategy=[("fsdp", {})], save_on_exit=False)
        tr = Trainer(_model(), args, _data, eval_data=_data)
        tr.train()
        loss = tr.evaluate()
        assert np.isfinite(loss)
        tr.ckpt.close()

    def test_lr_schedules(self, tmp_path):
        import optax

        for kind in ("cosine", "linear", "constant"):
            args = TrainingArgs(output_dir=str(tmp_path), max_steps=10,
                                lr_schedule=kind, warmup_steps=2)
            tr = Trainer.__new__(Trainer)
            tr.args = args
            sched = tr._make_schedule(optax)
            assert float(sched(0)) <= args.learning_rate
            assert np.isfinite(float(sched(9)))

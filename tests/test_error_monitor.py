"""Error-class catalogue + device-probe hang localization tests.

Parity targets: reference master/monitor/error_monitor.py (classification →
relaunch policy) and fault_tolerance/hanging_detector.py:86 (localizing the
wedged rank).
"""

import json
import threading
import time

import pytest

from dlrover_wuqiong_tpu.common import messages as msg
from dlrover_wuqiong_tpu.common.constants import NodeExitReason
from dlrover_wuqiong_tpu.common.util import is_oom_error
from dlrover_wuqiong_tpu.diagnosis.manager import (
    CheckTrainingHangOperator,
    DiagnosisDataManager,
    InferenceChain,
    ResolveHangCauseOperator,
)
from dlrover_wuqiong_tpu.diagnosis.probe import DeviceProber
from dlrover_wuqiong_tpu.master.error_monitor import (
    ErrorMonitor,
    classify_error,
)


class TestClassify:
    @pytest.mark.parametrize("text,cls,reason,relaunch", [
        ("XlaRuntimeError: RESOURCE_EXHAUSTED: Out of memory allocating",
         "device_oom", NodeExitReason.OOM, True),
        ("worker exit_code=137", "host_oom", NodeExitReason.OOM, True),
        ("INTERNAL: libtpu.so initialization failed", "hardware",
         NodeExitReason.HARDWARE_ERROR, True),
        ("DEADLINE_EXCEEDED: barrier timeout waiting for coordinator",
         "network", NodeExitReason.KILLED, True),
        ("SIGTERM received, pod evicted", "preempted",
         NodeExitReason.KILLED, True),
        ("ModuleNotFoundError: No module named 'foo'", "user_code",
         NodeExitReason.FATAL_ERROR, False),
        ("TypeError: unsupported operand", "user_code",
         NodeExitReason.FATAL_ERROR, False),
        ("watchdog fired: training hang", "hang", NodeExitReason.HANG,
         True),
        ("something entirely else", "unknown",
         NodeExitReason.UNKNOWN_ERROR, True),
    ])
    def test_catalog(self, text, cls, reason, relaunch):
        got_cls, got_reason, got_relaunch = classify_error(text)
        assert (got_cls, got_reason, got_relaunch) == (cls, reason,
                                                       relaunch)

    def test_traceback_final_line_beats_frame_paths(self):
        """A TypeError raised inside socket.py must classify as user_code,
        not network — the exception line wins over frame paths."""
        tb = ('exit_code=1\nTraceback (most recent call last):\n'
              '  File "/usr/lib/python3.12/socket.py", line 10, in recv\n'
              '    coordinator.connect()\n'
              "TypeError: unsupported operand type(s)")
        cls, reason, relaunch = classify_error(tb)
        assert (cls, relaunch) == ("user_code", False)

    def test_unlisted_exception_final_line_is_user_code(self):
        cls, reason, relaunch = classify_error(
            "Traceback ...\nZeroDivisionError: division by zero")
        assert (cls, relaunch) == ("user_code", False)

    def test_infra_exception_final_line_not_user_code(self):
        cls, _, relaunch = classify_error(
            "Traceback ...\nConnectionResetError: [Errno 104]")
        assert (cls, relaunch) == ("network", True)

    def test_multiline_xla_status_classifies_from_full_text(self):
        cls, _, _ = classify_error(
            "XlaRuntimeError: RESOURCE_EXHAUSTED: out of memory\n"
            "Allocation breakdown:\n  buffer 1: 2.0GiB\n  Total: 15.1GiB")
        assert cls == "device_oom"

    # -------- catalogue precedence: first match wins, top to bottom ----

    @pytest.mark.parametrize("text,winner", [
        # device_oom outranks network even when both patterns match
        ("RESOURCE_EXHAUSTED: allocation failed, socket buffers full",
         "device_oom"),
        # hardware outranks network on a libtpu fault seen over a socket
        ("libtpu halt: connection reset by interconnect probe", "hardware"),
        # network outranks preempted when both appear on one line
        ("DEADLINE_EXCEEDED waiting for SIGTERM drain", "network"),
        # host_oom (exit 137) outranks the generic preempt/evict class
        ("exit_code=137 pod evicted by kubelet", "host_oom"),
    ])
    def test_pattern_precedence_order(self, text, winner):
        cls, _, _ = classify_error(text)
        assert cls == winner

    def test_final_line_pass_outranks_full_text_pass(self):
        """Pass 1 (catalogue vs final line) must win over pass 3
        (catalogue vs full text): an OOM traceback whose earlier frames
        mention the coordinator is still device_oom."""
        tb = ("connecting to coordinator failed once, retried\n"
              "XlaRuntimeError: RESOURCE_EXHAUSTED: out of memory")
        cls, _, _ = classify_error(tb)
        assert cls == "device_oom"

    def test_transient_classes_never_cut_relaunch(self):
        em = ErrorMonitor()
        for pod in (1, 2, 3):
            em.process_error(0, 0, "SIGTERM received, pod evicted",
                             node_id=pod)
        assert em.repeated_class(0) is None  # preemption keeps relaunching

    def test_node_level_always_gets_replacement(self):
        em = ErrorMonitor()
        reason, relaunch = em.process_error(
            0, 0, "TypeError: agent crashed", level="node")
        assert relaunch is True
        assert reason != NodeExitReason.FATAL_ERROR

    def test_repeated_class_detection(self):
        em = ErrorMonitor()
        for rc in range(3):
            em.process_error(7, rc, "RESOURCE_EXHAUSTED: OOM")
        assert em.repeated_class(7) == "device_oom"
        em2 = ErrorMonitor()
        em2.process_error(7, 0, "RESOURCE_EXHAUSTED")
        em2.process_error(7, 1, "connection refused")
        em2.process_error(7, 2, "RESOURCE_EXHAUSTED")
        assert em2.repeated_class(7) is None

    def test_dedupe_same_restart(self):
        em = ErrorMonitor()
        em.process_error(1, 0, "RESOURCE_EXHAUSTED")
        em.process_error(1, 0, "RESOURCE_EXHAUSTED again")
        assert len(em.error_class_history(1)) == 1

    def test_replacement_pod_recurrence_accumulates(self):
        """The same class failing on successive REPLACEMENT pods (fresh
        restart_count=0 each time) must still build the rank's history —
        that recurrence is what repeated_class exists to catch."""
        em = ErrorMonitor()
        for pod in (10, 11, 12):  # rank 0 relaunched as new pods
            em.process_error(0, 0, "libtpu driver wedged", node_id=pod)
        assert len(em.error_class_history(0)) == 3
        assert em.repeated_class(0) == "hardware"

    def test_unknown_class_never_triggers_cutoff(self):
        em = ErrorMonitor()
        for pod in (1, 2, 3):
            em.process_error(0, 0, "exit_code=1", node_id=pod)
        assert em.repeated_class(0) is None

    def test_preemption_storm_never_triggers_cutoff(self):
        """The error_monitor.py comment promises the repeated-class cutoff
        never fires on preemption-class errors — pin it well past the
        min_repeats threshold (a capacity crunch can preempt the same rank
        ten times in a row and relaunching is STILL the right call)."""
        em = ErrorMonitor()
        for pod in range(10):
            em.process_error(3, 0, "SIGTERM: node preempted by scheduler",
                             node_id=pod)
        assert em.repeated_class(3) is None
        assert em.repeated_class(3, min_repeats=2) is None
        # and the classification itself stays relaunchable
        _, relaunch = em.process_error(3, 0, "exit_code=143", node_id=99)
        assert relaunch is True

    def test_network_storm_never_triggers_cutoff(self):
        """Coordinator blips (master restarts!) are transient by decree:
        a worker that fails with connection-refused N times while the
        master recovers must keep its relaunch budget."""
        em = ErrorMonitor()
        for pod in range(5):
            em.process_error(1, 0, "ConnectionRefusedError: [Errno 111]",
                             node_id=pod)
        assert em.repeated_class(1) is None

    def test_cutoff_resumes_after_transient_interleave(self):
        """A transient error BREAKS a hardware streak (set(tail) != 1),
        but a fresh uninterrupted streak after it still fires."""
        em = ErrorMonitor()
        em.process_error(2, 0, "libtpu wedged", node_id=0)
        em.process_error(2, 0, "libtpu wedged", node_id=1)
        em.process_error(2, 0, "SIGTERM preempt", node_id=2)
        assert em.repeated_class(2) is None
        for pod in (3, 4, 5):
            em.process_error(2, 0, "libtpu wedged", node_id=pod)
        assert em.repeated_class(2) == "hardware"


class TestPreemptionDisambiguation:
    """exit_code=137 is ambiguous (OOM-killer and preemption SIGKILL both
    exit 137).  With the policy engine's rate estimate bound, a BARE 137
    during a high-preemption regime classifies as preemption — TRANSIENT —
    so the repeated-class cutoff no longer depends on relaunch_always to
    keep a kill-stormed rank alive (ROADMAP item 2 leftover)."""

    def test_no_estimator_keeps_catalog_behavior(self):
        em = ErrorMonitor()
        reason, relaunch = em.process_error(0, 0, "worker exit_code=137")
        assert em.error_class_history(0) == [(0, "host_oom")]
        assert reason == NodeExitReason.OOM and relaunch is True

    def test_low_rate_regime_stays_host_oom(self):
        # MTBF 3600s (one kill/hour) is NOT a storm: trust the OOM prior
        em = ErrorMonitor(preemption_rate_fn=lambda: 1.0 / 3600.0)
        em.process_error(0, 0, "worker exit_code=137")
        assert em.error_class_history(0) == [(0, "host_oom")]

    def test_kill_storm_reclassifies_bare_137(self):
        # MTBF 60s: the regime prior says SIGKILL = preemption
        em = ErrorMonitor(preemption_rate_fn=lambda: 1.0 / 60.0)
        reason, relaunch = em.process_error(0, 0, "worker exit_code=137")
        assert em.error_class_history(0) == [(0, "preempted")]
        assert reason == NodeExitReason.KILLED and relaunch is True

    def test_storm_of_137s_never_triggers_cutoff(self):
        # the point of the satellite: a kill storm of bare 137s used to
        # build a host_oom streak and trip repeated_class — now it stays
        # TRANSIENT and the rank keeps its relaunch budget
        em = ErrorMonitor(preemption_rate_fn=lambda: 1.0 / 60.0)
        for pod in range(5):
            em.process_error(3, 0, "worker exit_code=137", node_id=pod)
        assert em.repeated_class(3) is None
        assert em.repeated_class(3, min_repeats=2) is None

    def test_explicit_oom_evidence_beats_the_regime_prior(self):
        # "oom-killed" text is direct evidence — regime or not, it's OOM
        em = ErrorMonitor(preemption_rate_fn=lambda: 1.0 / 60.0)
        em.process_error(0, 0, "exit_code=137 container oom-killed")
        assert em.error_class_history(0) == [(0, "host_oom")]

    def test_estimator_failure_degrades_to_catalog(self):
        def boom():
            raise RuntimeError("estimator gone")

        em = ErrorMonitor(preemption_rate_fn=boom)
        em.process_error(0, 0, "worker exit_code=137")
        assert em.error_class_history(0) == [(0, "host_oom")]

    def test_bind_after_construction_with_cutoff(self):
        em = ErrorMonitor()
        em.bind_preemption_estimator(lambda: 1.0 / 60.0,
                                     mtbf_cutoff_s=30.0)
        # MTBF 60s but cutoff tightened to 30s → not a storm
        em.process_error(0, 0, "worker exit_code=137")
        assert em.error_class_history(0) == [(0, "host_oom")]

    def test_real_estimator_end_to_end(self):
        """Drive the actual EWMA estimator into a storm regime and watch
        the catalogue flip: the same payload classifies host_oom cold and
        preempted hot."""
        from dlrover_wuqiong_tpu.brain.policy import (
            PreemptionRateEstimator)

        t = [0.0]
        est = PreemptionRateEstimator(tau_s=600.0, clock=lambda: t[0])
        em = ErrorMonitor(preemption_rate_fn=lambda: est.rate_per_s(t[0]))
        em.process_error(0, 0, "worker exit_code=137", node_id=0)
        assert em.error_class_history(0) == [(0, "host_oom")]
        for _ in range(6):  # a kill a minute
            t[0] += 60.0
            est.record(t[0])
        em.process_error(0, 0, "worker exit_code=137", node_id=1)
        assert em.error_class_history(0)[-1] == (0, "preempted")

    def test_master_binds_policy_estimator(self):
        from dlrover_wuqiong_tpu.brain.policy import PolicyEngine
        from dlrover_wuqiong_tpu.master.master import JobMaster

        engine = PolicyEngine()
        master = JobMaster(min_nodes=1, max_nodes=1,
                           policy_engine=engine)
        em = master.job_manager.error_monitor
        assert em._preempt_rate_fn == engine.estimator.rate_per_s


class TestIsOomError:
    def test_narrowed_heuristic(self):
        class XlaRuntimeError(Exception):
            pass

        assert is_oom_error(XlaRuntimeError("RESOURCE_EXHAUSTED: foo"))
        assert is_oom_error(XlaRuntimeError("Out of memory while running"))
        # host MemoryError / arbitrary "memory" strings are NOT device OOM
        assert not is_oom_error(MemoryError("out of memory"))
        assert not is_oom_error(ValueError("insufficient memory budget"))
        assert not is_oom_error(XlaRuntimeError("INVALID_ARGUMENT: shape"))


class TestRelaunchPolicy:
    def test_user_code_error_not_relaunched_via_rpc(self):
        """Full path: report_failure RPC → catalogue → no relaunch."""
        from dlrover_wuqiong_tpu.agent.master_client import MasterClient
        from dlrover_wuqiong_tpu.master.master import JobMaster

        master = JobMaster(min_nodes=1, max_nodes=1)
        master.prepare()
        try:
            c = MasterClient(master.addr, node_id=0)
            c.register_node(0)
            c.report_failure("ModuleNotFoundError: no module named 'x'",
                             restart_count=0)
            node = master.job_manager.get_node(0)
            assert node.exit_reason == NodeExitReason.FATAL_ERROR
            assert not node.relaunchable
        finally:
            master.stop()
            MasterClient.reset()

    def test_repeated_oom_stops_relaunching(self):
        from dlrover_wuqiong_tpu.master.job_manager import LocalJobManager

        jm = LocalJobManager(max_relaunch_count=10)
        node = jm.register_node("worker", 0, rank_index=0)
        node.exit_reason = NodeExitReason.OOM
        for rc in range(3):
            jm.error_monitor.process_error(0, rc, "RESOURCE_EXHAUSTED")
        assert jm._should_relaunch(node) is False

    def test_scheduler_raw_exit_reason_normalized(self):
        """Watcher-observed failures carry raw strings; process_event must
        classify them so the relaunch table and history work."""
        from dlrover_wuqiong_tpu.common.constants import (
            NodeEventType,
            NodeStatus,
        )
        from dlrover_wuqiong_tpu.common.node import Node, NodeEvent
        from dlrover_wuqiong_tpu.master.job_manager import LocalJobManager

        jm = LocalJobManager(max_relaunch_count=3)
        node = jm.register_node("worker", 0, rank_index=0)
        node.update_status(NodeStatus.RUNNING)
        node.config_resource.memory_mb = 1000
        ev_node = Node("worker", 0)
        ev_node.status = NodeStatus.FAILED
        ev_node.exit_reason = "exit_code=137"  # scheduler's raw string
        jm.process_event(NodeEvent(NodeEventType.MODIFIED, ev_node))
        # classified to OOM → history recorded + the 1.5x memory escalation
        # applied on relaunch (exit_reason itself is consumed by the local
        # in-place relaunch)
        assert jm.error_monitor.error_class_history(0) == [(0, "host_oom")]
        assert node.config_resource.memory_mb == 1500

    def test_single_oom_still_relaunches_with_bump(self):
        from dlrover_wuqiong_tpu.master.job_manager import LocalJobManager

        jm = LocalJobManager(max_relaunch_count=10)
        node = jm.register_node("worker", 0, rank_index=0)
        node.exit_reason = NodeExitReason.OOM
        node.config_resource.memory_mb = 1000
        jm.error_monitor.process_error(0, 0, "RESOURCE_EXHAUSTED")
        assert jm._should_relaunch(node) is True
        assert node.config_resource.memory_mb == 1500


class TestDeviceProber:
    def test_healthy_device_probes_ok(self):
        reports = []

        class FakeMC:
            def report_diagnosis(self, payload_type, content):
                reports.append((payload_type, json.loads(content)))

        prober = DeviceProber(FakeMC(), timeout=30.0)
        res = prober.probe_once()
        assert res["ok"] is True
        assert reports and reports[0][0] == "probe"
        assert reports[0][1]["ok"] is True

    def test_wedged_device_reports_blocked(self):
        release = threading.Event()

        def stuck_op():
            release.wait(30)

        prober = DeviceProber(None, timeout=0.2, probe_op=stuck_op)
        res = prober.probe_once()
        assert res["ok"] is False
        # a second probe does not stack another blocked thread
        res2 = prober.probe_once()
        assert res2["ok"] is False
        release.set()

    def test_probe_failure_reads_as_hung(self):
        def dying_op():
            raise RuntimeError("device gone")

        prober = DeviceProber(None, timeout=0.3, probe_op=dying_op)
        assert prober.probe_once()["ok"] is False


class TestHangLocalization:
    def _hang_data(self, probes):
        data = DiagnosisDataManager()
        old = time.time() - 3600  # graftlint: disable=wall-clock-duration -- forging node-reported wall timestamps (DiagnosisReport)
        # node 1's step report is NEWEST — oldest-step heuristic alone
        # would blame node 0
        data.store_report(msg.DiagnosisReport(
            node_id=0, payload_type="step", content="5", timestamp=old))
        data.store_report(msg.DiagnosisReport(
            node_id=1, payload_type="step", content="6",
            timestamp=old + 30))
        for node, ok in probes.items():
            data.store_report(msg.DiagnosisReport(
                node_id=node, payload_type="probe",
                content=json.dumps({"ok": ok}), timestamp=time.time()))
        return data

    def test_idle_device_overrides_oldest_step(self):
        """Node 1 probes idle while node 0 is wedged → node 1 never joined
        the collective and is named the culprit despite newer steps."""
        data = self._hang_data({0: False, 1: True})
        chain = InferenceChain([CheckTrainingHangOperator(timeout=60),
                                ResolveHangCauseOperator()])
        culprits = [c for c in chain.run(data) if c.name == "hang_culprit"]
        assert culprits and culprits[0].node_id == 1
        assert "never joined the collective" in culprits[0].detail

    def test_all_wedged_falls_back_to_oldest_step(self):
        data = self._hang_data({0: False, 1: False})
        chain = InferenceChain([CheckTrainingHangOperator(timeout=60),
                                ResolveHangCauseOperator()])
        culprits = [c for c in chain.run(data) if c.name == "hang_culprit"]
        assert culprits and culprits[0].node_id == 0
        assert "stalled first" in culprits[0].detail

    def test_stale_probes_ignored(self):
        data = DiagnosisDataManager()
        old = time.time() - 3600  # graftlint: disable=wall-clock-duration -- forging node-reported wall timestamps (DiagnosisReport)
        data.store_report(msg.DiagnosisReport(
            node_id=0, payload_type="step", content="5", timestamp=old))
        data.store_report(msg.DiagnosisReport(
            node_id=0, payload_type="probe",
            content=json.dumps({"ok": True}), timestamp=old))
        assert data.probe_status() == {}

"""Incident timeline: one causally-ordered observability plane.

Pins the ADD-ONLY schemas (TIMELINE_EVENT_KEYS, the Timeline* message
family, the flight envelope's anchor fields), the monotonic→wall
anchoring under skewed process clocks, the (epoch, seq) causal order
with nondecreasing-clamped wall times, cross-generation trace-tree
merge with cumulative-re-flush dedup, byte-equal determinism of the
assembler, the Perfetto export, the downtime-attribution narrative,
and the tools/incident_report.py rc/sha contract.
"""

import dataclasses
import json
import os
import subprocess
import sys

import pytest

from dlrover_wuqiong_tpu.common import messages as msg
from dlrover_wuqiong_tpu.master.journal import MasterJournal
from dlrover_wuqiong_tpu.telemetry import (
    TIMELINE_EVENT_KEYS,
    TIMELINE_SCHEMA_VERSION,
    FlightRecorder,
    assemble_incident,
    build_narrative,
    export_perfetto,
    incident_json,
    incident_sha256,
    trace_tree,
)
from dlrover_wuqiong_tpu.telemetry import timeline as tl

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_dump(ckpt_dir, role, pid, flushed_at, flushed_mono, events,
                ledger=None, serve_ledger=None, seq=1, reason="test"):
    """A flight dump written straight in the envelope schema — the tests
    need pids and clocks no single process could produce."""
    out = os.path.join(ckpt_dir, "flight")
    os.makedirs(out, exist_ok=True)
    payload = {"schema": 1, "role": role, "pid": pid, "reason": reason,
               "flushed_at": flushed_at, "flushed_mono": flushed_mono,
               "ledger": ledger, "serve_ledger": serve_ledger,
               "events": events}
    path = os.path.join(out, f"{role}-{pid}-{reason}-{seq}.json")
    with open(path, "w") as f:
        json.dump(payload, f)
    return path


def _span(trace_id, span_id, name, t_wall, t_mono, pid, role="worker",
          parent=""):
    return {"t_wall": t_wall, "t_mono": t_mono, "kind": "span",
            "name": name,
            "data": {"trace_id": trace_id, "span_id": span_id,
                     "name": name, "parent_span": parent, "pid": pid,
                     "role": role, "t_wall": t_wall, "dur_s": 0.5,
                     "status": "ok", "attrs": {}}}


# -------------------------------------------------------------- anchoring


class TestAnchoring:
    def test_skewed_wall_clock_is_corrected(self):
        # the process's own wall clock is 50s fast; its monotonic clock
        # plus the flush anchor pair recovers the TRUE wall time
        dump = {"flushed_at": 1000.0, "flushed_mono": 400.0}
        evt = {"t_wall": 1045.0, "t_mono": 395.0}  # wall lies, mono doesn't
        assert tl.anchored_wall(dump, evt) == pytest.approx(995.0)

    def test_two_skewed_processes_interleave_correctly(self, tmp_path):
        # A's wall is +100s, B's is -7s; both flushed at true wall 1000.
        # Anchoring must interleave their events by TRUE time: A@990,
        # B@994, A@998 — the recorded t_wall order (1090, 987, 1098)
        # would have said A, B, A too, but with garbage gaps; a third
        # event pair proves the ORDER flips vs raw walls
        ck = str(tmp_path)
        _write_dump(ck, "workerA", 111, 1000.0, 500.0, [
            {"t_wall": 1090.0, "t_mono": 490.0, "kind": "mark",
             "name": "a-early", "data": {}},
            {"t_wall": 1098.0, "t_mono": 498.0, "kind": "mark",
             "name": "a-late", "data": {}}])
        _write_dump(ck, "workerB", 222, 1000.0, 800.0, [
            {"t_wall": 987.0, "t_mono": 794.0, "kind": "mark",
             "name": "b-mid", "data": {}}])
        events, _ = tl.read_flight_events(ck)
        marks = [e for e in events if e["kind"] == "mark"]
        assert [e["name"] for e in sorted(marks,
                                          key=lambda e: e["t_wall"])] == \
            ["a-early", "b-mid", "a-late"]
        by_name = {e["name"]: e["t_wall"] for e in marks}
        assert by_name["a-early"] == pytest.approx(990.0)
        assert by_name["b-mid"] == pytest.approx(994.0)
        assert by_name["a-late"] == pytest.approx(998.0)

    def test_old_dump_without_anchor_falls_back_to_wall(self):
        # pre-anchor dumps have no flushed_mono; pre-anchor events have
        # no t_mono — both degrade to the recorded wall clock
        assert tl.anchored_wall({"flushed_at": 1000.0},
                                {"t_wall": 990.0}) == 990.0
        assert tl.anchored_wall(
            {"flushed_at": 1000.0, "flushed_mono": 1.0},
            {"t_wall": 990.0}) == 990.0


# ---------------------------------------------------------- journal order


class TestJournalEvents:
    def test_append_stamps_wall_ts(self, tmp_path):
        j = MasterJournal(str(tmp_path / "j"), fsync=False)
        j.append("register", {"node_id": 0})
        j.close()
        with open(tmp_path / "j" / "journal.frames", "rb") as f:
            frames = [json.loads(ln) for ln in f.read().splitlines() if ln]
        assert all("ts" in fr and fr["ts"] > 0 for fr in frames)

    def test_ts_less_frames_tolerated(self, tmp_path):
        # frames from a pre-ts journal replay fine: t_wall inherits the
        # last seen wall, (epoch, seq) still orders them
        jd = tmp_path / "j"
        jd.mkdir()
        with open(jd / "journal.frames", "w") as f:
            f.write(json.dumps({"seq": 1, "kind": "epoch",
                                "ts": 100.0,
                                "data": {"epoch": 1}}) + "\n")
            f.write(json.dumps({"seq": 2, "kind": "register",
                                "data": {"node_id": 0}}) + "\n")
        events = tl.read_journal_events(str(jd))
        assert [(e["seq"], e["t_wall"]) for e in events] == \
            [(1, 100.0), (2, 100.0)]

    def test_regressing_wall_clamped_to_causal_order(self, tmp_path):
        # a wall step backwards between master incarnations must not fold
        # the merge order back over the journal's causal order
        jd = tmp_path / "j"
        jd.mkdir()
        with open(jd / "journal.frames", "w") as f:
            for seq, ts in ((1, 100.0), (2, 90.0), (3, 95.0)):
                f.write(json.dumps({"seq": seq, "kind": "register",
                                    "ts": ts, "data": {}}) + "\n")
        events = tl.read_journal_events(str(jd))
        walls = [e["t_wall"] for e in events]
        assert walls == sorted(walls)
        assert [e["seq"] for e in events] == [1, 2, 3]

    def test_epoch_tagging_across_bump(self, tmp_path):
        # two master incarnations on one journal, the real lifecycle:
        # load() + open_epoch() per incarnation
        jd = str(tmp_path / "j")
        j = MasterJournal(jd, fsync=False)
        j.load()
        j.open_epoch()
        j.append("register", {"node_id": 0})
        j.close()
        j2 = MasterJournal(jd, fsync=False)
        j2.load()
        j2.open_epoch()
        j2.append("heartbeat", {"node_id": 0})
        j2.close()
        events = tl.read_journal_events(jd)
        keys = [(e["epoch"], e["seq"]) for e in events]
        assert keys == sorted(keys) and len(keys) == len(set(keys))
        assert events[0]["epoch"] == 1
        assert events[-1]["epoch"] == 2
        assert [e["epoch"] for e in events] == [1, 1, 2, 2]


# ------------------------------------------------------------ schema pins


class TestAddOnlySchemas:
    # Event envelope — ADD-ONLY: the drills, incident_report and the
    # Perfetto export key on these; new keys append, never rename.
    # Pin source of truth: analysis/schema.lock.json (graftlint schema
    # engine); one hand-pinned canary per surface.
    def test_event_keys_add_only(self, schema_lock):
        for k in schema_lock["registries"]["TIMELINE_EVENT_KEYS"]:
            assert k in TIMELINE_EVENT_KEYS, f"removed event key {k!r}"
        assert "trace_id" in TIMELINE_EVENT_KEYS   # hand-pinned canary
        assert TIMELINE_SCHEMA_VERSION >= 1

    def test_timeline_messages_add_only(self, schema_lock):
        locked_q = {f["name"] for f in
                    schema_lock["messages"]["TimelineQuery"]["fields"]}
        q = {f.name for f in dataclasses.fields(msg.TimelineQuery)}
        assert locked_q <= q
        locked_r = {f["name"] for f in
                    schema_lock["messages"]["TimelineResponse"]["fields"]}
        r = {f.name for f in dataclasses.fields(msg.TimelineResponse)}
        assert locked_r <= r
        assert {"content", "events"} <= r   # hand-pinned canary

    def test_timeline_query_never_journaled(self):
        # POLLING class: a read-only assembly must not grow the journal
        from dlrover_wuqiong_tpu.analysis.protocol_engine import (
            IDEM_VERBS,
            JOURNALED_VERBS,
        )

        assert "TimelineQuery" not in JOURNALED_VERBS
        assert "TimelineQuery" not in IDEM_VERBS

    def test_flight_envelope_anchor_fields(self, tmp_path):
        rec = FlightRecorder()
        rec.record("mark", "x", {})
        path = rec.flush(str(tmp_path), "t")
        with open(path) as f:
            dump = json.load(f)
        for key in ("schema", "role", "pid", "reason", "flushed_at",
                    "flushed_mono", "ledger", "serve_ledger", "events"):
            assert key in dump, f"removed envelope key {key!r}"
        evt = dump["events"][0]
        for key in ("t_wall", "t_mono", "kind", "name", "data"):
            assert key in evt, f"removed event key {key!r}"

    def test_event_builder_matches_pin(self):
        e = tl._event("journal", "k", "n", 1.0)
        assert tuple(e.keys()) == TIMELINE_EVENT_KEYS


# --------------------------------------------------------------- assembly


class TestAssembly:
    def _fixture(self, tmp_path):
        jd = str(tmp_path / "journal")
        ck = str(tmp_path / "ckpt")
        j = MasterJournal(jd, fsync=False)
        j.load()
        j.open_epoch()
        j.append("register", {"node_id": 0})
        j.close()
        j2 = MasterJournal(jd, fsync=False)  # restarted master
        j2.load()
        j2.open_epoch()
        j2.append("policy", {"decision": {"decision_id": 7,
                                          "reason": "drill"}})
        j2.close()
        tid = "t" * 32
        _write_dump(ck, "worker", 111, 1000.0, 500.0, [
            _span(tid, "a" * 16, "serve:admit", 1090.0, 490.0, 111)],
            ledger={"wall_s": 10.0, "states": {"productive": 8.0,
                                               "degraded": 2.0}},
            seq=1)
        # generation 2: re-flush carries gen-1's admit span AGAIN
        # (cumulative ring) plus its own child spans
        _write_dump(ck, "worker", 222, 1002.0, 900.0, [
            _span(tid, "a" * 16, "serve:admit", 1090.0, 890.0, 111),
            _span(tid, "b" * 16, "serve:decode", 1001.0, 899.0, 222,
                  parent="a" * 16),
            _span(tid, "c" * 16, "serve:finish", 1001.5, 899.5, 222,
                  parent="a" * 16)],
            ledger={"wall_s": 5.0, "states": {"productive": 4.0,
                                              "restore_storage": 1.0}},
            seq=1)
        return jd, ck, tid

    def test_byte_equal_determinism(self, tmp_path):
        jd, ck, _ = self._fixture(tmp_path)
        a = incident_json(assemble_incident(journal_dir=jd, ckpt_dir=ck))
        b = incident_json(assemble_incident(journal_dir=jd, ckpt_dir=ck))
        assert a == b
        assert incident_sha256(a) == incident_sha256(b)

    def test_cross_generation_one_tree(self, tmp_path):
        jd, ck, tid = self._fixture(tmp_path)
        report = assemble_incident(journal_dir=jd, ckpt_dir=ck)
        # dedup: the re-flushed admit span appears ONCE
        spans = [e for e in report["events"] if e["kind"] == "span"]
        assert len(spans) == 3
        roots = trace_tree(report["events"], tid)
        assert len(roots) == 1
        root = roots[0]
        assert root["name"] == "serve:admit"
        assert sorted(c["name"] for c in root["children"]) == \
            ["serve:decode", "serve:finish"]
        # the tree joins TWO worker generations (pids)
        pids = {root["pid"]} | {c["pid"] for c in root["children"]}
        assert pids == {111, 222}

    def test_events_json_safe_and_ordered(self, tmp_path):
        jd, ck, _ = self._fixture(tmp_path)
        report = assemble_incident(journal_dir=jd, ckpt_dir=ck)
        json.dumps(report)  # no typed-JSON leftovers, no message objects
        walls = [e["t_wall"] for e in report["events"]]
        assert walls == sorted(walls)
        jkeys = [(e["epoch"], e["seq"]) for e in report["events"]
                 if e["source"] == "journal"]
        assert jkeys == sorted(jkeys) and len(jkeys) == len(set(jkeys))

    def test_counts(self, tmp_path):
        jd, ck, _ = self._fixture(tmp_path)
        c = assemble_incident(journal_dir=jd, ckpt_dir=ck)["counts"]
        assert c["journal_events"] == 4  # fresh-epoch + 3 appends
        assert c["spans"] == 3 and c["traces"] == 1
        assert c["epochs"] == [1, 2]
        assert c["processes"] == [["worker", 111], ["worker", 222]] or \
            c["processes"] == [("worker", 111), ("worker", 222)]


# -------------------------------------------------------------- narrative


class TestNarrative:
    def test_attribution_and_policy_answer(self, tmp_path):
        jd = str(tmp_path / "j")
        j = MasterJournal(jd, fsync=False)
        j.append("epoch", {"epoch": 2})            # master restart
        j.append("recover", {"node_id": 3})        # worker failure
        j.append("policy", {"decision": {"decision_id": 9,
                                         "reason": "raise-cadence"}})
        j.close()
        ledgers = [{"role": "worker", "pid": 1, "ledger": {
            "wall_s": 20.0,
            "states": {"productive": 15.0, "degraded": 2.5,
                       "restore_storage": 1.0, "rework": 0.5}}}]
        narr = build_narrative(tl.read_journal_events(jd), ledgers)
        kinds = {i["kind"]: i for i in narr["incidents"]}
        assert kinds["master_restart"]["attributed_state"] == "degraded"
        assert kinds["master_restart"]["lost_s"] == pytest.approx(2.5)
        assert kinds["worker_failure"]["attributed_state"] == "restore"
        assert kinds["worker_failure"]["lost_s"] == pytest.approx(1.5)
        for i in narr["incidents"]:
            assert i["policy_response"]["decision_id"] == 9
        assert narr["productive_s"] == pytest.approx(15.0)
        assert narr["goodput_fraction"] == pytest.approx(15.0 / 20.0)

    def test_no_incident_without_trigger(self, tmp_path):
        jd = str(tmp_path / "j")
        j = MasterJournal(jd, fsync=False)
        j.append("register", {"node_id": 0})
        j.close()
        narr = build_narrative(tl.read_journal_events(jd), [])
        assert narr["incidents"] == []
        assert narr["policy_decisions"] == 0


# --------------------------------------------------------------- perfetto


class TestPerfettoExport:
    def test_export_contains_processes_spans_instants(self, tmp_path):
        jd = str(tmp_path / "journal")
        ck = str(tmp_path / "ckpt")
        j = MasterJournal(jd, fsync=False)
        j.append("register", {"node_id": 0})
        j.close()
        tid = "t" * 32
        _write_dump(ck, "worker", 111, 1000.0, 500.0, [
            _span(tid, "a" * 16, "serve:admit", 999.0, 499.0, 111),
            {"t_wall": 999.5, "t_mono": 499.5, "kind": "mark",
             "name": "m", "data": {}}])
        report = assemble_incident(journal_dir=jd, ckpt_dir=ck)
        out = str(tmp_path / "trace.json")
        n = export_perfetto(report, out)
        assert n > 0
        with open(out) as f:
            rows = json.load(f)["traceEvents"]
        phases = {r["ph"] for r in rows}
        assert {"M", "X", "i"} <= phases
        meta = {r["pid"]: r["args"]["name"] for r in rows
                if r["ph"] == "M"}
        assert meta[0] == "master(journal)"
        assert meta[111] == "worker"


# ------------------------------------------------------------ CLI contract


class TestIncidentReportCLI:
    def _run(self, *args, env_extra=None):
        env = {k: v for k, v in os.environ.items()
               if k != "DWT_MASTER_ADDR"}
        env["JAX_PLATFORMS"] = "cpu"
        env.update(env_extra or {})
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "incident_report.py"), *args],
            capture_output=True, text=True, env=env, timeout=120)

    def test_no_addr_rc2(self):
        p = self._run()
        assert p.returncode == 2
        assert "error" in json.loads(p.stdout)

    def test_help_rc0_stdout_clean(self):
        p = self._run("-h")
        assert p.returncode == 0
        assert p.stdout.strip() == ""
        assert "incident" in p.stderr.lower()

    def test_offline_sha_matches_assembler(self, tmp_path):
        jd = str(tmp_path / "j")
        j = MasterJournal(jd, fsync=False)
        j.append("epoch", {"epoch": 2})
        j.close()
        content = incident_json(assemble_incident(journal_dir=jd))
        p = self._run("--journal", jd)
        assert p.returncode == 0, p.stdout + p.stderr
        line = json.loads(p.stdout)
        assert line["timeline_sha256"] == incident_sha256(content)
        assert line["source"] == "disk"
        assert line["events"] == line["journal_events"] > 0
        assert line["incidents"] == 1

    def test_bad_journal_rc1(self, tmp_path):
        p = self._run("--journal", str(tmp_path / "missing"))
        assert p.returncode == 1
        assert "error" in json.loads(p.stdout)

"""The multi-chip dry-run gate must be bulletproof against caller state.

Round-1/2 failure mode: the driver called `dryrun_multichip(8)` from a
process whose jax default backend was the live TPU (axon tunnel) but which
happened to have >= 8 virtual CPU devices, so the dry run executed eager ops
on the TPU backend and died on environment skew. These tests pin the
contract: in-process execution ONLY in a pure-CPU jax world; anything else
re-execs a clean `JAX_PLATFORMS=cpu` subprocess.
"""

import os
import subprocess
import sys

import jax
import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

import __graft_entry__ as graft  # noqa: E402


def test_in_process_requires_cpu_default_backend(monkeypatch):
    # even with plenty of cpu devices, a non-cpu default backend must force
    # the subprocess path
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert not graft._can_run_in_process(2)


def test_in_process_requires_enough_devices():
    assert not graft._can_run_in_process(10_000)


def test_in_process_ok_in_cpu_world():
    # backend must already be initialized for the in-process fast path —
    # the gate never triggers discovery itself
    jax.devices()
    assert graft._can_run_in_process(8)


def test_dryrun_subprocess_path_from_noncpu_backend(monkeypatch):
    """Full dryrun_multichip(8) from a simulated TPU-default caller.

    Must take the subprocess path and succeed — this reproduces the driver's
    round-2 caller state (jax imported, cpu devices present, default backend
    not cpu).
    """
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert not graft._can_run_in_process(8)
    graft.dryrun_multichip(8)  # raises on failure


def test_dryrun_impl_pins_ops_to_cpu_devices(monkeypatch):
    """_dryrun_impl must not dispatch on the default backend implicitly.

    In this test env the default backend IS cpu, so a TPU escape is not
    directly observable; instead record the two pinning mechanisms in
    action: device selection must go through jax.devices('cpu') and the
    whole run must execute under jax.default_device(<cpu device>).
    """
    devices_platforms = []
    real_devices = jax.devices

    def recording_devices(platform=None):
        devices_platforms.append(platform)
        return real_devices(platform)

    pinned = []
    real_default_device = jax.default_device

    def recording_default_device(device):
        pinned.append(device)
        return real_default_device(device)

    monkeypatch.setattr(jax, "devices", recording_devices)
    monkeypatch.setattr(jax, "default_device", recording_default_device)
    graft._dryrun_impl(2)
    assert "cpu" in devices_platforms
    assert pinned and all(d.platform == "cpu" for d in pinned)


def test_can_run_in_process_does_not_initialize_backends(monkeypatch):
    """The gate must never trigger backend discovery in the caller: with no
    backend initialized yet it must answer False without calling
    jax.default_backend()/jax.devices()."""
    from jax._src import xla_bridge

    def boom(*a, **k):
        raise AssertionError("backend discovery triggered in caller")

    monkeypatch.setattr(jax, "default_backend", boom)
    monkeypatch.setattr(jax, "devices", boom)
    monkeypatch.setattr(xla_bridge, "_backends", {})
    assert not graft._can_run_in_process(2)


def test_dryrun_subprocess_env_is_clean():
    """The re-exec must force JAX_PLATFORMS=cpu and the device-count flag
    even when the caller env carries conflicting values."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "tpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    code = (
        "import __graft_entry__ as g; g.dryrun_multichip(4)"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=REPO_ROOT, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        timeout=900)
    assert proc.returncode == 0, proc.stdout[-4000:]
    assert "OK" in proc.stdout

"""Hot-swap re-mesh tests: state machine, fenced rendezvous, RPC ladder.

Covers master/mesh_transition.py (journal-fold determinism), the
rendezvous formation fence (hold/evict — a replacement node arriving
mid-transition must not race the fenced cutover), the full RPC ladder
over a real servicer, master-crash journal replay resuming the same
phase, and the worker-side participant (trainer/hotswap.py).
"""

import pytest

from dlrover_wuqiong_tpu.agent.master_client import MasterClient
from dlrover_wuqiong_tpu.common import messages as msg
from dlrover_wuqiong_tpu.common.constants import RendezvousName
from dlrover_wuqiong_tpu.master.master import JobMaster
from dlrover_wuqiong_tpu.master.mesh_transition import (
    MeshTransitionManager,
    PHASES,
)
from dlrover_wuqiong_tpu.master.rendezvous import (
    ElasticTrainingRendezvousManager,
)
from dlrover_wuqiong_tpu.trainer.hotswap import HotSwapParticipant


# ------------------------------------------------------------ state machine


class TestMeshTransitionManager:
    def _propose(self, mgr, survivors=(0, 3), rdzv_round=4):
        e = mgr.propose_event(2, 1, list(survivors), rdzv_round,
                              reason="test")
        assert e is not None
        mgr.apply(e)
        return e

    def test_phase_ladder_and_fence_epoch(self):
        mgr = MeshTransitionManager()
        e = self._propose(mgr)
        assert e["fence_epoch"] == 5  # rdzv_round + 1
        assert mgr.active()["phase"] == "propose"
        for phase in PHASES[:-1]:  # release has no worker acks
            for nid in (0, 3):
                a = mgr.ack_event(nid, e["tid"], phase, True)
                assert a is not None
                mgr.apply(a)
            adv = mgr.advance_event()
            assert adv["event"] == "phase"
            mgr.apply(adv)
        assert mgr.active()["phase"] == "release"
        adv = mgr.advance_event()
        assert adv == {"event": "phase", "tid": e["tid"], "phase": "done"}
        mgr.apply(adv)
        assert mgr.active() is None
        assert mgr.state_message().phase == "done"

    def test_partial_acks_do_not_advance(self):
        mgr = MeshTransitionManager()
        e = self._propose(mgr)
        a = mgr.ack_event(0, e["tid"], "propose", True)
        mgr.apply(a)
        assert mgr.advance_event() is None  # node 3 hasn't acked

    def test_nack_aborts(self):
        mgr = MeshTransitionManager()
        e = self._propose(mgr)
        mgr.apply(mgr.ack_event(0, e["tid"], "propose", True))
        mgr.apply(mgr.ack_event(3, e["tid"], "propose", False, "no peer"))
        ab = mgr.advance_event()
        assert ab["event"] == "abort"
        mgr.apply(ab)
        assert mgr.active() is None
        assert mgr.state_message().phase == "aborted"

    def test_stale_or_foreign_acks_rejected(self):
        mgr = MeshTransitionManager()
        e = self._propose(mgr)
        assert mgr.ack_event(7, e["tid"], "propose", True) is None  # not
        # a survivor
        assert mgr.ack_event(0, e["tid"] + 9, "propose", True) is None
        assert mgr.ack_event(0, e["tid"], "fence", True) is None  # wrong
        # phase

    def test_one_transition_at_a_time(self):
        mgr = MeshTransitionManager()
        self._propose(mgr)
        assert mgr.propose_event(5, 0, [1], 4) is None
        assert mgr.propose_event(5, 0, [], 4) is None  # and never with
        # zero survivors

    def test_event_replay_is_deterministic(self):
        # the journal IS the state: folding the same frames into a fresh
        # manager reproduces the exact mid-ladder state (master crash
        # replay contract)
        mgr = MeshTransitionManager()
        events = []

        def rec(e):
            events.append(e)
            mgr.apply(e)
            return e

        rec(mgr.propose_event(2, 1, [0, 3], 4))
        tid = events[0]["tid"]
        rec(mgr.ack_event(0, tid, "propose", True))
        rec(mgr.ack_event(3, tid, "propose", True))
        rec(mgr.advance_event())
        rec(mgr.ack_event(0, tid, "fence", True))
        assert mgr.active()["phase"] == "fence"
        replayed = MeshTransitionManager()
        for ev in events:
            replayed.apply(ev)
        assert replayed.active() == mgr.active()
        # replaying ACKS alone never advances — phase frames are the
        # only authority (a re-run advance decision is the live master's)
        assert replayed.active()["phase"] == "fence"

    def test_snapshot_roundtrip(self):
        mgr = MeshTransitionManager()
        e = self._propose(mgr)
        mgr.apply(mgr.ack_event(0, e["tid"], "propose", True))
        restored = MeshTransitionManager()
        restored.restore_state(mgr.export_state())
        assert restored.active() == mgr.active()
        # seq continues past the restored tid — no tid reuse
        restored.apply({"event": "abort", "tid": e["tid"], "reason": "x"})
        nxt = restored.propose_event(9, 0, [1], 7)
        assert nxt["tid"] == e["tid"] + 1


# --------------------------------------------------------- formation fence


class _AlwaysWarm:
    def is_warm_world(self, n_nodes: int) -> bool:
        return True


class TestFormationFence:
    def test_hold_blocks_warm_world_replacement_then_fenced_cutover(self):
        """Satellite: a replacement node arriving during a pending
        hot-swap transition must not race the fenced cutover — even down
        the warm-world fast path, which otherwise forms instantly.  Pins
        the epoch ordering: round 1 (original world) → 2 (fenced evict)
        → 3 (replacement integrates after release)."""
        rdzv = ElasticTrainingRendezvousManager()
        rdzv.update_rdzv_params(1, 3, waiting_timeout=30.0)
        rdzv.set_world_size_policy(_AlwaysWarm())
        rdzv.join_rendezvous(0, 0, 1)
        rdzv.join_rendezvous(1, 1, 1)
        rnd, _, world = rdzv.get_comm_world(0)
        assert rnd == 1 and len(world) == 2
        rdzv.hold_formation("mesh transition 1: hot-swap of node 1")
        # replacement arrives mid-transition; warm policy + min_nodes=1
        # would form a competing world immediately without the hold
        rdzv.join_rendezvous(2, 1, 1)
        rnd2, _, w2 = rdzv.get_comm_world(2)
        assert rnd2 == 1 and w2 == {}
        # fenced cutover: the evict IS the round bump the survivors
        # adopted as their fencing epoch
        assert rdzv.evict_from_world(1)
        assert rdzv.get_rdzv_round() == 2
        rnd3, _, w3 = rdzv.get_comm_world(0)
        assert rnd3 == 2 and len(w3) == 1
        assert w3[0].node_id == 0
        # still held: the replacement still cannot form
        rnd4, _, w4 = rdzv.get_comm_world(2)
        assert rnd4 == 2 and w4 == {}
        rdzv.release_formation()
        rdzv.join_rendezvous(0, 0, 1)
        rnd5, _, w5 = rdzv.get_comm_world(2)
        assert rnd5 == 3 and len(w5) == 2
        assert {s.node_id for s in w5.values()} == {0, 2}

    def test_evict_missing_node_is_noop(self):
        rdzv = ElasticTrainingRendezvousManager()
        rdzv.update_rdzv_params(2, 2, waiting_timeout=0.0)
        rdzv.join_rendezvous(0, 0, 1)
        rdzv.join_rendezvous(1, 1, 1)
        rdzv.get_comm_world(0)
        assert not rdzv.evict_from_world(9)
        assert rdzv.get_rdzv_round() == 1  # idempotent across replay

    def test_evict_journals_world(self):
        seen = []
        rdzv = ElasticTrainingRendezvousManager()
        rdzv.update_rdzv_params(2, 2, waiting_timeout=0.0)
        rdzv.on_world_formed = lambda name, state: seen.append(state)
        rdzv.join_rendezvous(0, 0, 1)
        rdzv.join_rendezvous(1, 1, 1)
        rdzv.get_comm_world(0)
        assert rdzv.evict_from_world(1)
        assert seen[-1]["round"] == 2
        assert [v[0] for v in seen[-1]["world"].values()] == [0]


# ------------------------------------------------------------- RPC ladder


class TestHotSwapOverRpc:
    @pytest.fixture()
    def master(self, tmp_path):
        m = JobMaster(min_nodes=2, max_nodes=2,
                      journal_dir=str(tmp_path / "journal"))
        m.prepare()
        yield m
        m.stop()
        MasterClient.reset()

    def _form_world(self, master):
        c0 = MasterClient(master.addr, node_id=0)
        c1 = MasterClient(master.addr, node_id=1)
        c0.register_node(0)
        c1.register_node(1)
        c0.join_rendezvous(0, 1, node_ip="127.0.0.1", free_port=4100)
        c1.join_rendezvous(1, 1, node_ip="127.0.0.1", free_port=4101)
        assert c0.get_comm_world().complete
        return c0, c1

    def test_full_ladder_rewrites_world(self, master):
        c0, c1 = self._form_world(master)
        c0.report_policy_decision(
            msg.PolicyDecision(recovery_route="hotswap"))
        c1.report_failure("SIGKILL", level="node")
        st = c0.get_mesh_transition()
        assert st.transition_id == 1 and st.phase == "propose"
        assert st.dead_node_id == 1 and st.survivors == [0]
        assert st.rdzv_round == 1 and st.fence_epoch == 2
        # a replacement joining mid-transition parks behind the fence
        c2 = MasterClient(master.addr, node_id=2)
        c2.register_node(2)
        c2.join_rendezvous(1, 1, node_ip="127.0.0.1", free_port=4102)
        assert not c2.get_comm_world().complete
        # the lone survivor walks the ladder; each ack advances
        for phase in ("propose", "fence", "hydrate", "cutover"):
            resp = c0.report_mesh_transition_phase(
                st.transition_id, phase, detail=f"{phase} done")
            assert resp.success
        done = c0.get_mesh_transition()
        assert done.transition_id == 1 and done.phase == "done"
        # cutover world: survivors only, round bumped to the fence epoch
        w = c0.get_comm_world()
        assert w.complete and w.rdzv_round == 2
        assert [v[0] for v in w.world.values()] == [0]
        # formation released: the parked replacement can integrate now
        c0.join_rendezvous(0, 1, node_ip="127.0.0.1", free_port=4100)
        w2 = c2.get_comm_world()
        assert w2.complete and w2.rdzv_round == 3
        assert sorted(v[0] for v in w2.world.values()) == [0, 2]

    def test_nack_falls_back_to_restart_the_world(self, master):
        c0, c1 = self._form_world(master)
        c0.report_policy_decision(
            msg.PolicyDecision(recovery_route="hotswap"))
        c1.report_failure("SIGKILL", level="node")
        st = c0.get_mesh_transition()
        c0.report_mesh_transition_phase(st.transition_id, "propose")
        resp = c0.report_mesh_transition_phase(
            st.transition_id, "fence", ok=False, detail="no ring")
        assert resp.success
        assert c0.get_mesh_transition().phase == "aborted"
        # fence released: a classic re-rendezvous can proceed
        rdzv = master.rdzv_managers[RendezvousName.ELASTIC_TRAINING]
        assert not rdzv._formation_hold

    def test_stale_ack_rejected(self, master):
        c0, c1 = self._form_world(master)
        c0.report_policy_decision(
            msg.PolicyDecision(recovery_route="hotswap"))
        c1.report_failure("SIGKILL", level="node")
        resp = c0.report_mesh_transition_phase(99, "propose")
        assert not resp.success
        resp = c0.report_mesh_transition_phase(1, "cutover")  # wrong phase
        assert not resp.success

    def test_no_hotswap_without_policy_route(self, master):
        c0, c1 = self._form_world(master)
        c1.report_failure("SIGKILL", level="node")
        assert c0.get_mesh_transition().transition_id == 0
        rdzv = master.rdzv_managers[RendezvousName.ELASTIC_TRAINING]
        assert not rdzv._formation_hold


# ----------------------------------------------------------- crash replay


class TestMasterCrashReplay:
    def test_replay_resumes_same_phase_and_refences(self, tmp_path):
        jd = str(tmp_path / "journal")
        m1 = JobMaster(min_nodes=2, max_nodes=2, journal_dir=jd)
        rdzv = m1.rdzv_managers[RendezvousName.ELASTIC_TRAINING]
        rdzv.join_rendezvous(0, 0, 1)
        rdzv.join_rendezvous(1, 1, 1)
        rdzv.get_comm_world(0)
        d = msg.PolicyDecision(decision_id=1, recovery_route="hotswap")
        m1.journal.append("policy", {"decision": d})
        m1._apply_policy(d)
        assert m1.maybe_start_hotswap(1, reason="test kill")
        ack = m1.mesh.ack_event(0, 1, "propose", True)
        m1._journal_mesh(ack)
        m1.mesh.apply(ack)
        m1.mesh_maybe_advance()
        assert m1.mesh.active()["phase"] == "fence"
        # SIGKILL: no stop(), no snapshot — replay is frames only
        m2 = JobMaster(min_nodes=2, max_nodes=2, journal_dir=jd)
        t = m2.mesh.active()
        assert t is not None
        assert t["tid"] == 1 and t["phase"] == "fence"
        assert t["fence_epoch"] == 2
        # the fence is re-armed: a replacement still cannot form
        rdzv2 = m2.rdzv_managers[RendezvousName.ELASTIC_TRAINING]
        assert rdzv2._formation_hold
        # and the ladder continues where it stopped
        ack = m2.mesh.ack_event(0, 1, "fence", True)
        m2._journal_mesh(ack)
        m2.mesh.apply(ack)
        m2.mesh_maybe_advance()
        assert m2.mesh.active()["phase"] == "hydrate"

    def test_replay_after_release_finishes_evict(self, tmp_path):
        # crash window: the "release" phase frame was durable but the
        # world rewrite wasn't — replay must re-run the evict and land
        # in "done" with the dead node gone
        jd = str(tmp_path / "journal")
        m1 = JobMaster(min_nodes=2, max_nodes=2, journal_dir=jd)
        rdzv = m1.rdzv_managers[RendezvousName.ELASTIC_TRAINING]
        rdzv.join_rendezvous(0, 0, 1)
        rdzv.join_rendezvous(1, 1, 1)
        rdzv.get_comm_world(0)
        d = msg.PolicyDecision(decision_id=1, recovery_route="hotswap")
        m1.journal.append("policy", {"decision": d})
        m1._apply_policy(d)
        assert m1.maybe_start_hotswap(1)
        for phase in ("propose", "fence", "hydrate", "cutover"):
            ack = m1.mesh.ack_event(0, 1, phase, True)
            m1._journal_mesh(ack)
            m1.mesh.apply(ack)
            if phase != "cutover":
                m1.mesh_maybe_advance()
        # journal ONLY the advance to "release", then crash before the
        # master-side evict/done work
        adv = m1.mesh.advance_event()
        assert adv == {"event": "phase", "tid": 1, "phase": "release"}
        m1._journal_mesh(adv)
        m1.mesh.apply(adv)
        m2 = JobMaster(min_nodes=2, max_nodes=2, journal_dir=jd)
        assert m2.mesh.active() is None
        assert m2.mesh.state_message().phase == "done"
        rdzv2 = m2.rdzv_managers[RendezvousName.ELASTIC_TRAINING]
        assert rdzv2.get_rdzv_round() == 2
        assert not rdzv2._formation_hold
        _, _, world = rdzv2.get_comm_world(0)
        assert [s.node_id for s in world.values()] == [0]


# ------------------------------------------------------------- participant


class _FakeMC:
    def __init__(self):
        self.state = msg.MeshTransitionState()
        self.acks = []

    def get_mesh_transition(self):
        return self.state

    def report_mesh_transition_phase(self, tid, phase, ok=True, detail=""):
        self.acks.append((tid, phase, ok, detail))
        return msg.OkResponse()


class TestHotSwapParticipant:
    def _state(self, phase, tid=1):
        return msg.MeshTransitionState(
            transition_id=tid, phase=phase, dead_node_id=2, dead_rank=1,
            survivors=[0, 3], rdzv_round=4, fence_epoch=5)

    def test_walks_ladder_with_hooks(self):
        mc = _FakeMC()
        fences, cuts = [], []
        hs = HotSwapParticipant(
            mc, node_id=0,
            hydrate_cb=lambda st: (11, {"w": [1.0]}, {}),
            cutover_cb=lambda hydrated, st: cuts.append(hydrated) or True,
            fence_cb=fences.append)
        assert hs.poll() is None  # idle: tid 0
        for phase in ("propose", "fence", "hydrate", "cutover"):
            mc.state = self._state(phase)
            assert hs.poll() == phase
            assert hs.poll() is None  # same phase never re-acked
        assert [a[1] for a in mc.acks] == ["propose", "fence", "hydrate",
                                           "cutover"]
        assert all(a[2] for a in mc.acks)
        assert fences == [5] and hs.fence_epoch == 5
        assert cuts == [(11, {"w": [1.0]}, {})]
        mc.state = self._state("done")
        assert hs.poll() == "done"

    def test_hydrate_without_ring_nacks(self):
        mc = _FakeMC()
        hs = HotSwapParticipant(mc, node_id=0)
        mc.state = self._state("hydrate")
        assert hs.poll() == "hydrate"
        tid, phase, ok, detail = mc.acks[-1]
        assert not ok and "no replica ring" in detail

    def test_non_survivor_ignores(self):
        mc = _FakeMC()
        hs = HotSwapParticipant(mc, node_id=7)
        mc.state = self._state("propose")
        assert hs.poll() is None
        assert mc.acks == []

    def test_ledger_credits_hydrate_and_cutover(self):
        from dlrover_wuqiong_tpu.telemetry.ledger import GoodputLedger

        led = GoodputLedger()
        mc = _FakeMC()
        hs = HotSwapParticipant(
            mc, node_id=0, ledger=led,
            hydrate_cb=lambda st: (1, {}, {}),
            cutover_cb=lambda hydrated, st: True)
        mc.state = self._state("hydrate")
        hs.poll()
        mc.state = self._state("cutover")
        hs.poll()
        snap = led.snapshot()
        assert snap["states"]["restore_replica"] > 0.0
        assert snap["states"]["rework"] > 0.0


# ------------------------------------------------------------ wire pinning


class TestMeshWireAddOnly:
    def test_message_family_canary(self):
        # ADD-ONLY canary (one per family — the schema lock enforces the
        # full surface): these fields exist with sentinel defaults so a
        # mixed-generation decode degrades to no-change
        st = msg.MeshTransitionState()
        assert st.transition_id == 0 and st.phase == ""
        assert st.dead_node_id == -1 and st.dead_rank == -1
        assert st.survivors == [] and st.fence_epoch == 0
        q = msg.MeshTransitionQuery()
        assert q.node_id == -1
        r = msg.MeshTransitionPhaseReport()
        assert r.transition_id == 0 and r.ok is True and r.detail == ""

    def test_state_roundtrips_codec(self):
        from dlrover_wuqiong_tpu.common.serialize import dumps, loads

        st = msg.MeshTransitionState(
            transition_id=3, phase="hydrate", dead_node_id=2, dead_rank=1,
            survivors=[0, 3], rdzv_round=4, fence_epoch=5,
            started_at=123.5, reason="kill")
        out = loads(dumps(st))
        assert out == st

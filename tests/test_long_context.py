"""Ring attention + Ulysses SP vs full attention on the virtual 8-device mesh.

Mirrors the reference's distributed-attention tests (atorch
modules/distributed_transformer) translated to shard_map/ppermute.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from version_gates import requires_shard_map

from dlrover_wuqiong_tpu.ops.flash_attention import _attention_reference
from dlrover_wuqiong_tpu.parallel.long_context import (
    _attention_with_lse,
    _merge_partials,
    ring_attention,
    ulysses_attention,
)
from dlrover_wuqiong_tpu.parallel.mesh import MeshPlan, build_mesh


@pytest.fixture(scope="module")
def sp_mesh():
    return build_mesh(MeshPlan(sp=4, fsdp=2))


def _qkv(key, b=2, h=4, s=128, d=16):
    kq, kk, kv = jax.random.split(key, 3)
    return (jax.random.normal(kq, (b, h, s, d), jnp.float32),
            jax.random.normal(kk, (b, h, s, d), jnp.float32),
            jax.random.normal(kv, (b, h, s, d), jnp.float32))


class TestMergePartials:
    def test_merge_two_halves_equals_full(self):
        q, k, v = _qkv(jax.random.PRNGKey(0), s=64)
        o_full, _ = _attention_with_lse(q, k, v, False, None)
        o1, l1 = _attention_with_lse(q, k[:, :, :32], v[:, :, :32], False,
                                     None)
        o2, l2 = _attention_with_lse(q, k[:, :, 32:], v[:, :, 32:], False,
                                     None)
        o, _ = _merge_partials(o1, l1, o2, l2)
        np.testing.assert_allclose(o, o_full, atol=1e-5)

    def test_merge_with_empty_partial(self):
        q, k, v = _qkv(jax.random.PRNGKey(1), s=32)
        o1, l1 = _attention_with_lse(q, k, v, False, None)
        o0 = jnp.zeros_like(o1)
        l0 = jnp.full(l1.shape, -jnp.inf)
        o, lse = _merge_partials(o1, l1, o0, l0)
        np.testing.assert_allclose(o, o1, atol=1e-6)
        np.testing.assert_allclose(lse, l1, atol=1e-6)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_full_attention(self, sp_mesh, causal):
        q, k, v = _qkv(jax.random.PRNGKey(2))
        ref = _attention_reference(q, k, v, causal, 1.0 / np.sqrt(16))
        out = ring_attention(q, k, v, sp_mesh, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

    def test_grads_match(self, sp_mesh):
        q, k, v = _qkv(jax.random.PRNGKey(3), s=64)

        def f_ring(q, k, v):
            return (ring_attention(q, k, v, sp_mesh, causal=True) ** 2).sum()

        def f_ref(q, k, v):
            return (_attention_reference(q, k, v, True,
                                         1.0 / np.sqrt(16)) ** 2).sum()

        gr = jax.grad(f_ring, argnums=(0, 1, 2))(q, k, v)
        gf = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gr, gf):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4)

    def test_sp1_mesh_falls_through(self):
        mesh = build_mesh(MeshPlan(fsdp=8))
        q, k, v = _qkv(jax.random.PRNGKey(4), s=64)
        out = ring_attention(q, k, v, mesh, causal=True)
        ref = _attention_reference(q, k, v, True, 1.0 / np.sqrt(16))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)


class TestUlysses:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_full_attention(self, sp_mesh, causal):
        q, k, v = _qkv(jax.random.PRNGKey(5))
        ref = _attention_reference(q, k, v, causal, 1.0 / np.sqrt(16))
        out = ulysses_attention(q, k, v, sp_mesh, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

    def test_grads_match(self, sp_mesh):
        q, k, v = _qkv(jax.random.PRNGKey(6), s=64)

        def f_uly(q, k, v):
            return (ulysses_attention(q, k, v, sp_mesh,
                                      causal=True) ** 2).sum()

        def f_ref(q, k, v):
            return (_attention_reference(q, k, v, True,
                                         1.0 / np.sqrt(16)) ** 2).sum()

        gu = jax.grad(f_uly, argnums=(0, 1, 2))(q, k, v)
        gf = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gu, gf):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4)

    def test_heads_not_divisible_rejected(self, sp_mesh):
        q, k, v = _qkv(jax.random.PRNGKey(7), h=3)
        with pytest.raises(ValueError, match="divisible"):
            ulysses_attention(q, k, v, sp_mesh)


class TestSequenceParallelTraining:
    """auto_accelerate with sequence_parallel trains end-to-end and matches
    the pure-FSDP numerics (the reference's SP promise: same model, sharded
    sequence)."""

    @pytest.mark.parametrize("impl", ["ulysses", "ring"])
    @requires_shard_map
    def test_sp_training_matches_fsdp(self, impl):
        import optax

        from dlrover_wuqiong_tpu.auto.accelerate import auto_accelerate
        from dlrover_wuqiong_tpu.models.gpt import GPT, GPTConfig

        def train(strategy, steps=4):
            model = GPT(GPTConfig(vocab_size=512, n_layer=2, n_head=4,
                                  n_embd=64, block_size=128,
                                  dtype=jnp.float32))
            res = auto_accelerate(model, optimizer=optax.adamw(1e-2),
                                  strategy=strategy)
            data = jax.random.randint(jax.random.PRNGKey(0), (8, 129), 0, 512)
            batch = res.place_batch({"input_ids": data[:, :-1],
                                     "labels": data[:, 1:]}, seq_axis=1)
            state, losses = res.state, []
            for _ in range(steps):
                state, m = res.train_step(state, batch)
                losses.append(float(m["loss"]))
            return losses

        base = train([("fsdp", {})])
        sp = train([("sequence_parallel", {"size": 4, "impl": impl}),
                    ("fsdp", {})])
        np.testing.assert_allclose(sp, base, rtol=2e-2)

"""Strategy search engine + Bayesian optimization tests.

Mirrors reference `atorch/tests/common_tests` engine/strategy tests and
`dlrover/python/tests/test_hpsearch_bo.py`.
"""

import dataclasses
import math

import pytest

import jax
import jax.numpy as jnp
import numpy as np
import optax

from dlrover_wuqiong_tpu.auto.bo import BayesianOptimizer, Param
from dlrover_wuqiong_tpu.auto.engine import (
    Candidate,
    generate_candidates,
    score_candidate,
    search_strategy,
)
from dlrover_wuqiong_tpu.models.gpt import GPT, GPTConfig
from dlrover_wuqiong_tpu.parallel.mesh import MeshPlan


class TestCandidateGeneration:
    def test_divisibility_constraints(self):
        cands = generate_candidates(8, n_head=4, n_layer=2,
                                    with_remat=False)
        for c in cands:
            assert 4 % c.plan.tp == 0
            assert 2 % c.plan.pp == 0
            assert c.plan.num_devices == 8
        # tp can't exceed head count divisors
        assert all(c.plan.tp in (1, 2, 4) for c in cands)
        assert any(c.plan.pp == 2 for c in cands)

    def test_remat_triples_space(self):
        # off / full-remat / selective-dots per mesh plan
        a = generate_candidates(4, with_remat=False)
        b = generate_candidates(4, with_remat=True)
        assert len(b) == 3 * len(a)
        assert any(c.remat and c.remat_policy == "dots" for c in b)
        strat = dict(next(c for c in b if c.remat_policy == "dots"
                          and c.remat).strategy())
        assert strat["checkpoint"] == {"enabled": True, "policy": "dots"}

    def test_strategy_roundtrip(self):
        c = Candidate(plan=MeshPlan(tp=2, fsdp=4), remat=True)
        strat = dict(c.strategy())
        assert strat["tensor_parallel"] == {"size": 2}
        assert strat["fsdp"] == {"size": 4}
        assert strat["checkpoint"] == {"enabled": True}


class TestScoring:
    def _model_batch(self):
        cfg = dataclasses.replace(GPTConfig.nano(), dtype=jnp.float32,
                                  use_flash_attention=False, remat=False)
        data = np.random.default_rng(0).integers(0, cfg.vocab_size, (8, 33))
        batch = {"input_ids": jnp.asarray(data[:, :-1]),
                 "labels": jnp.asarray(data[:, 1:])}
        return GPT(cfg), batch, cfg

    def test_score_feasible_candidate(self):
        model, batch, cfg = self._model_batch()
        c = Candidate(plan=MeshPlan(fsdp=8))
        score_candidate(c, model, optax.adam(1e-2), batch,
                        jax.devices())
        assert c.feasible
        assert 0 < c.score < math.inf

    def test_infeasible_marked_not_raised(self):
        model, batch, cfg = self._model_batch()
        # tp=8 > n_head=2 → ulysses/TP head divisibility fails inside
        c = Candidate(plan=MeshPlan(tp=8, fsdp=1))
        score_candidate(c, model, optax.adam(1e-2), batch, jax.devices())
        # nano has 2 heads; tp=8 model may still build (GSPMD pads) — the
        # point is: no exception escapes, feasibility is recorded
        assert isinstance(c.feasible, bool)

    # tier-2: ~42s multi-candidate compile sweep; scoring/feasibility
    # logic is tier-1 via the two single-candidate tests above
    @pytest.mark.slow
    def test_search_returns_ranked(self):
        model, batch, cfg = self._model_batch()
        top = search_strategy(model, optax.adam(1e-2), batch,
                              jax.devices(), n_head=cfg.n_head,
                              n_layer=cfg.n_layer, top_k=3)
        assert top
        scores = [c.score for c in top]
        assert scores == sorted(scores)
        assert all(c.feasible for c in top)


class TestBayesianOptimizer:
    def test_finds_quadratic_minimum(self):
        bo = BayesianOptimizer([Param("x", -2.0, 2.0)], seed=1, n_init=4)
        for _ in range(25):
            cfg = bo.ask()
            bo.tell(cfg, (cfg["x"] - 0.7) ** 2)
        best_cfg, best_y = bo.best()
        assert abs(best_cfg["x"] - 0.7) < 0.25
        assert best_y < 0.08

    def test_log_scale_param(self):
        p = Param("lr", 1e-5, 1e-1, log_scale=True)
        assert abs(p.from_unit(p.to_unit(1e-3)) - 1e-3) < 1e-9
        bo = BayesianOptimizer([p], seed=0, n_init=3)
        # minimum at lr=1e-3 on a log parabola
        for _ in range(20):
            cfg = bo.ask()
            bo.tell(cfg, (math.log10(cfg["lr"]) + 3.0) ** 2)
        best_cfg, _ = bo.best()
        assert 1e-4 < best_cfg["lr"] < 1e-2

    def test_multidim(self):
        bo = BayesianOptimizer([Param("a", 0, 1), Param("b", 0, 1)],
                               seed=2, n_init=5)
        for _ in range(30):
            cfg = bo.ask()
            bo.tell(cfg, (cfg["a"] - 0.3) ** 2 + (cfg["b"] - 0.6) ** 2)
        best_cfg, best_y = bo.best()
        assert best_y < 0.1


class TestScheduleCandidates:
    def test_interleaved_candidates_emitted(self):
        from dlrover_wuqiong_tpu.auto.engine import generate_candidates

        cands = generate_candidates(8, n_head=4, n_layer=8,
                                    with_remat=False)
        inter = [c for c in cands if c.pp_schedule == "interleaved"]
        assert inter, "expected interleaved pp candidates"
        for c in inter:
            assert c.plan.pp > 1
            assert c.pp_virtual_stages == 2
            # strategy round-trips the schedule config
            pp_cfg = dict(c.strategy())["pipeline_parallel"]
            assert pp_cfg["schedule"] == "interleaved"
            assert pp_cfg["virtual_stages"] == 2

    def test_no_interleaved_when_layers_dont_divide(self):
        from dlrover_wuqiong_tpu.auto.engine import generate_candidates

        cands = generate_candidates(4, n_head=4, n_layer=2,
                                    with_remat=False)
        assert not [c for c in cands if c.pp_schedule == "interleaved"]


class TestHEBO:
    """HEBO-class search (parity atorch auto/engine/sg_algo/hebo): input
    warping + power-transformed observations + MACE Pareto acquisition."""

    def test_finds_quadratic_minimum(self):
        from dlrover_wuqiong_tpu.auto.hebo import HEBO, Param

        hebo = HEBO([Param("x", -2.0, 2.0), Param("y", -2.0, 2.0)],
                    seed=3, n_init=6)
        for _ in range(26):
            cfg = hebo.ask()
            hebo.tell(cfg, (cfg["x"] - 0.7) ** 2 + (cfg["y"] + 0.3) ** 2)
        best_cfg, best_y = hebo.best()
        assert best_y < 0.08, (best_cfg, best_y)

    def test_outlier_robustness_beats_plain_gp(self):
        """A diverged trial (loss 1e6) must not blind the search — the
        power transform compresses it; plain standardization flattens the
        whole surrogate to ~zero contrast."""
        from dlrover_wuqiong_tpu.auto.hebo import HEBO, Param

        def obj(cfg):
            if cfg["x"] < -1.5:  # divergence region
                return 1e6
            return (cfg["x"] - 0.5) ** 2

        hebo = HEBO([Param("x", -2.0, 2.0)], seed=0, n_init=5)
        for _ in range(22):
            cfg = hebo.ask()
            hebo.tell(cfg, obj(cfg))
        _, best_y = hebo.best()
        assert best_y < 0.05, best_y

    def test_batch_ask_returns_distinct_configs(self):
        from dlrover_wuqiong_tpu.auto.hebo import HEBO, Param

        hebo = HEBO([Param("lr", 1e-5, 1e-1, log_scale=True)], seed=1,
                    n_init=4)
        for _ in range(6):
            cfg = hebo.ask()
            hebo.tell(cfg, abs(math.log10(cfg["lr"]) + 3.0))
        batch = hebo.ask(4)
        assert len(batch) == 4
        assert len({round(c["lr"], 10) for c in batch}) >= 3

    def test_warp_and_transform_sanity(self):
        import numpy as np

        from dlrover_wuqiong_tpu.auto.hebo import (
            _kumaraswamy_cdf,
            _power_transform,
        )

        u = np.linspace(0.01, 0.99, 50)
        w = _kumaraswamy_cdf(u, np.array([1.7]), np.array([0.6]))
        assert (np.diff(w) > 0).all()  # monotone
        assert 0.0 <= w.min() and w.max() <= 1.0
        y = np.array([1.0, 1.1, 0.9, 1.05, 1e6])  # one catastrophic trial
        t, lam, _ = _power_transform(y)
        spread = (t[:-1].max() - t[:-1].min())
        assert spread > 0  # healthy trials keep contrast
        # the outlier no longer dominates the scale by 6 orders
        assert (t[-1] - t[:-1].max()) < 50 * spread

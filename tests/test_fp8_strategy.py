"""FP8 training as a reachable strategy (round-3 VERDICT item #2).

Parity: reference `atorch/auto/opt_lib/amp_optimization.py:197-260`
(Fp8Optimization module filter).  Here ("amp", {"fp8": True}) rebuilds the
model with fp8 projections; these tests pin (a) param-tree compatibility so
sharding rules still bind, (b) numerics vs bf16 within a loss-delta bound,
(c) end-to-end reachability through auto_accelerate incl. tensor parallel.
"""

import jax
import jax.numpy as jnp
from jax import flatten_util
import numpy as np
import optax
import pytest

from dlrover_wuqiong_tpu.auto.accelerate import auto_accelerate
from dlrover_wuqiong_tpu.models.fp8 import Fp8Dense, fp8_selected
from dlrover_wuqiong_tpu.models.gpt import GPT, GPTConfig, cross_entropy_loss
from dlrover_wuqiong_tpu.models.llama import Llama, LlamaConfig

import dataclasses


def _batch(cfg, key=0, batch=4, seq=32):
    data = jax.random.randint(jax.random.PRNGKey(key), (batch, seq + 1), 0,
                              cfg.vocab_size)
    return data[:, :-1], data[:, 1:]


def test_param_tree_identical_to_bf16():
    cfg = GPTConfig.nano()
    p_bf16 = GPT(cfg).init_params(jax.random.PRNGKey(0))
    p_fp8 = GPT(dataclasses.replace(cfg, fp8=True)).init_params(
        jax.random.PRNGKey(0))
    flat_a = jax.tree_util.tree_leaves_with_path(p_bf16)
    flat_b = jax.tree_util.tree_leaves_with_path(p_fp8)
    assert [(jax.tree_util.keystr(k), v.shape, v.dtype)
            for k, v in flat_a] == \
           [(jax.tree_util.keystr(k), v.shape, v.dtype) for k, v in flat_b]
    # same init → identical master weights
    for (_, a), (_, b) in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("model_cls,cfg", [
    (GPT, GPTConfig.nano()),
    (Llama, LlamaConfig.nano()),
])
def test_fp8_numerics_close_to_bf16(model_cls, cfg):
    params = model_cls(cfg).init_params(jax.random.PRNGKey(0))
    ids, labels = _batch(cfg)
    logits_ref = model_cls(cfg).apply({"params": params}, ids)
    cfg8 = dataclasses.replace(cfg, fp8=True)
    logits_fp8 = model_cls(cfg8).apply({"params": params}, ids)
    loss_ref = float(cross_entropy_loss(logits_ref, labels))
    loss_fp8 = float(cross_entropy_loss(logits_fp8, labels))
    assert np.isfinite(loss_fp8)
    # fp8 rounding noise, not divergence: e4m3 keeps ~2 decimal digits
    assert abs(loss_fp8 - loss_ref) / loss_ref < 0.05, \
        (loss_fp8, loss_ref)


def test_fp8_grads_finite_and_close():
    cfg = GPTConfig.nano()
    params = GPT(cfg).init_params(jax.random.PRNGKey(0))
    ids, labels = _batch(cfg)

    def loss_fn(c):
        def f(p):
            return cross_entropy_loss(
                GPT(c).apply({"params": p}, ids), labels)
        return f

    g_ref = jax.grad(loss_fn(cfg))(params)
    g_fp8 = jax.grad(loss_fn(dataclasses.replace(cfg, fp8=True)))(params)
    ref_flat, _ = flatten_util.ravel_pytree(g_ref)
    fp8_flat, _ = flatten_util.ravel_pytree(g_fp8)
    assert np.all(np.isfinite(np.asarray(fp8_flat, np.float32)))
    cos = float(jnp.vdot(ref_flat.astype(jnp.float32),
                         fp8_flat.astype(jnp.float32)) /
                (jnp.linalg.norm(ref_flat.astype(jnp.float32)) *
                 jnp.linalg.norm(fp8_flat.astype(jnp.float32)) + 1e-12))
    assert cos > 0.97, cos  # e5m2 gradient rounding, same direction


def test_fp8_filter_selects_projections_only():
    cfg = GPTConfig(fp8=True)
    assert fp8_selected(cfg, "c_attn")
    assert fp8_selected(cfg, "c_fc")
    assert fp8_selected(cfg, "c_proj")
    assert not fp8_selected(cfg, "wte")
    assert not fp8_selected(cfg, "lm_head")
    custom = dataclasses.replace(cfg, fp8_filter=("c_fc",))
    assert fp8_selected(custom, "c_fc")
    assert not fp8_selected(custom, "c_attn")


def test_amp_fp8_strategy_reachable_with_tp():
    """auto_accelerate(("amp", {"fp8": True})) must rebuild the model with
    fp8 projections and train under tp=2 x fsdp sharding."""
    devices = jax.devices()[:8]
    cfg = GPTConfig(vocab_size=512, n_layer=2, n_head=4, n_embd=128,
                    block_size=64, dtype=jnp.float32)
    res = auto_accelerate(
        GPT(cfg), optimizer=optax.adamw(1e-3),
        strategy=[("amp", {"fp8": True}),
                  ("tensor_parallel", {"size": 2}),
                  ("fsdp", {})],
        devices=devices)
    assert res.model.config.fp8 is True
    assert res.strategy.amp is True
    ids, labels = _batch(res.model.config, batch=8, seq=32)
    batch = res.place_batch({"input_ids": ids, "labels": labels})
    state, metrics = res.train_step(res.state, batch)
    loss0 = float(metrics["loss"])
    assert np.isfinite(loss0)
    # a couple more steps must stay finite and trend down on memorized data
    for _ in range(8):
        state, metrics = res.train_step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    assert float(metrics["loss"]) < loss0


def test_fp8_custom_filter_through_strategy():
    devices = jax.devices()[:2]
    cfg = GPTConfig(vocab_size=512, n_layer=1, n_head=2, n_embd=64,
                    block_size=32, dtype=jnp.float32)
    res = auto_accelerate(
        GPT(cfg), optimizer=optax.sgd(1e-3),
        strategy=[("amp", {"fp8": True, "filter": ["c_fc"]}), ("fsdp", {})],
        devices=devices)
    assert res.model.config.fp8_filter == ("c_fc",)

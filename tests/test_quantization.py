"""Quantization op tests: int8 blockwise kernels, fp8 scaled matmul,
fp8 training step.

Mirrors reference atorch csrc quantize/dequantize unit coverage.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_wuqiong_tpu.ops.quantization import (
    E4M3,
    E5M2,
    Fp8Einsum,
    dequantize_int8_blockwise,
    fp8_dequantize,
    fp8_dot,
    fp8_matmul,
    fp8_quantize,
    quantize_int8_blockwise,
)


class TestInt8Blockwise:
    @pytest.mark.parametrize("shape", [(1000,), (64, 300), (8, 8, 8)])
    def test_roundtrip_error_bounded(self, shape):
        x = jax.random.normal(jax.random.PRNGKey(0), shape) * 3.0
        q, s = quantize_int8_blockwise(x)
        back = dequantize_int8_blockwise(q, s, x.size, shape)
        err = np.abs(np.asarray(back) - np.asarray(x)).max()
        # absmax int8: error ≤ scale/2 per block; scale = absmax/127
        assert err <= float(np.abs(np.asarray(x)).max()) / 127.0
        assert q.dtype == jnp.int8

    def test_zeros_stable(self):
        x = jnp.zeros((512,))
        q, s = quantize_int8_blockwise(x)
        back = dequantize_int8_blockwise(q, s, 512, (512,))
        np.testing.assert_array_equal(np.asarray(back), 0.0)

    def test_memory_shrinks(self):
        x = jnp.ones((4096,), jnp.float32)
        q, s = quantize_int8_blockwise(x)
        assert q.size + 4 * s.size <= x.size * 1.1  # ~1 byte/elt + scales


class TestFp8:
    def test_quantize_dequantize(self):
        x = jax.random.normal(jax.random.PRNGKey(1), (128,)) * 10
        q, s = fp8_quantize(x, E4M3)
        assert q.dtype == E4M3
        back = fp8_dequantize(q, s)
        rel = np.abs(np.asarray(back) - np.asarray(x)) / (
            np.abs(np.asarray(x)) + 1e-6)
        assert float(np.median(rel)) < 0.08  # e4m3 ~2 mantissa bits

    def test_fp8_dot_close_to_f32(self):
        a = jax.random.normal(jax.random.PRNGKey(2), (64, 128))
        b = jax.random.normal(jax.random.PRNGKey(3), (128, 32))
        want = a @ b
        got = fp8_dot(a, b, out_dtype=jnp.float32)
        rel = float(jnp.abs(got - want).mean() / jnp.abs(want).mean())
        assert rel < 0.1

    def test_fp8_matmul_grads(self):
        a = jax.random.normal(jax.random.PRNGKey(4), (16, 32))
        b = jax.random.normal(jax.random.PRNGKey(5), (32, 8))

        def loss(a, b):
            return fp8_matmul(a, b, jnp.float32).sum()

        ga, gb = jax.grad(loss, argnums=(0, 1))(a, b)
        # reference grads of sum(a@b): ga = ones @ b.T, gb = a.T @ ones
        ga_ref = jnp.ones((16, 8)) @ b.T
        gb_ref = a.T @ jnp.ones((16, 8))
        assert float(jnp.abs(ga - ga_ref).mean()
                     / jnp.abs(ga_ref).mean()) < 0.1
        assert float(jnp.abs(gb - gb_ref).mean()
                     / jnp.abs(gb_ref).mean()) < 0.1

    def test_projection_helper_trains(self):
        """A toy regression through Fp8Einsum converges."""
        import optax

        w = jax.random.normal(jax.random.PRNGKey(6), (16, 4)) * 0.1
        x = jax.random.normal(jax.random.PRNGKey(7), (4, 8, 16))
        target = jnp.ones((4, 8, 4))
        opt = optax.adam(5e-2)
        state = opt.init(w)

        @jax.jit
        def step(w, state):
            def loss_fn(w):
                y = Fp8Einsum.project(x, w, jnp.float32)
                return ((y - target) ** 2).mean()
            loss, g = jax.value_and_grad(loss_fn)(w)
            updates, state = opt.update(g, state, w)
            return optax.apply_updates(w, updates), state, loss

        losses = []
        for _ in range(60):
            w, state, loss = step(w, state)
            losses.append(float(loss))
        # fp8 rounding noise sets a loss floor — expect solid progress,
        # not convergence to zero
        assert losses[-1] < losses[0] * 0.6

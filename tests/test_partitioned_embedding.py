"""Cross-host partitioned embedding service tests.

Parity: KvVariable-on-PS placement (kv_variable.h:89) — a vocabulary
larger than one host's tables spreads over mod-sharded owners; lookups
and gradient pushes are batched RPCs over the control plane.
"""

import numpy as np
import pytest

from dlrover_wuqiong_tpu.embedding.kv_embedding import KvEmbedding
from dlrover_wuqiong_tpu.embedding.partitioned import (
    EmbeddingShardServer,
    PartitionedKvEmbedding,
)
from dlrover_wuqiong_tpu.embedding.sparse_optim import SparseOptConfig


DIM = 8


@pytest.fixture()
def two_shards():
    """Two shard servers (as two 'hosts') + a client local to shard 0."""
    embs = [KvEmbedding(dim=DIM, capacity=16, prefer_native=False,
                        optimizer=SparseOptConfig(kind="sgd", lr=0.5),
                        seed=w)
            for w in range(2)]
    servers = [EmbeddingShardServer(embs[w], shard_id=w, num_shards=2)
               for w in range(2)]
    for s in servers:
        s.start()
    client = PartitionedKvEmbedding(
        DIM, [s.addr for s in servers], local=(0, embs[0]))
    remote_only = PartitionedKvEmbedding(DIM, [s.addr for s in servers])
    yield embs, servers, client, remote_only
    client.close()
    remote_only.close()
    for s in servers:
        s.stop()


class TestPartitionedGather:
    def test_mod_sharding_routes_to_owners(self, two_shards):
        embs, servers, client, _ = two_shards
        ids = np.arange(100, 120, dtype=np.int64)
        rows = client.gather(ids)
        assert rows.shape == (20, DIM)
        # each shard admitted exactly its own ids (10 even + 10 odd),
        # +1 sentinel each
        assert len(embs[0].store) == 11
        assert len(embs[1].store) == 11

    def test_gather_row_identity_matches_owner(self, two_shards):
        """The client's assembled rows equal a direct gather on the owning
        shard — including duplicate ids in one batch."""
        embs, _, client, _ = two_shards
        ids = np.array([7, 100, 7, 42, 101, 100], np.int64)
        rows = client.gather(ids)
        for i, raw in enumerate(ids):
            owner = int(abs(raw) % 2)
            slot = embs[owner].lookup_slots(np.array([raw], np.int64),
                                            insert=False)
            np.testing.assert_allclose(
                rows[i], np.asarray(embs[owner].gather(slot))[0],
                rtol=1e-6)

    def test_remote_only_client_matches_local_client(self, two_shards):
        embs, _, client, remote_only = two_shards
        ids = np.array([11, 22, 33, 44], np.int64)
        a = client.gather(ids)
        b = remote_only.gather(ids)
        np.testing.assert_allclose(a, b, rtol=1e-6)

    def test_gather_or_zeros_for_unknown(self, two_shards):
        _, _, client, _ = two_shards
        rows = client.gather(np.array([999_999, 888_888], np.int64),
                             insert=False)
        np.testing.assert_array_equal(rows, 0.0)


class TestPartitionedTraining:
    def test_regression_trains_across_shards(self, two_shards):
        """E2e: ids exceed one shard's initial capacity; training converges
        with gradients routed over the control plane."""
        embs, _, client, _ = two_shards
        rng = np.random.default_rng(0)
        # 48 ids per shard > initial capacity 16 → both shards must grow
        ids = rng.permutation(np.arange(1000, 1096, dtype=np.int64))
        targets = {int(i): rng.standard_normal(DIM).astype(np.float32)
                   for i in ids}
        losses = []
        for step in range(60):
            batch = rng.choice(ids, 32)
            rows = client.gather(batch)
            t = np.stack([targets[int(i)] for i in batch])
            losses.append(float(np.mean((rows - t) ** 2)))
            client.apply_gradients(batch, 2 * (rows - t) / len(batch))
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
        stats = client.stats()
        # the vocabulary really is spread: each shard holds ~half, and the
        # total exceeds what one initial-capacity table could hold
        assert all(s["vocab"] >= 40 for s in stats)
        assert sum(s["vocab"] for s in stats) > 64

    def test_duplicate_grads_summed_once(self, two_shards):
        embs, _, client, _ = two_shards
        ids = np.array([4, 4, 4], np.int64)  # one unique id, shard 0
        client.gather(ids)
        before = client.gather(np.array([4], np.int64)).copy()
        g = np.ones((3, DIM), np.float32)
        client.apply_gradients(ids, g)
        after = client.gather(np.array([4], np.int64))
        # sgd lr=0.5: one update with the SUMMED grad (3.0), not three
        np.testing.assert_allclose(before - after, 0.5 * 3.0, rtol=1e-5)


class TestMinFreqInvariant:
    def test_low_freq_grads_go_to_null_row(self):
        """An id under min_freq reads zeros in forward; its gradient must
        hit the null row, never the real row (kv_embedding invariant)."""
        emb = KvEmbedding(dim=DIM, capacity=16, prefer_native=False,
                          min_freq=2,
                          optimizer=SparseOptConfig(kind="sgd", lr=1.0))
        srv = EmbeddingShardServer(emb, shard_id=0, num_shards=1)
        srv.start()
        client = PartitionedKvEmbedding(DIM, [srv.addr])
        try:
            ids = np.array([42], np.int64)
            rows = client.gather(ids)  # first sighting: freq 1 < 2
            np.testing.assert_array_equal(rows, 0.0)
            client.apply_gradients(ids, np.ones((1, DIM), np.float32))
            # the REAL row is untouched: on its 2nd sighting it surfaces
            # with its pristine init value, not init - lr*grad
            real_slot = emb.store.lookup(ids)
            before = np.asarray(emb.values[int(real_slot[0])]).copy()
            rows2 = client.gather(ids)  # freq 2 → real row now
            np.testing.assert_allclose(rows2[0], before, rtol=1e-6)
        finally:
            client.close()
            srv.stop()


class TestIdempotence:
    def test_replayed_grads_apply_once(self, two_shards):
        """An at-least-once retry replaying the same (client, seq) must not
        re-apply the gradient."""
        embs, servers, client, _ = two_shards
        from dlrover_wuqiong_tpu.embedding.partitioned import _pack

        ids = np.array([100], np.int64)  # shard 0
        client.gather(ids)
        before = client.gather(ids).copy()
        payload = {"op": "emb_grads", "ids": _pack(ids),
                   "grads": _pack(np.ones((1, DIM), np.float32)),
                   "client": "c1", "seq": 7}
        servers[0]._handle("report", -1, "", dict(payload))
        servers[0]._handle("report", -1, "", dict(payload))  # retry replay
        after = client.gather(ids)
        # sgd lr=0.5, grad 1.0 → exactly ONE 0.5 step despite two deliveries
        np.testing.assert_allclose(before - after, 0.5, rtol=1e-5)

    def test_duplicate_ids_count_frequency_per_occurrence(self):
        """min_freq admission parity with the single-host path: an id seen
        twice IN ONE BATCH is admitted (freq 2), not deferred."""
        emb = KvEmbedding(dim=DIM, capacity=16, prefer_native=False,
                          min_freq=2,
                          optimizer=SparseOptConfig(kind="sgd", lr=1.0))
        srv = EmbeddingShardServer(emb, shard_id=0, num_shards=1)
        srv.start()
        client = PartitionedKvEmbedding(DIM, [srv.addr])
        try:
            rows = client.gather(np.array([42, 42], np.int64))
            # freq reaches 2 within the batch → the second occurrence (and
            # the whole post-filter view) resolves to the real row
            assert np.abs(rows).sum() > 0.0
        finally:
            client.close()
            srv.stop()

    def test_wildcard_bind_requires_advertise_host(self):
        emb = KvEmbedding(dim=DIM, capacity=8, prefer_native=False)
        with pytest.raises(ValueError, match="advertise_host"):
            EmbeddingShardServer(emb, 0, 1, host="0.0.0.0")


class TestShardSafety:
    def test_wrong_owner_rejected(self, two_shards):
        _, servers, _, _ = two_shards
        from dlrover_wuqiong_tpu.common.comm import RpcClient, RpcError

        from dlrover_wuqiong_tpu.embedding.partitioned import _pack

        c = RpcClient(servers[0].addr)
        with pytest.raises(RpcError, match="does not own"):
            c.report({"op": "emb_gather",
                      "ids": _pack(np.array([3], np.int64))})  # odd → shard 1
        c.close()

    def test_unknown_op_rejected(self, two_shards):
        _, servers, _, _ = two_shards
        from dlrover_wuqiong_tpu.common.comm import RpcClient, RpcError

        c = RpcClient(servers[0].addr)
        with pytest.raises(RpcError, match="unknown embedding op"):
            c.report({"op": "emb_bogus"})
        c.close()

    def test_delta_export_over_rpc(self, two_shards):
        _, servers, client, _ = two_shards
        from dlrover_wuqiong_tpu.common.comm import RpcClient

        client.gather(np.array([2, 4, 6], np.int64))
        c = RpcClient(servers[0].addr)
        c.report({"op": "emb_advance_epoch"})
        client.apply_gradients(np.array([2], np.int64),
                               np.ones((1, DIM), np.float32))
        resp = c.report({"op": "emb_export_delta"})
        assert "delta" in resp and "keys" in resp["delta"]
        from dlrover_wuqiong_tpu.embedding.partitioned import _unpack

        keys = _unpack(resp["delta"]["keys"])
        assert 2 in keys.tolist()
        c.close()

"""Unified telemetry: goodput ledger, trace spans, flight recorder.

Pins the ADD-ONLY schemas (LEDGER_STATES, ledger snapshot keys, flight
dump envelope keys), the attribution-total invariant (states + other ==
wall), cross-process trace propagation over the real RPC path, the
master-side goodput aggregation (report → servicer → summary →
/metrics), and the tools/goodput_report.py offline CLI.
"""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from dlrover_wuqiong_tpu.telemetry import (
    FLIGHT_SCHEMA_VERSION,
    LEDGER_SCHEMA_VERSION,
    LEDGER_STATES,
    SPAN_SCHEMA_VERSION,
    FlightRecorder,
    GoodputLedger,
    get_ledger,
    get_recorder,
    load_flight_dumps,
    reset_ledger,
    reset_recorder,
)
from dlrover_wuqiong_tpu.telemetry import spans as tspans

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    """Singletons are process-global — every test gets clean ones."""
    reset_ledger()
    reset_recorder()
    tspans.clear_spans()
    yield
    reset_ledger()
    reset_recorder()
    tspans.clear_spans()


class _FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


# ------------------------------------------------------------------ ledger


class TestGoodputLedger:
    # ADD-ONLY: every locked name must stay forever (master aggregation,
    # /metrics labels, goodput_report and the chaos drills key on them);
    # new states append, never rename.  The pin source of truth is the
    # committed wire-surface lockfile (analysis/schema.lock.json, gated
    # by graftlint's schema engine) — only the canary is hand-pinned.
    def test_states_schema_add_only(self, schema_lock):
        locked = schema_lock["registries"]["LEDGER_STATES"]
        missing = set(locked) - set(LEDGER_STATES)
        assert not missing, f"removed ledger state(s) {missing}"
        assert "productive" in LEDGER_STATES   # hand-pinned canary
        assert LEDGER_SCHEMA_VERSION >= 1

    def test_snapshot_keys_add_only(self):
        led = GoodputLedger()
        snap = led.snapshot()
        for key in ("schema", "wall_s", "states", "other_s",
                    "goodput_fraction", "started_wall"):
            assert key in snap, f"removed snapshot key {key!r}"
        assert set(snap["states"]) == set(LEDGER_STATES)

    def test_attribution_is_total(self):
        clk = _FakeClock()
        led = GoodputLedger(clock=clk)
        led.start()
        with led.window("productive"):
            clk.t += 6.0
        with led.window("compile"):
            clk.t += 3.0
        clk.t += 1.0  # uncredited second -> residual
        snap = led.snapshot()
        assert snap["wall_s"] == pytest.approx(10.0)
        assert snap["states"]["productive"] == pytest.approx(6.0)
        assert snap["states"]["compile"] == pytest.approx(3.0)
        # states + other == wall BY CONSTRUCTION (other is computed)
        assert snap["other_s"] == pytest.approx(1.0)
        assert sum(snap["states"].values()) + snap["other_s"] == \
            pytest.approx(snap["wall_s"])
        assert snap["goodput_fraction"] == pytest.approx(0.6)

    def test_overcredit_never_goes_negative(self):
        # concurrent windows (saver thread + train loop) can credit more
        # than wall — the residual clamps at 0 and the fraction uses the
        # larger of (wall, credited) so it stays <= 1
        clk = _FakeClock()
        led = GoodputLedger(clock=clk)
        led.start()
        led.account("productive", 5.0)
        led.account("ckpt_persist", 5.0)
        clk.t += 4.0
        snap = led.snapshot()
        assert snap["other_s"] == 0.0
        assert 0.0 <= snap["goodput_fraction"] <= 1.0

    def test_unknown_state_raises(self):
        led = GoodputLedger()
        with pytest.raises(ValueError, match="add-only"):
            led.account("coffee_break", 1.0)

    def test_nonpositive_credit_ignored(self):
        led = GoodputLedger()
        led.account("productive", 0.0)
        led.account("productive", -3.0)
        assert led.snapshot()["states"]["productive"] == 0.0

    def test_start_idempotent_and_singleton_reset(self):
        led = get_ledger()
        assert led is get_ledger()
        led.start()
        w0 = led.snapshot()["started_wall"]
        time.sleep(0.01)
        led.start()  # first call wins
        assert led.snapshot()["started_wall"] == w0
        assert reset_ledger() is not led

    def test_thread_safety_under_concurrent_credits(self):
        led = GoodputLedger()

        def credit():
            for _ in range(500):
                led.account("productive", 0.001)

        threads = [threading.Thread(target=credit) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert led.snapshot()["states"]["productive"] == \
            pytest.approx(2.0, rel=1e-6)


# ------------------------------------------------------------------- spans


class TestSpans:
    def test_nesting_links_parent_child(self):
        with tspans.span("outer") as outer:
            with tspans.span("inner") as inner:
                pass
        assert inner["trace_id"] == outer["trace_id"]
        assert inner["parent_span"] == outer["span_id"]
        assert outer["parent_span"] == ""
        names = [s["name"] for s in tspans.spans_snapshot()]
        assert names[-2:] == ["inner", "outer"]  # closed innermost-first
        assert outer["schema"] == SPAN_SCHEMA_VERSION
        assert outer["dur_s"] >= inner["dur_s"] >= 0.0

    def test_error_status_on_raise(self):
        with pytest.raises(RuntimeError):
            with tspans.span("boom"):
                raise RuntimeError("x")
        rec = tspans.spans_snapshot()[-1]
        assert rec["name"] == "boom" and rec["status"] == "error"

    def test_extract_adopts_incoming_frame_context(self):
        incoming = {"trace_id": "t" * 16, "span_id": "s" * 16}
        with tspans.extract(incoming):
            with tspans.span("serve:op") as rec:
                pass
        assert rec["trace_id"] == incoming["trace_id"]
        assert rec["parent_span"] == incoming["span_id"]
        # stack restored: a new span outside starts a fresh trace
        with tspans.span("fresh") as rec2:
            pass
        assert rec2["trace_id"] != incoming["trace_id"]

    def test_env_context_propagates_to_spawned_child(self, monkeypatch):
        with tspans.span("parent") as parent:
            with tspans.env_context() as env:
                assert env["DWT_TRACE_ID"] == parent["trace_id"]
                assert env["DWT_TRACE_PARENT"] == parent["span_id"]
                child_env = dict(env)
        # simulate the spawned child: fresh thread (fresh TLS stack)
        # with the inherited env — its first span joins the trace
        monkeypatch.setenv("DWT_TRACE_ID", child_env["DWT_TRACE_ID"])
        monkeypatch.setenv("DWT_TRACE_PARENT",
                           child_env["DWT_TRACE_PARENT"])
        out = {}

        def child():
            with tspans.span("child-op") as rec:
                out.update(rec)

        t = threading.Thread(target=child)
        t.start()
        t.join()
        assert out["trace_id"] == parent["trace_id"]
        assert out["parent_span"] == parent["span_id"]

    def test_spans_are_flight_recorder_events(self):
        tspans.span_event("mark", {"k": 1})
        kinds = [(e["kind"], e["name"])
                 for e in get_recorder().snapshot()]
        assert ("span", "mark") in kinds

    def test_chrome_trace_dump(self, tmp_path):
        with tspans.span("a"):
            tspans.span_event("b")
        path = str(tmp_path / "trace.json")
        n = tspans.dump_chrome_trace(path)
        assert n >= 2
        data = json.loads(open(path).read())
        evt = data["traceEvents"][0]
        for key in ("name", "cat", "ph", "ts", "dur", "pid", "args"):
            assert key in evt


# ---------------------------------------------------------------- recorder


class TestFlightRecorder:
    def test_ring_is_bounded_drop_oldest(self):
        rec = FlightRecorder(max_events=4)
        for i in range(10):
            rec.record("mark", f"e{i}")
        events = rec.snapshot()
        assert len(events) == 4
        assert [e["name"] for e in events] == ["e6", "e7", "e8", "e9"]

    def test_flush_and_load_roundtrip(self, tmp_path):
        get_ledger().account("productive", 1.5)
        rec = get_recorder()
        rec.record("mark", "hello", {"x": 1})
        path = rec.flush(str(tmp_path), "fault")
        assert path and os.path.exists(path)
        dumps = load_flight_dumps(str(tmp_path))
        assert len(dumps) == 1
        dump = dumps[0]
        # ADD-ONLY envelope (tools/goodput_report.py --flight keys on it)
        for key in ("schema", "role", "pid", "reason", "flushed_at",
                    "ledger", "events"):
            assert key in dump, f"removed flight-dump key {key!r}"
        assert dump["schema"] == FLIGHT_SCHEMA_VERSION
        assert dump["reason"] == "fault"
        assert dump["pid"] == os.getpid()
        assert dump["ledger"]["states"]["productive"] == \
            pytest.approx(1.5)
        evt = [e for e in dump["events"] if e["name"] == "hello"][0]
        for key in ("t_wall", "kind", "name", "data"):
            assert key in evt
        assert evt["data"] == {"x": 1}

    def test_flush_sequence_keeps_all_dumps(self, tmp_path):
        rec = get_recorder()
        rec.record("mark", "a")
        p1 = rec.flush(str(tmp_path), "fault")
        p2 = rec.flush(str(tmp_path), "sigterm")
        assert p1 != p2
        reasons = [d["reason"] for d in load_flight_dumps(str(tmp_path))]
        assert reasons == ["fault", "sigterm"]

    def test_flush_never_raises(self, tmp_path):
        assert get_recorder().flush("", "fault") is None
        blocker = tmp_path / "f"
        blocker.write_text("not a dir")
        # flight dir creation fails (parent is a file) -> swallowed
        assert get_recorder().flush(str(blocker), "fault") is None


# -------------------------------------------- rpc trace + goodput flow


class TestRpcTraceAndGoodput:
    def test_goodput_report_to_summary_and_metrics(self):
        """report_goodput_ledger → servicer → latest-wins aggregation →
        GoodputSummary + dwt_goodput_* gauges on the master registry."""
        from dlrover_wuqiong_tpu.agent.master_client import MasterClient
        from dlrover_wuqiong_tpu.master.master import JobMaster

        master = JobMaster(min_nodes=1, max_nodes=1)
        master.prepare()
        try:
            mc = MasterClient(master.addr, node_id=0)
            led = reset_ledger()
            led.account("productive", 8.0)
            led.account("compile", 2.0)
            mc.report_goodput_ledger(led.snapshot())
            # cumulative resend: latest-wins, NOT double counted
            led.account("productive", 2.0)
            mc.report_goodput_ledger(led.snapshot())
            summary = mc.get_goodput_summary()
            assert summary.nodes == 1
            assert summary.states["productive"] == pytest.approx(10.0)
            assert summary.states["compile"] == pytest.approx(2.0)
            assert 0.0 < summary.goodput_fraction <= 1.0
            rendered = master.metric_collector.reg.render()
            assert "dwt_goodput_seconds" in rendered
            assert 'state="productive"' in rendered
            assert "dwt_goodput_fraction" in rendered
            mc.close()
        finally:
            master.stop()

    def test_trace_tree_spans_client_and_servicer(self):
        """One client operation under a root span produces rpc:<verb>
        (client thread) and serve:<verb> (servicer thread) spans sharing
        ONE trace_id, with serve parented under rpc — the cross-process
        propagation path, exercised over a real socket."""
        from dlrover_wuqiong_tpu.agent.master_client import MasterClient
        from dlrover_wuqiong_tpu.master.master import JobMaster

        master = JobMaster(min_nodes=1, max_nodes=1)
        master.prepare()
        try:
            mc = MasterClient(master.addr, node_id=0)
            with tspans.span("restore:drill") as root:
                mc.kv_store_set("tk", b"tv")
            assert mc.kv_store_get("tk") == b"tv"
            mc.close()
        finally:
            master.stop()
        spans = tspans.spans_snapshot()
        rpc = [s for s in spans if s["name"].startswith("rpc:")
               and s["trace_id"] == root["trace_id"]]
        assert rpc, [s["name"] for s in spans]
        assert rpc[0]["parent_span"] == root["span_id"]
        serve = [s for s in spans if s["name"].startswith("serve:")
                 and s["trace_id"] == root["trace_id"]]
        assert serve, [s["name"] for s in spans]
        rpc_ids = {s["span_id"] for s in rpc}
        assert serve[0]["parent_span"] in rpc_ids

    def test_goodput_report_cli_flight_mode(self, tmp_path):
        """tools/goodput_report.py --flight: one JSON line summarizing
        the dumps' latest-per-process ledgers and span counts."""
        led = get_ledger()
        led.account("productive", 4.0)
        led.account("restore_storage", 1.0)
        with tspans.span("ckpt:restore"):
            pass
        get_recorder().flush(str(tmp_path), "fault")
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "goodput_report.py"),
             "--flight", str(tmp_path)],
            capture_output=True, text=True, timeout=60, cwd=REPO)
        assert proc.returncode == 0, proc.stderr[-2000:]
        lines = proc.stdout.strip().splitlines()
        assert len(lines) == 1
        report = json.loads(lines[0])
        assert report["source"] == "flight"
        assert report["dumps"] == 1 and report["nodes"] == 1
        assert report["states"]["productive"] == pytest.approx(4.0)
        assert report["states"]["restore_storage"] == pytest.approx(1.0)
        assert 0.0 < report["goodput_fraction"] < 1.0
        assert report["spans"] >= 1 and report["traces"] >= 1

    def test_goodput_report_cli_no_address_fails_cleanly(self):
        env = dict(os.environ)
        env.pop("DWT_MASTER_ADDR", None)
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "goodput_report.py")],
            capture_output=True, text=True, timeout=60, cwd=REPO,
            env=env)
        assert proc.returncode == 2
        assert "error" in json.loads(proc.stdout.strip())

"""Serving subsystem: continuous batching, schemas, queue, journal replay.

Mirrors reference behavior only at the boundary (`atorch/atorch/rl/
model_engine/model_engine.py:35` delegates generation to vLLM — the
reference has no serving plane of its own to test), so everything here
pins the TPU redesign's OWN invariants:

- the continuous-batching EQUIVALENCE invariant: a request's tokens are
  a pure function of (weights, prompt, seed) — identical whether it
  decodes alone, packed in a busy batch, staggered mid-flight, or on an
  engine with a different slot/fusion geometry (serving/engine.py's
  write-then-attend + positional fold_in design);
- seeded-sampling determinism, for both the serving engine and the RLHF
  `generate()` that shares `forward_step` (rl/generation.py);
- ADD-ONLY schema pins for the serving telemetry (SERVE_STATES /
  SERVE_COUNTERS / snapshot keys, telemetry/serving.py) and the Serve*
  control-plane message family (common/messages.py), in the
  tests/test_policy.py pin style;
- ServeQueueManager semantics (dedupe, FIFO, front-requeue on recovery,
  idempotent complete, master-side requeue attribution) and their
  survival across a master restart via journal replay
  (master/serve_queue.py + master/master.py serve_* journal kinds).
"""

import dataclasses

import numpy as np
import pytest

import jax

from dlrover_wuqiong_tpu.common import messages as msg
from dlrover_wuqiong_tpu.master.serve_queue import ServeQueueManager
from dlrover_wuqiong_tpu.models.gpt import GPT, GPTConfig
from dlrover_wuqiong_tpu.serving import (
    LocalServer,
    ServeSpec,
    ServingEngine,
    serve_step_cache_key,
)
from dlrover_wuqiong_tpu.serving.scheduler import request_trace_id
from dlrover_wuqiong_tpu.telemetry.serving import (
    SERVE_COUNTERS,
    SERVE_SCHEMA_VERSION,
    SERVE_STATES,
    ServeLedger,
)

# ------------------------------------------------------------- fixtures


@pytest.fixture(scope="module")
def cfg():
    return GPTConfig.nano()


@pytest.fixture(scope="module")
def params(cfg):
    return GPT(cfg).init_params(jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def engine(cfg, params):
    """The shared 2-slot engine — small enough that 4 requests churn
    slots, wide enough (max_len 48) for every request below."""
    return ServingEngine(cfg, params, ServeSpec(
        max_slots=2, max_len=48, max_prompt_len=8, fused_tokens=4))


# (request_id, prompt, max_new_tokens, temperature, seed) — mixed
# temperatures INCLUDING greedy (temp=0), mixed lengths, distinct seeds
REQS = [
    ("a", [1, 7, 13], 12, 1.0, 5),
    ("b", [2, 9], 9, 0.0, 0),
    ("c", [3, 4, 5, 6], 11, 1.0, 6),
    ("d", [8], 12, 0.7, 7),
]


def _submit(server, spec):
    rid, prompt, n, temp, seed = spec
    server.submit(rid, prompt, max_new_tokens=n, seed=seed,
                  temperature=temp)


def _drain_scheduler(sch):
    out = {}
    while not sch.idle():
        sch.step()
        for r in sch.take_results():
            out[r.request_id] = list(r.tokens)
    for r in sch.take_results():
        out[r.request_id] = list(r.tokens)
    return out


def _alone(eng, spec):
    """Decode one request on an otherwise-empty batch."""
    s = LocalServer(eng)
    _submit(s, spec)
    return s.drain()[spec[0]]


# ---------------------------------------------------- spec validation


class TestServeSpecValidation:
    def test_bad_quant_mode(self, cfg, params):
        with pytest.raises(ValueError, match="quant mode"):
            ServingEngine(cfg, params, ServeSpec(quant="int4"))

    def test_max_len_exceeds_block_size(self, cfg, params):
        with pytest.raises(ValueError, match="block_size"):
            ServingEngine(cfg, params, ServeSpec(
                max_len=cfg.block_size + 1))

    def test_bad_max_prompt_len(self, cfg, params):
        with pytest.raises(ValueError, match="max_prompt_len"):
            ServingEngine(cfg, params, ServeSpec(
                max_len=32, max_prompt_len=64))
        with pytest.raises(ValueError, match="max_prompt_len"):
            ServingEngine(cfg, params, ServeSpec(max_prompt_len=0))

    def test_bad_slots_and_fusion(self, cfg, params):
        with pytest.raises(ValueError, match="max_slots"):
            ServingEngine(cfg, params, ServeSpec(max_slots=0))
        with pytest.raises(ValueError, match="fused_tokens"):
            ServingEngine(cfg, params, ServeSpec(fused_tokens=0))

    def test_admit_prompt_too_long(self, engine):
        with pytest.raises(ValueError, match="prompt length"):
            engine.admit(0, list(range(1, 10)), seed=0)  # 9 > 8

    def test_admit_budget_exceeds_max_len(self, engine):
        with pytest.raises(ValueError, match="max_len"):
            engine.admit(0, [1, 2, 3], seed=0, max_new_tokens=46)

    def test_admit_occupied_slot(self, engine):
        engine.admit(0, [1, 2], seed=0)
        try:
            with pytest.raises(ValueError, match="occupied"):
                engine.admit(0, [3, 4], seed=1)
        finally:
            engine.retire(0)


# ----------------------------------------- continuous-batching equivalence


class TestContinuousBatchingEquivalence:
    def test_busy_batch_matches_alone(self, engine):
        """4 requests on 2 slots: slots churn (finishers free a slot
        mid-drain, waiters admit into it) yet every request's tokens are
        bit-identical to decoding it alone."""
        busy = LocalServer(engine)
        for spec in REQS:
            _submit(busy, spec)
        packed = busy.drain()
        assert set(packed) == {r[0] for r in REQS}
        for spec in REQS:
            assert len(packed[spec[0]]) == spec[2]
            assert packed[spec[0]] == _alone(engine, spec), spec[0]

    def test_staggered_admission_matches_alone(self, engine):
        """Requests submitted MID-FLIGHT (after other requests already
        decoded a few windows) still match their alone decode — slot
        admission at a window boundary does not perturb tenants and the
        late request does not see the earlier tenants' cache state."""
        s = LocalServer(engine)
        _submit(s, REQS[0])
        _submit(s, REQS[1])
        s.scheduler.step()  # a window decodes before the late arrivals
        _submit(s, REQS[2])
        _submit(s, REQS[3])
        out = _drain_scheduler(s.scheduler)
        for spec in REQS:
            assert out[spec[0]] == _alone(engine, spec), spec[0]

    def test_cross_geometry_identical(self, cfg, params, engine):
        """A DIFFERENT batch geometry (3 slots, K=2 vs 2 slots, K=4)
        produces the same tokens: the equivalence invariant is about the
        request, not the executable."""
        other = ServingEngine(cfg, params, ServeSpec(
            max_slots=3, max_len=48, max_prompt_len=8, fused_tokens=2))
        assert other.cache_key != engine.cache_key  # distinct programs
        a = LocalServer(engine)
        b = LocalServer(other)
        for spec in REQS:
            _submit(a, spec)
            _submit(b, spec)
        assert a.drain() == b.drain()

    def test_greedy_ignores_seed(self, engine):
        """temp=0 rows take the argmax branch of the jnp.where select —
        the seed must be dead."""
        rid, prompt, n, _, _ = REQS[1]
        t1 = _alone(engine, (rid, prompt, n, 0.0, 0))
        t2 = _alone(engine, (rid, prompt, n, 0.0, 12345))
        assert t1 == t2


# ------------------------------------------------- seeded determinism


class TestSeededDeterminism:
    def test_same_seed_same_tokens(self, engine):
        spec = ("det", [5, 6, 7], 10, 1.0, 42)
        assert _alone(engine, spec) == _alone(engine, spec)

    def test_different_seed_differs(self, engine):
        a = _alone(engine, ("s0", [5, 6, 7], 12, 1.0, 0))
        b = _alone(engine, ("s1", [5, 6, 7], 12, 1.0, 1))
        assert a != b

    def test_rl_generate_same_key_deterministic(self, cfg, params):
        """Serving and RLHF share one decode-step implementation
        (rl/generation.forward_step); generate() must be a pure function
        of (params, prompt, rng, sample)."""
        from dlrover_wuqiong_tpu.rl.generation import (
            SampleConfig,
            generate,
        )
        prompt = jax.numpy.asarray([[1, 7, 13]], dtype=jax.numpy.int32)
        sample = SampleConfig(max_new_tokens=8, temperature=1.0)
        key = jax.random.PRNGKey(9)
        t1, lp1 = generate(cfg, params, prompt, key, sample)
        t2, lp2 = generate(cfg, params, prompt, key, sample)
        assert np.array_equal(np.asarray(t1), np.asarray(t2))
        assert np.array_equal(np.asarray(lp1), np.asarray(lp2))
        t3, _ = generate(cfg, params, prompt, jax.random.PRNGKey(10),
                         sample)
        assert not np.array_equal(np.asarray(t1), np.asarray(t3))


# ------------------------------------------------------- quant modes


class TestQuantizedDecode:
    def test_int8_decodes_and_syncs(self, cfg, params):
        eng = ServingEngine(cfg, params, ServeSpec(
            max_slots=1, max_len=16, max_prompt_len=4, fused_tokens=2,
            quant="int8"))
        spec = ("q", [1, 2], 6, 1.0, 3)
        first = _alone(eng, spec)
        assert len(first) == 6
        # one-hop weight refresh: same tree structure → same programs,
        # deterministic under the new weights too
        fresh = GPT(cfg).init_params(jax.random.PRNGKey(1))
        eng.sync_from_trainer(fresh)
        after = _alone(eng, spec)
        assert len(after) == 6
        assert _alone(eng, spec) == after  # still deterministic

    def test_sync_rejects_different_tree(self, cfg, params):
        eng = ServingEngine(cfg, params, ServeSpec(
            max_slots=1, max_len=16, max_prompt_len=4, fused_tokens=2))
        with pytest.raises(ValueError, match="tree structure"):
            eng.sync_from_trainer({"bogus": jax.numpy.ones((2, 2))})

    def test_cache_key_covers_spec_and_quant(self, cfg):
        base = ServeSpec(max_slots=2, max_len=32, max_prompt_len=8,
                         fused_tokens=4)
        k = serve_step_cache_key(cfg, base)
        assert k == serve_step_cache_key(cfg, base)  # stable digest
        for changed in (
            dataclasses.replace(base, quant="int8"),
            dataclasses.replace(base, quant="fp8"),
            dataclasses.replace(base, max_slots=3),
            dataclasses.replace(base, max_len=64),
            dataclasses.replace(base, fused_tokens=2),
            dataclasses.replace(base, top_k=8),
        ):
            assert serve_step_cache_key(cfg, changed) != k, changed


# ------------------------------------------------- ADD-ONLY schema pins


class TestServingSchemasAddOnly:
    # pin source of truth: the committed wire-surface lockfile
    # (analysis/schema.lock.json, gated by graftlint's schema engine);
    # one hand-pinned canary per surface guards the lock itself.
    def test_serve_states_pinned(self, schema_lock):
        required = set(schema_lock["registries"]["SERVE_STATES"])
        missing = required - set(SERVE_STATES)
        assert not missing, f"SERVE_STATES is add-only; lost {missing}"
        assert "decode" in SERVE_STATES   # hand-pinned canary

    def test_serve_counters_pinned(self, schema_lock):
        required = set(schema_lock["registries"]["SERVE_COUNTERS"])
        missing = required - set(SERVE_COUNTERS)
        assert not missing, f"SERVE_COUNTERS is add-only; lost {missing}"
        assert "tokens_out" in SERVE_COUNTERS   # hand-pinned canary
        assert SERVE_SCHEMA_VERSION >= 1

    def test_snapshot_keys_pinned(self):
        led = ServeLedger()
        led.start()
        led.note_admit("r")
        led.note_first_token("r")
        led.note_finish("r")
        snap = led.snapshot()
        required = {"schema", "wall_s", "states", "other_s", "counters",
                    "active_requests", "latency", "started_wall"}
        missing = required - set(snap)
        assert not missing, f"snapshot keys are add-only; lost {missing}"
        lat = {"samples", "p50_ms", "p99_ms", "ttft_p50_ms",
               "ttft_p99_ms"}
        assert not lat - set(snap["latency"])
        assert snap["latency"]["samples"] == 1
        assert snap["active_requests"] == 0

    def test_unknown_names_rejected(self):
        led = ServeLedger()
        led.start()
        with pytest.raises(ValueError, match="add-only"):
            led.account("serving", 1.0)
        with pytest.raises(ValueError, match="add-only"):
            led.count("dropped")

    def test_window_accounting_uses_injected_clock(self):
        t = {"now": 100.0}
        led = ServeLedger(clock=lambda: t["now"])
        led.start()
        with led.window("decode"):
            t["now"] += 2.5
        snap = led.snapshot()
        assert snap["states"]["decode"] == pytest.approx(2.5)
        assert snap["wall_s"] == pytest.approx(2.5)

    @pytest.mark.parametrize("cls", [
        msg.ServeRequest, msg.ServeResult, msg.ServeStatsReport,
        msg.ServeSummary,
    ])
    def test_message_fields_pinned(self, cls, schema_lock):
        required = {f["name"] for f in
                    schema_lock["messages"][cls.__name__]["fields"]}
        names = {f.name for f in dataclasses.fields(cls)}
        missing = required - names
        assert not missing, \
            f"{cls.__name__} is add-only; lost {missing}"

    def test_message_field_canary(self):
        # hand-pinned canary: survives even a bad lock regeneration
        assert "tokens" in {f.name
                            for f in dataclasses.fields(msg.ServeResult)}

    def test_request_trace_id_deterministic(self):
        tid = request_trace_id("req-00")
        assert tid == request_trace_id("req-00")
        assert len(tid) == 16 and int(tid, 16) >= 0
        assert tid != request_trace_id("req-01")


# --------------------------------------------------- serve queue manager


def _req(rid, prompt=(1, 2)):
    return msg.ServeRequest(request_id=rid, prompt=list(prompt),
                            max_new_tokens=4, seed=0)


def _res(rid, tokens=(7, 8, 9, 10)):
    return msg.ServeResult(request_id=rid, tokens=list(tokens),
                           latency_s=0.5, ttft_s=0.1)


class TestServeQueueManager:
    def test_submit_dedupes_pending_and_done(self):
        q = ServeQueueManager()
        assert q.submit([_req("a"), _req("b"), _req("a")]) == 2
        assert q.submit([_req("a")]) == 0  # still pending
        q.lease(1, 2)
        q.complete([_res("a")])
        assert q.submit([_req("a")]) == 0  # already done
        assert q.summary().submitted_total == 2

    def test_lease_is_fifo(self):
        q = ServeQueueManager()
        q.submit([_req(f"r{i}") for i in range(4)])
        assert [r.request_id for r in q.lease(1, 2)] == ["r0", "r1"]
        assert [r.request_id for r in q.lease(2, 9)] == ["r2", "r3"]
        assert q.lease(3, 1) == []

    def test_recover_requeues_to_front_in_order(self):
        q = ServeQueueManager()
        q.submit([_req(f"r{i}") for i in range(4)])
        q.lease(1, 2)  # r0, r1 leased
        assert q.recover_node(1) == 2
        # requeued requests OUTRANK never-leased ones, original order
        assert [r.request_id for r in q.lease(2, 4)] == \
            ["r0", "r1", "r2", "r3"]
        assert q.recover_node(99) == 0  # unknown node is a no-op

    def test_complete_is_idempotent(self):
        q = ServeQueueManager()
        q.submit([_req("a")])
        q.lease(1, 1)
        assert q.complete([_res("a")]) == 1
        assert q.complete([_res("a")]) == 0  # the retry after a lost ack
        summ = q.summary()
        assert summ.done_total == 1 and summ.leased == 0

    def test_lease_exact_replays_assignment(self):
        q = ServeQueueManager()
        q.submit([_req("a"), _req("b")])
        q.lease_exact(7, ["b"])  # journal replay path
        assert [r.request_id for r in q.lease(1, 5)] == ["a"]
        assert q.summary().leased == 2
        # "b" really is node 7's lease: its recovery requeues exactly it
        assert q.recover_node(7) == 1
        assert [r.request_id for r in q.lease(2, 5)] == ["b"]

    def test_summary_attributes_requeues_master_side(self):
        """Workers cannot see their own death: the master folds its
        requeue count into the pinned `requeued` counter even when no
        worker ever reported one."""
        q = ServeQueueManager()
        q.submit([_req("a"), _req("b")])
        q.lease(1, 2)
        q.recover_node(1)
        summ = q.summary()
        assert summ.requeued_total == 2
        assert summ.counters["requeued"] == 2

    def test_take_results_pops_and_counts_pending(self):
        q = ServeQueueManager()
        q.submit([_req("a"), _req("b")])
        q.lease(1, 2)
        q.complete([_res("a")])
        results, pending = q.take_results(["a", "b"])
        assert [r.request_id for r in results] == ["a"]
        assert pending == 1
        assert q.take_results(["a", "b"]) == ([], 1)  # popped

    def test_collect_stats_latest_sent_wins(self):
        q = ServeQueueManager()
        q.collect_stats(msg.ServeStatsReport(
            node_id=1, counters={"finished": 9}, sent_at=200.0))
        q.collect_stats(msg.ServeStatsReport(  # stale BUFFERED drain
            node_id=1, counters={"finished": 3}, sent_at=100.0))
        summ = q.summary()
        assert summ.counters["finished"] == 9
        assert summ.workers == 1


# ------------------------------------------------- journal replay


class TestServeJournalReplay:
    def test_queue_state_survives_master_crash(self, tmp_path):
        from dlrover_wuqiong_tpu.agent.master_client import MasterClient
        from dlrover_wuqiong_tpu.master.master import JobMaster

        jd = str(tmp_path / "journal")
        m1 = JobMaster(port=0, journal_dir=jd)
        m1.prepare()
        front = MasterClient(f"127.0.0.1:{m1.port}", node_id=90)
        wrk = MasterClient(f"127.0.0.1:{m1.port}", node_id=7,
                           node_type="serve-worker")
        ack = front.submit_serve_requests(
            [_req("a"), _req("b"), _req("c")])
        assert ack.accepted == 3
        leased = wrk.lease_serve_requests(max_requests=2)
        assert [r.request_id for r in leased] == ["a", "b"]
        wrk.report_serve_results([_res("a")])
        # crash: no clean stop, no final snapshot — replay must rebuild
        m1._server.stop()  # noqa: SLF001

        m2 = JobMaster(port=0, journal_dir=jd)
        m2.prepare()
        try:
            front2 = MasterClient(f"127.0.0.1:{m2.port}", node_id=90)
            summ = front2.get_serve_summary()
            assert summ.submitted_total == 3
            assert summ.done_total == 1
            assert summ.leased == 1      # "b" still assigned to node 7
            assert summ.queue_depth == 1  # "c" still pending
            # the done result survives the restart and is collectable
            resp = front2.get_serve_results(["a"])
            assert [r.request_id for r in resp.results] == ["a"]
            assert resp.results[0].tokens == [7, 8, 9, 10]
            # node 7 died with the old master: its failure report routes
            # through recover_node, and "b" requeues AHEAD of "c"
            wrk2 = MasterClient(f"127.0.0.1:{m2.port}", node_id=7,
                                node_type="serve-worker")
            wrk2.report_failure("drill: node lost", level="process")
            summ2 = front2.get_serve_summary()
            assert summ2.requeued_total >= 1
            assert summ2.counters.get("requeued", 0) >= 1
            relief = MasterClient(f"127.0.0.1:{m2.port}", node_id=8,
                                  node_type="serve-worker")
            got = relief.lease_serve_requests(max_requests=1)
            assert [r.request_id for r in got] == ["b"]
        finally:
            m2.stop()

    def test_submit_retry_across_restart_is_idempotent(self, tmp_path):
        """A ServeSubmitRequest acked by master #1 and RETRIED with the
        same idem key against replayed master #2 must not re-enqueue."""
        from dlrover_wuqiong_tpu.agent.master_client import MasterClient
        from dlrover_wuqiong_tpu.master.master import JobMaster

        jd = str(tmp_path / "journal")
        m1 = JobMaster(port=0, journal_dir=jd)
        m1.prepare()
        mc = MasterClient(f"127.0.0.1:{m1.port}", node_id=90)
        idem = "node90:serve-submit:1"
        payload = msg.ServeSubmitRequest(node_id=90,
                                         requests=[_req("a")])
        ack = mc._client.report(payload, idem=idem)  # noqa: SLF001
        assert ack.accepted == 1
        m1._server.stop()  # noqa: SLF001

        m2 = JobMaster(port=0, journal_dir=jd)
        m2.prepare()
        try:
            mc2 = MasterClient(f"127.0.0.1:{m2.port}", node_id=90)
            replay = mc2._client.report(payload, idem=idem)  # noqa: SLF001
            assert replay.accepted == 1  # the JOURNALED response, not a
            # re-application (dedupe would have returned accepted=0)
            summ = mc2.get_serve_summary()
            assert summ.submitted_total == 1
            assert summ.queue_depth == 1
        finally:
            m2.stop()

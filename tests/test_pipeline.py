"""Pipeline parallelism tests: GPipe schedule over the pp mesh axis.

Mirrors reference `atorch/atorch/tests` pipe tests in spirit — numerics of
the staged execution must match the dense model, and training must step.
Runs on the virtual 8-device CPU mesh (conftest).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from version_gates import requires_shard_map

from dlrover_wuqiong_tpu.auto.accelerate import auto_accelerate
from dlrover_wuqiong_tpu.models.gpt import GPT, GPTConfig
from dlrover_wuqiong_tpu.models.llama import Llama, LlamaConfig
from dlrover_wuqiong_tpu.parallel.mesh import MeshPlan, build_mesh
from dlrover_wuqiong_tpu.parallel.pipeline import (
    PipelinedLM,
    circular_layer_order,
    pipeline_1f1b,
    pipeline_apply,
    schedule_ticks,
    split_layer_params,
    stack_layer_params,
)


def _pp_mesh(pp=2, fsdp=1, tp=1):
    n = pp * fsdp * tp
    return build_mesh(MeshPlan(pp=pp, fsdp=fsdp, tp=tp), jax.devices()[:n])


@requires_shard_map
class TestPipelineApply:
    def test_matches_sequential_scan(self):
        """The staged pipeline must be numerically identical to running the
        stacked layers sequentially."""
        mesh = _pp_mesh(pp=4)
        L, B, T, C = 4, 8, 16, 32
        key = jax.random.PRNGKey(0)
        w = jax.random.normal(key, (L, C, C), jnp.float32) * 0.1
        x = jax.random.normal(jax.random.PRNGKey(1), (B, T, C))

        def block(pl, h):
            return jnp.tanh(h @ pl)

        def seq(w, x):
            for i in range(L):
                x = block(w[i], x)
            return x

        with mesh:
            got = jax.jit(
                lambda w, x: pipeline_apply(block, w, x, mesh, 4))(w, x)
        want = seq(w, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5)

    @requires_shard_map
    def test_grads_match_sequential(self):
        mesh = _pp_mesh(pp=2)
        L, B, T, C = 2, 4, 8, 16
        w = jax.random.normal(jax.random.PRNGKey(0), (L, C, C)) * 0.1
        x = jax.random.normal(jax.random.PRNGKey(1), (B, T, C))

        def block(pl, h):
            return jnp.tanh(h @ pl)

        def loss_pp(w):
            with mesh:
                return pipeline_apply(block, w, x, mesh, 2).sum()

        def loss_seq(w):
            h = x
            for i in range(L):
                h = block(w[i], h)
            return h.sum()

        g_pp = jax.jit(jax.grad(loss_pp))(w)
        g_seq = jax.grad(loss_seq)(w)
        np.testing.assert_allclose(np.asarray(g_pp), np.asarray(g_seq),
                                   atol=1e-4)


class TestInterleavedSchedule:
    """Circular virtual-stage schedule (Megatron interleaved 1F1B's bubble
    reduction, ref StageInterleaver.py)."""

    def _toy(self, L=8, B=8, T=4, C=16):
        w = jax.random.normal(jax.random.PRNGKey(0), (L, C, C)) * 0.1
        x = jax.random.normal(jax.random.PRNGKey(1), (B, T, C))

        def block(pl, h):
            return jnp.tanh(h @ pl)

        def seq(w, x):
            for i in range(L):
                x = block(w[i], x)
            return x

        return w, x, block, seq

    @requires_shard_map
    def test_matches_sequential(self):
        mesh = _pp_mesh(pp=2)
        w, x, block, seq = self._toy()
        order = circular_layer_order(8, pp=2, v=2)
        with mesh:
            got = jax.jit(lambda w, x: pipeline_apply(
                block, w, x, mesh, 4, schedule="interleaved",
                virtual_stages=2))(w[jnp.array(order)], x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(seq(w, x)),
                                   atol=1e-5)

    @requires_shard_map
    def test_grads_match_sequential(self):
        mesh = _pp_mesh(pp=2)
        w, x, block, seq = self._toy()
        order = jnp.array(circular_layer_order(8, pp=2, v=2))

        def loss_ppl(w):
            with mesh:
                return pipeline_apply(block, w[order], x, mesh, 4,
                                      schedule="interleaved",
                                      virtual_stages=2).sum()

        g_ppl = jax.jit(jax.grad(loss_ppl))(w)
        g_seq = jax.grad(lambda w: seq(w, x).sum())(w)
        np.testing.assert_allclose(np.asarray(g_ppl), np.asarray(g_seq),
                                   atol=1e-4)

    def test_bubble_smaller_than_gpipe(self):
        """At m=4, s=4, v=2 the interleaved bubble must beat GPipe's."""
        _, gpipe = schedule_ticks("gpipe", 4, 4)
        _, inter = schedule_ticks("interleaved", 4, 4, virtual_stages=2)
        assert inter < gpipe
        assert gpipe == pytest.approx(3 / 7)
        assert inter == pytest.approx(3 / 11)

    def test_rejects_bad_microbatches(self):
        mesh = _pp_mesh(pp=2)
        w, x, block, _ = self._toy()
        with pytest.raises(ValueError, match="divisible"):
            with mesh:
                pipeline_apply(block, w, x, mesh, 3,
                               schedule="interleaved", virtual_stages=2)


class TestOneFOneB:
    """Manual 1F1B schedule: numerics + O(pp) stash."""

    def _setup(self, pp, L=4, M=4, B=8, T=4, C=16):
        mesh = _pp_mesh(pp=pp)
        w = jax.random.normal(jax.random.PRNGKey(0), (L, C, C)) * 0.1
        hp = {"w": jax.random.normal(jax.random.PRNGKey(1), (C,)) * 0.1}
        x = jax.random.normal(jax.random.PRNGKey(2), (M, B // M, T, C))
        tgt = jax.random.normal(jax.random.PRNGKey(3), (M, B // M, T))

        def block(pl, h):
            return jnp.tanh(h @ pl)

        def head_loss(hp, h, t):
            return jnp.mean((h @ hp["w"] - t) ** 2)

        return mesh, w, hp, x, tgt, block, head_loss

    def _reference(self, w, hp, x, tgt, block, head_loss):
        """Plain autodiff over the sequential model."""
        def total(w, hp, x):
            def one(mx, mt):
                h = mx
                for i in range(w.shape[0]):
                    h = block(w[i], h)
                return head_loss(hp, h, mt)
            return jnp.mean(jax.vmap(one)(x, tgt))

        loss, grads = jax.value_and_grad(total, argnums=(0, 1, 2))(w, hp, x)
        return loss, grads

    @pytest.mark.parametrize("pp", [2, 4])
    @requires_shard_map
    def test_matches_autodiff(self, pp):
        mesh, w, hp, x, tgt, block, head_loss = self._setup(pp)
        with mesh:
            loss, d_w, d_hp, d_x = jax.jit(
                lambda w, hp, x, tgt: pipeline_1f1b(
                    block, head_loss, w, hp, x, tgt, mesh))(w, hp, x, tgt)
        ref_loss, (rd_w, rd_hp, rd_x) = self._reference(
            w, hp, x, tgt, block, head_loss)
        np.testing.assert_allclose(float(loss), float(ref_loss), atol=1e-5)
        np.testing.assert_allclose(np.asarray(d_w), np.asarray(rd_w),
                                   atol=1e-4)
        np.testing.assert_allclose(np.asarray(d_hp["w"]),
                                   np.asarray(rd_hp["w"]), atol=1e-4)
        np.testing.assert_allclose(np.asarray(d_x), np.asarray(rd_x),
                                   atol=1e-4)

    def test_pp1_path_matches(self):
        mesh, w, hp, x, tgt, block, head_loss = self._setup(pp=2)
        mesh1 = _pp_mesh(pp=1)
        loss, d_w, d_hp, d_x = pipeline_1f1b(block, head_loss, w, hp, x,
                                             tgt, mesh1)
        ref_loss, (rd_w, _, _) = self._reference(w, hp, x, tgt, block,
                                                 head_loss)
        np.testing.assert_allclose(float(loss), float(ref_loss), atol=1e-6)
        np.testing.assert_allclose(np.asarray(d_w), np.asarray(rd_w),
                                   atol=1e-5)

    @requires_shard_map
    def test_gpt_value_and_grad_matches_dense(self):
        """PipelinedLM.value_and_grad (1f1b) vs autodiff on the dense GPT —
        including the tied-wte grad that sums embed+head contributions."""
        from dlrover_wuqiong_tpu.trainer.train_step import make_lm_loss

        cfg = dataclasses.replace(GPTConfig.nano(), dtype=jnp.float32,
                                  remat=False, use_flash_attention=False)
        mesh = _pp_mesh(pp=2)
        model = GPT(cfg)
        dense_params = model.init_params(jax.random.PRNGKey(0))
        plm = PipelinedLM(model, mesh, num_microbatches=2, schedule="1f1b")
        pp_params = plm.from_flat_params(dense_params)
        data = jax.random.randint(jax.random.PRNGKey(1), (4, 17), 0,
                                  cfg.vocab_size)
        batch = {"input_ids": data[:, :-1], "labels": data[:, 1:]}
        with mesh:
            loss, grads = jax.jit(plm.value_and_grad)(pp_params, batch)
        dense_loss, dense_grads = jax.value_and_grad(
            make_lm_loss(model.apply))(dense_params, batch)
        np.testing.assert_allclose(float(loss), float(dense_loss),
                                   atol=2e-4)
        flat = plm.to_flat_params(grads)
        for k in ("wte", "wpe", "ln_f"):
            np.testing.assert_allclose(
                np.asarray(jax.tree.leaves(flat[k])[0]),
                np.asarray(jax.tree.leaves(dense_grads[k])[0]), atol=5e-3)

    @requires_shard_map
    def test_1f1b_compiled_memory_below_gpipe(self):
        """The O(pp) stash must show up as lower temp memory than GPipe's
        O(M) residuals when M >> pp (compiled on the CPU mesh)."""
        pp, L, M, B, T, C = 2, 4, 16, 32, 8, 64
        mesh, w, hp, x, tgt, block, head_loss = self._setup(
            pp=pp, L=L, M=M, B=B, T=T, C=C)

        def loss_gpipe(w, hp, x, tgt):
            with mesh:
                xf = x.reshape(B, T, C)
                y = pipeline_apply(block, w, xf, mesh, M)
                ym = y.reshape(M, B // M, T, C)
                return jnp.mean(jax.vmap(
                    lambda h, t: head_loss(hp, h, t))(ym, tgt))

        def grads_1f1b(w, hp, x, tgt):
            with mesh:
                return pipeline_1f1b(block, head_loss, w, hp, x, tgt, mesh)

        gpipe_c = jax.jit(jax.grad(loss_gpipe, argnums=(0, 1, 2))).lower(
            w, hp, x, tgt).compile()
        f1b_c = jax.jit(grads_1f1b).lower(w, hp, x, tgt).compile()
        try:
            gp_tmp = gpipe_c.memory_analysis().temp_size_in_bytes
            fb_tmp = f1b_c.memory_analysis().temp_size_in_bytes
        except (AttributeError, NotImplementedError):
            pytest.skip("backend has no memory_analysis")
        assert fb_tmp < gp_tmp, (fb_tmp, gp_tmp)


@requires_shard_map
class TestPipelinedLM:
    def _gpt_cfg(self):
        return dataclasses.replace(GPTConfig.nano(), dtype=jnp.float32,
                                   remat=False, use_flash_attention=False)

    def test_gpt_logits_match_dense(self):
        cfg = self._gpt_cfg()
        mesh = _pp_mesh(pp=2)
        model = GPT(cfg)
        dense_params = model.init_params(jax.random.PRNGKey(0))
        plm = PipelinedLM(model, mesh, num_microbatches=2)
        pp_params = plm.init_params(jax.random.PRNGKey(0))
        # restructure dense params into the pipelined layout for comparison
        non_layer, layers, _ = split_layer_params(dict(dense_params))
        pp_from_dense = dict(non_layer, blocks=stack_layer_params(layers))

        idx = jax.random.randint(jax.random.PRNGKey(2), (4, 16), 0,
                                 cfg.vocab_size)
        with mesh:
            got = jax.jit(lambda p: plm.apply({"params": p}, idx))(
                pp_from_dense)
        want = model.apply({"params": dense_params}, idx)
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(want, np.float32), atol=2e-4)
        # init layouts agree structurally
        assert jax.tree.structure(pp_params) == jax.tree.structure(
            pp_from_dense)

    def test_llama_logits_match_dense(self):
        cfg = dataclasses.replace(LlamaConfig.nano(), dtype=jnp.float32,
                                  remat=False, use_flash_attention=False)
        mesh = _pp_mesh(pp=2)
        model = Llama(cfg)
        dense_params = model.init_params(jax.random.PRNGKey(0))
        plm = PipelinedLM(model, mesh, num_microbatches=2)
        plm.init_params(jax.random.PRNGKey(0))
        non_layer, layers, _ = split_layer_params(dict(dense_params))
        pp_from_dense = dict(non_layer, blocks=stack_layer_params(layers))

        idx = jax.random.randint(jax.random.PRNGKey(2), (4, 16), 0,
                                 cfg.vocab_size)
        with mesh:
            got = jax.jit(lambda p: plm.apply({"params": p}, idx))(
                pp_from_dense)
        want = model.apply({"params": dense_params}, idx)
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(want, np.float32), atol=2e-4)


class TestPipelineTraining:
    @requires_shard_map
    def test_auto_accelerate_pp_trains(self):
        """pp=2 x fsdp=2 end-to-end: loss decreases over steps."""
        cfg = dataclasses.replace(GPTConfig.nano(), remat=False,
                                  use_flash_attention=False,
                                  dtype=jnp.float32)
        res = auto_accelerate(
            GPT(cfg), optimizer=optax.adam(1e-2),
            strategy=[("pipeline_parallel", {"size": 2, "microbatches": 2}),
                      ("fsdp", {})],
            devices=jax.devices()[:4])
        data = jax.random.randint(jax.random.PRNGKey(0), (8, 33), 0,
                                  cfg.vocab_size)
        batch = res.place_batch({"input_ids": data[:, :-1],
                                 "labels": data[:, 1:]})
        state = res.state
        losses = []
        for _ in range(5):
            state, m = res.train_step(state, batch)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0]
        # stacked block params actually sharded over pp
        blocks_sh = res.state_shardings.params["blocks"]
        leaf = jax.tree.leaves(blocks_sh)[0]
        assert "pp" in str(leaf.spec)

    @pytest.mark.parametrize("schedule,vstages",
                             [("1f1b", 1), ("interleaved", 2)])
    @requires_shard_map
    def test_auto_accelerate_schedules_train(self, schedule, vstages):
        """pp=2 end-to-end under each non-default schedule: loss decreases
        and tp composition holds (tp=2 exercises GSPMD inside the stage)."""
        cfg = dataclasses.replace(GPTConfig.nano(), remat=False,
                                  use_flash_attention=False,
                                  n_layer=2 * vstages,
                                  dtype=jnp.float32)
        res = auto_accelerate(
            GPT(cfg), optimizer=optax.adam(1e-2),
            strategy=[("pipeline_parallel",
                       {"size": 2, "microbatches": 2,
                        "schedule": schedule, "virtual_stages": vstages}),
                      ("tensor_parallel", {"size": 2})],
            devices=jax.devices()[:4])
        data = jax.random.randint(jax.random.PRNGKey(0), (8, 33), 0,
                                  cfg.vocab_size)
        batch = res.place_batch({"input_ids": data[:, :-1],
                                 "labels": data[:, 1:]})
        state = res.state
        losses = []
        for _ in range(5):
            state, m = res.train_step(state, batch)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0], losses

    @requires_shard_map
    def test_generic_adapter_model_stages(self):
        """Arbitrary layer-stack models pipeline via the adapter hooks."""
        import flax.linen as nn

        class ToyCfg:
            n_layer = 2

        class ToyBlock(nn.Module):
            @nn.compact
            def __call__(self, h):
                return h + nn.Dense(h.shape[-1])(jnp.tanh(h))

        class Toy:
            """Minimal custom model: h_<i> blocks + in/out dense."""
            config = ToyCfg()

            def init_params(self, rng):
                C = 8
                ks = jax.random.split(rng, 4)
                p = {"inp": nn.Dense(C).init(
                    ks[0], jnp.zeros((1, 1, 4)))["params"],
                    "out": nn.Dense(3).init(
                        ks[1], jnp.zeros((1, 1, C)))["params"]}
                blk = ToyBlock()
                for i in range(2):
                    p[f"h_{i}"] = blk.init(
                        ks[2 + i], jnp.zeros((1, 1, C)))["params"]
                return p

            def apply(self, variables, x, deterministic=True, mutable=None):
                p = variables["params"]
                h = nn.Dense(8).apply({"params": p["inp"]}, x)
                for i in range(2):
                    h = ToyBlock().apply({"params": p[f"h_{i}"]}, h)
                return nn.Dense(3).apply({"params": p["out"]}, h)

        mesh = _pp_mesh(pp=2)
        toy = Toy()
        dense = toy.init_params(jax.random.PRNGKey(0))
        plm = PipelinedLM(
            toy, mesh, num_microbatches=2,
            embed_fn=lambda p, x: nn.Dense(8).apply(
                {"params": p["inp"]}, x),
            block_builder=lambda p, x, det: (
                lambda pl, h: ToyBlock().apply({"params": pl}, h)),
            head_fn=lambda p, h: nn.Dense(3).apply(
                {"params": p["out"]}, h),
            embed_keys=("inp",), head_keys=("out",))
        pp_params = plm.from_flat_params(dense)
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 6, 4))
        with mesh:
            got = jax.jit(lambda p: plm.apply({"params": p}, x))(pp_params)
        want = toy.apply({"params": dense}, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5)

    @pytest.mark.parametrize("schedule,vstages",
                             [("gpipe", 1), ("interleaved", 2)])
    @requires_shard_map
    def test_moe_through_pipeline(self, schedule, vstages):
        """MoE models pipeline: the router aux loss crosses the schedule
        as an explicit scalar and matches the dense model's."""
        from dlrover_wuqiong_tpu.trainer.train_step import make_lm_loss

        cfg = dataclasses.replace(GPTConfig.nano(), remat=False,
                                  use_flash_attention=False,
                                  n_layer=2 * vstages, moe_experts=4,
                                  dtype=jnp.float32)
        mesh = _pp_mesh(pp=2)
        model = GPT(cfg)
        dense_params = model.init_params(jax.random.PRNGKey(0))
        plm = PipelinedLM(model, mesh, num_microbatches=2,
                          schedule=schedule, virtual_stages=vstages)
        pp_params = plm.from_flat_params(dense_params)
        data = jax.random.randint(jax.random.PRNGKey(1), (4, 17), 0,
                                  cfg.vocab_size)
        batch = {"input_ids": data[:, :-1], "labels": data[:, 1:]}
        with mesh:
            loss = jax.jit(make_lm_loss(plm.apply))(pp_params, batch)
        dense_loss = make_lm_loss(model.apply)(dense_params, batch)
        # router statistics (capacity drops, aux balance) are computed per
        # microbatch in a pipeline vs whole-batch densely — standard
        # microbatched-MoE semantics, so close but not bitwise equal
        np.testing.assert_allclose(float(loss), float(dense_loss),
                                   atol=2e-2)
        # the aux term is actually present (loss > plain ce)
        logits = model.apply({"params": dense_params},
                             batch["input_ids"])
        from dlrover_wuqiong_tpu.models.gpt import cross_entropy_loss

        ce = float(cross_entropy_loss(logits, batch["labels"]))
        assert float(loss) > ce

    @requires_shard_map
    def test_moe_pipeline_trains_e2e(self):
        cfg = dataclasses.replace(GPTConfig.nano(), remat=False,
                                  use_flash_attention=False,
                                  moe_experts=4, dtype=jnp.float32)
        res = auto_accelerate(
            GPT(cfg), optimizer=optax.adam(1e-2),
            strategy=[("pipeline_parallel", {"size": 2,
                                             "microbatches": 2}),
                      ("fsdp", {})],
            devices=jax.devices()[:4])
        data = jax.random.randint(jax.random.PRNGKey(0), (8, 33), 0,
                                  cfg.vocab_size)
        batch = res.place_batch({"input_ids": data[:, :-1],
                                 "labels": data[:, 1:]})
        state, losses = res.state, []
        for _ in range(5):
            state, m = res.train_step(state, batch)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0], losses

    @pytest.mark.parametrize("schedule,vstages",
                             [("1f1b", 1), ("interleaved", 2)])
    @requires_shard_map
    def test_schedules_compose_with_grad_accum(self, schedule, vstages):
        """Outer grad-accum microbatches wrap the pipeline's inner ones."""
        cfg = dataclasses.replace(GPTConfig.nano(), remat=False,
                                  use_flash_attention=False,
                                  n_layer=2 * vstages, dtype=jnp.float32)
        res = auto_accelerate(
            GPT(cfg), optimizer=optax.adam(1e-2),
            strategy=[("pipeline_parallel",
                       {"size": 2, "microbatches": 2,
                        "schedule": schedule, "virtual_stages": vstages}),
                      ("fsdp", {}), ("grad_accum", {"steps": 2})],
            devices=jax.devices()[:4])
        data = jax.random.randint(jax.random.PRNGKey(0), (2, 8, 33), 0,
                                  cfg.vocab_size)
        batch = res.place_batch({"input_ids": data[..., :-1],
                                 "labels": data[..., 1:]})
        state, losses = res.state, []
        for _ in range(4):
            state, m = res.train_step(state, batch)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0], losses

    @requires_shard_map
    def test_pp_sp_gspmd_composes(self):
        """Sequence parallel in gspmd mode (XLA-inserted collectives)
        composes with the pipeline — only ring/ulysses are rejected."""
        cfg = dataclasses.replace(GPTConfig.nano(), remat=False,
                                  use_flash_attention=False,
                                  dtype=jnp.float32)
        res = auto_accelerate(
            GPT(cfg), optimizer=optax.adam(1e-2),
            strategy=[("pipeline_parallel", {"size": 2,
                                             "microbatches": 2}),
                      ("sequence_parallel", {"size": 2, "impl": "gspmd"}),
                      ("fsdp", {})],
            devices=jax.devices()[:8])
        data = jax.random.randint(jax.random.PRNGKey(0), (8, 33), 0,
                                  cfg.vocab_size)
        batch = res.place_batch({"input_ids": data[:, :-1],
                                 "labels": data[:, 1:]})
        state, losses = res.state, []
        for _ in range(4):
            state, m = res.train_step(state, batch)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0], losses

    @pytest.mark.parametrize("impl", ["ring", "ulysses"])
    @requires_shard_map
    def test_pp_sp_ring_ulysses_grads_match_plain_pp(self, impl):
        """pp x ring/ulysses SP (round-4 closure): the attention shard_map
        nests inside the pipeline's manual-pp body (context AbstractMesh +
        VMA tracking), and the gradients must equal plain-pp's — this
        exact check caught the check_vma=False transpose corruption."""
        cfg = dataclasses.replace(GPTConfig.nano(), remat=False,
                                  use_flash_attention=False,
                                  dtype=jnp.float32)
        data = jax.random.randint(jax.random.PRNGKey(0), (8, 33), 0,
                                  cfg.vocab_size)

        def grads_of(strategy):
            res = auto_accelerate(GPT(cfg), optimizer=optax.sgd(0.0),
                                  strategy=strategy,
                                  devices=jax.devices()[:8],
                                  rng=jax.random.PRNGKey(5))
            batch = res.place_batch({"input_ids": data[:, :-1],
                                     "labels": data[:, 1:]})
            g = jax.jit(jax.grad(lambda p: res.loss_fn(p, batch)))(
                dict(res.state.params))
            return jax.tree.map(np.asarray, g)

        pp = [("pipeline_parallel", {"size": 2, "microbatches": 2})]
        base = grads_of(pp + [("fsdp", {})])
        sp = grads_of(pp + [("sequence_parallel",
                             {"size": 2, "impl": impl}), ("fsdp", {})])
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4,
                                                    atol=1e-6),
            base, sp)

    @requires_shard_map
    def test_1f1b_ring_sp_grads_match_and_train(self):
        """ring-SP inside the MANUAL 1f1b backward: gradient-exact vs
        plain 1f1b, and training steps."""
        cfg = dataclasses.replace(GPTConfig.nano(), remat=False,
                                  use_flash_attention=False,
                                  dtype=jnp.float32)
        data = jax.random.randint(jax.random.PRNGKey(0), (8, 33), 0,
                                  cfg.vocab_size)

        def vg_of(strategy):
            res = auto_accelerate(GPT(cfg), optimizer=optax.adam(1e-2),
                                  strategy=strategy,
                                  devices=jax.devices()[:8],
                                  rng=jax.random.PRNGKey(5))
            batch = res.place_batch({"input_ids": data[:, :-1],
                                     "labels": data[:, 1:]})
            loss, g = jax.jit(res.model.value_and_grad)(
                dict(res.state.params), batch)
            return res, batch, float(loss), jax.tree.map(np.asarray, g)

        pp = [("pipeline_parallel", {"size": 2, "microbatches": 2,
                                     "schedule": "1f1b"})]
        _, _, l0, g0 = vg_of(pp + [("fsdp", {})])
        res, batch, l1, g1 = vg_of(
            pp + [("sequence_parallel", {"size": 2, "impl": "ring"}),
                  ("fsdp", {})])
        assert abs(l0 - l1) < 1e-5
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4,
                                                    atol=1e-6),
            g0, g1)
        state, losses = res.state, []
        for _ in range(4):
            state, m = res.train_step(state, batch)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0], losses

    @requires_shard_map
    def test_llama_trains_under_1f1b(self):
        """The 1f1b value_and_grad path handles the Llama family (untied
        embed/head key split) too."""
        cfg = dataclasses.replace(LlamaConfig.nano(), remat=False,
                                  use_flash_attention=False,
                                  dtype=jnp.float32)
        res = auto_accelerate(
            Llama(cfg), optimizer=optax.adam(1e-2),
            strategy=[("pipeline_parallel",
                       {"size": 2, "microbatches": 2,
                        "schedule": "1f1b"}), ("fsdp", {})],
            devices=jax.devices()[:4])
        data = jax.random.randint(jax.random.PRNGKey(1), (8, 33), 0,
                                  cfg.vocab_size)
        batch = res.place_batch({"input_ids": data[:, :-1],
                                 "labels": data[:, 1:]})
        state, losses = res.state, []
        for _ in range(4):
            state, m = res.train_step(state, batch)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0], losses

    @requires_shard_map
    def test_moe_pp_ep_composes(self):
        """Expert parallelism composes with the pipeline: experts shard
        over ep inside the stage while layers shard over pp."""
        cfg = dataclasses.replace(GPTConfig.nano(), remat=False,
                                  use_flash_attention=False,
                                  moe_experts=4, dtype=jnp.float32)
        res = auto_accelerate(
            GPT(cfg), optimizer=optax.adam(1e-2),
            strategy=[("pipeline_parallel", {"size": 2,
                                             "microbatches": 2}),
                      ("expert_parallel", {"size": 2}), ("fsdp", {})],
            devices=jax.devices()[:8])
        data = jax.random.randint(jax.random.PRNGKey(0), (8, 33), 0,
                                  cfg.vocab_size)
        batch = res.place_batch({"input_ids": data[:, :-1],
                                 "labels": data[:, 1:]})
        state, losses = res.state, []
        for _ in range(4):
            state, m = res.train_step(state, batch)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0], losses

    def test_local_sgd_pp_rejected_clearly(self):
        cfg = dataclasses.replace(GPTConfig.nano(), remat=False)
        with pytest.raises(ValueError, match="local_sgd.*pipeline"):
            auto_accelerate(
                GPT(cfg),
                strategy=[("pipeline_parallel", {"size": 2}),
                          ("data_parallel", {"size": 2}),
                          ("local_sgd", {"sync_every": 2})],
                devices=jax.devices()[:4])

    @requires_shard_map
    def test_moe_1f1b_composes_and_matches_gpipe(self):
        """MoE x 1f1b (round-3 rejection, now closed): the manual backward
        seeds the router aux-loss cotangent (1/M per microbatch), so the
        1f1b loss equals gpipe's on identical init/batch and training
        makes progress."""
        cfg = dataclasses.replace(GPTConfig.nano(), remat=False,
                                  use_flash_attention=False,
                                  moe_experts=4, dtype=jnp.float32)
        data = jax.random.randint(jax.random.PRNGKey(0), (8, 33), 0,
                                  cfg.vocab_size)

        def build(schedule):
            res = auto_accelerate(
                GPT(cfg), optimizer=optax.adam(1e-2),
                strategy=[("pipeline_parallel",
                           {"size": 2, "microbatches": 2,
                            "schedule": schedule}), ("fsdp", {})],
                devices=jax.devices()[:4], rng=jax.random.PRNGKey(5))
            batch = res.place_batch({"input_ids": data[:, :-1],
                                     "labels": data[:, 1:]})
            return res, batch

        res_g, b_g = build("gpipe")
        res_f, b_f = build("1f1b")
        _, m_g = res_g.train_step(res_g.state, b_g)
        state, m_f = res_f.train_step(res_f.state, b_f)
        # same init, same batch, aux included on both paths
        assert abs(float(m_g["loss"]) - float(m_f["loss"])) < 1e-4, (
            float(m_g["loss"]), float(m_f["loss"]))
        losses = [float(m_f["loss"])]
        for _ in range(3):
            state, m_f = res_f.train_step(state, b_f)
            losses.append(float(m_f["loss"]))
        assert losses[-1] < losses[0], losses

    def test_pp_rejects_indivisible_layers(self):
        cfg = dataclasses.replace(GPTConfig.nano(), remat=False)  # 2 layers
        with pytest.raises(ValueError, match="divisible"):
            auto_accelerate(GPT(cfg),
                            strategy=[("pipeline_parallel", {"size": 3})],
                            devices=jax.devices()[:3])


class TestOneFOneBCustomHeadLoss:
    """1f1b x custom loss (round-4 partial closure): a PER-MICROBATCH
    head loss — the shape the in-schedule backward can seed — threads
    through ('pipeline_parallel', {'head_loss': fn}); whole-batch
    loss_fn stays rejected with a message pointing here."""

    @requires_shard_map
    def test_label_smoothed_head_loss_matches_gpipe_equivalent(self):
        import flax.linen as nn

        cfg = dataclasses.replace(GPTConfig.nano(), remat=False,
                                  use_flash_attention=False,
                                  dtype=jnp.float32)
        data = jax.random.randint(jax.random.PRNGKey(0), (8, 33), 0,
                                  cfg.vocab_size)
        EPS = 0.1

        def smoothed_ce_from_logits(logits, labels):
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
            nll = -jnp.take_along_axis(logp, labels[..., None],
                                       -1)[..., 0]
            uniform = -logp.mean(-1)
            return ((1 - EPS) * nll + EPS * uniform).mean()

        def head_loss(hp, h, labels):
            x = nn.LayerNorm(dtype=cfg.dtype).apply({"params": hp["ln_f"]},
                                                    h)
            logits = jnp.einsum("bte,ve->btv", x,
                                hp["wte"]["embedding"].astype(cfg.dtype))
            return smoothed_ce_from_logits(logits, labels)

        res = auto_accelerate(
            GPT(cfg), optimizer=optax.sgd(0.0),
            strategy=[("pipeline_parallel",
                       {"size": 2, "microbatches": 2, "schedule": "1f1b",
                        "head_loss": head_loss}), ("fsdp", {})],
            devices=jax.devices()[:8], rng=jax.random.PRNGKey(5))
        batch = res.place_batch({"input_ids": data[:, :-1],
                                 "labels": data[:, 1:]})
        loss_1f1b, g_1f1b = jax.jit(res.model.value_and_grad)(
            dict(res.state.params), batch)

        # gpipe equivalent: whole-batch custom loss over the same model
        def whole_batch_loss(params, batch):
            logits = res_g.model.apply({"params": params},
                                       batch["input_ids"])
            return smoothed_ce_from_logits(logits, batch["labels"])

        res_g = auto_accelerate(
            GPT(cfg), optimizer=optax.sgd(0.0),
            strategy=[("pipeline_parallel",
                       {"size": 2, "microbatches": 2}), ("fsdp", {})],
            devices=jax.devices()[:8], rng=jax.random.PRNGKey(5))
        batch_g = res_g.place_batch({"input_ids": data[:, :-1],
                                     "labels": data[:, 1:]})
        loss_g, g_g = jax.jit(jax.value_and_grad(whole_batch_loss))(
            dict(res_g.state.params), batch_g)
        np.testing.assert_allclose(float(loss_1f1b), float(loss_g),
                                   atol=1e-5)
        # both grads are in the pipelined {blocks, ...} layout
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
            g_1f1b, g_g)

    def test_whole_batch_loss_fn_still_rejected_with_pointer(self):
        cfg = dataclasses.replace(GPTConfig.nano(), remat=False)
        with pytest.raises(ValueError, match="head_loss"):
            auto_accelerate(
                GPT(cfg), loss_fn=lambda p, b: 0.0,
                strategy=[("pipeline_parallel",
                           {"size": 2, "schedule": "1f1b"})],
                devices=jax.devices()[:2])

    def test_head_loss_outside_1f1b_rejected(self):
        cfg = dataclasses.replace(GPTConfig.nano(), remat=False)
        with pytest.raises(ValueError, match="1f1b"):
            auto_accelerate(
                GPT(cfg),
                strategy=[("pipeline_parallel",
                           {"size": 2, "head_loss": lambda *a: 0.0})],
                devices=jax.devices()[:2])

    def test_head_loss_with_pp1_rejected(self):
        cfg = dataclasses.replace(GPTConfig.nano(), remat=False)
        with pytest.raises(ValueError, match="size"):
            auto_accelerate(
                GPT(cfg),
                strategy=[("pipeline_parallel",
                           {"schedule": "1f1b",
                            "head_loss": lambda *a: 0.0})],
                devices=jax.devices()[:2])

"""Pipeline parallelism tests: GPipe schedule over the pp mesh axis.

Mirrors reference `atorch/atorch/tests` pipe tests in spirit — numerics of
the staged execution must match the dense model, and training must step.
Runs on the virtual 8-device CPU mesh (conftest).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_wuqiong_tpu.auto.accelerate import auto_accelerate
from dlrover_wuqiong_tpu.models.gpt import GPT, GPTConfig
from dlrover_wuqiong_tpu.models.llama import Llama, LlamaConfig
from dlrover_wuqiong_tpu.parallel.mesh import MeshPlan, build_mesh
from dlrover_wuqiong_tpu.parallel.pipeline import (
    PipelinedLM,
    pipeline_apply,
    split_layer_params,
    stack_layer_params,
)


def _pp_mesh(pp=2, fsdp=1, tp=1):
    n = pp * fsdp * tp
    return build_mesh(MeshPlan(pp=pp, fsdp=fsdp, tp=tp), jax.devices()[:n])


class TestPipelineApply:
    def test_matches_sequential_scan(self):
        """The staged pipeline must be numerically identical to running the
        stacked layers sequentially."""
        mesh = _pp_mesh(pp=4)
        L, B, T, C = 4, 8, 16, 32
        key = jax.random.PRNGKey(0)
        w = jax.random.normal(key, (L, C, C), jnp.float32) * 0.1
        x = jax.random.normal(jax.random.PRNGKey(1), (B, T, C))

        def block(pl, h):
            return jnp.tanh(h @ pl)

        def seq(w, x):
            for i in range(L):
                x = block(w[i], x)
            return x

        with mesh:
            got = jax.jit(
                lambda w, x: pipeline_apply(block, w, x, mesh, 4))(w, x)
        want = seq(w, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5)

    def test_grads_match_sequential(self):
        mesh = _pp_mesh(pp=2)
        L, B, T, C = 2, 4, 8, 16
        w = jax.random.normal(jax.random.PRNGKey(0), (L, C, C)) * 0.1
        x = jax.random.normal(jax.random.PRNGKey(1), (B, T, C))

        def block(pl, h):
            return jnp.tanh(h @ pl)

        def loss_pp(w):
            with mesh:
                return pipeline_apply(block, w, x, mesh, 2).sum()

        def loss_seq(w):
            h = x
            for i in range(L):
                h = block(w[i], h)
            return h.sum()

        g_pp = jax.jit(jax.grad(loss_pp))(w)
        g_seq = jax.grad(loss_seq)(w)
        np.testing.assert_allclose(np.asarray(g_pp), np.asarray(g_seq),
                                   atol=1e-4)


class TestPipelinedLM:
    def _gpt_cfg(self):
        return dataclasses.replace(GPTConfig.nano(), dtype=jnp.float32,
                                   remat=False, use_flash_attention=False)

    def test_gpt_logits_match_dense(self):
        cfg = self._gpt_cfg()
        mesh = _pp_mesh(pp=2)
        model = GPT(cfg)
        dense_params = model.init_params(jax.random.PRNGKey(0))
        plm = PipelinedLM(model, mesh, num_microbatches=2)
        pp_params = plm.init_params(jax.random.PRNGKey(0))
        # restructure dense params into the pipelined layout for comparison
        non_layer, layers, _ = split_layer_params(dict(dense_params))
        pp_from_dense = dict(non_layer, blocks=stack_layer_params(layers))

        idx = jax.random.randint(jax.random.PRNGKey(2), (4, 16), 0,
                                 cfg.vocab_size)
        with mesh:
            got = jax.jit(lambda p: plm.apply({"params": p}, idx))(
                pp_from_dense)
        want = model.apply({"params": dense_params}, idx)
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(want, np.float32), atol=2e-4)
        # init layouts agree structurally
        assert jax.tree.structure(pp_params) == jax.tree.structure(
            pp_from_dense)

    def test_llama_logits_match_dense(self):
        cfg = dataclasses.replace(LlamaConfig.nano(), dtype=jnp.float32,
                                  remat=False, use_flash_attention=False)
        mesh = _pp_mesh(pp=2)
        model = Llama(cfg)
        dense_params = model.init_params(jax.random.PRNGKey(0))
        plm = PipelinedLM(model, mesh, num_microbatches=2)
        plm.init_params(jax.random.PRNGKey(0))
        non_layer, layers, _ = split_layer_params(dict(dense_params))
        pp_from_dense = dict(non_layer, blocks=stack_layer_params(layers))

        idx = jax.random.randint(jax.random.PRNGKey(2), (4, 16), 0,
                                 cfg.vocab_size)
        with mesh:
            got = jax.jit(lambda p: plm.apply({"params": p}, idx))(
                pp_from_dense)
        want = model.apply({"params": dense_params}, idx)
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(want, np.float32), atol=2e-4)


class TestPipelineTraining:
    def test_auto_accelerate_pp_trains(self):
        """pp=2 x fsdp=2 end-to-end: loss decreases over steps."""
        cfg = dataclasses.replace(GPTConfig.nano(), remat=False,
                                  use_flash_attention=False,
                                  dtype=jnp.float32)
        res = auto_accelerate(
            GPT(cfg), optimizer=optax.adam(1e-2),
            strategy=[("pipeline_parallel", {"size": 2, "microbatches": 2}),
                      ("fsdp", {})],
            devices=jax.devices()[:4])
        data = jax.random.randint(jax.random.PRNGKey(0), (8, 33), 0,
                                  cfg.vocab_size)
        batch = res.place_batch({"input_ids": data[:, :-1],
                                 "labels": data[:, 1:]})
        state = res.state
        losses = []
        for _ in range(5):
            state, m = res.train_step(state, batch)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0]
        # stacked block params actually sharded over pp
        blocks_sh = res.state_shardings.params["blocks"]
        leaf = jax.tree.leaves(blocks_sh)[0]
        assert "pp" in str(leaf.spec)

    def test_pp_rejects_indivisible_layers(self):
        cfg = dataclasses.replace(GPTConfig.nano(), remat=False)  # 2 layers
        with pytest.raises(ValueError, match="divisible"):
            auto_accelerate(GPT(cfg),
                            strategy=[("pipeline_parallel", {"size": 3})],
                            devices=jax.devices()[:3])

"""Ray job submitter tests (client/platform/ray/ray_job_submitter.py
parity) — the Jobs API client is injected, ray itself is optional."""

import json

import pytest

from dlrover_wuqiong_tpu.scheduler.ray_job_submitter import (
    RayJobSubmitter,
    load_conf,
    main,
)


class FakeJobsClient:
    def __init__(self, statuses=("PENDING", "RUNNING", "SUCCEEDED")):
        self.submitted = []
        self._statuses = list(statuses)
        self._stopped = False
        self._logs = "step 1\n"

    def submit_job(self, entrypoint, runtime_env):
        self.submitted.append((entrypoint, runtime_env))
        return "raysubmit_test123"

    def get_job_status(self, job_id):
        s = self._statuses[0]
        if len(self._statuses) > 1:
            self._statuses.pop(0)
        self._logs += f"status {s}\n"
        return s

    def get_job_logs(self, job_id):
        return self._logs

    def stop_job(self, job_id):
        self._stopped = True
        return True


def _conf(tmp_path, **over):
    conf = {"dashboardUrl": "127.0.0.1:8265",
            "command": "dwt-run --standalone train.py",
            "workingDir": "/src", "requirements": ["einops"],
            "pollInterval": 0.01}
    conf.update(over)
    p = tmp_path / "job.json"
    p.write_text(json.dumps(conf))
    return str(p)


def test_submit_and_wait_success(tmp_path, capsys):
    client = FakeJobsClient()
    sub = RayJobSubmitter(_conf(tmp_path), client=client)
    job_id = sub.submit()
    assert job_id == "raysubmit_test123"
    entry, env = client.submitted[0]
    assert entry.startswith("dwt-run")
    assert env == {"working_dir": "/src", "pip": ["einops"]}
    status = sub.wait(timeout=10)
    assert status == "SUCCEEDED"
    assert "status RUNNING" in capsys.readouterr().out  # logs streamed

def test_failed_job_status(tmp_path):
    sub = RayJobSubmitter(_conf(tmp_path),
                          client=FakeJobsClient(statuses=("FAILED",)))
    sub.submit()
    assert sub.wait(timeout=10, stream_logs=False) == "FAILED"


def test_stop(tmp_path):
    client = FakeJobsClient(statuses=("RUNNING",))
    sub = RayJobSubmitter(_conf(tmp_path), client=client)
    sub.submit()
    assert sub.stop() is True
    assert client._stopped


def test_conf_validation(tmp_path):
    p = tmp_path / "bad.json"
    p.write_text(json.dumps({"dashboardUrl": "x"}))
    with pytest.raises(ValueError, match="command"):
        RayJobSubmitter(str(p))


def test_yaml_conf(tmp_path):
    p = tmp_path / "job.yaml"
    p.write_text("command: echo hi\nworkingDir: ./\n")
    assert load_conf(str(p))["command"] == "echo hi"


def test_cli_usage():
    assert main([]) == 2

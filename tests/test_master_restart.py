"""Master fault tolerance: retry/backoff, journal replay, fencing epochs.

Mirrors reference tests `dlrover/python/tests/test_master_client.py` (retry
decorator) and `test_servicer.py` style — in-process servers, no cluster —
extended with the fault shapes the reference never covers because its
master state dies with the master: refused / half-open / mid-frame-dropped
connections against RpcClient, idempotent replay of mutating verbs across
a master restart, epoch-bump re-registration, and the journal's
snapshot/compaction + torn-tail handling (master/journal.py).
"""

import json
import os
import signal
import socket
import struct
import subprocess
import sys
import threading
import time

import pytest

from dlrover_wuqiong_tpu.agent.master_client import MasterClient
from dlrover_wuqiong_tpu.common import comm, serialize
from dlrover_wuqiong_tpu.common.messages import (
    HeartBeat,
    HeartbeatResponse,
    NodeMeta,
    OkResponse,
)
from dlrover_wuqiong_tpu.common.util import retry_call
from dlrover_wuqiong_tpu.master.journal import IdemCache, MasterJournal
from dlrover_wuqiong_tpu.master.master import JobMaster


# --------------------------------------------------------------- retry_call


class TestRetryCall:
    def test_returns_value_and_attempt_budget(self):
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise OSError("boom")
            return 42

        assert retry_call(flaky, attempts=3, base_delay_s=0.0,
                          jitter=0.0) == 42
        assert calls["n"] == 3

    def test_exhausted_attempts_reraise(self):
        calls = {"n": 0}

        def always():
            calls["n"] += 1
            raise OSError("down")

        with pytest.raises(OSError):
            retry_call(always, attempts=3, base_delay_s=0.0, jitter=0.0)
        assert calls["n"] == 3

    def test_non_retryable_propagates_immediately(self):
        calls = {"n": 0}

        def wrong():
            calls["n"] += 1
            raise ValueError("logic bug")

        with pytest.raises(ValueError):
            retry_call(wrong, attempts=5, base_delay_s=0.0,
                       retry_on=(OSError,))
        assert calls["n"] == 1

    def test_deadline_bounds_wall_clock(self):
        t0 = time.monotonic()
        with pytest.raises(OSError):
            retry_call(lambda: (_ for _ in ()).throw(OSError()),
                       attempts=None, deadline_s=0.3, base_delay_s=0.05,
                       max_delay_s=0.1, jitter=0.0)
        assert time.monotonic() - t0 < 1.5

    def test_backoff_grows_exponentially_and_on_retry_fires(self):
        delays = []
        with pytest.raises(OSError):
            retry_call(lambda: (_ for _ in ()).throw(OSError()),
                       attempts=4, base_delay_s=0.1, max_delay_s=10.0,
                       jitter=0.0, sleep=lambda s: None,
                       on_retry=lambda n, e, d: delays.append(d))
        assert delays == [0.1, 0.2, 0.4]


# --------------------------------------------------- RpcClient under faults


def _free_port():
    return comm.find_free_port()


class _ScriptedServer:
    """TCP stub whose per-connection behavior is scripted: 'refuse' is
    modeled by not listening at all; 'hang' accepts and never answers;
    'truncate' sends a torn frame; 'serve' answers like a real master."""

    def __init__(self, behaviors, epoch=1):
        self.behaviors = list(behaviors)
        self.epoch = epoch
        self.served = 0
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(8)
        self.port = self._sock.getsockname()[1]
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while True:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            behavior = self.behaviors.pop(0) if self.behaviors else "serve"
            threading.Thread(target=self._handle, args=(conn, behavior),
                             daemon=True).start()

    def _handle(self, conn, behavior):
        with conn:
            try:
                if behavior == "close":
                    return  # half-open: accepted then dropped pre-read
                req = comm._recv_frame(conn)  # noqa: SLF001
                if behavior == "hang":
                    time.sleep(5.0)
                    return
                body = serialize.dumps({"ok": True, "error": "",
                                        "payload": OkResponse(),
                                        "epoch": self.epoch})
                if behavior == "truncate":
                    # length prefix + half the body, then die mid-frame
                    conn.sendall(struct.pack(">I", len(body))
                                 + body[: len(body) // 2])
                    return
                comm._send_frame(conn, body)  # noqa: SLF001
                self.served += 1
                del req
            except OSError:
                return

    def close(self):
        self._sock.close()


class TestRpcClientFaults:
    def test_connection_refused_bounded_retry(self):
        port = _free_port()  # nobody listening
        client = comm.RpcClient(f"127.0.0.1:{port}", retries=3,
                                base_delay_s=0.01)
        t0 = time.monotonic()
        with pytest.raises(comm.MasterUnreachableError):
            client.get(HeartBeat())
        assert time.monotonic() - t0 < 5.0  # bounded, no hang

    def test_half_open_connection_recovers(self):
        """Server accepts then drops the connection twice; third attempt
        is served — the client must reconnect and succeed."""
        srv = _ScriptedServer(["close", "close", "serve"])
        try:
            client = comm.RpcClient(f"127.0.0.1:{srv.port}", retries=5,
                                    base_delay_s=0.01)
            resp = client.get(HeartBeat())
            assert isinstance(resp, OkResponse)
            # the server thread increments after replying — poll briefly
            deadline = time.monotonic() + 2.0
            while time.monotonic() < deadline and srv.served < 1:
                time.sleep(0.01)
            assert srv.served == 1
        finally:
            srv.close()

    def test_mid_frame_drop_recovers(self):
        """A response torn mid-frame (master died while answering) must
        poison the socket and retry on a fresh connection."""
        srv = _ScriptedServer(["truncate", "serve"])
        try:
            client = comm.RpcClient(f"127.0.0.1:{srv.port}", retries=4,
                                    base_delay_s=0.01)
            resp = client.get(HeartBeat())
            assert isinstance(resp, OkResponse)
        finally:
            srv.close()

    def test_rpc_error_never_retried(self):
        calls = {"n": 0}

        def handler(verb, node_id, node_type, payload):
            calls["n"] += 1
            raise ValueError("handler bug")

        server = comm.RpcServer(handler, host="127.0.0.1")
        server.start()
        try:
            client = comm.RpcClient(f"127.0.0.1:{server.port}", retries=5)
            with pytest.raises(comm.RpcError, match="handler bug"):
                client.get(HeartBeat())
            assert calls["n"] == 1  # the master ANSWERED — no blind retry
        finally:
            server.stop()


# ----------------------------------------------------------- fencing epoch


class TestEpochFencing:
    def test_epoch_change_fires_once_per_bump(self):
        epoch_cell = {"e": 1}
        server = comm.RpcServer(lambda *a, **k: OkResponse(),
                                host="127.0.0.1",
                                epoch_provider=lambda: epoch_cell["e"])
        server.start()
        try:
            client = comm.RpcClient(f"127.0.0.1:{server.port}")
            bumps = []
            client.on_epoch_change = lambda old, new: bumps.append((old,
                                                                    new))
            client.get(HeartBeat())
            assert client.epoch == 1 and bumps == []
            epoch_cell["e"] = 2
            client.get(HeartBeat())
            client.get(HeartBeat())
            assert client.epoch == 2
            assert bumps == [(1, 2)]  # once, not per call
        finally:
            server.stop()

    def test_master_client_reregisters_and_resyncs_on_bump(self):
        """An epoch bump must replay the node registration and recent task
        results (same idem keys) against the new master."""
        epoch_cell = {"e": 1}
        seen = {"meta": 0, "results": []}

        def handler(verb, node_id, node_type, payload, idem=None):
            if isinstance(payload, NodeMeta):
                seen["meta"] += 1
            from dlrover_wuqiong_tpu.common.messages import TaskResult
            if isinstance(payload, TaskResult):
                seen["results"].append((payload.task_id, idem))
            if isinstance(payload, HeartBeat):
                return HeartbeatResponse()
            return OkResponse()

        server = comm.RpcServer(handler, host="127.0.0.1",
                                epoch_provider=lambda: epoch_cell["e"])
        server.start()
        try:
            mc = MasterClient(f"127.0.0.1:{server.port}", node_id=0)
            mc.register_node(node_rank=0)
            mc.report_task_result("ds", 7)
            assert seen["meta"] == 1 and len(seen["results"]) == 1
            epoch_cell["e"] = 2  # "the master restarted"
            mc.report_heart_beat()
            assert seen["meta"] == 2  # re-registered
            # the result re-sync reused the ORIGINAL idem key
            assert len(seen["results"]) == 2
            assert seen["results"][0] == seen["results"][1]
            assert mc.degraded_stats()["reregistrations"] == 1
        finally:
            server.stop()


# ------------------------------------------------------------ degraded mode


class TestDegradedMode:
    def test_heartbeats_buffer_through_outage_and_drain(self):
        """Fire-and-forget verbs must not block or raise on a dead master;
        the buffered frames drain after it returns."""
        received = []

        def handler(verb, node_id, node_type, payload, idem=None):
            received.append(type(payload).__name__)
            return OkResponse()

        port = _free_port()
        mc = MasterClient(f"127.0.0.1:{port}", node_id=0)
        t0 = time.monotonic()
        for step in range(3):
            resp = mc.report_heart_beat_full(step)  # master is DOWN
            assert isinstance(resp, HeartbeatResponse)
        assert time.monotonic() - t0 < 5.0  # never blocked on the outage
        stats = mc.degraded_stats()
        assert stats["buffered_total"] == 3 and stats["pending"] == 3
        # master comes up on the SAME port
        server = comm.RpcServer(handler, host="127.0.0.1", port=port,
                                epoch_provider=lambda: 1)
        server.start()
        try:
            mc.report_heart_beat_full(99)  # success → buffer drains
            stats = mc.degraded_stats()
            assert stats["pending"] == 0
            assert stats["flushed_total"] == 3
            assert len(received) == 4
        finally:
            server.stop()

    def test_buffer_is_bounded(self):
        port = _free_port()
        mc = MasterClient(f"127.0.0.1:{port}", node_id=0)
        mc.BUFFER_CAP = 5
        for step in range(8):
            mc.report_heart_beat_full(step)
        stats = mc.degraded_stats()
        assert stats["pending"] == 5
        assert stats["dropped_total"] == 3

    def test_kv_store_wait_timeout_carries_epoch(self):
        server = comm.RpcServer(
            lambda *a, **k: __import__(
                "dlrover_wuqiong_tpu.common.messages",
                fromlist=["KVStoreResponse"]).KVStoreResponse(found=False),
            host="127.0.0.1", epoch_provider=lambda: 3)
        server.start()
        try:
            mc = MasterClient(f"127.0.0.1:{server.port}", node_id=0)
            t0 = time.monotonic()
            with pytest.raises(TimeoutError, match="master epoch=3"):
                mc.kv_store_wait(["never"], timeout=0.6, poll=0.05)
            assert time.monotonic() - t0 < 5.0
        finally:
            server.stop()


# ------------------------------------------------------------- the journal


class TestJournal:
    def test_append_load_roundtrip(self, tmp_path):
        j = MasterJournal(str(tmp_path))
        j.load()
        j.open_epoch()
        j.append("kv_set", {"key": "a", "value": b"\x00\x01"})
        j.append("kv_add", {"key": "c", "amount": 2})
        j.close()
        j2 = MasterJournal(str(tmp_path))
        snapshot, entries = j2.load()
        assert snapshot is None
        assert [e["kind"] for e in entries] == ["kv_set", "kv_add"]
        assert entries[0]["data"]["value"] == b"\x00\x01"
        assert j2.epoch == 1
        assert j2.open_epoch() == 2

    def test_torn_tail_dropped(self, tmp_path):
        j = MasterJournal(str(tmp_path))
        j.load()
        j.append("kv_add", {"key": "a", "amount": 1})
        j.append("kv_add", {"key": "a", "amount": 1})
        j.close()
        # master SIGKILLed mid-append: torn trailing frame
        with open(os.path.join(str(tmp_path), "journal.frames"), "ab") as f:
            f.write(b'{"seq": 99, "kind": "kv_a')
        j2 = MasterJournal(str(tmp_path))
        _, entries = j2.load()
        assert len(entries) == 2  # torn frame dropped, intact ones kept

    def test_snapshot_compacts_and_seq_skips_replayed_prefix(self, tmp_path):
        j = MasterJournal(str(tmp_path))
        j.load()
        j.open_epoch()
        for i in range(5):
            j.append("kv_add", {"key": "a", "amount": 1})
        j.snapshot({"kv": {"a": b"5"}})
        j.append("kv_add", {"key": "a", "amount": 1})
        j.close()
        j2 = MasterJournal(str(tmp_path))
        snapshot, entries = j2.load()
        assert snapshot == {"kv": {"a": b"5"}}
        # only the post-snapshot event replays — the 5 compacted adds are
        # inside the snapshot (no double-apply)
        assert len(entries) == 1

    def test_idem_cache_bounded_lru(self):
        c = IdemCache(cap=3)
        for i in range(5):
            c.put(f"k{i}", i)
        assert len(c) == 3
        assert c.get("k0") is c.MISS
        assert c.get("k4") == 4


class TestGroupCommit:
    """Batched journal fsync (master/journal.py group commit)."""

    def test_batch_coalesces_queued_frames_one_commit(self, tmp_path):
        # deterministic coalescing: enqueue K frames, then gate on the
        # last — the leader must take the whole queue in ONE batch
        j = MasterJournal(str(tmp_path))
        j.load()
        seqs = [j.append_nowait("kv_add", {"key": "a", "amount": i})
                for i in range(7)]
        assert j.wait_durable(seqs[-1]) == seqs[-1]
        st = j.group_commit_stats()
        assert st["batches"] == 1 and st["frames"] == 7
        assert st["batch_max"] == 7
        assert st["durable_seq"] == seqs[-1]
        j.close()
        # durable before wait_durable returned: a FRESH journal sees all
        _, entries = MasterJournal(str(tmp_path)).load()
        assert [e["data"]["amount"] for e in entries] == list(range(7))

    def test_concurrent_appends_all_durable_and_ordered(self, tmp_path):
        j = MasterJournal(str(tmp_path))
        j.load()
        n_threads, per = 8, 25
        done = []

        def writer(t):
            for i in range(per):
                done.append(j.append("kv_add",
                                     {"key": f"t{t}", "amount": i}))

        ts = [threading.Thread(target=writer, args=(t,))
              for t in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        j.close()
        _, entries = MasterJournal(str(tmp_path)).load()
        got = [e["seq"] for e in entries]
        # every acked frame is on disk, in strict seq (= file) order
        assert got == sorted(done)
        assert len(got) == n_threads * per

    def test_append_races_compaction_no_frame_lost(self, tmp_path):
        # regression: compaction swaps the log file while appenders are
        # in flight — the fence must drain the queue durably first, so
        # a seq-assigned frame can never vanish with the truncated file
        j = MasterJournal(str(tmp_path), snapshot_every=1_000_000)
        j.load()
        appended = []
        stop = threading.Event()

        def writer(t):
            i = 0
            while not stop.is_set():
                appended.append(
                    j.append("kv_add", {"key": f"t{t}", "amount": i}))
                i += 1

        ts = [threading.Thread(target=writer, args=(t,)) for t in range(4)]
        for t in ts:
            t.start()
        snap_seqs = []
        for k in range(6):
            time.sleep(0.02)
            j.snapshot({"round": k})
            snap_seqs.append(j._seq)
        stop.set()
        for t in ts:
            t.join()
        j.close()
        snapshot, entries = MasterJournal(str(tmp_path)).load()
        assert snapshot == {"round": 5}
        covered = snap_seqs[-1]
        live = {e["seq"] for e in entries}
        # every acked append is either inside the snapshot's coverage or
        # still replayable — none fell between the cracks
        assert all(s <= covered or s in live for s in appended)
        seq_order = [e["seq"] for e in entries]
        assert seq_order == sorted(seq_order)

    def test_torn_batch_tail_drops_whole_frames_only(self, tmp_path):
        # SIGKILL mid-batch-write: the tail frame tears, frames earlier
        # in the SAME batch survive intact (one write, but the loader
        # works line by line)
        j = MasterJournal(str(tmp_path))
        j.load()
        for i in range(5):
            j.append_nowait("kv_add", {"key": "a", "amount": i})
        j.wait_durable(5)
        j.close()
        path = os.path.join(str(tmp_path), "journal.frames")
        with open(path, "rb") as f:
            raw = f.read()
        with open(path, "wb") as f:
            f.write(raw[:-9])  # shear the last frame mid-JSON
        _, entries = MasterJournal(str(tmp_path)).load()
        assert [e["data"]["amount"] for e in entries] == [0, 1, 2, 3]

    def test_disabled_mode_is_per_frame_fsync(self, tmp_path, monkeypatch):
        monkeypatch.setenv("DWT_JOURNAL_GROUP_COMMIT", "0")
        j = MasterJournal(str(tmp_path))
        assert j.group_commit_max_frames == 1
        j.load()
        for i in range(3):
            j.append_nowait("kv_add", {"key": "a", "amount": i})
        j.wait_durable(3)
        st = j.group_commit_stats()
        # per-frame baseline: every frame is its own batch/fsync
        assert st["batches"] == 3 and st["batch_max"] == 1
        assert st["group_commit"] is False
        j.close()

    def test_knob_defaults_and_env_overrides(self, tmp_path, monkeypatch):
        j = MasterJournal(str(tmp_path / "a"))
        assert j.group_commit_max_frames == 256
        assert j.group_commit_max_wait_ms == 0.0
        assert j.fsync_floor_ms == 0.0
        monkeypatch.setenv("DWT_JOURNAL_GROUP_MAX_FRAMES", "32")
        monkeypatch.setenv("DWT_JOURNAL_GROUP_MAX_WAIT_MS", "2")
        monkeypatch.setenv("DWT_JOURNAL_FSYNC_FLOOR_MS", "1")
        j = MasterJournal(str(tmp_path / "b"))
        assert j.group_commit_max_frames == 32
        assert j.group_commit_max_wait_ms == 2.0
        assert j.fsync_floor_ms == 1.0
        # explicit constructor args beat the env
        j = MasterJournal(str(tmp_path / "c"), group_commit_max_frames=4,
                          group_commit_max_wait_ms=0)
        assert j.group_commit_max_frames == 4
        assert j.group_commit_max_wait_ms == 0.0
        # a non-integer env value is ignored, not fatal
        monkeypatch.setenv("DWT_JOURNAL_GROUP_MAX_FRAMES", "lots")
        assert MasterJournal(
            str(tmp_path / "d")).group_commit_max_frames == 256

    def test_leader_linger_extends_batch(self, tmp_path):
        # max_wait_ms > 0: the leader waits one window for followers, so
        # a frame enqueued DURING the linger joins the in-flight batch
        j = MasterJournal(str(tmp_path), group_commit_max_wait_ms=100.0)
        j.load()
        s1 = j.append_nowait("kv_add", {"key": "a", "amount": 1})

        def late_follower():
            time.sleep(0.02)
            j.append("kv_add", {"key": "a", "amount": 2})

        t = threading.Thread(target=late_follower)
        t.start()
        j.wait_durable(s1)
        t.join()
        st = j.group_commit_stats()
        assert st["frames"] == 2
        assert st["batch_max"] == 2  # the linger caught the follower
        j.close()


# ----------------------------------------- in-process master restart replay


def _client_for(master, node_id=0):
    return MasterClient(f"127.0.0.1:{master.port}", node_id=node_id)


class TestMasterRestartReplay:
    def test_state_survives_crash_and_epoch_bumps(self, tmp_path):
        jd = str(tmp_path / "journal")
        m1 = JobMaster(port=0, journal_dir=jd)
        m1.prepare()
        mc = _client_for(m1)
        mc.report_dataset_shard_params(
            batch_size=4, dataset_size=64, dataset_name="ds",
            num_minibatches_per_shard=2)
        t1 = mc.get_task("ds")
        t2 = mc.get_task("ds")
        mc.report_task_result("ds", t1.task_id)
        mc.kv_store_set("boot", b"coord")
        assert mc.kv_store_add("counter", 2) == 2
        mc.join_rendezvous(node_rank=0, local_world_size=1)
        world = mc.get_comm_world()
        assert world.complete
        assert mc.epoch == 1
        # crash: drop the master with NO clean stop (no final snapshot)
        m1._server.stop()  # noqa: SLF001

        m2 = JobMaster(port=0, journal_dir=jd)
        m2.prepare()
        try:
            assert m2.epoch == 2
            mc2 = _client_for(m2)
            # kv + rendezvous world replayed
            assert mc2.kv_store_get("boot") == b"coord"
            assert mc2.kv_store_add("counter", 1) == 3  # cursor exact
            world2 = mc2.get_comm_world()
            assert world2.complete
            assert world2.rdzv_round == world.rdzv_round  # same world, no
            # re-rendezvous forced by a master-only failure
            # dispatch state replayed: t2 still in-flight, next task fresh
            t3 = mc2.get_task("ds")
            assert t3.task_id not in (t1.task_id, t2.task_id)
            mgr = m2.task_manager._datasets["ds"]  # noqa: SLF001
            assert t2.task_id in mgr.doing
            assert t1.task_id not in mgr.doing  # done stayed done
        finally:
            m2.stop()

    def test_idempotent_replay_of_mutating_verbs(self, tmp_path):
        """A mutating verb acked by master #1 and RETRIED (same idem key)
        against replayed master #2 must not re-apply."""
        jd = str(tmp_path / "journal")
        m1 = JobMaster(port=0, journal_dir=jd)
        m1.prepare()
        mc = _client_for(m1)
        from dlrover_wuqiong_tpu.common.messages import KVStoreAddRequest

        idem = "node0:test:1"
        resp = mc._client.get(  # noqa: SLF001 — fixed idem on purpose
            KVStoreAddRequest(key="ct", amount=5), idem=idem)
        assert resp.num == 5
        m1._server.stop()  # noqa: SLF001

        m2 = JobMaster(port=0, journal_dir=jd)
        m2.prepare()
        try:
            mc2 = _client_for(m2)
            # the retry crossing the restart: journaled response replayed,
            # counter NOT drifted
            replay = mc2._client.get(  # noqa: SLF001
                KVStoreAddRequest(key="ct", amount=5), idem=idem)
            assert replay.num == 5
            fresh = mc2.kv_store_add("ct", 1)
            assert fresh == 6  # 5 (+1), not 10 (+1)
        finally:
            m2.stop()

    def test_clean_stop_snapshot_then_restart(self, tmp_path):
        jd = str(tmp_path / "journal")
        m1 = JobMaster(port=0, journal_dir=jd)
        m1.prepare()
        mc = _client_for(m1)
        mc.kv_store_set("k", b"v")
        m1.stop()  # clean: compacts into one snapshot frame
        m2 = JobMaster(port=0, journal_dir=jd)
        m2.prepare()
        try:
            assert m2.epoch == 2
            assert _client_for(m2).kv_store_get("k") == b"v"
        finally:
            m2.stop()


# ------------------------------------- subprocess master SIGKILL (tier-1)


_MASTER_PROC_SRC = """
import sys
from dlrover_wuqiong_tpu.master.master import run_master_forever
run_master_forever(int(sys.argv[1]), 1, 1, journal_dir=sys.argv[2],
                   poll_interval=0.2)
"""


class TestSubprocessMasterRestart:
    def test_sigkill_master_restart_on_same_journal(self, tmp_path):
        """The fast in-tier-1 shape of the chaos master-kill drill: a real
        master PROCESS (launched through the subprocess scheduler) is
        SIGKILLed mid-stream and a successor on the same journal serves
        the replayed state at a bumped epoch."""
        from dlrover_wuqiong_tpu.scheduler.base import NodeSpec
        from dlrover_wuqiong_tpu.scheduler.subprocess_scheduler import (
            SubprocessSchedulerClient,
        )

        jd = str(tmp_path / "journal")
        script = str(tmp_path / "master_main.py")
        with open(script, "w") as f:
            f.write(_MASTER_PROC_SRC)
        port = comm.find_free_port()
        sched = SubprocessSchedulerClient(log_dir=str(tmp_path / "logs"))

        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(comm.__file__)))
        pkg_root = os.path.dirname(repo_root)

        def spawn(node_id):
            spec = NodeSpec(node_type="master", node_id=node_id,
                            command=[sys.executable, script, str(port), jd])
            spec.env["JAX_PLATFORMS"] = "cpu"
            # the script lives in tmp_path — the package root must be on
            # the child's path explicitly
            spec.env["PYTHONPATH"] = pkg_root + os.pathsep + \
                os.environ.get("PYTHONPATH", "")
            assert sched.create_node(spec)

        try:
            spawn(0)
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and \
                    not comm.addr_connectable(f"127.0.0.1:{port}"):
                time.sleep(0.1)
            mc = MasterClient(f"127.0.0.1:{port}", node_id=0,
                              outage_grace_s=60.0)
            mc.report_dataset_shard_params(
                batch_size=2, dataset_size=16, dataset_name="ds",
                num_minibatches_per_shard=2)
            t1 = mc.get_task("ds")
            mc.kv_store_set("k", b"v")
            assert mc.epoch == 1
            # SIGKILL — no snapshot, no goodbye
            proc = sched._procs[("master", 0)]  # noqa: SLF001
            os.killpg(proc.pid, signal.SIGKILL)
            proc.wait(timeout=10)
            spawn(1)  # successor on the same journal + port
            # the client's own retry rides through the restart window
            assert mc.kv_store_get("k") == b"v"
            assert mc.epoch == 2
            t2 = mc.get_task("ds")
            assert t2.task_id != t1.task_id  # t1 still in-flight, not
            # re-dispatched: journal replay was exact
            assert mc.degraded_stats()["reregistrations"] >= 0
        finally:
            sched.close()


# ------------------------------- goodput snapshots across a master restart


class TestGoodputAcrossRestart:
    def test_buffered_drain_latest_sent_per_node_wins(self, tmp_path):
        """Snapshots buffered through an outage drain AFTER the frame
        that reconnected to the new master — the master must keep the
        newest-SENT cumulative snapshot per node, not whichever frame
        happened to arrive last."""
        jd = str(tmp_path / "journal")
        port = comm.find_free_port()
        m1 = JobMaster(port=port, journal_dir=jd)
        m1.prepare()
        mc = MasterClient(f"127.0.0.1:{port}", node_id=0)

        def snap(wall):
            return {"wall_s": wall, "states": {"productive": wall * 0.8},
                    "other_s": 0.0, "goodput_fraction": 0.8}

        mc.report_goodput_ledger(snap(10.0))
        assert m1.goodput_summary().wall_s == 10.0
        m1._server.stop()  # noqa: SLF001 — crash, no snapshot
        mc._client.close()  # noqa: SLF001 — the kill severs the socket too
        # outage: two newer cumulative snapshots park in the buffer
        mc.report_goodput_ledger(snap(20.0))
        mc.report_goodput_ledger(snap(30.0))
        assert mc.degraded_stats()["pending"] == 2
        m2 = JobMaster(port=port, journal_dir=jd)
        m2.prepare()
        try:
            assert m2.epoch == 2  # the restart was a real fencing bump
            # reconnect frame lands first, THEN the buffer drains (the
            # older frames arrive after the newer one)
            mc.report_goodput_ledger(snap(40.0))
            assert mc.degraded_stats()["pending"] == 0
            s = m2.goodput_summary()
            assert s.nodes == 1
            assert s.wall_s == 40.0
            assert s.states["productive"] == 40.0 * 0.8
        finally:
            m2.stop()

    def test_unstamped_report_still_lands(self):
        """Back-compat: a report without sent_at (old sender) must apply
        — only a PROVABLY older stamp loses."""
        from dlrover_wuqiong_tpu.common import messages as msg

        m = JobMaster(port=0)
        m.collect_goodput(msg.GoodputLedgerReport(
            node_id=1, wall_s=5.0, sent_at=100.0))
        m.collect_goodput(msg.GoodputLedgerReport(node_id=1, wall_s=7.0))
        assert m.goodput_summary().wall_s == 7.0


# ------------------------------ buffered verbs across a standby FAILOVER


class TestAcrossFailover:
    """ISSUE 20: the degraded-mode buffer and idem keys must behave across
    a PROMOTION exactly as they do across a same-journal restart — the
    promoted standby replayed the shipped journal, so original idem keys
    hit its replayed cache and buffered snapshots still resolve
    latest-SENT-wins."""

    def _pair(self, tmp_path, ttl=0.5):
        from dlrover_wuqiong_tpu.master.standby import StandbyTailer

        jd1 = str(tmp_path / "j1")
        jd2 = str(tmp_path / "j2")
        m1 = JobMaster(port=0, journal_dir=jd1, lease_ttl_s=ttl)
        m1.prepare()
        m1.start_lease_heartbeat()
        m2 = JobMaster(port=0, journal_dir=jd2, standby=True,
                       lease_ttl_s=ttl)
        m2.prepare()
        tailer = StandbyTailer(m2, f"127.0.0.1:{m1.port}",
                               lease_ttl_s=ttl, poll_interval_s=0.05)
        return m1, m2, tailer

    def _mirror_until_leased(self, m1, m2, tailer):
        # catch the mirror up AND arm the lease clock: promotion is
        # gated on a lease frame having been ADOPTED (a no-lease
        # primary makes the standby a pure mirror on purpose)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            tailer.poll_once()
            if tailer._last_lease_mono and \
                    m2.journal_stats().durable_seq >= \
                    m1.journal_stats().durable_seq:  # noqa: SLF001
                return
            time.sleep(0.02)
        raise AssertionError("mirror never caught up / lease never armed")

    def _kill_and_promote(self, m1, mc, tailer):
        # hard-kill the primary: stop the server AND sever the client's
        # persistent connection (a real SIGKILL resets the TCP stream;
        # the in-process server.stop() leaves accepted conns alive)
        m1._stopped.set()  # noqa: SLF001
        m1._server.stop()  # noqa: SLF001
        m1.is_leader = False
        mc._client.close()  # noqa: SLF001
        assert tailer.run(threading.Event(), max_seconds=30)

    def test_idem_retry_exactly_once_across_promotion(self, tmp_path):
        from dlrover_wuqiong_tpu.common.messages import KVStoreAddRequest

        m1, m2, tailer = self._pair(tmp_path)
        try:
            mc = MasterClient(
                f"127.0.0.1:{m1.port},127.0.0.1:{m2.port}", node_id=0)
            idem = "node0:failover:1"
            r1 = mc._client.get(  # noqa: SLF001 — fixed idem on purpose
                KVStoreAddRequest(key="ct", amount=5), idem=idem)
            assert r1.num == 5
            self._mirror_until_leased(m1, m2, tailer)
            old_epoch = m1.epoch
            self._kill_and_promote(m1, mc, tailer)
            assert m2.is_leader
            assert m2.epoch == old_epoch + 2  # fenced above corpse+1
            # one client-API verb dials over to the new leader (raw
            # RpcClient calls below deliberately skip that machinery)
            mc.kv_store_set("dial", b"over")
            # the retry under the ORIGINAL key crosses the failover:
            # journaled response replayed on the standby, no re-apply
            replay = mc._client.get(  # noqa: SLF001
                KVStoreAddRequest(key="ct", amount=5), idem=idem)
            assert replay.num == 5
            assert mc.kv_store_add("ct", 1) == 6  # 5+1, never 10+1
            assert mc.degraded_stats()["failovers"] >= 1
            mc.close()
        finally:
            tailer.close()
            m2.stop()

    def test_buffered_drain_latest_sent_wins_across_promotion(
            self, tmp_path):
        m1, m2, tailer = self._pair(tmp_path)
        try:
            mc = MasterClient(
                f"127.0.0.1:{m1.port},127.0.0.1:{m2.port}", node_id=0)

            def snap(wall):
                return {"wall_s": wall,
                        "states": {"productive": wall * 0.8},
                        "other_s": 0.0, "goodput_fraction": 0.8}

            mc.report_goodput_ledger(snap(10.0))
            self._mirror_until_leased(m1, m2, tailer)
            # kill the primary but do NOT promote yet: the leadership
            # gap is where buffered verbs park (primary unreachable,
            # standby still refusing mutations with NotLeaderError)
            m1._stopped.set()  # noqa: SLF001
            m1._server.stop()  # noqa: SLF001
            m1.is_leader = False
            mc._client.close()  # noqa: SLF001
            mc.report_goodput_ledger(snap(20.0))
            mc.report_goodput_ledger(snap(30.0))
            assert mc.degraded_stats()["pending"] == 2
            assert tailer.run(threading.Event(), max_seconds=30)
            assert m2.is_leader
            # buffered verbs never block on dialing: the first beat
            # after promotion parks its frame too and ROTATES the
            # endpoint (pending 2 -> 3) ...
            mc.report_goodput_ledger(snap(40.0))
            assert mc.degraded_stats()["pending"] == 3
            # ... so the next beat lands inline on the new leader
            # FIRST and the older buffered frames drain BEHIND it —
            # exactly the arrival-order hazard latest-SENT-wins absorbs
            mc.report_goodput_ledger(snap(50.0))
            assert mc.degraded_stats()["pending"] == 0
            s = m2.goodput_summary()
            assert s.nodes == 1
            assert s.wall_s == 50.0  # latest-SENT cumulative wins
            assert mc.degraded_stats()["failovers"] >= 1
            mc.close()
        finally:
            tailer.close()
            m2.stop()


# --------------------------------- policy decisions across a master restart


class TestPolicyAcrossRestart:
    def test_decision_log_replays_from_journal_alone(self, tmp_path):
        """brain/policy.py durability contract: every decision is
        journaled before it becomes visible, so a successor master — even
        one started WITHOUT a policy engine — serves the identical
        history after replay."""
        from dlrover_wuqiong_tpu.brain.policy import (
            PolicyConfig,
            PolicyEngine,
        )

        jd = str(tmp_path / "journal")
        m1 = JobMaster(port=0, journal_dir=jd,
                       policy_engine=PolicyEngine(PolicyConfig(tau_s=30.0)))
        m1.prepare()
        m1._policy_tick()  # noqa: SLF001 — quiet-regime decision #1
        mc = _client_for(m1)
        d1 = mc.get_policy_decision()
        assert d1.decision_id == 1
        assert d1.fused_steps == 4  # quiet: full ladder
        # failure burst → the regime shifts, decision #2 fires
        for _ in range(4):
            m1.note_policy_failure(0)
        m1._policy_tick()  # noqa: SLF001
        hist1 = mc.get_policy_history()
        assert [h["decision_id"] for h in hist1] == [1, 2]
        assert hist1[1]["fused_steps"] == 1
        assert hist1[1]["replica_count"] == 2
        assert hist1[1]["ckpt_interval_steps"] < \
            hist1[0]["ckpt_interval_steps"]
        m1._server.stop()  # noqa: SLF001 — crash, no snapshot

        m2 = JobMaster(port=0, journal_dir=jd)  # replay-only successor
        m2.prepare()
        try:
            mc2 = _client_for(m2)
            hist2 = mc2.get_policy_history()
            assert [h["decision_id"] for h in hist2] == [1, 2]
            assert hist2 == hist1  # byte-identical decisions, not just ids
            assert mc2.get_policy_decision().decision_id == 2
        finally:
            m2.stop()

    def test_reported_decision_idempotent_across_restart(self, tmp_path):
        """An externally reported decision acked by master #1 and RETRIED
        (same idem key) against replayed master #2 must replay the ack,
        not admit a duplicate decision."""
        from dlrover_wuqiong_tpu.common.messages import (
            PolicyDecision,
            PolicyDecisionReport,
        )

        jd = str(tmp_path / "journal")
        m1 = JobMaster(port=0, journal_dir=jd)
        m1.prepare()
        mc = _client_for(m1)
        idem = "node0:policy:1"
        report = PolicyDecisionReport(
            node_id=0, decision=PolicyDecision(ckpt_interval_steps=40,
                                               fused_steps=1,
                                               recovery_route="warm"))
        ack = mc._client._call("report", report, idem=idem)  # noqa: SLF001
        assert ack.applied and ack.decision_id == 1
        m1._server.stop()  # noqa: SLF001

        m2 = JobMaster(port=0, journal_dir=jd)
        m2.prepare()
        try:
            mc2 = _client_for(m2)
            replay = mc2._client._call(  # noqa: SLF001
                "report", report, idem=idem)
            assert replay.decision_id == 1  # the journaled ack, replayed
            hist = mc2.get_policy_history()
            assert [h["decision_id"] for h in hist] == [1]  # no duplicate
            # a FRESH decision still advances the sequence
            ack2 = mc2.report_policy_decision(
                PolicyDecision(ckpt_interval_steps=80))
            assert ack2.decision_id == 2
        finally:
            m2.stop()

"""End-to-end elasticity: CLI → master → agent → worker crash → restart →
resume from flash checkpoint.

Mirrors the reference's chaos experiments (docs/tech_report/
fault_tolerance_exps.md) at unit scale: injected worker failure, loss of no
committed state, training completes after automatic restart.
"""

import json
import os
import subprocess
import sys

import pytest

from version_gates import requires_multiprocess_cpu

WORKER_SCRIPT = r"""
import os, sys, time
import numpy as np

from dlrover_wuqiong_tpu.trainer.elastic import init_elastic
from dlrover_wuqiong_tpu.checkpoint.checkpointer import (
    FlashCheckpointer, StorageType)

ckpt_dir = sys.argv[1]
marker_dir = sys.argv[2]

ctx = init_elastic()
restart = ctx.world.restart_count
ckpt = FlashCheckpointer(ckpt_dir, job_name=os.environ["DWT_JOB_NAME"])

template = {"w": np.zeros((4, 4), np.float32), "step": np.zeros((), np.int64)}
state = ckpt.load_checkpoint(template)
start_step = int(state["step"]) + 1 if state is not None else 0

with open(os.path.join(marker_dir, f"start_r{restart}.json"), "w") as f:
    f.write(str(start_step))

for step in range(start_step, 21):
    w = np.full((4, 4), float(step), np.float32)
    ckpt.save_checkpoint(step, {"w": w, "step": np.int64(step)},
                         storage_type=StorageType.DISK)
    ctx.report_step(step)
    time.sleep(0.02)
    if step == 12 and restart == 0:
        ckpt.wait_latest_checkpoint(30)
        os._exit(17)  # injected fault

ok = ckpt.wait_latest_checkpoint(60)
with open(os.path.join(marker_dir, "done.txt"), "w") as f:
    f.write(f"{ok} {step}")
"""


def test_crash_restart_resume(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER_SCRIPT)
    ckpt_dir = tmp_path / "ckpt"
    marker_dir = tmp_path / "markers"
    marker_dir.mkdir()

    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "DWT_JOB_NAME": "e2e1",
        "DWT_SOCKET_DIR": str(tmp_path / "sockets"),
        "DWT_CTX_NODE_HEARTBEAT_TIMEOUT": "600",
    })
    proc = subprocess.run(
        [sys.executable, "-m", "dlrover_wuqiong_tpu.run", "--standalone",
         "--nproc_per_node=1", "--max_restarts=2",
         str(script), str(ckpt_dir), str(marker_dir)],
        env=env, capture_output=True, text=True, timeout=150,
        cwd="/root/repo")

    assert proc.returncode == 0, (proc.stdout[-3000:], proc.stderr[-3000:])
    done = (marker_dir / "done.txt").read_text()
    assert done.startswith("True 20"), done
    # restart happened and resumed from >= the crash checkpoint
    start_r1 = int((marker_dir / "start_r1.txt").read_text()) \
        if (marker_dir / "start_r1.txt").exists() else None
    r1 = (marker_dir / "start_r1.json")
    assert r1.exists(), "worker was not restarted"
    resumed_from = int(r1.read_text())
    assert resumed_from >= 12, f"resumed too early: {resumed_from}"
    # committed tracker shows the final step
    tracker = ckpt_dir / "latest_checkpointed_iteration.txt"
    assert tracker.read_text().strip() == "20"


JAX_WORKER = r"""
import json, os, sys, time
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")

ckpt_dir, marker_dir, mode = sys.argv[1], sys.argv[2], sys.argv[3]

from dlrover_wuqiong_tpu.trainer.elastic import init_elastic
ctx = init_elastic()
restart = ctx.world.restart_count
pid = ctx.world.process_id
nprocs = ctx.world.num_processes

import dataclasses
import jax.numpy as jnp
import optax
from dlrover_wuqiong_tpu.auto.accelerate import auto_accelerate
from dlrover_wuqiong_tpu.models.gpt import GPT, GPTConfig
from dlrover_wuqiong_tpu.checkpoint.checkpointer import (
    FlashCheckpointer, StorageType)

cfg = dataclasses.replace(GPTConfig.nano(), dtype=jnp.float32,
                          use_flash_attention=False, remat=False)
res = auto_accelerate(GPT(cfg), optimizer=optax.adam(1e-2),
                      strategy=[("fsdp", {})], devices=jax.devices())
ck = FlashCheckpointer(ckpt_dir, job_name=os.environ["DWT_JOB_NAME"])

state = res.state
start = 0
restored = ck.load_checkpoint(res.state)
if restored is not None:
    state = restored
    start = int(np.asarray(state.step))

data = np.random.default_rng(0).integers(0, cfg.vocab_size, (8, 33))
batch = res.place_batch({"input_ids": jnp.asarray(data[:, :-1]),
                         "labels": jnp.asarray(data[:, 1:])})

marker = os.path.join(
    marker_dir,
    f"start_r{restart}_p{pid}_n{os.getenv('DWT_NODE_ID', 'x')}.json")
with open(marker, "w") as f:
    json.dump({"start": start, "nprocs": nprocs,
               "devices": len(jax.devices()),
               "node": int(os.getenv("DWT_NODE_ID", "-1")),
               "restart": restart, "ospid": os.getpid()}, f)

TOTAL = 30 if mode == "slice" else 8
loss_log = os.path.join(marker_dir, f"losses_r{restart}_p{pid}.jsonl")
for _ in range(start, TOTAL):
    state, m = res.train_step(state, batch)
    step = int(np.asarray(state.step))
    with open(loss_log, "a") as f:
        f.write(json.dumps([step, float(m["loss"])]) + "\n")
    ck.save_checkpoint(step, state, storage_type=StorageType.DISK)
    ck.wait_latest_checkpoint(60)
    ctx.report_step(step, force=True)
    if mode == "slice":
        time.sleep(0.2)  # widen the externally-injected kill window
    if mode == "crash" and restart == 0 and pid == 0 and step == 3:
        os._exit(17)  # injected fault AFTER step-3 commit

if pid == 0:
    with open(os.path.join(marker_dir, "done.txt"), "w") as f:
        f.write(str(int(np.asarray(state.step))))
ck.close()
"""


def _free_port():
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_master(port, min_nodes, max_nodes, env):
    return subprocess.Popen(
        [sys.executable, "-c",
         "from dlrover_wuqiong_tpu.master.master import run_master_forever;"
         f"run_master_forever({port}, {min_nodes}, {max_nodes})"],
        env=env, cwd="/root/repo",
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


def _spawn_agent(node_id, script, args, master_port, env, nnodes="2"):
    aenv = dict(env)
    aenv.update({
        "DWT_MASTER_ADDR": f"127.0.0.1:{master_port}",
        "DWT_NODE_ID": str(node_id),
        "DWT_NODE_RANK": str(node_id),
        "DWT_JOB_NAME": f"{env['DWT_JOB_NAME']}-n{node_id}",
    })
    return subprocess.Popen(
        [sys.executable, "-m", "dlrover_wuqiong_tpu.run",
         f"--nnodes={nnodes}", "--nproc_per_node=2", "--max_restarts=3",
         str(script)] + [str(a) for a in args],
        env=aenv, cwd="/root/repo",
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)


def _base_env(tmp_path, job):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "DWT_JOB_NAME": job,
        "DWT_SOCKET_DIR": str(tmp_path / "sockets"),
        "DWT_CTX_NODE_HEARTBEAT_TIMEOUT": "600",
        "DWT_RESTART_DEBOUNCE_SECS": "2",
    })
    return env


@requires_multiprocess_cpu
def test_jax_world_crash_restart_resume(tmp_path):
    """Real-mesh elasticity: 2 hosts x 2 virtual devices, fsdp=4 sharded
    TrainState; rank-0 worker crashes after the step-3 commit; both agents
    re-rendezvous, jax.distributed re-forms, sharded state restores, loss
    continues to step 8."""
    script = tmp_path / "worker.py"
    script.write_text(JAX_WORKER)
    ckpt_dir = tmp_path / "ckpt"
    markers = tmp_path / "markers"
    markers.mkdir()
    env = _base_env(tmp_path, "jx1")
    port = _free_port()
    master = _spawn_master(port, 2, 2, env)
    agents = []
    try:
        import time as _t
        _t.sleep(2.0)
        agents = [_spawn_agent(i, script, [ckpt_dir, markers, "crash"],
                               port, env) for i in range(2)]
        for a in agents:
            out, _ = a.communicate(timeout=420)
            assert a.returncode == 0, out[-4000:]
        done = (markers / "done.txt").read_text()
        assert done == "8", done
        # the restarted world resumed from the committed step, not zero
        resumes = [json.loads(p.read_text())
                   for p in markers.glob("start_r*_p*.json")
                   if "start_r0" not in p.name]
        assert resumes, "no restarted worker markers"
        assert all(r["start"] >= 3 for r in resumes), resumes
        assert all(r["nprocs"] == 2 and r["devices"] == 4 for r in resumes)
        # loss continuity: post-restart losses carry on below the first loss
        def _read(pattern):
            out = []
            for f in markers.glob(pattern):
                for line in f.read_text().splitlines():
                    out.append(json.loads(line))
            return out

        pre = _read("losses_r0_p*.jsonl")
        post = _read("losses_r1_p*.jsonl")
        assert pre and post
        first = min(v for s_, v in pre if s_ == 1)
        assert max(v for _, v in post) < first
    finally:
        master.kill()
        for a in agents:
            if a.poll() is None:
                a.kill()


@requires_multiprocess_cpu
def test_jax_world_scale_up(tmp_path):
    """Membership change: a world of 1 node is joined by a second node;
    the running agent restarts its worker into the 2-node world
    (drives ElasticAgent._membership_changed) with state carried over."""
    script = tmp_path / "worker.py"
    script.write_text(JAX_WORKER)
    ckpt_dir = tmp_path / "ckpt"
    markers = tmp_path / "markers"
    markers.mkdir()
    env = _base_env(tmp_path, "jx2")
    port = _free_port()
    master = _spawn_master(port, 1, 2, env)
    agents = []
    try:
        import time as _t
        _t.sleep(2.0)
        agents.append(_spawn_agent(0, script, [ckpt_dir, markers, "plain"],
                                   port, env, nnodes="1:2"))
        # wait until node 0 trains alone, then add node 1
        deadline = _t.time() + 180
        while _t.time() < deadline and \
                not list(markers.glob("start_r0_p0_*.json")):
            _t.sleep(0.5)
        assert list(markers.glob("start_r0_p0_*.json"))
        # wait for a COMMITTED checkpoint, not a fixed sleep: under CI
        # load the solo worker can take >4s to commit its first steps,
        # and the scale-up restart would then legitimately start from 0
        commit_marker = ckpt_dir / "latest_checkpointed_iteration.txt"
        deadline = _t.time() + 120
        while _t.time() < deadline and not commit_marker.exists():
            _t.sleep(0.5)
        assert commit_marker.exists(), "solo worker never committed"
        agents.append(_spawn_agent(1, script, [ckpt_dir, markers, "plain"],
                                   port, env, nnodes="1:2"))
        for a in agents:
            out, _ = a.communicate(timeout=420)
            assert a.returncode == 0, out[-4000:]
        # some worker ran in a 2-process world spanning 4 devices
        worlds = [json.loads(p.read_text())
                  for p in markers.glob("start_r*_p*.json")]
        assert any(w["nprocs"] == 2 and w["devices"] == 4 for w in worlds), \
            worlds
        # node 0's restarted worker carried state over (start > 0)
        restarted = [w for w in worlds if w["nprocs"] == 2 and w["start"] > 0]
        assert restarted, worlds
        assert (markers / "done.txt").exists()
    finally:
        master.kill()
        for a in agents:
            if a.poll() is None:
                a.kill()


@requires_multiprocess_cpu
def test_jax_world_slice_loss(tmp_path):
    """Multi-slice failure domain (SURVEY §2.5 DCN row; reference node
    groups dist_job_manager.py:88): a whole node group — agent AND its
    worker, i.e. "slice 0", which hosts the jax.distributed coordinator —
    is SIGKILLed mid-training.  The survivor's worker dies on the broken
    world, a replacement node joins, the master re-forms the world with
    {survivor, replacement}, and training resumes from the committed step
    through to completion."""
    import signal
    import time as _t

    script = tmp_path / "worker.py"
    script.write_text(JAX_WORKER)
    ckpt_dir = tmp_path / "ckpt"
    markers = tmp_path / "markers"
    markers.mkdir()
    env = _base_env(tmp_path, "jx3")
    port = _free_port()
    master = _spawn_master(port, 2, 3, env)
    agents = []
    try:
        _t.sleep(2.0)
        a0 = _spawn_agent(0, script, [ckpt_dir, markers, "slice"],
                          port, env)
        a1 = _spawn_agent(1, script, [ckpt_dir, markers, "slice"],
                          port, env)
        agents = [a0, a1]
        # wait until both slices train and a step committed
        deadline = _t.time() + 180
        tracker = ckpt_dir / "latest_checkpointed_iteration.txt"
        node0_marker = None
        while _t.time() < deadline:
            r0 = [json.loads(p.read_text())
                  for p in markers.glob("start_r0_p*.json")]
            if len(r0) == 2 and tracker.exists():
                node0_marker = next(m for m in r0 if m["node"] == 0)
                break
            _t.sleep(0.5)
        assert node0_marker is not None, "slices never started training"
        # kill slice 0 whole: the agent's process group AND its worker
        # (the worker runs in its own session — start_new_session=True)
        os.kill(a0.pid, signal.SIGKILL)
        try:
            os.killpg(node0_marker["ospid"], signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            os.kill(node0_marker["ospid"], signal.SIGKILL)
        # replacement slice joins
        a2 = _spawn_agent(2, script, [ckpt_dir, markers, "slice"],
                          port, env)
        agents.append(a2)
        for a in (a1, a2):
            out, _ = a.communicate(timeout=420)
            assert a.returncode == 0, out[-4000:]
        assert (markers / "done.txt").read_text() == "30"
        # the re-formed 2-node world includes the REPLACEMENT node and
        # resumed from committed state, not zero.  Post-kill markers:
        # the survivor's restarts (restart > 0) and the replacement's
        # first run (node 2, restart 0).
        worlds = [json.loads(p.read_text())
                  for p in markers.glob("start_r*_p*_n*.json")]
        post = [w for w in worlds if w["restart"] > 0 or w["node"] == 2]
        assert any(w["node"] == 2 and w["nprocs"] == 2 for w in post), \
            worlds
        assert all(w["start"] > 0 for w in post), post
    finally:
        master.kill()
        for a in agents:
            if a.poll() is None:
                try:
                    a.kill()
                except ProcessLookupError:
                    pass

"""End-to-end elasticity: CLI → master → agent → worker crash → restart →
resume from flash checkpoint.

Mirrors the reference's chaos experiments (docs/tech_report/
fault_tolerance_exps.md) at unit scale: injected worker failure, loss of no
committed state, training completes after automatic restart.
"""

import json
import os
import subprocess
import sys

import pytest

WORKER_SCRIPT = r"""
import os, sys, time
import numpy as np

from dlrover_wuqiong_tpu.trainer.elastic import init_elastic
from dlrover_wuqiong_tpu.checkpoint.checkpointer import (
    FlashCheckpointer, StorageType)

ckpt_dir = sys.argv[1]
marker_dir = sys.argv[2]

ctx = init_elastic()
restart = ctx.world.restart_count
ckpt = FlashCheckpointer(ckpt_dir, job_name=os.environ["DWT_JOB_NAME"])

template = {"w": np.zeros((4, 4), np.float32), "step": np.zeros((), np.int64)}
state = ckpt.load_checkpoint(template)
start_step = int(state["step"]) + 1 if state is not None else 0

with open(os.path.join(marker_dir, f"start_r{restart}.json"), "w") as f:
    f.write(str(start_step))

for step in range(start_step, 21):
    w = np.full((4, 4), float(step), np.float32)
    ckpt.save_checkpoint(step, {"w": w, "step": np.int64(step)},
                         storage_type=StorageType.DISK)
    ctx.report_step(step)
    time.sleep(0.02)
    if step == 12 and restart == 0:
        ckpt.wait_latest_checkpoint(30)
        os._exit(17)  # injected fault

ok = ckpt.wait_latest_checkpoint(60)
with open(os.path.join(marker_dir, "done.txt"), "w") as f:
    f.write(f"{ok} {step}")
"""


def test_crash_restart_resume(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER_SCRIPT)
    ckpt_dir = tmp_path / "ckpt"
    marker_dir = tmp_path / "markers"
    marker_dir.mkdir()

    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "DWT_JOB_NAME": "e2e1",
        "DWT_SOCKET_DIR": str(tmp_path / "sockets"),
        "DWT_CTX_NODE_HEARTBEAT_TIMEOUT": "600",
    })
    proc = subprocess.run(
        [sys.executable, "-m", "dlrover_wuqiong_tpu.run", "--standalone",
         "--nproc_per_node=1", "--max_restarts=2",
         str(script), str(ckpt_dir), str(marker_dir)],
        env=env, capture_output=True, text=True, timeout=150,
        cwd="/root/repo")

    assert proc.returncode == 0, (proc.stdout[-3000:], proc.stderr[-3000:])
    done = (marker_dir / "done.txt").read_text()
    assert done.startswith("True 20"), done
    # restart happened and resumed from >= the crash checkpoint
    start_r1 = int((marker_dir / "start_r1.txt").read_text()) \
        if (marker_dir / "start_r1.txt").exists() else None
    r1 = (marker_dir / "start_r1.json")
    assert r1.exists(), "worker was not restarted"
    resumed_from = int(r1.read_text())
    assert resumed_from >= 12, f"resumed too early: {resumed_from}"
    # committed tracker shows the final step
    tracker = ckpt_dir / "latest_checkpointed_iteration.txt"
    assert tracker.read_text().strip() == "20"

"""Smoke tests for the synthetic-fleet RPC benchmark (fleet_bench.py).

The full A/B (200 clients, two master phases, slow-storage floor) runs
from bench.py / `tools/perf_probe.py rpc`; here we pin the cheap
invariants: the module stays jax-free (spawn'd client workers re-import
it), the percentile helper, and one tiny end-to-end fleet round against
a real spawned master with group commit on.
"""

import json
import os
import subprocess
import sys

from dlrover_wuqiong_tpu.fleet_bench import (
    VERB_CLASSES,
    FleetMaster,
    _percentile,
    run_fleet,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestModuleIsLight:
    def test_import_does_not_pull_jax(self):
        # client worker processes re-import this module on spawn; if it
        # ever grows a jax import, every fleet worker pays jax startup
        # (and the CPU-only guarantee dies)
        code = ("import sys; import dlrover_wuqiong_tpu.fleet_bench; "
                "print(json.dumps([m for m in ('jax', 'jaxlib', 'flax') "
                "if m in sys.modules]))")
        out = subprocess.run(
            [sys.executable, "-c", "import json; " + code],
            env=dict(os.environ, PYTHONPATH=REPO_ROOT),
            capture_output=True, text=True, timeout=60, check=True)
        assert json.loads(out.stdout.strip().splitlines()[-1]) == []


class TestPercentile:
    def test_empty_is_zero(self):
        assert _percentile([], 0.99) == 0.0

    def test_singleton(self):
        assert _percentile([7.0], 0.5) == 7.0
        assert _percentile([7.0], 0.99) == 7.0

    def test_tail_rank(self):
        vals = [float(i) for i in range(1, 101)]
        assert _percentile(vals, 0.50) == 50.0
        assert _percentile(vals, 0.99) == 99.0
        assert _percentile(vals, 1.0) == 100.0


class TestTinyFleet:
    def test_one_round_against_real_master(self):
        # smallest honest fleet: 4 clients over 2 spawned procs, short
        # window, no storage floor — pins the report contract and that
        # the journal gauges attribute to a group-commit master
        with FleetMaster(group_commit=True) as fm:
            report = run_fleet(fm.addr, clients=4, procs=2,
                               duration_s=0.8)
            js = fm.journal_stats()
        assert report["clients"] == 4 and report["procs"] == 2
        for cls in VERB_CLASSES:
            assert set(report[cls]) == {"count", "rpc_per_s", "p50_ms",
                                        "p99_ms"}
        assert report["rpc_total"] == sum(
            report[c]["count"] for c in VERB_CLASSES)
        assert report["rpc_total"] > 0
        assert report["journaled"]["count"] > 0  # kv_set/kv_add landed
        assert report["rpc_p99_ms"] > 0.0
        assert report["rpc_errors"] == 0
        assert js["enabled"] and js["group_commit"]
        assert js["max_frames"] == 256
        assert js["fsync_floor_ms"] == 0.0
        assert js["frames"] >= report["journaled"]["count"]
        assert js["durable_seq"] >= js["frames"]

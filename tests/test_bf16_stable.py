"""Stable-bf16 optimizer + host-offloaded optimizer states.

Parity: reference atorch/optimizers/bf16_optimizer.py (stable bf16
master-weight training) and adam_offload.py (host-offloaded Adam states).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from version_gates import requires_pinned_host

from dlrover_wuqiong_tpu.auto.accelerate import auto_accelerate
from dlrover_wuqiong_tpu.models.gpt import GPT, GPTConfig
from dlrover_wuqiong_tpu.optimizers.bf16_stable import stable_bf16


def _run_quadratic(optimizer, p0, steps=200, lr_scale=1.0):
    """Minimize 0.5*(p - t)^2 with tiny per-step updates — exactly the
    regime where naive bf16 application loses every update."""
    target = jnp.full_like(p0, 1.5)
    params = {"w": p0}
    state = optimizer.init(params)

    @jax.jit
    def step(params, state):
        grads = {"w": (params["w"].astype(jnp.float32)
                       - target).astype(params["w"].dtype)}
        updates, state = optimizer.update(grads, state, params)
        return optax.apply_updates(params, updates), state

    for _ in range(steps):
        params, state = step(params, state)
    return np.asarray(params["w"], np.float32)


class TestStableBF16:
    @pytest.mark.parametrize("master", [False, True])
    def test_tracks_f32_trajectory(self, master):
        sgd = optax.sgd(1e-3)
        ref = _run_quadratic(sgd, jnp.ones((64,), jnp.float32))
        got = _run_quadratic(stable_bf16(sgd, master=master),
                             jnp.ones((64,), jnp.bfloat16))
        # naive bf16: every 1e-3-scale update under the 0.0078 ulp at 1.0
        # is rounded away and params never move
        naive = _run_quadratic(sgd, jnp.ones((64,), jnp.bfloat16))
        np.testing.assert_allclose(got, ref, atol=5e-3)
        assert abs(naive - ref).max() > 20 * abs(got - ref).max()

    def test_adamw_composition(self):
        adamw = optax.adamw(1e-3)
        ref = _run_quadratic(adamw, jnp.ones((64,), jnp.float32))
        got = _run_quadratic(stable_bf16(adamw),
                             jnp.ones((64,), jnp.bfloat16))
        np.testing.assert_allclose(got, ref, atol=1e-2)

    def test_strategy_casts_params_and_trains(self):
        cfg = GPTConfig.nano()
        res = auto_accelerate(
            GPT(cfg), optimizer=optax.adamw(1e-2),
            strategy=[("fsdp", {}), ("stable_bf16", {})])
        leaf = res.state.params["wte"]["embedding"]
        assert leaf.dtype == jnp.bfloat16
        # comp tree exists and is bf16 (Kahan), param-shaped
        comp = res.state.opt_state.comp["wte"]["embedding"]
        assert comp.dtype == jnp.bfloat16 and comp.shape == leaf.shape
        data = jax.random.randint(jax.random.PRNGKey(0), (8, 33), 0,
                                  cfg.vocab_size)
        batch = res.place_batch({"input_ids": data[:, :-1],
                                 "labels": data[:, 1:]})
        state, losses = res.state, []
        for _ in range(8):
            state, m = res.train_step(state, batch)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0], losses


@requires_pinned_host
class TestOptimizerOffload:
    def test_moments_land_in_host_memory(self):
        cfg = GPTConfig.nano()
        res = auto_accelerate(
            GPT(cfg), optimizer=optax.adamw(1e-2),
            strategy=[("fsdp", {}), ("optimizer_offload", {})])
        mu = res.state.opt_state[0].mu["wte"]["embedding"]
        assert mu.sharding.memory_kind == "pinned_host"
        # params stay on device
        assert res.state.params["wte"]["embedding"].sharding.memory_kind \
            == "device"

    def test_offloaded_step_matches_on_device_step(self):
        cfg = GPTConfig.nano()
        data = jax.random.randint(jax.random.PRNGKey(0), (8, 33), 0,
                                  cfg.vocab_size)

        def run(strategy):
            res = auto_accelerate(GPT(cfg), optimizer=optax.adamw(1e-2),
                                  strategy=strategy,
                                  rng=jax.random.PRNGKey(3))
            batch = res.place_batch({"input_ids": data[:, :-1],
                                     "labels": data[:, 1:]})
            state = res.state
            for _ in range(3):
                state, m = res.train_step(state, batch)
            return float(m["loss"]), state

        l_dev, s_dev = run([("fsdp", {})])
        l_off, s_off = run([("fsdp", {}), ("optimizer_offload", {})])
        np.testing.assert_allclose(l_off, l_dev, rtol=1e-5)
        for a, b in zip(jax.tree.leaves(
                jax.tree.map(np.asarray, s_dev.params)),
                jax.tree.leaves(jax.tree.map(np.asarray, s_off.params))):
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


@requires_pinned_host
class TestSlowOffloadLinkGuard:
    """r4 verdict weak #5: offload strategies on a slow host link must
    warn at resolve time with the measured rate, not silently regress."""

    def _accelerate(self, caplog, monkeypatch, gbps):
        import dataclasses
        import logging

        import optax

        from dlrover_wuqiong_tpu.auto.accelerate import auto_accelerate
        from dlrover_wuqiong_tpu.models.gpt import GPT, GPTConfig

        monkeypatch.setenv("DWT_H2D_GBPS", str(gbps))
        # the package logger does not propagate to root (common/log.py);
        # caplog's handler sits on root
        monkeypatch.setattr(logging.getLogger("dwt"), "propagate", True)
        cfg = dataclasses.replace(GPTConfig.nano(), dtype=jnp.float32,
                                  use_flash_attention=False, remat=False)
        with caplog.at_level(logging.WARNING, logger="dwt.accelerate"):
            auto_accelerate(GPT(cfg), optimizer=optax.adam(1e-3),
                            strategy=[("fsdp", {}),
                                      ("optimizer_offload", {})],
                            devices=jax.devices())
        return caplog.text

    def test_slow_link_warns(self, caplog, monkeypatch):
        text = self._accelerate(caplog, monkeypatch, gbps=0.05)
        assert "slow host link" in text and "0.050 GB/s" in text

    def test_fast_link_silent(self, caplog, monkeypatch):
        text = self._accelerate(caplog, monkeypatch, gbps=50.0)
        assert "slow host link" not in text

"""graftlint v2 protocol engine (analysis/protocol_engine.py).

One good + one bad fixture per interprocedural rule (journal-before-ack,
idem-key-required, commit-order, atomic-publish, lock-leak), the
suppression-reason grammar, the v2 CLI surface (--catalog, --changed,
JSON schema stability — downstream parsers of the one-line output must
never break silently), and the tier-1 repo self-lint: the protocol
engine over this tree must come back clean.  Pure AST work — no jax
device computation anywhere in this file.
"""

import json
import os
import subprocess
import sys
import textwrap

from dlrover_wuqiong_tpu.analysis.findings import (
    Finding,
    RULE_CATALOG,
    check_suppression_reasons,
    render_report,
    summarize_severity,
)
from dlrover_wuqiong_tpu.analysis.protocol_engine import run_paths

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _scan(tmp_path, relpath, source, **kw):
    """Write one fixture file and run the protocol engine over it."""
    path = tmp_path / relpath
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    findings, _ = run_paths([str(tmp_path)], **kw)
    return findings


# ------------------------------------------------- journal-before-ack


_SERVICER_PREAMBLE = """\
    class Servicer:
        def _journal(self, kind, data, idem=None, resp=None):
            journal = self.m.journal
            if journal is None:
                return
            journal.append(kind, data)

"""


class TestJournalBeforeAck:
    def test_unjournaled_mutating_verb_flagged(self, tmp_path):
        found = _scan(tmp_path, "servicer.py", _SERVICER_PREAMBLE + """\
        def _report(self, node_id, payload, idem=None):
            if isinstance(payload, msg.TaskResult):
                self.m.task_manager.report_dataset_task(
                    node_id, payload.dataset_name, payload.task_id)
                return msg.OkResponse()
            return None
""")
        assert [f.checker for f in found] == ["journal-before-ack"]
        assert "TaskResult" in found[0].message

    def test_ack_before_append_flagged(self, tmp_path):
        found = _scan(tmp_path, "servicer.py", _SERVICER_PREAMBLE + """\
        def _report(self, node_id, payload, idem=None):
            if isinstance(payload, msg.KVStoreSetRequest):
                self.m.kv_store.set(payload.key, payload.value)
                return msg.OkResponse()
                self._journal("kv_set", {"key": payload.key})
            return None
""")
        assert any(f.checker == "journal-before-ack"
                   and "BEFORE its journal append" in f.message
                   for f in found)

    def test_journal_then_ack_clean(self, tmp_path):
        found = _scan(tmp_path, "servicer.py", _SERVICER_PREAMBLE + """\
        def _report(self, node_id, payload, idem=None):
            if isinstance(payload, msg.TaskResult):
                self.m.task_manager.report_dataset_task(
                    node_id, payload.dataset_name, payload.task_id)
                resp = msg.OkResponse()
                self._journal("task_result", {"task_id": payload.task_id},
                              idem=idem, resp=resp)
                return resp
            return None
""")
        assert found == []

    def test_conditional_journal_before_final_return_clean(self, tmp_path):
        # the in-tree DatasetShardParams shape: a no-op mutation need
        # not journal, so the append may sit under `if created:`
        found = _scan(tmp_path, "servicer.py", _SERVICER_PREAMBLE + """\
        def _report(self, node_id, payload, idem=None):
            if isinstance(payload, msg.DatasetShardParams):
                created = self.m.task_manager.new_dataset(payload.name)
                if created:
                    self._journal("dataset", {"name": payload.name})
                return msg.OkResponse()
            return None
""")
        assert found == []

    def test_non_servicer_module_ignored(self, tmp_path):
        # no _journal method => not a servicer class, rule stays quiet
        found = _scan(tmp_path, "other.py", """\
            class Helper:
                def dispatch(self, payload):
                    if isinstance(payload, msg.TaskResult):
                        return handle(payload)
        """)
        assert found == []


# ------------------------------------------- group-commit batched shape


_GC_PREAMBLE = """\
    class Servicer:
        def _journal(self, kind, data, idem=None, resp=None):
            journal = self.m.journal
            if journal is None:
                return
            seq = journal.append_nowait(kind, data)
            journal.wait_durable(seq)

"""


class TestGroupCommitShape:
    """The batched journal-before-ack shape: an ack gated on the durable
    watermark (append_nowait + wait_durable) counts as journal-append
    reaching the ack; an async enqueue with NO watermark gate is the new
    bad shape (the ack would race the batch leader's fsync)."""

    def test_batched_journal_helper_clean(self, tmp_path):
        # the in-tree MasterServicer._journal shape after group commit
        found = _scan(tmp_path, "servicer.py", _GC_PREAMBLE + """\
        def _report(self, node_id, payload, idem=None):
            if isinstance(payload, msg.TaskResult):
                self.m.task_manager.report_dataset_task(
                    node_id, payload.dataset_name, payload.task_id)
                resp = msg.OkResponse()
                self._journal("task_result", {"task_id": payload.task_id},
                              idem=idem, resp=resp)
                return resp
            return None
""")
        assert found == []

    def test_async_append_without_durable_wait_flagged(self, tmp_path):
        found = _scan(tmp_path, "servicer.py", _GC_PREAMBLE + """\
        def _enqueue_only(self, kind, data, idem=None):
            self.m.journal.append_nowait(kind, data)

        def _report(self, node_id, payload, idem=None):
            if isinstance(payload, msg.KVStoreSetRequest):
                self.m.kv_store.set(payload.key, payload.value)
                self._enqueue_only("kv_set", {"key": payload.key})
                return msg.OkResponse()
            return None
""")
        assert [f.checker for f in found] == ["journal-before-ack"]
        assert "wait_durable" in found[0].message

    def test_split_shape_assembled_in_branch_clean(self, tmp_path):
        # enqueue and watermark gate via SEPARATE helpers, paired in the
        # branch before the ack — a legal decomposition of group commit
        found = _scan(tmp_path, "servicer.py", _GC_PREAMBLE + """\
        def _enqueue(self, kind, data, idem=None):
            return self.m.journal.append_nowait(kind, data)

        def _gate(self, seq):
            self.m.journal.wait_durable(seq)

        def _report(self, node_id, payload, idem=None):
            if isinstance(payload, msg.KVStoreSetRequest):
                self.m.kv_store.set(payload.key, payload.value)
                seq = self._enqueue("kv_set", {"key": payload.key})
                self._gate(seq)
                return msg.OkResponse()
            return None
""")
        assert found == []

    def test_idem_key_rides_the_async_half(self, tmp_path):
        # idem-key-required must see idem= on the enqueue call even when
        # the durability gate is a separate helper
        found = _scan(tmp_path, "servicer.py", _GC_PREAMBLE + """\
        def _enqueue(self, kind, data, idem=None):
            return self.m.journal.append_nowait(kind, data)

        def _gate(self, seq):
            self.m.journal.wait_durable(seq)

        def _report(self, node_id, payload, idem=None):
            if isinstance(payload, msg.TaskResult):
                resp = msg.OkResponse()
                seq = self._enqueue("task_result", {"id": payload.task_id},
                                    idem=idem)
                self._gate(seq)
                return resp
            return None
""")
        assert found == []

    def test_idem_missing_on_batched_shape_flagged(self, tmp_path):
        found = _scan(tmp_path, "servicer.py", _GC_PREAMBLE + """\
        def _report(self, node_id, payload, idem=None):
            if isinstance(payload, msg.TaskResult):
                resp = msg.OkResponse()
                self._journal("task_result", {"id": payload.task_id})
                return resp
            return None
""")
        assert [f.checker for f in found] == ["idem-key-required"]


class TestPolicyVerbs:
    """PolicyDecisionReport sits in JOURNALED_VERBS + IDEM_VERBS: an
    adaptive decision that vanishes across a master restart would leave
    trainers on knobs the replayed master never heard of."""

    def test_policy_ack_without_journal_flagged(self, tmp_path):
        found = _scan(tmp_path, "servicer.py", _SERVICER_PREAMBLE + """\
        def _report(self, node_id, payload, idem=None):
            if isinstance(payload, msg.PolicyDecisionReport):
                decision = self.m.admit_policy_decision(payload.decision)
                return msg.PolicyDecisionAck(
                    decision_id=decision.decision_id)
            return None
""")
        assert [f.checker for f in found] == ["journal-before-ack"]
        assert "PolicyDecisionReport" in found[0].message

    def test_policy_journal_without_idem_flagged(self, tmp_path):
        found = _scan(tmp_path, "servicer.py", _SERVICER_PREAMBLE + """\
        def _report(self, node_id, payload, idem=None):
            if isinstance(payload, msg.PolicyDecisionReport):
                decision = self.m.admit_policy_decision(payload.decision)
                resp = msg.PolicyDecisionAck(
                    decision_id=decision.decision_id)
                self._journal("policy", {"decision": decision})
                return resp
            return None
""")
        assert [f.checker for f in found] == ["idem-key-required"]
        assert "PolicyDecisionReport" in found[0].message

    def test_policy_journal_before_ack_with_idem_clean(self, tmp_path):
        # the in-tree servicer shape: journal carries idem + resp in ONE
        # frame (a separate frame could tear between them)
        found = _scan(tmp_path, "servicer.py", _SERVICER_PREAMBLE + """\
        def _report(self, node_id, payload, idem=None):
            if isinstance(payload, msg.PolicyDecisionReport):
                decision = self.m.admit_policy_decision(payload.decision)
                resp = msg.PolicyDecisionAck(
                    decision_id=decision.decision_id)
                self._journal("policy", {"decision": decision},
                              idem=idem, resp=resp)
                return resp
            return None
""")
        assert found == []

    def test_policy_client_send_without_idem_flagged(self, tmp_path):
        found = _scan(tmp_path, "client.py", """\
            class Client:
                def report_policy_decision(self, decision):
                    req = msg.PolicyDecisionReport(decision=decision)
                    return self._call_critical("report", req)
        """)
        assert [f.checker for f in found] == ["idem-key-required"]


class TestServeVerbs:
    """The serving verb family (ServeSubmitRequest / ServeLeaseRequest /
    ServeResultReport) sits in JOURNALED_VERBS + IDEM_VERBS: a lease or
    result that vanishes across a master restart would double-decode or
    drop an in-flight inference request — the exact property `chaos
    serve-drain` pins end to end."""

    def test_serve_submit_ack_without_journal_flagged(self, tmp_path):
        found = _scan(tmp_path, "servicer.py", _SERVICER_PREAMBLE + """\
        def _report(self, node_id, payload, idem=None):
            if isinstance(payload, msg.ServeSubmitRequest):
                accepted = self.m.serve_queue.submit(payload.requests)
                return msg.ServeSubmitAck(accepted=accepted)
            return None
""")
        assert [f.checker for f in found] == ["journal-before-ack"]
        assert "ServeSubmitRequest" in found[0].message

    def test_serve_result_journal_without_idem_flagged(self, tmp_path):
        found = _scan(tmp_path, "servicer.py", _SERVICER_PREAMBLE + """\
        def _report(self, node_id, payload, idem=None):
            if isinstance(payload, msg.ServeResultReport):
                self.m.serve_queue.complete(payload.results)
                resp = msg.OkResponse()
                self._journal("serve_result", {"node_id": node_id})
                return resp
            return None
""")
        assert [f.checker for f in found] == ["idem-key-required"]
        assert "ServeResultReport" in found[0].message

    def test_serve_lease_journal_before_ack_with_idem_clean(self, tmp_path):
        # the in-tree servicer shape: the leased request ids are the
        # journal payload (replay re-assigns the EXACT set), idem + resp
        # ride the same frame
        found = _scan(tmp_path, "servicer.py", _SERVICER_PREAMBLE + """\
        def _get(self, node_id, payload, idem=None):
            if isinstance(payload, msg.ServeLeaseRequest):
                leased = self.m.serve_queue.lease(
                    payload.node_id, payload.max_requests)
                resp = msg.ServeLease(requests=leased)
                self._journal("serve_lease",
                              {"node_id": payload.node_id,
                               "request_ids": [r.request_id
                                               for r in leased]},
                              idem=idem, resp=resp)
                return resp
            return None
""")
        assert found == []

    def test_serve_client_send_without_idem_flagged(self, tmp_path):
        found = _scan(tmp_path, "client.py", """\
            class Client:
                def submit_serve_requests(self, requests):
                    req = msg.ServeSubmitRequest(requests=requests)
                    return self._call_critical("report", req)
        """)
        assert [f.checker for f in found] == ["idem-key-required"]


# ------------------------------------------------- idem-key-required


class TestIdemKeyRequired:
    def test_servicer_journal_without_idem_flagged(self, tmp_path):
        found = _scan(tmp_path, "servicer.py", _SERVICER_PREAMBLE + """\
        def _report(self, node_id, payload, idem=None):
            if isinstance(payload, msg.KVStoreAddRequest):
                num = self.m.kv_store.add(payload.key, payload.amount)
                resp = msg.KVStoreResponse(num=num)
                self._journal("kv_add", {"key": payload.key})
                return resp
            return None
""")
        assert [f.checker for f in found] == ["idem-key-required"]
        assert "KVStoreAddRequest" in found[0].message

    def test_client_send_without_idem_flagged(self, tmp_path):
        found = _scan(tmp_path, "client.py", """\
            class Client:
                def report_task_result(self, dataset, task_id):
                    req = msg.TaskResult(dataset_name=dataset,
                                         task_id=task_id)
                    return self._call_critical("report", req)
        """)
        assert [f.checker for f in found] == ["idem-key-required"]
        assert "idem=self._next_idem()" in found[0].message

    def test_threaded_end_to_end_clean(self, tmp_path):
        found = _scan(tmp_path, "client.py", """\
            class Client:
                def report_task_result(self, dataset, task_id):
                    req = msg.TaskResult(dataset_name=dataset,
                                         task_id=task_id)
                    return self._call_critical("report", req,
                                               idem=self._next_idem())
        """)
        found += _scan(tmp_path, "servicer.py",
                       _SERVICER_PREAMBLE + """\
        def _report(self, node_id, payload, idem=None):
            if isinstance(payload, msg.TaskResult):
                self.m.task_manager.report_dataset_task(node_id,
                                                        payload.task_id)
                resp = msg.OkResponse()
                self._journal("task_result", {"id": payload.task_id},
                              idem=idem, resp=resp)
                return resp
            return None
""")
        assert found == []


# --------------------------------------------- failover-frame durability


class TestFailoverDurability:
    """ISSUE 20: the promotion fence is only real if the ``failover``
    frame is DURABLE before the new epoch opens — an async append that
    never gates on the watermark could vanish in a crash and revive a
    corpse at an unfenced epoch."""

    def test_async_failover_append_flagged(self, tmp_path):
        found = _scan(tmp_path, "master.py", """\
            class JobMaster:
                def promote_to_leader(self):
                    self.journal.append_nowait(
                        "failover", {"new_epoch": self.epoch + 2})
                    self.epoch = self.journal.open_epoch()
        """)
        assert [f.checker for f in found] == ["journal-before-ack"]
        assert "failover" in found[0].message
        assert "wait_durable" in found[0].message

    def test_sync_failover_append_clean(self, tmp_path):
        found = _scan(tmp_path, "master.py", """\
            class JobMaster:
                def promote_to_leader(self):
                    self.journal.append(
                        "failover", {"new_epoch": self.epoch + 2})
                    self.epoch = self.journal.open_epoch()
        """)
        assert found == []

    def test_nowait_gated_on_watermark_clean(self, tmp_path):
        found = _scan(tmp_path, "master.py", """\
            class JobMaster:
                def promote_to_leader(self):
                    seq = self.journal.append_nowait(
                        "failover", {"new_epoch": self.epoch + 2})
                    self.journal.wait_durable(seq)
                    self.epoch = self.journal.open_epoch()
        """)
        assert found == []

    def test_fetch_journal_polling_never_journaled(self, tmp_path):
        """The shipping pull is POLLING class: a servicer branch that
        answers FetchJournalRequest WITHOUT journaling is the sanctioned
        shape (a fetch that journaled would feed the journal it ships —
        the verb is deliberately absent from JOURNALED_VERBS)."""
        from dlrover_wuqiong_tpu.analysis.protocol_engine import (
            IDEM_VERBS,
            JOURNALED_VERBS,
        )

        assert "FetchJournalRequest" not in JOURNALED_VERBS
        assert "FetchJournalRequest" not in IDEM_VERBS
        found = _scan(tmp_path, "servicer.py", _SERVICER_PREAMBLE + """\
        def _get(self, node_id, payload):
            if isinstance(payload, msg.FetchJournalRequest):
                snap, sseq, frames, durable = self.m.journal.fetch_batch(
                    payload.from_seq, payload.max_frames)
                return msg.FetchJournalResponse(frames=frames,
                                                durable_seq=durable)
            return None
""")
        assert found == []


# ------------------------------------------------------- commit-order


class TestCommitOrder:
    def test_marker_without_manifest_flagged(self, tmp_path):
        found = _scan(tmp_path, "saver.py", """\
            import os

            def commit(storage, step, sdir):
                storage.write(str(step), os.path.join(
                    sdir, CheckpointConstant.COMMIT_MARKER))
        """)
        assert [f.checker for f in found] == ["commit-order"]
        assert ".commit marker" in found[0].message

    def test_tracker_without_evidence_flagged(self, tmp_path):
        found = _scan(tmp_path, "saver.py", """\
            import os

            def publish(storage, step, path):
                storage.write(str(step), os.path.join(
                    path, CheckpointConstant.TRACKER_FILE))
        """)
        assert [f.checker for f in found] == ["commit-order"]
        assert "tracker" in found[0].message

    def test_full_commit_order_clean(self, tmp_path):
        found = _scan(tmp_path, "saver.py", """\
            import os

            def _write_step_manifest(storage, step, sdir):
                write_manifest(storage, sdir, {"step": step})

            def commit(storage, step, sdir, path):
                _write_step_manifest(storage, step, sdir)
                storage.write(str(step), os.path.join(
                    sdir, CheckpointConstant.COMMIT_MARKER))
                storage.write(str(step), os.path.join(
                    path, CheckpointConstant.TRACKER_FILE))
        """)
        assert found == []

    def test_tracker_repoint_after_verify_clean(self, tmp_path):
        # the engine.py self-heal shape: repointing the tracker at a
        # generation whose manifest was just read and verified is legal
        found = _scan(tmp_path, "engine.py", """\
            import os

            def repoint(storage, step, path):
                manifest = read_manifest(storage, step_dir(path, step))
                if manifest is None:
                    return
                storage.write(str(step), os.path.join(
                    path, CheckpointConstant.TRACKER_FILE))
        """)
        assert found == []


# ----------------------------------------------------- atomic-publish


class TestAtomicPublish:
    def test_raw_open_on_manifest_flagged(self, tmp_path):
        found = _scan(tmp_path, "saver.py", """\
            import os

            def publish(sdir, blob):
                with open(os.path.join(sdir, "manifest.json"), "w") as f:
                    f.write(blob)
        """)
        assert [f.checker for f in found] == ["atomic-publish"]

    def test_resolved_assignment_flagged(self, tmp_path):
        # the warm_pool.py shape this rule caught in-tree: the hint
        # lives in an assignment, not the open() call itself
        found = _scan(tmp_path, "pool.py", """\
            import os

            def publish(pool, key, blob):
                spec_path = os.path.join(pool, f"{key}.spec.json")
                with open(spec_path, "w") as f:
                    f.write(blob)
        """)
        assert [f.checker for f in found] == ["atomic-publish"]

    def test_write_tmp_then_rename_clean(self, tmp_path):
        found = _scan(tmp_path, "saver.py", """\
            import os

            def publish(sdir, blob):
                target = os.path.join(sdir, "manifest.json")
                tmp = f"{target}.tmp.{os.getpid()}"
                with open(tmp, "w") as f:
                    f.write(blob)
                os.replace(tmp, target)
        """)
        assert found == []

    def test_storage_write_helper_clean(self, tmp_path):
        found = _scan(tmp_path, "saver.py", """\
            import os

            def publish(storage, sdir, blob):
                storage.write(blob, os.path.join(sdir, "manifest.json"))
        """)
        assert found == []

    def test_unpublished_file_ignored(self, tmp_path):
        found = _scan(tmp_path, "notes.py", """\
            def dump(path, blob):
                with open(path, "w") as f:
                    f.write(blob)
        """)
        assert found == []


# ---------------------------------------------------------- lock-leak


class TestLockLeak:
    def test_release_outside_finally_flagged(self, tmp_path):
        found = _scan(tmp_path, "stage.py", """\
            def stage(shm_lock, payload):
                shm_lock.acquire(timeout=60)
                write(payload)
                shm_lock.release()
        """)
        assert [f.checker for f in found] == ["lock-leak"]
        assert "finally" in found[0].message

    def test_release_in_finally_clean(self, tmp_path):
        found = _scan(tmp_path, "stage.py", """\
            def stage(shm_lock, payload):
                acquired = shm_lock.acquire(timeout=60)
                try:
                    write(payload)
                finally:
                    if acquired:
                        shm_lock.release()
        """)
        assert found == []

    def test_non_lock_acquire_ignored(self, tmp_path):
        found = _scan(tmp_path, "pool.py", """\
            def take(semaphore):
                semaphore.acquire()
                return semaphore
        """)
        assert found == []

    def test_suppression_with_reason_honored(self, tmp_path):
        found = _scan(tmp_path, "drill.py", """\
            def die_holding(lock):
                lock.acquire(timeout=5)  # graftlint: disable=lock-leak -- drill: the leak is the scenario
                raise SystemExit(9)
        """)
        assert found == []


# ------------------------------------------------ suppression grammar


class TestSuppressionReasons:
    def test_reasonless_disable_flagged(self):
        # literal split so THIS file's raw-line scan doesn't match it
        lines = ["x = 1  # graftlint: " + "disable=lock-leak"]
        found = check_suppression_reasons("a.py", lines)
        assert [f.checker for f in found] == ["suppression-no-reason"]
        assert found[0].line == 1

    def test_reasoned_disable_clean(self):
        lines = ["x = 1  # graftlint: disable=lock-leak -- drill needs it"]
        assert check_suppression_reasons("a.py", lines) == []

    def test_reasonless_disable_still_suppresses(self, tmp_path):
        # additive migration: the old syntax keeps suppressing (the AST
        # engine reports the missing reason separately) so turning the
        # rule on cannot flip previously-suppressed findings back on.
        # The fixture's disable is assembled at runtime so this file's
        # own raw-line scan doesn't see a reason-less literal.
        found = _scan(tmp_path, "stage.py", (
            "def stage(lock):\n"
            "    lock.acquire()  # graftlint: " + "disable=lock-leak\n"))
        assert found == []


# ------------------------------------------------------- rule catalog


class TestRuleCatalog:
    def test_every_emitted_checker_is_cataloged(self):
        # engines may only emit rule ids the catalog documents
        for rule_id, entry in RULE_CATALOG.items():
            assert entry["engine"] in ("ast", "protocol", "concurrency",
                                       "schema", "jaxpr", "hlo")
            assert entry["severity"] in ("error", "warning")
            assert len(entry["rationale"]) > 20

    def test_finding_severity_defaults_from_catalog(self):
        f = Finding("budget-coverage", "msg")
        assert f.severity == "warning"
        g = Finding("lock-leak", "msg")
        assert g.severity == "error"
        assert summarize_severity([f, g]) == {"error": 1, "warning": 1}
        assert "warning" in f.format() and "error" in g.format()

    def test_readme_catalog_in_sync(self):
        # the README rule-catalog section must list every rule id
        readme = open(os.path.join(REPO_ROOT, "README.md")).read()
        for rule_id in RULE_CATALOG:
            assert f"`{rule_id}`" in readme, (
                f"README graftlint catalog is missing {rule_id}")


# ------------------------------------------------------- CLI surface


class TestCliV2:
    def test_json_schema_stable(self, tmp_path, capsys):
        """Downstream parsers pin this schema; keys are ADD-only."""
        from dlrover_wuqiong_tpu.analysis.__main__ import main

        (tmp_path / "ok.py").write_text("x = 1\n")
        rc = main(["--engine", "protocol", str(tmp_path)])
        out = capsys.readouterr().out.strip().splitlines()
        assert rc == 0 and len(out) == 1
        rec = json.loads(out[0])["graftlint"]
        assert set(rec) == {"engines", "files_scanned", "findings",
                            "by_checker", "by_severity",
                            "hlo_collectives", "elapsed_s", "ok"}
        assert isinstance(rec["engines"], list)
        assert isinstance(rec["files_scanned"], int)
        assert isinstance(rec["findings"], int)
        assert isinstance(rec["by_checker"], dict)
        assert isinstance(rec["by_severity"], dict)
        assert isinstance(rec["hlo_collectives"], dict)
        assert isinstance(rec["elapsed_s"], float)
        assert isinstance(rec["ok"], bool)

    def test_json_schema_section_when_schema_engine_runs(self, capsys):
        """ADD-only evolution: the ``schema`` key appears exactly when
        the schema engine ran, on top of the pinned base key set."""
        from dlrover_wuqiong_tpu.analysis.__main__ import main

        rc = main(["--engine", "schema"])
        out = capsys.readouterr().out.strip().splitlines()
        assert rc == 0 and len(out) == 1
        rec = json.loads(out[0])["graftlint"]
        assert set(rec) == {"engines", "files_scanned", "findings",
                            "by_checker", "by_severity",
                            "hlo_collectives", "elapsed_s", "ok",
                            "schema"}
        assert rec["engines"] == ["schema"]
        assert set(rec["schema"]) == {"surface", "lock"}
        assert rec["schema"]["lock"] == "ok"
        counts = rec["schema"]["surface"]
        assert counts["messages"] > 0 and counts["fields"] > 0
        assert set(counts["verbs"]) == {"journaled", "idem",
                                        "buffered", "polling"}

    def test_protocol_violation_rc1(self, tmp_path, capsys):
        from dlrover_wuqiong_tpu.analysis.__main__ import main

        (tmp_path / "stage.py").write_text(textwrap.dedent("""\
            def stage(lock):
                lock.acquire()
                lock.release()
            """))
        rc = main(["--engine", "protocol", str(tmp_path)])
        cap = capsys.readouterr()
        assert rc == 1
        rec = json.loads(cap.out.strip())["graftlint"]
        assert rec["by_checker"] == {"lock-leak": 1}
        assert rec["by_severity"] == {"error": 1}
        assert "stage.py:2" in cap.err

    def test_catalog_flag_single_json_line(self, capsys):
        from dlrover_wuqiong_tpu.analysis.__main__ import main

        rc = main(["--catalog"])
        out = capsys.readouterr().out.strip().splitlines()
        assert rc == 0 and len(out) == 1
        cat = json.loads(out[0])["graftlint_catalog"]
        assert set(cat) == set(RULE_CATALOG)

    def test_changed_mode_skips_trace_engines(self, tmp_path, capsys):
        from dlrover_wuqiong_tpu.analysis.__main__ import main

        (tmp_path / "ok.py").write_text("x = 1\n")
        rc = main(["--changed", str(tmp_path)])
        out = capsys.readouterr().out.strip()
        rec = json.loads(out)["graftlint"]
        assert rc == 0
        assert rec["engines"] == ["ast", "protocol", "concurrency",
                                  "schema"]  # no jaxpr/hlo

    def test_changed_paths_smoke(self):
        from dlrover_wuqiong_tpu.analysis.__main__ import _changed_paths

        got = _changed_paths()
        assert isinstance(got, list)
        assert all(p.endswith(".py") and os.path.exists(p) for p in got)

    def test_lint_wrapper_changed_mode(self):
        """tools/lint.py forwards --changed (the CI fast path)."""
        out = subprocess.run(
            [sys.executable, os.path.join(REPO_ROOT, "tools", "lint.py"),
             "--changed", "--engine", "protocol",
             os.path.join(REPO_ROOT, "tools", "lint.py")],
            capture_output=True, text=True, timeout=120)
        lines = out.stdout.strip().splitlines()
        assert len(lines) == 1
        rec = json.loads(lines[0])["graftlint"]
        assert rec["engines"] == ["protocol"]
        assert out.returncode == 0


# -------------------------------------------------- repo self-lint (t1)


class TestProtocolSelfLint:
    def test_protocol_engine_repo_clean(self):
        paths = [os.path.join(REPO_ROOT, p)
                 for p in ("dlrover_wuqiong_tpu", "tests", "examples",
                           "tools", "bench.py", "__graft_entry__.py")]
        findings, n_files = run_paths([p for p in paths
                                       if os.path.exists(p)])
        assert n_files > 100
        assert findings == [], "\n" + render_report(findings)

"""Sparse-embedding service tests (tfplus KvVariable parity axis).

Mirrors reference `tfplus/py_ut/` op tests + `kernels/kv_variable_test.cc`:
insert-or-default gather, frequency filtering, eviction, group sparse
optimizers, full/delta export-import, and an end-to-end toy recommendation
model with dynamic vocabulary growth and restore.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_wuqiong_tpu.embedding import (
    KvEmbedding,
    SparseOptConfig,
    apply_sparse_update,
    create_kv_store,
    dedup_grads,
    init_slot_state,
)
from dlrover_wuqiong_tpu.embedding.kv_store import (
    NativeKvStore,
    PyKvStore,
    _build_lib,
)

_HAS_NATIVE = _build_lib() is not None


def _stores():
    out = [PyKvStore(64)]
    if _HAS_NATIVE:
        out.append(NativeKvStore(64))
    return out


class TestKvStore:
    @pytest.mark.parametrize("store", _stores(),
                             ids=lambda s: type(s).__name__)
    def test_insert_lookup_freq(self, store):
        ids = np.array([10, 20, 10, 30], np.int64)
        slots, n_new = store.lookup_or_insert(ids, now=100)
        assert n_new == 3
        assert slots[0] == slots[2]  # same id → same slot
        assert len(set(slots.tolist())) == 3
        assert len(store) == 3
        # lookup-only does not insert
        miss = store.lookup(np.array([999], np.int64))
        assert miss[0] == -1
        freq = store.freq(slots)
        assert freq[0] == 2  # id 10 seen twice

    @pytest.mark.parametrize("store", _stores(),
                             ids=lambda s: type(s).__name__)
    def test_eviction_recycles_slots(self, store):
        ids = np.arange(5, dtype=np.int64)
        slots, _ = store.lookup_or_insert(ids, now=100)
        evicted = store.evict_older_than(200)
        assert len(evicted) == 5
        assert len(store) == 0
        slots2, n_new = store.lookup_or_insert(
            np.arange(100, 105, dtype=np.int64), now=300)
        assert n_new == 5
        assert set(slots2.tolist()) == set(slots.tolist())  # recycled

    @pytest.mark.parametrize("store", _stores(),
                             ids=lambda s: type(s).__name__)
    def test_full_export_import(self, store):
        ids = np.array([7, 8, 9], np.int64)
        slots, _ = store.lookup_or_insert(ids, now=50)
        keys, eslots, freqs, tss = store.export(with_meta=True)
        order = np.argsort(keys)
        np.testing.assert_array_equal(np.sort(keys), [7, 8, 9])
        fresh = type(store)(64)
        fresh.import_(keys, eslots, freqs, tss)
        np.testing.assert_array_equal(fresh.lookup(ids), slots)
        # allocator skips imported slots
        s2, _ = fresh.lookup_or_insert(np.array([1000], np.int64))
        assert s2[0] not in set(eslots.tolist())

    @pytest.mark.parametrize("store", _stores(),
                             ids=lambda s: type(s).__name__)
    def test_delta_export_tracks_epoch(self, store):
        store.lookup_or_insert(np.array([1, 2], np.int64))
        epoch = store.epoch
        k0, _ = store.export_delta(epoch)
        assert set(k0.tolist()) == {1, 2}
        store.advance_epoch()
        # nothing touched since → empty delta
        k1, _ = store.export_delta(store.epoch)
        assert len(k1) == 0
        store.lookup_or_insert(np.array([2, 3], np.int64))
        k2, _ = store.export_delta(store.epoch)
        assert set(k2.tolist()) == {2, 3}

    @pytest.mark.skipif(not _HAS_NATIVE, reason="no g++/native lib")
    def test_native_concurrent_inserts(self):
        store = NativeKvStore(100_000)
        errs = []

        def worker(base):
            try:
                for i in range(20):
                    ids = np.arange(base + i * 50, base + i * 50 + 50,
                                    dtype=np.int64) % 5000
                    store.lookup_or_insert(ids)
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=worker, args=(b * 997,))
                   for b in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        assert len(store) <= 5000
        # every id maps to exactly one slot
        ids = np.arange(5000, dtype=np.int64)
        slots = store.lookup(ids)
        seen = slots[slots >= 0]
        assert len(np.unique(seen)) == len(seen)

    @pytest.mark.skipif(not _HAS_NATIVE, reason="no g++/native lib")
    def test_native_grow(self):
        store = NativeKvStore(4)
        store.lookup_or_insert(np.arange(4, dtype=np.int64))
        with pytest.raises(MemoryError):
            store.lookup_or_insert(np.array([99], np.int64))
        store.grow(8)
        slots, _ = store.lookup_or_insert(np.array([99], np.int64))
        assert slots[0] == 4


class TestSparseOptim:
    def test_dedup_grads(self):
        slots = jnp.array([3, 1, 3, 2], jnp.int32)
        grads = jnp.ones((4, 2)) * jnp.arange(1.0, 5.0)[:, None]
        uniq, summed = dedup_grads(slots, grads, 4)
        lookup = {int(s): summed[i].tolist() for i, s in enumerate(uniq)}
        assert lookup[3] == [4.0, 4.0]  # rows 1 + 3
        assert lookup[1] == [2.0, 2.0]
        assert lookup[2] == [4.0, 4.0]

    def test_sparse_adam_matches_dense_adam(self):
        """Rows updated every step must follow dense Adam exactly."""
        import optax

        cfg = SparseOptConfig(kind="adam", lr=0.1)
        dim, cap = 4, 8
        table = jnp.ones((cap, dim))
        state = init_slot_state(cfg, cap, dim)
        opt = optax.adam(0.1)
        ref = jnp.ones((2, dim))
        ref_state = opt.init(ref)
        slots = jnp.array([1, 5], jnp.int32)
        for step in range(5):
            g = jnp.full((2, dim), 0.5) * (step + 1)
            table, state = apply_sparse_update(cfg, table, state, slots, g)
            updates, ref_state = opt.update(g, ref_state, ref)
            ref = optax.apply_updates(ref, updates)
        np.testing.assert_allclose(np.asarray(table[slots]),
                                   np.asarray(ref), rtol=2e-5)
        # untouched rows unchanged
        np.testing.assert_array_equal(np.asarray(table[0]), np.ones(dim))

    def test_group_adam_l21_prunes_rows(self):
        cfg = SparseOptConfig(kind="group_adam", lr=0.5, l21=10.0)
        table = jnp.full((4, 3), 0.01)
        state = init_slot_state(cfg, 4, 3)
        slots = jnp.array([2], jnp.int32)
        g = jnp.full((1, 3), 1e-4)
        table, state = apply_sparse_update(cfg, table, state, slots, g)
        assert float(jnp.abs(table[2]).sum()) == 0.0  # whole row zeroed

    @pytest.mark.parametrize("kind", ["adagrad", "ftrl", "sgd"])
    def test_optimizers_step(self, kind):
        cfg = SparseOptConfig(kind=kind, lr=0.1, l1=0.01, l2=0.01)
        table = jnp.ones((6, 2))
        state = init_slot_state(cfg, 6, 2)
        slots = jnp.array([1, 4], jnp.int32)
        g = jnp.ones((2, 2))
        t2, _ = apply_sparse_update(cfg, table, state, slots, g)
        assert not np.allclose(np.asarray(t2[slots]), 1.0)
        np.testing.assert_array_equal(np.asarray(t2[0]), [1.0, 1.0])

    # ---- the wide optimizer family (training_ops.cc:103-837 parity):
    # rows touched every step must track the dense optax reference exactly

    def _vs_optax(self, kind, opt, cfgkw=None, per_row_leaves=False,
                  steps=6):
        import optax

        cfg = SparseOptConfig(kind=kind, lr=0.1, **(cfgkw or {}))
        dim, cap = 4, 8
        init = jax.random.normal(jax.random.PRNGKey(0), (2, dim))
        table = jnp.zeros((cap, dim)).at[jnp.array([1, 5])].set(init)
        state = init_slot_state(cfg, cap, dim)
        # for LAMB the trust ratio is per-leaf in optax; an embedding row
        # is our "layer", so the reference treats each row as a leaf
        ref = ({"r0": init[0], "r1": init[1]} if per_row_leaves
               else init)
        ref_state = opt.init(ref)
        slots = jnp.array([1, 5], jnp.int32)
        for step in range(steps):
            g = jax.random.normal(jax.random.PRNGKey(step + 1), (2, dim))
            table, state = apply_sparse_update(cfg, table, state, slots, g)
            gg = ({"r0": g[0], "r1": g[1]} if per_row_leaves else g)
            up, ref_state = opt.update(gg, ref_state, ref)
            ref = optax.apply_updates(ref, up)
        got = np.asarray(table[slots])
        want = (np.stack([ref["r0"], ref["r1"]]) if per_row_leaves
                else np.asarray(ref))
        np.testing.assert_allclose(got, want, atol=1e-5)
        np.testing.assert_array_equal(np.asarray(table[0]), np.zeros(dim))

    def test_momentum_matches_optax(self):
        import optax

        self._vs_optax("momentum", optax.sgd(0.1, momentum=0.9))

    def test_nesterov_momentum_matches_optax(self):
        import optax

        self._vs_optax("momentum",
                       optax.sgd(0.1, momentum=0.9, nesterov=True),
                       {"nesterov": True})

    def test_adadelta_matches_optax(self):
        import optax

        self._vs_optax("adadelta", optax.adadelta(0.1, rho=0.95, eps=1e-8),
                       {"rho": 0.95, "eps": 1e-8})

    def test_adabelief_matches_optax(self):
        import optax
        from version_gates import optax_belief_uses_stale_mu

        if optax_belief_uses_stale_mu():
            pytest.xfail(
                "this optax's scale_by_belief computes the prediction "
                "error against the PRE-update EMA (optax 0.2.x); the "
                "sparse kernel follows the AdaBelief paper (post-update "
                "EMA) — exact tracking is impossible here (probed "
                "numerically, tests/version_gates.py)")
        self._vs_optax("adabelief",
                       optax.adabelief(0.1, eps=1e-8, eps_root=1e-8),
                       {"eps": 1e-8})

    def test_amsgrad_matches_optax(self):
        import optax

        self._vs_optax("amsgrad", optax.amsgrad(0.1, eps=1e-8),
                       {"eps": 1e-8})

    def test_lamb_matches_optax(self):
        import optax

        self._vs_optax("lamb", optax.lamb(0.1, eps=1e-8, weight_decay=0.01),
                       {"eps": 1e-8, "weight_decay": 0.01},
                       per_row_leaves=True)

    def test_adahessian_with_grad_hessian_equals_adam(self):
        """hessian=None degenerates to adam second moments (the documented
        fallback); a real Hutchinson estimate changes the denominator."""
        cfg_h = SparseOptConfig(kind="adahessian", lr=0.1)
        cfg_a = SparseOptConfig(kind="adam", lr=0.1)
        sh, sa = (init_slot_state(c, 4, 3) for c in (cfg_h, cfg_a))
        slots = jnp.array([1], jnp.int32)
        g = jnp.full((1, 3), 0.5)
        # fresh tables per call: apply_sparse_update donates its inputs
        th, _ = apply_sparse_update(cfg_h, jnp.ones((4, 3)), sh, slots, g)
        ta, _ = apply_sparse_update(cfg_a, jnp.ones((4, 3)), sa, slots, g)
        np.testing.assert_allclose(np.asarray(th), np.asarray(ta))
        # explicit hessian diverges from the grad fallback
        sh2 = init_slot_state(cfg_h, 4, 3)
        th2, _ = apply_sparse_update(cfg_h, jnp.ones((4, 3)), sh2, slots, g,
                                     hessian=jnp.full((1, 3), 2.0))
        assert not np.allclose(np.asarray(th2[1]), np.asarray(th[1]))

    @pytest.mark.parametrize("kind", ["group_lamb", "group_amsgrad",
                                      "group_adabelief", "group_momentum"])
    def test_group_variants_prune_rows(self, kind):
        cfg = SparseOptConfig(kind=kind, lr=0.5, l21=10.0)
        table = jnp.full((4, 3), 0.01)
        state = init_slot_state(cfg, 4, 3)
        slots = jnp.array([2], jnp.int32)
        g = jnp.full((1, 3), 1e-4)
        table, state = apply_sparse_update(cfg, table, state, slots, g)
        assert float(jnp.abs(table[2]).sum()) == 0.0  # whole row zeroed
        assert float(jnp.abs(table[1]).sum()) > 0.0   # untouched row kept

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown sparse optimizer"):
            init_slot_state(SparseOptConfig(kind="adamw"), 4, 2)


class TestKvEmbedding:
    def test_insert_or_default_and_growth(self):
        emb = KvEmbedding(dim=4, capacity=4, prefer_native=False)
        ids = np.arange(100, 110, dtype=np.int64)
        slots = emb.lookup_slots(ids)  # forces growth 4 → 16
        assert emb.capacity >= 11
        assert emb.vocab_size == 10
        rows = emb.gather(slots)
        assert rows.shape == (10, 4)
        # same ids → same rows
        slots2 = emb.lookup_slots(ids)
        np.testing.assert_array_equal(slots, slots2)

    def test_min_freq_filters_rare_ids(self):
        emb = KvEmbedding(dim=2, capacity=16, min_freq=2,
                          prefer_native=False)
        s1 = emb.lookup_slots(np.array([42], np.int64))
        assert s1[0] == 0  # first sight → null row
        s2 = emb.lookup_slots(np.array([42], np.int64))
        assert s2[0] != 0  # admitted at freq 2
        np.testing.assert_array_equal(np.asarray(emb.gather(s1)),
                                      np.zeros((1, 2)))

    def test_gradients_update_only_touched_rows(self):
        emb = KvEmbedding(dim=3, capacity=8,
                          optimizer=SparseOptConfig(kind="sgd", lr=1.0),
                          prefer_native=False)
        slots = emb.lookup_slots(np.array([5, 6], np.int64))
        before = np.asarray(emb.values).copy()
        emb.apply_gradients(slots, np.ones((2, 3), np.float32))
        after = np.asarray(emb.values)
        np.testing.assert_allclose(after[slots], before[slots] - 1.0,
                                   atol=1e-6)
        untouched = [i for i in range(8) if i not in slots.tolist()]
        np.testing.assert_array_equal(after[untouched], before[untouched])

    def test_full_and_delta_checkpoint_roundtrip(self, tmp_path):
        emb = KvEmbedding(dim=4, capacity=16, prefer_native=False,
                          optimizer=SparseOptConfig(kind="adam", lr=0.1))
        ids_a = np.array([1, 2, 3], np.int64)
        slots_a = emb.lookup_slots(ids_a)
        emb.apply_gradients(slots_a, np.ones((3, 4), np.float32))
        emb.save(str(tmp_path), delta=False)  # full snapshot

        ids_b = np.array([4, 5], np.int64)  # new ids after the full export
        slots_b = emb.lookup_slots(ids_b)
        emb.apply_gradients(slots_b, np.ones((2, 4), np.float32))
        emb.save(str(tmp_path), delta=True)  # delta on top

        fresh = KvEmbedding(dim=4, capacity=16, prefer_native=False,
                            optimizer=SparseOptConfig(kind="adam", lr=0.1))
        assert fresh.load(str(tmp_path))
        all_ids = np.concatenate([ids_a, ids_b])
        np.testing.assert_allclose(
            np.asarray(fresh.gather(fresh.lookup_slots(all_ids,
                                                       insert=False))),
            np.asarray(emb.gather(emb.lookup_slots(all_ids, insert=False))),
            atol=1e-6)
        # optimizer state restored too: next identical step matches
        emb.apply_gradients(slots_a, np.ones((3, 4), np.float32))
        fs = fresh.lookup_slots(ids_a, insert=False)
        fresh.apply_gradients(fs, np.ones((3, 4), np.float32))
        np.testing.assert_allclose(np.asarray(fresh.gather(fs)),
                                   np.asarray(emb.gather(slots_a)),
                                   atol=1e-6)

    def test_eviction_reinitializes_rows(self):
        emb = KvEmbedding(dim=2, capacity=8, prefer_native=False)
        slots = emb.lookup_slots(np.array([11], np.int64))
        emb.apply_gradients(slots, np.full((1, 2), 5.0, np.float32))
        trained = np.asarray(emb.gather(slots)).copy()
        n = emb.evict_older_than(1 << 31)  # everything is older
        assert n >= 1
        slots2 = emb.lookup_slots(np.array([999], np.int64))
        fresh_row = np.asarray(emb.gather(slots2))
        assert not np.allclose(fresh_row, trained)


class TestToyRecommendationModel:
    """End-to-end: CTR-style two-feature model trained with dynamic vocab,
    checkpointed (full + delta), restored, and verified convergent."""

    def _step(self, emb_u, emb_i, uids, iids, labels):
        us = emb_u.lookup_slots(uids)
        is_ = emb_i.lookup_slots(iids)

        def loss_fn(u_rows, i_rows):
            logits = jnp.sum(u_rows * i_rows, axis=-1)
            return jnp.mean(
                jnp.maximum(logits, 0) - logits * labels +
                jnp.log1p(jnp.exp(-jnp.abs(logits))))

        u_rows = jnp.asarray(emb_u.gather(us))
        i_rows = jnp.asarray(emb_i.gather(is_))
        loss, (gu, gi) = jax.value_and_grad(loss_fn, argnums=(0, 1))(
            u_rows, i_rows)
        emb_u.apply_gradients(us, gu)
        emb_i.apply_gradients(is_, gi)
        return float(loss)

    def test_train_grow_checkpoint_resume(self, tmp_path):
        rng = np.random.default_rng(0)
        opt = SparseOptConfig(kind="adam", lr=0.05)
        emb_u = KvEmbedding(dim=8, capacity=8, optimizer=opt, seed=1,
                            prefer_native=False)
        emb_i = KvEmbedding(dim=8, capacity=8, optimizer=opt, seed=2,
                            prefer_native=False)

        losses = []
        for step in range(30):
            # vocabulary grows over time: later steps see new ids
            hi = 10 + step * 2
            uids = rng.integers(0, hi, 16).astype(np.int64)
            iids = rng.integers(1000, 1000 + hi, 16).astype(np.int64)
            labels = ((uids % 3) == (iids % 3)).astype(np.float32)
            losses.append(self._step(emb_u, emb_i, uids, iids,
                                     jnp.asarray(labels)))
        assert np.mean(losses[-5:]) < np.mean(losses[:5])
        assert emb_u.capacity > 8  # grew past the initial capacity

        emb_u.save(str(tmp_path / "u"))
        emb_i.save(str(tmp_path / "i"))

        # restore and verify predictions match
        ru = KvEmbedding(dim=8, capacity=8, optimizer=opt,
                         prefer_native=False)
        ri = KvEmbedding(dim=8, capacity=8, optimizer=opt,
                         prefer_native=False)
        assert ru.load(str(tmp_path / "u")) and ri.load(str(tmp_path / "i"))
        uids = np.arange(10, dtype=np.int64)
        iids = np.arange(1000, 1010, dtype=np.int64)
        pred = lambda eu, ei: np.asarray(jnp.sum(  # noqa: E731
            jnp.asarray(eu.gather(eu.lookup_slots(uids, insert=False))) *
            jnp.asarray(ei.gather(ei.lookup_slots(iids, insert=False))),
            axis=-1))
        np.testing.assert_allclose(pred(ru, ri), pred(emb_u, emb_i),
                                   atol=1e-5)


class TestReviewInvariants:
    def test_null_row_never_trains(self):
        emb = KvEmbedding(dim=3, capacity=8, min_freq=2,
                          optimizer=SparseOptConfig(kind="sgd", lr=1.0),
                          prefer_native=False)
        slots = emb.lookup_slots(np.array([77], np.int64))  # filtered → 0
        assert slots[0] == 0
        emb.apply_gradients(slots, np.ones((1, 3), np.float32))
        np.testing.assert_array_equal(np.asarray(emb.values[0]),
                                      np.zeros(3))

    def test_eviction_preserves_null_row(self):
        emb = KvEmbedding(dim=2, capacity=8, prefer_native=False)
        emb.lookup_slots(np.array([5], np.int64))
        emb.evict_older_than(1 << 31)  # sweeps everything incl. sentinel
        assert emb.vocab_size == 0
        # null row still zero, and a new id must NOT land on slot 0
        s = emb.lookup_slots(np.array([123], np.int64))
        assert s[0] != 0
        np.testing.assert_array_equal(np.asarray(emb.values[0]), np.zeros(2))

    def test_growth_does_not_double_count_freq(self):
        emb = KvEmbedding(dim=2, capacity=3, min_freq=2,
                          prefer_native=False)
        # batch larger than capacity forces growth mid-batch; every id is
        # seen exactly once → all must still be filtered (freq 1 < 2)
        ids = np.arange(10, 20, dtype=np.int64)
        slots = emb.lookup_slots(ids)
        assert (slots == 0).all(), "single-sight ids must stay filtered"

    def test_import_removes_slot_from_free_list(self):
        store = create_kv_store(8, prefer_native=False)
        slots, _ = store.lookup_or_insert(np.array([1, 2], np.int64))
        store.evict_older_than(1 << 31)
        # re-import key 1 at its old slot, then insert a fresh key: it must
        # not be handed the imported slot
        store.import_(np.array([1], np.int64), slots[:1])
        s_new, _ = store.lookup_or_insert(np.array([99], np.int64))
        assert s_new[0] != slots[0]


class TestKvRemove:
    @pytest.mark.parametrize("store", _stores(),
                             ids=lambda s: type(s).__name__)
    def test_remove_recycles(self, store):
        slots, _ = store.lookup_or_insert(np.array([1, 2, 3], np.int64))
        assert store.remove(np.array([2], np.int64)) == 1
        assert store.lookup(np.array([2], np.int64))[0] == -1
        # the freed slot is handed to the next insert
        s_new, _ = store.lookup_or_insert(np.array([99], np.int64))
        assert s_new[0] == slots[1]


class TestHybridEmbedding:
    def test_spill_and_promote_roundtrip(self, tmp_path):
        from dlrover_wuqiong_tpu.embedding.hybrid import HybridKvEmbedding

        emb = HybridKvEmbedding(dim=4, max_hot_rows=8,
                                optimizer=SparseOptConfig(kind="sgd",
                                                          lr=1.0),
                                prefer_native=False)
        # train distinctive rows for the first ids
        ids_a = np.arange(1, 6, dtype=np.int64)
        slots_a = emb.lookup_slots(ids_a)
        grads = -np.eye(5, 4, dtype=np.float32)  # row i gets +e_i
        before = np.asarray(emb.gather(slots_a)).copy()
        emb.apply_gradients(slots_a, grads)

        # flood with new ids: capacity 8 forces demotion, not growth
        for step in range(6):
            emb.lookup_slots(np.arange(100 + step * 5, 105 + step * 5,
                                       dtype=np.int64))
        assert emb.capacity == 8  # hot tier never grew
        assert len(emb.overflow) > 0

        # the trained rows promote back with values + opt state intact
        slots_back = emb.lookup_slots(ids_a)
        after = np.asarray(emb.gather(slots_back))
        np.testing.assert_allclose(after, before + np.eye(5, 4), atol=1e-6)

    def test_disk_spill(self, tmp_path):
        from dlrover_wuqiong_tpu.embedding.hybrid import (
            HybridKvEmbedding,
            OverflowStore,
        )

        store = OverflowStore(3, ("m",), spill_dir=str(tmp_path))
        store.put(42, np.ones(3, np.float32), {"m": np.full(3, 2.0)})
        assert 42 in store
        entry = store.pop(42)
        np.testing.assert_array_equal(entry["value"], np.ones(3))
        np.testing.assert_array_equal(entry["m"], np.full(3, 2.0))
        assert 42 not in store


class TestHybridPromotionSemantics:
    def test_min_freq_promotion_never_loses_rows(self):
        """A demoted row re-seen under min_freq gating must survive even
        when the training lookup masks it to the null slot."""
        from dlrover_wuqiong_tpu.embedding.hybrid import HybridKvEmbedding

        emb = HybridKvEmbedding(dim=2, max_hot_rows=6, min_freq=2,
                                optimizer=SparseOptConfig(kind="sgd",
                                                          lr=1.0),
                                prefer_native=False)
        ids = np.array([5], np.int64)
        emb.lookup_slots(ids)           # freq 1 → masked
        slots = emb.lookup_slots(ids)   # freq 2 → admitted
        assert slots[0] != 0
        emb.apply_gradients(slots, np.full((1, 2), -3.0, np.float32))
        trained = np.asarray(emb.gather(slots)).copy()
        # flood to demote id 5
        for s in range(8):
            emb.lookup_slots(np.arange(100 + s * 4, 104 + s * 4,
                                       dtype=np.int64))
        # re-sight: promotion restores the trained row (freq restarts, so
        # the first sighting may mask — data must still be intact)
        emb.lookup_slots(ids)
        s2 = emb.lookup_slots(ids)
        got = np.asarray(emb.gather(s2))
        np.testing.assert_allclose(got, trained, atol=1e-6)

    def test_readonly_lookup_does_not_mutate(self):
        from dlrover_wuqiong_tpu.embedding.hybrid import HybridKvEmbedding

        emb = HybridKvEmbedding(dim=2, max_hot_rows=4, prefer_native=False)
        emb.lookup_slots(np.array([1], np.int64))
        for s in range(4):
            emb.lookup_slots(np.arange(50 + s * 3, 53 + s * 3,
                                       dtype=np.int64))
        held = len(emb.overflow)
        assert held > 0
        vocab = emb.vocab_size
        slots = emb.lookup_slots(np.array([1, 999], np.int64),
                                 insert=False)
        # spilled + unknown ids read the null row; nothing inserted or
        # promoted
        assert len(emb.overflow) == held
        assert emb.vocab_size == vocab

"""Perf observatory (telemetry/perf.py): executable keying, baseline
store durability, sentinel firing discipline, observatory self-limiting,
the BUFFERED latest-SENT-wins PerfSnapshotReport verb end to end
(master aggregation + /metrics gauges + the ONE op-profile source of
truth in diagnosis), policy decision-effect attribution, the flight
recorder embed, and the ADD-ONLY schema pins for every new surface.
"""

import json
import os
import subprocess
import sys
import time

import pytest

from dlrover_wuqiong_tpu.common import messages as msg
from dlrover_wuqiong_tpu.telemetry import reset_ledger, reset_recorder
from dlrover_wuqiong_tpu.telemetry.perf import (
    PERF_EVENT_KEYS,
    PERF_SCHEMA,
    PERF_SNAPSHOT_KEYS,
    BaselineStore,
    PerfObservatory,
    RegressionSentinel,
    executable_key,
    latest_snapshot,
    reset_observatory,
    set_observatory,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    reset_ledger()
    reset_recorder()
    reset_observatory()
    yield
    reset_ledger()
    reset_recorder()
    reset_observatory()


def _windows(sentinel, store, key, values, coll_frac=0.3, start=0):
    """Drive observe+update the way the observatory does (beyond-bound
    windows stay out of the baseline); returns fired events."""
    fired = []
    for i, v in enumerate(values):
        cats = {"matmul": v * (1 - coll_frac), "collective": v * coll_frac}
        beyond, event = sentinel.observe(key, v, cats, step=start + i)
        if not beyond:
            store.update(key, v, cats)
        if event is not None:
            fired.append(event)
    return fired


# ----------------------------------------------------------- executable key


class TestExecutableKey:
    def test_folds_identity_and_trace_env(self, monkeypatch):
        base = executable_key("fp", 8, "cpu")
        assert base == executable_key("fp", 8, "cpu")  # deterministic
        assert executable_key("fp2", 8, "cpu") != base
        assert executable_key("fp", 4, "cpu") != base
        assert executable_key("fp", 8, "tpu") != base
        # the same trace-env toggles that key the compile cache: a
        # DWT_FA_* flip is a DIFFERENT executable, never a regression
        monkeypatch.setenv("DWT_FA_NO_FUSED", "1")
        assert executable_key("fp", 8, "cpu") != base


# ------------------------------------------------------------ baseline store


class TestBaselineStore:
    def test_rolling_window_trims(self):
        st = BaselineStore()  # memory-only
        for i in range(100):
            st.update("k", float(i), {"matmul": float(i)})
        assert st.stats("k")["n"] == 64  # max_samples default
        # the oldest samples fell off: median over the surviving tail
        assert st.stats("k")["median"] > 60
        assert st.category_medians("k")["matmul"] > 60
        assert st.publish() is False  # no path → memory-only contract

    def test_aggregate_categories_sums_across_keys(self):
        # the autotuner's ordering hint (ROADMAP 4d): one coarse
        # op-category profile over EVERY executable key
        st = BaselineStore()
        assert st.aggregate_categories() == {}
        for v in (1.0, 1.0, 1.0):
            st.update("k1", v, {"matmul": 0.8, "collective": 0.1})
        for v in (2.0, 2.0, 2.0):
            st.update("k2", v, {"matmul": 0.2, "host": 0.05})
        agg = st.aggregate_categories()
        assert agg["matmul"] == pytest.approx(1.0)  # 0.8 + 0.2
        assert agg["collective"] == pytest.approx(0.1)
        assert agg["host"] == pytest.approx(0.05)

    def test_atomic_publish_and_reload(self, tmp_path):
        path = str(tmp_path / "perf" / "baseline.json")
        st = BaselineStore(path)
        for v in (0.1, 0.11, 0.09):
            st.update("k", v, {"collective": v / 2})
        assert st.publish() is True
        assert not [n for n in os.listdir(tmp_path / "perf")
                    if ".tmp." in n], "tmp file leaked past os.replace"
        st2 = BaselineStore(path)
        assert st2.stats("k") == st.stats("k")
        assert st2.category_medians("k") == st.category_medians("k")

    def test_corrupt_baseline_relearned_not_fatal(self, tmp_path):
        path = str(tmp_path / "baseline.json")
        with open(path, "w") as f:
            f.write('{"schema": 1, "keys": TORN')
        st = BaselineStore(path)
        assert st.stats("k") is None  # fresh, no crash
        st.update("k", 0.1)
        assert st.publish() is True
        assert json.load(open(path))["keys"]["k"]["step_s"] == [0.1]


# -------------------------------------------------------- regression sentinel


class TestRegressionSentinel:
    def test_quiet_tunnel_noise_never_fires(self):
        import random

        st = BaselineStore()
        sen = RegressionSentinel(st, m_consecutive=3)
        rng = random.Random(7)
        vals = [0.1 * (1 + 0.1 * (rng.random() * 2 - 1))
                for _ in range(40)]  # the documented ±10% chip drift
        assert _windows(sen, st, "k", vals) == []

    def test_fires_exactly_once_at_m_consecutive(self):
        st = BaselineStore()
        sen = RegressionSentinel(st, m_consecutive=3)
        _windows(sen, st, "k", [0.1] * 8)
        fired = _windows(sen, st, "k", [0.16] * 7, coll_frac=0.6,
                         start=100)
        assert len(fired) == 1
        ev = fired[0]
        assert ev["kind"] == "perf-regression"
        assert ev["consecutive"] == 3
        assert ev["step"] == 102  # third beyond-bound window
        assert tuple(sorted(ev)) == tuple(sorted(PERF_EVENT_KEYS))

    def test_streak_resets_on_recovery(self):
        st = BaselineStore()
        sen = RegressionSentinel(st, m_consecutive=3)
        _windows(sen, st, "k", [0.1] * 8)
        # two slow, one normal, two slow: never 3 consecutive → no fire
        fired = _windows(sen, st, "k", [0.2, 0.2, 0.1, 0.2, 0.2],
                         start=100)
        assert fired == []

    def test_needs_min_baseline(self):
        st = BaselineStore()
        sen = RegressionSentinel(st, m_consecutive=1, min_baseline=5)
        # 4 samples then an excursion: below min_baseline → silent
        _windows(sen, st, "k", [0.1] * 4)
        assert _windows(sen, st, "k", [9.9], start=50) == []

    def test_attributes_the_moved_category(self):
        st = BaselineStore()
        sen = RegressionSentinel(st, m_consecutive=1)
        for _ in range(8):
            st.update("k", 0.1, {"matmul": 0.07, "collective": 0.03})
        _, ev = sen.observe(
            "k", 0.16, {"matmul": 0.07, "collective": 0.09}, step=9)
        assert ev is not None
        assert ev["category"] == "collective"
        assert ev["category_delta_s"] == pytest.approx(0.06)

    def test_regression_does_not_poison_baseline(self):
        st = BaselineStore()
        sen = RegressionSentinel(st, m_consecutive=3)
        _windows(sen, st, "k", [0.1] * 8)
        med_before = st.stats("k")["median"]
        _windows(sen, st, "k", [0.2] * 10, start=100)
        # sustained-slow windows are beyond bound → excluded: the old
        # normal survives and the NEXT excursion still measures against it
        assert st.stats("k")["median"] == med_before


# ------------------------------------------------------------- observatory


class TestPerfObservatory:
    def test_cadence_and_self_limit(self, tmp_path):
        obs = PerfObservatory(key="k", ckpt_dir=str(tmp_path), every=3)
        obs._t_start -= 1e6  # long-running job: overhead fully amortized
        opened = []
        for step in range(0, 90, 10):
            win = obs.maybe_open(step, 1)
            if win is not None:
                obs.close(win)
                opened.append(step)
        assert opened == [0, 30, 60]  # every 3rd eligible boundary
        # overhead beyond budget: next eligible boundary is SKIPPED and
        # accounted, not silently dropped
        obs._overhead_s = 1e9
        assert obs.maybe_open(90, 1) is None
        assert obs.snapshot()["windows"] == 3
        snap_skips = obs._skipped
        assert snap_skips == 1

    def test_snapshot_shape_and_ledger_credit(self, tmp_path):
        from dlrover_wuqiong_tpu.telemetry import get_ledger

        get_ledger().start()
        obs = PerfObservatory(key="k", ckpt_dir=str(tmp_path), every=1)
        win = obs.maybe_open(8, 4)
        assert win is not None
        snap = obs.close(win)
        assert tuple(sorted(snap)) == tuple(sorted(PERF_SNAPSHOT_KEYS))
        assert snap["schema"] == PERF_SCHEMA
        assert snap["fused_k"] == 4 and snap["step"] == 8
        assert snap["windows"] == 1
        # window overhead is ledger-credited to the "profile" state
        assert get_ledger().snapshot()["states"]["profile"] > 0.0
        # baseline landed on disk atomically
        assert os.path.isfile(
            os.path.join(str(tmp_path), "perf", "baseline.json"))
        assert latest_snapshot() is None  # singleton not set here
        set_observatory(obs)
        assert latest_snapshot() is snap

    def test_retrace_event_from_cache_miss_growth(self, tmp_path):
        from dlrover_wuqiong_tpu.auto.compile_cache import counters

        events = []
        obs = PerfObservatory(key="k", ckpt_dir=str(tmp_path), every=1,
                              on_event=events.append)
        obs._t_start -= 1e6  # long-running job: overhead fully amortized
        w = obs.maybe_open(0, 1)
        obs.close(w)  # first window: seeds the counter snapshot, no event
        assert events == []
        before = counters.misses
        try:
            counters.misses += 2  # a steady-state retrace storm
            w = obs.maybe_open(8, 1)
            obs.close(w)
        finally:
            counters.misses = before
        kinds = [e["kind"] for e in events]
        assert kinds == ["retrace"]
        assert events[0]["consecutive"] == 2  # miss delta
        assert events[0]["category"] == "compile"
        assert tuple(sorted(events[0])) == tuple(sorted(PERF_EVENT_KEYS))
        assert obs.snapshot()["retraces"] == 2

    def test_on_event_failure_never_propagates(self):
        def boom(event):
            raise RuntimeError("operator wiring bug")

        obs = PerfObservatory(key="k", every=1, on_event=boom)
        event = {k: 0 for k in PERF_EVENT_KEYS}
        event["kind"] = "perf-regression"
        obs._fire(dict(event))  # must not raise through the fire path
        assert obs._last_event["kind"] == "perf-regression"


# ----------------------------------------------- master round-trip + policy


class TestPerfVerbRoundTrip:
    def test_report_to_summary_metrics_and_diagnosis(self):
        """report_perf_snapshot → servicer → latest-SENT-wins aggregation
        → PerfSummary + dwt_perf_* gauges + the diagnosis op-profile
        store (ONE source of truth for the op-category split)."""
        from dlrover_wuqiong_tpu.agent.master_client import MasterClient
        from dlrover_wuqiong_tpu.master.master import JobMaster

        master = JobMaster(min_nodes=1, max_nodes=1)
        master.prepare()
        try:
            mc = MasterClient(master.addr, node_id=0)
            snap = {"schema": PERF_SCHEMA, "key": "k", "step": 80,
                    "step_time_s": 0.12, "baseline_median_s": 0.1,
                    "overhead_frac": 0.004, "regressions": 1,
                    "retraces": 2,
                    "categories": {"matmul": 0.08, "collective": 0.04},
                    "captured_at": time.time()}
            mc.report_perf_snapshot(snap)
            summary = mc.get_perf_summary()
            assert summary.nodes == 1
            assert summary.regressions == 1 and summary.retraces == 2
            assert summary.snapshots["0"]["step_time_s"] == \
                pytest.approx(0.12)
            rendered = master.metric_collector.reg.render()
            assert "dwt_perf_step_seconds" in rendered
            assert "dwt_perf_baseline_median_seconds" in rendered
            assert "dwt_perf_overhead_fraction" in rendered
            # satellite: the snapshot's category split IS the op-profile
            # evidence hang resolution reads — no second source of truth
            prof = master.diagnosis_manager.data.node_op_profile(0)
            assert prof is not None
            evidence = json.loads(prof)
            assert evidence["source"] == "perf_snapshot"
            assert evidence["categories"]["collective"] == \
                pytest.approx(0.04)
            assert tuple(sorted(evidence)) == tuple(sorted(
                master.diagnosis_manager.data.PERF_EVIDENCE_KEYS))
            mc.close()
        finally:
            master.stop()

    def test_latest_sent_wins_not_latest_received(self):
        """A delayed buffered flush must never clobber a fresher snapshot
        (the drain-ordering hazard every buffered verb shares)."""
        from dlrover_wuqiong_tpu.master.master import JobMaster

        master = JobMaster(min_nodes=1, max_nodes=1)
        # no prepare(): collect_perf is exercised in-process
        fresh = msg.PerfSnapshotReport(
            node_id=0, snapshot={"step": 100, "step_time_s": 0.1},
            sent_at=200.0)
        stale = msg.PerfSnapshotReport(
            node_id=0, snapshot={"step": 50, "step_time_s": 0.5},
            sent_at=100.0)
        master.collect_perf(fresh)
        master.collect_perf(stale)  # arrives later, SENT earlier
        assert master.perf_summary().snapshots["0"]["step"] == 100

    def test_policy_tick_feeds_observe_perf(self):
        """The master's policy loop hands the perf aggregation to the
        engine; decision_effect exposes measured before/after."""
        from dlrover_wuqiong_tpu.brain.policy import (
            PolicyConfig,
            PolicyEngine,
        )

        eng = PolicyEngine(PolicyConfig())
        eng.observe_perf({"step_time_s": {"0": 0.10}, "regressions": 0,
                          "retraces": 0, "nodes": 1})
        assert eng.decision_effect() == {}  # no decision yet
        d = eng.maybe_decide()
        assert d is not None
        assert eng.decision_effect() == {}  # before frozen, no after yet
        eng.observe_perf({"step_time_s": {"0": 0.16}, "regressions": 1,
                          "retraces": 0, "nodes": 1})
        effect = eng.decision_effect()
        assert effect["decision_id"] == d.decision_id
        assert effect["before"]["step_time_s"]["0"] == 0.10
        assert effect["after"]["regressions"] == 1

    def test_note_emitted_replay_does_not_double_freeze(self):
        """Journal replay routes the SAME decision through note_emitted;
        the before-side frozen at maybe_decide must survive."""
        from dlrover_wuqiong_tpu.brain.policy import (
            PolicyConfig,
            PolicyEngine,
        )

        eng = PolicyEngine(PolicyConfig())
        eng.observe_perf({"nodes": 1, "tag": "before"})
        d = eng.maybe_decide()
        eng.observe_perf({"nodes": 1, "tag": "after"})
        eng.note_emitted(d)  # master's _apply_policy path: same object
        assert eng.decision_effect()["before"]["tag"] == "before"


# -------------------------------------------------------- recorder + CLI


class TestFlightEmbedAndReportCli:
    def test_flight_dump_embeds_latest_snapshot(self, tmp_path):
        from dlrover_wuqiong_tpu.telemetry import (
            get_recorder,
            load_flight_dumps,
        )

        obs = PerfObservatory(key="k", every=1)
        obs._snapshot = {"schema": PERF_SCHEMA, "key": "k", "step": 8,
                         "step_time_s": 0.1}
        set_observatory(obs)
        get_recorder().record("mark", "m", {})
        assert get_recorder().flush(str(tmp_path), "test") is not None
        dumps = load_flight_dumps(str(tmp_path))
        assert dumps and dumps[0]["perf"]["step"] == 8

    def test_perf_report_baseline_and_rc_contract(self, tmp_path):
        st = BaselineStore(str(tmp_path / "perf" / "baseline.json"))
        for v in (0.1, 0.11, 0.09):
            st.update("kk", v, {"matmul": v})
        assert st.publish()
        cli = os.path.join(REPO, "tools", "perf_report.py")
        env = {k: v for k, v in os.environ.items()
               if k != "DWT_MASTER_ADDR"}
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        p = subprocess.run(
            [sys.executable, cli, "--baseline", str(tmp_path)],
            capture_output=True, text=True, env=env, timeout=120)
        assert p.returncode == 0, p.stdout + p.stderr
        lines = p.stdout.strip().splitlines()
        assert len(lines) == 1
        report = json.loads(lines[0])
        assert report["source"] == "baseline"
        assert report["keys"]["kk"]["n"] == 3
        assert report["keys"]["kk"]["median_s"] == pytest.approx(0.1)
        # live query with no address: rc=2 + error line
        p = subprocess.run([sys.executable, cli], capture_output=True,
                           text=True, env=env, timeout=120)
        assert p.returncode == 2
        assert "error" in json.loads(p.stdout)

    def test_perf_report_flight_mode(self, tmp_path):
        from dlrover_wuqiong_tpu.telemetry import get_recorder

        obs = PerfObservatory(key="k", every=1)
        obs._snapshot = {"schema": PERF_SCHEMA, "key": "k", "step": 8,
                         "step_time_s": 0.1, "regressions": 2,
                         "retraces": 1}
        set_observatory(obs)
        get_recorder().flush(str(tmp_path), "test")
        cli = os.path.join(REPO, "tools", "perf_report.py")
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        p = subprocess.run(
            [sys.executable, cli, "--flight", str(tmp_path)],
            capture_output=True, text=True, env=env, timeout=120)
        assert p.returncode == 0, p.stdout + p.stderr
        report = json.loads(p.stdout)
        assert report["source"] == "flight"
        assert report["nodes"] == 1
        assert report["regressions"] == 2 and report["retraces"] == 1
        (snap,) = report["snapshots"].values()
        assert snap["step"] == 8


# ------------------------------------------------------- compile counters


class TestCompileCacheMetricsExport:
    def test_listener_mirrors_into_registry(self):
        """Satellite: the XLA cache listeners export
        dwt_compile_cache_hits/misses through the shared MetricRegistry —
        the same stream counters.snapshot() feeds the retrace watcher."""
        import dlrover_wuqiong_tpu.auto.compile_cache as cc
        from dlrover_wuqiong_tpu.master.metrics import get_registry

        # reach the installed listeners exactly as jax monitoring does
        # (idempotent install — never register a duplicate pair, which
        # would double-count for the rest of the process)
        try:
            from jax._src import monitoring
        except ImportError:
            pytest.skip("jax monitoring API unavailable")
        before_h, before_m = cc.counters.snapshot()
        cc._install_listeners()
        monitoring.record_event("/jax/compilation_cache/cache_hits")
        monitoring.record_event("/jax/compilation_cache/cache_misses")
        monitoring.record_event_duration_secs(
            "/jax/compilation_cache/compile_time_saved_sec", 1.5)
        assert cc.counters.snapshot() == (before_h + 1, before_m + 1)
        assert cc.counters.time_saved_s >= 1.5
        rendered = get_registry().render()
        assert "dwt_compile_cache_hits" in rendered
        assert "dwt_compile_cache_misses" in rendered
        assert "dwt_compile_cache_time_saved_seconds" in rendered


# ------------------------------------------------------------ schema pins


class TestAddOnlySchemas:
    # ADD-ONLY: every consumer (flight dumps, PerfSnapshotReport,
    # tools/perf_report.py, incident timeline) keys into these dicts —
    # extend the tuples, never rename or remove members.  Pin source of
    # truth: the committed wire-surface lockfile (analysis/
    # schema.lock.json, gated by graftlint's schema engine) — only the
    # canaries are hand-pinned.  PERF_EVIDENCE_KEYS is a diagnosis-
    # internal surface (not on the wire), so it stays fully hand-pinned.
    PINNED_EVIDENCE = {"source", "step", "key", "step_time_s",
                       "categories"}

    def test_snapshot_keys_add_only(self, schema_lock):
        locked = set(schema_lock["registries"]["PERF_SNAPSHOT_KEYS"])
        assert locked.issubset(set(PERF_SNAPSHOT_KEYS))
        assert "step_time_s" in PERF_SNAPSHOT_KEYS   # hand-pinned canary

    def test_event_keys_add_only(self, schema_lock):
        locked = set(schema_lock["registries"]["PERF_EVENT_KEYS"])
        assert locked.issubset(set(PERF_EVENT_KEYS))
        assert "deviation" in PERF_EVENT_KEYS   # hand-pinned canary

    def test_diagnosis_evidence_keys_add_only(self):
        from dlrover_wuqiong_tpu.diagnosis.manager import (
            DiagnosisDataManager,
        )

        assert self.PINNED_EVIDENCE.issubset(
            set(DiagnosisDataManager.PERF_EVIDENCE_KEYS))

    def test_message_family_add_only(self):
        import dataclasses

        assert {"node_id", "snapshot", "sent_at"}.issubset(
            {f.name for f in dataclasses.fields(msg.PerfSnapshotReport)})
        assert {"snapshots", "regressions", "retraces", "nodes"}.issubset(
            {f.name for f in dataclasses.fields(msg.PerfSummary)})
        # PerfQuery stays constructible with no arguments forever
        msg.PerfQuery()

    def test_perf_verbs_buffered_never_journaled(self):
        """Protocol invariant: PerfSnapshotReport is pure telemetry —
        lossy by design, so it must stay OUT of the journaled/idempotent
        verb sets (a journaled perf stream would bloat replay)."""
        from dlrover_wuqiong_tpu.analysis.protocol_engine import (
            IDEM_VERBS,
            JOURNALED_VERBS,
        )

        assert "PerfSnapshotReport" not in JOURNALED_VERBS
        assert "PerfSnapshotReport" not in IDEM_VERBS

    def test_profile_state_in_ledger(self):
        from dlrover_wuqiong_tpu.telemetry import LEDGER_STATES

        assert "profile" in LEDGER_STATES


# ------------------------------------------------------ trainer integration


class TestTrainerWindows:
    def test_train_loop_opens_windows_and_publishes_baseline(
            self, tmp_path):
        """End to end on the real Trainer: windows open at logging
        boundaries (the boundary that carries the ONE readback), the
        snapshot folds the executable key, and the baseline store lands
        under $ckpt_dir/perf/."""
        import dataclasses

        import jax.numpy as jnp
        import numpy as np

        from dlrover_wuqiong_tpu.models.gpt import GPT, GPTConfig
        from dlrover_wuqiong_tpu.telemetry import get_ledger
        from dlrover_wuqiong_tpu.telemetry.perf import get_observatory
        from dlrover_wuqiong_tpu.trainer.trainer import (
            Trainer,
            TrainingArgs,
        )

        def data(step, batch=8, seq=32, vocab=512):
            rng = np.random.default_rng(step % 4)
            x = rng.integers(0, vocab, (batch, seq + 1))
            return {"input_ids": x[:, :-1], "labels": x[:, 1:]}

        args = TrainingArgs(
            output_dir=str(tmp_path), max_steps=6, seq_len=32,
            global_batch_size=8, warmup_steps=1, logging_steps=2,
            save_steps=0, save_on_exit=False, fused_steps=1,
            strategy=[("fsdp", {})], perf_window_every=1)
        model = GPT(dataclasses.replace(
            GPTConfig.nano(), dtype=jnp.float32,
            use_flash_attention=False, remat=False))
        tr = Trainer(model, args, data)
        try:
            tr.train()
        finally:
            tr.ckpt.close()
        obs = get_observatory()
        assert obs is tr._perf
        snap = obs.snapshot()
        assert snap is not None and snap["windows"] >= 1
        assert len(snap["key"]) == 16  # executable_key digest
        assert snap["fused_k"] == 1
        assert snap["step_time_s"] > 0.0
        assert os.path.isfile(os.path.join(
            str(tmp_path), "checkpoints", "perf", "baseline.json"))
        # window overhead was ledger-credited, never a new readback
        assert get_ledger().snapshot()["states"]["profile"] > 0.0

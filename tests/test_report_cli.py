"""Direct unit tests for common/report_cli.py — the shared one-line-JSON
contract every tools/ report CLI rides (goodput/policy/serve/incident/
perf/warm/perf_probe).  Pins the rc semantics and the exactly-one-stdout-
line invariant in EVERY path, so a tool migration can't silently bend
the driver-facing contract.
"""

import json
import os

import pytest

from dlrover_wuqiong_tpu.common.report_cli import (
    parse_value_flags,
    run_report,
)

DOC = "tool docstring for -h"
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lines(capsys):
    out, err = capsys.readouterr()
    return out.splitlines(), err


class TestParseValueFlags:
    def test_pairs_and_help(self):
        vals = parse_value_flags(
            ["--flight", "/d", "-h", "--addr", "h:1"],
            ("--flight", "--addr"))
        assert vals == {"--flight": "/d", "--help": "-h",
                        "--addr": "h:1"}

    def test_unknown_args_tolerated(self):
        # historical manual loops ignored positionals/unknown flags —
        # the shared parser must too (warm_report's positional cache_dir)
        assert parse_value_flags(["pos", "--nope", "x"], ("--addr",)) == {}

    def test_flag_missing_value_is_none(self):
        assert parse_value_flags(["--addr"], ("--addr",)) == \
            {"--addr": None}


class TestRunReportContract:
    def test_help_goes_to_stderr_rc0(self, capsys):
        rc = run_report(["-h"], DOC,
                        offline=lambda v: {"never": True},
                        live=lambda a, v: {"never": True},
                        no_addr_error="no addr")
        out, err = _lines(capsys)
        assert rc == 0
        assert out == []  # stdout stays machine-parseable
        assert DOC in err

    def test_offline_success_one_json_line(self, capsys):
        rc = run_report(["--src", "x"], DOC,
                        offline=lambda v: {"src": v.get("--src")},
                        live=lambda a, v: {"never": True},
                        no_addr_error="no addr",
                        value_flags=("--src",))
        out, _ = _lines(capsys)
        assert rc == 0
        assert len(out) == 1
        assert json.loads(out[0]) == {"src": "x"}

    def test_live_success_uses_addr_flag(self, capsys, monkeypatch):
        monkeypatch.delenv("DWT_MASTER_ADDR", raising=False)
        rc = run_report(["--addr", "h:9"], DOC,
                        offline=lambda v: None,
                        live=lambda addr, v: {"addr": addr},
                        no_addr_error="no addr")
        out, _ = _lines(capsys)
        assert rc == 0
        assert len(out) == 1
        assert json.loads(out[0]) == {"addr": "h:9"}

    def test_live_addr_from_env(self, capsys, monkeypatch):
        monkeypatch.setenv("DWT_MASTER_ADDR", "envhost:7")
        rc = run_report([], DOC,
                        offline=lambda v: None,
                        live=lambda addr, v: {"addr": addr},
                        no_addr_error="no addr")
        out, _ = _lines(capsys)
        assert rc == 0
        assert json.loads(out[0]) == {"addr": "envhost:7"}

    def test_no_addr_rc2_with_error_line(self, capsys, monkeypatch):
        monkeypatch.delenv("DWT_MASTER_ADDR", raising=False)
        rc = run_report([], DOC,
                        offline=lambda v: None,
                        live=lambda a, v: {"never": True},
                        no_addr_error="pass --addr or set env")
        out, _ = _lines(capsys)
        assert rc == 2
        assert len(out) == 1
        assert json.loads(out[0]) == {"error": "pass --addr or set env"}

    @pytest.mark.parametrize("which", ["offline", "live"])
    def test_failure_rc1_error_line_never_traceback(self, which, capsys,
                                                    monkeypatch):
        monkeypatch.setenv("DWT_MASTER_ADDR", "h:1")

        def blow(*a, **k):
            raise FileNotFoundError("/missing/dir")

        rc = run_report([], DOC,
                        offline=blow if which == "offline"
                        else lambda v: None,
                        live=blow if which == "live"
                        else lambda a, v: {},
                        no_addr_error="no addr")
        out, err = _lines(capsys)
        assert rc == 1
        assert len(out) == 1  # ONE parseable line, no traceback on stdout
        line = json.loads(out[0])
        assert "/missing/dir" in line["error"]
        assert "Traceback" not in out[0]

    def test_error_repr_truncated(self, capsys, monkeypatch):
        monkeypatch.setenv("DWT_MASTER_ADDR", "h:1")
        rc = run_report([], DOC,
                        offline=lambda v: (_ for _ in ()).throw(
                            ValueError("x" * 5000)),
                        live=lambda a, v: {},
                        no_addr_error="no addr")
        out, _ = _lines(capsys)
        assert rc == 1
        assert len(json.loads(out[0])["error"]) <= 500

    def test_argv_none_reads_sys_argv(self, capsys, monkeypatch):
        monkeypatch.setattr("sys.argv", ["tool", "-h"])
        rc = run_report(None, DOC,
                        offline=lambda v: {"never": True},
                        live=lambda a, v: {"never": True},
                        no_addr_error="no addr")
        out, err = _lines(capsys)
        assert rc == 0 and out == [] and DOC in err


class TestMigratedProbeTool:
    def test_perf_probe_streams_lines_then_summary(self, capsys,
                                                   monkeypatch):
        """tools/perf_probe.py after the run_report migration: the
        historical per-probe JSON lines still stream, and the FINAL line
        is the contract summary folding every emitted record."""
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "perf_probe_tool", os.path.join(REPO, "tools",
                                            "perf_probe.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        monkeypatch.setitem(
            mod.ALL, "fake",
            lambda: mod._emit("fake", 0.001, note="x"))
        rc = mod.main(["fake"])
        out = capsys.readouterr().out.splitlines()
        assert rc == 0
        assert len(out) == 2  # one per-probe line + ONE summary line
        assert json.loads(out[0]) == {"probe": "fake", "ms": 1.0,
                                      "note": "x"}
        summary = json.loads(out[1])
        assert summary["emitted"] == 1
        assert summary["probes"] == [json.loads(out[0])]

"""Chaos scenarios as CI tests (docs/tech_report/fault_tolerance_exps.md
parity: pod delete / straggler / network break with recovery invariants).
"""

import pytest

from dlrover_wuqiong_tpu import chaos


def test_pod_kill_recovers_with_goodput():
    report = chaos.pod_kill()
    assert report["ok"], report
    assert report["restarts"] == 1
    assert 0 < report["resume_step"] <= 9
    assert report["ckpt_intact"]
    assert report["goodput"] >= 0.8


def test_straggler_is_localized():
    report = chaos.straggler()
    assert report["ok"], report
    assert report["network_check_stragglers"] == [3]
    assert report["runtime_stragglers"] == [3]


def test_network_partition_relaunches_silent_node():
    report = chaos.network_partition()
    assert report["ok"], report
    assert report["dead_detected"] == [1]


def test_cli_runs_all(capsys):
    rc = chaos.main(["straggler", "network-partition"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 2


def test_cli_unknown_scenario():
    assert chaos.main(["bogus"]) == 2

"""Chaos scenarios as CI tests (docs/tech_report/fault_tolerance_exps.md
parity: pod delete / straggler / network break with recovery invariants).
"""

import pytest

from dlrover_wuqiong_tpu import chaos


def test_pod_kill_recovers_with_goodput():
    report = chaos.pod_kill()
    assert report["ok"], report
    assert report["restarts"] == 1
    assert 0 < report["resume_step"] <= 9
    assert report["ckpt_intact"]
    assert report["goodput"] >= 0.8


def test_straggler_is_localized():
    report = chaos.straggler()
    assert report["ok"], report
    assert report["network_check_stragglers"] == [3]
    assert report["runtime_stragglers"] == [3]


def test_network_partition_relaunches_silent_node():
    report = chaos.network_partition()
    assert report["ok"], report
    assert report["dead_detected"] == [1]


def test_ckpt_corrupt_zero_silent_restores():
    """Checkpoint trust boundary (ISSUE 5): the full corruption fault
    matrix — flipped bytes in shm/replica/storage, truncated shard,
    missing manifest, stale-generation-only, SIGKILL mid-persist — with
    zero silent restores, best-healthy-tier selection, bit-identical
    resume, and self-heal after every degraded restore."""
    report = chaos.ckpt_corrupt()
    assert report["ok"], report
    assert report["silent_restores"] == 0
    assert len(report["cases"]) == 7
    # every corrupt-fault case both detected the fault AND healed
    for case in report["cases"]:
        assert case["bit_identical"], case
    assert report["doctor"]["flagged_steps"] == [4]
    # telemetry contract: a degraded restore reconstructs as ONE trace
    # tree (ckpt:restore root + >1 tier children) from the flight dump,
    # and the goodput ledger carries the per-tier restore credits
    assert report["flight"]["dumps"] >= 1, report["flight"]
    assert report["flight"]["degraded_trace_trees"] >= 1, report["flight"]
    assert report["flight"]["ledger"]["restore_replica"] > 0
    assert report["flight"]["ledger"]["restore_storage"] > 0


def test_cli_policy_prior_flag(capsys, monkeypatch):
    """`--policy-prior PATH` routes to preempt-adaptive ONLY (other
    scenarios keep their zero-arg contract) and both `--policy-prior P`
    and `--policy-prior=P` spellings parse."""
    seen = {}

    def fake_adaptive(policy_prior=""):
        seen["prior"] = policy_prior
        return {"scenario": "preempt-adaptive", "ok": True}

    monkeypatch.setitem(chaos.SCENARIOS, "preempt-adaptive", fake_adaptive)
    monkeypatch.setitem(chaos.SCENARIOS, "straggler",
                        lambda: {"scenario": "straggler", "ok": True})
    rc = chaos.main(["preempt-adaptive", "--policy-prior", "/tmp/p.json"])
    assert rc == 0 and seen["prior"] == "/tmp/p.json"
    rc = chaos.main(["preempt-adaptive", "--policy-prior=/x.json"])
    assert rc == 0 and seen["prior"] == "/x.json"
    # the flag must not leak into the scenario name list
    rc = chaos.main(["straggler", "--policy-prior", "/tmp/p.json"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 3


def test_cli_runs_all(capsys):
    rc = chaos.main(["straggler", "network-partition"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 2


def test_cli_unknown_scenario():
    assert chaos.main(["bogus"]) == 2


def test_preempt_goodput_at_tuned_interval():
    """r4 verdict weak #3: the goodput story must meet the >=0.95 north
    star under RANDOMIZED repeated kills, with ckpt cadence as the lever.
    Flash per-step staging + agent save-on-failure makes the loss per
    kill interval-independent — goodput (step accounting) >= 0.95."""
    from dlrover_wuqiong_tpu.chaos import preempt

    r = preempt(total_steps=300, dt=0.05, ckpt_interval=50, kills=2,
                seed=3, flash=True, target=0.95)
    assert r["ok"], r
    assert r["goodput"] >= 0.95, r
    assert len(r["kills"]) == 2, r
    # the downtime split is GOODPUT-LEDGER-derived: one cumulative
    # snapshot per worker generation, summed by the drill
    assert r["ledger"]["generations"] == len(r["kills"]) + 1, r["ledger"]
    assert r["ledger"]["states"]["productive"] > 0, r["ledger"]
    assert r["downtime"]["restarts"] == len(r["kills"]), r["downtime"]


@pytest.mark.slow  # tier-2: ~37s wall-clock goodput drill; preempt goodput
# is tier-1 via test_preempt_goodput_at_tuned_interval and fused-boundary
# equivalence via test_fused_steps
def test_preempt_fused_boundaries_keep_goodput():
    """Fused K-step dispatch (ISSUE 3): shm staging, disk saves and
    recovery fire at fusion boundaries ONLY, quantizing the loss per
    kill to at most K-1 steps — the goodput north star must still hold
    and the resume step must be a fusion boundary."""
    from dlrover_wuqiong_tpu.chaos import preempt

    k = 5
    r = preempt(total_steps=300, dt=0.05, ckpt_interval=50, kills=2,
                seed=3, flash=True, target=0.95, fused_steps=k)
    assert r["ok"], r
    assert r["fused_steps"] == k
    assert r["goodput"] >= 0.95, r
    assert len(r["kills"]) == 2, r
    # boundary-quantized recovery: every generation resumed at a step
    # the fused driver could actually have committed (a multiple of K,
    # since staging happens at block boundaries)
    # (start_step recorded per generation in the timing markers)
    # rework bounded: each kill loses < K staged + re-executed tail
    assert r["wasted_steps"] <= 2 * (k + 1), r
    """The inverse direction pins the metric is real: a sparse disk-only
    cadence must SHOW the re-execution loss after a kill."""
    from dlrover_wuqiong_tpu.chaos import preempt

    r = preempt(total_steps=200, dt=0.05, ckpt_interval=150, kills=1,
                seed=5, flash=False, target=0.0)
    assert r["completed"], r
    assert r["wasted_steps"] > 10, r
    assert r["goodput"] < 0.95, r


def test_preempt_table_persists_policy_prior(tmp_path, monkeypatch):
    """The curve is the adaptive engine's offline prior: rows land
    atomically in out_dir/policy/preempt_table.json and load_prior can
    calibrate from the file as written (drills stubbed for speed)."""
    def fake_preempt(**kw):
        return {"goodput": 0.9 + kw["ckpt_interval"] / 1e4,
                "wasted_steps": 3, "completed": True,
                "kills": [{"gen": 1}, {"gen": 2}],
                "downtime": {"restarts": 2}}

    monkeypatch.setattr(chaos, "preempt", fake_preempt)
    report = chaos.preempt_table(total_steps=10, dt=0.05, kills=2,
                                 out_dir=str(tmp_path))
    assert report["ok"], report
    assert report["table_path"] == str(
        tmp_path / "policy" / "preempt_table.json")
    import json

    with open(report["table_path"]) as f:
        table = json.load(f)
    assert table["dt"] == 0.05
    assert [r["interval"] for r in table["rows"]] == \
        [200, 50, 10, 50, 50, 50]
    # no torn tmp file left behind by the atomic publish
    assert sorted(p.name for p in (tmp_path / "policy").iterdir()) == \
        ["preempt_table.json"]
    from dlrover_wuqiong_tpu.brain.policy import load_prior

    prior = load_prior(report["table_path"])
    assert prior["step_time_s"] == 0.05
    assert prior["ckpt_cost_s"] > 0


@pytest.mark.slow  # tier-2: ~3-4 min closed-loop drill (two full runs +
# warm-pool precompile + master SIGKILL); the pure policy parts are
# tier-1 in test_policy.py and the journal replay in test_master_restart
def test_preempt_adaptive_beats_static_baseline():
    """Adaptive policy engine (ISSUE 9 acceptance): failure rate shifts
    mid-run; the closed loop must beat the static-cadence baseline by
    the checked-in margin, apply K changes only through the warm pool
    (zero cold compiles), and the decision log must reconstruct from the
    journal alone across a master SIGKILL."""
    report = chaos.preempt_adaptive()
    assert report["ok"], report
    assert report["goodput_ledger"] >= \
        report["baseline"]["goodput_ledger"] + report["margin"], report
    assert report["goodput"] >= \
        report["baseline"]["goodput"] + report["margin"], report
    assert len(report["decisions_applied"]) >= 2, report
    assert report["adaptation"]["tightened"], report
    assert report["adaptation"]["protected"], report
    # fused-K cutovers never hit a cold compile
    assert report["warm"]["kchange_hits"] >= 1, report["warm"]
    assert report["warm"]["kchange_misses"] == 0, report["warm"]
    assert report["warm"]["start_misses"] == 0, report["warm"]
    assert report["journal_matches_history"], report

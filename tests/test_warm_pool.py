"""Warm re-mesh subsystem: cache-key invalidation, degraded-world specs,
the master's warm-mesh scale policy, and the kill→re-mesh e2e where the
degraded mesh's train_step is served from the warm pool.

Tier-1 fast paths run on the virtual CPU mesh (conftest: 8 devices); the
e2e pieces spawn fresh interpreters because the persistent compilation
cache only proves itself ACROSS processes — in-process jit caching would
mask everything.
"""

import dataclasses
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import pytest

from dlrover_wuqiong_tpu.auto.compile_cache import (
    train_step_cache_key,
)
from dlrover_wuqiong_tpu.auto.warm_pool import (
    WarmPool,
    WarmSpec,
    build_model,
    degraded_specs,
    model_spec,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _key(**over):
    base = dict(
        plan_sizes={"dp": 1, "pp": 1, "fsdp": 8, "ep": 1, "sp": 1,
                    "tp": 1},
        resolved_strategy={"extra": {}, "amp": None, "remat": None,
                           "flash_attention": None},
        model_config={"n_layer": 2, "n_embd": 128},
        donate=True,
        accum_steps=1,
        backend="cpu",
    )
    base.update(over)
    return train_step_cache_key(**base)


class TestCacheKeyInvalidation:
    """Same config → same key; any trace-relevant change → new key."""

    def test_same_config_same_key(self):
        assert _key() == _key()

    def test_mesh_shape_changes_key(self):
        assert _key() != _key(plan_sizes={"dp": 1, "pp": 1, "fsdp": 4,
                                          "ep": 1, "sp": 1, "tp": 2})

    def test_strategy_changes_key(self):
        assert _key() != _key(resolved_strategy={
            "extra": {"remat_policy": "dots"}, "amp": None,
            "remat": True, "flash_attention": None})

    def test_model_config_changes_key(self):
        assert _key() != _key(model_config={"n_layer": 4, "n_embd": 128})

    def test_donate_changes_key(self):
        assert _key() != _key(donate=False)

    def test_accum_changes_key(self):
        assert _key() != _key(accum_steps=4)

    def test_fused_steps_changes_key(self):
        # the K-step scan wraps the whole step (trainer/train_step.py):
        # K=1 and K=8 are different HLO, so different compiles
        assert _key() != _key(fused_steps=8)
        assert _key(fused_steps=8) == _key(fused_steps=8)

    def test_trace_env_changes_key(self, monkeypatch):
        cold = _key()
        monkeypatch.setenv("DWT_FA_NO_FUSED", "1")
        assert _key() != cold
        monkeypatch.delenv("DWT_FA_NO_FUSED")
        assert _key() == cold

    def test_backend_changes_key(self):
        assert _key() != _key(backend="tpu")

    def test_callable_payload_is_stable(self):
        # head_loss-style callables key on qualname, not object identity
        def head_loss(p, h, y):
            return 0.0

        k1 = _key(resolved_strategy={"extra": {"pp_head_loss": head_loss}})
        k2 = _key(resolved_strategy={"extra": {"pp_head_loss": head_loss}})
        assert k1 == k2


class TestAutoAccelerateKey:
    """The key as computed by the real resolve path."""

    def _build(self, n_dev, **kw):
        import optax

        from dlrover_wuqiong_tpu.auto.accelerate import auto_accelerate
        from dlrover_wuqiong_tpu.models.gpt import GPT, GPTConfig

        cfg = dataclasses.replace(GPTConfig.nano(), dtype=jnp.float32,
                                  use_flash_attention=False, remat=False)
        return auto_accelerate(GPT(cfg), optimizer=optax.adamw(3e-4),
                               devices=jax.devices()[:n_dev],
                               materialize=False,
                               **kw)

    def test_same_build_same_key_and_registry_warms(self, tmp_path,
                                                    monkeypatch):
        monkeypatch.setenv("DWT_COMPILE_CACHE_DIR", str(tmp_path))
        r1 = self._build(8, strategy=[("fsdp", {})])
        r2 = self._build(8, strategy=[("fsdp", {})])
        assert r1.cache_key == r2.cache_key
        assert not r1.cache_warm  # first serve of this topology
        assert r2.cache_warm      # registry remembers the first
        assert r1.strategy_spec == [["fsdp", {}]]

    def test_mesh_and_env_change_key(self, tmp_path, monkeypatch):
        monkeypatch.setenv("DWT_COMPILE_CACHE_DIR", str(tmp_path))
        r8 = self._build(8, strategy=[("fsdp", {})])
        r4 = self._build(4, strategy=[("fsdp", {})])
        assert r8.cache_key != r4.cache_key
        monkeypatch.setenv("DWT_FA_STREAMED", "1")
        r8b = self._build(8, strategy=[("fsdp", {})])
        assert r8b.cache_key != r8.cache_key

    def test_auto_path_spells_out_plan(self, tmp_path, monkeypatch):
        monkeypatch.setenv("DWT_COMPILE_CACHE_DIR", str(tmp_path))
        r = self._build(8)  # no strategy → auto_plan
        assert ["fsdp", {"size": 8}] in r.strategy_spec


class TestWarmSpecs:
    def _spec(self, n=8, strategy=None, policy="fixed_global"):
        return WarmSpec(
            n_devices=n, strategy=strategy or [["fsdp", {}]],
            model={"kind": "gpt", "config": {"n_layer": 2}},
            batch_shape=[8, 32], batch_policy=policy)

    def test_node_kill_degrades_world(self):
        out = degraded_specs(self._spec(8), num_nodes=2,
                             devices_per_node=4)
        assert [s.n_devices for s in out] == [4]
        # fixed global batch: the elasticity contract keeps B constant
        assert out[0].batch_shape == [8, 32]

    def test_single_node_has_no_degraded_world(self):
        assert degraded_specs(self._spec(8), 1, 8) == []

    def test_per_device_batch_scales(self):
        out = degraded_specs(self._spec(8, policy="per_device"),
                             num_nodes=2, devices_per_node=4)
        assert out[0].batch_shape == [4, 32]

    def test_multi_slice_degrades_to_fewer_slices(self):
        spec = self._spec(12, strategy=[["multi_slice", {"slices": 3}]])
        out = degraded_specs(spec, num_nodes=3, devices_per_node=4)
        assert len(out) == 1
        assert out[0].n_devices == 8
        assert out[0].strategy[0][1]["slices"] == 2

    def test_two_slices_fall_back_to_fsdp(self):
        spec = self._spec(8, strategy=[["multi_slice", {"slices": 2}]])
        out = degraded_specs(spec, num_nodes=2, devices_per_node=4)
        assert len(out) == 1
        assert out[0].n_devices == 4
        names = [s[0] for s in out[0].strategy]
        assert "multi_slice" not in names and "fsdp" in names

    def test_model_spec_round_trip(self):
        from dlrover_wuqiong_tpu.models.gpt import GPT, GPTConfig

        cfg = dataclasses.replace(GPTConfig.nano(), dtype=jnp.float32,
                                  remat=False)
        ms = model_spec(GPT(cfg))
        assert ms["kind"] == "gpt"
        rebuilt = build_model(ms)
        assert rebuilt.config == cfg

    def test_spec_json_round_trip(self):
        spec = self._spec()
        assert WarmSpec.from_json(spec.to_json()) == spec
        assert spec.spec_key() == WarmSpec.from_json(
            spec.to_json()).spec_key()

    def test_fused_steps_rides_spec_and_degradation(self):
        # K changes the HLO: a degraded-world warm compile at the wrong
        # K would be a cache miss for the restarted fused worker
        spec = dataclasses.replace(self._spec(8), fused_steps=4)
        assert WarmSpec.from_json(spec.to_json()).fused_steps == 4
        assert spec.spec_key() != self._spec(8).spec_key()
        out = degraded_specs(spec, num_nodes=2, devices_per_node=4)
        assert out and out[0].fused_steps == 4


def _fake_pool_entry(cache_dir, n_devices, key="k"):
    pool = os.path.join(str(cache_dir), "warm-pool")
    os.makedirs(pool, exist_ok=True)
    with open(os.path.join(pool, f"{key}{n_devices}.json"), "w") as f:
        json.dump({"spec_key": f"s{n_devices}", "cache_key":
                   f"{key}{n_devices}", "n_devices": n_devices,
                   "ready": True, "platform": "cpu"}, f)


class TestWarmMeshPolicy:
    def test_policy_reads_pool_state(self, tmp_path):
        from dlrover_wuqiong_tpu.master.job_manager import WarmMeshPolicy

        _fake_pool_entry(tmp_path, 4)
        policy = WarmMeshPolicy(cache_dir=str(tmp_path),
                                devices_per_node_fn=lambda: 2)
        assert policy.is_warm_world(2)       # 2 nodes x 2 devices = 4
        assert not policy.is_warm_world(3)
        assert policy.preferred_world_size([1, 2, 3]) == 2

    def test_rendezvous_forms_warm_world_without_grace_wait(self,
                                                            tmp_path):
        """The scale-plan path: min reached, below max — normally the
        manager holds a straggler grace window open; with the degraded
        world warm it forms immediately (waiting is pure downtime when
        the restart is near-free)."""
        from dlrover_wuqiong_tpu.master.job_manager import WarmMeshPolicy
        from dlrover_wuqiong_tpu.master.rendezvous import (
            ElasticTrainingRendezvousManager,
        )

        def _join(rdzv, n):
            for nid in range(n):
                rdzv.join_rendezvous(nid, nid, 1)

        # control: no policy → the 1h grace window keeps the world open
        rdzv = ElasticTrainingRendezvousManager()
        rdzv.update_rdzv_params(2, 4, waiting_timeout=3600.0)
        _join(rdzv, 3)
        _round, _g, world = rdzv.get_comm_world(0)
        assert world == {}

        # warm 3-node world → formed despite the grace window
        _fake_pool_entry(tmp_path, 3)
        rdzv2 = ElasticTrainingRendezvousManager()
        rdzv2.update_rdzv_params(2, 4, waiting_timeout=3600.0)
        rdzv2.set_world_size_policy(WarmMeshPolicy(
            cache_dir=str(tmp_path), devices_per_node_fn=lambda: 1))
        _join(rdzv2, 3)
        _round, _g, world = rdzv2.get_comm_world(0)
        assert len(world) == 3

    def test_cold_pool_keeps_grace_window(self, tmp_path):
        from dlrover_wuqiong_tpu.master.job_manager import WarmMeshPolicy
        from dlrover_wuqiong_tpu.master.rendezvous import (
            ElasticTrainingRendezvousManager,
        )

        rdzv = ElasticTrainingRendezvousManager()
        rdzv.update_rdzv_params(2, 4, waiting_timeout=3600.0)
        rdzv.set_world_size_policy(WarmMeshPolicy(
            cache_dir=str(tmp_path), devices_per_node_fn=lambda: 1))
        for nid in range(3):
            rdzv.join_rendezvous(nid, nid, 1)
        _round, _g, world = rdzv.get_comm_world(0)
        assert world == {}  # nothing warm → still waiting on stragglers


# --------------------------------------------------------------- e2e


_RESTART_WORKER = r"""
import json, os, sys, time
n_dev = int(sys.argv[1])
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    f"--xla_force_host_platform_device_count={n_dev}")
import jax
jax.config.update("jax_platforms", "cpu")
import dataclasses
import jax.numpy as jnp
import optax
from dlrover_wuqiong_tpu.auto.accelerate import auto_accelerate
from dlrover_wuqiong_tpu.auto.compile_cache import counters
from dlrover_wuqiong_tpu.models.gpt import GPT, GPTConfig

cfg = dataclasses.replace(GPTConfig.nano(), dtype=jnp.float32,
                          use_flash_attention=False, remat=False)
res = auto_accelerate(GPT(cfg), optimizer=optax.adamw(3e-4),
                      strategy=[("fsdp", {})], devices=jax.devices(),
                      materialize=False)
bsh = res.batch_sharding_fn(2, None, 0)
ab = {"input_ids": jax.ShapeDtypeStruct((8, 32), jnp.int32, sharding=bsh),
      "labels": jax.ShapeDtypeStruct((8, 32), jnp.int32, sharding=bsh)}
h0, m0 = counters.snapshot()
t0 = time.time()
res.train_step.lower(res.state, ab).compile()
print(json.dumps({
    "cache_key": res.cache_key, "cache_warm": res.cache_warm,
    "step_hits": counters.hits - h0, "step_misses": counters.misses - m0,
    "compile_s": round(time.time() - t0, 3)}))
"""


def _run_restart_worker(tmp_path, cache_dir, n_dev):
    script = tmp_path / "restart_worker.py"
    script.write_text(_RESTART_WORKER)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["DWT_COMPILE_CACHE_DIR"] = str(cache_dir)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, str(script), str(n_dev)], env=env, cwd=REPO,
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-3000:]
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_kill_remesh_served_from_warm_pool(tmp_path):
    """The acceptance e2e: while an 8-device world 'trains', the warm
    pool pre-compiles the 4-device degraded mesh in a background child;
    the post-kill re-meshed worker (fresh interpreter, 4 devices — what
    the agent relaunches after a node dies) then gets its train_step
    FROM THE POOL: framework key warm, XLA cache hit, zero fresh
    compiles in the step window.  A cold-control worker on an empty
    cache pays the full compile."""
    warm_cache = tmp_path / "warm-cache"
    cold_cache = tmp_path / "cold-cache"
    spec = WarmSpec(
        n_devices=4, strategy=[["fsdp", {}]],
        model={"kind": "gpt",
               "config": {"vocab_size": 512, "n_layer": 2, "n_head": 2,
                          "n_embd": 128, "block_size": 128,
                          "dtype": "float32", "remat": False,
                          "use_flash_attention": False}},
        batch_shape=[8, 32], platform="cpu")

    pool = WarmPool(str(warm_cache))
    assert pool.warm_async(spec) is not None
    assert pool.wait(timeout=240), "warm child failed"
    assert pool.is_warm(4)
    # dedup: an already-warm spec does not respawn
    assert pool.warm_async(spec) is None

    warm = _run_restart_worker(tmp_path, warm_cache, 4)
    cold = _run_restart_worker(tmp_path, cold_cache, 4)

    # the pool child and the restarted worker derived the SAME framework
    # key — the spec replay is faithful to the real build
    entry = [e for e in pool.status()["entries"] if e.get("ready")][0]
    assert entry["cache_key"] == warm["cache_key"]

    assert warm["cache_warm"], warm
    assert warm["step_hits"] >= 1 and warm["step_misses"] == 0, warm
    assert not cold["cache_warm"], cold
    assert cold["step_misses"] >= 1, cold
    assert warm["compile_s"] < cold["compile_s"], (warm, cold)

    # serve accounting: the warm worker's serve recorded a pool hit
    from dlrover_wuqiong_tpu.auto.compile_cache import serve_stats

    stats = serve_stats(str(warm_cache))
    assert stats["pool_hits"] >= 1 and stats["warm_hits"] >= 1, stats


@pytest.mark.slow  # tier-2: ~33s two-drill A/B; warm-pool serving is
# tier-1 via test_kill_remesh_served_from_warm_pool
def test_preempt_drill_reports_compile_saved(tmp_path):
    """chaos preempt with model=True: warm run (persistent cache) vs
    cold control — the downtime split shows a NONZERO compile_s saved
    on the restart, and the warm restart was served from cache."""
    from dlrover_wuqiong_tpu.chaos import preempt_warm

    r = preempt_warm(total_steps=100, dt=0.05, kills=1, seed=1)
    assert r["ok"], r
    assert r["compile_s_saved"] > 0, r
    assert r["warm"]["downtime"]["warm_restarts"] \
        == r["warm"]["downtime"]["restarts"] > 0, r
    assert r["cold"]["downtime"]["warm_restarts"] == 0, r


def test_warm_report_tool(tmp_path):
    """tools/warm_report.py: one line of JSON, parseable, with the pool
    and serve fields the driver snapshots."""
    _fake_pool_entry(tmp_path, 4)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "warm_report.py"),
         str(tmp_path)], capture_output=True, text=True, timeout=60,
        cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = proc.stdout.strip().splitlines()
    assert len(lines) == 1
    report = json.loads(lines[0])
    assert report["warm_device_counts"] == {"4": 1}
    assert report["warm_meshes"][0]["n_devices"] == 4
    assert "serve" in report and "cache_dir_bytes" in report

"""graftlint schema engine (Engine F) — wire-surface lockfile tests.

Parity: reference `dlrover/python/common/grpc.py:1` evolves its
message set by convention only; here the convention (ADD-ONLY wire
surface, CLAUDE.md) is enforced by extraction + a committed lockfile.
These tests drive the engine against seeded-mutation FIXTURE packages
(a minimal mirror of the repo's wire-bearing files) so every rule is
proven to fire on the exact shape it guards, plus lockfile-lifecycle
contracts: bootstrap, --update-lock determinism, corrupt-lock
degradation, suppression grammar, and the CLI/SARIF rc mapping.

The fixtures are parsed, never imported — the engine is pure AST, so
the mini-package needs no runnable code.
"""

import json
import os
import textwrap

import pytest

from dlrover_wuqiong_tpu.analysis.schema_engine import (
    canonical_json, default_lock_path, diff_lock, extract_surface,
    load_lock, run_schema, surface_counts, write_lock)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# ------------------------------------------------------------ fixtures

MESSAGES_SRC = textwrap.dedent('''\
    """fixture wire messages."""
    from dataclasses import dataclass, field


    def message(cls):
        return dataclass(cls)


    @message
    class PolicyDecision:
        verb: str = ""
        cadence: int = 0
        replica_count: int = -1
        tags: list = field(default_factory=list)


    @message
    class HeartBeat:
        ts: float = 0.0
        node_id: str = ""
    ''')

LEDGER_SRC = textwrap.dedent('''\
    """fixture ledger registry."""
    LEDGER_STATES = (
        "productive",
        "rework",
        "degraded",
    )
    ''')

PROTOCOL_SRC = textwrap.dedent('''\
    """fixture verb classes."""
    JOURNALED_VERBS = {"PolicyDecisionReport", "TaskResultReport"}
    IDEM_VERBS = {"PolicyDecisionReport"}
    ''')

CLIENT_SRC = textwrap.dedent('''\
    """fixture master client."""


    class Client:
        def report(self):
            self._call_buffered(msg.HeartBeat(ts=0.0))

        def poll(self):
            return self._call_polling(5.0, msg.PolicyStateRequest())
    ''')

SERVICER_SRC = textwrap.dedent('''\
    """fixture servicer — journal write sites."""


    class Servicer:
        def handle(self, req):
            self._journal("policy", req)
            self._journal("task_result", req)
    ''')

MASTER_SRC = textwrap.dedent('''\
    """fixture master — replay dispatch + snapshot pair."""


    class Master:
        def _apply_entry(self, kind, data):
            if kind == "policy":
                pass
            elif kind == "task_result":
                pass

        def _journal_state(self):
            return {"kv": 1, "policy": 2}

        def _restore_snapshot(self, state):
            self.kv = state.get("kv")
            self.policy = state["policy"]
    ''')

FIXTURE_FILES = {
    "common/messages.py": MESSAGES_SRC,
    "telemetry/ledger.py": LEDGER_SRC,
    "analysis/protocol_engine.py": PROTOCOL_SRC,
    "agent/master_client.py": CLIENT_SRC,
    "master/servicer.py": SERVICER_SRC,
    "master/master.py": MASTER_SRC,
}


def make_pkg(root, overrides=None):
    """Write the fixture mini-package; overrides replace whole files."""
    files = dict(FIXTURE_FILES)
    files.update(overrides or {})
    for rel, text in files.items():
        path = os.path.join(str(root), rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(text)
    return str(root)


@pytest.fixture
def locked_pkg(tmp_path):
    """Fixture package with a committed (freshly generated) lockfile."""
    root = make_pkg(tmp_path / "pkg")
    findings, summary = run_schema(pkg_root=root, update_lock=True)
    assert findings == [] and summary["lock"] == "updated"
    return root


def checkers(findings):
    return sorted({f.checker for f in findings})


def mutate(root, rel, old, new):
    path = os.path.join(root, rel)
    text = open(path).read()
    assert old in text, f"fixture drift: {old!r} not in {rel}"
    with open(path, "w") as f:
        f.write(text.replace(old, new))


# ------------------------------------------------------ extraction


class TestExtraction:
    def test_fixture_surface_shape(self, tmp_path):
        root = make_pkg(tmp_path)
        surface, anchors, _ = extract_surface(root)
        assert set(surface["messages"]) == {"PolicyDecision", "HeartBeat"}
        pd = surface["messages"]["PolicyDecision"]["fields"]
        assert [f["name"] for f in pd] == ["verb", "cadence",
                                           "replica_count", "tags"]
        assert [f["default"] for f in pd] == ["''", "0", "-1",
                                              "factory:list"]
        assert all(f["sentinel"] for f in pd)
        assert surface["registries"]["LEDGER_STATES"] == [
            "productive", "rework", "degraded"]
        assert surface["verbs"] == {
            "journaled": ["PolicyDecisionReport", "TaskResultReport"],
            "idem": ["PolicyDecisionReport"],
            "buffered": ["HeartBeat"],
            "polling": ["PolicyStateRequest"]}
        assert surface["journal_kinds"] == {
            "written": ["policy", "task_result"],
            "replayed": ["policy", "task_result"]}
        assert surface["snapshot_keys"] == {
            "exported": ["kv", "policy"],
            "restored": ["kv", "policy"]}
        assert ("field", "PolicyDecision", "verb") in anchors

    def test_missing_files_are_partial_not_fatal(self, tmp_path):
        # a fixture (or a future repo layout change) missing a surface
        # file extracts what exists — never crashes the lint run
        root = make_pkg(tmp_path, overrides={})
        os.unlink(os.path.join(root, "agent/master_client.py"))
        surface, _, _ = extract_surface(root)
        assert surface["verbs"]["buffered"] == []
        assert surface["messages"]  # rest of the surface intact

    def test_real_repo_surface_is_populated(self):
        surface, _, _ = extract_surface()
        counts = surface_counts(surface)
        assert counts["messages"] >= 68
        assert counts["fields"] >= 211
        assert counts["registries"] >= 7
        assert counts["verbs"]["journaled"] >= 13
        assert counts["journal_kinds_written"] >= 16
        assert counts["snapshot_exported"] >= 8


# ------------------------------------------------- lockfile lifecycle


class TestLockfileLifecycle:
    def test_bootstrap_missing_lock_is_silent(self, tmp_path):
        root = make_pkg(tmp_path)
        findings, summary = run_schema(pkg_root=root)
        assert findings == []
        assert summary["lock"] == "missing"

    def test_update_lock_is_byte_identical(self, locked_pkg):
        lock_path = default_lock_path(locked_pkg)
        first = open(lock_path, "rb").read()
        findings, summary = run_schema(pkg_root=locked_pkg,
                                       update_lock=True)
        assert findings == [] and summary["lock"] == "updated"
        assert open(lock_path, "rb").read() == first
        # deterministic canonical form: sorted keys + trailing newline
        surface, _, _ = extract_surface(locked_pkg)
        assert first.decode() == canonical_json(surface)
        assert first.endswith(b"\n")

    def test_lockfile_is_world_readable(self, locked_pkg):
        # a committed artifact must not carry mkstemp's 0600
        mode = os.stat(default_lock_path(locked_pkg)).st_mode & 0o777
        assert mode == 0o644

    def test_clean_tree_diffs_clean(self, locked_pkg):
        findings, summary = run_schema(pkg_root=locked_pkg)
        assert findings == []
        assert summary["lock"] == "ok"

    def test_corrupt_lock_warns_never_fatal(self, locked_pkg):
        with open(default_lock_path(locked_pkg), "w") as f:
            f.write("{torn")
        findings, summary = run_schema(pkg_root=locked_pkg)
        assert checkers(findings) == ["schema-lock-corrupt"]
        assert all(f.severity == "warning" for f in findings)
        assert summary["lock"] == "corrupt"
        # --update-lock recovers
        findings, summary = run_schema(pkg_root=locked_pkg,
                                       update_lock=True)
        assert findings == [] and summary["lock"] == "updated"

    def test_non_dict_lock_is_corrupt(self, locked_pkg):
        with open(default_lock_path(locked_pkg), "w") as f:
            f.write("[1, 2]\n")
        lock, status = load_lock(default_lock_path(locked_pkg))
        assert lock is None and status == "corrupt"

    def test_write_lock_atomic_no_tmp_residue(self, tmp_path):
        root = make_pkg(tmp_path / "pkg")
        surface, _, _ = extract_surface(root)
        path = default_lock_path(root)
        write_lock(path, surface)
        residue = [n for n in os.listdir(os.path.dirname(path))
                   if n.startswith(".schema.lock.")]
        assert residue == []

    def test_addition_is_stale_until_update(self, locked_pkg):
        # ADD-ONLY means additions are legal — but the lock must be
        # regenerated so the delta shows up as a reviewed git diff
        mutate(locked_pkg, "telemetry/ledger.py",
               '"degraded",\n', '"degraded",\n    "compile",\n')
        findings, summary = run_schema(pkg_root=locked_pkg)
        assert checkers(findings) == ["schema-lock-stale"]
        assert summary["lock"] == "stale"
        findings, _ = run_schema(pkg_root=locked_pkg, update_lock=True)
        assert findings == []
        findings, summary = run_schema(pkg_root=locked_pkg)
        assert findings == [] and summary["lock"] == "ok"


# --------------------------------------------------- seeded mutations


class TestSeededMutations:
    def test_removed_message_field(self, locked_pkg):
        mutate(locked_pkg, "common/messages.py",
               "    replica_count: int = -1\n", "")
        findings, summary = run_schema(pkg_root=locked_pkg)
        assert "schema-removed" in checkers(findings)
        assert summary["lock"] == "stale"
        hit = [f for f in findings if f.checker == "schema-removed"]
        assert any("replica_count" in f.message for f in hit)
        assert all(f.severity == "error" for f in hit)

    def test_removed_message(self, locked_pkg):
        mutate(locked_pkg, "common/messages.py",
               "@message\nclass HeartBeat:\n    ts: float = 0.0\n"
               "    node_id: str = \"\"\n", "")
        findings, _ = run_schema(pkg_root=locked_pkg)
        hit = [f for f in findings if f.checker == "schema-removed"]
        assert any("HeartBeat" in f.message for f in hit)

    def test_renamed_field_same_ordinal(self, locked_pkg):
        mutate(locked_pkg, "common/messages.py",
               "replica_count: int = -1", "replicas: int = -1")
        findings, _ = run_schema(pkg_root=locked_pkg)
        hit = [f for f in findings if f.checker == "schema-renamed"]
        assert len(hit) == 1
        assert "replica_count" in hit[0].message
        assert "replicas" in hit[0].message

    def test_default_changed(self, locked_pkg):
        mutate(locked_pkg, "common/messages.py",
               "replica_count: int = -1", "replica_count: int = 0")
        findings, _ = run_schema(pkg_root=locked_pkg)
        assert "schema-default-changed" in checkers(findings)

    def test_stripped_sentinel_default(self, locked_pkg):
        mutate(locked_pkg, "common/messages.py",
               "replica_count: int = -1", "replica_count: int")
        findings, _ = run_schema(pkg_root=locked_pkg)
        assert "schema-field-no-sentinel" in checkers(findings)
        hit = [f for f in findings
               if f.checker == "schema-field-no-sentinel"]
        assert all(f.severity == "error" for f in hit)
        # internal rule: fires even with no lock at all
        os.unlink(default_lock_path(locked_pkg))
        findings, summary = run_schema(pkg_root=locked_pkg)
        assert checkers(findings) == ["schema-field-no-sentinel"]
        assert summary["lock"] == "missing"

    def test_removed_registry_member(self, locked_pkg):
        mutate(locked_pkg, "telemetry/ledger.py", '    "rework",\n', "")
        findings, _ = run_schema(pkg_root=locked_pkg)
        hit = [f for f in findings if f.checker == "schema-removed"]
        assert any("rework" in f.message and "LEDGER_STATES" in f.message
                   for f in hit)

    def test_dropped_replay_branch(self, locked_pkg):
        mutate(locked_pkg, "master/master.py",
               'elif kind == "task_result":', 'elif kind == "zzz":')
        findings, _ = run_schema(pkg_root=locked_pkg)
        got = checkers(findings)
        assert "journal-kind-unreplayed" in got   # written w/o replay
        assert "schema-removed" in got            # replayed set shrank

    def test_unreplayed_kind_fires_without_lock(self, tmp_path):
        # journal-kind-unreplayed is internal consistency, not a diff
        root = make_pkg(tmp_path, overrides={
            "master/master.py": MASTER_SRC.replace(
                'elif kind == "task_result":\n            pass\n', "")})
        findings, _ = run_schema(pkg_root=root)
        hit = [f for f in findings
               if f.checker == "journal-kind-unreplayed"]
        assert len(hit) == 1 and "task_result" in hit[0].message
        assert hit[0].severity == "error"

    def test_snapshot_asymmetric_both_directions(self, tmp_path):
        # exported-not-restored
        root = make_pkg(tmp_path / "a", overrides={
            "master/master.py": MASTER_SRC.replace(
                '        self.policy = state["policy"]\n', "")})
        findings, _ = run_schema(pkg_root=root)
        hit = [f for f in findings if f.checker == "snapshot-asymmetric"]
        assert len(hit) == 1 and "policy" in hit[0].message
        assert hit[0].severity == "warning"
        # restored-not-exported
        root = make_pkg(tmp_path / "b", overrides={
            "master/master.py": MASTER_SRC.replace(
                '"policy": 2', "")})
        findings, _ = run_schema(pkg_root=root)
        hit = [f for f in findings if f.checker == "snapshot-asymmetric"]
        assert len(hit) == 1 and "policy" in hit[0].message

    def test_restored_snapshot_key_removal_is_error(self, locked_pkg):
        # dropping a restore read regresses crash-recovery coverage:
        # both the asymmetry warning and the lock diff must fire
        mutate(locked_pkg, "master/master.py",
               '        self.policy = state["policy"]\n', "")
        findings, _ = run_schema(pkg_root=locked_pkg)
        got = checkers(findings)
        assert "snapshot-asymmetric" in got
        assert "schema-removed" in got

    def test_suppression_grammar_honored(self, tmp_path):
        root = make_pkg(tmp_path, overrides={
            "common/messages.py": MESSAGES_SRC.replace(
                "        node_id: str = \"\"\n",
                "        node_id: str  # graftlint: "
                "disable=schema-field-no-sentinel -- fixture probe\n")})
        findings, _ = run_schema(pkg_root=root)
        assert "schema-field-no-sentinel" not in checkers(findings)

    def test_diff_lock_verb_demotion(self, locked_pkg):
        # dropping a verb from JOURNALED_VERBS is a removal, not churn
        mutate(locked_pkg, "analysis/protocol_engine.py",
               '{"PolicyDecisionReport", "TaskResultReport"}',
               '{"PolicyDecisionReport"}')
        findings, _ = run_schema(pkg_root=locked_pkg)
        hit = [f for f in findings if f.checker == "schema-removed"]
        assert any("TaskResultReport" in f.message for f in hit)

    def test_diff_lock_pure_function(self, locked_pkg):
        surface, anchors, sources = extract_surface(locked_pkg)
        lock, status = load_lock(default_lock_path(locked_pkg))
        assert status == "ok"
        assert diff_lock(surface, lock, anchors, sources, "lock") == []


# ------------------------------------------------------- CLI surface


class TestSchemaCli:
    def _point_at(self, monkeypatch, root):
        from dlrover_wuqiong_tpu.analysis import schema_engine

        monkeypatch.setattr(schema_engine, "default_pkg_root",
                            lambda: root)

    def test_mutation_flips_rc1(self, locked_pkg, monkeypatch, capsys):
        from dlrover_wuqiong_tpu.analysis.__main__ import main

        self._point_at(monkeypatch, locked_pkg)
        assert main(["--engine", "schema"]) == 0
        capsys.readouterr()
        mutate(locked_pkg, "common/messages.py",
               "replica_count: int = -1", "replica_count: int")
        rc = main(["--engine", "schema"])
        cap = capsys.readouterr()
        assert rc == 1
        rec = json.loads(cap.out.strip())["graftlint"]
        assert rec["ok"] is False
        assert "schema-field-no-sentinel" in rec["by_checker"]
        assert rec["schema"]["lock"] == "stale"
        assert "schema-field-no-sentinel" in cap.err

    def test_corrupt_lock_rc0(self, locked_pkg, monkeypatch, capsys):
        from dlrover_wuqiong_tpu.analysis.__main__ import main

        self._point_at(monkeypatch, locked_pkg)
        with open(default_lock_path(locked_pkg), "w") as f:
            f.write("{torn")
        rc = main(["--engine", "schema"])
        cap = capsys.readouterr()
        assert rc == 0   # warning-only: degraded, never fatal
        rec = json.loads(cap.out.strip())["graftlint"]
        assert rec["by_severity"] == {"warning": 1}
        assert rec["schema"]["lock"] == "corrupt"

    def test_update_lock_flag_forces_schema(self, locked_pkg,
                                            monkeypatch, capsys):
        from dlrover_wuqiong_tpu.analysis.__main__ import main

        self._point_at(monkeypatch, locked_pkg)
        mutate(locked_pkg, "telemetry/ledger.py",
               '"degraded",\n', '"degraded",\n    "compile",\n')
        # --update-lock without --engine schema still runs the engine
        rc = main(["--engine", "ast", "--update-lock",
                   os.path.join(locked_pkg, "common")])
        cap = capsys.readouterr()
        assert rc == 0
        rec = json.loads(cap.out.strip())["graftlint"]
        assert "schema" in rec["engines"]
        assert rec["schema"]["lock"] == "updated"
        rc = main(["--engine", "schema"])
        capsys.readouterr()
        assert rc == 0

    def test_sarif_contract_over_schema_rules(self, locked_pkg,
                                              monkeypatch, capsys):
        from dlrover_wuqiong_tpu.analysis.__main__ import main

        self._point_at(monkeypatch, locked_pkg)
        mutate(locked_pkg, "common/messages.py",
               "replica_count: int = -1", "replicas: int = -1")
        rc = main(["--engine", "schema", "--format", "sarif"])
        cap = capsys.readouterr()
        assert rc == 1
        lines = cap.out.strip().splitlines()
        assert len(lines) == 1   # still exactly one stdout line
        sarif = json.loads(lines[0])
        assert sarif["version"] == "2.1.0"
        run = sarif["runs"][0]
        rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
        result_ids = {r["ruleId"] for r in run["results"]}
        assert "schema-renamed" in result_ids
        assert result_ids <= rule_ids
        for res in run["results"]:
            if res["ruleId"] == "schema-renamed":
                loc = res["locations"][0]["physicalLocation"]
                assert loc["artifactLocation"]["uri"]
                assert res["level"] == "error"


# ------------------------------------------------ repo self-lint (t1)


class TestSchemaSelfLint:
    def test_repo_surface_matches_committed_lock(self):
        """The committed lockfile is in sync with the live tree — the
        same gate __graft_entry__'s preflight runs before every dryrun."""
        findings, summary = run_schema()
        assert findings == [], "\n".join(f.format() for f in findings)
        assert summary["lock"] == "ok"

    def test_committed_lock_is_canonical_bytes(self):
        """git's copy byte-equals the canonical serialization — a hand
        edit or non-canonical writer would silently defeat the
        byte-level determinism contract."""
        surface, _, _ = extract_surface()
        with open(default_lock_path(), "rb") as f:
            assert f.read().decode() == canonical_json(surface)

"""HLO collective-budget engine tests.

Three layers: pure-text `count_collectives` parsing, pure-dict
`check_budget` gating (fires on over-count / over-bytes / unexpected op
kinds, clean within budget), and the real-lowering regression pins —
the checked-in BUDGETS are exact count pins against the repo's actual
`make_train_step` lowering on the 8-device virtual CPU mesh, so a model
or partitioner-facing change that inserts a collective fails here
before it ships (ROADMAP item 5's gate).
"""

import pytest

from dlrover_wuqiong_tpu.analysis.hlo_budget import (
    BUDGETS,
    budget_audit,
    check_budget,
    count_collectives,
    lower_case_hlo,
)


class TestCountCollectives:
    def test_counts_ops_and_bytes(self):
        hlo = """
        %ar = f32[16,8]{1,0} all-reduce(f32[16,8]{1,0} %p0), replica_groups={}
        %ag = f32[64]{0} all-gather(f32[8]{0} %p1), dimensions={0}
        %ar2 = f32[4]{0} all-reduce(f32[4]{0} %p2), replica_groups={}
        """
        got = count_collectives(hlo)
        assert got["all-reduce"]["count"] == 2
        assert got["all-reduce"]["bytes"] == 16 * 8 * 4 + 4 * 4
        assert got["all-gather"]["count"] == 1
        assert got["all-gather"]["bytes"] == 64 * 4

    def test_tuple_output_and_start_form(self):
        # async `-start` counts once; `-done` is ignored; tuple outputs
        # sum their element payloads
        hlo = """
        %s = (f32[8]{0}, f32[8]{0}) all-reduce-start(f32[8]{0} %a, f32[8]{0} %b)
        %d = f32[8]{0} all-reduce-done((f32[8]{0}, f32[8]{0}) %s)
        %cp = bf16[2,4]{1,0} collective-permute(bf16[2,4]{1,0} %c), source_target_pairs={{0,1}}
        """
        got = count_collectives(hlo)
        assert got["all-reduce"]["count"] == 1
        assert got["all-reduce"]["bytes"] == 2 * 8 * 4
        assert got["collective-permute"]["count"] == 1
        assert got["collective-permute"]["bytes"] == 2 * 4 * 2  # bf16

    def test_non_collectives_ignored(self):
        hlo = """
        %add = f32[8]{0} add(f32[8]{0} %a, f32[8]{0} %b)
        %dot = f32[8,8]{1,0} dot(f32[8,4]{1,0} %x, f32[4,8]{1,0} %y)
        """
        assert count_collectives(hlo) == {}

    def test_scalar_shape(self):
        hlo = "%r = f32[] all-reduce(f32[] %x), replica_groups={}\n"
        got = count_collectives(hlo)
        assert got["all-reduce"] == {"count": 1, "bytes": 4}


class TestCheckBudget:
    BUDGET = {"ops": {"all-reduce": {"max_count": 2, "max_bytes": 1000}}}

    def test_within_budget_clean(self):
        counts = {"all-reduce": {"count": 2, "bytes": 900}}
        assert check_budget("t", counts, self.BUDGET) == []

    def test_over_count_fires(self):
        counts = {"all-reduce": {"count": 3, "bytes": 900}}
        found = check_budget("t", counts, self.BUDGET)
        assert len(found) == 1
        assert found[0].checker == "collective-budget"
        assert found[0].severity == "error"
        assert "count 3 exceeds budget 2" in found[0].message

    def test_over_bytes_fires(self):
        counts = {"all-reduce": {"count": 2, "bytes": 2000}}
        found = check_budget("t", counts, self.BUDGET)
        assert len(found) == 1
        assert "2000 B exceeds budget 1000 B" in found[0].message

    def test_unexpected_op_kind_fires(self):
        counts = {"all-reduce": {"count": 1, "bytes": 10},
                  "all-gather": {"count": 1, "bytes": 10}}
        found = check_budget("t", counts, self.BUDGET)
        assert len(found) == 1
        assert "unexpected collective kind all-gather" in found[0].message


class TestBudgetRegression:
    """Exact pins of the real lowering — the actual regression gate."""

    @pytest.fixture(scope="class")
    def measured(self):
        findings, measured = budget_audit(n_devices=8)
        return findings, measured

    def test_repo_within_budget(self, measured):
        findings, _ = measured
        assert findings == [], [f.format() for f in findings]

    def test_all_strategies_lowered(self, measured):
        _, m = measured
        assert sorted(m) == sorted(BUDGETS)

    def test_fsdp_collective_pin(self, measured):
        # fsdp on CPU: all param gathers/scatters lower to all-reduce
        _, m = measured
        assert m["fsdp"]["all-reduce"]["count"] == 65
        assert set(m["fsdp"]) == {"all-reduce"}

    def test_dp_tp_collective_pin(self, measured):
        _, m = measured
        assert m["dp-tp"]["all-reduce"]["count"] == 28
        assert m["dp-tp"]["collective-permute"]["count"] == 12
        assert set(m["dp-tp"]) == {"all-reduce", "collective-permute"}

    def test_budget_fires_when_tightened(self, measured):
        # acceptance: a strategy exceeding its budget IS a finding —
        # reuse the real measured lowering against a tightened budget
        # instead of lowering twice
        _, m = measured
        tight = {"ops": {"all-reduce": {
            "max_count": m["fsdp"]["all-reduce"]["count"] - 1,
            "max_bytes": 1}}}
        found = check_budget("fsdp", m["fsdp"], tight)
        assert len(found) == 2  # over-count AND over-bytes
        assert all(f.checker == "collective-budget" for f in found)

    def test_coverage_warning_on_unbuildable_case(self):
        # an environment that cannot build a case (here: more devices
        # than the harness has) reports a non-gating coverage warning
        # instead of silently skipping the budget
        findings, measured = budget_audit(
            n_devices=4096, budgets={"fsdp": BUDGETS["fsdp"]})
        assert measured == {}
        assert [f.checker for f in findings] == ["budget-coverage"]
        assert findings[0].severity == "warning"

"""Capability gates for features this container's jax cannot run.

The container ships jax 0.4.37; two feature families genuinely cannot
run on it (ISSUE 3 satellite — report them as skips with a reason, not
failures):

- ``requires_shard_map`` — pipeline parallelism, local_sgd/DiLoCo and
  ring/ulysses context-parallel attention build on the manual-axes
  `jax.shard_map(axis_names=...)` API (jax >= 0.6;
  parallel/pipeline.py:87 raises RuntimeError without it).
- ``requires_pinned_host`` — optimizer_offload parks moments in
  `pinned_host` memory; this jax's CPU backend only addresses
  `unpinned_host`, so the offload shardings cannot even build
  (trainer/train_step.py train_state_shardings).

Both probes live in `common/util.py` so the dryrun gate
(__graft_entry__.py) and the tests share one definition.
"""

import jax
import pytest

from dlrover_wuqiong_tpu.common.util import (
    has_jax_shard_map,
    has_multiprocess_cpu,
    has_pinned_host_memory,
)

requires_shard_map = pytest.mark.skipif(
    not has_jax_shard_map(),
    reason="needs jax>=0.6 shard_map(axis_names=...) — container has "
           f"jax {jax.__version__} (feature genuinely cannot run)")

def shard_index_set(arr):
    """Distinct shard indices of a jax Array, as hashable tuples.

    `{s.index for s in arr.addressable_shards}` breaks on python < 3.12
    (slices are unhashable) — the sharding feature works fine, only the
    set idiom didn't; this helper keeps those assertions runnable."""
    return {tuple((sl.start, sl.stop, sl.step) for sl in s.index)
            for s in arr.addressable_shards}


requires_pinned_host = pytest.mark.skipif(
    not has_pinned_host_memory(),
    reason="optimizer_offload needs a pinned_host memory kind; this "
           f"backend on jax {jax.__version__} only addresses "
           "unpinned_host (feature genuinely cannot run)")

requires_multiprocess_cpu = pytest.mark.skipif(
    not has_multiprocess_cpu(),
    reason="multi-process SPMD is not implemented on the CPU backend "
           f"before jax 0.5 (container has {jax.__version__}); the "
           "jax.distributed e2e drills genuinely cannot run")


def optax_belief_uses_stale_mu() -> bool:
    """True when this optax's AdaBelief computes the prediction error
    against the PRE-update EMA (``g - state.mu``), as optax 0.2.x does —
    the paper (and our sparse kernel, embedding/sparse_optim.py) uses the
    POST-update EMA (``g - m_t``), so an exact match is impossible under
    such an optax.  Probed numerically (one scalar step from zero state
    distinguishes the two closed forms) rather than by version string, so
    the gate answers for whatever optax is actually installed."""
    import jax.numpy as jnp
    import optax

    opt = optax.adabelief(1.0, b1=0.9, b2=0.9, eps=0.0, eps_root=0.0)
    p = jnp.float32(0.0)
    up, _ = opt.update(jnp.float32(1.0), opt.init(p), p)
    # stale mu: nu=(1-b2)g² → |update| = 1;  post-update mu:
    # nu=(1-b2)(b1·g)² → |update| = 1/b1 ≈ 1.111
    return abs(float(up)) < 1.05

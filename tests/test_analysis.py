"""graftlint static-analysis subsystem (dlrover_wuqiong_tpu/analysis/).

Positive + negative fixtures per checker, the resolve-time wiring into
auto_accelerate, the CLI contract (one JSON line on stdout, rc 1 on
findings), and the tier-1 repo self-lint: graftlint run over this tree
must come back clean — the CLAUDE.md hard-won rules are an enforced
contract, not tribal knowledge.  None of the jaxpr fixtures execute any
device computation: everything goes through jax.make_jaxpr / abstract
state (the acceptance bar for the subsystem).
"""

import json
import os
import textwrap

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from dlrover_wuqiong_tpu.analysis.ast_engine import (
    run_paths,
    trace_env_key_vars,
)
from dlrover_wuqiong_tpu.analysis.findings import (
    Finding,
    render_report,
    summarize,
)
from dlrover_wuqiong_tpu.analysis.jaxpr_engine import (
    check_collective_in_cond,
    check_donation_alias,
    check_host_out_shardings,
    check_remat_noop,
    resolve_donation,
    self_audit,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mesh(n=4):
    return Mesh(jax.devices()[:n], ("x",))


# --------------------------------------------------- collective-in-cond


class TestCollectiveInCond:
    def test_varying_pred_psum_flagged(self):
        def bad(x):
            pred = x[0] > 0  # derived from the sharded input → varying
            return jax.lax.cond(pred,
                                lambda v: jax.lax.psum(v, "x"),
                                lambda v: v, x)

        f = shard_map(bad, mesh=_mesh(), in_specs=P("x"),
                      out_specs=P("x"), check_rep=False)
        found = check_collective_in_cond(f, jnp.ones((8,)))
        assert len(found) == 1
        assert found[0].checker == "collective-in-cond"
        assert "psum" in found[0].message and "'x'" in found[0].message

    def test_axis_index_pred_flagged(self):
        def bad(x):
            i = jax.lax.axis_index("x")
            return jax.lax.cond(i == 0,
                                lambda v: jax.lax.psum(v, "x"),
                                lambda v: v, x)

        f = shard_map(bad, mesh=_mesh(), in_specs=P("x"),
                      out_specs=P("x"), check_rep=False)
        assert check_collective_in_cond(f, jnp.ones((8,)))

    def test_where_masking_clean(self):
        # the CLAUDE.md-prescribed fix: compute unconditionally, mask
        def good(x):
            s = jax.lax.psum(x, "x")
            return jnp.where(x[0] > 0, s, x)

        f = shard_map(good, mesh=_mesh(), in_specs=P("x"),
                      out_specs=P("x"), check_rep=False)
        assert check_collective_in_cond(f, jnp.ones((8,))) == []

    def test_replicated_pred_clean(self):
        # every shard sees the same predicate → same branch → no deadlock
        def ok(x, t):
            return jax.lax.cond(t > 0,
                                lambda v: jax.lax.psum(v, "x"),
                                lambda v: v, x)

        f = shard_map(ok, mesh=_mesh(), in_specs=(P("x"), P()),
                      out_specs=P("x"), check_rep=False)
        assert check_collective_in_cond(
            f, jnp.ones((8,)), jnp.float32(1.0)) == []

    def test_psum_cancels_varyingness(self):
        # pred derived from a psum over 'x' is invariant over 'x' → safe
        def ok(x):
            total = jax.lax.psum(x, "x")
            return jax.lax.cond(total[0] > 0,
                                lambda v: jax.lax.psum(v, "x"),
                                lambda v: v, x)

        f = shard_map(ok, mesh=_mesh(), in_specs=P("x"),
                      out_specs=P("x"), check_rep=False)
        assert check_collective_in_cond(f, jnp.ones((8,))) == []

    def test_abstract_args_no_execution(self):
        def bad(x):
            return jax.lax.cond(x[0] > 0,
                                lambda v: jax.lax.psum(v, "x"),
                                lambda v: v, x)

        f = shard_map(bad, mesh=_mesh(), in_specs=P("x"),
                      out_specs=P("x"), check_rep=False)
        # ShapeDtypeStruct in → pure trace, nothing dispatched
        sds = jax.ShapeDtypeStruct((8,), jnp.float32)
        assert check_collective_in_cond(f, sds)


# ----------------------------------------------------------- remat-noop


def _layer(x, w):
    return jnp.tanh(x @ w)


class TestRematNoop:
    def test_python_loop_prevent_cse_false_flagged(self):
        ck = jax.checkpoint(_layer, prevent_cse=False)

        def loop(x, w):
            for _ in range(3):
                x = ck(x, w)
            return x.sum()

        found = check_remat_noop(jax.grad(loop), jnp.ones((4, 4)),
                                 jnp.ones((4, 4)))
        assert len(found) == 1
        assert found[0].checker == "remat-noop"
        assert "3 identical instances" in found[0].message

    def test_scan_body_prevent_cse_false_clean(self):
        # under scan the loop body is a separate computation: the exact
        # situation prevent_cse=False exists for
        ck = jax.checkpoint(_layer, prevent_cse=False)

        def scanned(x, w):
            def body(c, _):
                return ck(c, w), None

            y, _ = jax.lax.scan(body, x, None, length=3)
            return y.sum()

        assert check_remat_noop(jax.grad(scanned), jnp.ones((4, 4)),
                                jnp.ones((4, 4))) == []

    def test_prevent_cse_true_clean(self):
        ck = jax.checkpoint(_layer)  # prevent_cse=True default

        def loop(x, w):
            for _ in range(3):
                x = ck(x, w)
            return x.sum()

        assert check_remat_noop(jax.grad(loop), jnp.ones((4, 4)),
                                jnp.ones((4, 4))) == []


# ---------------------------------------------- donation / host kinds


class _FakeSharding:
    """Sharding stand-in: memory_kind + device_set(platform), no jax.

    Deliberately NOT a real NamedSharding: the checker must never touch
    the memories API (see _is_explicit_host_kind), so all it needs from
    a leaf is these two attributes.
    """

    def __init__(self, kind, platform="tpu"):
        self.memory_kind = kind
        self._platform = platform

    @property
    def device_set(self):
        class _Dev:
            def __init__(self, platform):
                self.platform = platform

        return {_Dev(self._platform)}


class TestDonationAndHostKinds:
    def test_donation_alias_flagged(self):
        assert check_donation_alias({"optimizer_offload": True}, True)
        assert check_donation_alias({"optimizer_offload": True},
                                    None) == []
        assert check_donation_alias({}, True) == []

    def test_resolve_donation(self):
        assert resolve_donation({}, None) is True
        assert resolve_donation({"optimizer_offload": True}, None) is False
        assert resolve_donation({}, False) is False
        with pytest.raises(ValueError, match="donation-alias"):
            resolve_donation({"optimizer_offload": True}, True)

    def test_host_kind_flagged_when_not_default(self):
        tree = {"m": _FakeSharding("pinned_host", platform="tpu"),
                "ok": _FakeSharding("device", platform="tpu")}
        found = check_host_out_shardings(tree)
        assert len(found) == 1
        assert "pinned_host" in found[0].message
        assert "'m'" in found[0].message

    def test_pinned_host_flagged_even_on_cpu(self):
        # explicit host offload is explicit on every platform
        tree = {"m": _FakeSharding("pinned_host", platform="cpu")}
        assert len(check_host_out_shardings(tree)) == 1

    def test_default_host_kind_on_cpu_clean(self):
        # the CPU backend's default memory kind IS unpinned_host: plain
        # CPU shardings must not be flagged (regression: the first
        # wiring of this check broke every CPU-mesh init)
        tree = {"x": _FakeSharding("unpinned_host", platform="cpu")}
        assert check_host_out_shardings(tree) == []

    def test_unpinned_host_on_tpu_flagged(self):
        tree = {"x": _FakeSharding("unpinned_host", platform="tpu")}
        assert len(check_host_out_shardings(tree)) == 1

    def test_real_cpu_state_shardings_clean(self):
        from dlrover_wuqiong_tpu.parallel.mesh import MeshPlan, build_mesh
        from dlrover_wuqiong_tpu.parallel.sharding import ShardingPlanner

        planner = ShardingPlanner(build_mesh(MeshPlan(fsdp=8)))
        assert check_host_out_shardings(planner.replicated()) == []

    def test_auto_accelerate_rejects_donate_with_offload(self):
        import optax

        from dlrover_wuqiong_tpu.auto.accelerate import auto_accelerate
        from dlrover_wuqiong_tpu.models.gpt import GPT, GPTConfig

        with pytest.raises(ValueError, match="donation-alias"):
            auto_accelerate(
                GPT(GPTConfig.nano()), optimizer=optax.adamw(1e-3),
                strategy=[("fsdp", {}), ("optimizer_offload", {})],
                donate=True, materialize=False)

    def test_make_train_step_rejects_donate_with_host_shardings(self):
        import optax

        from dlrover_wuqiong_tpu.trainer.train_step import make_train_step

        with pytest.raises(ValueError, match="donation-alias"):
            make_train_step(lambda p, b: jnp.float32(0), optax.sgd(0.1),
                            _mesh(), donate=True,
                            opt_host_shardings={"m": None},
                            opt_device_shardings={"m": None})


# --------------------------------------------------------- AST fixtures


def _scan_source(tmp_path, relpath, source, **kw):
    """Write one fixture file into a fake package tree and lint it."""
    path = tmp_path / relpath
    path.parent.mkdir(parents=True, exist_ok=True)
    # package markers so the citation checker sees a real package
    d = path.parent
    while d != tmp_path:
        (d / "__init__.py").touch()
        d = d.parent
    path.write_text(textwrap.dedent(source))
    findings, _ = run_paths([str(tmp_path)], **kw)
    return findings


class TestEnvAtTrace:
    def test_unkeyed_env_read_flagged(self, tmp_path):
        found = _scan_source(
            tmp_path, "pkg/ops/kern.py", """\
            '''Parity: ref.py:1'''
            import os

            def build_kernel(x):
                if os.getenv("DWT_FAKE_TOGGLE"):
                    return x
                return x + 1
            """, key_vars={"DWT_FA_STREAMED"})
        assert [f.checker for f in found] == ["env-at-trace"]
        assert "DWT_FAKE_TOGGLE" in found[0].message
        assert found[0].line == 5

    def test_keyed_env_read_clean(self, tmp_path):
        found = _scan_source(
            tmp_path, "pkg/ops/kern.py", """\
            '''Parity: ref.py:1'''
            import os

            def build_kernel(x):
                return os.environ.get("DWT_FAKE_TOGGLE")
            """, key_vars={"DWT_FAKE_TOGGLE"})
        assert found == []

    def test_module_level_and_non_compute_reads_exempt(self, tmp_path):
        found = _scan_source(
            tmp_path, "pkg/master/sched.py", """\
            '''Parity: ref.py:1'''
            import os

            def pick():
                return os.getenv("DWT_JOB_NAME")
            """, key_vars=set())
        assert found == []

    def test_key_vars_parsed_from_repo(self):
        vars_ = trace_env_key_vars([
            os.path.join(REPO_ROOT, "dlrover_wuqiong_tpu")])
        # the DWT_FA_PACK omission was graftlint's first real catch —
        # pin the kernel-path toggles plus the ISSUE-16 tuner axes
        # (fp8 dense + remat policy) in the key set
        assert {"DWT_FA_NO_FUSED", "DWT_FA_PACK", "DWT_FA_STREAMED",
                "DWT_FP8_DENSE", "DWT_REMAT_POLICY"} <= vars_


class TestEnvFlipOutsideTuner:
    """env-flip-outside-tuner: raw os.environ writes of TRACE_ENV_VARS
    names belong to auto/tuner.py (variant_env / apply_variant)."""

    def test_raw_writes_flagged(self, tmp_path):
        found = _scan_source(
            tmp_path, "pkg/runtime/flip.py", """\
            '''Parity: ref.py:1'''
            import os

            def go():
                os.environ["DWT_FA_STREAMED"] = "1"
                os.environ.pop("DWT_FA_NO_FUSED", None)
                os.environ.setdefault("DWT_FA_PACK", "4")
                del os.environ["DWT_FA_STREAMED"]
            """,
            checkers=["env-flip-outside-tuner"],
            key_vars={"DWT_FA_STREAMED", "DWT_FA_NO_FUSED",
                      "DWT_FA_PACK"})
        assert [f.checker for f in found] == \
            ["env-flip-outside-tuner"] * 4
        assert sorted(f.line for f in found) == [5, 6, 7, 8]
        assert "variant_env" in found[0].message

    def test_tuner_file_and_tests_exempt(self, tmp_path):
        src = """\
            '''Parity: ref.py:1'''
            import os

            def _set(name, value):
                os.environ["DWT_FA_STREAMED"] = value
            """
        for rel in ("pkg/auto/tuner.py", "pkg/tests/test_flip.py",
                    "pkg/test_flip.py"):
            found = _scan_source(
                tmp_path / rel.replace("/", "_"), rel, src,
                checkers=["env-flip-outside-tuner"],
                key_vars={"DWT_FA_STREAMED"})
            assert found == [], rel

    def test_non_key_vars_and_reads_clean(self, tmp_path):
        found = _scan_source(
            tmp_path, "pkg/runtime/flip.py", """\
            '''Parity: ref.py:1'''
            import os

            def go():
                os.environ["DWT_JOB_NAME"] = "j"       # not a trace var
                v = os.environ.get("DWT_FA_STREAMED")  # read, not write
                return v
            """,
            checkers=["env-flip-outside-tuner"],
            key_vars={"DWT_FA_STREAMED"})
        assert found == []

    def test_suppression_honored(self, tmp_path):
        found = _scan_source(
            tmp_path, "pkg/runtime/flip.py", """\
            '''Parity: ref.py:1'''
            import os

            def go():
                os.environ["DWT_FA_PACK"] = "4"  \
# graftlint: disable=env-flip-outside-tuner -- fixture exercises raw flip
            """,
            checkers=["env-flip-outside-tuner"],
            key_vars={"DWT_FA_PACK"})
        assert found == []

    def test_newly_registered_name_flagged_via_lint_time_sourcing(
            self, tmp_path):
        """Registering a NEW name in TRACE_ENV_VARS is all it takes for
        the rule to cover it: key_vars are parsed from the linted tree's
        own auto/compile_cache.py at LINT TIME (no hardcoded list), so a
        raw write of the new toggle is flagged while the same write in
        the tuner module stays exempt."""
        # key-builder at <root>/auto/compile_cache.py — exactly where
        # trace_env_key_vars looks under each scanned root
        (tmp_path / "auto").mkdir()
        (tmp_path / "runtime").mkdir()
        for d in ("auto", "runtime"):
            (tmp_path / d / "__init__.py").touch()
        (tmp_path / "auto" / "compile_cache.py").write_text(
            textwrap.dedent("""\
            '''Parity: ref.py:1'''
            TRACE_ENV_VARS = ("DWT_FA_NO_FUSED", "DWT_NEW_TOGGLE")
            """))
        bad = textwrap.dedent("""\
            '''Parity: ref.py:1'''
            import os

            def go():
                os.environ["DWT_NEW_TOGGLE"] = "1"
            """)
        (tmp_path / "runtime" / "flip.py").write_text(bad)
        # the good twin: byte-identical write, but in the tuner module —
        # the ONE sanctioned writer stays exempt
        (tmp_path / "auto" / "tuner.py").write_text(bad)
        # key_vars=None -> auto-sourced from the fixture tree itself
        findings, _ = run_paths(
            [str(tmp_path)], checkers=["env-flip-outside-tuner"])
        assert [(f.checker, f.line) for f in findings] == \
            [("env-flip-outside-tuner", 5)]
        assert findings[0].path.endswith("runtime/flip.py")
        assert "DWT_NEW_TOGGLE" in findings[0].message


class TestWallClockDuration:
    """wall-clock-duration (warning): time.time() in duration math."""

    def test_elapsed_subtraction_flagged(self, tmp_path):
        found = _scan_source(
            tmp_path, "pkg/master/loop.py", """\
            '''Parity: ref.py:1'''
            import time

            def wait(t0):
                return time.time() - t0
            """)
        assert [f.checker for f in found] == ["wall-clock-duration"]
        assert found[0].severity == "warning"
        assert found[0].line == 5
        assert "monotonic" in found[0].message

    def test_deadline_addition_flagged(self, tmp_path):
        found = _scan_source(
            tmp_path, "pkg/master/loop.py", """\
            '''Parity: ref.py:1'''
            import time

            def deadline(timeout):
                return time.time() + timeout
            """)
        assert [f.checker for f in found] == ["wall-clock-duration"]

    def test_file_timestamp_comparison_exempt(self, tmp_path):
        # mtimes ARE wall clock — comparing against one is correct as is
        found = _scan_source(
            tmp_path, "pkg/master/loop.py", """\
            '''Parity: ref.py:1'''
            import os
            import time

            def age(path):
                return time.time() - os.path.getmtime(path)

            def stat_age(st):
                return time.time() - st.st_mtime
            """)
        assert found == []

    def test_suppression_with_reason_honored(self, tmp_path):
        found = _scan_source(
            tmp_path, "pkg/master/loop.py", """\
            '''Parity: ref.py:1'''
            import time

            def journal_ts(t0):
                return time.time() - t0  # graftlint: disable=wall-clock-duration -- cross-process journal timestamps are wall clock
            """)
        assert found == []

    def test_monotonic_clean(self, tmp_path):
        found = _scan_source(
            tmp_path, "pkg/master/loop.py", """\
            '''Parity: ref.py:1'''
            import time

            def wait(t0, timeout):
                return (time.monotonic() - t0) < timeout

            def stamp():
                return time.time()  # bare read, no arithmetic: fine
            """)
        assert found == []

    def test_warning_severity_does_not_gate(self, tmp_path):
        # warnings report but keep ok=true / rc 0 (README contract)
        from dlrover_wuqiong_tpu.analysis.__main__ import main

        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "__init__.py").touch()
        (pkg / "m.py").write_text(
            "'''Parity: ref.py:1'''\n"
            "import time\n\n\n"
            "def wait(t0):\n"
            "    return time.time() - t0\n")
        rc = main(["--engine", "ast", str(tmp_path)])
        assert rc == 0


class TestDonatedReuse:
    def test_reuse_after_donation_flagged(self, tmp_path):
        found = _scan_source(
            tmp_path, "tests/test_x.py", """\
            def test_step(res, batch):
                state = res.state
                new_state, m = res.train_step(state, batch)
                return state.params  # dead buffer
            """)
        assert [f.checker for f in found] == ["donated-reuse"]
        assert "`state`" in found[0].message

    def test_attribute_reuse_flagged(self, tmp_path):
        found = _scan_source(
            tmp_path, "tests/test_x.py", """\
            def test_step(res, batch):
                s, m = res.train_step(res.state, batch)
                return res.state  # dead buffer
            """)
        assert len(found) == 1 and "`res.state`" in found[0].message

    def test_rebind_pattern_clean(self, tmp_path):
        found = _scan_source(
            tmp_path, "tests/test_x.py", """\
            def test_step(res, batch, n):
                state = res.state
                for _ in range(n):
                    state, m = res.train_step(state, batch)
                return state
            """)
        assert found == []

    def test_loop_without_rebind_flagged(self, tmp_path):
        found = _scan_source(
            tmp_path, "tests/test_x.py", """\
            def test_step(res, state, batch, n):
                for _ in range(n):
                    out, m = res.train_step(state, batch)
                return out
            """)
        assert len(found) == 1
        assert "loop" in found[0].message

    def test_copy_argument_clean(self, tmp_path):
        found = _scan_source(
            tmp_path, "tests/test_x.py", """\
            import jax.numpy as jnp

            def test_step(res, state, batch):
                s, m = res.train_step(jax.tree.map(jnp.copy, state), batch)
                return state
            """)
        assert found == []

    def test_pragma_suppression(self, tmp_path):
        found = _scan_source(
            tmp_path, "tests/test_x.py", """\
            def test_step(res, state, batch):
                s, m = res.train_step(state, batch)
                return state  # graftlint: disable=donated-reuse -- fixture: suppression honored
            """)
        assert found == []

    def test_sparse_update_positions(self, tmp_path):
        found = _scan_source(
            tmp_path, "tests/test_x.py", """\
            def test_emb(cfg, table, state, slots, g):
                t2, s2 = apply_sparse_update(cfg, table, state, slots, g)
                assert g.shape  # grads are NOT donated — fine
                return table.sum()  # table IS donated
            """)
        assert len(found) == 1 and "`table`" in found[0].message


class TestBlockingReadback:
    def test_unconditional_float_in_train_loop_flagged(self, tmp_path):
        found = _scan_source(
            tmp_path, "pkg/examples/loop.py", """\
            '''Parity: ref.py:1'''

            def run(res, state, batch, n):
                for _ in range(n):
                    state, m = res.train_step(state, batch)
                    loss = float(m["loss"])  # per-step host sync
                return state
            """)
        assert [f.checker for f in found] == ["blocking-readback"]
        assert "float" in found[0].message
        assert found[0].line == 6

    def test_np_asarray_on_step_output_flagged(self, tmp_path):
        found = _scan_source(
            tmp_path, "pkg/examples/loop.py", """\
            '''Parity: ref.py:1'''
            import numpy as np

            def run(res, state, batch, n):
                for _ in range(n):
                    state, m = res.train_step(state, batch)
                    np.asarray(m["grad_norm"])
                return state
            """)
        assert [f.checker for f in found] == ["blocking-readback"]

    def test_fused_factory_call_recognized(self, tmp_path):
        found = _scan_source(
            tmp_path, "pkg/examples/loop.py", """\
            '''Parity: ref.py:1'''

            def run(res, state, batch, n, k):
                for _ in range(n):
                    state, m = res.fused_train_step(k)(state, batch)
                    float(m["loss"])
                return state
            """)
        assert [f.checker for f in found] == ["blocking-readback"]
        assert "fused_train_step" in found[0].message

    def test_cadence_gated_readback_clean(self, tmp_path):
        found = _scan_source(
            tmp_path, "pkg/examples/loop.py", """\
            '''Parity: ref.py:1'''

            def run(res, state, batch, n, log_every):
                for i in range(n):
                    state, m = res.train_step(state, batch)
                    if (i + 1) % log_every == 0:
                        print(float(m["loss"]))  # throttled: fine
                return state
            """)
        assert found == []

    def test_readback_after_loop_clean(self, tmp_path):
        found = _scan_source(
            tmp_path, "pkg/examples/loop.py", """\
            '''Parity: ref.py:1'''

            def run(res, state, batch, n):
                for _ in range(n):
                    state, m = res.train_step(state, batch)
                return float(m["loss"])  # one sync for the whole chain
            """)
        assert found == []

    def test_non_step_value_readback_clean(self, tmp_path):
        found = _scan_source(
            tmp_path, "pkg/examples/loop.py", """\
            '''Parity: ref.py:1'''

            def run(res, state, batch, lrs):
                for lr in lrs:
                    state, m = res.train_step(state, batch)
                    rate = float(lr)  # host value, not a step output
                return state
            """)
        assert found == []

    def test_tests_dir_exempt(self, tmp_path):
        found = _scan_source(
            tmp_path, "tests/test_loop.py", """\
            def test_converges(res, state, batch):
                for _ in range(4):
                    state, m = res.train_step(state, batch)
                    assert float(m["loss"]) < 10  # convergence test: fine
            """)
        assert found == []

    def test_pragma_suppression(self, tmp_path):
        found = _scan_source(
            tmp_path, "pkg/examples/loop.py", """\
            '''Parity: ref.py:1'''

            def run(res, state, batch, n):
                for _ in range(n):
                    state, m = res.train_step(state, batch)
                    float(m["loss"])  # graftlint: disable=blocking-readback -- fixture: suppression honored
                return state
            """)
        assert found == []


class TestRawRpcCall:
    def test_bare_dial_flagged(self, tmp_path):
        found = _scan_source(
            tmp_path, "pkg/agent/probe.py", """\
            '''Parity: ref.py:1'''
            import socket

            def ping(addr):
                host, port = addr.rsplit(":", 1)
                with socket.create_connection((host, int(port))) as s:
                    s.sendall(b"hi")
            """)
        assert [f.checker for f in found] == ["raw-rpc-call"]
        assert found[0].line == 6

    def test_sock_connect_flagged(self, tmp_path):
        found = _scan_source(
            tmp_path, "pkg/agent/probe.py", """\
            '''Parity: ref.py:1'''
            import socket

            def dial(path):
                sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                sock.connect(path)
                return sock
            """)
        assert [f.checker for f in found] == ["raw-rpc-call"]

    def test_frame_io_outside_comm_flagged(self, tmp_path):
        found = _scan_source(
            tmp_path, "pkg/agent/sidechan.py", """\
            '''Parity: ref.py:1'''
            from ..common.comm import _send_frame

            def push(sk, data):
                _send_frame(sk, data)
            """)
        assert [f.checker for f in found] == ["raw-rpc-call"]

    def test_dial_under_retry_call_clean(self, tmp_path):
        """The sanctioned shape: the dial is the retried attempt — any
        enclosing function routing through retry_call blesses it."""
        found = _scan_source(
            tmp_path, "pkg/agent/probe.py", """\
            '''Parity: ref.py:1'''
            import socket
            from ..common.util import retry_call

            def ping(addr):
                host, port = addr.rsplit(":", 1)

                def attempt():
                    with socket.create_connection((host, int(port))) as s:
                        s.sendall(b"hi")

                return retry_call(attempt, attempts=3)
            """)
        assert found == []

    def test_comm_module_and_tests_exempt(self, tmp_path):
        src = """\
            '''Parity: ref.py:1'''
            import socket

            def dial(addr):
                return socket.create_connection(addr)
            """
        assert _scan_source(tmp_path, "pkg/common/comm.py", src) == []
        assert _scan_source(tmp_path, "tests/test_dial.py", src) == []

    def test_non_socket_connect_clean(self, tmp_path):
        found = _scan_source(
            tmp_path, "pkg/agent/db.py", """\
            '''Parity: ref.py:1'''
            import sqlite3

            def open_db(path):
                return sqlite3.connect(path)
            """)
        assert found == []

    def test_pragma_suppression(self, tmp_path):
        found = _scan_source(
            tmp_path, "pkg/agent/probe.py", """\
            '''Parity: ref.py:1'''
            import socket

            def ping(addr):
                return socket.create_connection(addr)  # graftlint: disable=raw-rpc-call -- fixture: suppression honored
            """)
        assert found == []


class TestUnverifiedRestore:
    def test_shm_bytes_to_device_put_flagged(self, tmp_path):
        found = _scan_source(
            tmp_path, "pkg/ckpt/restorer.py", """\
            '''Parity: ref.py:1'''
            import jax

            def resume(handler, sharding):
                step, flat, metas, extra = handler.load_state_dict()
                return jax.device_put(flat["w"], sharding)
            """)
        assert [f.checker for f in found] == ["unverified-restore"]
        assert "device_put" in found[0].message
        assert found[0].line == 6

    def test_frombuffer_to_restore_pytree_flagged(self, tmp_path):
        found = _scan_source(
            tmp_path, "pkg/ckpt/loader.py", """\
            '''Parity: ref.py:1'''
            import numpy as np

            def load(storage, template, path):
                raw = storage.read(path)
                flat = {"w": np.frombuffer(raw, dtype=np.float32)}
                return restore_pytree(template, flat)
            """)
        assert [f.checker for f in found] == ["unverified-restore"]
        assert "restore_pytree" in found[0].message

    def test_verified_decode_clean(self, tmp_path):
        found = _scan_source(
            tmp_path, "pkg/ckpt/loader.py", """\
            '''Parity: ref.py:1'''
            import numpy as np

            def load(storage, template, path, entry):
                raw = storage.read(path)
                verify_rank_bytes(raw, entry, "crc32c", 0)
                flat = {"w": np.frombuffer(raw, dtype=np.float32)}
                return restore_pytree(template, flat)
            """)
        assert found == []

    def test_sink_without_raw_source_clean(self, tmp_path):
        # restore_pytree fed by the verified engine API in ANOTHER
        # function: the sanctioned shape (engine.load verifies inside)
        found = _scan_source(
            tmp_path, "pkg/ckpt/user.py", """\
            '''Parity: ref.py:1'''
            import jax

            def resume(engine, template, sharding):
                flat = engine.load()
                return jax.device_put(flat["w"], sharding)
            """)
        assert found == []

    def test_tests_and_suppression_exempt(self, tmp_path):
        src = """\
            '''Parity: ref.py:1'''
            import jax

            def resume(handler, sharding):
                step, flat, metas, extra = handler.load_state_dict()
                return jax.device_put(flat["w"], sharding)  # graftlint: disable=unverified-restore -- fixture: suppression honored
            """
        assert _scan_source(tmp_path, "pkg/tests/test_x.py", src) == []
        assert _scan_source(tmp_path, "pkg/ckpt/sanctioned.py", src) == []


class TestControlPlaneHygiene:
    def test_pickle_on_frame_path_flagged(self, tmp_path):
        found = _scan_source(
            tmp_path, "pkg/common/comm.py", """\
            '''Parity: ref.py:1'''
            import pickle

            def encode(x):
                return pickle.dumps(x)
            """)
        assert any(f.checker == "control-plane-hygiene" and
                   "pickle" in f.message for f in found)

    def test_fork_context_flagged(self, tmp_path):
        found = _scan_source(
            tmp_path, "pkg/data/loader.py", """\
            '''Parity: ref.py:1'''
            import multiprocessing

            def start():
                return multiprocessing.get_context("fork")
            """)
        assert any("fork" in f.message for f in found)

    def test_spawn_and_json_clean(self, tmp_path):
        found = _scan_source(
            tmp_path, "pkg/common/comm.py", """\
            '''Parity: ref.py:1'''
            import json
            import multiprocessing

            def start():
                return multiprocessing.get_context("spawn")
            """)
        assert found == []


class TestDocstringCitation:
    def test_uncited_module_flagged(self, tmp_path):
        found = _scan_source(
            tmp_path, "pkg/core/thing.py", """\
            '''Helpers.'''

            def f():
                pass
            """)
        assert [f.checker for f in found] == ["docstring-citation"]

    def test_cited_module_clean(self, tmp_path):
        found = _scan_source(
            tmp_path, "pkg/core/thing.py", """\
            '''Does X.  Parity: reference foo/bar.py:42.'''

            def f():
                pass
            """)
        assert found == []

    def test_init_and_defless_modules_exempt(self, tmp_path):
        found = _scan_source(
            tmp_path, "pkg/core/constants.py", """\
            '''Just constants, no citation needed.'''

            X = 1
            """)
        assert found == []


# ------------------------------------------------------------ findings


class TestFindings:
    def test_format_and_summary(self):
        f = Finding("env-at-trace", "boom", "a/b.py", 7)
        # v2: severity (catalog-defaulted) rides between location and rule
        assert f.format() == "a/b.py:7: error: [env-at-trace] boom"
        assert summarize([f, f, Finding("remat-noop", "x")]) == {
            "env-at-trace": 2, "remat-noop": 1}
        assert "and 1 more" in render_report([f, f, f], limit=2)


# ------------------------------------------------------- CLI contract


class TestCli:
    def test_cli_clean_dir_rc0_single_json_line(self, tmp_path, capsys):
        from dlrover_wuqiong_tpu.analysis.__main__ import main

        (tmp_path / "ok.py").write_text("x = 1\n")
        rc = main(["--engine", "ast", str(tmp_path)])
        out = capsys.readouterr().out.strip().splitlines()
        assert rc == 0
        assert len(out) == 1
        rec = json.loads(out[0])["graftlint"]
        assert rec["ok"] is True and rec["engines"] == ["ast"]

    def test_cli_violations_rc1_with_file_line_report(self, tmp_path,
                                                      capsys):
        from dlrover_wuqiong_tpu.analysis.__main__ import main

        bad = tmp_path / "test_bad.py"
        bad.write_text(textwrap.dedent("""\
            def test_step(res, state, batch):
                s, m = res.train_step(state, batch)
                return state
            """))
        rc = main(["--engine", "ast", str(tmp_path)])
        cap = capsys.readouterr()
        assert rc == 1
        rec = json.loads(cap.out.strip())["graftlint"]
        assert rec["findings"] == 1
        assert rec["by_checker"] == {"donated-reuse": 1}
        # file:line report on stderr
        assert "test_bad.py:3" in cap.err


# -------------------------------------------------- repo self-lint (t1)


class TestSelfLint:
    def test_ast_engine_repo_clean(self):
        paths = [os.path.join(REPO_ROOT, p)
                 for p in ("dlrover_wuqiong_tpu", "tests", "examples",
                           "tools", "bench.py", "__graft_entry__.py")]
        findings, n_files = run_paths([p for p in paths
                                       if os.path.exists(p)])
        assert n_files > 100
        assert findings == [], "\n" + render_report(findings)

    def test_jaxpr_self_audit_clean(self):
        assert self_audit() == []

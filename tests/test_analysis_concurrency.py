"""graftlint v3 concurrency engine (analysis/concurrency_engine.py).

One good + one bad fixture per rule (blocking-under-lock,
lock-order-cycle, unguarded-shared-state, thread-lifecycle), the two
historical-wedge regression fixtures (PR 1 sleep-under-SharedLock, PR 4
replica dial-under-lock — moving the dial back inside the lock span must
fail lint), the suppression grammar against the new rules, the SARIF
output contract, the catalog rows, and the tier-1 repo self-lint: the
concurrency engine over this tree must come back clean.  Pure AST work —
no jax device computation anywhere in this file.
"""

import json
import os
import textwrap

from dlrover_wuqiong_tpu.analysis.concurrency_engine import run_paths
from dlrover_wuqiong_tpu.analysis.findings import (
    RULE_CATALOG,
    check_suppression_reasons,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _scan(tmp_path, relpath, source, **kw):
    """Write one fixture file and run the concurrency engine over it."""
    path = tmp_path / relpath
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    findings, _ = run_paths([str(tmp_path)], **kw)
    return findings


# ------------------------------------------------- blocking-under-lock


class TestBlockingUnderLock:
    def test_sleep_in_with_lock_flagged(self, tmp_path):
        # the PR 1 wedge shape: a wait inside a lock-held span means a
        # SIGKILLed holder wedges every waiter for the full timeout
        found = _scan(tmp_path, "stage.py", """\
            import time

            class Stager:
                def stage(self):
                    with self.shm_lock:
                        time.sleep(600)
            """)
        assert [f.checker for f in found] == ["blocking-under-lock"]
        assert "time.sleep" in found[0].message
        assert found[0].line == 6

    def test_rpc_in_acquire_span_flagged(self, tmp_path):
        found = _scan(tmp_path, "stage.py", """\
            class Stager:
                def stage(self):
                    ok = self.shm_lock.acquire(timeout=5)
                    try:
                        body = retry_call(self._dial)
                    finally:
                        if ok:
                            self.shm_lock.release()
                    return body
            """)
        assert [f.checker for f in found] == ["blocking-under-lock"]
        assert "retry_call" in found[0].message

    def test_blocking_after_release_clean(self, tmp_path):
        # copy under the lock, send after release — the sanctioned shape
        found = _scan(tmp_path, "stage.py", """\
            class Stager:
                def stage(self):
                    ok = self.shm_lock.acquire(timeout=5)
                    try:
                        payload = bytes(self._buf)
                    finally:
                        if ok:
                            self.shm_lock.release()
                    return retry_call(lambda: self._send(payload))
            """)
        assert found == []

    def test_transitive_dial_under_lock_flagged(self, tmp_path):
        # PR 4 regression fixture: checkpoint/replica.py's _segment_bytes
        # holds _seg_lock over the memory copy ONLY and backup() dials
        # AFTER release; moving the dial back inside the span must fail —
        # each call to a dead peer burned the full 150s RPC floor with
        # the staging lock held.
        found = _scan(tmp_path, "replica.py", """\
            import socket
            import threading

            class ReplicaManager:
                def __init__(self):
                    self._seg_lock = threading.Lock()

                def _rpc(self, addr, payload):
                    def dial():
                        return socket.create_connection(addr, timeout=5)
                    return retry_call(dial)

                def _segment_bytes(self):
                    ok = self._seg_lock.acquire(timeout=5)
                    try:
                        payload = bytes(self._buf)
                        return self._rpc(("peer", 1), payload)
                    finally:
                        if ok:
                            self._seg_lock.release()
            """)
        assert "blocking-under-lock" in [f.checker for f in found]
        msg = [f for f in found
               if f.checker == "blocking-under-lock"][0].message
        assert "_rpc" in msg and "_seg_lock" in msg

    def test_pr4_fixed_shape_clean(self, tmp_path):
        # the shipped replica.py shape: lock covers the copy, the dial
        # happens after — lint-clean by construction
        found = _scan(tmp_path, "replica.py", """\
            import socket
            import threading

            class ReplicaManager:
                def __init__(self):
                    self._seg_lock = threading.Lock()

                def _rpc(self, addr, payload):
                    def dial():
                        return socket.create_connection(addr, timeout=5)
                    return retry_call(dial)

                def _segment_bytes(self):
                    ok = self._seg_lock.acquire(timeout=5)
                    try:
                        return bytes(self._buf)
                    finally:
                        if ok:
                            self._seg_lock.release()

                def backup(self, addr):
                    payload = self._segment_bytes()
                    return self._rpc(addr, payload)
            """)
        assert found == []

    def test_subprocess_under_lock_flagged(self, tmp_path):
        found = _scan(tmp_path, "build.py", """\
            import subprocess

            def build(build_lock):
                with build_lock:
                    subprocess.run(["make"], check=True)
            """)
        assert [f.checker for f in found] == ["blocking-under-lock"]
        assert "subprocess" in found[0].message

    def test_lock_typed_attr_resolved_without_lock_name(self, tmp_path):
        # `self._meta = threading.Lock()` makes self._meta a lock even
        # though its name never says so (the SharedLock._meta shape)
        found = _scan(tmp_path, "svc.py", """\
            import threading
            import time

            class Svc:
                def __init__(self):
                    self._meta = threading.Lock()

                def poll(self):
                    with self._meta:
                        time.sleep(1)
            """)
        assert [f.checker for f in found] == ["blocking-under-lock"]
        assert "Svc._meta" in found[0].message


# --------------------------------------------------- lock-order-cycle


class TestLockOrderCycle:
    def test_abba_cycle_flagged(self, tmp_path):
        found = _scan(tmp_path, "mgr.py", """\
            import threading

            class Mgr:
                def __init__(self):
                    self._a_lock = threading.Lock()
                    self._b_lock = threading.Lock()

                def one(self):
                    with self._a_lock:
                        with self._b_lock:
                            pass

                def two(self):
                    with self._b_lock:
                        with self._a_lock:
                            pass
            """)
        assert [f.checker for f in found] == ["lock-order-cycle"]
        assert "Mgr._a_lock" in found[0].message
        assert "Mgr._b_lock" in found[0].message

    def test_consistent_order_clean(self, tmp_path):
        found = _scan(tmp_path, "mgr.py", """\
            import threading

            class Mgr:
                def __init__(self):
                    self._a_lock = threading.Lock()
                    self._b_lock = threading.Lock()

                def one(self):
                    with self._a_lock:
                        with self._b_lock:
                            pass

                def two(self):
                    with self._a_lock:
                        with self._b_lock:
                            pass
            """)
        assert found == []

    def test_transitive_cycle_through_helper_flagged(self, tmp_path):
        # A held while calling a helper that takes B, plus a direct B->A
        # path elsewhere: the cycle spans functions, like the real code
        found = _scan(tmp_path, "mgr.py", """\
            import threading

            class Mgr:
                def __init__(self):
                    self._a_lock = threading.Lock()
                    self._b_lock = threading.Lock()

                def one(self):
                    with self._a_lock:
                        self._under_b()

                def _under_b(self):
                    with self._b_lock:
                        pass

                def two(self):
                    with self._b_lock:
                        with self._a_lock:
                            pass
            """)
        assert [f.checker for f in found] == ["lock-order-cycle"]

    def test_same_lock_reentry_not_an_edge(self, tmp_path):
        # self-edges are out of scope (RLock re-entry is legal); only
        # cycles between DISTINCT locks are ordering deadlocks
        found = _scan(tmp_path, "mgr.py", """\
            import threading

            class Mgr:
                def __init__(self):
                    self._lock = threading.RLock()

                def outer(self):
                    with self._lock:
                        self.inner()

                def inner(self):
                    with self._lock:
                        pass
            """)
        assert found == []


# ---------------------------------------------- unguarded-shared-state


class TestUnguardedSharedState:
    def test_write_write_race_flagged(self, tmp_path):
        found = _scan(tmp_path, "svc.py", """\
            import threading

            class Svc:
                def __init__(self):
                    self._count = 0
                    self._t = threading.Thread(target=self._run,
                                               daemon=True)

                def _run(self):
                    self._count += 1

                def reset(self):
                    self._count = 0
            """)
        assert [f.checker for f in found] == ["unguarded-shared-state"]
        assert "self._count" in found[0].message
        assert "reset" in found[0].message

    def test_inconsistent_guard_flagged(self, tmp_path):
        # the reader holds a lock the worker write ignores — the lock
        # protects nothing
        found = _scan(tmp_path, "svc.py", """\
            import threading

            class Svc:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._state = {}
                    self._t = threading.Thread(target=self._run,
                                               daemon=True)

                def _run(self):
                    self._state = {"fresh": True}

                def snapshot(self):
                    with self._lock:
                        return dict(self._state)
            """)
        assert [f.checker for f in found] == ["unguarded-shared-state"]
        assert "does not hold" in found[0].message

    def test_both_sites_guarded_clean(self, tmp_path):
        found = _scan(tmp_path, "svc.py", """\
            import threading

            class Svc:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._count = 0
                    self._t = threading.Thread(target=self._run,
                                               daemon=True)

                def _run(self):
                    with self._lock:
                        self._count += 1

                def reset(self):
                    with self._lock:
                        self._count = 0
            """)
        assert found == []

    def test_worker_confined_private_helper_clean(self, tmp_path):
        # a private method called only from the worker runs on the
        # worker thread — its writes are same-thread (the ckpt_saver
        # _sync_shm_to_storage -> _update_shard_num shape)
        found = _scan(tmp_path, "svc.py", """\
            import threading

            class Svc:
                def __init__(self):
                    self._num = 0
                    self._t = threading.Thread(target=self._run,
                                               daemon=True)

                def _run(self):
                    self._num = 1
                    self._apply(2)

                def _apply(self, n):
                    self._num = n
            """)
        assert found == []

    def test_join_synchronized_handoff_clean(self, tmp_path):
        # the engine._wait_drain shape: the reader joins the worker
        # before touching the handoff attribute — happens-before, not a
        # race
        found = _scan(tmp_path, "svc.py", """\
            import threading

            class Svc:
                def __init__(self):
                    self._err = None
                    self._t = threading.Thread(target=self._run,
                                               daemon=True)

                def _run(self):
                    self._err = ValueError("boom")

                def wait(self):
                    self._t.join()
                    if self._err is not None:
                        err, self._err = self._err, None
                        raise err
            """)
        assert found == []


# --------------------------------------------------- thread-lifecycle


class TestThreadLifecycle:
    def test_fire_and_forget_nondaemon_flagged(self, tmp_path):
        found = _scan(tmp_path, "svc.py", """\
            import threading

            class Svc:
                def start(self):
                    threading.Thread(target=self._run).start()

                def _run(self):
                    pass
            """)
        assert [f.checker for f in found] == ["thread-lifecycle"]
        assert found[0].severity == "warning"

    def test_daemon_kwarg_clean(self, tmp_path):
        found = _scan(tmp_path, "svc.py", """\
            import threading

            class Svc:
                def start(self):
                    threading.Thread(target=self._run, daemon=True).start()

                def _run(self):
                    pass
            """)
        assert found == []

    def test_joined_on_stop_clean(self, tmp_path):
        found = _scan(tmp_path, "svc.py", """\
            import threading

            class Svc:
                def start(self):
                    self._t = threading.Thread(target=self._run)
                    self._t.start()

                def stop(self):
                    self._t.join(timeout=10)

                def _run(self):
                    pass
            """)
        assert found == []

    def test_daemon_attr_assign_clean(self, tmp_path):
        found = _scan(tmp_path, "svc.py", """\
            import threading

            class Svc:
                def start(self):
                    self._t = threading.Thread(target=self._run)
                    self._t.daemon = True
                    self._t.start()

                def _run(self):
                    pass
            """)
        assert found == []


# ------------------------------------------------ suppression grammar


class TestSuppressions:
    def test_reasoned_disable_silences(self, tmp_path):
        found = _scan(tmp_path, "drill.py", """\
            import time

            def drill(shm_lock):
                with shm_lock:
                    time.sleep(5)  # graftlint: disable=blocking-under-lock -- chaos lock-death drill: the wedge IS the fixture
            """)
        assert found == []

    def test_reasonless_disable_still_suppresses_but_reported(self,
                                                              tmp_path):
        # additive migration contract shared with the other engines: a
        # reason-less disable keeps suppressing, and the AST engine's
        # suppression-reason pass reports the missing reason.  The
        # fixture's disable is assembled at runtime so this file's own
        # raw-line scan doesn't see a reason-less literal.
        src = ("import time\n"
               "def drill(shm_lock):\n"
               "    with shm_lock:\n"
               "        time.sleep(5)  # graftlint: "
               + "disable=blocking-under-lock\n")
        path = tmp_path / "drill.py"
        path.write_text(src)
        found, _ = run_paths([str(tmp_path)])
        assert found == []
        reasons = check_suppression_reasons("drill.py", src.splitlines())
        assert [f.checker for f in reasons] == ["suppression-no-reason"]

    def test_unrelated_disable_does_not_silence(self, tmp_path):
        found = _scan(tmp_path, "drill.py", """\
            import time

            def drill(shm_lock):
                with shm_lock:
                    time.sleep(5)  # graftlint: disable=lock-leak -- wrong rule id on purpose
            """)
        assert [f.checker for f in found] == ["blocking-under-lock"]


# ------------------------------------------------- catalog + CLI + sarif


class TestCatalogAndCli:
    CONCURRENCY_RULES = ("blocking-under-lock", "lock-order-cycle",
                         "unguarded-shared-state", "thread-lifecycle")

    def test_four_rules_cataloged(self):
        for rid in self.CONCURRENCY_RULES:
            entry = RULE_CATALOG[rid]
            assert entry["engine"] == "concurrency"
            assert entry["severity"] in ("error", "warning")
            assert len(entry["rationale"]) > 20

    def test_readme_documents_engine_and_wedges(self):
        readme = open(os.path.join(REPO_ROOT, "README.md")).read()
        for rid in self.CONCURRENCY_RULES:
            assert f"`{rid}`" in readme
        assert "Concurrency discipline" in readme
        # the two motivating historical wedges must stay named
        assert "SAVE_TIMEOUT" in readme
        assert "dial" in readme.lower()

    def test_cli_engine_concurrency(self, tmp_path, capsys):
        from dlrover_wuqiong_tpu.analysis.__main__ import main

        (tmp_path / "ok.py").write_text("x = 1\n")
        rc = main(["--engine", "concurrency", str(tmp_path)])
        out = capsys.readouterr().out.strip().splitlines()
        assert rc == 0 and len(out) == 1
        rec = json.loads(out[0])["graftlint"]
        assert rec["engines"] == ["concurrency"]
        assert rec["ok"] is True

    def test_cli_violation_rc1(self, tmp_path, capsys):
        from dlrover_wuqiong_tpu.analysis.__main__ import main

        (tmp_path / "bad.py").write_text(textwrap.dedent("""\
            import time

            def drill(shm_lock):
                with shm_lock:
                    time.sleep(5)
            """))
        rc = main(["--engine", "concurrency", str(tmp_path)])
        cap = capsys.readouterr()
        assert rc == 1
        rec = json.loads(cap.out.strip())["graftlint"]
        assert rec["by_checker"] == {"blocking-under-lock": 1}
        assert "bad.py:5" in cap.err


class TestSarifOutput:
    def test_sarif_contract(self, tmp_path, capsys):
        """--format sarif: one line, SARIF 2.1.0, findings as results."""
        from dlrover_wuqiong_tpu.analysis.__main__ import main

        (tmp_path / "bad.py").write_text(textwrap.dedent("""\
            import time

            def drill(shm_lock):
                with shm_lock:
                    time.sleep(5)
            """))
        rc = main(["--engine", "concurrency", "--format", "sarif",
                   str(tmp_path)])
        out = capsys.readouterr().out.strip().splitlines()
        assert rc == 1 and len(out) == 1
        doc = json.loads(out[0])
        assert doc["version"] == "2.1.0"
        run = doc["runs"][0]
        assert run["tool"]["driver"]["name"] == "graftlint"
        rules = {r["id"]: r for r in run["tool"]["driver"]["rules"]}
        assert rules["blocking-under-lock"][
            "defaultConfiguration"]["level"] == "error"
        res = run["results"][0]
        assert res["ruleId"] == "blocking-under-lock"
        assert res["level"] == "error"
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"].endswith("bad.py")
        assert loc["region"]["startLine"] == 5

    def test_sarif_clean_run(self, tmp_path, capsys):
        from dlrover_wuqiong_tpu.analysis.__main__ import main

        (tmp_path / "ok.py").write_text("x = 1\n")
        rc = main(["--engine", "concurrency", "--format", "sarif",
                   str(tmp_path)])
        out = capsys.readouterr().out.strip().splitlines()
        assert rc == 0 and len(out) == 1
        doc = json.loads(out[0])
        assert doc["runs"][0]["results"] == []
        assert doc["runs"][0]["tool"]["driver"]["rules"] == []


# -------------------------------------------------- repo self-lint (t1)


class TestConcurrencySelfLint:
    def test_concurrency_engine_repo_clean(self):
        paths = [os.path.join(REPO_ROOT, p)
                 for p in ("dlrover_wuqiong_tpu", "tests", "examples",
                           "tools", "bench.py", "__graft_entry__.py")]
        findings, n_files = run_paths([p for p in paths
                                       if os.path.exists(p)])
        assert findings == [], "\n".join(f.format() for f in findings)
        assert n_files > 100

"""Test config: run JAX on a virtual 8-device CPU mesh (no TPU needed).

Mirrors the reference's test strategy (SURVEY.md §4): multi-node logic is tested
on a single host — here with XLA's forced host-platform device count.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force off TPU even if axon is set
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("DWT_SOCKET_DIR", "/tmp/dwt-test/sockets")

# The axon sitecustomize sets jax_platforms="axon,cpu" via jax.config at
# interpreter start (config beats env); force it back to CPU for tests.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import json  # noqa: E402
import threading  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def schema_lock():
    """The committed wire-surface lockfile (analysis/schema.lock.json).

    The ADD-ONLY pin tests assert the LIVE registries/messages still
    cover the locked surface, so the lock is the single source of truth
    for what "add-only" means; graftlint's schema engine gates the lock
    itself against the source tree.  Each family keeps ONE hand-pinned
    canary so a bad `--update-lock` regeneration can't silently shrink
    both sides at once."""
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "dlrover_wuqiong_tpu", "analysis", "schema.lock.json")
    with open(path) as f:
        return json.load(f)

#: thread-name prefixes tests may legitimately leave running: pytest/
#: plugin internals plus library pools that outlive a single test by
#: design (jax/XLA dispatch pools, concurrent.futures executors are
#: daemonic or process-lifetime and excluded by the daemon check anyway).
_THREAD_ALLOWLIST_PREFIXES = (
    "MainThread", "pydevd.", "ThreadPoolExecutor",
)


def _nondaemon_threads():
    return {
        t for t in threading.enumerate()
        if t.is_alive() and not t.daemon
        and not t.name.startswith(_THREAD_ALLOWLIST_PREFIXES)
    }


@pytest.fixture(autouse=True)
def _thread_leak_guard(request):
    """Fail any test that leaks a non-daemon thread.

    A leaked non-daemon thread hangs interpreter exit — exactly the
    thread-lifecycle wedge graftlint's concurrency engine flags in
    product code; this guard enforces the same discipline on test
    scaffolding.  Pre-existing survivors (leaked by an EARLIER test)
    are baselined out so one leaker doesn't cascade failures; a short
    join grace absorbs threads that are mid-shutdown when the test
    body returns."""
    before = _nondaemon_threads()
    yield
    leaked = _nondaemon_threads() - before
    if not leaked:
        return
    deadline = 1.0 / max(len(leaked), 1)
    for t in leaked:
        t.join(timeout=deadline)
    leaked = {t for t in leaked if t.is_alive()}
    if leaked:
        names = sorted(f"{t.name} (target={getattr(t, '_target', None)})"
                       for t in leaked)
        pytest.fail(
            f"test leaked non-daemon thread(s): {names} — join them or "
            f"mark them daemon (see graftlint thread-lifecycle)",
            pytrace=False)

"""Test config: run JAX on a virtual 8-device CPU mesh (no TPU needed).

Mirrors the reference's test strategy (SURVEY.md §4): multi-node logic is tested
on a single host — here with XLA's forced host-platform device count.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force off TPU even if axon is set
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("DWT_SOCKET_DIR", "/tmp/dwt-test/sockets")

# The axon sitecustomize sets jax_platforms="axon,cpu" via jax.config at
# interpreter start (config beats env); force it back to CPU for tests.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

"""MoE layer + expert parallelism tests on the virtual 8-device mesh.

Mirrors the reference's moe tests (atorch modules/moe) translated to
dense-dispatch GShard-style MoE under GSPMD.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from version_gates import shard_index_set

from dlrover_wuqiong_tpu.auto.accelerate import auto_accelerate
from dlrover_wuqiong_tpu.models.gpt import GPT, GPTConfig
from dlrover_wuqiong_tpu.models.moe import MoEConfig, MoEMLP, top_k_gating


class TestTopKGating:
    def test_top1_each_token_dispatched_once(self):
        logits = jax.random.normal(jax.random.PRNGKey(0), (16, 4))
        combine, dispatch = top_k_gating(logits, k=1, capacity=16)
        # every token lands in exactly one (expert, slot)
        assert dispatch.sum() == 16
        np.testing.assert_allclose(combine.sum(axis=(1, 2)),
                                   np.ones(16), atol=1e-6)

    def test_top2_combine_normalized(self):
        logits = jax.random.normal(jax.random.PRNGKey(1), (32, 8))
        combine, dispatch = top_k_gating(logits, k=2, capacity=32)
        assert int(dispatch.sum()) == 64  # 2 slots per token
        np.testing.assert_allclose(combine.sum(axis=(1, 2)),
                                   np.ones(32), atol=1e-6)

    def test_capacity_drops_overflow(self):
        # all tokens prefer expert 0; capacity 4 keeps only 4
        logits = jnp.stack([jnp.full((16,), 5.0)] + [jnp.zeros(16)] * 3,
                           axis=1)
        combine, dispatch = top_k_gating(logits, k=1, capacity=4)
        assert int(dispatch[:, 0].sum()) == 4

    def test_no_slot_collisions(self):
        logits = jax.random.normal(jax.random.PRNGKey(2), (64, 4))
        combine, dispatch = top_k_gating(logits, k=2, capacity=64)
        # each (expert, slot) holds at most one token
        per_slot = dispatch.sum(axis=0)
        assert int(per_slot.max()) <= 1

    def test_aux_loss_penalizes_imbalance(self):
        """The Switch aux loss (sown by MoEMLP) must be larger for skewed
        than for balanced routing."""
        import jax

        def sown_aux(router_kernel):
            cfg = MoEConfig(num_experts=4, top_k=1, dtype=jnp.float32)
            mlp = MoEMLP(hidden=4, ffn=8, moe=cfg)
            x = jnp.ones((1, 16, 4))
            params = mlp.init(jax.random.PRNGKey(0), x)["params"]
            params["router"]["kernel"] = router_kernel
            _, upd = mlp.apply({"params": params}, x,
                               mutable=["intermediates"])
            return float(jax.tree.leaves(
                upd["intermediates"]["moe_aux_loss"])[0])

        # uniform tokens: router weights decide the distribution entirely
        balanced = jnp.eye(4)           # argmax varies... all tokens equal
        skewed = jnp.zeros((4, 4)).at[:, 0].set(5.0)
        assert sown_aux(skewed) > sown_aux(balanced) - 1e-6


class TestMoEMLP:
    def test_forward_shape_and_aux(self):
        layer = MoEMLP(hidden=32, ffn=64, moe=MoEConfig(
            num_experts=4, top_k=2, dtype=jnp.float32))
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 32))
        params = layer.init(jax.random.PRNGKey(1), x)
        y, updates = layer.apply(params, x, mutable=["intermediates"])
        assert y.shape == x.shape
        assert "moe_aux_loss" in updates["intermediates"]


class TestMoETraining:
    def test_gpt_moe_trains_with_expert_parallelism(self):
        cfg = GPTConfig(vocab_size=256, n_layer=2, n_head=2, n_embd=64,
                        block_size=64, dtype=jnp.float32, moe_experts=4)
        res = auto_accelerate(
            GPT(cfg), optimizer=optax.adamw(1e-2),
            strategy=[("expert_parallel", {"size": 4}), ("fsdp", {})])
        data = jax.random.randint(jax.random.PRNGKey(0), (8, 65), 0, 256)
        batch = res.place_batch({"input_ids": data[:, :-1],
                                 "labels": data[:, 1:]})
        state, losses = res.state, []
        for _ in range(8):
            state, m = res.train_step(state, batch)
            losses.append(float(m["loss"]))
        assert np.isfinite(losses).all() and losses[-1] < losses[0]

    def test_expert_weights_sharded_over_ep(self):
        cfg = GPTConfig(vocab_size=256, n_layer=1, n_head=2, n_embd=64,
                        block_size=64, dtype=jnp.float32, moe_experts=4)
        res = auto_accelerate(
            GPT(cfg), optimizer=optax.adamw(1e-2),
            strategy=[("expert_parallel", {"size": 4}), ("fsdp", {})])
        w = res.state.params["h_0"]["moe_mlp"]["experts_w_in"]
        # 4 experts over ep=4 (x fsdp=2): expert dim must be split
        idx = {t[0] for t in shard_index_set(w)}
        assert len(idx) == 4

    def test_moe_matches_dense_param_count_scaling(self):
        dense = GPTConfig(vocab_size=256, n_layer=1, n_head=2, n_embd=64,
                          block_size=64)
        moe = GPTConfig(vocab_size=256, n_layer=1, n_head=2, n_embd=64,
                        block_size=64, moe_experts=4)
        pd = GPT(dense).init_params(jax.random.PRNGKey(0))
        pm = GPT(moe).init_params(jax.random.PRNGKey(0))
        nd = sum(x.size for x in jax.tree.leaves(pd))
        nm = sum(x.size for x in jax.tree.leaves(pm))
        assert nm > nd  # experts multiply MLP params


class TestGroupedMoE:
    """Dropless grouped-GEMM path (parity grouped_gemm_moe.py)."""

    def test_matches_explicit_expert_loop(self):
        import jax
        from dlrover_wuqiong_tpu.models.moe import grouped_moe

        T, d, f, E, k = 16, 8, 16, 4, 2
        key = jax.random.PRNGKey(0)
        ks = jax.random.split(key, 5)
        tokens = jax.random.normal(ks[0], (T, d))
        probs = jax.nn.softmax(jax.random.normal(ks[1], (T, E)), -1)
        w_gate = jax.random.normal(ks[2], (E, d, f)) * 0.1
        w_in = jax.random.normal(ks[3], (E, d, f)) * 0.1
        w_down = jax.random.normal(ks[4], (E, f, d)) * 0.1

        got = grouped_moe(tokens, probs, w_gate, w_in, w_down, k)

        # explicit reference: per token, run its top-k experts densely
        gates, experts = jax.lax.top_k(probs, k)
        gates = gates / gates.sum(-1, keepdims=True)
        want = np.zeros((T, d), np.float32)
        for t in range(T):
            for j in range(k):
                e = int(experts[t, j])
                x = tokens[t]
                h = jax.nn.silu(x @ w_gate[e]) * (x @ w_in[e])
                want[t] += float(gates[t, j]) * np.asarray(h @ w_down[e])
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-4)

    def test_no_tokens_dropped_under_imbalance(self):
        """Every token contributes even when one expert takes the whole
        batch (the capacity impl would drop overflow)."""
        import jax
        from dlrover_wuqiong_tpu.models.moe import grouped_moe

        T, d, f, E = 32, 4, 8, 4
        tokens = jnp.ones((T, d))
        # router sends EVERYTHING to expert 0
        probs = jnp.zeros((T, E)).at[:, 0].set(1.0)
        w = jnp.ones((E, d, f)) * 0.1
        wd = jnp.ones((E, f, d)) * 0.1
        out = grouped_moe(tokens, probs, w, w, wd, 1)
        # all rows identical and nonzero — nothing dropped
        assert float(jnp.abs(out).sum()) > 0
        np.testing.assert_allclose(np.asarray(out[0]), np.asarray(out[-1]),
                                   atol=1e-6)

    def test_grouped_impl_trains_in_model(self):
        import dataclasses as dc

        import jax
        import optax
        from dlrover_wuqiong_tpu.models.moe import MoEConfig, MoEMLP

        cfg = MoEConfig(num_experts=4, top_k=2, dtype=jnp.float32,
                        impl="grouped")
        mlp = MoEMLP(hidden=8, ffn=16, moe=cfg)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 8))
        params = mlp.init(jax.random.PRNGKey(1), x)["params"]
        target = jnp.ones((2, 8, 8))
        opt = optax.adam(1e-2)
        state = opt.init(params)

        @jax.jit
        def step(params, state):
            def loss_fn(p):
                y, upd = mlp.apply({"params": p}, x,
                                   mutable=["intermediates"])
                return ((y - target) ** 2).mean()
            loss, g = jax.value_and_grad(loss_fn)(params)
            updates, state = opt.update(g, state, params)
            return optax.apply_updates(params, updates), state, loss

        losses = []
        for _ in range(30):
            params, state, loss = step(params, state)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.5

"""MoE layer + expert parallelism tests on the virtual 8-device mesh.

Mirrors the reference's moe tests (atorch modules/moe) translated to
dense-dispatch GShard-style MoE under GSPMD.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_wuqiong_tpu.auto.accelerate import auto_accelerate
from dlrover_wuqiong_tpu.models.gpt import GPT, GPTConfig
from dlrover_wuqiong_tpu.models.moe import MoEConfig, MoEMLP, top_k_gating


class TestTopKGating:
    def test_top1_each_token_dispatched_once(self):
        logits = jax.random.normal(jax.random.PRNGKey(0), (16, 4))
        combine, dispatch, aux = top_k_gating(logits, k=1, capacity=16)
        # every token lands in exactly one (expert, slot)
        assert dispatch.sum() == 16
        np.testing.assert_allclose(combine.sum(axis=(1, 2)),
                                   np.ones(16), atol=1e-6)

    def test_top2_combine_normalized(self):
        logits = jax.random.normal(jax.random.PRNGKey(1), (32, 8))
        combine, dispatch, aux = top_k_gating(logits, k=2, capacity=32)
        assert int(dispatch.sum()) == 64  # 2 slots per token
        np.testing.assert_allclose(combine.sum(axis=(1, 2)),
                                   np.ones(32), atol=1e-6)

    def test_capacity_drops_overflow(self):
        # all tokens prefer expert 0; capacity 4 keeps only 4
        logits = jnp.stack([jnp.full((16,), 5.0)] + [jnp.zeros(16)] * 3,
                           axis=1)
        combine, dispatch, aux = top_k_gating(logits, k=1, capacity=4)
        assert int(dispatch[:, 0].sum()) == 4

    def test_no_slot_collisions(self):
        logits = jax.random.normal(jax.random.PRNGKey(2), (64, 4))
        combine, dispatch, aux = top_k_gating(logits, k=2, capacity=64)
        # each (expert, slot) holds at most one token
        per_slot = dispatch.sum(axis=0)
        assert int(per_slot.max()) <= 1

    def test_aux_loss_penalizes_imbalance(self):
        balanced = jnp.tile(jnp.eye(4), (4, 1)) * 4.0
        skewed = jnp.stack([jnp.full((16,), 4.0)] + [jnp.zeros(16)] * 3,
                           axis=1)
        _, _, aux_b = top_k_gating(balanced, 1, 16)
        _, _, aux_s = top_k_gating(skewed, 1, 16)
        assert float(aux_s) > float(aux_b)


class TestMoEMLP:
    def test_forward_shape_and_aux(self):
        layer = MoEMLP(hidden=32, ffn=64, moe=MoEConfig(
            num_experts=4, top_k=2, dtype=jnp.float32))
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 32))
        params = layer.init(jax.random.PRNGKey(1), x)
        y, updates = layer.apply(params, x, mutable=["intermediates"])
        assert y.shape == x.shape
        assert "moe_aux_loss" in updates["intermediates"]


class TestMoETraining:
    def test_gpt_moe_trains_with_expert_parallelism(self):
        cfg = GPTConfig(vocab_size=256, n_layer=2, n_head=2, n_embd=64,
                        block_size=64, dtype=jnp.float32, moe_experts=4)
        res = auto_accelerate(
            GPT(cfg), optimizer=optax.adamw(1e-2),
            strategy=[("expert_parallel", {"size": 4}), ("fsdp", {})])
        data = jax.random.randint(jax.random.PRNGKey(0), (8, 65), 0, 256)
        batch = res.place_batch({"input_ids": data[:, :-1],
                                 "labels": data[:, 1:]})
        state, losses = res.state, []
        for _ in range(8):
            state, m = res.train_step(state, batch)
            losses.append(float(m["loss"]))
        assert np.isfinite(losses).all() and losses[-1] < losses[0]

    def test_expert_weights_sharded_over_ep(self):
        cfg = GPTConfig(vocab_size=256, n_layer=1, n_head=2, n_embd=64,
                        block_size=64, dtype=jnp.float32, moe_experts=4)
        res = auto_accelerate(
            GPT(cfg), optimizer=optax.adamw(1e-2),
            strategy=[("expert_parallel", {"size": 4}), ("fsdp", {})])
        w = res.state.params["h_0"]["moe_mlp"]["experts_w_in"]
        # 4 experts over ep=4 (x fsdp=2): expert dim must be split
        idx = {s.index[0] for s in w.addressable_shards}
        assert len(idx) == 4

    def test_moe_matches_dense_param_count_scaling(self):
        dense = GPTConfig(vocab_size=256, n_layer=1, n_head=2, n_embd=64,
                          block_size=64)
        moe = GPTConfig(vocab_size=256, n_layer=1, n_head=2, n_embd=64,
                        block_size=64, moe_experts=4)
        pd = GPT(dense).init_params(jax.random.PRNGKey(0))
        pm = GPT(moe).init_params(jax.random.PRNGKey(0))
        nd = sum(x.size for x in jax.tree.leaves(pd))
        nm = sum(x.size for x in jax.tree.leaves(pm))
        assert nm > nd  # experts multiply MLP params

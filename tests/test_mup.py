"""muP tests: classification, lr table, and the coordinate check —
hidden-activation scale must stay ~width-independent under μP while
drifting with width under standard parametrization.

Mirrors reference atorch/mup tests in spirit.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_wuqiong_tpu.optimizers.mup import (
    classify_param,
    mup_adam,
    mup_attn_scale,
    mup_init,
    width_mults,
)


class MLP(nn.Module):
    width: int
    vocab: int = 32

    @nn.compact
    def __call__(self, x):
        h = nn.Embed(self.vocab, self.width, name="embed")(x)
        h = nn.relu(nn.Dense(self.width, name="hidden1")(h))
        h = nn.relu(nn.Dense(self.width, name="hidden2")(h))
        return nn.Dense(self.vocab, name="lm_head")(h)


def _init(width, seed=0):
    m = MLP(width)
    p = m.init(jax.random.PRNGKey(seed), jnp.zeros((2, 4), jnp.int32))
    return m, p["params"]


class TestClassification:
    def test_roles(self):
        _, base = _init(8)
        _, big = _init(32)
        mults = width_mults(base, big)
        assert mults["embed"]["embedding"]["role"] == "input"
        assert mults["hidden1"]["kernel"]["role"] == "hidden"
        assert mults["hidden1"]["kernel"]["mult"] == 4.0
        assert mults["lm_head"]["kernel"]["role"] == "output"
        assert mults["hidden1"]["bias"]["role"] == "finite"

    def test_finite_when_same_width(self):
        _, a = _init(8)
        _, b = _init(8, seed=1)
        mults = width_mults(a, b)
        for leaf in jax.tree.leaves(
                mults, is_leaf=lambda x: isinstance(x, dict)
                and "mult" in x):
            assert leaf["role"] == "finite" or leaf["mult"] == 1.0

    def test_classify_param_direct(self):
        assert classify_param("h/ln/scale", (8,), (32,)) == "finite"
        assert classify_param("wte/embedding", (32, 8), (32, 32)) == "input"
        assert classify_param("lm_head/kernel", (8, 32),
                              (32, 32)) == "output"
        assert classify_param("mlp/kernel", (8, 8), (32, 32)) == "hidden"


class TestInitAndLr:
    def test_init_rescale(self):
        _, base = _init(8)
        _, big = _init(32)
        mults = width_mults(base, big)
        scaled = mup_init(big, mults)
        # hidden kernel shrunk by sqrt(4)=2; embedding untouched
        np.testing.assert_allclose(
            np.asarray(scaled["hidden1"]["kernel"]),
            np.asarray(big["hidden1"]["kernel"]) / 2.0)
        np.testing.assert_array_equal(
            np.asarray(scaled["embed"]["embedding"]),
            np.asarray(big["embed"]["embedding"]))

    def test_adam_lr_table(self):
        _, base = _init(8)
        _, big = _init(32)
        mults = width_mults(base, big)
        opt = mup_adam(1.0, mults)
        state = opt.init(big)
        grads = jax.tree.map(jnp.ones_like, big)
        updates, _ = opt.update(grads, state, big)
        # adam normalizes to ~1; μP divides hidden/output by mult=4
        hid = float(jnp.abs(updates["hidden1"]["kernel"]).mean())
        emb = float(jnp.abs(updates["embed"]["embedding"]).mean())
        assert abs(emb / hid - 4.0) < 0.2

    def test_attn_scale(self):
        assert mup_attn_scale(64) == 1.0 / 64


class TestCoordinateCheck:
    """The μP acceptance test: after a few training steps, hidden
    pre-activation magnitudes stay O(1) across widths under μP, while SP
    (standard Adam) grows them with width."""

    def _run(self, width, use_mup, steps=5, lr=1e-2):
        model, params = _init(width)
        _, base = _init(8)
        if use_mup:
            mults = width_mults(base, params)
            params = mup_init(params, mults)
            opt = mup_adam(lr, mults)
        else:
            opt = optax.adam(lr)
        state = opt.init(params)
        x = jax.random.randint(jax.random.PRNGKey(1), (16, 4), 0, 32)
        y = jax.random.randint(jax.random.PRNGKey(2), (16, 4), 0, 32)

        @jax.jit
        def step(params, state):
            def loss_fn(p):
                logits = model.apply({"params": p}, x)
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits, y).mean()
            g = jax.grad(loss_fn)(params)
            updates, state = opt.update(g, state, params)
            return optax.apply_updates(params, updates), state

        for _ in range(steps):
            params, state = step(params, state)
        # magnitude of the 2nd hidden pre-activation
        h = nn.Embed(32, width).apply(
            {"params": params["embed"]}, x)
        h = nn.relu(nn.Dense(width).apply({"params": params["hidden1"]}, h))
        pre = nn.Dense(width).apply({"params": params["hidden2"]}, h)
        return float(jnp.abs(pre).mean())

    def test_mup_width_stability(self):
        mags_mup = [self._run(w, use_mup=True) for w in (32, 128, 512)]
        mags_sp = [self._run(w, use_mup=False) for w in (32, 128, 512)]
        ratio_mup = mags_mup[-1] / mags_mup[0]
        ratio_sp = mags_sp[-1] / mags_sp[0]
        # μP: roughly flat across 16x width; SP: grows markedly faster
        assert ratio_mup < 2.0, (mags_mup, mags_sp)
        assert ratio_sp > ratio_mup * 1.5, (mags_mup, mags_sp)

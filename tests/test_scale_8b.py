"""The 8B north-star scale proof (round-5 verdict item #1).

BASELINE.json's headline metric is Llama-8B on v5p; before this suite,
nothing in the repo had ever been compiled above 124M params.  These tests
AOT-compile the FULL auto_accelerate train step for the real Llama-3-8B
config (32 layers / 128256 vocab / seq 4096) on a virtual 16-device mesh —
no weights materialized (auto_accelerate(materialize=False); parity:
reference meta_model_utils.py:1-759 meta-device init for 65B-class models)
— and assert per-device memory from `compiled.memory_analysis()`.

What is asserted vs. what is bounded:

- argument/output bytes are EXACT per-device train-state bytes under the
  strategy's shardings — the dominant 8B fit term.  fsdp16 + f32 Adam:
  8.03e9 params x 12 B / 16 dev = 5.61 GiB/device (vs v5p's 95 GiB).
- `temp_size_in_bytes` is NOT asserted: XLA:CPU buffer assignment reports
  the SUM of temps without TPU's liveness reuse (measured: remat OFF and
  remat 'dots' report identical CPU temps), so it cannot model TPU peak.
  The TPU activation peak is bounded analytically instead: full remat
  saves L x T_local x C block inputs (32 x 4096 x 4096 x 2B = 1 GiB at
  per-device batch 1) + f32 logits (4096 x 128256 x 4B = 2.1 GiB) + one
  layer's recompute working set — comfortably inside the v5p budget next
  to 5.6 GiB of state.

The subprocess runs use 16 virtual CPU devices (the in-process suite mesh
is fixed at 8 by conftest), exercising exactly the per-device shard sizes
a v5p-16 would see.
"""

import json
import os
import subprocess
import sys

import pytest

from version_gates import requires_pinned_host

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
V5P_HBM_GIB = 95.0


def _run_fit(n_dev: int, config: dict, timeout: float = 540.0) -> dict:
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # the probe sets its own device count
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "scale_fit.py"),
         str(n_dev), json.dumps(config)],
        capture_output=True, text=True, timeout=timeout, env=env)
    assert out.returncode == 0, out.stderr[-3000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


class TestScale8B:
    # tier-2: ~190s AOT compile; the analytic fit bounds are asserted by
    # the fast TestScaleAbstract siblings, and tools/scale_fit.py runs
    # this compile on demand
    @pytest.mark.slow
    def test_fsdp16_remat_dots_compiles_and_fits(self):
        """Full Llama-8B train step, fsdp16, remat dots, seq 4096."""
        r = _run_fit(16, {
            "model": "8b", "seq": 4096,
            "strategy": [["fsdp", {}],
                         ["checkpoint", {"policy": "dots"}]]})
        assert r["ok"] and r["mesh"] == "fsdp16"
        assert r["params"] == 8030261248
        # exact per-device state: params f32 + adam mu/nu f32 = 12 B/param
        expect = 8030261248 * 12 / 16 / 2**30
        assert abs(r["arg_gib"] - expect) < 0.2, r
        # the fit itself: state + the analytic activation bound (~6 GiB,
        # module docstring) is far inside one v5p's HBM
        assert r["arg_gib"] + 6.0 < V5P_HBM_GIB, r

    @requires_pinned_host
    def test_fsdp8_tp2_bf16_offload_compiles_and_fits(self):
        """fsdp8 x tp2 with bf16 params (stable master) + host moments."""
        r = _run_fit(16, {
            "model": "8b", "seq": 4096,
            "strategy": [["fsdp", {"size": 8}],
                         ["tensor_parallel", {"size": 2}],
                         ["stable_bf16", {"master": True}],
                         ["optimizer_offload", {}]]})
        assert r["ok"] and "tp2" in r["mesh"], r
        # bf16 params (2B) + f32 master (4B) + f32 mu/nu (8B) = 14 B/param
        # over 16 devices.  (CPU memory_analysis does not split host args
        # out — the pinned_host placement is asserted separately below.)
        expect = 8030261248 * 14 / 16 / 2**30
        assert abs(r["arg_gib"] - expect) < 0.3, r
        # on device after offload: params 6 B/param -> ~2.8 GiB/device
        device_resident = 8030261248 * 6 / 16 / 2**30
        assert device_resident + 6.0 < V5P_HBM_GIB


class TestScaleAbstract:
    """No-compile scale checks: eval_shape state + shardings are cheap."""

    def _abstract_state(self, model_name, strategy, n_dev=8):
        import jax
        import optax

        from dlrover_wuqiong_tpu.auto.accelerate import auto_accelerate
        from dlrover_wuqiong_tpu.models.llama import Llama, LlamaConfig

        cfg = {"8b": LlamaConfig.llama3_8b,
               "70b": LlamaConfig.llama3_70b}[model_name]()
        return auto_accelerate(
            Llama(cfg), optimizer=optax.adamw(3e-4), strategy=strategy,
            materialize=False, devices=jax.devices()[:n_dev]).state

    @requires_pinned_host
    def test_offload_moments_are_pinned_host_at_8b(self):
        import jax

        state = self._abstract_state(
            "8b", [["fsdp", {}], ["optimizer_offload", {}]])
        kinds = {getattr(leaf.sharding, "memory_kind", None)
                 for leaf in jax.tree.leaves(state.opt_state)
                 if hasattr(leaf, "sharding") and leaf.ndim > 0}
        assert "pinned_host" in kinds, kinds
        pkinds = {leaf.sharding.memory_kind
                  for leaf in jax.tree.leaves(state.params)}
        assert pkinds == {"device"}

    def test_70b_state_bytes_per_device_fit_v5p64(self):
        """70B f32-Adam state sharded over 64 devices fits v5p HBM."""
        import jax

        state = self._abstract_state("70b", [["fsdp", {}]])
        total = sum(leaf.size * leaf.dtype.itemsize
                    for leaf in jax.tree.leaves(state))
        assert total > 70e9 * 12 * 0.99  # it really is the 70B f32 state
        per_dev_64 = total / 64 / 2**30
        assert per_dev_64 < V5P_HBM_GIB, per_dev_64


class TestAutoPlanPins:
    """Regression pins for the heuristic planner at north-star shapes
    (round-4 verdict weak #6: a silent heuristic change must not ship)."""

    def test_8b_16dev(self):
        from dlrover_wuqiong_tpu.parallel.mesh import auto_plan

        p = auto_plan(16, int(8.03e9), hbm_per_device=95 << 30)
        assert (p.fsdp, p.tp, p.dp, p.pp) == (16, 1, 1, 1), p

    def test_8b_16dev_v5e(self):
        from dlrover_wuqiong_tpu.parallel.mesh import auto_plan

        p = auto_plan(16, int(8.03e9), hbm_per_device=16 << 30)
        assert (p.fsdp, p.tp) == (16, 1), p

    def test_70b_128dev(self):
        from dlrover_wuqiong_tpu.parallel.mesh import auto_plan

        p = auto_plan(128, int(70.6e9), hbm_per_device=95 << 30)
        assert (p.fsdp, p.tp) == (16, 8), p

    def test_70b_64dev(self):
        from dlrover_wuqiong_tpu.parallel.mesh import auto_plan

        p = auto_plan(64, int(70.6e9), hbm_per_device=95 << 30)
        assert (p.fsdp, p.tp) == (8, 8), p


class TestAutoPlanGridInvariants:
    """Beyond the 4 pinned north-star shapes: a realistic (params,
    devices, HBM) grid where every plan must satisfy the planner's own
    contract — axes multiply to the device count, and the optimizer
    state fits the combined HBM of the state-sharding axes."""

    GRID = [
        (1.5e9, 8, 16), (1.5e9, 8, 95), (8.03e9, 8, 95),
        (8.03e9, 32, 16), (8.03e9, 32, 95), (13e9, 16, 95),
        (34e9, 64, 95), (70.6e9, 256, 95), (180e9, 256, 95),
        (405e9, 512, 95),
    ]

    @pytest.mark.parametrize("params,devices,hbm_gib", GRID)
    def test_plan_fits_and_multiplies(self, params, devices, hbm_gib):
        import math

        from dlrover_wuqiong_tpu.parallel.mesh import auto_plan

        plan = auto_plan(devices, int(params),
                         hbm_per_device=hbm_gib << 30)
        sizes = [plan.dp, plan.pp, plan.fsdp, plan.ep, plan.sp, plan.tp]
        assert math.prod(sizes) == devices, (plan, devices)
        # the planner's own fit rule: state (14 B/param incl. bf16
        # params + f32 master+moments) sharded over tp*fsdp must fit
        # 70% of per-device HBM
        state_bytes = params * 14
        min_shards = max(1, math.ceil(
            state_bytes / ((hbm_gib << 30) * 0.7)))
        assert plan.tp * plan.fsdp >= min(min_shards, devices), (
            plan, min_shards)

    def test_sp_only_for_long_sequences(self):
        from dlrover_wuqiong_tpu.parallel.mesh import auto_plan

        short = auto_plan(32, int(8e9), hbm_per_device=95 << 30,
                          seq_len=8192)
        assert short.sp == 1, short
        long = auto_plan(32, int(8e9), hbm_per_device=95 << 30,
                         seq_len=131072)
        assert long.sp > 1, long

"""Brain service/client tests (reference go/brain parity).

Mirrors the brain's gotest coverage in spirit: persist → optimize flows,
fleet prior for cold jobs, degradation when the service is down.
"""

import pytest

from dlrover_wuqiong_tpu.brain import (
    BrainClient,
    BrainResourceOptimizer,
    BrainService,
)
from dlrover_wuqiong_tpu.common.node import NodeResource


_OPT_KW = dict(default_resource=NodeResource(cpu=2, memory_mb=500),
               sample_after=2, stable_after=4, headroom=2.0)


@pytest.fixture()
def brain():
    svc = BrainService(**_OPT_KW)
    svc.start()
    yield svc
    svc.stop()


class TestBrain:
    def test_persist_then_optimize(self, brain):
        c = BrainClient(brain.addr, "job1")
        c.persist_metrics("worker", cpu=1.0, memory_mb=800)
        c.persist_metrics("worker", cpu=1.0, memory_mb=900)
        resp = c.optimize("worker")
        assert resp.stage == "sample"
        assert resp.memory_mb == 1800  # max * headroom
        c.close()

    def test_cold_job_inherits_fleet_prior(self, brain):
        c1 = BrainClient(brain.addr, "jobA")
        for _ in range(3):
            c1.persist_metrics("worker", cpu=2.0, memory_mb=1000)
        # a brand-new job gets the fleet's plan, not defaults
        c2 = BrainClient(brain.addr, "jobB")
        resp = c2.optimize("worker")
        assert resp.stage in ("sample", "stable")
        assert resp.memory_mb == 2000
        c1.close()
        c2.close()

    def test_get_job_metrics(self, brain):
        c = BrainClient(brain.addr, "jobM")
        c.persist_metrics("worker", cpu=1.5, memory_mb=512)
        samples = c.get_job_metrics("worker")
        assert "512" in samples
        c.close()

    def test_snapshot_roundtrip(self, tmp_path):
        path = str(tmp_path / "brain.json")
        svc = BrainService(snapshot_path=path, **_OPT_KW)
        svc.start()
        c = BrainClient(svc.addr, "jobS")
        for _ in range(3):
            c.persist_metrics("worker", cpu=1.0, memory_mb=700)
        c.close()
        svc.stop()
        # a restarted brain remembers the fleet
        svc2 = BrainService(snapshot_path=path, **_OPT_KW)
        svc2.start()
        c2 = BrainClient(svc2.addr, "another-job")
        resp = c2.optimize("worker")
        assert resp.stage != "init"
        c2.close()
        svc2.stop()


class TestBrainResourceOptimizer:
    def test_prefers_brain_plan(self, brain):
        opt = BrainResourceOptimizer(brain.addr, "jobO", **_OPT_KW)
        opt.report_usage("worker", NodeResource(cpu=1, memory_mb=600))
        opt.report_usage("worker", NodeResource(cpu=1, memory_mb=650))
        plan = opt.plan_node_resource("worker")
        assert plan.memory_mb == 1300  # brain's answer (same math here)

    def test_degrades_to_local_when_brain_down(self):
        opt = BrainResourceOptimizer("127.0.0.1:1", "jobX", **_OPT_KW)
        # reports fail silently; local samples still accumulate
        opt.report_usage("worker", NodeResource(cpu=1, memory_mb=500))
        opt.report_usage("worker", NodeResource(cpu=1, memory_mb=600))
        plan = opt.plan_node_resource("worker")
        assert plan.memory_mb == 1200  # local phased plan


class TestBrainPlugins:
    """Datastore + named-algorithm plugin layer (plugins.py)."""

    def test_algorithm_registry_names(self):
        from dlrover_wuqiong_tpu.brain.plugins import algorithms

        assert set(algorithms()) >= {
            "optimize_job_worker_create_resource",
            "optimize_job_worker_init_adjust_resource",
            "optimize_job_worker_resource",
            "optimize_job_worker_create_oom_resource"}

    def test_oom_event_selects_bump_algorithm(self, brain):
        c = BrainClient(brain.addr, "jobOOM")
        for _ in range(3):
            c.persist_metrics("worker", cpu=1.0, memory_mb=1000)
        from dlrover_wuqiong_tpu.common import messages as msg

        resp = c._client.get(msg.BrainOptimizeRequest(
            job_name="jobOOM", node_type="worker", event="oom"))
        assert resp.algorithm == "optimize_job_worker_create_oom_resource"
        assert resp.memory_mb >= 1500  # peak x oom_factor
        c.close()

    def test_stage_algorithm_progression(self, brain):
        c = BrainClient(brain.addr, "jobProg")
        r0 = c.optimize("worker")
        assert r0.algorithm == "optimize_job_worker_create_resource"
        for _ in range(3):
            c.persist_metrics("worker", cpu=1.0, memory_mb=100)
        r1 = c.optimize("worker")
        assert r1.algorithm == "optimize_job_worker_init_adjust_resource"
        for _ in range(12):
            c.persist_metrics("worker", cpu=1.0, memory_mb=100)
        r2 = c.optimize("worker")
        assert r2.algorithm == "optimize_job_worker_resource"
        c.close()

    def test_json_datastore_batched_flush(self, tmp_path):
        import json as _json

        from dlrover_wuqiong_tpu.brain.plugins import JsonFileDataStore

        path = str(tmp_path / "ds.json")
        ds = JsonFileDataStore(path, flush_every=3)
        ds.append("j", "worker", {"cpu": 1, "memory_mb": 2})
        ds.append("j", "worker", {"cpu": 1, "memory_mb": 2})
        import os

        assert not os.path.exists(path)  # below the batch threshold
        ds.append("j", "worker", {"cpu": 1, "memory_mb": 2})
        assert os.path.exists(path)      # batch flushed
        data = _json.loads(open(path).read())
        assert len(data["j"]["worker"]) == 3
        # reload sees the same history
        ds2 = JsonFileDataStore(path)
        assert len(ds2.samples("j", "worker")) == 3

    def test_nearest_rank_percentile(self):
        from dlrover_wuqiong_tpu.brain.plugins import _percentile

        assert _percentile([1000, 1000, 8000], 0.95) == 8000
        assert _percentile([1, 2, 3, 4], 0.5) == 2
        assert _percentile([5], 0.95) == 5

    def test_pre_plugin_snapshot_rebuilds_fleet(self, tmp_path):
        """Snapshots written by the pre-plugin service (no __fleet__ key)
        must still seed the fleet prior after a restart."""
        import json as _json

        path = str(tmp_path / "old.json")
        with open(path, "w") as f:
            _json.dump({"legacy-job": {"worker": [
                {"cpu": 2.0, "memory_mb": 1000}] * 3}}, f)
        svc = BrainService(snapshot_path=path, **_OPT_KW)
        svc.start()
        c = BrainClient(svc.addr, "fresh-job")
        resp = c.optimize("worker")
        assert resp.stage != "init"       # fleet prior present
        assert resp.memory_mb > 0
        c.close()
        svc.stop()


class TestSqliteDataStore:
    """SQL-durable datastore (reference MySQL datastore role, mysql.go):
    every append is a durable row; restart replays the table."""

    def test_append_survives_restart(self, tmp_path):
        from dlrover_wuqiong_tpu.brain.plugins import SqliteDataStore

        path = str(tmp_path / "brain.db")
        ds = SqliteDataStore(path)
        for i in range(5):
            ds.append("j1", "worker", {"cpu": float(i), "memory_mb": 100})
        ds.append("j2", "ps", {"cpu": 2.0, "memory_mb": 200})
        ds.close()
        # fresh process view: replay from the table, no flush() needed
        ds2 = SqliteDataStore(path)
        assert len(ds2.samples("j1", "worker")) == 5
        assert ds2.samples("j2", "ps")[0]["memory_mb"] == 200
        assert sorted(ds2.jobs()) == ["j1", "j2"]
        ds2.close()

    def test_table_bounded_by_max_samples(self, tmp_path):
        from dlrover_wuqiong_tpu.brain.plugins import SqliteDataStore

        path = str(tmp_path / "brain.db")
        ds = SqliteDataStore(path, max_samples=10)
        for i in range(25):
            ds.append("j", "worker", {"cpu": float(i), "memory_mb": 1})
        ds.close()
        ds2 = SqliteDataStore(path, max_samples=10)
        got = ds2.samples("j", "worker")
        assert len(got) <= 10
        assert got[-1]["cpu"] == 24.0  # newest retained
        ds2.close()

    def test_service_selects_sqlite_by_extension(self, tmp_path):
        from dlrover_wuqiong_tpu.brain.plugins import SqliteDataStore
        from dlrover_wuqiong_tpu.brain.service import BrainService

        svc = BrainService(snapshot_path=str(tmp_path / "b.db"))
        assert isinstance(svc.store, SqliteDataStore)

    def test_replay_drops_schema_invalid_rows(self, tmp_path):
        """Rows that parse as JSON but are not valid samples must be
        dropped at replay, not left to crash optimize()."""
        import sqlite3

        from dlrover_wuqiong_tpu.brain.plugins import SqliteDataStore

        path = str(tmp_path / "brain.db")
        ds = SqliteDataStore(path)
        ds.append("j", "worker", {"cpu": 1.0, "memory_mb": 10})
        ds.close()
        db = sqlite3.connect(path)
        db.execute("INSERT INTO samples (job, node_type, sample)"
                   " VALUES ('j', 'worker', '\"garbage\"')")
        db.execute("INSERT INTO samples (job, node_type, sample)"
                   " VALUES ('j', 'worker', '{\"foo\": 1}')")
        db.commit()
        db.close()
        ds2 = SqliteDataStore(path)
        got = ds2.samples("j", "worker")
        assert len(got) == 1 and got[0]["cpu"] == 1.0, got
        ds2.close()

"""Optimizers (AGD/WSAM/8-bit Adam) + elastic data pipeline tests.

The AGD test checks step-by-step agreement against an independent numpy
transcription of the reference's update rule (atorch/optimizers/agd.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_wuqiong_tpu.data import (
    DevicePrefetcher,
    ElasticDataLoader,
    ElasticDistributedSampler,
)
from dlrover_wuqiong_tpu.optimizers import (
    adamw8bit,
    agd,
    dequantize_blockwise,
    make_wsam_train_step,
    quantize_blockwise,
)


def _agd_numpy_reference(w0, grads, lr=0.1, b1=0.9, b2=0.999, delta=1e-5,
                         wd=0.0):
    """Independent transcription of the reference AGD step (agd.py:120-148)."""
    w = w0.copy()
    m = np.zeros_like(w)
    v = np.zeros_like(w)
    traj = []
    for t, g in enumerate(grads, start=1):
        w = w * (1.0 - lr * wd)
        m_old = m.copy()
        m = b1 * m + (1 - b1) * g
        bc1 = 1 - b1 ** t
        bc1_old = 1 - b1 ** (t - 1)
        bc2 = 1 - b2 ** t
        if t == 1:
            d = m / bc1
        else:
            d = m / bc1 - m_old / bc1_old
        v = b2 * v + (1 - b2) * d * d
        den = np.maximum(np.sqrt(v), delta * np.sqrt(bc2))
        lr_adj = lr * np.sqrt(bc2) / bc1
        w = w - lr_adj * (m / den)
        traj.append(w.copy())
    return traj


class TestAGD:
    def test_matches_reference_math(self):
        rng = np.random.RandomState(0)
        w0 = rng.randn(5).astype(np.float32)
        grads = [rng.randn(5).astype(np.float32) for _ in range(6)]

        opt = agd(learning_rate=0.1)
        params = {"w": jnp.asarray(w0)}
        state = opt.init(params)
        got = []
        for g in grads:
            updates, state = opt.update({"w": jnp.asarray(g)}, state, params)
            params = optax.apply_updates(params, updates)
            got.append(np.asarray(params["w"]))
        want = _agd_numpy_reference(w0, grads, lr=0.1)
        for a, b in zip(got, want):
            np.testing.assert_allclose(a, b, atol=1e-5)

    def test_weight_decay_decoupled(self):
        w0 = np.ones(3, np.float32)
        grads = [np.zeros(3, np.float32)] * 3
        opt = agd(learning_rate=0.1, weight_decay=0.5)
        params = {"w": jnp.asarray(w0)}
        state = opt.init(params)
        for g in grads:
            updates, state = opt.update({"w": jnp.asarray(g)}, state, params)
            params = optax.apply_updates(params, updates)
        want = _agd_numpy_reference(w0, grads, lr=0.1, wd=0.5)[-1]
        np.testing.assert_allclose(np.asarray(params["w"]), want, atol=1e-5)

    def test_converges_on_quadratic(self):
        target = jnp.asarray([3.0, -2.0, 0.5])

        def loss(p):
            return jnp.sum((p["w"] - target) ** 2)

        opt = agd(learning_rate=0.05)
        params = {"w": jnp.zeros(3)}
        state = opt.init(params)
        for _ in range(300):
            g = jax.grad(loss)(params)
            updates, state = opt.update(g, state, params)
            params = optax.apply_updates(params, updates)
        assert float(loss(params)) < 1e-3


class TestWSAM:
    def test_decoupled_step_reduces_loss(self):
        target = jnp.asarray([1.0, -1.0])

        def loss(p, batch):
            return jnp.sum((p["w"] - target) ** 2) + 0.0 * batch.sum()

        opt = optax.sgd(0.1)
        # note: SAM's ascent perturbation floors the loss near rho^2
        step = make_wsam_train_step(loss, opt, learning_rate=0.1, rho=0.01)
        params = {"w": jnp.zeros(2)}
        carry = (params, opt.init(params))
        batch = jnp.zeros(1)
        losses = []
        for _ in range(50):
            carry, lv = step(carry, batch)
            losses.append(float(lv))
        assert losses[-1] < 1e-3 < losses[0]

    def test_coupled_variant(self):
        def loss(p, batch):
            return jnp.sum(p["w"] ** 2) + 0.0 * batch.sum()

        opt = optax.sgd(0.1)
        step = make_wsam_train_step(loss, opt, learning_rate=0.1,
                                    decouple=False)
        carry = ({"w": jnp.ones(2)}, opt.init({"w": jnp.ones(2)}))
        for _ in range(30):
            carry, lv = step(carry, jnp.zeros(1))
        assert float(lv) < 1e-2


class TestAdam8bit:
    def test_quant_roundtrip(self):
        x = jnp.asarray(np.random.RandomState(0).randn(1000).astype(
            np.float32) * 5)
        q = quantize_blockwise(x)
        y = dequantize_blockwise(q)
        assert q.q.dtype == jnp.int8
        np.testing.assert_allclose(y, x, atol=float(jnp.abs(x).max()) / 100)

    def test_tracks_adamw_trajectory(self):
        target = jnp.asarray(np.random.RandomState(1).randn(64).astype(
            np.float32))

        def loss(p):
            return jnp.sum((p["w"] - target) ** 2)

        def run(opt):
            params = {"w": jnp.zeros(64)}
            state = opt.init(params)

            @jax.jit
            def step(params, state):
                g = jax.grad(loss)(params)
                updates, state = opt.update(g, state, params)
                return optax.apply_updates(params, updates), state

            for _ in range(100):
                params, state = step(params, state)
            return np.asarray(params["w"])

        w8 = run(adamw8bit(1e-2))
        w32 = run(optax.adamw(1e-2))
        np.testing.assert_allclose(w8, w32, atol=0.075)

    def test_state_is_int8(self):
        opt = adamw8bit(1e-3)
        state = opt.init({"w": jnp.zeros(500)})
        q = jax.tree.leaves(state[0].mu,
                            is_leaf=lambda x: hasattr(x, "q"))[0]
        assert q.q.dtype == jnp.int8


class TestElasticSampler:
    def test_rank_partition_complete_disjoint(self):
        got = []
        for r in range(4):
            s = ElasticDistributedSampler(100, num_replicas=4, rank=r,
                                          shuffle=True, seed=7)
            got.append(list(s))
        all_idx = sorted(i for part in got for i in part)
        assert all_idx == list(range(100))

    def test_resume_mid_epoch(self):
        s = ElasticDistributedSampler(32, num_replicas=2, rank=0,
                                      shuffle=False)
        it = iter(s)
        consumed = [next(it) for _ in range(4)]  # rank0 saw 0,2,4,6
        saved = s.state_dict()
        # restart with a DIFFERENT world size (elastic rescale 2 -> 4)
        done = saved["completed_num"]
        parts = []
        for r in range(4):
            s2 = ElasticDistributedSampler(32, num_replicas=4, rank=r,
                                           shuffle=False)
            s2.load_state_dict(saved)
            parts.append(list(s2))
        remaining = sorted(i for p in parts for i in p)
        assert remaining == list(range(done, 32))
        assert set(remaining).isdisjoint(consumed)

    def test_len_accounts_for_progress(self):
        s = ElasticDistributedSampler(100, num_replicas=4, rank=0,
                                      shuffle=False)
        assert len(s) == 25
        s.load_state_dict({"epoch": 0, "completed_num": 40})
        assert len(s) == 15


class TestLoaderAndPrefetch:
    def test_sampler_loader_batches(self):
        data = np.arange(64, dtype=np.int64)
        sampler = ElasticDistributedSampler(64, num_replicas=2, rank=0,
                                            shuffle=False)
        dl = ElasticDataLoader(lambda i: {"x": data[i]}, batch_size=4,
                               sampler=sampler)
        batches = list(dl)
        assert len(batches) == 8
        assert batches[0]["x"].shape == (4,)
        np.testing.assert_array_equal(batches[0]["x"], [0, 2, 4, 6])

    def test_loader_requires_exactly_one_source(self):
        with pytest.raises(ValueError):
            ElasticDataLoader(lambda i: i, 4)

    def test_prefetcher_preserves_order_and_errors(self):
        src = iter(range(10))
        pf = DevicePrefetcher(src, lambda x: x * 2, depth=2)
        assert list(pf) == [0, 2, 4, 6, 8, 10, 12, 14, 16, 18]

        def bad(x):
            raise RuntimeError("boom")

        pf2 = DevicePrefetcher(iter([1]), bad)
        with pytest.raises(RuntimeError, match="boom"):
            list(pf2)

    def test_with_state_snapshots_lag_prefetch(self):
        """Checkpointing the state attached to the consumed batch (not the
        live sampler) must not skip prefetched-but-unconsumed samples."""
        sampler = ElasticDistributedSampler(64, num_replicas=1, rank=0,
                                            shuffle=False)
        dl = ElasticDataLoader(lambda i: {"x": np.int64(i)}, batch_size=4,
                               sampler=sampler, with_state=True)
        pf = DevicePrefetcher(iter(dl), lambda b: b, depth=2)
        it = iter(pf)
        consumed = []
        state = None
        for _ in range(6):  # consume 24 samples; prefetcher is ~8 ahead
            batch, state = next(it)
            consumed.extend(batch["x"].tolist())
        assert sampler.completed_num > state["completed_num"] or \
            sampler.completed_num == 64
        # resume from the snapshot: continues at exactly consumed+1
        s2 = ElasticDistributedSampler(64, num_replicas=1, rank=0,
                                       shuffle=False)
        s2.load_state_dict(state)
        assert next(iter(s2)) == len(consumed)

    def test_batch_size_update_mid_epoch(self):
        """The master tuner adjusts batch size DURING iteration."""
        sampler = ElasticDistributedSampler(32, num_replicas=1, rank=0,
                                            shuffle=False)
        dl = ElasticDataLoader(lambda i: {"x": np.int64(i)}, batch_size=4,
                               sampler=sampler)
        it = iter(dl)
        assert next(it)["x"].shape == (4,)
        dl.update_batch_size(8)
        assert next(it)["x"].shape == (8,)

    def test_no_drop_last_pads_ranks_equally(self):
        """SPMD: every rank must yield the same sample count or collectives
        hang at epoch end."""
        counts = []
        for r in range(4):
            s = ElasticDistributedSampler(10, num_replicas=4, rank=r,
                                          shuffle=False, drop_last=False)
            counts.append(len(list(s)))
        assert len(set(counts)) == 1

    def test_client_reporting_counts_samples(self):
        """Shard completion is counted in samples, not batches."""
        class FakeClient:
            def __init__(self):
                self.reported = 0

            def fetch_sample_index(self):
                if self.reported >= 0 and not hasattr(self, "_it"):
                    self._it = iter(range(12))
                return next(self._it, None)

            def report_batch_done(self, n):
                self.reported += n

        fc = FakeClient()
        dl = ElasticDataLoader(lambda i: {"x": np.int64(i)}, batch_size=4,
                               sharding_client=fc)
        list(dl)
        assert fc.reported == 12

"""Observability tests: metric registry, Prometheus endpoint, collector,
step profiler wiring.

Mirrors reference `master/stats` tests + the xpu_timer Prometheus intent.
"""

import urllib.request

from dlrover_wuqiong_tpu.master.metrics import (
    JobMetricCollector,
    MetricRegistry,
    PrometheusExporter,
)
from dlrover_wuqiong_tpu.utils.profiler import StepProfiler


class TestMetricRegistry:
    def test_gauge_counter_histogram(self):
        reg = MetricRegistry()
        reg.gauge("g", 1.5, {"job": "j"})
        reg.inc("c", 2.0)
        reg.inc("c", 3.0)
        for v in (1.0, 2.0, 3.0, 4.0):
            reg.observe("h", v)
        assert reg.get_gauge("g", {"job": "j"}) == 1.5
        assert reg.get_counter("c") == 5.0
        text = reg.render()
        assert 'g{job="j"} 1.5' in text
        assert "c_total 5.0" in text
        assert "h_count 4" in text
        assert 'quantile="0.5"' in text

    def test_collector_surfaces(self):
        reg = MetricRegistry()
        col = JobMetricCollector("jobx", registry=reg)
        col.collect_global_step(42)
        col.collect_speed(1.25, tokens_per_sec=1e5)
        col.collect_node_resource(0, cpu=2.0, memory_mb=512)
        col.collect_ckpt_timing("blocking", 0.05)
        col.collect_node_event("relaunch")
        text = reg.render()
        assert 'dwt_job_global_step{job="jobx"} 42' in text
        assert "dwt_job_tokens_per_second" in text
        assert "dwt_node_memory_mb" in text
        assert "dwt_ckpt_seconds" in text
        assert "dwt_node_events_total" in text


class TestPrometheusExporter:
    def test_http_scrape(self):
        reg = MetricRegistry()
        reg.gauge("dwt_up", 1.0)
        exp = PrometheusExporter(port=0, registry=reg)
        exp.start()
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{exp.port}/metrics", timeout=5
            ).read().decode()
            assert "dwt_up 1.0" in body
        finally:
            exp.stop()


class TestStepProfiler:
    def test_step_timing_recorded(self):
        reg = MetricRegistry()
        prof = StepProfiler(registry=reg, job_name="p")
        for step in range(3):
            with prof.step(step):
                pass
        text = reg.render()
        assert "dwt_train_step_seconds" in text
        assert reg.get_gauge("dwt_train_last_step", {"job": "p"}) == 2

    def test_trace_window(self, tmp_path):
        # trace start/stop around the window without error (CPU backend)
        prof = StepProfiler(trace_dir=str(tmp_path), start_step=1,
                            end_step=2, registry=MetricRegistry())
        for step in range(4):
            with prof.step(step):
                pass
        prof.close()
        assert not prof._tracing

"""Observability tests: metric registry, Prometheus endpoint, collector,
step profiler wiring, agent resource monitor.

Mirrors reference `master/stats` tests + the xpu_timer Prometheus intent.
"""

import re
import sys
import types
import urllib.error
import urllib.request

import pytest

from dlrover_wuqiong_tpu.master.metrics import (
    JobMetricCollector,
    MetricRegistry,
    PrometheusExporter,
)
from dlrover_wuqiong_tpu.utils.profiler import StepProfiler


class TestMetricRegistry:
    def test_gauge_counter_histogram(self):
        reg = MetricRegistry()
        reg.gauge("g", 1.5, {"job": "j"})
        reg.inc("c", 2.0)
        reg.inc("c", 3.0)
        for v in (1.0, 2.0, 3.0, 4.0):
            reg.observe("h", v)
        assert reg.get_gauge("g", {"job": "j"}) == 1.5
        assert reg.get_counter("c") == 5.0
        text = reg.render()
        assert 'g{job="j"} 1.5' in text
        assert "c_total 5.0" in text
        assert "h_count 4" in text
        assert 'le="+Inf"' in text

    def test_label_value_escaping(self):
        # exposition format: backslash first, then quote, then newline —
        # a scraper must get one parseable line per series
        reg = MetricRegistry()
        reg.gauge("g", 1.0, {"path": 'C:\\tmp', "msg": 'say "hi"\nbye'})
        text = reg.render()
        assert 'path="C:\\\\tmp"' in text
        assert 'msg="say \\"hi\\"\\nbye"' in text
        line = [ln for ln in text.splitlines() if ln.startswith("g{")][0]
        assert "\n" not in line  # the newline is escaped, not emitted

    def test_counter_is_monotonic(self):
        reg = MetricRegistry()
        vals = []
        for _ in range(5):
            reg.inc("c", 1.0, {"job": "j"})
            vals.append(reg.get_counter("c", {"job": "j"}))
        assert vals == sorted(vals) and vals[-1] == 5.0
        # negative increments would break scrape-side rate(): the
        # registry exposes inc() only, so going down requires a caller
        # bug — pin that counters never render a lower value than before
        before = reg.render()
        reg.inc("c", 0.0, {"job": "j"})
        assert reg.get_counter("c", {"job": "j"}) == 5.0
        assert 'c_total{job="j"} 5.0' in before

    def test_histogram_buckets_cumulative_and_closed(self):
        reg = MetricRegistry()
        for v in (0.004, 0.004, 0.02, 0.2, 100.0):
            reg.observe("h", v, buckets=(0.005, 0.05, 0.5))
        text = reg.render()
        counts = [int(m) for m in re.findall(
            r'h_bucket\{le="[^"]*"\} (\d+)', text)]
        # one count per bound + the mandatory +Inf closure
        assert len(counts) == 4
        assert counts == sorted(counts), "buckets must be cumulative"
        assert counts == [2, 3, 4, 5]
        assert 'h_bucket{le="+Inf"} 5' in text
        assert "h_count 5" in text
        # le label values parse as floats (repr, not locale-formatted)
        for le in re.findall(r'h_bucket\{le="([^"]*)"\}', text):
            assert le == "+Inf" or float(le) > 0

    def test_collector_surfaces(self):
        reg = MetricRegistry()
        col = JobMetricCollector("jobx", registry=reg)
        col.collect_global_step(42)
        col.collect_speed(1.25, tokens_per_sec=1e5)
        col.collect_node_resource(0, cpu=2.0, memory_mb=512)
        col.collect_ckpt_timing("blocking", 0.05)
        col.collect_node_event("relaunch")
        text = reg.render()
        assert 'dwt_job_global_step{job="jobx"} 42' in text
        assert "dwt_job_tokens_per_second" in text
        assert "dwt_node_memory_mb" in text
        assert "dwt_ckpt_seconds" in text
        assert "dwt_node_events_total" in text


class TestPrometheusExporter:
    def test_http_scrape(self):
        reg = MetricRegistry()
        reg.gauge("dwt_up", 1.0)
        exp = PrometheusExporter(port=0, registry=reg)
        exp.start()
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{exp.port}/metrics", timeout=5
            ).read().decode()
            assert "dwt_up 1.0" in body
        finally:
            exp.stop()

    def test_scrape_carries_escaped_labels_and_types(self):
        reg = MetricRegistry()
        reg.gauge("dwt_g", 2.0, {"node": 'a"b'})
        reg.inc("dwt_c", 3.0)
        reg.observe("dwt_h", 0.01)
        exp = PrometheusExporter(port=0, registry=reg)
        exp.start()
        try:
            resp = urllib.request.urlopen(
                f"http://127.0.0.1:{exp.port}/metrics", timeout=5)
            assert resp.headers["Content-Type"].startswith("text/plain")
            body = resp.read().decode()
            assert 'dwt_g{node="a\\"b"} 2.0' in body
            assert "# TYPE dwt_c counter" in body
            assert "dwt_c_total 3.0" in body
            assert "# TYPE dwt_h histogram" in body
            assert 'dwt_h_bucket{le="+Inf"} 1' in body
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{exp.port}/nope", timeout=5)
            assert ei.value.code == 404
        finally:
            exp.stop()


class TestStepProfiler:
    def test_step_timing_recorded(self):
        reg = MetricRegistry()
        prof = StepProfiler(registry=reg, job_name="p")
        for step in range(3):
            with prof.step(step):
                pass
        text = reg.render()
        assert "dwt_train_step_seconds" in text
        assert reg.get_gauge("dwt_train_last_step", {"job": "p"}) == 2

    def test_trace_window(self, tmp_path):
        # trace start/stop around the window without error (CPU backend)
        prof = StepProfiler(trace_dir=str(tmp_path), start_step=1,
                            end_step=2, registry=MetricRegistry())
        for step in range(4):
            with prof.step(step):
                pass
        prof.close()
        assert not prof._tracing


class TestResourceMonitorPriming:
    """agent/monitor.py: psutil cpu_percent needs a primed baseline."""

    @pytest.fixture()
    def fake_psutil(self, monkeypatch):
        from dlrover_wuqiong_tpu.agent import monitor as mon

        calls = {"created": 0, "cpu": 0}

        class FakeProcess:
            def __init__(self, pid=None):
                import os
                calls["created"] += 1
                self.pid = pid if pid is not None else os.getpid()
                self._primed = False

            def cpu_percent(self, interval=None):
                calls["cpu"] += 1
                # real psutil semantics: no baseline on the first call
                if not self._primed:
                    self._primed = True
                    return 0.0
                return 37.5

            def memory_info(self):
                return types.SimpleNamespace(rss=256 << 20)

        fake = types.ModuleType("psutil")
        fake.Process = FakeProcess
        monkeypatch.setitem(sys.modules, "psutil", fake)
        monkeypatch.setattr(mon, "_PROC", None)
        return mon, calls, FakeProcess

    def test_first_report_is_primed(self, fake_psutil):
        mon, calls, _ = fake_psutil
        stats = mon.get_process_resource()
        # without priming this would be the 0.0 baseline sample — the
        # regression the cached-Process fix exists for
        assert stats["cpu_percent"] == 37.5
        assert stats["memory_mb"] == 256.0
        assert calls == {"created": 1, "cpu": 2}  # prime + measure

    def test_process_object_is_reused(self, fake_psutil):
        mon, calls, _ = fake_psutil
        mon.get_process_resource()
        mon.get_process_resource()
        assert calls["created"] == 1
        assert calls["cpu"] == 3  # prime once, then one per report

    def test_reprime_after_pid_change(self, fake_psutil):
        mon, calls, FakeProcess = fake_psutil
        mon.get_process_resource()
        # simulate a spawned child inheriting the module global: the
        # cached Process carries the PARENT's pid and baseline
        mon._PROC = FakeProcess(pid=-1)
        stats = mon.get_process_resource()
        assert stats["cpu_percent"] == 37.5  # re-primed, not 0.0 baseline
        assert mon._PROC.pid != -1

    def test_no_psutil_falls_back(self, monkeypatch):
        from dlrover_wuqiong_tpu.agent import monitor as mon

        monkeypatch.setattr(mon, "_PROC", None)
        import builtins

        real_import = builtins.__import__

        def no_psutil(name, *a, **k):
            if name == "psutil":
                raise ImportError("nope")
            return real_import(name, *a, **k)

        monkeypatch.setattr(builtins, "__import__", no_psutil)
        stats = mon.get_process_resource()
        assert stats["cpu_percent"] == 0.0
        assert stats["memory_mb"] > 0.0  # resource.getrusage fallback

"""Fused multi-step dispatch (ISSUE 3 tentpole) — correctness contract.

The fused K-step driver (trainer/train_step.py) must be a pure dispatch
optimization: same math as K=1 (exact-resume equivalence), same donation
semantics across the scan carry, boundary checkpoints restore
bit-identically, and the auto-tune policy respects the hook cadences.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_wuqiong_tpu.auto.accelerate import auto_accelerate
from dlrover_wuqiong_tpu.data.elastic_dataset import (
    FusedBatchStager,
    stack_batches,
)
from dlrover_wuqiong_tpu.models.gpt import GPT, GPTConfig
from dlrover_wuqiong_tpu.trainer.train_step import auto_fused_steps

VOCAB = 512
SEQ = 32


def _model():
    return GPT(dataclasses.replace(GPTConfig.nano(), dtype=jnp.float32,
                                   use_flash_attention=False, remat=False))


def _res(**kw):
    import optax

    return auto_accelerate(_model(), optimizer=optax.adam(1e-2),
                           strategy=[("fsdp", {})], **kw)


def _host_batch(step, batch=8, accum=0):
    rng = np.random.default_rng(step)
    shape = (accum, batch, SEQ + 1) if accum else (batch, SEQ + 1)
    x = rng.integers(0, VOCAB, shape, dtype=np.int32)
    return {"input_ids": x[..., :-1], "labels": x[..., 1:]}


def _tree_equal(a, b):
    return all(bool(jnp.all(x == y))
               for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))


class TestFusedEquivalence:
    def test_k8_matches_k1_exactly(self):
        """8 unfused steps and one K=8 fusion over the SAME batches land
        on the same params AND opt state — the fused driver is a dispatch
        optimization, not a different training algorithm."""
        res = _res()
        hbs = [_host_batch(i) for i in range(8)]

        st1 = jax.tree.map(jnp.copy, res.state)
        for hb in hbs:
            st1, m1 = res.train_step(st1, res.place_batch(dict(hb)))

        fused = res.fused_train_step(8)
        fb = res.place_fused_batch(stack_batches(hbs))
        st8, m8 = fused(jax.tree.map(jnp.copy, res.state), fb)

        assert int(st1.step) == int(st8.step) == 8
        assert _tree_equal(st1.params, st8.params)
        assert _tree_equal(st1.opt_state, st8.opt_state)
        # per-step metrics accumulated on device: one readback, K values
        assert m8["losses"].shape == (8,)
        assert float(m8["losses"][-1]) == float(m8["loss"])
        assert float(m1["loss"]) == float(m8["loss"])

    def test_fused_composes_with_grad_accum(self):
        """K-step fusion over microbatch accumulation: batch leaves carry
        (K, accum, batch, seq) and both scan levels peel correctly."""
        res = _res(accum_steps=2)
        hbs = [_host_batch(i, accum=2) for i in range(4)]

        st1 = jax.tree.map(jnp.copy, res.state)
        for hb in hbs:
            st1, _ = res.train_step(st1, res.place_batch(dict(hb)))

        fused = res.fused_train_step(4)
        fb = res.place_fused_batch(stack_batches(hbs))
        st4, m4 = fused(jax.tree.map(jnp.copy, res.state), fb)
        assert int(st4.step) == 4
        assert m4["losses"].shape == (4,)
        assert _tree_equal(st1.params, st4.params)

    def test_boundary_checkpoint_restores_bit_identically(self, tmp_path):
        """A checkpoint taken at a fusion boundary round-trips exactly:
        restore-then-continue is indistinguishable from never stopping."""
        from dlrover_wuqiong_tpu.checkpoint.checkpointer import (
            FlashCheckpointer,
            StorageType,
        )
        from dlrover_wuqiong_tpu.checkpoint.ckpt_saver import (
            AsyncCheckpointSaver,
        )

        AsyncCheckpointSaver.reset()
        try:
            res = _res()
            fused = res.fused_train_step(4)
            hbs = [_host_batch(i) for i in range(8)]

            st = jax.tree.map(jnp.copy, res.state)
            st, _ = fused(st, res.place_fused_batch(stack_batches(hbs[:4])))
            ck = FlashCheckpointer(str(tmp_path), job_name="fusedt")
            ck.save_checkpoint(4, st, storage_type=StorageType.DISK)
            ck.wait_latest_checkpoint(120)
            restored = ck.load_checkpoint(jax.tree.map(jnp.copy, st))
            assert restored is not None
            assert _tree_equal(st, restored)

            # continue 4 more steps from the restored state vs straight
            # through: identical end states
            st_cont, _ = fused(restored,
                               res.place_fused_batch(
                                   stack_batches(hbs[4:])))
            st_straight, _ = fused(st, res.place_fused_batch(
                stack_batches(hbs[4:])))
            assert _tree_equal(st_cont.params, st_straight.params)
            assert _tree_equal(st_cont.opt_state, st_straight.opt_state)
            ck.close()
        finally:
            AsyncCheckpointSaver.reset()

    def test_scan_carry_donation_regression(self):
        """The fused driver DONATES its input state exactly like K=1:
        reusing the donated tree afterwards reads dead buffers (CLAUDE.md:
        copy first in tests)."""
        res = _res()
        fused = res.fused_train_step(2)
        donated = jax.tree.map(jnp.copy, res.state)
        _ = fused(donated, res.place_fused_batch(
            stack_batches([_host_batch(0), _host_batch(1)])))
        leaf = jax.tree.leaves(donated.params)[0]
        assert leaf.is_deleted()
        with pytest.raises(RuntimeError):
            _ = float(jnp.asarray(leaf).reshape(-1)[0])
        # res.state itself was never donated here (we passed a copy)
        assert not jax.tree.leaves(res.state.params)[0].is_deleted()

    def test_fused_key_differs_and_local_sgd_rejected(self):
        """K is part of the framework cache key (K changes the HLO), and
        the strategy matrix rejects fusion under local_sgd at resolve
        time, before any parameter init."""
        import optax

        res = _res()
        k1 = res._fused_key_fn(1)
        k8 = res._fused_key_fn(8)
        assert k1 == res.cache_key and k1 != k8

        # resolve-time rejection fires BEFORE any param init, so it does
        # not depend on local_sgd actually being buildable on this jax
        with pytest.raises(ValueError, match="local_sgd"):
            auto_accelerate(
                _model(), optimizer=optax.adam(1e-2),
                strategy=[("data_parallel", {"size": 2}),
                          ("local_sgd", {"sync_every": 4}), ("fsdp", {})],
                fused_steps=4)
        from dlrover_wuqiong_tpu.common.util import has_jax_shard_map

        if has_jax_shard_map():  # the lazily-built driver refuses too
            res_ls = auto_accelerate(
                _model(), optimizer=optax.adam(1e-2),
                strategy=[("data_parallel", {"size": 2}),
                          ("local_sgd", {"sync_every": 4}), ("fsdp", {})])
            with pytest.raises(ValueError, match="local_sgd"):
                res_ls.fused_train_step(4)


class TestAutoTunePolicy:
    def test_target_overhead_formula(self):
        # 6ms dispatch, 100ms step, 2% target -> ceil(6 / 2) = 3
        assert auto_fused_steps(0.1, overhead_s=0.006) == 3
        # already amortized: big step, tiny overhead -> K=1
        assert auto_fused_steps(1.0, overhead_s=0.0001) == 1
        # dispatch-bound nano regime hits the cap
        assert auto_fused_steps(0.0001, overhead_s=0.006, cap=64) == 64

    def test_cadence_clamp_keeps_ckpt_reachable(self):
        # K must divide the hook cadence so checkpoint steps stay exact
        assert auto_fused_steps(0.0001, overhead_s=0.006, cadence=10) == 10
        assert auto_fused_steps(0.0001, overhead_s=0.006, cap=8,
                                cadence=10) == 5
        assert auto_fused_steps(0.0001, overhead_s=0.006, cadence=7) == 7
        assert auto_fused_steps(0.0001, overhead_s=0.006, cap=6,
                                cadence=7) == 1

    def test_zero_step_time_capped(self):
        assert auto_fused_steps(0.0, overhead_s=0.006, cap=32) == 32


class TestFusedBatchStager:
    def test_alignment_and_tail(self):
        placed = []

        def place(b):
            placed.append(b)
            return b

        # resume at step 3 (mid-cycle, e.g. rollback), K=4, 13 steps total
        blocks = list(FusedBatchStager(
            lambda s: {"x": np.full((2,), s, np.int32)},
            place, fused_steps=4, start_step=3, max_steps=13,
            place_single=place))
        spans = [(s, k) for s, k, _ in blocks]
        # first block truncated to the next K-boundary, then full blocks,
        # then the tail
        assert spans == [(3, 1), (4, 4), (8, 4), (12, 1)]
        # stacked leaves carry the fused axis; k_eff=1 blocks stay flat
        assert blocks[1][2]["x"].shape == (4, 2)
        assert blocks[1][2]["x"][0, 0] == 4
        assert blocks[0][2]["x"].shape == (2,)

    def test_prefetch_thread_overlaps(self):
        import threading

        main = threading.get_ident()
        threads = set()

        def place(b):
            threads.add(threading.get_ident())
            return b

        out = list(FusedBatchStager(
            lambda s: {"x": np.zeros((1,), np.int32)}, place,
            fused_steps=2, start_step=0, max_steps=6))
        assert [(s, k) for s, k, _ in out] == [(0, 2), (2, 2), (4, 2)]
        assert threads and main not in threads  # placed off-thread

    def test_trainer_fused_matches_unfused(self, tmp_path):
        """End to end: the SAME data schedule through Trainer at K=1 and
        K=4 lands on the same final loss (hooks at boundaries only)."""
        from dlrover_wuqiong_tpu.checkpoint.ckpt_saver import (
            AsyncCheckpointSaver,
        )
        from dlrover_wuqiong_tpu.trainer.trainer import (
            Trainer,
            TrainingArgs,
        )

        def data(step):
            return _host_batch(step % 4)

        losses = {}
        for k in (1, 4):
            AsyncCheckpointSaver.reset()
            args = TrainingArgs(
                output_dir=str(tmp_path / f"k{k}"), max_steps=12,
                global_batch_size=8, seq_len=SEQ, learning_rate=1e-2,
                warmup_steps=2, logging_steps=4, save_steps=0,
                save_on_exit=False, strategy=[("fsdp", {})],
                fused_steps=k)
            tr = Trainer(_model(), args, data)
            out = tr.train()
            losses[k] = out["final_loss"]
            tr.ckpt.close()
        AsyncCheckpointSaver.reset()
        assert losses[1] == pytest.approx(losses[4], rel=1e-6)

"""Adaptive fault-tolerance policy engine (brain/policy.py).

The closed loop's pure parts, deterministically: the EWMA preemption
estimator on an injected clock, the four knob algorithms at pinned
regimes, offline-prior calibration (+ config overrides), and the engine's
hysteresis contract.  The live loop (master tick → journal → trainer
knob pickup) is covered by tests/test_master_restart.py and the
`chaos preempt-adaptive` drill.
"""

import dataclasses
import json
import math

import pytest

from dlrover_wuqiong_tpu.brain.plugins import get_algorithm
from dlrover_wuqiong_tpu.brain.policy import (
    PolicyConfig,
    PolicyEngine,
    PreemptionRateEstimator,
    load_prior,
)
from dlrover_wuqiong_tpu.common import messages as msg


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


# ---------------------------------------------------------------- estimator


class TestPreemptionRateEstimator:
    def test_no_events_means_infinite_mtbf(self):
        est = PreemptionRateEstimator(tau_s=60.0, clock=FakeClock())
        assert est.rate_per_s() == 0.0
        assert est.mtbf_s() == float("inf")

    def test_rate_converges_and_decays(self):
        clk = FakeClock()
        est = PreemptionRateEstimator(tau_s=60.0, clock=clk)
        # a burst of 3 failures in 2 seconds: weight ≈ 3, rate ≈ 3/tau
        for t in (0.0, 1.0, 2.0):
            clk.t = t
            est.record()
        rate = est.rate_per_s()
        assert rate == pytest.approx(3.0 / 60.0, rel=0.05)
        assert est.mtbf_s() == pytest.approx(20.0, rel=0.05)
        # one tau later the weight has decayed by e
        clk.t = 2.0 + 60.0
        assert est.rate_per_s() == pytest.approx(rate / math.e, rel=0.05)
        assert est.events == 3

    def test_decay_is_deterministic_on_injected_clock(self):
        def run():
            clk = FakeClock()
            est = PreemptionRateEstimator(tau_s=30.0, clock=clk)
            for t in (5.0, 6.0, 40.0):
                clk.t = t
                est.record()
            clk.t = 55.0
            return est.rate_per_s()

        assert run() == run()


# ----------------------------------------------------------- knob algorithms


class TestPolicyAlgorithms:
    CFG = PolicyConfig()  # ckpt_cost=0.1s, step=0.05s, bounds [5, 500]

    def _cfg(self, mtbf_s, replica_count=1):
        return self.CFG.algo_cfg(mtbf_s, replica_count)

    def test_registry_has_policy_algorithms(self):
        from dlrover_wuqiong_tpu.brain.plugins import algorithms

        assert set(algorithms()) >= {
            "optimize_job_ckpt_interval", "optimize_job_fused_steps",
            "optimize_job_replica_count", "optimize_job_recovery_route"}

    def test_young_daly_interval(self):
        f = get_algorithm("optimize_job_ckpt_interval")
        # sqrt(2 * 0.1 * 20) = 2s → 40 steps at 0.05s/step
        assert f([], [], self._cfg(20.0)) == 40
        # quiet regime clamps at the max bound (never unbounded)
        assert f([], [], self._cfg(float("inf"))) == 500
        # brutal regime clamps at the min bound (never thrashing saves)
        assert f([], [], self._cfg(1e-6)) == 5

    def test_fused_ladder_descends_with_mtbf(self):
        f = get_algorithm("optimize_job_fused_steps")
        assert f([], [], self._cfg(1e9)) == 4      # >= 600s floor
        assert f([], [], self._cfg(300.0)) == 2    # >= 120s floor
        assert f([], [], self._cfg(20.0)) == 1     # below every floor

    def test_replica_and_route(self):
        rep = get_algorithm("optimize_job_replica_count")
        route = get_algorithm("optimize_job_recovery_route")
        assert rep([], [], self._cfg(1e9)) == 1
        assert rep([], [], self._cfg(20.0)) == 2
        assert route([], [], self._cfg(1e9)) == ("cold", "shm")
        # hot regime with a ring: survivors can absorb the dead rank in
        # place from peer replicas — hotswap tops the route ladder
        assert route([], [], self._cfg(20.0, replica_count=2)) == \
            ("hotswap", "replica")
        # hot regime WITHOUT a ring: warm route but no replica tier
        # (nothing to hydrate from, so no in-place takeover either)
        assert route([], [], self._cfg(20.0, replica_count=1)) == \
            ("warm", "shm")


# -------------------------------------------------------------------- prior


class TestLoadPrior:
    def test_calibrates_step_and_ckpt_cost_from_curve(self, tmp_path):
        p = tmp_path / "preempt_table.json"
        p.write_text(json.dumps({
            "dt": 0.05,
            "rows": [{"interval": 10, "goodput": 0.78},
                     {"interval": 200, "goodput": 0.97}]}))
        prior = load_prior(str(p))
        assert prior["step_time_s"] == 0.05
        # C = dt·(g2-g1)/(1/I1 - 1/I2) = 0.05*0.19/0.095 = 0.1
        assert prior["ckpt_cost_s"] == pytest.approx(0.1, rel=1e-6)

    def test_missing_or_garbage_file_keeps_defaults(self, tmp_path):
        assert load_prior(str(tmp_path / "nope.json")) == {}
        p = tmp_path / "bad.json"
        p.write_text("not json")
        assert load_prior(str(p)) == {}

    def test_config_overrides_flow_into_engine(self, tmp_path):
        p = tmp_path / "prior.json"
        p.write_text(json.dumps({
            "dt": 0.05,
            "rows": [{"interval": 10, "goodput": 0.78},
                     {"interval": 200, "goodput": 0.97}],
            "config": {"tau_s": 20.0, "max_interval_steps": 200,
                       "fused_ladder": [[4, 300.0]],
                       "step_time_s": 99.0,       # must NOT apply
                       "no_such_knob": 7}}))      # must be ignored
        eng = PolicyEngine(prior_path=str(p), clock=FakeClock())
        assert eng.cfg.tau_s == 20.0
        assert eng.cfg.max_interval_steps == 200
        assert eng.cfg.fused_ladder == ((4, 300.0),)
        # calibration comes from the CURVE, not the config block
        assert eng.cfg.step_time_s == 0.05
        assert eng.cfg.ckpt_cost_s == pytest.approx(0.1, rel=1e-6)
        assert not hasattr(eng.cfg, "no_such_knob")


# -------------------------------------------------------------------- engine


class TestPolicyEngine:
    def test_quiet_then_burst_then_cooldown(self):
        clk = FakeClock()
        eng = PolicyEngine(PolicyConfig(tau_s=30.0), clock=clk)
        quiet = eng.propose()
        assert quiet.ckpt_interval_steps == 500
        assert quiet.fused_steps == 4
        assert quiet.replica_count == 1
        assert quiet.recovery_route == "cold"
        # the interval lands on a fusion-boundary multiple of K
        assert quiet.ckpt_interval_steps % quiet.fused_steps == 0
        # burst: 4 failures inside 3s collapses every knob
        for t in (10.0, 11.0, 12.0, 13.0):
            clk.t = t
            eng.record_failure()
        burst = eng.propose()
        assert burst.ckpt_interval_steps < quiet.ckpt_interval_steps
        assert burst.fused_steps == 1
        assert burst.replica_count == 2
        # ring exists in the burst regime → in-place takeover route
        assert burst.recovery_route == "hotswap"
        assert burst.preferred_tier == "replica"
        assert burst.preempt_rate_per_hr > quiet.preempt_rate_per_hr
        assert "mtbf=" in burst.reason
        # several tau later the regime cools back off
        clk.t = 13.0 + 10 * 30.0
        cooled = eng.propose()
        assert cooled.ckpt_interval_steps == 500
        assert cooled.fused_steps == 4

    def test_hysteresis_suppresses_noise(self):
        clk = FakeClock()
        eng = PolicyEngine(PolicyConfig(tau_s=30.0), clock=clk)
        first = eng.maybe_decide()
        assert first is not None
        # nothing changed: no decision thrash
        clk.t = 1.0
        assert eng.maybe_decide() is None
        # regime shift: a new decision fires
        for t in (2.0, 2.5, 3.0):
            clk.t = t
            eng.record_failure()
        second = eng.maybe_decide()
        assert second is not None
        assert second.fused_steps == 1

    def test_note_emitted_restores_baseline(self):
        """A restarted master replays journaled decisions through
        note_emitted: the hysteresis baseline must come back, so an
        identical proposal does not re-fire."""
        clk = FakeClock()
        eng = PolicyEngine(PolicyConfig(tau_s=30.0), clock=clk)
        d = eng.propose()
        eng2 = PolicyEngine(PolicyConfig(tau_s=30.0), clock=clk)
        eng2.note_emitted(d)
        assert eng2.maybe_decide() is None

    def test_observe_goodput_lands_in_reason(self):
        eng = PolicyEngine(PolicyConfig(), clock=FakeClock())
        eng.observe_goodput({"goodput_fraction": 0.875})
        assert "goodput=0.875" in eng.propose().reason


# ------------------------------------------------------------ message schema


class TestPolicyDecisionSchema:
    # ADD-ONLY (like the telemetry schemas, tests/test_telemetry.py):
    # trainers/agents/report tools key off these names and old journals
    # must replay into new masters — extend, never rename or remove.
    # Pin source of truth: analysis/schema.lock.json (graftlint schema
    # engine); the no-change-sentinel test below is the hand-pinned
    # canary.
    def test_decision_fields_add_only(self, schema_lock):
        locked = schema_lock["messages"]["PolicyDecision"]["fields"]
        names = {f.name for f in dataclasses.fields(msg.PolicyDecision)}
        missing = {f["name"] for f in locked} - names
        assert not missing, f"ADD-ONLY schema lost fields: {missing}"
        # every wire field carries a no-change sentinel default — the
        # codec drops unknown fields, so this is what makes old journals
        # replayable into new masters (schema-field-no-sentinel rule)
        assert all(f["sentinel"] for f in locked)
        assert "decision_id" in names   # hand-pinned canary

    def test_no_change_sentinels(self):
        d = msg.PolicyDecision()
        assert d.ckpt_interval_steps == 0   # 0 = leave cadence alone
        assert d.replica_count == -1        # -1 = leave ring alone
        assert d.fused_steps == 0           # 0 = leave K alone
        assert d.recovery_route == ""
        assert d.preferred_tier == ""

    def test_report_roundtrips_through_serializer(self):
        from dlrover_wuqiong_tpu.common import serialize

        d = msg.PolicyDecision(decision_id=3, ckpt_interval_steps=40,
                               replica_count=2, fused_steps=1,
                               recovery_route="warm",
                               preferred_tier="replica",
                               preempt_rate_per_hr=180.0, reason="burst",
                               issued_at=123.0)
        blob = serialize.dumps(msg.PolicyDecisionReport(node_id=7,
                                                        decision=d))
        back = serialize.loads(blob)
        assert back.decision == d
        assert back.node_id == 7

"""Diagnosis inference-chain tests.

Mirrors reference `dlrover/python/tests/test_diagnosis.py`: symptom →
cause refinement, straggler detection, OOM-precursor trend, and the
coupling of conclusions into the job manager's restart machinery.
"""

import json
import time

from dlrover_wuqiong_tpu.common import messages as msg
from dlrover_wuqiong_tpu.common.constants import NodeStatus, NodeType
from dlrover_wuqiong_tpu.diagnosis.manager import (
    CheckMemoryTrendOperator,
    CheckStragglerOperator,
    CheckTrainingHangOperator,
    DiagnosisDataManager,
    DiagnosisManager,
    InferenceChain,
    ResolveHangCauseOperator,
)
from dlrover_wuqiong_tpu.master.job_manager import JobManager


def _step(data, node, ts):
    data.store_report(msg.DiagnosisReport(node_id=node, payload_type="step",
                                          content="s", timestamp=ts))


def _resource(data, node, ts, mem):
    data.store_report(msg.DiagnosisReport(
        node_id=node, payload_type="resource",
        content=json.dumps({"memory_mb": mem}), timestamp=ts))


class TestHangChain:
    def test_hang_refined_to_culprit(self):
        data = DiagnosisDataManager()
        now = time.time()
        # node 0 stalled 100s before node 1; both silent past the timeout
        _step(data, 0, now - 200)
        _step(data, 1, now - 100)
        data.store_report(msg.DiagnosisReport(
            node_id=0, payload_type="stack", content="stuck in psum"))
        chain = InferenceChain([CheckTrainingHangOperator(timeout=50),
                                ResolveHangCauseOperator()])
        conclusions = chain.run(data)
        assert len(conclusions) == 1
        c = conclusions[0]
        assert c.name == "hang_culprit" and c.node_id == 0
        assert "stack available" in c.detail

    def test_no_hang_when_progressing(self):
        data = DiagnosisDataManager()
        _step(data, 0, time.time())
        chain = InferenceChain([CheckTrainingHangOperator(timeout=50),
                                ResolveHangCauseOperator()])
        assert chain.run(data) == []


class TestStraggler:
    def test_slow_node_flagged(self):
        data = DiagnosisDataManager()
        base = time.time() - 1000  # graftlint: disable=wall-clock-duration -- forging node-reported wall timestamps (DiagnosisReport)
        for i in range(10):
            _step(data, 0, base + i * 1.0)   # 1s cadence
            _step(data, 1, base + i * 1.1)
            _step(data, 2, base + i * 10.0)  # 10x slower
        out = CheckStragglerOperator(ratio=3.0).infer(data, [])
        assert [c.node_id for c in out] == [2]
        assert out[0].name == "straggler"

    def test_uniform_cadence_clean(self):
        data = DiagnosisDataManager()
        base = time.time() - 100  # graftlint: disable=wall-clock-duration -- forging node-reported wall timestamps (DiagnosisReport)
        for i in range(10):
            for n in range(3):
                _step(data, n, base + i * 1.0 + n * 0.01)
        assert CheckStragglerOperator().infer(data, []) == []


class TestMemoryTrend:
    def test_over_limit_and_trend(self):
        data = DiagnosisDataManager()
        now = time.time()
        # node 0 already over; node 1 trending 10MB/s toward 2000 limit
        _resource(data, 0, now, 2500)
        for i in range(5):
            _resource(data, 1, now - 50 + i * 10, 1500 + i * 100)
        op = CheckMemoryTrendOperator(memory_limit_mb=2000, horizon_s=600)
        out = {c.node_id: c.name for c in op.infer(data, [])}
        assert out[0] == "memory_over_limit"
        assert out[1] == "memory_trend"


class TestActionCoupling:
    def test_restart_flag_set_on_hang(self):
        jm = JobManager()
        node = jm.register_node(NodeType.WORKER, 0)
        node.update_status(NodeStatus.RUNNING)
        dm = DiagnosisManager(hang_timeout=1, job_manager=jm)
        _step(dm.data, 0, time.time() - 100)  # graftlint: disable=wall-clock-duration -- forging node-reported wall timestamps (DiagnosisReport)
        actions = dm.diagnose_once()
        assert any(a.action == "restart_worker" for a in actions)
        assert node.restart_training  # delivered via next heartbeat
        assert jm.collect_heartbeat(0) == "restart"

    def test_memory_over_limit_relaunches(self):
        jm = JobManager()
        node = jm.register_node(NodeType.WORKER, 0)
        node.update_status(NodeStatus.RUNNING)
        before_mem = node.config_resource.memory_mb = 1000
        dm = DiagnosisManager(hang_timeout=1e9, job_manager=jm)
        dm.chain.operators[2] = CheckMemoryTrendOperator(
            memory_limit_mb=2000)
        _resource(dm.data, 0, time.time(), 2500)
        dm.diagnose_once()
        # OOM path: old node released, replacement registered w/ more memory
        assert node.is_released
        assert any(n.id != 0 and n.config_resource.memory_mb > before_mem
                   for n in jm.all_nodes())

    def test_worker_polls_pending_action(self):
        dm = DiagnosisManager(hang_timeout=1)
        _step(dm.data, 0, time.time() - 100)  # graftlint: disable=wall-clock-duration -- forging node-reported wall timestamps (DiagnosisReport)
        dm.diagnose_once()
        act = dm.collect_report(msg.DiagnosisReport(
            node_id=0, payload_type="step", content="s",
            timestamp=time.time()))
        assert act.action == "restart_worker"


def _loss(data, node, step, loss, ts=None):
    data.store_report(msg.DiagnosisReport(
        node_id=node, payload_type="loss",
        content=json.dumps({"step": step, "loss": loss}),
        timestamp=ts or time.time()))


class TestLossSpike:
    """Loss-spike detection (parity atorch utils/loss_spike_utils.py):
    windowed robust statistics on reported losses; spike -> diagnosis
    conclusion -> rollback action (restart; auto-resume from the last
    committed checkpoint = pre-spike state)."""

    def _feed(self, dm, losses, node=0):
        for i, l in enumerate(losses):
            _loss(dm.data, node, i, l)

    def test_spike_triggers_rollback_and_restart(self):
        jm = JobManager()
        node = jm.register_node(NodeType.WORKER, 0)
        node.update_status(NodeStatus.RUNNING)
        dm = DiagnosisManager(hang_timeout=1e9, job_manager=jm)
        _step(dm.data, 0, time.time())  # alive — no hang noise
        self._feed(dm, [2.0 + 0.01 * (i % 5) for i in range(20)] + [9.5])
        actions = dm.diagnose_once()
        assert any(a.action == "rollback" and "loss_spike" in a.reason
                   for a in actions), actions
        assert node.restart_training  # rollback = restart + flash resume
        assert jm.collect_heartbeat(0) == "restart"

    def test_normal_noise_does_not_fire(self):
        dm = DiagnosisManager(hang_timeout=1e9)
        _step(dm.data, 0, time.time())
        # decreasing loss with ordinary noise, incl. a mild 20% bump
        losses = [3.0 - 0.05 * i for i in range(20)] + [2.4]
        self._feed(dm, losses)
        actions = dm.diagnose_once()
        assert not any(a.action == "rollback" for a in actions), actions

    def test_non_finite_loss_always_fires(self):
        dm = DiagnosisManager(hang_timeout=1e9)
        _step(dm.data, 0, time.time())
        self._feed(dm, [2.0, 1.9, float("nan")])
        actions = dm.diagnose_once()
        assert any(a.action == "rollback" for a in actions), actions

    def test_warmup_window_silent(self):
        dm = DiagnosisManager(hang_timeout=1e9)
        _step(dm.data, 0, time.time())
        self._feed(dm, [5.0, 100.0])  # too few points to judge
        actions = dm.diagnose_once()
        assert not any(a.action == "rollback" for a in actions), actions

    def test_rollback_carries_spike_step_to_heartbeat(self):
        """ADVICE r4: the rollback must target a PRE-spike checkpoint — the
        spike-onset step flows detector -> action -> node -> heartbeat."""
        jm = JobManager()
        node = jm.register_node(NodeType.WORKER, 0)
        node.update_status(NodeStatus.RUNNING)
        dm = DiagnosisManager(hang_timeout=1e9, job_manager=jm)
        _step(dm.data, 0, time.time())
        self._feed(dm, [2.0 + 0.01 * (i % 5) for i in range(20)] + [9.5])
        actions = dm.diagnose_once()
        spike = [a for a in actions if a.action == "rollback"]
        assert spike and spike[0].step == 20, spike  # onset = 21st sample
        assert node.rollback_before_step == 20
        action, rb = jm.collect_heartbeat_full(0)
        assert action == "restart" and rb == 20
        # one-shot: the ceiling clears after delivery
        assert jm.collect_heartbeat_full(0) == ("", -1)

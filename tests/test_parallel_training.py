"""Parallel-training tests on the virtual 8-device CPU mesh.

Covers: mesh planning, sharding rules, flash-attention numerics,
auto_accelerate end-to-end training (loss decreases) under several strategies
— the reference's auto_accelerate/strategy tests
(atorch/tests/common_tests) translated to GSPMD.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from version_gates import shard_index_set
from jax.sharding import PartitionSpec as P

from dlrover_wuqiong_tpu.auto.accelerate import (
    auto_accelerate,
    resolve_strategy,
)
from dlrover_wuqiong_tpu.models.gpt import GPT, GPTConfig
from dlrover_wuqiong_tpu.models.llama import Llama, LlamaConfig
from dlrover_wuqiong_tpu.ops.flash_attention import (
    _attention_reference,
    flash_attention,
    mha,
)
from dlrover_wuqiong_tpu.parallel.mesh import (
    MeshPlan,
    auto_plan,
    build_mesh,
    hybrid_slice_plan,
)
from dlrover_wuqiong_tpu.parallel.sharding import (
    ShardingPlanner,
    TRANSFORMER_RULES,
    spec_for_path,
)


class TestMeshPlan:
    def test_build_mesh_8(self):
        plan = MeshPlan(dp=2, fsdp=2, tp=2)
        mesh = build_mesh(plan)
        assert mesh.shape["dp"] == 2 and mesh.shape["tp"] == 2
        assert mesh.devices.size == 8

    def test_validate_rejects_mismatch(self):
        with pytest.raises(ValueError):
            build_mesh(MeshPlan(dp=3))

    def test_auto_plan_small_model(self):
        plan = auto_plan(8, num_params=10_000_000)
        assert plan.num_devices == 8
        assert plan.tp == 1  # no TP for small models

    def test_auto_plan_huge_model_uses_tp(self):
        # 70B fits on 64 v5p-class chips (95 GiB HBM) and engages TP
        plan = auto_plan(64, num_params=70_000_000_000,
                         hbm_per_device=95 << 30)
        assert plan.tp > 1

    def test_auto_plan_rejects_state_that_cannot_fit(self):
        # 70B state (~980 GB) cannot fit 8 x 16 GiB chips: planner must say
        # so instead of emitting a plan that OOMs at runtime
        with pytest.raises(ValueError, match="does not fit"):
            auto_plan(8, num_params=70_000_000_000)

    def test_hybrid_slice_plan(self):
        plan = hybrid_slice_plan(num_slices=2, devices_per_slice=4, tp=2)
        assert plan.dp == 2 and plan.fsdp == 2 and plan.tp == 2


class TestShardingRules:
    def test_attention_specs(self):
        assert spec_for_path("h_0/attn/c_attn/kernel",
                             TRANSFORMER_RULES) == P("fsdp", "tp")
        assert spec_for_path("h_0/attn/c_proj/kernel",
                             TRANSFORMER_RULES) == P("tp", "fsdp")
        assert spec_for_path("layers_3/attention/q_proj/kernel",
                             TRANSFORMER_RULES) == P("fsdp", "tp")
        assert spec_for_path("wte/embedding",
                             TRANSFORMER_RULES) == P("tp", "fsdp")
        assert spec_for_path("h_0/ln_1/scale", TRANSFORMER_RULES) == P()

    def test_planner_shards_params(self):
        mesh = build_mesh(MeshPlan(fsdp=4, tp=2))
        model = GPT(GPTConfig.nano())
        params = model.init_params(jax.random.PRNGKey(0))
        planner = ShardingPlanner(mesh)
        sharded = planner.shard_params(params)
        k = sharded["h_0"]["attn"]["c_attn"]["kernel"]
        # sharded over both fsdp and tp → 8 distinct shards
        assert len(shard_index_set(k)) == 8
        # layernorm scales replicated
        ln = sharded["h_0"]["ln_1"]["scale"]
        assert len(shard_index_set(ln)) == 1


class TestFlashAttention:
    def test_matches_reference(self):
        key = jax.random.PRNGKey(1)
        q, k, v = (jax.random.normal(k_, (2, 4, 64, 32), jnp.float32)
                   for k_ in jax.random.split(key, 3))
        out = flash_attention(q, k, v, True, None)
        ref = _attention_reference(q, k, v, True, 1.0 / np.sqrt(32))
        np.testing.assert_allclose(out, ref, atol=2e-5)

    def test_grad_matches_reference(self):
        key = jax.random.PRNGKey(2)
        q, k, v = (jax.random.normal(k_, (1, 2, 32, 16), jnp.float32)
                   for k_ in jax.random.split(key, 3))

        def f_fa(q, k, v):
            return flash_attention(q, k, v, True, None).sum()

        def f_ref(q, k, v):
            return _attention_reference(q, k, v, True,
                                        1.0 / np.sqrt(16)).sum()

        g_fa = jax.grad(f_fa, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_fa, g_ref):
            np.testing.assert_allclose(a, b, atol=2e-4)

    def test_pallas_kernel_interpret_mode(self):
        """Run the actual pallas kernel in interpreter mode on CPU."""
        from dlrover_wuqiong_tpu.ops.flash_attention import (
            _fa_forward_pallas,
        )
        key = jax.random.PRNGKey(3)
        q, k, v = (jax.random.normal(k_, (2, 128, 128), jnp.float32)
                   for k_ in jax.random.split(key, 3))
        o, _ = _fa_forward_pallas(q, k, v, causal=True,
                                     sm_scale=1.0 / np.sqrt(128),
                                     block_q=64, block_k=64, interpret=True)
        ref = _attention_reference(q[None], k[None], v[None], True,
                                   1.0 / np.sqrt(128))[0]
        np.testing.assert_allclose(o, ref, atol=2e-5)

    def test_pallas_kernel_causal_sq_ne_sk(self):
        """Bottom-right-aligned causal mask when sq != sk (decode append)."""
        from dlrover_wuqiong_tpu.ops.flash_attention import (
            _fa_forward_pallas,
        )
        key = jax.random.PRNGKey(4)
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(kq, (2, 64, 128), jnp.float32)
        k = jax.random.normal(kk, (2, 256, 128), jnp.float32)
        v = jax.random.normal(kv, (2, 256, 128), jnp.float32)
        o, _ = _fa_forward_pallas(q, k, v, causal=True,
                                     sm_scale=1.0 / np.sqrt(128),
                                     block_q=64, block_k=64, interpret=True)
        ref = _attention_reference(q[None], k[None], v[None], True,
                                   1.0 / np.sqrt(128))[0]
        np.testing.assert_allclose(o, ref, atol=2e-5)

    def test_pallas_kernel_padded_head_dim(self):
        """d=64 (GPT-2 heads) rides the kernel via zero-padding to 128."""
        from dlrover_wuqiong_tpu.ops.flash_attention import (
            _fa_forward_pallas,
            _pad_head_dim,
        )
        key = jax.random.PRNGKey(5)
        q, k, v = (jax.random.normal(k_, (2, 128, 64), jnp.float32)
                   for k_ in jax.random.split(key, 3))
        qp, kp, vp = (_pad_head_dim(x, 128) for x in (q, k, v))
        o, _ = _fa_forward_pallas(qp, kp, vp, causal=True,
                                     sm_scale=1.0 / np.sqrt(64),
                                     block_q=64, block_k=64, interpret=True)
        ref = _attention_reference(q[None], k[None], v[None], True,
                                   1.0 / np.sqrt(64))[0]
        np.testing.assert_allclose(o[:, :, :64], ref, atol=2e-5)

    @pytest.mark.parametrize("causal,sq,sk", [(True, 128, 128),
                                              (False, 128, 128),
                                              (True, 64, 256)])
    def test_pallas_backward_kernel(self, causal, sq, sk):
        """dq/dk/dv kernels vs autodiff-of-reference, interpret mode."""
        from dlrover_wuqiong_tpu.ops.flash_attention import (
            _fa_backward_pallas,
            _fa_forward_pallas,
        )
        key = jax.random.PRNGKey(6)
        kq, kk, kv, kg = jax.random.split(key, 4)
        scale = 1.0 / np.sqrt(128)
        q = jax.random.normal(kq, (2, sq, 128), jnp.float32)
        k = jax.random.normal(kk, (2, sk, 128), jnp.float32)
        v = jax.random.normal(kv, (2, sk, 128), jnp.float32)
        g = jax.random.normal(kg, (2, sq, 128), jnp.float32)

        o, lse = _fa_forward_pallas(q, k, v, causal, scale, 64, 64,
                                    interpret=True)
        dq, dk, dv = _fa_backward_pallas(q, k, v, o, lse, g, causal, scale,
                                         64, 64, interpret=True)

        def ref_loss(q, k, v):
            out = _attention_reference(q[None], k[None], v[None], causal,
                                       scale)[0]
            return (out * g).sum()

        rq, rk, rv = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_allclose(dq, rq, atol=5e-4)
        np.testing.assert_allclose(dk, rk, atol=5e-4)
        np.testing.assert_allclose(dv, rv, atol=5e-4)


def _toy_batch(key, accum, batch, seq, vocab):
    data = jax.random.randint(key, (accum, batch, seq + 1), 0, vocab) \
        if accum > 1 else jax.random.randint(key, (batch, seq + 1), 0, vocab)
    return {"input_ids": data[..., :-1], "labels": data[..., 1:]}


class TestAutoAccelerate:
    def _train(self, strategy, model=None, accum=1, steps=8):
        model = model or GPT(GPTConfig.nano())
        res = auto_accelerate(
            model, optimizer=optax.adamw(1e-2), strategy=strategy,
            accum_steps=accum)
        key = jax.random.PRNGKey(0)
        batch = _toy_batch(key, accum, 8, 32, 16)
        batch = res.place_batch(batch)
        state = res.state
        losses = []
        for _ in range(steps):
            state, metrics = res.train_step(state, batch)
            losses.append(float(metrics["loss"]))
        return losses

    def test_fsdp_training_loss_decreases(self):
        losses = self._train([("fsdp", {})])
        assert losses[-1] < losses[0]

    def test_tp_fsdp_training(self):
        losses = self._train([("tensor_parallel", {"size": 2}),
                              ("fsdp", {})])
        assert losses[-1] < losses[0]

    def test_dp_tp_matches_fsdp_numerics(self):
        l1 = self._train([("fsdp", {})], steps=4)
        l2 = self._train([("tensor_parallel", {"size": 4}),
                          ("data_parallel", {})], steps=4)
        # the model computes in bf16 (GPTConfig.nano default) and tp=4
        # splits the contraction axis: per-shard partial sums round at
        # shard boundaries before the cross-shard reduce, so the two
        # shardings are different bf16 rounding schedules, and adamw's
        # rsqrt amplifies the gap step over step (measured 3.7% at step
        # 1 → 10.3% at step 4 on jax 0.4.37 XLA:CPU).  rtol covers that
        # compounding; the parity claim that survives bf16 is that both
        # runs optimize the same trajectory shape.
        np.testing.assert_allclose(l1, l2, rtol=0.15)
        assert l1[-1] < l1[0] and l2[-1] < l2[0]
        assert all(b < a for a, b in zip(l1, l1[1:]))  # monotone descent

    def test_grad_accum(self):
        losses = self._train([("fsdp", {}), ("grad_accum", {"steps": 2})],
                             accum=2)
        assert losses[-1] < losses[0]

    def test_strategy_flags_reach_model_config(self):
        model = GPT(GPTConfig.nano())
        assert model.config.dtype == jnp.bfloat16
        res = auto_accelerate(
            model, optimizer=optax.adamw(1e-2),
            strategy=[("fsdp", {}), ("half", {"enabled": False}),
                      ("checkpoint", {"enabled": False})])
        # the result carries the rebuilt model with the overridden flags
        assert res.model.config.dtype == jnp.float32
        assert res.model.config.remat is False

    def test_adafactor_opt_state_shards(self):
        """Factored states mirror the param treedef with reduced leaf shapes:
        they must NOT inherit param shardings (regression test)."""
        model = GPT(GPTConfig.nano())
        res = auto_accelerate(
            model, optimizer=optax.adafactor(1e-3),
            strategy=[("fsdp", {}), ("tensor_parallel", {"size": 2})])
        batch = _toy_batch(jax.random.PRNGKey(0), 1, 4, 32, 16)
        state, m = res.train_step(res.state, res.place_batch(batch))
        assert np.isfinite(float(m["loss"]))

    def test_unknown_strategy_rejected(self):
        with pytest.raises(ValueError, match="unknown optimization strategy"):
            auto_accelerate(GPT(GPTConfig.nano()),
                            strategy=[("fsdppp", {})])

    def test_llama_model_trains(self):
        model = Llama(LlamaConfig.nano())
        res = auto_accelerate(model, optimizer=optax.adamw(1e-2),
                              strategy=[("fsdp", {}),
                                        ("tensor_parallel", {"size": 2})])
        key = jax.random.PRNGKey(1)
        batch = _toy_batch(key, 1, 4, 64, 16)
        batch = res.place_batch(batch)
        state = res.state
        losses = []
        for _ in range(6):
            state, m = res.train_step(state, batch)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0]

    def test_unknown_strategy_raises(self):
        with pytest.raises(ValueError, match="unknown optimization"):
            resolve_strategy([("warp_drive", {})], 8)


class TestShardedByConstructionInit:
    """Sharded-by-construction init (parity: reference meta-device init,
    atorch/utils/meta_model_utils.py + fsdp_init_util.py): auto_accelerate
    must never materialize the full unsharded train-state tree — params and
    optimizer moments are jit-initialized straight into their shards."""

    def _per_device_bytes(self, state):
        per_dev = {}
        for leaf in jax.tree.leaves(state):
            for sh in leaf.addressable_shards:
                per_dev[sh.device] = per_dev.get(sh.device, 0) + \
                    sh.data.nbytes
        return per_dev

    def test_fsdp_state_is_partitioned_not_replicated(self):
        cfg = GPTConfig(vocab_size=2048, n_layer=2, n_head=4, n_embd=256,
                        block_size=128)
        res = auto_accelerate(GPT(cfg), optimizer=optax.adamw(1e-3),
                              strategy=[("fsdp", {})])
        total = sum(leaf.nbytes for leaf in jax.tree.leaves(res.state))
        per_dev = self._per_device_bytes(res.state)
        assert len(per_dev) == 8
        # fully replicated would be ~total per device; sharded-by-
        # construction must land near total/8 (+ replicated scalars/biases)
        worst = max(per_dev.values())
        assert worst < total * 0.25, (
            f"device holds {worst} of {total} bytes — state is (near-)"
            "replicated, not sharded by construction")
        # optimizer moments follow the param shardings
        mu = res.state.opt_state[0].mu["wte"]["embedding"]
        p = res.state.params["wte"]["embedding"]
        assert mu.sharding == p.sharding
        assert not p.sharding.is_fully_replicated

    def test_jit_init_matches_eager_init(self):
        cfg = GPTConfig.nano()
        model = GPT(cfg)
        rng = jax.random.PRNGKey(7)
        res = auto_accelerate(model, optimizer=optax.sgd(1e-2),
                              strategy=[("fsdp", {})], rng=rng)
        eager = model.init_params(rng)
        # same PRNG stream (partitionable threefry), tiny tolerance for
        # jit-fusion rounding (~3e-8 measured on the initializer scaling)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
            eager, dict(res.state.params))

    def test_tp_fsdp_composed_init_shards_both_axes(self):
        cfg = GPTConfig(vocab_size=1024, n_layer=2, n_head=4, n_embd=256,
                        block_size=128)
        res = auto_accelerate(
            GPT(cfg), optimizer=optax.adamw(1e-3),
            strategy=[("tensor_parallel", {"size": 2}), ("fsdp", {})])
        p = res.state.params["h_0"]["mlp"]["c_fc"]["kernel"]
        assert not p.sharding.is_fully_replicated
        total = sum(leaf.nbytes for leaf in jax.tree.leaves(res.state))
        worst = max(self._per_device_bytes(res.state).values())
        assert worst < total * 0.3


class TestSelectiveRematPolicies:
    """("checkpoint", {policy}) strategy — selective activation
    checkpointing + host offload (parity: reference
    selective_offloading_checkpoint.py / activation_checkpointing.py).
    Every policy must train to the SAME loss and gradients; only what is
    saved vs recomputed vs offloaded differs."""

    POLICIES = ["full", "dots", "offload_dots", "save_names",
                "offload_names"]

    def _loss_and_grads(self, strategy):
        cfg = GPTConfig.nano()
        model = GPT(cfg)
        rng = jax.random.PRNGKey(3)
        res = auto_accelerate(model, optimizer=optax.sgd(1e-2),
                              strategy=strategy, rng=rng)
        data = jax.random.randint(jax.random.PRNGKey(1), (8, 33), 0,
                                  cfg.vocab_size)
        batch = res.place_batch({"input_ids": data[:, :-1],
                                 "labels": data[:, 1:]})
        # jit the whole loss+grad: eager op-by-op dispatch of the sharded
        # remat'd model issues collectives one at a time, which can abort
        # XLA:CPU's collective rendezvous under pytest process state
        loss, grads = jax.jit(
            lambda p: (res.loss_fn(p, batch),
                       jax.grad(lambda q: res.loss_fn(q, batch))(p)))(
            dict(res.state.params))
        return float(loss), jax.device_get(grads)

    # tier-2: ~34s three-policy gradient sweep; policy plumbing is
    # tier-1 via test_policy_threads_into_model_config and remat
    # correctness via the jaxpr-engine remat-noop gate
    @pytest.mark.slow
    def test_policies_match_no_remat_gradients(self):
        base_loss, base_grads = self._loss_and_grads(
            [("fsdp", {}), ("checkpoint", {"enabled": False})])
        for policy in self.POLICIES:
            loss, grads = self._loss_and_grads(
                [("fsdp", {}), ("checkpoint", {"policy": policy})])
            assert abs(loss - base_loss) < 1e-4, policy
            # bf16 compute: recompute-vs-saved changes fusion order, so
            # grads wobble at bf16 ulp scale (~1e-3 abs at these magnitudes)
            jax.tree.map(
                lambda a, b: np.testing.assert_allclose(
                    np.asarray(a, np.float32), np.asarray(b, np.float32),
                    rtol=5e-2, atol=2e-3, err_msg=policy),
                grads, base_grads)

    def test_bad_policy_rejected_at_resolve_time(self):
        with pytest.raises(ValueError, match="remat policy"):
            auto_accelerate(GPT(GPTConfig.nano()),
                            strategy=[("checkpoint", {"policy": "bogus"})])

    def test_policy_threads_into_model_config(self):
        res = auto_accelerate(
            GPT(GPTConfig.nano()),
            strategy=[("fsdp", {}), ("checkpoint", {"policy": "dots"})])
        assert res.model.config.remat is True
        assert res.model.config.remat_policy == "dots"


class TestStreamedAttention:
    """Blockwise-scan fallback (_use_streamed): O(s*block) temps on any
    backend — the memory-faithful stand-in for the Pallas kernels used by
    the 8B AOT fit proof (tests/test_scale_8b.py)."""

    @pytest.mark.parametrize("causal,sq,sk", [(True, 256, 256),
                                              (False, 256, 256),
                                              (True, 128, 384)])
    def test_streamed_matches_reference(self, causal, sq, sk):
        from dlrover_wuqiong_tpu.ops.flash_attention import (
            _reference_with_lse,
            _streamed_with_lse,
        )
        key = jax.random.PRNGKey(11)
        kq, kk, kv = jax.random.split(key, 3)
        scale = 1.0 / np.sqrt(32)
        q = jax.random.normal(kq, (2, 3, sq, 32), jnp.float32)
        k = jax.random.normal(kk, (2, 3, sk, 32), jnp.float32)
        v = jax.random.normal(kv, (2, 3, sk, 32), jnp.float32)
        o_s, lse_s = _streamed_with_lse(q, k, v, causal, scale, 128)
        o_r, lse_r = _reference_with_lse(q, k, v, causal, scale)
        np.testing.assert_allclose(o_s, o_r, atol=2e-5)
        np.testing.assert_allclose(lse_s, lse_r, atol=2e-5)

    @pytest.mark.parametrize("causal", [True, False])
    def test_streamed_grads_match_dense_path(self, causal, monkeypatch):
        """End-to-end through flash_attention's custom VJP: forcing the
        streamed path must give the same grads as the dense fallback."""
        from dlrover_wuqiong_tpu.ops import flash_attention as fa

        key = jax.random.PRNGKey(12)
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(kq, (1, 2, 256, 32), jnp.float32)
        k = jax.random.normal(kk, (1, 2, 256, 32), jnp.float32)
        v = jax.random.normal(kv, (1, 2, 256, 32), jnp.float32)

        def loss(q, k, v):
            return (fa.flash_attention(q, k, v, causal=causal) ** 2).sum()

        monkeypatch.setenv("DWT_FA_STREAMED", "0")
        g_dense = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        monkeypatch.setenv("DWT_FA_STREAMED", "1")
        g_str = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_str, g_dense):
            np.testing.assert_allclose(a, b, atol=3e-4)

    def test_streamed_lse_cotangent(self, monkeypatch):
        """flash_attention_with_lse differentiates through BOTH outputs on
        the streamed path (the ring-attention building block)."""
        from dlrover_wuqiong_tpu.ops import flash_attention as fa

        key = jax.random.PRNGKey(13)
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(kq, (1, 2, 128, 32), jnp.float32)
        k = jax.random.normal(kk, (1, 2, 128, 32), jnp.float32)
        v = jax.random.normal(kv, (1, 2, 128, 32), jnp.float32)

        def loss(q, k, v):
            o, lse = fa.flash_attention_with_lse(q, k, v, causal=True)
            return (o ** 2).sum() + (lse ** 2).sum()

        monkeypatch.setenv("DWT_FA_STREAMED", "0")
        g_dense = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        monkeypatch.setenv("DWT_FA_STREAMED", "1")
        g_str = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_str, g_dense):
            np.testing.assert_allclose(a, b, atol=3e-4)

    def test_streamed_fully_masked_rows_sq_gt_sk(self):
        """causal with sq > sk: rows that see NO keys must return 0 with
        lse=-inf (matching the dense reference), not uniform attention
        (the m_new == NEG_INF exp(0) pitfall)."""
        from dlrover_wuqiong_tpu.ops.flash_attention import (
            _reference_with_lse,
            _streamed_with_lse,
        )
        key = jax.random.PRNGKey(14)
        kq, kk, kv = jax.random.split(key, 3)
        scale = 1.0 / np.sqrt(16)
        q = jax.random.normal(kq, (1, 2, 128, 16), jnp.float32)
        k = jax.random.normal(kk, (1, 2, 64, 16), jnp.float32)
        v = jax.random.normal(kv, (1, 2, 64, 16), jnp.float32)
        o_s, lse_s = _streamed_with_lse(q, k, v, True, scale, 32)
        o_r, lse_r = _reference_with_lse(q, k, v, True, scale)
        np.testing.assert_allclose(o_s, o_r, atol=2e-5)
        np.testing.assert_allclose(lse_s, lse_r, atol=2e-5)
        # the first sq-sk rows are fully masked
        assert np.all(np.asarray(o_s[:, :, :63]) == 0.0)
        assert np.all(np.isneginf(np.asarray(lse_s[:, :, :63])))

    @pytest.mark.parametrize("causal,sq,sk", [(True, 128, 128),
                                              (False, 128, 128),
                                              (True, 64, 128),
                                              (True, 128, 64)])
    def test_fused_single_block_backward(self, causal, sq, sk):
        """num_q == num_kv == 1 rides the fused dq+dk+dv kernel — must
        match autodiff-of-reference exactly like the split path."""
        from dlrover_wuqiong_tpu.ops.flash_attention import (
            _fa_backward_pallas,
            _fa_forward_pallas,
        )
        key = jax.random.PRNGKey(8)
        kq, kk, kv, kg = jax.random.split(key, 4)
        scale = 1.0 / np.sqrt(128)
        q = jax.random.normal(kq, (2, sq, 128), jnp.float32)
        k = jax.random.normal(kk, (2, sk, 128), jnp.float32)
        v = jax.random.normal(kv, (2, sk, 128), jnp.float32)
        g = jax.random.normal(kg, (2, sq, 128), jnp.float32)
        o, lse = _fa_forward_pallas(q, k, v, causal, scale, sq, sk,
                                    interpret=True)
        dq, dk, dv = _fa_backward_pallas(q, k, v, o, lse, g, causal,
                                         scale, sq, sk, interpret=True)

        def ref_loss(q, k, v):
            # _reference_with_lse (not _attention_reference): the naive
            # softmax turns a row with NO visible keys (sq > sk) into
            # NaN and poisons its grads via 0*NaN; the lse variant
            # defines out = 0 for empty rows, matching the kernels
            from dlrover_wuqiong_tpu.ops.flash_attention import (
                _reference_with_lse,
            )

            out, _ = _reference_with_lse(q[None], k[None], v[None],
                                         causal, scale)
            return (out[0] * g).sum()

        rq, rk, rv = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_allclose(dq, rq, atol=3e-4)
        np.testing.assert_allclose(dk, rk, atol=3e-4)
        np.testing.assert_allclose(dv, rv, atol=3e-4)


class TestMultiSliceStrategy:
    """Resolve-time contract of the multi_slice (DCN) strategy."""

    def test_plan_shape(self):
        from dlrover_wuqiong_tpu.auto.accelerate import resolve_strategy

        ctx = resolve_strategy(
            [("multi_slice", {"slices": 2, "tp": 2})], 8)
        p = ctx.plan
        assert (p.dp, p.fsdp, p.tp) == (2, 2, 2), p

    def test_uneven_slices_rejected(self):
        from dlrover_wuqiong_tpu.auto.accelerate import resolve_strategy

        with pytest.raises(ValueError, match="devices/slice"):
            resolve_strategy(
                [("multi_slice", {"slices": 3})], 8)

    def test_tp_must_divide_slice(self):
        from dlrover_wuqiong_tpu.auto.accelerate import resolve_strategy

        with pytest.raises(ValueError, match="divide the"):
            resolve_strategy(
                [("multi_slice", {"slices": 2, "tp": 3})], 8)

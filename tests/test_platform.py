"""Platform layer tests: scheduler backends, PodScaler/PodWatcher,
DistJobManager relaunch, resource optimizer, JobAutoScaler.

Mirrors reference `dlrover/python/tests/test_pod_scaler.py` /
`test_job_manager.py` style: real master objects over a fake platform.
"""

import sys
import time

import pytest

from dlrover_wuqiong_tpu.common.constants import (
    NodeExitReason,
    NodeStatus,
    NodeType,
)
from dlrover_wuqiong_tpu.common.node import Node, NodeResource
from dlrover_wuqiong_tpu.master.job_manager import DistJobManager
from dlrover_wuqiong_tpu.master.resource_optimizer import (
    JobAutoScaler,
    LocalResourceOptimizer,
    OptimizeStage,
)
from dlrover_wuqiong_tpu.master.scaler import PodScaler, ScalePlan
from dlrover_wuqiong_tpu.master.watcher import PodWatcher
from dlrover_wuqiong_tpu.master.speed_monitor import SpeedMonitor
from dlrover_wuqiong_tpu.scheduler import (
    FakeSchedulerClient,
    NodeSpec,
    SubprocessSchedulerClient,
    new_scheduler_client,
)


def _wait(cond, timeout=10.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


class TestSchedulerBackends:
    def test_factory(self):
        assert isinstance(new_scheduler_client("fake"), FakeSchedulerClient)
        assert isinstance(new_scheduler_client("local"),
                          SubprocessSchedulerClient)
        with pytest.raises(ValueError):
            new_scheduler_client("nope")

    def test_fake_crud_and_watch(self):
        c = FakeSchedulerClient()
        assert c.create_node(NodeSpec(NodeType.WORKER, 0))
        assert len(c.list_nodes()) == 1
        events = list(c.watch(timeout=0.1))
        assert len(events) == 1 and events[0].node.id == 0
        assert c.delete_node(NodeType.WORKER, 0)
        assert c.list_nodes() == []

    def test_subprocess_lifecycle(self):
        c = SubprocessSchedulerClient()
        spec = NodeSpec(NodeType.WORKER, 0,
                        command=[sys.executable, "-c",
                                 "import time; time.sleep(30)"])
        assert c.create_node(spec)
        nodes = c.list_nodes()
        assert nodes[0].status == NodeStatus.RUNNING
        assert c.delete_node(NodeType.WORKER, 0)
        assert c.list_nodes() == []

    def test_subprocess_exit_events(self):
        c = SubprocessSchedulerClient()
        c.create_node(NodeSpec(NodeType.WORKER, 1,
                               command=[sys.executable, "-c", "exit(3)"]))
        events = []
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            events += list(c.watch(timeout=0.3))
            if any(e.node.status == NodeStatus.FAILED for e in events):
                break
        failed = [e for e in events if e.node.status == NodeStatus.FAILED]
        assert failed and "exit_code=3" in failed[0].node.exit_reason
        c.close()


class TestPodScaler:
    def test_scale_plan(self):
        c = FakeSchedulerClient()
        s = PodScaler(c)
        plan = ScalePlan(launch_nodes=[NodeSpec(NodeType.WORKER, i, i)
                                       for i in range(3)])
        s.scale(plan)
        assert len(c.list_nodes()) == 3
        node = Node(NodeType.WORKER, 1)
        s.scale_down(node)
        assert len(c.list_nodes()) == 2

    def test_create_retry_on_platform_flake(self):
        c = FakeSchedulerClient(fail_creates=2)
        s = PodScaler(c, retry_interval=0.05)
        s.scale_up(Node(NodeType.WORKER, 0))
        assert _wait(lambda: len(c.list_nodes()) == 1, timeout=5)
        assert len(c.create_calls) == 3  # 2 failures + 1 success
        s.stop()


class TestPodWatcher:
    def test_events_reach_handler(self):
        c = FakeSchedulerClient()
        seen = []
        w = PodWatcher(c, seen.append, poll_timeout=0.1)
        w.start()
        c.create_node(NodeSpec(NodeType.WORKER, 0))
        c.set_node_status(NodeType.WORKER, 0, NodeStatus.RUNNING)
        assert _wait(lambda: len(seen) >= 2)
        w.stop()


class TestDistJobManager:
    def test_initial_scale_and_failure_relaunch(self):
        c = FakeSchedulerClient()
        jm = DistJobManager(c, num_workers=2)
        jm.start()
        assert _wait(lambda: len(c.list_nodes()) == 2)
        # platform reports running, then one worker dies
        c.set_node_status(NodeType.WORKER, 0, NodeStatus.RUNNING)
        c.set_node_status(NodeType.WORKER, 1, NodeStatus.RUNNING)
        c.set_node_status(NodeType.WORKER, 0, NodeStatus.FAILED,
                          exit_reason=NodeExitReason.KILLED)
        # relaunch drives a NEW create through the scaler
        assert _wait(lambda: len(c.create_calls) >= 3)
        jm.stop()

    def test_fatal_error_not_relaunched(self):
        c = FakeSchedulerClient()
        jm = DistJobManager(c, num_workers=1)
        jm.start()
        assert _wait(lambda: len(c.list_nodes()) == 1)
        c.set_node_status(NodeType.WORKER, 0, NodeStatus.RUNNING)
        c.set_node_status(NodeType.WORKER, 0, NodeStatus.FAILED,
                          exit_reason=NodeExitReason.FATAL_ERROR)
        time.sleep(0.5)
        assert len(c.create_calls) == 1  # no relaunch
        jm.stop()


class TestResourceOptimizer:
    def test_phased_plans(self):
        opt = LocalResourceOptimizer(
            default_resource=NodeResource(cpu=2, memory_mb=1000),
            sample_after=2, stable_after=4, headroom=2.0)
        assert opt.stage() == OptimizeStage.INIT
        assert opt.plan_node_resource().memory_mb == 1000
        opt.report_usage(NodeType.WORKER, NodeResource(cpu=1, memory_mb=800))
        opt.report_usage(NodeType.WORKER, NodeResource(cpu=1, memory_mb=900))
        assert opt.stage() == OptimizeStage.SAMPLE
        assert opt.plan_node_resource().memory_mb == 1800  # max * headroom
        opt.report_usage(NodeType.WORKER, NodeResource(cpu=1, memory_mb=850))
        opt.report_usage(NodeType.WORKER, NodeResource(cpu=1, memory_mb=820))
        assert opt.stage() == OptimizeStage.STABLE
        plan = opt.plan_node_resource()
        assert 1600 <= plan.memory_mb <= 1800  # p95-ish * headroom

    def test_oom_bump_capped(self):
        opt = LocalResourceOptimizer(oom_factor=2.0, max_memory_mb=5000)
        r = opt.bump_oom(NodeResource(cpu=1, memory_mb=2000))
        assert r.memory_mb == 4000
        r2 = opt.bump_oom(r)
        assert r2.memory_mb == 5000  # capped


class TestJobAutoScaler:
    def _mk(self, target=3):
        c = FakeSchedulerClient()
        jm = DistJobManager(c, num_workers=target)
        opt = LocalResourceOptimizer()
        sm = SpeedMonitor()
        scaler = PodScaler(c)
        auto = JobAutoScaler(jm, sm, opt, scaler, target_workers=target,
                             interval=3600)
        return c, jm, auto

    def test_reconcile_missing_workers(self):
        c, jm, auto = self._mk(target=3)
        # only 1 of 3 registered alive
        n = jm.register_node(NodeType.WORKER, 0, rank_index=0)
        n.update_status(NodeStatus.RUNNING)
        plan = auto.decide()
        assert len(plan.launch_nodes) == 2
        ranks = sorted(s.rank_index for s in plan.launch_nodes)
        assert ranks == [1, 2]  # fills the missing ranks
        auto.execute(plan)
        assert len(c.list_nodes()) == 2

    def test_scale_down_removes_highest_ranks(self):
        c, jm, auto = self._mk(target=2)
        for i in range(4):
            n = jm.register_node(NodeType.WORKER, i, rank_index=i)
            n.update_status(NodeStatus.RUNNING)
        plan = auto.decide()
        assert {n.rank_index for n in plan.remove_nodes} == {2, 3}

    def test_oom_event_bumps_resource(self):
        _, jm, auto = self._mk()
        node = jm.register_node(NodeType.WORKER, 0)
        node.config_resource = NodeResource(cpu=1, memory_mb=1000)
        auto.handle_oom(node)
        assert node.config_resource.memory_mb > 1000


class TestDistJobManagerSubprocess:
    def test_requires_spec_factory(self):
        with pytest.raises(ValueError, match="spec_factory"):
            DistJobManager(SubprocessSchedulerClient(), num_workers=1)

    def test_real_process_crash_relaunch_succeed(self, tmp_path):
        """The same scaler/watcher path drives real processes: a worker
        that fails twice then succeeds is relaunched until success."""
        counter = tmp_path / "count"
        script = (
            "import os,sys;p=%r;n=int(open(p).read()) if os.path.exists(p)"
            " else 0;open(p,'w').write(str(n+1));sys.exit(9 if n<2 else 0)"
            % str(counter))

        def spec_factory(node):
            return NodeSpec(node.type, node.id,
                            rank_index=node.rank_index or 0,
                            command=[sys.executable, "-c", script],
                            relaunch_count=node.relaunch_count)

        client = SubprocessSchedulerClient()
        jm = DistJobManager(client, num_workers=1,
                            spec_factory=spec_factory)
        jm.start()
        assert _wait(jm.all_workers_succeeded, timeout=30)
        assert any(n.relaunch_count > 0 for n in jm.all_nodes())
        jm.stop()
        client.close()


class TestRayBackend:
    def test_factory_raises_without_ray(self):
        try:
            import ray  # noqa: F401
            pytest.skip("ray installed — guarded-import test not applicable")
        except ImportError:
            pass
        with pytest.raises(RuntimeError, match="ray"):
            new_scheduler_client("ray")

"""ElasticJob operator tests (reference go/operator parity).

Mirrors `go/operator/pkg/controllers/suite_test.go` in spirit: reconcile an
ElasticJob CR into a running master, drive phase transitions, apply a
ScalePlan.
"""

import sys
import time

from dlrover_wuqiong_tpu.operator import (
    ElasticJob,
    ElasticJobController,
    ElasticJobSpec,
    InMemoryJobStore,
    JobPhase,
    ReplicaSpec,
    ScalePlan,
    elasticjob_crd_manifest,
)
from dlrover_wuqiong_tpu.scheduler import NodeSpec, SubprocessSchedulerClient


class _FakeMaster:
    def __init__(self, job):
        self.addr = "127.0.0.1:1234"
        self.exit_code = None
        self.scaled = []

    def poll(self):
        return self.exit_code

    def scale(self, counts):
        self.scaled.append(counts)


class TestController:
    def _setup(self):
        store = InMemoryJobStore()
        masters = {}

        def factory(job):
            masters[job.name] = _FakeMaster(job)
            return masters[job.name]

        ctl = ElasticJobController(store, master_factory=factory)
        return store, ctl, masters

    def test_reconcile_creates_master_once(self):
        store, ctl, masters = self._setup()
        job = ElasticJob("j1", spec=ElasticJobSpec(
            replica_specs={"worker": ReplicaSpec(replicas=3)}))
        store.submit(job)
        ctl.reconcile_once()
        assert "j1" in masters
        assert store.list_jobs()[0].phase == JobPhase.LAUNCHING
        ctl.reconcile_once()  # idempotent: still one master, now RUNNING
        assert len(masters) == 1
        assert store.list_jobs()[0].phase == JobPhase.RUNNING

    def test_phase_follows_master_exit(self):
        store, ctl, masters = self._setup()
        store.submit(ElasticJob("j2"))
        ctl.reconcile_once()
        ctl.reconcile_once()
        masters["j2"].exit_code = 0
        ctl.reconcile_once()
        assert store.list_jobs()[0].phase == JobPhase.SUCCEEDED

    def test_failed_master(self):
        store, ctl, masters = self._setup()
        store.submit(ElasticJob("j3"))
        ctl.reconcile_once()
        masters["j3"].exit_code = 2
        ctl.reconcile_once()
        assert store.list_jobs()[0].phase == JobPhase.FAILED

    def test_scale_plan_forwarded(self):
        store, ctl, masters = self._setup()
        store.submit(ElasticJob("j4"))
        ctl.reconcile_once()
        store.submit_scale_plan(ScalePlan("j4", {"worker": 5}))
        ctl.reconcile_once()
        assert masters["j4"].scaled == [{"worker": 5}]


class TestManifests:
    def test_crd_manifest_shape(self):
        m = elasticjob_crd_manifest()
        assert m["kind"] == "CustomResourceDefinition"
        assert m["spec"]["names"]["kind"] == "ElasticJob"

    def test_job_from_manifest(self):
        obj = {
            "metadata": {"name": "trainer", "namespace": "ml"},
            "spec": {
                "distributionStrategy": "AllreduceStrategy",
                "replicaSpecs": {"worker": {"replicas": 4,
                                            "memory_mb": 2048}},
            },
        }
        job = ElasticJob.from_manifest(obj)
        assert job.name == "trainer" and job.namespace == "ml"
        assert job.spec.replica_specs["worker"].replicas == 4


class TestRealMasterProcess:
    def test_subprocess_master_lifecycle(self):
        """The default factory launches a real master process through the
        scheduler client and tracks it to completion."""
        client = SubprocessSchedulerClient()
        store = InMemoryJobStore()
        ctl = ElasticJobController(store, scheduler_client=client)
        # a short-lived stand-in master (runs 1s then exits 0)
        def factory(job):
            spec = NodeSpec(node_type="master", node_id=0,
                            command=[sys.executable, "-c",
                                     "import time; time.sleep(1)"])
            assert client.create_node(spec)
            return _handle(client)

        class _handle:
            def __init__(self, client):
                self.client = client
                self.addr = ""

            def poll(self):
                nodes = self.client.list_nodes()
                if not nodes:
                    return 0
                from dlrover_wuqiong_tpu.common.constants import NodeStatus
                st = nodes[0].status
                return {NodeStatus.SUCCEEDED: 0,
                        NodeStatus.FAILED: 1}.get(st)

            def scale(self, counts):
                pass

        ctl.master_factory = factory
        store.submit(ElasticJob("real1"))
        ctl.reconcile_once()
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            ctl.reconcile_once()
            if store.list_jobs()[0].phase in (JobPhase.SUCCEEDED,
                                              JobPhase.FAILED):
                break
            time.sleep(0.3)
        assert store.list_jobs()[0].phase == JobPhase.SUCCEEDED
        client.close()

"""Online variant autotuner (auto/tuner.py) + fused-window host overlap.

The jax-free pieces deterministically: the interleaved A/B scorer on an
injected clock (drift robustness, hysteresis no-flap), the atomic
corrupt-tolerant winner store, the autotuner state machine, the
sanctioned env writers, and the trainer's metrics pump.  The
zero-cold-compile cutover pin runs a subprocess worker against a real
persistent compile cache (the warm-pool test idiom).  The live trainer
loop is covered by tests/test_trainer.py and `chaos perf-regress`
invariant 4.
"""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from dlrover_wuqiong_tpu.auto.tuner import (
    InterleavedScorer,
    TuningStore,
    Variant,
    VariantAutotuner,
    apply_variant,
    default_variants,
    env_signature,
    family_key,
    load_winner,
    make_record,
    order_variants,
    shape_class,
    tuning_path,
    variant_env,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


# ---------------------------------------------------------------- env


class TestVariantEnv:
    def test_scoped_flip_restores_previous(self):
        os.environ.pop("DWT_FA_STREAMED", None)
        with variant_env({"DWT_FA_STREAMED": "1"}):
            assert os.environ["DWT_FA_STREAMED"] == "1"
        assert "DWT_FA_STREAMED" not in os.environ

    def test_scoped_flip_restores_explicit_value(self):
        os.environ["DWT_FA_PACK"] = "8"
        try:
            with variant_env({"DWT_FA_PACK": "4"}):
                assert os.environ["DWT_FA_PACK"] == "4"
            assert os.environ["DWT_FA_PACK"] == "8"
        finally:
            os.environ.pop("DWT_FA_PACK", None)

    def test_empty_string_genuinely_deletes(self):
        # unset is a distinct value for DWT_FA_STREAMED (the kernel's
        # heuristic path) — "" must delete, not set-to-empty
        os.environ["DWT_FA_STREAMED"] = "1"
        try:
            with variant_env({"DWT_FA_STREAMED": ""}):
                assert "DWT_FA_STREAMED" not in os.environ
            assert os.environ["DWT_FA_STREAMED"] == "1"
        finally:
            os.environ.pop("DWT_FA_STREAMED", None)

    def test_non_trace_var_rejected(self):
        with pytest.raises(ValueError, match="not a trace-time toggle"):
            apply_variant({"DWT_JOB_NAME": "x"})

    def test_signature_tracks_flips(self):
        base = env_signature()
        with variant_env({"DWT_FA_NO_FUSED": "1"}):
            assert env_signature() != base
        assert env_signature() == base

    def test_new_axes_are_sanctioned_toggles(self):
        # the ISSUE-16 names registered in TRACE_ENV_VARS flow through
        # the tuner's writers like the DWT_FA_* originals
        base = env_signature()
        with variant_env({"DWT_FP8_DENSE": "1"}):
            assert os.environ["DWT_FP8_DENSE"] == "1"
            assert env_signature() != base
        assert "DWT_FP8_DENSE" not in os.environ
        with variant_env({"DWT_REMAT_POLICY": "dots"}):
            assert env_signature() != base
        assert env_signature() == base


class TestDefaultVariants:
    def test_cpu_matrix_small(self):
        names = [v.name for v in default_variants("cpu")]
        assert names == ["default", "no-fused", "streamed"]

    def test_tpu_matrix_adds_pack_axes(self):
        names = [v.name for v in default_variants("tpu")]
        assert "pack4" in names and "unstreamed" in names

    def test_fused_k_ladder(self):
        vs = {v.name: v for v in default_variants("cpu", include_k=(4, 8))}
        assert vs["fused-k4"].fused_steps == 4
        assert vs["fused-k8"].fused_steps == 8

    def test_numerics_axis_is_opt_in(self):
        # fp8 changes the loss trajectory: absent unless explicitly
        # opted in, and marked numerics=True when present
        assert "fp8-dense" not in {v.name for v in default_variants("cpu")}
        vs = {v.name: v for v in default_variants("cpu", numerics=True)}
        fp8 = vs["fp8-dense"]
        assert fp8.numerics and fp8.axis == "quant"
        assert fp8.env == {"DWT_FP8_DENSE": "1"}
        # every other default stays numerics-neutral
        assert not any(v.numerics for n, v in vs.items()
                       if n != "fp8-dense")

    def test_remat_ladder(self):
        vs = {v.name: v
              for v in default_variants(
                  "cpu", remat_policies=("dots", "save_names"))}
        assert vs["remat-dots"].env == {"DWT_REMAT_POLICY": "dots"}
        assert vs["remat-dots"].axis == "remat"
        assert not vs["remat-dots"].numerics  # same math, new HLO
        assert vs["remat-save_names"].env == \
            {"DWT_REMAT_POLICY": "save_names"}


class TestShapeClass:
    def test_geometry_key(self):
        assert shape_class(32, 1024) == "b32-s1024"
        assert shape_class(32, 1024, "d768x12") == "b32-s1024-d768x12"

    def test_distinct_geometries_distinct_keys(self):
        assert shape_class(8, 128, "d128x2") != shape_class(8, 4096,
                                                            "d128x2")
        assert shape_class(8, 128, "d128x2") != shape_class(8, 128,
                                                            "d768x12")


class TestOrderVariants:
    def _space(self):
        return default_variants("tpu", numerics=True,
                                remat_policies=("dots",))

    def test_matmul_heavy_tries_quant_first(self):
        ordered = order_variants(
            self._space(), {"matmul": 8.0, "collective": 1.0})
        names = [v.name for v in ordered]
        assert names[0] == "default"  # incumbent anchors the comparison
        assert names[1] == "fp8-dense"  # quant targets matmul
        # collective-targeting axes follow, untagged keep decl order
        assert names.index("fp8-dense") < names.index("streamed")

    def test_collective_heavy_tries_pack_stream_first(self):
        ordered = order_variants(
            self._space(), {"collective": 8.0, "matmul": 1.0})
        names = [v.name for v in ordered]
        assert names[0] == "default"
        # pack/stream (collective-targeted) outrank quant; ties among
        # them keep declaration order (streamed declared before pack4)
        assert set(names[1:4]) == {"streamed", "pack4", "unstreamed"}
        assert names.index("streamed") < names.index("pack4")
        assert names.index("pack4") < names.index("fp8-dense")

    def test_empty_profile_keeps_declaration_order(self):
        space = self._space()
        assert [v.name for v in order_variants(space, {})] == \
            [v.name for v in space]
        assert [v.name for v in order_variants(space, None)] == \
            [v.name for v in space]


# ------------------------------------------------------------- scorer


class TestInterleavedScorer:
    def test_round_robin_interleave(self):
        s = InterleavedScorer(["a", "b", "c"], min_samples=2)
        order = []
        for _ in range(6):
            c = s.next_candidate()
            order.append(c)
            s.note(c, 1.0)
        assert order == ["a", "b", "c", "a", "b", "c"]

    def test_drift_robust_winner(self):
        # chip-load drift: +8%/sample ramp on EVERY sample.  Interleaved
        # medians keep the 15%-faster candidate ahead; a back-to-back
        # schedule (all of "fast" measured last) would have buried it.
        s = InterleavedScorer(["slow", "fast"], min_samples=5,
                              hysteresis=0.05)
        drift = 1.0
        for i in range(10):
            c = s.next_candidate()
            base = 1.0 if c == "slow" else 0.85
            s.note(c, base * drift)
            drift *= 1.08
        name, decided = s.winner(incumbent="slow")
        assert decided and name == "fast"
        # the same samples laid back-to-back: fast's median exceeds
        # slow's — drift would have flipped the verdict
        back_to_back_fast = [0.85 * 1.08 ** i for i in range(5, 10)]
        back_to_back_slow = [1.0 * 1.08 ** i for i in range(0, 5)]
        assert sorted(back_to_back_fast)[2] > sorted(back_to_back_slow)[2]

    def test_hysteresis_keeps_tied_incumbent(self):
        s = InterleavedScorer(["default", "alt"], min_samples=3,
                              hysteresis=0.05)
        for _ in range(3):
            s.note("default", 1.00)
            s.note("alt", 0.97)  # 3% better: inside the 5% margin
        name, decided = s.winner(incumbent="default")
        assert decided and name == "default"

    def test_clear_margin_beats_hysteresis(self):
        s = InterleavedScorer(["default", "alt"], min_samples=3,
                              hysteresis=0.05)
        for _ in range(3):
            s.note("default", 1.00)
            s.note("alt", 0.90)
        name, decided = s.winner(incumbent="default")
        assert decided and name == "alt"

    def test_incomplete_returns_incumbent_undecided(self):
        s = InterleavedScorer(["a", "b"], min_samples=2)
        s.note("a", 1.0)
        name, decided = s.winner(incumbent="b")
        assert not decided and name == "b"

    def test_measure_uses_injected_clock(self):
        clk = FakeClock()

        def work():
            clk.t += 0.25

        s = InterleavedScorer(["a"], min_samples=1, clock=clk)
        dt = s.measure("a", work)
        assert dt == 0.25 and s.samples["a"] == [0.25]

    def test_input_validation(self):
        with pytest.raises(ValueError, match="at least one"):
            InterleavedScorer([])
        with pytest.raises(ValueError, match="duplicate"):
            InterleavedScorer(["a", "a"])
        with pytest.raises(KeyError):
            InterleavedScorer(["a"]).note("b", 1.0)

    def test_remove_discards_samples_and_rotation(self):
        s = InterleavedScorer(["a", "b", "c"], min_samples=1)
        s.note("a", 1.0)
        s.note("b", 0.1)  # would win
        s.remove("b")
        assert "b" not in s.samples and "b" not in s.candidates
        s.note("c", 0.5)
        assert s.complete()
        name, decided = s.winner(incumbent="a")
        assert decided and name == "c"  # b's samples are gone

    def test_remove_guards(self):
        s = InterleavedScorer(["a", "b"], min_samples=1)
        with pytest.raises(KeyError):
            s.remove("zz")
        s.remove("b")
        with pytest.raises(ValueError, match="last candidate"):
            s.remove("a")


# -------------------------------------------------------------- store


class TestTuningStore:
    def test_missing_file_starts_empty(self, tmp_path):
        st = TuningStore(tuning_path(str(tmp_path)))
        assert st.rows() == {} and st.lookup("fam") is None

    def test_corrupt_file_relearned_not_fatal(self, tmp_path):
        p = tuning_path(str(tmp_path))
        os.makedirs(os.path.dirname(p))
        for payload in ("{truncated", '{"families": "not-a-dict"}',
                        '[]', ""):
            with open(p, "w") as f:
                f.write(payload)
            st = TuningStore(p)
            assert st.rows() == {}
        # and a corrupt store still accepts a fresh publish
        st.publish("fam", {"variant": "streamed"})
        assert TuningStore(p).lookup("fam") == {"variant": "streamed"}

    def test_publish_reload_roundtrip(self, tmp_path):
        p = tuning_path(str(tmp_path))
        rec = make_record(
            Variant("streamed", {"DWT_FA_STREAMED": "1"}, fused_steps=4),
            executable_key="exe-1", fused_steps=4,
            medians={"default": 0.012, "streamed": 0.009}, windows=6)
        TuningStore(p).publish("fam", rec)
        got = TuningStore(p).lookup("fam")
        assert got == rec
        raw = json.load(open(p))
        assert raw["schema"] == 2 and "fam" in raw["families"]
        # v2 nested row: the family winner + the per-geometry map
        assert raw["families"]["fam"]["winner"] == rec
        assert raw["families"]["fam"]["shapes"] == {}
        # atomic publish leaves no tmp droppings
        assert [f for f in os.listdir(os.path.dirname(p))
                if f.endswith(".tmp")] == []

    def test_per_shape_publish_and_fallback(self, tmp_path):
        p = tuning_path(str(tmp_path))
        st = TuningStore(p)
        rec_small = make_record(
            Variant("streamed", {"DWT_FA_STREAMED": "1"}),
            executable_key="e1", fused_steps=1,
            medians={"streamed": 0.01}, windows=6,
            shape_class="b8-s128-d128x2")
        rec_big = make_record(
            Variant("no-fused", {"DWT_FA_NO_FUSED": "1"}),
            executable_key="e2", fused_steps=1,
            medians={"no-fused": 0.09}, windows=6,
            shape_class="b32-s4096-d128x2")
        st.publish("fam", rec_small, shape="b8-s128-d128x2")
        st.publish("fam", rec_big, shape="b32-s4096-d128x2")
        re = TuningStore(p)  # fresh reload
        # exact geometries answer their own winners
        assert re.lookup("fam", "b8-s128-d128x2")["variant"] == "streamed"
        assert re.lookup("fam", "b32-s4096-d128x2")["variant"] == \
            "no-fused"
        # an unseen geometry falls back to the family winner
        # (latest-published wins)
        assert re.lookup("fam", "b1-s32-d128x2")["variant"] == "no-fused"
        assert re.lookup("fam")["variant"] == "no-fused"

    def test_v1_shapeless_store_migrates_forward(self, tmp_path):
        """A PR-14-era flat tuning.json loads, serves its rows as the
        family fallback for every shape, and is upgraded in place to the
        nested layout by the next atomic publish — never re-learned."""
        p = tuning_path(str(tmp_path))
        os.makedirs(os.path.dirname(p))
        v1_row = {"variant": "streamed", "env": {"DWT_FA_STREAMED": "1"},
                  "fused_steps": 0, "executable_key": "e-old",
                  "medians": {"streamed": 0.01}, "windows": 6,
                  "exe_env": {"DWT_FA_STREAMED": "1"}}
        with open(p, "w") as f:
            json.dump({"schema": 1, "families": {"fam": v1_row}}, f)
        st = TuningStore(p)
        # served shapeless AND as the fallback for any geometry
        assert st.lookup("fam")["variant"] == "streamed"
        assert st.lookup("fam", "b8-s128")["variant"] == "streamed"
        assert load_winner(str(tmp_path), "fam",
                           shape="b1-s1")["variant"] == "streamed"
        # next publish upgrades the FILE in place (schema 2, nested),
        # keeping the migrated winner visible alongside the new shape row
        rec = make_record(Variant("no-fused", {"DWT_FA_NO_FUSED": "1"}),
                          executable_key="e-new", fused_steps=1,
                          medians={"no-fused": 0.02}, windows=4,
                          shape_class="b8-s128")
        st.publish("fam2", rec, shape="b8-s128")
        raw = json.load(open(p))
        assert raw["schema"] == 2
        assert raw["families"]["fam"]["winner"]["variant"] == "streamed"
        assert raw["families"]["fam2"]["shapes"]["b8-s128"] == rec
        # and the migrated v1 winner still serves after the upgrade
        assert TuningStore(p).lookup(
            "fam", "b9-s9")["variant"] == "streamed"

    def test_load_winner_shortcut(self, tmp_path):
        fam = family_key("fp", "cpu")
        assert load_winner(str(tmp_path), fam) is None
        assert load_winner("", fam) is None
        TuningStore(tuning_path(str(tmp_path))).publish(
            fam, {"variant": "no-fused"})
        assert load_winner(str(tmp_path), fam)["variant"] == "no-fused"

    def test_family_key_excludes_tunables(self):
        # same program, different backend → different family; the key
        # has no fused-K / env ingredient at all
        assert family_key("fp", "cpu") != family_key("fp", "tpu")
        assert family_key("fp", "cpu") == family_key("fp", "cpu")


# ----------------------------------------------------------- autotuner


def _drive(tuner, times):
    """Feed one window per entry; apply any requested cutover like the
    trainer does (pre-warm assumed instant)."""
    for t in times:
        nxt = tuner.note_window(t(tuner.current().name)
                                if callable(t) else t)
        if nxt is not None:
            tuner.cutover(nxt)


class TestVariantAutotuner:
    def _mk(self, tmp_path, **kw):
        store = TuningStore(tuning_path(str(tmp_path)))
        t = VariantAutotuner(
            default_variants("cpu"), store=store, family="fam",
            windows_per_variant=kw.pop("windows_per_variant", 2),
            clock=FakeClock(), **kw)
        t.bind_executable_context(
            strategy_fingerprint="fp", fused_steps=1, backend="cpu")
        return t

    def test_search_converges_and_persists(self, tmp_path):
        t = self._mk(tmp_path)
        per = {"default": 1.0, "no-fused": 1.2, "streamed": 0.8}
        _drive(t, [lambda n, per=per: per[n]] * 6)
        assert t.finished
        assert t.result().name == "streamed"
        assert t.current().name == "streamed"  # poll converges on winner
        row = load_winner(str(tmp_path), "fam")
        assert row["variant"] == "streamed"
        assert row["exe_env"]["DWT_FA_STREAMED"] == "1"
        assert row["exe_env"]["DWT_FA_NO_FUSED"] == ""
        assert row["executable_key"]  # joinable against baselines
        assert row["medians"]["streamed"] == pytest.approx(0.8)

    def test_decision_carries_measured_before_after(self, tmp_path):
        t = self._mk(tmp_path)
        per = {"default": 1.0, "no-fused": 1.2, "streamed": 0.8}
        _drive(t, [lambda n, per=per: per[n]] * 6)
        (d,) = t.decisions
        assert d["kind"] == "tuner" and d["variant"] == "streamed"
        assert d["before"]["step_time_s"] == pytest.approx(1.0)
        assert d["after"]["step_time_s"] == pytest.approx(0.8)
        assert d["windows"] == 6
        from dlrover_wuqiong_tpu.brain.policy import tuner_decision_effects

        (row,) = tuner_decision_effects(t.decisions)
        assert row["effect"]["before"] == d["before"]
        assert row["effect"]["after"] == d["after"]
        assert row["decision_id"] == d["decision_id"]

    def test_tied_search_keeps_incumbent(self, tmp_path):
        t = self._mk(tmp_path)
        _drive(t, [1.0] * 6)  # everyone identical: hysteresis holds
        assert t.finished and t.result().name == "default"

    def test_settled_tuner_ignores_further_windows(self, tmp_path):
        t = self._mk(tmp_path)
        _drive(t, [1.0] * 6)
        assert t.note_window(99.0) is None
        assert t.result().name == "default"

    def test_executable_key_changes_with_winner_env(self, tmp_path):
        # the persisted key must be the key the WINNER's windows land on
        t = self._mk(tmp_path)
        per = {"default": 1.0, "no-fused": 1.2, "streamed": 0.8}
        _drive(t, [lambda n, per=per: per[n]] * 6)
        from dlrover_wuqiong_tpu.telemetry.perf import executable_key

        row = load_winner(str(tmp_path), "fam")
        assert row["executable_key"] != executable_key("fp", 1, "cpu")
        with variant_env({"DWT_FA_STREAMED": "1"}):
            assert row["executable_key"] == executable_key("fp", 1, "cpu")

    def test_thread_safe_interleave(self, tmp_path):
        # pump thread notes windows while the main loop polls current()
        t = self._mk(tmp_path, windows_per_variant=32)
        stop = threading.Event()
        seen = []

        def poll():
            while not stop.is_set():
                seen.append(t.current().name)

        th = threading.Thread(target=poll, daemon=True)
        th.start()
        try:
            _drive(t, [1.0] * (32 * 3))
        finally:
            stop.set()
            th.join(10)
        assert t.finished and set(seen) <= set(t.variants)

    def test_category_hint_orders_search(self, tmp_path):
        """Observatory-driven search (ROADMAP 4d): under a matmul-heavy
        profile the quant variant is measured before pack/stream; under
        a collective-heavy one pack/stream come first."""
        def first_challenger(hint):
            t = VariantAutotuner(
                default_variants("tpu", numerics=True),
                windows_per_variant=1, category_hint=hint,
                loss_bound=1e9,  # guard armed but never trips here
                clock=FakeClock())
            # first window goes to the incumbent; the answer is the
            # first CHALLENGER the ordered interleave schedules
            nxt = t.note_window(1.0, loss=1.0)
            return nxt.name
        assert first_challenger(
            {"matmul": 8.0, "collective": 1.0}) == "fp8-dense"
        assert first_challenger(
            {"collective": 8.0, "matmul": 1.0}) == "streamed"
        assert first_challenger(None) == "no-fused"  # declaration order

    def test_max_candidates_prunes_ordered_tail(self, tmp_path):
        t = VariantAutotuner(
            default_variants("tpu", numerics=True),
            category_hint={"matmul": 8.0, "collective": 1.0},
            max_candidates=3, clock=FakeClock())
        # incumbent + the two most matmul-relevant survive
        assert set(t.variants) == {"default", "fp8-dense", "streamed"}

    def test_per_shape_winners_distinct_geometries(self, tmp_path):
        """Acceptance (a): two geometries learn DIFFERENT winners in one
        family; a third unseen geometry serves the family fallback."""
        store_path = tuning_path(str(tmp_path))
        per_small = {"default": 1.0, "no-fused": 1.2, "streamed": 0.8}
        per_big = {"default": 1.0, "no-fused": 0.7, "streamed": 1.3}

        def learn(shape, per):
            t = VariantAutotuner(
                default_variants("cpu"), store=TuningStore(store_path),
                family="fam", windows_per_variant=2, shape_class=shape,
                clock=FakeClock())
            t.bind_executable_context(strategy_fingerprint="fp",
                                      fused_steps=1, backend="cpu")
            _drive(t, [lambda n, per=per: per[n]] * 6)
            assert t.finished
            return t.result().name

        assert learn("b8-s128-d128x2", per_small) == "streamed"
        assert learn("b32-s4096-d128x2", per_big) == "no-fused"
        # both winners persisted per geometry, third shape falls back
        assert load_winner(str(tmp_path), "fam",
                           shape="b8-s128-d128x2")["variant"] == "streamed"
        assert load_winner(str(tmp_path), "fam",
                           shape="b32-s4096-d128x2")["variant"] == \
            "no-fused"
        fb = load_winner(str(tmp_path), "fam", shape="b1-s32-d128x2")
        assert fb["variant"] == "no-fused"  # latest family-wide winner
        # the decision carries its geometry
        assert load_winner(str(tmp_path), "fam",
                           shape="b8-s128-d128x2")["shape_class"] == \
            "b8-s128-d128x2"


class TestLossDivergenceGuard:
    """Acceptance (c): a numerics variant whose loss diverges is
    auto-reverted — removed from the search, cut back to the incumbent,
    journaled as a PolicyDecision-style revert."""

    def _mk(self, tmp_path, loss_bound=0.05, **kw):
        t = VariantAutotuner(
            default_variants("cpu", numerics=True),
            store=TuningStore(tuning_path(str(tmp_path))), family="fam",
            windows_per_variant=2, loss_bound=loss_bound,
            shape_class="b8-s128", clock=FakeClock(), **kw)
        t.bind_executable_context(strategy_fingerprint="fp",
                                  fused_steps=1, backend="cpu")
        return t

    def _drive_losses(self, t, per, loss_fn, max_windows=64):
        guard = 0
        while not t.finished and guard < max_windows:
            guard += 1
            cur = t.current()
            nxt = t.note_window(per[cur.name], loss=loss_fn(cur))
            if nxt is not None:
                t.cutover(nxt)

    def test_diverged_fp8_reverted_and_journaled(self, tmp_path):
        t = self._mk(tmp_path)
        # fp8 is the FASTEST candidate — without the guard it would win
        per = {"default": 1.0, "no-fused": 1.2, "streamed": 0.8,
               "fp8-dense": 0.4}
        self._drive_losses(
            t, per, lambda v: 9.0 if v.numerics else 2.0)
        assert t.finished and t.result().name == "streamed"
        assert "fp8-dense" not in t.variants
        reverts = [d for d in t.decisions if d["kind"] == "tuner-revert"]
        assert len(reverts) == 1
        r = reverts[0]
        assert r["reverted"] == "fp8-dense"
        assert r["variant"] == "default"  # cut-back target
        assert r["loss"] == pytest.approx(9.0)
        assert r["loss_ref"] == pytest.approx(2.0)
        assert r["loss_bound"] == pytest.approx(0.05)
        # the degraded step time never entered the scorer
        assert "fp8-dense" not in t.snapshot()["medians"]
        # the persisted winner is the guard's survivor
        assert load_winner(str(tmp_path), "fam",
                           shape="b8-s128")["variant"] == "streamed"

    def test_revert_surfaces_through_policy_bridge(self, tmp_path):
        from dlrover_wuqiong_tpu.brain.policy import tuner_decision_effects

        t = self._mk(tmp_path)
        per = {"default": 1.0, "no-fused": 1.2, "streamed": 0.8,
               "fp8-dense": 0.4}
        self._drive_losses(
            t, per, lambda v: 9.0 if v.numerics else 2.0)
        rows = tuner_decision_effects(t.decisions)
        kinds = [r["kind"] for r in rows]
        assert "tuner-revert" in kinds and "tuner" in kinds
        rev = rows[kinds.index("tuner-revert")]
        assert rev["reverted"] == "fp8-dense"
        assert rev["loss"] == pytest.approx(9.0)
        assert rev["effect"]["before"] == {"loss": 9.0}
        assert rev["effect"]["after"] == {"loss": 2.0}
        assert rev["shape_class"] == "b8-s128"

    def test_within_bound_fp8_stays_and_can_win(self, tmp_path):
        t = self._mk(tmp_path)
        per = {"default": 1.0, "no-fused": 1.2, "streamed": 0.8,
               "fp8-dense": 0.4}
        # fp8 loss within the 5% margin of the 2.0 reference: no revert
        self._drive_losses(
            t, per, lambda v: 2.05 if v.numerics else 2.0)
        assert t.finished and t.result().name == "fp8-dense"
        assert [d["kind"] for d in t.decisions] == ["tuner"]

    def test_loss_decline_never_reverts(self, tmp_path):
        # one-sided guard: training loss naturally FALLS — a numerics
        # variant with lower loss than the reference must never trip
        t = self._mk(tmp_path)
        per = {"default": 1.0, "no-fused": 1.2, "streamed": 0.8,
               "fp8-dense": 0.4}
        self._drive_losses(
            t, per, lambda v: 1.0 if v.numerics else 2.0)
        assert t.finished and t.result().name == "fp8-dense"
        assert not [d for d in t.decisions
                    if d["kind"] == "tuner-revert"]

    def test_guard_disarmed_without_bound(self, tmp_path):
        # loss_bound=0 (trainer default when tune_numerics is off):
        # losses ride along but never disqualify
        t = self._mk(tmp_path, loss_bound=0.0)
        per = {"default": 1.0, "no-fused": 1.2, "streamed": 0.8,
               "fp8-dense": 0.4}
        self._drive_losses(
            t, per, lambda v: 9.0 if v.numerics else 2.0)
        assert t.finished and t.result().name == "fp8-dense"
        assert not [d for d in t.decisions
                    if d["kind"] == "tuner-revert"]


# ------------------------------------------------------- metrics pump


class _FakeTrainer:
    """Just enough surface for _MetricsPump: consume returns the loss,
    optionally raising on demand."""

    def __init__(self):
        self.consumed = []
        self.boom = False

    def _consume_boundary(self, job):
        if self.boom:
            raise RuntimeError("boundary boom")
        self.consumed.append(job["step"])
        return float(job["metrics"]["loss"])


def _job(step, loss, pw=None):
    return {"step": step, "metrics": {"loss": loss}, "pw": pw}


class TestMetricsPump:
    def _pump(self, enabled=True):
        from dlrover_wuqiong_tpu.trainer.trainer import _MetricsPump

        tr = _FakeTrainer()
        return tr, _MetricsPump(tr, enabled=enabled)

    def test_async_drains_in_order(self):
        tr, pump = self._pump()
        try:
            for i in range(5):
                pump.submit(_job(i, float(i)))
        finally:
            pump.stop()
        assert tr.consumed == list(range(5))
        assert pump.last_loss() == 4.0
        assert pump.stats() == {"drained": 5, "errors": 0}

    def test_window_inflight_gates_next_open(self):
        tr, pump = self._pump()
        try:
            pump.submit(_job(0, 0.0, pw=object()))
            deadline = time.monotonic() + 10
            while pump.windows_inflight() and time.monotonic() < deadline:
                time.sleep(0.01)
            assert pump.windows_inflight() == 0
        finally:
            pump.stop()

    def test_consume_error_keeps_window_gate_closed(self):
        # a half-closed window may hold the profiler trace: the error
        # path deliberately leaves windows_inflight elevated (stuck gate
        # safe, nested trace not) and counts the error
        tr, pump = self._pump()
        tr.boom = True
        try:
            pump.submit(_job(0, 0.0, pw=object()))
        finally:
            pump.stop()
        assert pump.windows_inflight() == 1
        assert pump.stats() == {"drained": 0, "errors": 1}

    def test_inline_mode_propagates_exceptions(self):
        tr, pump = self._pump(enabled=False)
        tr.boom = True
        with pytest.raises(RuntimeError, match="boundary boom"):
            pump.submit(_job(0, 0.0))
        tr.boom = False
        pump.submit(_job(1, 2.5))
        assert pump.last_loss() == 2.5
        pump.stop()  # no-op without a thread

    def test_no_thread_leak_after_stop(self):
        _, pump = self._pump()
        pump.stop()
        assert not any(th.name == "dwt-metrics-pump" and th.is_alive()
                       for th in threading.enumerate())


# ---------------------------------------- zero-cold-compile cutover pin


_CUTOVER_WORKER = r"""
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
import dataclasses
import jax.numpy as jnp
import optax
from dlrover_wuqiong_tpu.auto.accelerate import auto_accelerate
from dlrover_wuqiong_tpu.auto.compile_cache import counters
from dlrover_wuqiong_tpu.auto.tuner import apply_variant, variant_env
from dlrover_wuqiong_tpu.models.gpt import GPT, GPTConfig

# flash attention + remat ON: the DWT_FA_*/DWT_REMAT_POLICY toggles
# change the emitted HLO, so the two variants are genuinely distinct
# executables; DWT_FP8_DENSE swaps the dense matmul kernel without
# touching the param tree, so one state serves both
cfg = dataclasses.replace(GPTConfig.nano(), dtype=jnp.float32,
                          use_flash_attention=True, remat=True)
res = auto_accelerate(GPT(cfg), optimizer=optax.adamw(3e-4),
                      strategy=[("fsdp", {})], devices=jax.devices(),
                      materialize=False)
bsh = res.batch_sharding_fn(2, None, 0)
ab = {"input_ids": jax.ShapeDtypeStruct((8, 32), jnp.int32, sharding=bsh),
      "labels": jax.ShapeDtypeStruct((8, 32), jnp.int32, sharding=bsh)}

# pre-warm both candidates (the warm pool does this out of process; the
# in-process fused cache re-keys on the env signature either way)
with variant_env({}):
    fn_a = res.fused_train_step(1)
    fn_a.lower(res.state, ab).compile()
winner_env = {"DWT_FA_NO_FUSED": "", "DWT_FA_PACK": "",
              "DWT_FA_STREAMED": "", "DWT_FP8_DENSE": "1",
              "DWT_REMAT_POLICY": "dots"}
with variant_env(winner_env):
    fn_b = res.fused_train_step(1)
    fn_b.lower(res.state, ab).compile()
prewarm_hits, prewarm_misses = counters.snapshot()

# cutover: adopt the winner for the rest of the process
apply_variant(winner_env)
fn_cut = res.fused_train_step(1)
fn_cut.lower(res.state, ab).compile()
h1, m1 = counters.snapshot()
print(json.dumps({
    "prewarm_misses": prewarm_misses,
    "cutover_misses": m1 - prewarm_misses,
    "cutover_hits": h1 - prewarm_hits,
    "fused_cache_hit": fn_cut is fn_b,
}))
"""


def test_winner_cutover_zero_cold_compiles(tmp_path):
    """Cutover to a pre-warmed winner pays NO cold compile: the fused
    cache answers the same jitted callable (env-signature key) and the
    XLA persistent cache serves the executable it compiled during
    pre-warm — miss counters stay flat across the cutover."""
    script = tmp_path / "cutover_worker.py"
    script.write_text(_CUTOVER_WORKER)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    for var in ("DWT_FA_NO_FUSED", "DWT_FA_PACK", "DWT_FA_STREAMED",
                "DWT_FP8_DENSE", "DWT_REMAT_POLICY"):
        env.pop(var, None)
    env["DWT_COMPILE_CACHE_DIR"] = str(tmp_path / "cache")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, str(script)], env=env, cwd=REPO,
        capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-3000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["prewarm_misses"] >= 1      # the candidates DID compile
    assert out["cutover_misses"] == 0      # ...and the cutover did not
    assert out["fused_cache_hit"] is True  # same jitted callable back

"""RLHF engine tests: KV-cache generation parity with the dense model,
GAE math, PPO loss, and an end-to-end reward-climbing mini-RLHF run.

Mirrors reference `atorch/tests/rl_tests/` in spirit.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_wuqiong_tpu.models.gpt import GPT, GPTConfig
from dlrover_wuqiong_tpu.rl import (
    ActorCritic,
    PPOConfig,
    PPOTrainer,
    SampleConfig,
    gae_advantages,
    generate,
)


def _cfg(**kw):
    return dataclasses.replace(
        GPTConfig(vocab_size=64, n_layer=2, n_head=2, n_embd=32,
                  block_size=64, dtype=jnp.float32,
                  use_flash_attention=False, remat=False), **kw)


class TestGeneration:
    def test_cached_forward_matches_dense_model(self):
        """Greedy decode with the KV cache must follow the dense model's
        argmax continuation exactly."""
        cfg = _cfg()
        model = GPT(cfg)
        params = model.init_params(jax.random.PRNGKey(0))
        prompt = jnp.array([[1, 2, 3, 4]], jnp.int32)
        toks, _ = generate(cfg, params, prompt, jax.random.PRNGKey(1),
                           SampleConfig(max_new_tokens=6,
                                        temperature=1e-6))  # ~greedy
        # dense-model greedy reference
        seq = prompt
        for _ in range(6):
            logits = model.apply({"params": params}, seq)
            nxt = jnp.argmax(logits[:, -1], -1)[:, None]
            seq = jnp.concatenate([seq, nxt.astype(seq.dtype)], axis=1)
        np.testing.assert_array_equal(np.asarray(toks), np.asarray(seq))

    def test_logprobs_match_sampled_tokens(self):
        cfg = _cfg()
        params = GPT(cfg).init_params(jax.random.PRNGKey(0))
        prompt = jnp.ones((2, 3), jnp.int32)
        toks, logps = generate(cfg, params, prompt, jax.random.PRNGKey(2),
                               SampleConfig(max_new_tokens=5))
        assert toks.shape == (2, 8)
        assert logps.shape == (2, 5)
        assert bool(jnp.all(logps <= 0))


class TestGAE:
    def test_terminal_only_reward(self):
        rewards = jnp.zeros((1, 4)).at[0, -1].set(1.0)
        values = jnp.zeros((1, 4))
        adv, ret = gae_advantages(rewards, values, gamma=1.0, lam=1.0)
        np.testing.assert_allclose(np.asarray(ret[0]), np.ones(4))

    def test_lambda_zero_is_td(self):
        rewards = jnp.array([[1.0, 0.0, 0.0]])
        values = jnp.array([[0.5, 0.2, 0.1]])
        adv, _ = gae_advantages(rewards, values, gamma=1.0, lam=0.0)
        expected = np.array([1.0 + 0.2 - 0.5, 0.1 - 0.2, -0.1])
        np.testing.assert_allclose(np.asarray(adv[0]), expected,
                                   atol=1e-6)


class TestPPOEndToEnd:
    def test_reward_increases(self):
        """Mini-RLHF: reward = fraction of TARGET tokens in the response.
        PPO must push the policy toward emitting TARGET."""
        TARGET = 7
        cfg = _cfg()

        def reward_fn(tokens, prompt_len):
            resp = tokens[:, prompt_len:]
            return (resp == TARGET).mean(axis=1).astype(np.float32) * 4.0

        trainer = PPOTrainer(cfg, PPOConfig(
            lr=1e-3, max_new_tokens=8, ppo_epochs=4, kl_coef=0.002),
            reward_fn, seed=0)
        prompts = jnp.ones((32, 4), jnp.int32)
        rewards = []
        for _ in range(12):
            out = trainer.step(prompts)
            rewards.append(out["reward"])
        early = np.mean(rewards[:3])
        late = np.mean(rewards[-3:])
        assert late > early + 0.5, rewards

    def test_actor_critic_shapes(self):
        cfg = _cfg()
        ac = ActorCritic(cfg)
        params = ac.init_params(jax.random.PRNGKey(0))
        logits, values = ac.apply({"params": params},
                                  jnp.ones((2, 6), jnp.int32))
        assert logits.shape == (2, 6, cfg.vocab_size)
        assert values.shape == (2, 6)
        # the trunk params live under "gpt" (generation reuses them as-is)
        assert "wte" in params["gpt"]

"""RLHF engine tests: KV-cache generation parity with the dense model,
GAE math, PPO loss, and an end-to-end reward-climbing mini-RLHF run.

Mirrors reference `atorch/tests/rl_tests/` in spirit.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from version_gates import shard_index_set

from dlrover_wuqiong_tpu.models.gpt import GPT, GPTConfig
from dlrover_wuqiong_tpu.rl import (
    ActorCritic,
    PPOConfig,
    PPOTrainer,
    SampleConfig,
    gae_advantages,
    generate,
)


def _cfg(**kw):
    return dataclasses.replace(
        GPTConfig(vocab_size=64, n_layer=2, n_head=2, n_embd=32,
                  block_size=64, dtype=jnp.float32,
                  use_flash_attention=False, remat=False), **kw)


class TestGeneration:
    def test_cached_forward_matches_dense_model(self):
        """Greedy decode with the KV cache must follow the dense model's
        argmax continuation exactly."""
        cfg = _cfg()
        model = GPT(cfg)
        params = model.init_params(jax.random.PRNGKey(0))
        prompt = jnp.array([[1, 2, 3, 4]], jnp.int32)
        toks, _ = generate(cfg, params, prompt, jax.random.PRNGKey(1),
                           SampleConfig(max_new_tokens=6,
                                        temperature=1e-6))  # ~greedy
        # dense-model greedy reference
        seq = prompt
        for _ in range(6):
            logits = model.apply({"params": params}, seq)
            nxt = jnp.argmax(logits[:, -1], -1)[:, None]
            seq = jnp.concatenate([seq, nxt.astype(seq.dtype)], axis=1)
        np.testing.assert_array_equal(np.asarray(toks), np.asarray(seq))

    def test_logprobs_match_sampled_tokens(self):
        cfg = _cfg()
        params = GPT(cfg).init_params(jax.random.PRNGKey(0))
        prompt = jnp.ones((2, 3), jnp.int32)
        toks, logps = generate(cfg, params, prompt, jax.random.PRNGKey(2),
                               SampleConfig(max_new_tokens=5))
        assert toks.shape == (2, 8)
        assert logps.shape == (2, 5)
        assert bool(jnp.all(logps <= 0))


class TestGAE:
    def test_terminal_only_reward(self):
        rewards = jnp.zeros((1, 4)).at[0, -1].set(1.0)
        values = jnp.zeros((1, 4))
        adv, ret = gae_advantages(rewards, values, gamma=1.0, lam=1.0)
        np.testing.assert_allclose(np.asarray(ret[0]), np.ones(4))

    def test_lambda_zero_is_td(self):
        rewards = jnp.array([[1.0, 0.0, 0.0]])
        values = jnp.array([[0.5, 0.2, 0.1]])
        adv, _ = gae_advantages(rewards, values, gamma=1.0, lam=0.0)
        expected = np.array([1.0 + 0.2 - 0.5, 0.1 - 0.2, -0.1])
        np.testing.assert_allclose(np.asarray(adv[0]), expected,
                                   atol=1e-6)


class TestPPOEndToEnd:
    def test_reward_increases(self):
        """Mini-RLHF: reward = fraction of TARGET tokens in the response.
        PPO must push the policy toward emitting TARGET."""
        TARGET = 7
        cfg = _cfg()

        def reward_fn(tokens, prompt_len):
            resp = tokens[:, prompt_len:]
            return (resp == TARGET).mean(axis=1).astype(np.float32) * 4.0

        trainer = PPOTrainer(cfg, PPOConfig(
            lr=1e-3, max_new_tokens=8, ppo_epochs=4, kl_coef=0.002),
            reward_fn, seed=0)
        prompts = jnp.ones((32, 4), jnp.int32)
        rewards = []
        for _ in range(12):
            out = trainer.step(prompts)
            rewards.append(out["reward"])
        early = np.mean(rewards[:3])
        late = np.mean(rewards[-3:])
        assert late > early + 0.5, rewards

    def test_actor_critic_shapes(self):
        cfg = _cfg()
        ac = ActorCritic(cfg)
        params = ac.init_params(jax.random.PRNGKey(0))
        logits, values = ac.apply({"params": params},
                                  jnp.ones((2, 6), jnp.int32))
        assert logits.shape == (2, 6, cfg.vocab_size)
        assert values.shape == (2, 6)
        # the trunk params live under "gpt" (generation reuses them as-is)
        assert "wte" in params["gpt"]


class TestHybridEngine:
    """Train/decode mesh separation (parity: reference
    ds_hybrid_engine/hybrid_engine.py): rollouts run on a tp-only decode
    placement fed by a timed weight sync; updates run on the train mesh."""

    def _trainer(self):
        cfg = _cfg()

        def reward_fn(tokens, prompt_len):
            resp = tokens[:, prompt_len:]
            return (resp == 7).mean(axis=1).astype(np.float32) * 4.0

        return PPOTrainer(cfg, PPOConfig(max_new_tokens=8, lr=1e-3,
                                         ppo_epochs=4, kl_coef=0.002),
                          reward_fn, seed=0, devices=jax.devices(),
                          decode_tp=2)

    def test_meshes_differ_and_placements_are_real(self):
        tr = self._trainer()
        assert tr.engine.train_mesh.shape["fsdp"] == 8
        assert tr.engine.decode_mesh.shape["tp"] == 2
        assert tr.engine.decode_mesh.shape["dp"] == 4
        # train placement: qkv kernel sharded over fsdp (8 shards)
        k_train = tr.params["gpt"]["h_0"]["attn"]["c_attn"]["kernel"]
        assert len(shard_index_set(k_train)) == 8
        # decode placement after sync: tp-only (2 distinct shards)
        dec = tr.engine.sync_to_decode(tr.params["gpt"])
        k_dec = dec["h_0"]["attn"]["c_attn"]["kernel"]
        assert len(shard_index_set(k_dec)) == 2
        assert tr.engine.last_sync_s > 0.0

    # tier-2: ~35s reward-improvement e2e; PPO learning is tier-1 via
    # TestPPOEndToEnd.test_reward_increases, mesh-hop weight sync via the
    # fast TestHybridEngine assertions above
    @pytest.mark.slow
    def test_ppo_e2e_across_meshes_improves_reward(self):
        tr = self._trainer()
        prompts = jnp.ones((32, 4), jnp.int32)
        first = tr.step(prompts)
        assert "weight_sync_s" in first and first["weight_sync_s"] > 0
        rewards = [first["reward"]]
        # 15 rounds: this container's jax/optax land the same trajectory
        # slightly slower than the version the 11-round horizon was tuned
        # on (the reward was climbing 0.06 -> 0.45 at round 12 and kept
        # going); the invariant under test is improvement, not speed
        for _ in range(15):
            rewards.append(tr.step(prompts)["reward"])
        assert np.mean(rewards[-3:]) > np.mean(rewards[:3]) + 0.5, rewards


class TestRewardModel:
    """Reward-model role (parity: reference model_engine reward_model/
    cost_model roles): Bradley-Terry preference training and the adapter
    into PPOTrainer's reward_fn."""

    def _pairs(self, rng, n, seq=12, vocab=64, good=7):
        """chosen contains the `good` token; rejected never does."""
        chosen = rng.integers(0, vocab, (n, seq)).astype(np.int32)
        chosen[np.arange(n), rng.integers(0, seq, n)] = good
        rejected = rng.integers(0, vocab, (n, seq)).astype(np.int32)
        rejected[rejected == good] = good + 1
        return chosen, rejected

    def test_learns_synthetic_preference(self):
        from dlrover_wuqiong_tpu.rl import RewardModel, RewardModelTrainer

        cfg = _cfg()
        tr = RewardModelTrainer(RewardModel(cfg), lr=3e-4, seed=0)
        rng = np.random.default_rng(0)
        acc = 0.0
        for _ in range(60):
            c, r = self._pairs(rng, 32)
            acc = tr.step(c, r)["pairwise_acc"]
        assert acc > 0.9, acc

    def test_adapter_feeds_ppo(self):
        from dlrover_wuqiong_tpu.rl import (
            RewardModel,
            RewardModelTrainer,
            as_reward_fn,
        )

        cfg = _cfg()
        tr = RewardModelTrainer(RewardModel(cfg), lr=3e-4, seed=0)
        rng = np.random.default_rng(1)
        for _ in range(40):
            c, r = self._pairs(rng, 32)
            tr.step(c, r)
        reward_fn = as_reward_fn(tr.model, tr.params)
        # scores preference-bearing sequences higher
        c, r = self._pairs(rng, 16)
        assert reward_fn(c, 4).mean() > reward_fn(r, 4).mean()
        # and plugs into the PPO loop end to end
        ppo = PPOTrainer(cfg, PPOConfig(max_new_tokens=8, ppo_epochs=1),
                         reward_fn, seed=0)
        out = ppo.step(jnp.ones((8, 4), jnp.int32))
        assert np.isfinite(out["loss"])

"""XPlane parsing → per-op-category latency tests (xpu_timer parity).

Real traces from jax.profiler on the CPU mesh, parsed by the stdlib wire
reader, cross-validated against the generated protobuf bindings when
available.
"""

import glob
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_wuqiong_tpu.utils.xplane import (
    OpProfile,
    categorize,
    parse_trace_dir,
    parse_xspace,
    summarize_planes,
)


@pytest.fixture(scope="module")
def traced_dir(tmp_path_factory):
    """One real profiler trace of a sharded matmul + collective."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    d = str(tmp_path_factory.mktemp("trace"))
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "tp"))
    x = jax.device_put(jnp.ones((256, 256)),
                       NamedSharding(mesh, P("dp", "tp")))
    w = jax.device_put(jnp.ones((256, 256)),
                       NamedSharding(mesh, P("tp", None)))

    @jax.jit
    def f(x, w):
        return jnp.tanh(x @ w).sum()

    f(x, w).block_until_ready()  # compile outside the window
    jax.profiler.start_trace(d)
    for _ in range(3):
        f(x, w).block_until_ready()
    jax.profiler.stop_trace()
    return d


class TestWireParser:
    def test_parses_real_trace(self, traced_dir):
        prof = parse_trace_dir(traced_dir)
        assert prof is not None
        assert prof.categories, "no op categories found"
        # the traced program has a dot and a cross-device reduction
        assert "matmul" in prof.categories
        assert "collective" in prof.categories
        assert all(s > 0 for s in prof.categories.values())
        names = [o.name for o in prof.ops]
        assert any("dot" in n for n in names)

    def test_matches_generated_protobuf(self, traced_dir):
        """Cross-validate the stdlib wire reader against the generated
        xplane_pb2 bindings (plane/line/event counts and durations)."""
        import importlib.util

        tf_spec = importlib.util.find_spec("tensorflow")
        pb2_path = None
        if tf_spec and tf_spec.submodule_search_locations:
            for base in tf_spec.submodule_search_locations:
                cand = os.path.join(base, "tsl", "profiler", "protobuf",
                                    "xplane_pb2.py")
                if os.path.exists(cand):
                    pb2_path = cand
                    break
        if pb2_path is None:
            pytest.skip("no generated xplane_pb2 available")
        spec = importlib.util.spec_from_file_location("xplane_pb2", pb2_path)
        pb2 = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(pb2)

        files = glob.glob(os.path.join(traced_dir, "plugins", "profile",
                                       "*", "*.xplane.pb"))
        assert files
        for path in files:
            ours = parse_xspace(path)
            theirs = pb2.XSpace()
            with open(path, "rb") as f:
                theirs.ParseFromString(f.read())
            assert len(ours) == len(theirs.planes)
            for op, tp in zip(ours, theirs.planes):
                assert op.name == tp.name
                assert len(op.lines) == len(tp.lines)
                assert sum(len(ln.events) for ln in op.lines) == \
                    sum(len(ln.events) for ln in tp.lines)
                our_dur = sum(e.duration_ps for ln in op.lines
                              for e in ln.events)
                their_dur = sum(e.duration_ps for ln in tp.lines
                                for e in ln.events)
                assert our_dur == their_dur


class TestCategorize:
    @pytest.mark.parametrize("name,cat", [
        ("all-reduce.1", "collective"),
        ("collective-permute.3", "collective"),
        ("reduce-scatter", "collective"),
        ("dot.17", "matmul"),
        ("wrapped_convolution", "matmul"),
        ("ragged-dot", "matmul"),
        ("copy-start.2", "transfer"),
        ("fusion.42", "fused"),
        ("Rendezvous", "sync"),
        ("Wait: pending_threads=3/4", None),  # ':' → host artifact
        ("add.3", "other"),
    ])
    def test_name_prefixes(self, name, cat):
        assert categorize(name) == cat

    def test_host_noise_is_dropped(self):
        assert categorize("PjitFunction(f)") is None
        assert categorize("$profiler.py:213 stop_trace") is None
        assert categorize("") is None

    def test_hlo_category_stat_wins(self):
        # TPU planes carry hlo_category stats; they beat name heuristics
        assert categorize("fusion.3", "convolution fusion") == "matmul"
        assert categorize("fusion.9", "all-reduce") == "collective"
        assert categorize("bitcast.1", "data formatting") == "transfer"


class TestStepProfilerIntegration:
    def test_window_publishes_categories_and_evidence(self, tmp_path):
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from dlrover_wuqiong_tpu.master.metrics import MetricRegistry
        from dlrover_wuqiong_tpu.utils.profiler import StepProfiler

        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "tp"))
        x = jax.device_put(jnp.ones((128, 128)),
                           NamedSharding(mesh, P("dp", "tp")))
        w = jax.device_put(jnp.ones((128, 128)),
                           NamedSharding(mesh, P("tp", None)))

        @jax.jit
        def f(x, w):
            return jnp.tanh(x @ w).sum()

        f(x, w).block_until_ready()
        reg = MetricRegistry()
        prof = StepProfiler(trace_dir=str(tmp_path), start_step=1,
                            end_step=2, registry=reg, job_name="t")
        for step in range(4):
            with prof.step(step):
                f(x, w).block_until_ready()
        assert prof.last_profile is not None
        rendered = reg.render()
        assert "dwt_op_category_seconds" in rendered
        assert 'category="matmul"' in rendered
        evidence = prof.last_profile.collective_evidence()
        assert evidence, "expected collective evidence"
        parsed = json.loads(evidence)
        assert parsed and {"op", "seconds", "count"} <= set(parsed[0])

    def test_diagnosis_evidence_includes_collectives(self):
        import time

        from dlrover_wuqiong_tpu.common import messages as msg
        from dlrover_wuqiong_tpu.diagnosis.manager import (
            CheckTrainingHangOperator,
            DiagnosisDataManager,
            InferenceChain,
            ResolveHangCauseOperator,
        )

        data = DiagnosisDataManager()
        old = time.time() - 3600  # graftlint: disable=wall-clock-duration -- forging node-reported wall timestamps (DiagnosisReport)
        data.store_report(msg.DiagnosisReport(
            node_id=0, payload_type="step", content="5", timestamp=old))
        data.store_report(msg.DiagnosisReport(
            node_id=0, payload_type="op_profile",
            content='[{"op": "all-reduce", "seconds": 1.5, "count": 3}]',
            timestamp=time.time() - 100))  # graftlint: disable=wall-clock-duration -- forging node-reported wall timestamps (DiagnosisReport)
        # stale evidence (older than max_age) is withheld
        assert data.node_op_profile(0, max_age=10) == ""
        chain = InferenceChain([CheckTrainingHangOperator(timeout=60),
                                ResolveHangCauseOperator()])
        conclusions = chain.run(data)
        culprits = [c for c in conclusions if c.name == "hang_culprit"]
        assert culprits
        assert "slowest collectives" in culprits[0].detail
        assert "all-reduce" in culprits[0].detail


class TestParserRobustness:
    def test_corrupt_pb_file_is_skipped(self, tmp_path):
        """A torn/foreign .xplane.pb must not kill the profile publish."""
        run = tmp_path / "plugins" / "profile" / "2026_01_01"
        run.mkdir(parents=True)
        (run / "host.xplane.pb").write_bytes(b"\xff\xfe\xfd garbage")
        assert parse_trace_dir(str(tmp_path)) is None

    def test_empty_trace_dir(self, tmp_path):
        assert parse_trace_dir(str(tmp_path)) is None

    def test_truncated_varint_rejected_cleanly(self, tmp_path):
        run = tmp_path / "plugins" / "profile" / "r"
        run.mkdir(parents=True)
        # field 1, wire type 2, length 100 but no payload → the reader's
        # bounds check raises ValueError (a silent short slice would
        # misparse the corrupt file as an empty plane), caught per-file
        # by parse_trace_dir
        (run / "h.xplane.pb").write_bytes(b"\x0a\x64")
        assert parse_trace_dir(str(tmp_path)) is None

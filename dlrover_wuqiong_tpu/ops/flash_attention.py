"""Flash attention for TPU: Pallas kernels (fwd + bwd) with online softmax.

Parity: reference flash-attn integrations — atorch
`modules/transformer/layers.py:1167` (`flash_attn_with_mask_bias`,
`FlashAttnModule` :1278) and tfplus FMHA ops
(`tfplus/tfplus/flash_attn/ops/flash_attention_ops.cc:8,39`).  Those wrap the
CUDA flash-attn library; here the kernels are written natively in Pallas
against the MXU/VMEM model (guide: /opt/skills/guides/pallas_guide.md).

Design (FA2 scheme, canonical Mosaic structure):
- the KV loop lives in the *grid* (innermost dim), not a fori_loop: Mosaic
  double-buffers the KV block HBM→VMEM copies against compute, and the
  q/o blocks stay resident in VMEM across the KV sweep.  Online-softmax
  state (m, l, acc) lives in VMEM scratch that persists across grid steps;
  `@pl.when` initializes it on the first KV step and finalizes o/lse on the
  last.
- causal masking is bottom-right aligned (a query at position i attends to
  keys k_idx <= i + (sk - sq)); fully-masked KV blocks skip compute via
  `@pl.when`.
- backward: two kernels — dq (grid: q outer, kv inner) and dk/dv (grid: kv
  outer, q inner) — each recomputing p = exp(s - lse) per tile IN
  TRANSPOSED SPACE (queries in lanes) so the (sq, sk) attention matrix
  never hits HBM and the per-row lse/delta broadcast without relayouts.
  delta = rowsum(dO ∘ O) is one fused XLA reduce into the row-major
  (bh, 1, sq) layout the kernels consume.
- head_dim runs natively when lane-aligned (d % 8 == 0, e.g. GPT-2's 64);
  otherwise it is zero-padded to the 128 boundary.  lse lives as (bh, sq)
  f32 everywhere — residuals, kernel outputs and inputs — with a cheap
  in-kernel (block_q, 1) <-> (block_q,) relayout instead of padded HBM
  traffic; the causal mask is one broadcast compare, not 2D iotas.
- on non-TPU backends a jnp reference path keeps tests runnable; the kernels
  themselves are additionally tested in interpret mode.
"""

from __future__ import annotations

import functools
import math
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from ..common.log import get_logger

logger = get_logger("flash_attention")

NEG_INF = -1e30  # avoids inf-inf NaNs while dominating any real score
LOG2E = 1.4426950408889634  # exp(x) == exp2(x * LOG2E); folding LOG2E
# into the q pre-scale turns every exp over the (block_q, block_k) score
# matrix into a bare exp2 — one VPU multiply pass saved per exp site
# (the hardware exponent unit is base-2; jnp.exp emits the mul per call)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:  # pragma: no cover
        return False


def _compiler_params(*semantics, vmem_limit: Optional[int] = None):
    if pltpu is None:  # pragma: no cover
        return None
    kw = {}
    if vmem_limit is not None:
        # the fused multi-head kernels hold q/k/v/o blocks for ALL heads
        # plus per-head f32 scratch: past the 16MB default scoped limit,
        # well inside v5e's 128MB physical VMEM
        kw["vmem_limit_bytes"] = vmem_limit
    # jax < 0.6 spells it TPUCompilerParams; same fields either way
    params_cls = getattr(pltpu, "CompilerParams", None) or \
        getattr(pltpu, "TPUCompilerParams")
    return params_cls(dimension_semantics=semantics, **kw)


def _dot(a, b):
    """a @ b with native-dtype (bf16) MXU multiply, f32 accumulation."""
    return jax.lax.dot(a, b, preferred_element_type=jnp.float32)


def _dot_t(a, b):
    """a @ b.T with native-dtype MXU multiply, f32 accumulation."""
    return jax.lax.dot_general(a, b, (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _causal_mask_block(qi, ki, block_q, block_k, kv_offset):
    # (block_q, 1) >= (1, block_k) broadcast: one VPU pass over the block,
    # vs two materialized 2D iotas + compare (3 extra full passes)
    q_idx = qi * block_q + kv_offset + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, 1), 0)
    k_idx = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (1, block_k), 1)
    return q_idx >= k_idx


# ------------------------------------------------------------- forward kernel


def _fa_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                   m_scr, l_scr, acc_scr, *,
                   num_kv: int, causal: bool, sm_scale: float,
                   block_q: int, block_k: int, kv_offset: int, pack: int):
    """Packed forward: refs carry `pack` heads in the leading dim.

    Leading-dim indexing (ref[hh]) is a free address offset (unlike lane
    slicing), so packing amortizes per-grid-step fixed costs and generates
    the causal mask once for all packed heads.
    """
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    single = num_kv == 1  # whole KV sweep in one step: no online state

    if causal:
        # block fully masked when its first key exceeds the last query's reach
        run = (qi + 1) * block_q + kv_offset > ki * block_k
    else:
        run = True

    if not single:
        @pl.when(ki == 0)
        def _init():
            m_scr[...] = jnp.full_like(m_scr, NEG_INF)
            l_scr[...] = jnp.zeros_like(l_scr)
            acc_scr[...] = jnp.zeros_like(acc_scr)
    elif causal and kv_offset < 0:
        # single-step path skips the init, but with sq > sk a q block can be
        # FULLY masked (run=False): _inner never writes the scratch while
        # _finalize still reads it — seed the empty-key values so it
        # finalizes to o=0, lse=-inf instead of stale VMEM
        @pl.when(jnp.logical_not(run))
        def _init_masked():
            m_scr[...] = jnp.full_like(m_scr, NEG_INF)
            l_scr[...] = jnp.zeros_like(l_scr)
            acc_scr[...] = jnp.zeros_like(acc_scr)

    def _inner(mask_block: bool):
        mask = (_causal_mask_block(qi, ki, block_q, block_k, kv_offset)
                if mask_block else None)
        for hh in range(pack):
            # pre-scale q (block_q x d) instead of s (block_q x block_k):
            # one fewer full VPU pass over the score matrix.  LOG2E folds
            # here too: s lives in log2 units, every exp below is a bare
            # exp2, and only the final lse converts back to natural log.
            q = (q_ref[hh].astype(jnp.float32)
                 * (sm_scale * LOG2E)).astype(q_ref.dtype)
            k = k_ref[hh]                              # (block_k, d)
            v = v_ref[hh]
            # bf16 MXU multiply, f32 accumulate — never cast operands up
            s = _dot_t(q, k)                           # (block_q, block_k)
            if mask_block:
                s = jnp.where(mask, s, NEG_INF)
            if single:
                m_new = s.max(axis=-1, keepdims=True)
                p = jnp.exp2(s - m_new)
                if mask_block and kv_offset < 0:
                    p = jnp.where(s <= NEG_INF, 0.0, p)
                m_scr[hh] = m_new
                l_scr[hh] = p.sum(axis=-1, keepdims=True)
                acc_scr[hh] = _dot(p.astype(v.dtype), v)
                continue
            m_prev = m_scr[hh]                         # (block_q, 1)
            m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
            p = jnp.exp2(s - m_new)
            if mask_block and kv_offset < 0:
                # rows can be fully masked only when sq > sk: exp(0)=1 junk
                p = jnp.where(s <= NEG_INF, 0.0, p)
            alpha = jnp.exp2(m_prev - m_new)
            m_scr[hh] = m_new
            l_scr[hh] = l_scr[hh] * alpha + p.sum(axis=-1, keepdims=True)
            acc_scr[hh] = acc_scr[hh] * alpha + _dot(p.astype(v.dtype), v)

    if causal:
        # only blocks straddling the diagonal pay for mask generation
        diag = (qi * block_q + kv_offset < (ki + 1) * block_k) & run

        @pl.when(diag)
        def _compute_masked():
            _inner(True)

        @pl.when(jnp.logical_not(diag) & run)
        def _compute_unmasked():
            _inner(False)
    else:

        @pl.when(run)
        def _compute():
            _inner(False)

    @pl.when(ki == num_kv - 1)
    def _finalize():
        for hh in range(pack):
            l = l_scr[hh]
            l_safe = jnp.where(l > 0, l, 1.0)
            o_ref[hh] = (acc_scr[hh] / l_safe).astype(o_ref.dtype)
            # empty key set → logsumexp = -inf (matches the jnp reference
            # path and long_context._merge_partials' isfinite handling).
            # m is in log2 units (LOG2E folded into the q pre-scale) —
            # convert back so the public lse stays natural-log.
            lse = jnp.where(l > 0, m_scr[hh] * (1.0 / LOG2E)
                            + jnp.log(l_safe), -jnp.inf)
            # lse lives as (bh, 1, sq) in HBM — a (…, sq, 1) f32 array pads
            # its minor dim 128x in the tiled layout (~150MB of padding
            # traffic per call at the bench shape); with sq in lanes the
            # padding is 8x of a tiny array, and the (block_q, 1) ->
            # (1, block_q) relayout happens once per q block in VMEM
            lse_ref[hh] = lse.T


def _fit_pack(bh: int) -> int:
    """Heads packed per grid step: largest of 8/4/2/1 dividing bh.

    DWT_FA_PACK overrides the preference order's head (sweep hook).  The
    override is clamped to 8: kernel VMEM scratch scales linearly with
    pack against the fixed 100MB vmem_limit, and an oversized value would
    fail at Mosaic compile time with an opaque error (ADVICE r4)."""
    import os

    try:
        pref = int(os.getenv("DWT_FA_PACK", "8"))
    except ValueError:  # empty/garbage env value: fall back, don't abort
        pref = 8
    if pref > 8:
        logger.warning("DWT_FA_PACK=%d exceeds the VMEM-safe maximum of 8 "
                       "— clamping", pref)
        pref = 8
    for p in (pref, 8, 4, 2):
        if p >= 1 and bh % p == 0:
            return p
    return 1


def _fa_forward_pallas(q, k, v, causal: bool, sm_scale: float,
                       block_q: int, block_k: int, interpret: bool):
    """q: (bh, sq, d), k/v: (bh, sk, d) → (o, lse (bh, 1, sq) f32)."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    num_kv = sk // block_k
    pack = _fit_pack(bh)
    grid = (bh // pack, sq // block_q, num_kv)

    kernel = functools.partial(
        _fa_fwd_kernel, num_kv=num_kv, causal=causal, sm_scale=sm_scale,
        block_q=block_q, block_k=block_k, kv_offset=sk - sq, pack=pack)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((pack, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((pack, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((pack, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=(
            pl.BlockSpec((pack, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((pack, 1, block_q), lambda b, i, j: (b, 0, i)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, sq), jnp.float32),
        ),
        scratch_shapes=[
            pltpu.VMEM((pack, block_q, 1), jnp.float32),
            pltpu.VMEM((pack, block_q, 1), jnp.float32),
            pltpu.VMEM((pack, block_q, d), jnp.float32),
        ] if pltpu is not None else [],
        compiler_params=_compiler_params("parallel", "parallel", "arbitrary",
                                         vmem_limit=100 * 1024 * 1024),
        interpret=interpret,
    )(q, k, v)
    return o, lse


# ------------------------------------------------------------ backward kernels


def _causal_mask_block_t(qi, ki, block_q, block_k, kv_offset):
    """Transposed-space causal mask: (block_k, block_q), queries in lanes."""
    q_idx = qi * block_q + kv_offset + jax.lax.broadcasted_iota(
        jnp.int32, (1, block_q), 1)
    k_idx = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_k, 1), 0)
    return q_idx >= k_idx


def _dot_c0(a, b):
    """Contract dim 0 of both: (K, M) x (K, N) -> (M, N), f32 accumulate."""
    return jax.lax.dot_general(a, b, (((0,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _p_transposed(q, k, lse, mask, sm_scale):
    """Recompute p^T = exp(s^T - lse) as (block_k, block_q).

    Both backward kernels work in transposed space — scores with queries in
    LANES — so the per-row lse/delta arrive as native (1, block_q) row
    vectors and broadcast straight across sublanes.  The row-major layout
    (bh, 1, sq) costs no 128x lane padding in HBM and no per-grid-step
    sublane<->lane relayouts in VMEM (measured ~1.5ms/call at the bench
    shape for the (block_q, 1) variant).  It also removes the full
    (block_q, block_k) p.T / ds.T transposes the dkv kernel otherwise pays:
    dv = dot(p^T, do) and dk = dot(ds^T, q) contract directly.
    """
    qs = (q.astype(jnp.float32) * (sm_scale * LOG2E)).astype(q.dtype)
    sT = _dot_t(k, qs)                          # (block_k, block_q)
    if mask is not None:
        sT = jnp.where(mask, sT, NEG_INF)
    # lse = -inf marks a fully-masked row: its p must be 0, not
    # exp(s + inf) = nan.  sT is in log2 units (LOG2E folded into the q
    # pre-scale, a (block_q, d) array 16x smaller than the score matrix);
    # the natural-log lse converts on its (1, block_q) row, so the only
    # score-matrix-sized transcendental is a bare exp2.
    finite = jnp.isfinite(lse)
    return jnp.where(
        finite, jnp.exp2(sT - jnp.where(finite, lse * LOG2E, 0.0)), 0.0)


def _fa_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, dq_scr, *, num_kv: int, causal: bool,
                      sm_scale: float, block_q: int, block_k: int,
                      kv_offset: int, pack: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    if causal:
        run = (qi + 1) * block_q + kv_offset > ki * block_k
    else:
        run = True

    def _inner(mask_block: bool):
        mask = (_causal_mask_block_t(qi, ki, block_q, block_k, kv_offset)
                if mask_block else None)
        for hh in range(pack):
            k = k_ref[hh]
            pT = _p_transposed(q_ref[hh], k, lse_ref[hh], mask, sm_scale)
            dpT = _dot_t(v_ref[hh], do_ref[hh])    # (block_k, block_q)
            dsT = (pT * (dpT - delta_ref[hh]) * sm_scale).astype(k.dtype)
            dq_scr[hh] += _dot_c0(dsT, k)          # (block_q, d)

    if causal:
        diag = (qi * block_q + kv_offset < (ki + 1) * block_k) & run

        @pl.when(diag)
        def _compute_masked():
            _inner(True)

        @pl.when(jnp.logical_not(diag) & run)
        def _compute_unmasked():
            _inner(False)
    else:

        @pl.when(run)
        def _compute():
            _inner(False)

    @pl.when(ki == num_kv - 1)
    def _finalize():
        for hh in range(pack):
            dq_ref[hh] = dq_scr[hh].astype(dq_ref.dtype)


def _fa_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                       dk_ref, dv_ref, dk_scr, dv_scr, *, num_q: int,
                       causal: bool, sm_scale: float, block_q: int,
                       block_k: int, kv_offset: int, pack: int):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    if causal:
        run = (qi + 1) * block_q + kv_offset > ki * block_k
    else:
        run = True

    def _inner(mask_block: bool):
        mask = (_causal_mask_block_t(qi, ki, block_q, block_k, kv_offset)
                if mask_block else None)
        for hh in range(pack):
            q = q_ref[hh]
            do = do_ref[hh]
            pT = _p_transposed(q, k_ref[hh], lse_ref[hh], mask,
                               sm_scale).astype(q.dtype)
            dv_scr[hh] += _dot(pT, do)             # (block_k, d)
            dpT = _dot_t(v_ref[hh], do)
            dsT = (pT.astype(jnp.float32)
                   * (dpT - delta_ref[hh]) * sm_scale).astype(q.dtype)
            dk_scr[hh] += _dot(dsT, q)             # (block_k, d)

    if causal:
        diag = (qi * block_q + kv_offset < (ki + 1) * block_k) & run

        @pl.when(diag)
        def _compute_masked():
            _inner(True)

        @pl.when(jnp.logical_not(diag) & run)
        def _compute_unmasked():
            _inner(False)
    else:

        @pl.when(run)
        def _compute():
            _inner(False)

    @pl.when(qi == num_q - 1)
    def _finalize():
        for hh in range(pack):
            dk_ref[hh] = dk_scr[hh].astype(dk_ref.dtype)
            dv_ref[hh] = dv_scr[hh].astype(dv_ref.dtype)


def _fa_bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, dk_ref, dv_ref, *, causal: bool,
                         sm_scale: float, block_q: int, block_k: int,
                         kv_offset: int, pack: int):
    """Single-block fused backward: dq, dk AND dv in one pass.

    Only legal when the whole sequence fits one block each way (num_q ==
    num_kv == 1) — the general case cannot fuse because dq accumulates
    over the kv grid axis while dk/dv accumulate over the q axis, and a
    Pallas TPU output block only stays resident across CONSECUTIVE grid
    steps (the reason the split kernels exist).  At the 1k-context bench
    shape this saves 2 of the split path's 7 dots (the second S and dP
    recomputes) and one full exp pass over the score matrix.
    """
    mask = (_causal_mask_block_t(0, 0, block_q, block_k, kv_offset)
            if causal else None)
    for hh in range(pack):
        q = q_ref[hh]
        k = k_ref[hh]
        do = do_ref[hh]
        pT = _p_transposed(q, k, lse_ref[hh], mask, sm_scale)  # (bk, bq)
        pTb = pT.astype(q.dtype)
        dv_ref[hh] = _dot(pTb, do).astype(dv_ref.dtype)        # (bk, d)
        dpT = _dot_t(v_ref[hh], do)                            # (bk, bq)
        dsT = (pT * (dpT - delta_ref[hh]) * sm_scale).astype(q.dtype)
        dk_ref[hh] = _dot(dsT, q).astype(dk_ref.dtype)         # (bk, d)
        dq_ref[hh] = _dot_c0(dsT, k).astype(dq_ref.dtype)      # (bq, d)


def _fa_backward_pallas(q, k, v, o, lse, do, causal: bool, sm_scale: float,
                        block_q: int, block_k: int, interpret: bool,
                        glse=None):
    """All operands flat (bh, s, d); lse (bh, 1, sq) f32. Returns dq, dk, dv.

    The kernels recompute p in TRANSPOSED space (queries in lanes) so the
    per-row lse/delta broadcast natively — see `_p_transposed`.  delta and
    the optional lse cotangent `glse` (bh, 1, sq) fold together outside
    (d lse / d s = p, so ds = p * (dp - delta + glse))."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    kv_offset = sk - sq
    num_q = sq // block_q
    num_kv = sk // block_k
    pack = _fit_pack(bh)

    # delta = rowsum(dO ∘ O) — cheap fused reduce; (bh, 1, sq) row-major
    # layout avoids the 128x lane padding a (bh, sq, 1) array would pay
    delta = (do.astype(jnp.float32) * o.astype(jnp.float32)).sum(
        -1)[:, None, :]
    if glse is not None:
        delta = delta - glse

    qspec = pl.BlockSpec((pack, block_q, d), lambda b, i, j: (b, i, 0))
    kspec = pl.BlockSpec((pack, block_k, d), lambda b, i, j: (b, j, 0))
    rowspec = pl.BlockSpec((pack, 1, block_q), lambda b, i, j: (b, 0, i))
    ops = [q, k, v, do, lse, delta]

    if num_q == 1 and num_kv == 1 and not os.getenv("DWT_FA_NO_FUSED"):
        bspec_q = pl.BlockSpec((pack, block_q, d), lambda b: (b, 0, 0))
        bspec_k = pl.BlockSpec((pack, block_k, d), lambda b: (b, 0, 0))
        bspec_row = pl.BlockSpec((pack, 1, block_q), lambda b: (b, 0, 0))
        return pl.pallas_call(
            functools.partial(
                _fa_bwd_fused_kernel, causal=causal, sm_scale=sm_scale,
                block_q=block_q, block_k=block_k, kv_offset=kv_offset,
                pack=pack),
            grid=(bh // pack,),
            in_specs=[bspec_q, bspec_k, bspec_k, bspec_q, bspec_row,
                      bspec_row],
            out_specs=(bspec_q, bspec_k, bspec_k),
            out_shape=(
                jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
                jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
                jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
            ),
            compiler_params=_compiler_params(
                "parallel", vmem_limit=100 * 1024 * 1024),
            interpret=interpret,
        )(*ops)

    dq = pl.pallas_call(
        functools.partial(_fa_bwd_dq_kernel, num_kv=num_kv, causal=causal,
                          sm_scale=sm_scale, block_q=block_q,
                          block_k=block_k, kv_offset=kv_offset, pack=pack),
        grid=(bh // pack, num_q, num_kv),
        in_specs=[qspec, kspec, kspec, qspec, rowspec, rowspec],
        out_specs=pl.BlockSpec((pack, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((pack, block_q, d), jnp.float32)]
        if pltpu is not None else [],
        compiler_params=_compiler_params("parallel", "parallel", "arbitrary",
                                         vmem_limit=100 * 1024 * 1024),
        interpret=interpret,
    )(*ops)

    # dkv grid: kv outer, q inner — same operands, transposed index maps
    qspec_t = pl.BlockSpec((pack, block_q, d), lambda b, j, i: (b, i, 0))
    kspec_t = pl.BlockSpec((pack, block_k, d), lambda b, j, i: (b, j, 0))
    rowspec_t = pl.BlockSpec((pack, 1, block_q), lambda b, j, i: (b, 0, i))

    dk, dv = pl.pallas_call(
        functools.partial(_fa_bwd_dkv_kernel, num_q=num_q, causal=causal,
                          sm_scale=sm_scale, block_q=block_q,
                          block_k=block_k, kv_offset=kv_offset, pack=pack),
        grid=(bh // pack, num_kv, num_q),
        in_specs=[qspec_t, kspec_t, kspec_t, qspec_t, rowspec_t, rowspec_t],
        out_specs=(
            pl.BlockSpec((pack, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((pack, block_k, d), lambda b, j, i: (b, j, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ),
        scratch_shapes=[
            pltpu.VMEM((pack, block_k, d), jnp.float32),
            pltpu.VMEM((pack, block_k, d), jnp.float32),
        ] if pltpu is not None else [],
        compiler_params=_compiler_params("parallel", "parallel", "arbitrary",
                                         vmem_limit=100 * 1024 * 1024),
        interpret=interpret,
    )(*ops)
    return dq, dk, dv


# ----------------------------------------------------------------- reference


def _attention_reference(q, k, v, causal: bool, sm_scale: float):
    """Plain jnp attention — numerics oracle + non-TPU fallback.

    q: (b, h, sq, d); k/v: (b, h, sk, d)
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * sm_scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


# ---------------------------------------------------------------- public API


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def flash_attention(q, k, v, causal: bool = True,
                    sm_scale: Optional[float] = None,
                    block_q: int = 1024, block_k: int = 1024,
                    bwd_block_q: int = 0, bwd_block_k: int = 0):
    """Multi-head attention, FA2-style.

    Args: q (b, h, sq, d); k, v (b, h, sk, d).  Returns (b, h, sq, d).
    `bwd_block_q`/`bwd_block_k` tile the dq/dkv backward kernels
    independently (0 = inherit block_q/block_k — swept best at the bench
    shape, README table).
    """
    out, _ = _fa_fwd(q, k, v, causal, sm_scale, block_q, block_k,
                     bwd_block_q, bwd_block_k)
    return out


def _resolve_scale(sm_scale, d):
    return sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)


def _fit_block(seq: int, pref: int) -> Optional[int]:
    """Largest block ≤ pref that tiles `seq`; None if nothing reasonable.

    Falls back through the standard tile sizes so e.g. seq=640 still rides
    the kernel with block 128 instead of silently hitting the dense path.
    A block equal to the whole (modest) sequence is always legal — Mosaic
    accepts blocks equal to the array dimension.
    """
    for b in (pref, 1024, 512, 256, 128, 64, 32, 16, 8):
        if b <= pref and b <= seq and seq % b == 0:
            return b
    return seq if seq <= 2048 else None


def _use_pallas(sq, sk, d, block_q, block_k) -> bool:
    if not _on_tpu():
        return False
    # head_dim runs natively (lane-aligned) or zero-padded, so any d
    # qualifies; sequences need a workable tile size
    return (_fit_block(sq, block_q) is not None
            and _fit_block(sk, block_k) is not None)


def _use_streamed(sq, sk) -> bool:
    """Blockwise-scan fallback instead of the dense O(sq*sk) reference.

    Only consulted when the Pallas kernels are unavailable (non-TPU
    backend).  The dense fallback materializes full f32 score matrices —
    fine for small test shapes, but it misrepresents the TPU program's
    memory on big shapes: the 8B AOT fit proof (tests/test_scale_8b.py)
    compiles on a virtual CPU mesh, where dense attention would dominate
    `memory_analysis()` with buffers the Pallas path never allocates.
    DWT_FA_STREAMED=1/0 forces the choice; the default switches at the
    point where a per-head score matrix reaches 2048^2 (16MB f32)."""
    env = os.getenv("DWT_FA_STREAMED")
    if env is not None:
        return env == "1"
    return sq * sk >= 2048 * 2048


def _kernel_head_dim(d: int) -> int:
    """Head dim as seen by the kernels.

    Mosaic accepts any block whose last dim equals the array's, so lane-
    aligned head dims (multiples of 8) run natively — d=64 (GPT-2) included,
    avoiding pad copies.  Odd dims are zero-padded to the 128-lane boundary
    (padded q/k columns add 0 to scores; padded v columns are sliced off).
    """
    return d if d % 8 == 0 else max(128, -(-d // 128) * 128)


def _pad_head_dim(x, d_pad):
    d = x.shape[-1]
    if d == d_pad:
        return x
    return jnp.pad(x, ((0, 0), (0, 0), (0, d_pad - d)))


def _flat_padded(q, k, v, d_pad):
    b, h, sq, d = q.shape
    qf = _pad_head_dim(q.reshape(b * h, sq, d), d_pad)
    kf = _pad_head_dim(k.reshape(b * h, k.shape[2], d), d_pad)
    vf = _pad_head_dim(v.reshape(b * h, v.shape[2], d), d_pad)
    return qf, kf, vf


def _fa_fwd_lse(q, k, v, causal, sm_scale, block_q, block_k):
    """Shared forward: returns ((out, lse_bhs), residuals)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    scale = _resolve_scale(sm_scale, d)
    if _use_pallas(sq, sk, d, block_q, block_k):
        bq = _fit_block(sq, block_q)
        bk = _fit_block(sk, block_k)
        d_pad = _kernel_head_dim(d)
        qf, kf, vf = _flat_padded(q, k, v, d_pad)
        o, lse = _fa_forward_pallas(qf, kf, vf, causal, scale, bq, bk,
                                    interpret=False)
        out = o[:, :, :d].reshape(b, h, sq, d)
        return (out, lse.reshape(b, h, sq)), (q, k, v, o, lse)
    if _use_streamed(sq, sk):
        out, lse = _streamed_with_lse(q, k, v, causal, scale, block_k)
        return (out, lse), (q, k, v, out, lse)
    out, lse = _reference_with_lse(q, k, v, causal, scale)
    return (out, lse), (q, k, v, out, None)


def _reference_with_lse(q, k, v, causal, scale):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, -jnp.inf)
    m = jnp.max(s, axis=-1, keepdims=True)
    m = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - m)
    l = p.sum(-1, keepdims=True)
    l_safe = jnp.where(l > 0, l, 1.0)
    lse = jnp.where(l[..., 0] > 0, (m + jnp.log(l_safe))[..., 0], -jnp.inf)
    o = jnp.einsum("bhqk,bhkd->bhqd", (p / l_safe).astype(v.dtype), v)
    return o, lse


def _streamed_with_lse(q, k, v, causal, scale, block_k):
    """Online-softmax forward as a `lax.scan` over key blocks.

    Same math as the Pallas kernel, in plain jnp: peak temps are
    O(h * sq * block_k) instead of the dense path's O(h * sq * sk) — the
    memory-faithful any-backend stand-in for the kernel (used by the 8B
    AOT fit proof on the virtual CPU mesh)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bk = _fit_block(sk, min(block_k, 512)) or sk
    nb = sk // bk
    q32 = q.astype(jnp.float32)
    kb = jnp.moveaxis(k.reshape(b, h, nb, bk, d), 2, 0)
    vb = jnp.moveaxis(v.reshape(b, h, nb, bk, d), 2, 0)
    rows = jnp.arange(sq) + (sk - sq)  # absolute key index each row sees

    def body(carry, inp):
        acc, m, l = carry
        j, kblk, vblk = inp
        s = jnp.einsum("bhqd,bhkd->bhqk", q32,
                       kblk.astype(jnp.float32)) * scale
        mask = None
        if causal:
            cols = j * bk + jnp.arange(bk)
            mask = rows[:, None] >= cols[None, :]
            s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        if mask is not None:
            # a fully-masked row has m_new == NEG_INF and exp(s - m_new)
            # == 1 for its masked entries — zero them so l stays 0 and
            # the l>0 guard below yields out=0 / lse=-inf (matching the
            # dense reference; sq > sk rows exercise this)
            p = jnp.where(mask, p, 0.0)
        l = l * alpha + p.sum(-1)
        acc = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vblk.astype(jnp.float32))
        return (acc, m_new, l), None

    init = (jnp.zeros((b, h, sq, d), jnp.float32),
            jnp.full((b, h, sq), NEG_INF, jnp.float32),
            jnp.zeros((b, h, sq), jnp.float32))
    (acc, m, l), _ = jax.lax.scan(body, init, (jnp.arange(nb), kb, vb))
    l_safe = jnp.where(l > 0, l, 1.0)
    out = (acc / l_safe[..., None]).astype(q.dtype)
    lse = jnp.where(l > 0, m + jnp.log(l_safe), -jnp.inf)
    return out, lse


def _streamed_bwd(q, k, v, out, lse, g, causal, scale, block_q, glse):
    """Flash-style recompute backward as one `lax.scan` over query blocks.

    Each step re-derives p for its q block from the stored lse, emits the
    block's dq, and accumulates dk/dv — peak temps O(h * block_q * sk)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq = _fit_block(sq, min(block_q, 512)) or sq
    nb = sq // bq
    q32, k32, v32 = (t.astype(jnp.float32) for t in (q, k, v))
    g32 = g.astype(jnp.float32)
    delta = (g32 * out.astype(jnp.float32)).sum(-1)  # (b, h, sq)
    if glse is not None:
        delta = delta - glse
    lse_safe = jnp.where(jnp.isfinite(lse), lse, 0.0)
    qb = jnp.moveaxis(q32.reshape(b, h, nb, bq, d), 2, 0)
    gb = jnp.moveaxis(g32.reshape(b, h, nb, bq, d), 2, 0)
    lb = jnp.moveaxis(lse_safe.reshape(b, h, nb, bq), 2, 0)
    db = jnp.moveaxis(delta.reshape(b, h, nb, bq), 2, 0)
    cols = jnp.arange(sk)
    off = sk - sq

    def body(carry, inp):
        dk, dv = carry
        i, qblk, gblk, lseblk, dblk = inp
        s = jnp.einsum("bhqd,bhkd->bhqk", qblk, k32) * scale
        p = jnp.exp(s - lseblk[..., None])
        if causal:
            rows = i * bq + jnp.arange(bq) + off
            p = jnp.where(rows[:, None] >= cols[None, :], p, 0.0)
        dp = jnp.einsum("bhqd,bhkd->bhqk", gblk, v32)
        ds = p * (dp - dblk[..., None])
        dqblk = jnp.einsum("bhqk,bhkd->bhqd", ds, k32) * scale
        dk = dk + jnp.einsum("bhqk,bhqd->bhkd", ds, qblk) * scale
        dv = dv + jnp.einsum("bhqk,bhqd->bhkd", p, gblk)
        return (dk, dv), dqblk

    init = (jnp.zeros((b, h, sk, d), jnp.float32),
            jnp.zeros((b, h, sk, d), jnp.float32))
    (dk, dv), dqb = jax.lax.scan(
        body, init, (jnp.arange(nb), qb, gb, lb, db))
    dq = jnp.moveaxis(dqb, 0, 2).reshape(b, h, sq, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _fa_bwd_impl(causal, sm_scale, block_q, block_k, res, g, glse):
    """Shared backward; glse (b, h, sq) f32 or None folds the lse cotangent
    into delta (d lse / d s = p, so ds = p * (dp - delta + glse))."""
    q, k, v, out, lse = res
    b, h, sq, d = q.shape
    sk = k.shape[2]
    scale = _resolve_scale(sm_scale, d)
    if lse is not None and not _use_pallas(sq, sk, d, block_q, block_k):
        # streamed forward ran (lse present, kernels unavailable): its
        # recompute backward — NOT the dense path, which would undo the
        # memory bound the streamed path exists for
        return _streamed_bwd(q, k, v, out, lse, g, causal, scale,
                             block_q, glse)
    if lse is not None:  # pallas forward ran: pallas backward
        bq = _fit_block(sq, block_q)
        bk = _fit_block(sk, block_k)
        d_pad = _kernel_head_dim(d)
        qf, kf, vf = _flat_padded(q, k, v, d_pad)
        gf = _pad_head_dim(g.reshape(b * h, sq, d), d_pad)
        glse_f = None if glse is None else glse.reshape(b * h, 1, sq)
        dq, dk, dv = _fa_backward_pallas(qf, kf, vf, out, lse,
                                         gf, causal, scale, bq, bk,
                                         interpret=False, glse=glse_f)
        return (dq[:, :, :d].reshape(b, h, sq, d).astype(q.dtype),
                dk[:, :, :d].reshape(b, h, sk, d).astype(k.dtype),
                dv[:, :, :d].reshape(b, h, sk, d).astype(v.dtype))
    # jnp recompute fallback (matches _attention_reference numerics)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    g32 = g.astype(jnp.float32)
    v32 = v.astype(jnp.float32)
    dp = jnp.einsum("bhqd,bhkd->bhqk", g32, v32)
    delta = (g32 * out.astype(jnp.float32)).sum(-1, keepdims=True)
    if glse is not None:
        delta = delta - glse[..., None]
    ds = p * (dp - delta)
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, k.astype(jnp.float32)) * scale
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q.astype(jnp.float32)) * scale
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, g32)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _fa_fwd(q, k, v, causal, sm_scale, block_q, block_k,
            bwd_block_q=0, bwd_block_k=0):
    (out, _), res = _fa_fwd_lse(q, k, v, causal, sm_scale, block_q, block_k)
    return out, res


def _fa_bwd(causal, sm_scale, block_q, block_k, bwd_block_q, bwd_block_k,
            res, g):
    return _fa_bwd_impl(causal, sm_scale, bwd_block_q or block_q,
                        bwd_block_k or block_k, res, g, None)


flash_attention.defvjp(_fa_fwd, _fa_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def flash_attention_with_lse(q, k, v, causal: bool = True,
                             sm_scale: Optional[float] = None,
                             block_q: int = 1024, block_k: int = 1024,
                             bwd_block_q: int = 0, bwd_block_k: int = 0):
    """Like `flash_attention` but also returns lse (b, h, sq) f32 — the
    building block for ring/blockwise attention where partial results over
    disjoint key sets merge by logsumexp weights.  Differentiable in both
    outputs (the lse cotangent folds into the delta term)."""
    (out, lse), _ = _fa_fwd_lse(q, k, v, causal, sm_scale, block_q, block_k)
    return out, lse


def _fa_lse_fwd(q, k, v, causal, sm_scale, block_q, block_k,
                bwd_block_q=0, bwd_block_k=0):
    return _fa_fwd_lse(q, k, v, causal, sm_scale, block_q, block_k)


def _fa_lse_bwd(causal, sm_scale, block_q, block_k, bwd_block_q,
                bwd_block_k, res, gs):
    g, glse = gs
    return _fa_bwd_impl(causal, sm_scale, bwd_block_q or block_q,
                        bwd_block_k or block_k, res, g,
                        glse.astype(jnp.float32))


flash_attention_with_lse.defvjp(_fa_lse_fwd, _fa_lse_bwd)


def mha(q, k, v, causal: bool = True, sm_scale: Optional[float] = None):
    """Convenience wrapper accepting (b, s, h, d) layout (flax convention).

    The transposes to (b, h, s, d) cost ~1ms/layer at the bench shape; a
    fused kernel taking (b, s, h*d) directly was built and measured SLOWER
    (lane slices at non-128 offsets relayout per head: ~7.2ms vs 5.6ms
    fwd+bwd), so the transpose + flat-kernel route stays.
    """
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out = flash_attention(qt, kt, vt, causal, sm_scale)
    return out.transpose(0, 2, 1, 3)

"""Flash attention for TPU: Pallas kernels (fwd + bwd) with online softmax.

Parity: reference flash-attn integrations — atorch
`modules/transformer/layers.py:1167` (`flash_attn_with_mask_bias`,
`FlashAttnModule` :1278) and tfplus FMHA ops
(`tfplus/tfplus/flash_attn/ops/flash_attention_ops.cc:8,39`).  Those wrap the
CUDA flash-attn library; here the kernels are written natively in Pallas
against the MXU/VMEM model (guide: /opt/skills/guides/pallas_guide.md).

Design (FA2 scheme, canonical Mosaic structure):
- the KV loop lives in the *grid* (innermost dim), not a fori_loop: Mosaic
  double-buffers the KV block HBM→VMEM copies against compute, and the
  q/o blocks stay resident in VMEM across the KV sweep.  Online-softmax
  state (m, l, acc) lives in VMEM scratch that persists across grid steps;
  `@pl.when` initializes it on the first KV step and finalizes o/lse on the
  last.
- causal masking is bottom-right aligned (a query at position i attends to
  keys k_idx <= i + (sk - sq)); fully-masked KV blocks skip compute via
  `@pl.when`.
- backward: two kernels — dq (grid: q outer, kv inner) and dk/dv (grid: kv
  outer, q inner) — each recomputing p = exp(s - lse) per tile so the
  (sq, sk) attention matrix never hits HBM.  delta = rowsum(dO ∘ O) is a
  cheap fused jnp reduction outside the kernels.
- head_dim runs natively when lane-aligned (d % 8 == 0, e.g. GPT-2's 64);
  otherwise it is zero-padded to the 128 boundary.  lse is carried as
  (bh, sq) compactly in residuals and fed to kernels as (bh, sq, 1).
- on non-TPU backends a jnp reference path keeps tests runnable; the kernels
  themselves are additionally tested in interpret mode.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

NEG_INF = -1e30  # avoids inf-inf NaNs while dominating any real score


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:  # pragma: no cover
        return False


def _compiler_params(*semantics):
    if pltpu is None:  # pragma: no cover
        return None
    return pltpu.CompilerParams(dimension_semantics=semantics)


def _dot(a, b):
    """a @ b with native-dtype (bf16) MXU multiply, f32 accumulation."""
    return jax.lax.dot(a, b, preferred_element_type=jnp.float32)


def _dot_t(a, b):
    """a @ b.T with native-dtype MXU multiply, f32 accumulation."""
    return jax.lax.dot_general(a, b, (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _causal_mask_block(qi, ki, block_q, block_k, kv_offset):
    q_idx = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_idx = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return q_idx + kv_offset >= k_idx


# ------------------------------------------------------------- forward kernel


def _fa_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                   m_scr, l_scr, acc_scr, *,
                   num_kv: int, causal: bool, sm_scale: float,
                   block_q: int, block_k: int, kv_offset: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    if causal:
        # block fully masked when its first key exceeds the last query's reach
        run = (qi + 1) * block_q + kv_offset > ki * block_k
    else:
        run = True

    def _inner(mask_block: bool):
        # pre-scale q (block_q x d) instead of s (block_q x block_k): one
        # fewer full VPU pass over the score matrix
        q = (q_ref[...].astype(jnp.float32) * sm_scale).astype(q_ref.dtype)
        k = k_ref[...]                                 # (block_k, d)
        v = v_ref[...]
        # bf16 MXU multiply, f32 accumulate — never cast operands up first
        s = _dot_t(q, k)                               # (block_q, block_k)
        if mask_block:
            s = jnp.where(
                _causal_mask_block(qi, ki, block_q, block_k, kv_offset),
                s, NEG_INF)
        m_prev = m_scr[...]                            # (block_q, 1)
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if mask_block and kv_offset < 0:
            # rows can be fully masked only when sq > sk: exp(0)=1 junk
            p = jnp.where(s <= NEG_INF, 0.0, p)
        alpha = jnp.exp(m_prev - m_new)
        m_scr[...] = m_new
        l_scr[...] = l_scr[...] * alpha + p.sum(axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + _dot(p.astype(v.dtype), v)

    if causal:
        # only blocks straddling the diagonal pay for mask generation
        diag = (qi * block_q + kv_offset < (ki + 1) * block_k) & run

        @pl.when(diag)
        def _compute_masked():
            _inner(True)

        @pl.when(jnp.logical_not(diag) & run)
        def _compute_unmasked():
            _inner(False)
    else:

        @pl.when(run)
        def _compute():
            _inner(False)

    @pl.when(ki == num_kv - 1)
    def _finalize():
        l = l_scr[...]
        l_safe = jnp.where(l > 0, l, 1.0)
        o_ref[...] = (acc_scr[...] / l_safe).astype(o_ref.dtype)
        # empty key set → logsumexp = -inf (matches the jnp reference path
        # and long_context._merge_partials' isfinite handling)
        lse = jnp.where(l > 0, m_scr[...] + jnp.log(l_safe), -jnp.inf)
        lse_ref[...] = lse


def _fa_forward_pallas(q, k, v, causal: bool, sm_scale: float,
                       block_q: int, block_k: int, interpret: bool):
    """q: (bh, sq, d), k/v: (bh, sk, d) → (o, lse (bh, sq, 1) f32)."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    num_kv = sk // block_k
    grid = (bh, sq // block_q, num_kv)

    kernel = functools.partial(
        _fa_fwd_kernel, num_kv=num_kv, causal=causal, sm_scale=sm_scale,
        block_q=block_q, block_k=block_k, kv_offset=sk - sq)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=(
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i, j: (b, i, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ] if pltpu is not None else [],
        compiler_params=_compiler_params("parallel", "parallel", "arbitrary"),
        interpret=interpret,
    )(q, k, v)
    return o, lse


# ------------------------------------------------------------ backward kernels


def _fa_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, dq_scr, *, num_kv: int, causal: bool,
                      sm_scale: float, block_q: int, block_k: int,
                      kv_offset: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    if causal:
        run = (qi + 1) * block_q + kv_offset > ki * block_k
    else:
        run = True

    def _inner(mask_block: bool):
        q = (q_ref[...].astype(jnp.float32) * sm_scale).astype(q_ref.dtype)
        k = k_ref[...]
        v = v_ref[...]
        do = do_ref[...]
        lse = lse_ref[...]                      # (block_q, 1)
        delta = delta_ref[...]                  # (block_q, 1)
        s = _dot_t(q, k)
        if mask_block:
            s = jnp.where(
                _causal_mask_block(qi, ki, block_q, block_k, kv_offset),
                s, NEG_INF)
        # lse = -inf marks a fully-masked row: its p must be 0, not
        # exp(s + inf) = nan
        finite = jnp.isfinite(lse)
        p = jnp.where(finite, jnp.exp(s - jnp.where(finite, lse, 0.0)), 0.0)
        dp = _dot_t(do, v)
        ds = (p * (dp - delta) * sm_scale).astype(q.dtype)
        dq_scr[...] += _dot(ds, k)

    if causal:
        diag = (qi * block_q + kv_offset < (ki + 1) * block_k) & run

        @pl.when(diag)
        def _compute_masked():
            _inner(True)

        @pl.when(jnp.logical_not(diag) & run)
        def _compute_unmasked():
            _inner(False)
    else:

        @pl.when(run)
        def _compute():
            _inner(False)

    @pl.when(ki == num_kv - 1)
    def _finalize():
        dq_ref[...] = dq_scr[...].astype(dq_ref.dtype)


def _fa_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                       dk_ref, dv_ref, dk_scr, dv_scr, *, num_q: int,
                       causal: bool, sm_scale: float, block_q: int,
                       block_k: int, kv_offset: int):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    if causal:
        run = (qi + 1) * block_q + kv_offset > ki * block_k
    else:
        run = True

    def _inner(mask_block: bool):
        qs = (q_ref[...].astype(jnp.float32) * sm_scale).astype(q_ref.dtype)
        q = q_ref[...]
        k = k_ref[...]
        v = v_ref[...]
        do = do_ref[...]
        lse = lse_ref[...]
        delta = delta_ref[...]
        s = _dot_t(qs, k)                       # (block_q, block_k)
        if mask_block:
            s = jnp.where(
                _causal_mask_block(qi, ki, block_q, block_k, kv_offset),
                s, NEG_INF)
        finite = jnp.isfinite(lse)
        p = jnp.where(finite, jnp.exp(s - jnp.where(finite, lse, 0.0)),
                      0.0).astype(q.dtype)
        dv_scr[...] += _dot(p.T, do)
        dp = _dot_t(do, v)
        ds = (p.astype(jnp.float32) * (dp - delta) * sm_scale).astype(q.dtype)
        dk_scr[...] += _dot(ds.T, q)

    if causal:
        diag = (qi * block_q + kv_offset < (ki + 1) * block_k) & run

        @pl.when(diag)
        def _compute_masked():
            _inner(True)

        @pl.when(jnp.logical_not(diag) & run)
        def _compute_unmasked():
            _inner(False)
    else:

        @pl.when(run)
        def _compute():
            _inner(False)

    @pl.when(qi == num_q - 1)
    def _finalize():
        dk_ref[...] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_scr[...].astype(dv_ref.dtype)


def _fa_backward_pallas(q, k, v, o, lse, do, causal: bool, sm_scale: float,
                        block_q: int, block_k: int, interpret: bool,
                        glse=None):
    """All operands flat (bh, s, d); lse (bh, sq, 1). Returns dq, dk, dv.

    `glse` (bh, sq, 1): optional cotangent of the lse output — since
    d lse / d s = p, it folds into delta (ds = p * (dp - delta + glse))."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    kv_offset = sk - sq
    num_q = sq // block_q
    num_kv = sk // block_k

    # delta = rowsum(dO ∘ O) — cheap elementwise reduce, XLA fuses it
    delta = (do.astype(jnp.float32) * o.astype(jnp.float32)).sum(
        -1, keepdims=True)  # (bh, sq, 1)
    if glse is not None:
        delta = delta - glse

    dq = pl.pallas_call(
        functools.partial(_fa_bwd_dq_kernel, num_kv=num_kv, causal=causal,
                          sm_scale=sm_scale, block_q=block_q,
                          block_k=block_k, kv_offset=kv_offset),
        grid=(bh, num_q, num_kv),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)]
        if pltpu is not None else [],
        compiler_params=_compiler_params("parallel", "parallel", "arbitrary"),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_fa_bwd_dkv_kernel, num_q=num_q, causal=causal,
                          sm_scale=sm_scale, block_q=block_q,
                          block_k=block_k, kv_offset=kv_offset),
        grid=(bh, num_kv, num_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((None, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=(
            pl.BlockSpec((None, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, j, i: (b, j, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ] if pltpu is not None else [],
        compiler_params=_compiler_params("parallel", "parallel", "arbitrary"),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ----------------------------------------------------------------- reference


def _attention_reference(q, k, v, causal: bool, sm_scale: float):
    """Plain jnp attention — numerics oracle + non-TPU fallback.

    q: (b, h, sq, d); k/v: (b, h, sk, d)
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * sm_scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


# ---------------------------------------------------------------- public API


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def flash_attention(q, k, v, causal: bool = True,
                    sm_scale: Optional[float] = None,
                    block_q: int = 1024, block_k: int = 1024,
                    bwd_block_q: int = 0, bwd_block_k: int = 0):
    """Multi-head attention, FA2-style.

    Args: q (b, h, sq, d); k, v (b, h, sk, d).  Returns (b, h, sq, d).
    `bwd_block_q`/`bwd_block_k` tile the dq/dkv backward kernels
    independently (0 = inherit block_q/block_k — swept best at the bench
    shape, README table).
    """
    out, _ = _fa_fwd(q, k, v, causal, sm_scale, block_q, block_k,
                     bwd_block_q, bwd_block_k)
    return out


def _resolve_scale(sm_scale, d):
    return sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)


def _fit_block(seq: int, pref: int) -> Optional[int]:
    """Largest block ≤ pref that tiles `seq`; None if nothing reasonable.

    Falls back through the standard tile sizes so e.g. seq=640 still rides
    the kernel with block 128 instead of silently hitting the dense path.
    A block equal to the whole (modest) sequence is always legal — Mosaic
    accepts blocks equal to the array dimension.
    """
    for b in (pref, 1024, 512, 256, 128, 64, 32, 16, 8):
        if b <= pref and b <= seq and seq % b == 0:
            return b
    return seq if seq <= 2048 else None


def _use_pallas(sq, sk, d, block_q, block_k) -> bool:
    if not _on_tpu():
        return False
    # head_dim runs natively (lane-aligned) or zero-padded, so any d
    # qualifies; sequences need a workable tile size
    return (_fit_block(sq, block_q) is not None
            and _fit_block(sk, block_k) is not None)


def _kernel_head_dim(d: int) -> int:
    """Head dim as seen by the kernels.

    Mosaic accepts any block whose last dim equals the array's, so lane-
    aligned head dims (multiples of 8) run natively — d=64 (GPT-2) included,
    avoiding pad copies.  Odd dims are zero-padded to the 128-lane boundary
    (padded q/k columns add 0 to scores; padded v columns are sliced off).
    """
    return d if d % 8 == 0 else max(128, -(-d // 128) * 128)


def _pad_head_dim(x, d_pad):
    d = x.shape[-1]
    if d == d_pad:
        return x
    return jnp.pad(x, ((0, 0), (0, 0), (0, d_pad - d)))


def _flat_padded(q, k, v, d_pad):
    b, h, sq, d = q.shape
    qf = _pad_head_dim(q.reshape(b * h, sq, d), d_pad)
    kf = _pad_head_dim(k.reshape(b * h, k.shape[2], d), d_pad)
    vf = _pad_head_dim(v.reshape(b * h, v.shape[2], d), d_pad)
    return qf, kf, vf


def _fa_fwd_lse(q, k, v, causal, sm_scale, block_q, block_k):
    """Shared forward: returns ((out, lse_bhs), residuals)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    scale = _resolve_scale(sm_scale, d)
    if _use_pallas(sq, sk, d, block_q, block_k):
        bq = _fit_block(sq, block_q)
        bk = _fit_block(sk, block_k)
        d_pad = _kernel_head_dim(d)
        qf, kf, vf = _flat_padded(q, k, v, d_pad)
        o, lse = _fa_forward_pallas(qf, kf, vf, causal, scale, bq, bk,
                                    interpret=False)
        out = o[:, :, :d].reshape(b, h, sq, d)
        # keep residuals compact: lse (bh, sq, 1) has a 128x-padded layout
        lse_c = lse[..., 0]
        return (out, lse_c.reshape(b, h, sq)), (q, k, v, o, lse_c)
    out, lse = _reference_with_lse(q, k, v, causal, scale)
    return (out, lse), (q, k, v, out, None)


def _reference_with_lse(q, k, v, causal, scale):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, -jnp.inf)
    m = jnp.max(s, axis=-1, keepdims=True)
    m = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - m)
    l = p.sum(-1, keepdims=True)
    l_safe = jnp.where(l > 0, l, 1.0)
    lse = jnp.where(l[..., 0] > 0, (m + jnp.log(l_safe))[..., 0], -jnp.inf)
    o = jnp.einsum("bhqk,bhkd->bhqd", (p / l_safe).astype(v.dtype), v)
    return o, lse


def _fa_bwd_impl(causal, sm_scale, block_q, block_k, res, g, glse):
    """Shared backward; glse (b, h, sq) f32 or None folds the lse cotangent
    into delta (d lse / d s = p, so ds = p * (dp - delta + glse))."""
    q, k, v, out, lse = res
    b, h, sq, d = q.shape
    sk = k.shape[2]
    scale = _resolve_scale(sm_scale, d)
    if lse is not None:  # pallas forward ran: pallas backward
        bq = _fit_block(sq, block_q)
        bk = _fit_block(sk, block_k)
        d_pad = _kernel_head_dim(d)
        qf, kf, vf = _flat_padded(q, k, v, d_pad)
        gf = _pad_head_dim(g.reshape(b * h, sq, d), d_pad)
        glse_f = None if glse is None else glse.reshape(b * h, sq, 1)
        dq, dk, dv = _fa_backward_pallas(qf, kf, vf, out, lse[..., None],
                                         gf, causal, scale, bq, bk,
                                         interpret=False, glse=glse_f)
        return (dq[:, :, :d].reshape(b, h, sq, d).astype(q.dtype),
                dk[:, :, :d].reshape(b, h, sk, d).astype(k.dtype),
                dv[:, :, :d].reshape(b, h, sk, d).astype(v.dtype))
    # jnp recompute fallback (matches _attention_reference numerics)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    g32 = g.astype(jnp.float32)
    v32 = v.astype(jnp.float32)
    dp = jnp.einsum("bhqd,bhkd->bhqk", g32, v32)
    delta = (g32 * out.astype(jnp.float32)).sum(-1, keepdims=True)
    if glse is not None:
        delta = delta - glse[..., None]
    ds = p * (dp - delta)
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, k.astype(jnp.float32)) * scale
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q.astype(jnp.float32)) * scale
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, g32)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _fa_fwd(q, k, v, causal, sm_scale, block_q, block_k,
            bwd_block_q=0, bwd_block_k=0):
    (out, _), res = _fa_fwd_lse(q, k, v, causal, sm_scale, block_q, block_k)
    return out, res


def _fa_bwd(causal, sm_scale, block_q, block_k, bwd_block_q, bwd_block_k,
            res, g):
    return _fa_bwd_impl(causal, sm_scale, bwd_block_q or block_q,
                        bwd_block_k or block_k, res, g, None)


flash_attention.defvjp(_fa_fwd, _fa_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def flash_attention_with_lse(q, k, v, causal: bool = True,
                             sm_scale: Optional[float] = None,
                             block_q: int = 1024, block_k: int = 1024,
                             bwd_block_q: int = 0, bwd_block_k: int = 0):
    """Like `flash_attention` but also returns lse (b, h, sq) f32 — the
    building block for ring/blockwise attention where partial results over
    disjoint key sets merge by logsumexp weights.  Differentiable in both
    outputs (the lse cotangent folds into the delta term)."""
    (out, lse), _ = _fa_fwd_lse(q, k, v, causal, sm_scale, block_q, block_k)
    return out, lse


def _fa_lse_fwd(q, k, v, causal, sm_scale, block_q, block_k,
                bwd_block_q=0, bwd_block_k=0):
    return _fa_fwd_lse(q, k, v, causal, sm_scale, block_q, block_k)


def _fa_lse_bwd(causal, sm_scale, block_q, block_k, bwd_block_q,
                bwd_block_k, res, gs):
    g, glse = gs
    return _fa_bwd_impl(causal, sm_scale, bwd_block_q or block_q,
                        bwd_block_k or block_k, res, g,
                        glse.astype(jnp.float32))


flash_attention_with_lse.defvjp(_fa_lse_fwd, _fa_lse_bwd)


def mha(q, k, v, causal: bool = True, sm_scale: Optional[float] = None):
    """Convenience wrapper accepting (b, s, h, d) layout (flax convention)."""
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out = flash_attention(qt, kt, vt, causal, sm_scale)
    return out.transpose(0, 2, 1, 3)

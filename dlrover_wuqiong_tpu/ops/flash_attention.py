"""Flash attention for TPU: Pallas kernel with online softmax + custom VJP.

Parity: reference flash-attn integrations — atorch
`modules/transformer/layers.py:1167` (`flash_attn_with_mask_bias`,
`FlashAttnModule` :1278) and tfplus FMHA ops
(`tfplus/tfplus/flash_attn/ops/flash_attention_ops.cc:8,39`).  Those wrap the
CUDA flash-attn library; here the kernel is written natively in Pallas against
the MXU/VMEM model (guide: /opt/skills/guides/pallas_guide.md).

Design: block-tiled over (batch*heads, q_blocks); inner loop over KV blocks
with running max/denominator (online softmax).  Causal masking prunes
fully-masked KV blocks via the grid.  Backward recomputes attention per block
(memory-lean, standard FA2 scheme).  On non-TPU backends a jnp reference path
keeps tests runnable; numerics match to bf16 tolerance.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:  # pragma: no cover
        return False


# --------------------------------------------------------------------- kernel


def _fa_fwd_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, *,
                   block_k: int, seq_k: int, causal: bool, sm_scale: float,
                   block_q: int):
    qi = pl.program_id(1)
    q = q_ref[...].astype(jnp.float32) * sm_scale  # (block_q, d)

    m = jnp.full((block_q,), -jnp.inf, jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)
    acc = jnp.zeros_like(q)

    num_k_blocks = seq_k // block_k
    if causal:
        # highest kv block this q block attends to
        max_kb = ((qi + 1) * block_q + block_k - 1) // block_k
        num_iters = jnp.minimum(num_k_blocks, max_kb)
    else:
        num_iters = num_k_blocks

    def body(kb, carry):
        m, l, acc = carry
        k = k_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = q @ k.T  # (block_q, block_k)
        if causal:
            q_idx = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_idx = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_idx >= k_idx, s, -jnp.inf)
        m_new = jnp.maximum(m, s.max(axis=-1))
        # guard fully-masked rows
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - m_safe[:, None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
        l_new = l * alpha + p.sum(axis=-1)
        acc_new = acc * alpha[:, None] + p @ v
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, num_iters, body, (m, l, acc))
    l_safe = jnp.where(l > 0, l, 1.0)
    o_ref[...] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    m_ref[...] = m
    l_ref[...] = l


def _fa_forward_pallas(q, k, v, causal: bool, sm_scale: float,
                       block_q: int, block_k: int, interpret: bool):
    """q: (bh, sq, d), k/v: (bh, sk, d) → (o, m, l)"""
    bh, sq, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    grid = (bh, sq // block_q)

    kernel = functools.partial(
        _fa_fwd_kernel, block_k=block_k, seq_k=sk, causal=causal,
        sm_scale=sm_scale, block_q=block_q)
    out_shapes = (
        jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        jax.ShapeDtypeStruct((bh, sq), jnp.float32),
        jax.ShapeDtypeStruct((bh, sq), jnp.float32),
    )
    o, m, l = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, sk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, sk, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q), lambda b, i: (b, i)),
            pl.BlockSpec((None, block_q), lambda b, i: (b, i)),
        ),
        out_shape=out_shapes,
        interpret=interpret,
    )(q, k, v)
    return o, m, l


# ----------------------------------------------------------------- reference


def _attention_reference(q, k, v, causal: bool, sm_scale: float):
    """Plain jnp attention — numerics oracle + non-TPU fallback.

    q: (b, h, sq, d); k/v: (b, h, sk, d)
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * sm_scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


# ---------------------------------------------------------------- public API


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = True,
                    sm_scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128):
    """Multi-head attention, FA2-style.

    Args: q (b, h, sq, d); k, v (b, h, sk, d).  Returns (b, h, sq, d).
    """
    out, _ = _fa_fwd(q, k, v, causal, sm_scale, block_q, block_k)
    return out


def _resolve_scale(sm_scale, d):
    return sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)


def _use_pallas(sq, sk, d, block_q, block_k) -> bool:
    if not _on_tpu():
        return False
    # pallas path needs tile-able shapes
    return (sq % min(block_q, sq) == 0 and sk % min(block_k, sk) == 0
            and d % 128 == 0)


def _fa_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    b, h, sq, d = q.shape
    scale = _resolve_scale(sm_scale, d)
    if _use_pallas(sq, k.shape[2], d, block_q, block_k):
        qf = q.reshape(b * h, sq, d)
        kf = k.reshape(b * h, k.shape[2], d)
        vf = v.reshape(b * h, v.shape[2], d)
        o, m, l = _fa_forward_pallas(qf, kf, vf, causal, scale, block_q,
                                     block_k, interpret=False)
        out = o.reshape(b, h, sq, d)
        return out, (q, k, v, out, m.reshape(b, h, sq), l.reshape(b, h, sq))
    out = _attention_reference(q, k, v, causal, scale)
    return out, (q, k, v, out, None, None)


def _fa_bwd(causal, sm_scale, block_q, block_k, res, g):
    q, k, v, out, m, l = res
    b, h, sq, d = q.shape
    scale = _resolve_scale(sm_scale, d)
    # recompute-based backward (XLA fuses this well; a fully hand-written
    # pallas bwd kernel is a later optimization)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        sk = s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    g32 = g.astype(jnp.float32)
    v32 = v.astype(jnp.float32)
    dp = jnp.einsum("bhqd,bhkd->bhqk", g32, v32)
    delta = (g32 * out.astype(jnp.float32)).sum(-1, keepdims=True)
    ds = p * (dp - delta)
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, k.astype(jnp.float32)) * scale
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q.astype(jnp.float32)) * scale
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, g32)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention.defvjp(_fa_fwd, _fa_bwd)


def mha(q, k, v, causal: bool = True, sm_scale: Optional[float] = None):
    """Convenience wrapper accepting (b, s, h, d) layout (flax convention)."""
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out = flash_attention(qt, kt, vt, causal, sm_scale)
    return out.transpose(0, 2, 1, 3)

"""Selective activation checkpointing + host offload policies.

Parity: reference
`atorch/atorch/auto/opt_lib/selective_offloading_checkpoint.py:1-252`
(OffloadOpManager moving selected saved tensors to CPU) and
`atorch/atorch/modules/distributed_modules/activation_checkpointing.py:1-366`
(module-granular checkpoint wrapping).

TPU redesign: XLA already gives first-class hooks for both halves —
`jax.checkpoint` policies decide per-primitive what is SAVED vs RECOMPUTED,
and offload variants move the saved residuals to host memory
(`pinned_host` memory kind) instead of holding HBM.  The policy is a
config string resolved here, applied by the model's `nn.remat` wrapper, and
selected through `auto_accelerate`'s ("checkpoint", {...}) strategy:

    ("checkpoint", {})                          # full remat (recompute all)
    ("checkpoint", {"policy": "dots"})          # save matmul outputs in HBM
    ("checkpoint", {"policy": "offload_dots"})  # matmul outputs -> host
    ("checkpoint", {"policy": "save_names", "names": ["attn_out"]})
    ("checkpoint", {"policy": "offload_names", "names": ["attn_out"]})

The named policies key on `checkpoint_name` annotations the models place on
their attention/MLP block outputs ("attn_out", "mlp_out").
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax

#: annotation names the in-tree models emit (models/gpt.py Block)
MODEL_CHECKPOINT_NAMES = ("attn_out", "mlp_out")


def trace_remat_policy(default: Optional[str]) -> Optional[str]:
    """Trace-time remat-policy override (DWT_REMAT_POLICY, TRACE_ENV_VARS).

    Unset/"" defers to the config policy; any other value replaces it,
    validated by `resolve_remat_policy` (unknown names raise at trace
    time, before any step runs).  The models read this inside their
    `nn.remat` wrapping, so the value changes the emitted HLO and rides
    every framework cache key (auto/compile_cache.py) — the variant
    autotuner searches the policy ladder as warm-pooled cutovers without
    a model rebuild.  Remat is numerically neutral (same math, different
    save/recompute split), so unlike DWT_FP8_DENSE this axis needs no
    numerics opt-in.  Only the tuner's sanctioned writers flip it
    (graftlint env-flip-outside-tuner).
    """
    return os.environ.get("DWT_REMAT_POLICY", "") or default


def resolve_remat_policy(policy: Optional[str],
                         names: Sequence[str] = MODEL_CHECKPOINT_NAMES):
    """Map a config string to a jax.checkpoint policy callable.

    Returns None for "full" — `jax.checkpoint` with no policy saves nothing
    and recomputes everything, the classic full-remat behavior.
    """
    if policy in (None, "", "full"):
        return None
    cp = jax.checkpoint_policies
    if policy == "dots":
        # save matmul outputs on device, recompute elementwise — the
        # standard "selective" policy: most recompute FLOPs are avoided
        # while activations shrink to the dot outputs
        return cp.dots_with_no_batch_dims_saveable
    if policy == "offload_dots":
        return cp.offload_dot_with_no_batch_dims("device", "pinned_host")
    if policy == "save_names":
        return cp.save_only_these_names(*names)
    if policy == "offload_names":
        return cp.save_and_offload_only_these_names(
            names_which_can_be_saved=[],
            names_which_can_be_offloaded=list(names),
            offload_src="device", offload_dst="pinned_host")
    raise ValueError(
        f"unknown remat policy {policy!r}; expected one of "
        "'full', 'dots', 'offload_dots', 'save_names', 'offload_names'")

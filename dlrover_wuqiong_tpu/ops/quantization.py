"""Quantization ops: blockwise int8 kernels + scaled FP8 matmul.

Parity: reference `atorch/atorch/ops/csrc/` CUDA suite (`quantize.cu`,
`dequantize.cu`, `swizzled_quantize.cu`, `quant_reduce.cu`) and the fp8
module filter (`auto/opt_lib/amp_optimization.py:197` Fp8Optimization via
TransformerEngine).

TPU redesign:
- int8: blockwise absmax quantize/dequantize as Pallas kernels (VPU
  elementwise + per-block reduction in VMEM) with a jnp fallback that XLA
  fuses; used by the low-bit optimizer states.
- fp8: e4m3/e5m2 live natively in XLA (ml_dtypes).  `fp8_dot` runs a
  scaled matmul: per-tensor dynamic scaling into fp8, dot with f32
  accumulation, rescale.  On hardware without fp8 MXU paths XLA upcasts —
  numerics (the fp8 rounding) are preserved either way, which is the
  property training cares about.
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pl = None
    pltpu = None

BLOCK = 256


def fp8_dense_override() -> Optional[bool]:
    """Trace-time fp8-dense toggle (DWT_FP8_DENSE — a TRACE_ENV_VARS name).

    "1" forces the name-filtered dense projections onto the fp8 matmul
    path, "0" forces them off, unset/"" defers to the model config's
    `fp8` flag.  Read at TRACE time inside the model's `dense()` factory
    (models/fp8.py), so the value is part of the emitted HLO and rides
    every framework cache key (auto/compile_cache.py TRACE_ENV_VARS) —
    which is what lets the variant autotuner A/B fp8 against bf16 as a
    warm-pooled cutover instead of a model rebuild.  Only the tuner's
    sanctioned writers may flip it (graftlint env-flip-outside-tuner);
    fp8 changes the loss trajectory, so the trainer gates this axis
    behind the explicit `tune_numerics` opt-in plus a loss-divergence
    guard.
    """
    value = os.environ.get("DWT_FP8_DENSE", "")
    if value == "":
        return None
    return value == "1"


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # noqa: BLE001
        return False


# ------------------------------------------------------------- int8 blockwise


def _quant_kernel(x_ref, q_ref, s_ref):
    x = x_ref[...].astype(jnp.float32)         # (rows, BLOCK)
    absmax = jnp.abs(x).max(axis=-1, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(x / scale), -127, 127)
    q_ref[...] = q.astype(jnp.int8)
    s_ref[...] = scale


def _dequant_kernel(q_ref, s_ref, x_ref):
    x_ref[...] = q_ref[...].astype(jnp.float32) * s_ref[...]


def quantize_int8_blockwise(x: jax.Array, block: int = BLOCK
                            ) -> Tuple[jax.Array, jax.Array]:
    """x (any shape) → (int8 (n_blocks, block), f32 scales (n_blocks, 1)).

    Flat blockwise absmax: the layout the low-bit optimizer stores.
    Pallas on TPU, fused jnp elsewhere.
    """
    flat = x.reshape(-1)
    pad = (-flat.size) % block
    if pad:
        flat = jnp.pad(flat, (0, pad))
    rows = flat.size // block
    tiled = flat.reshape(rows, block)
    if _on_tpu() and pl is not None and rows % 8 == 0:
        grid = (rows // 8,)
        q, s = pl.pallas_call(
            _quant_kernel,
            grid=grid,
            in_specs=[pl.BlockSpec((8, block), lambda i: (i, 0))],
            out_specs=(pl.BlockSpec((8, block), lambda i: (i, 0)),
                       pl.BlockSpec((8, 1), lambda i: (i, 0))),
            out_shape=(jax.ShapeDtypeStruct((rows, block), jnp.int8),
                       jax.ShapeDtypeStruct((rows, 1), jnp.float32)),
        )(tiled)
        return q, s
    xf = tiled.astype(jnp.float32)
    absmax = jnp.abs(xf).max(axis=-1, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    return q, scale


def dequantize_int8_blockwise(q: jax.Array, scale: jax.Array,
                              size: int, shape: Tuple[int, ...],
                              dtype=jnp.float32) -> jax.Array:
    """Inverse of quantize_int8_blockwise."""
    rows, block = q.shape
    if _on_tpu() and pl is not None and rows % 8 == 0:
        x = pl.pallas_call(
            _dequant_kernel,
            grid=(rows // 8,),
            in_specs=[pl.BlockSpec((8, block), lambda i: (i, 0)),
                      pl.BlockSpec((8, 1), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((8, block), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((rows, block), jnp.float32),
        )(q, scale)
    else:
        x = q.astype(jnp.float32) * scale
    return x.reshape(-1)[:size].reshape(shape).astype(dtype)


# ------------------------------------------------------------------- fp8


E4M3 = jnp.float8_e4m3fn
E5M2 = jnp.float8_e5m2

_FP8_MAX = {E4M3: 448.0, E5M2: 57344.0}


def fp8_quantize(x: jax.Array, dtype=E4M3,
                 scale: Optional[jax.Array] = None
                 ) -> Tuple[jax.Array, jax.Array]:
    """Dynamic per-tensor scaling into fp8; returns (fp8 x, f32 scale).

    scale maps the tensor's amax onto the format's max representable —
    te-style current scaling (amax history is the caller's policy).
    """
    if scale is None:
        amax = jnp.abs(x).max().astype(jnp.float32)
        scale = jnp.where(amax > 0, _FP8_MAX[dtype] / amax, 1.0)
    q = (x.astype(jnp.float32) * scale).astype(dtype)
    return q, scale


def fp8_dequantize(q: jax.Array, scale: jax.Array,
                   dtype=jnp.float32) -> jax.Array:
    return (q.astype(jnp.float32) / scale).astype(dtype)


def fp8_dot(a: jax.Array, b: jax.Array, out_dtype=jnp.bfloat16,
            fwd_dtype=E4M3) -> jax.Array:
    """Scaled fp8 matmul: a @ b with both operands rounded through fp8.

    The contraction accumulates in f32 (`preferred_element_type`), then the
    combined scale divides out.  Parity target: the Fp8Optimization module
    filter — this is the op it swaps into Linear layers.
    """
    qa, sa = fp8_quantize(a, fwd_dtype)
    qb, sb = fp8_quantize(b, fwd_dtype)
    acc = jax.lax.dot_general(
        qa, qb, (((qa.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return (acc / (sa * sb)).astype(out_dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def fp8_matmul(a, b, out_dtype=jnp.bfloat16):
    """2D fp8 matmul: e4m3 forward, e5m2 gradients (te convention).

    a (m, k) @ b (k, n) → (m, n).  Callers flatten leading batch dims.
    """
    return fp8_dot(a, b, out_dtype, E4M3)


def _fp8_mm_fwd(a, b, out_dtype):
    return fp8_dot(a, b, out_dtype, E4M3), (a, b)


def _fp8_mm_bwd(out_dtype, res, g):
    a, b = res
    # grads flow through e5m2 (wider range, lower precision)
    qg, sg = fp8_quantize(g, E5M2)
    qb, sb = fp8_quantize(b, E5M2)
    qa, sa = fp8_quantize(a, E5M2)
    ga = jax.lax.dot_general(
        qg, qb, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) / (sg * sb)
    gb = jax.lax.dot_general(
        qa, qg, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) / (sa * sg)
    return ga.astype(a.dtype), gb.astype(b.dtype)


fp8_matmul.defvjp(_fp8_mm_fwd, _fp8_mm_bwd)


class Fp8Einsum:
    """Drop-in helper for (B, T, C) x (C, F) projections via fp8_matmul."""

    @staticmethod
    def project(x: jax.Array, w: jax.Array,
                out_dtype=jnp.bfloat16) -> jax.Array:
        B = x.shape[:-1]
        y = fp8_matmul(x.reshape(-1, x.shape[-1]), w, out_dtype)
        return y.reshape(*B, w.shape[-1])

"""graftlint Engine A — jaxpr-level checks on traced (never executed) steps.

Parity: reference runtime diagnosis (`dlrover/python/diagnosis/
inferencechain/inference_chain.py:1`, error_monitor.py:1) observes NCCL
hangs and OOMs AFTER they fire; on TPU the same bug classes are visible
in the jaxpr before any chip is touched.  Each checker encodes one
CLAUDE.md hard-won rule:

- ``collective-in-cond`` — a collective (psum/all_gather/ppermute/...)
  reachable inside a ``lax.cond`` branch whose predicate VARIES over a
  shard_map manual axis: shards disagree on the branch, the collective
  rendezvous never completes → deadlock.  The fix is to compute
  unconditionally and mask with ``jnp.where`` (all pipeline schedules
  do, parallel/pipeline.py).  Detection is a varying-axes dataflow over
  the jaxpr: shard_map inputs start varying per their in_names, psum-like
  reductions cancel varyingness over their axes, ``axis_index``
  introduces it, and a cond whose predicate still varies over a manual
  axis with a collective in either branch is flagged.
- ``remat-noop`` — ``remat(..., prevent_cse=False)`` outside a
  ``lax.scan``/``while`` body: XLA CSE merges the recompute against the
  forward and silently undoes the rematerialization (identical time AND
  temps, CLAUDE.md).  Under scan the loop body is a separate computation
  and prevent_cse=False is exactly right; unrolled python layer loops
  are the trap (models use prevent_cse=True).
- ``donation-alias`` — donated argnums must be OFF when the resolved
  strategy carries ``optimizer_offload``: XLA would alias a pinned_host
  input onto a device output and the runtime rejects the memory-kind
  mismatch (trainer/train_step.py:102).
- ``host-kind-out-shardings`` — jit ``out_shardings`` carrying a host
  memory kind trips the SPMD partitioner ("Side-effect HLO must have
  sharding"): init on device shardings, then ``jax.device_put`` to the
  host-kind tree (auto/accelerate.py:607).

Everything here works on abstract values (``jax.make_jaxpr`` /
``materialize=False`` state) — no device computation is ever dispatched.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, FrozenSet, List, Optional, Sequence

from .findings import Finding

# collectives that rendezvous across shards (deadlock candidates inside a
# divergent cond) — name -> whether the result is INVARIANT over the
# collective's axes afterwards (psum of x over 'x' is the same on every
# 'x' shard; ppermute stays varying)
_COLLECTIVES: Dict[str, bool] = {
    "psum": True, "psum2": True, "pmax": True, "pmin": True,
    "all_gather": True, "all_to_all": False, "reduce_scatter": False,
    "ppermute": False, "pbroadcast": False, "pgather": False,
}

_HOST_MEMORY_KINDS = ("pinned_host", "unpinned_host", "host")


def _collective_axes(eqn) -> FrozenSet[str]:
    axes = eqn.params.get("axes", eqn.params.get("axis_name", ()))
    if isinstance(axes, str):
        axes = (axes,)
    try:
        return frozenset(a for a in axes if isinstance(a, str))
    except TypeError:
        return frozenset()


def _source_line(eqn) -> str:
    """file:line of the python frame that emitted this eqn, best-effort."""
    try:
        from jax._src import source_info_util

        return source_info_util.summarize(eqn.source_info)
    except Exception:  # noqa: BLE001 — private API; cosmetic only
        return ""


def _sub_jaxprs(eqn):
    """(sub_jaxpr, invars_for_binders) pairs for eqns that nest jaxprs."""
    import jax.core as core

    name = eqn.primitive.name
    if name == "cond":
        for br in eqn.params.get("branches", ()):
            yield br.jaxpr if hasattr(br, "jaxpr") else br, eqn.invars[1:]
        return
    if name == "while":
        cn = eqn.params.get("cond_nconsts", 0)
        bn = eqn.params.get("body_nconsts", 0)
        carry = eqn.invars[cn + bn:]
        yield eqn.params["cond_jaxpr"].jaxpr, eqn.invars[:cn] + carry
        yield eqn.params["body_jaxpr"].jaxpr, \
            eqn.invars[cn:cn + bn] + carry
        return
    for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
        sub = eqn.params.get(key)
        if sub is None:
            continue
        body = sub.jaxpr if hasattr(sub, "jaxpr") else sub
        if not isinstance(body, core.Jaxpr):
            continue
        yield body, eqn.invars


def _closed(fn_or_jaxpr, args):
    import jax

    if hasattr(fn_or_jaxpr, "jaxpr") or hasattr(fn_or_jaxpr, "eqns"):
        return fn_or_jaxpr
    return jax.make_jaxpr(fn_or_jaxpr)(*args)


def _find_collectives(jaxpr, manual_axes: FrozenSet[str]) -> List:
    """All collective eqns over any manual axis, recursively."""
    out = []
    for eqn in jaxpr.eqns:
        if eqn.primitive.name in _COLLECTIVES and \
                _collective_axes(eqn) & manual_axes:
            out.append(eqn)
        for sub, _ in _sub_jaxprs(eqn):
            out.extend(_find_collectives(sub, manual_axes))
    return out


# ------------------------------------------------- collective-in-cond


def check_collective_in_cond(fn_or_jaxpr, *args) -> List[Finding]:
    """Deadlock scan: cond with a shard-varying predicate guarding a
    collective.  Pass a callable plus example (abstract ok) args, or a
    jaxpr from ``jax.make_jaxpr``."""
    closed = _closed(fn_or_jaxpr, args)
    jaxpr = closed.jaxpr if hasattr(closed, "jaxpr") else closed
    findings: List[Finding] = []
    _walk_varying(jaxpr, {v: frozenset() for v in jaxpr.invars},
                  frozenset(), findings)
    return findings


def _walk_varying(jaxpr, varying: Dict, manual_axes: FrozenSet[str],
                  findings: List[Finding]) -> None:
    import jax.core as core

    def axes_of(v) -> FrozenSet[str]:
        if isinstance(v, core.Literal):
            return frozenset()
        return varying.get(v, frozenset())

    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        in_axes = frozenset().union(*(axes_of(v) for v in eqn.invars)) \
            if eqn.invars else frozenset()

        if name == "shard_map":
            mesh = eqn.params.get("mesh")
            auto = eqn.params.get("auto", frozenset()) or frozenset()
            mesh_axes = frozenset(getattr(mesh, "axis_names", ()) or ())
            manual = (mesh_axes - frozenset(auto)) | manual_axes
            body = eqn.params["jaxpr"]
            body = body.jaxpr if hasattr(body, "jaxpr") else body
            in_names = eqn.params.get("in_names") or \
                eqn.params.get("in_specs") or ()
            sub_env: Dict = {}
            for i, bv in enumerate(body.invars):
                axes: FrozenSet[str] = in_axes
                if i < len(in_names) and isinstance(in_names[i], dict):
                    axes = axes | frozenset(
                        a for names in in_names[i].values()
                        for a in names)
                sub_env[bv] = axes & manual
            _walk_varying(body, sub_env, manual, findings)
            out_axes = manual  # conservative: shard outputs vary
            for ov in eqn.outvars:
                varying[ov] = out_axes
            continue

        if name == "cond":
            pred_axes = axes_of(eqn.invars[0]) & manual_axes
            if pred_axes:
                for br in eqn.params.get("branches", ()):
                    body = br.jaxpr if hasattr(br, "jaxpr") else br
                    for coll in _find_collectives(body, manual_axes):
                        where = _source_line(coll)
                        findings.append(Finding(
                            "collective-in-cond",
                            f"`{coll.primitive.name}` over axis "
                            f"{sorted(_collective_axes(coll))} inside a "
                            f"cond branch whose predicate varies over "
                            f"manual axis {sorted(pred_axes)} — shards "
                            f"that take different branches deadlock the "
                            f"collective rendezvous; compute "
                            f"unconditionally and mask with jnp.where"
                            + (f" (at {where})" if where else ""),
                            rule="collectives inside lax.cond with a "
                                 "shard-varying predicate deadlock"))

        if name in _COLLECTIVES:
            axes = _collective_axes(eqn)
            out = in_axes | (axes if name == "axis_index" else frozenset())
            if _COLLECTIVES[name]:
                out = out - axes
            for ov in eqn.outvars:
                varying[ov] = out
            continue
        if name == "axis_index":
            ax = eqn.params.get("axis_name", ())
            ax = (ax,) if isinstance(ax, str) else tuple(ax)
            for ov in eqn.outvars:
                varying[ov] = in_axes | frozenset(
                    a for a in ax if isinstance(a, str))
            continue

        for sub, binder_args in _sub_jaxprs(eqn):
            if len(sub.invars) == len(binder_args):
                sub_env = {bv: axes_of(av)
                           for bv, av in zip(sub.invars, binder_args)}
            else:  # unknown calling convention: every binder inherits all
                sub_env = {bv: in_axes for bv in sub.invars}
            _walk_varying(sub, sub_env, manual_axes, findings)

        for ov in eqn.outvars:
            varying[ov] = in_axes


# ------------------------------------------------------------ remat-noop


def check_remat_noop(fn_or_jaxpr, *args) -> List[Finding]:
    """remat(prevent_cse=False) outside a scan/while body: XLA CSE undoes
    the recompute (the python-layer-loop trap, CLAUDE.md)."""
    closed = _closed(fn_or_jaxpr, args)
    jaxpr = closed.jaxpr if hasattr(closed, "jaxpr") else closed
    findings: List[Finding] = []
    _walk_remat(jaxpr, in_loop_body=False, findings=findings)
    return findings


def _walk_remat(jaxpr, in_loop_body: bool,
                findings: List[Finding]) -> None:
    unsafe = [e for e in jaxpr.eqns
              if e.primitive.name in ("remat2", "remat")
              and not e.params.get("prevent_cse", True)]
    if not in_loop_body and unsafe:
        # group structurally identical instances: an unrolled layer loop
        # shows up as N clones side by side
        sig = {}
        for e in unsafe:
            body = e.params.get("jaxpr")
            key = tuple(se.primitive.name
                        for se in getattr(body, "eqns", ()))
            sig.setdefault(key, []).append(e)
        for eqns in sig.values():
            e = eqns[0]
            where = _source_line(e)
            n = len(eqns)
            findings.append(Finding(
                "remat-noop",
                f"remat with prevent_cse=False outside a scan/while body"
                + (f" ({n} identical instances — an unrolled python "
                   f"layer loop)" if n > 1 else "")
                + " — XLA CSE merges the recompute against the forward "
                  "and silently undoes rematerialization; use "
                  "prevent_cse=True (models do) or move the loop into "
                  "lax.scan"
                + (f" (at {where})" if where else ""),
                rule="prevent_cse=False under a python layer loop is "
                     "silently undone by XLA CSE"))
    for eqn in jaxpr.eqns:
        is_loop = eqn.primitive.name in ("scan", "while")
        for sub, _ in _sub_jaxprs(eqn):
            _walk_remat(sub, in_loop_body or is_loop, findings)


# -------------------------------------------------------- donation-alias


def check_donation_alias(strategy_extra: Dict[str, Any],
                         donate: Optional[bool]) -> List[Finding]:
    """Donation requested while the strategy offloads optimizer state."""
    if donate and strategy_extra.get("optimizer_offload"):
        return [Finding(
            "donation-alias",
            "donate=True with the 'optimizer_offload' strategy — XLA "
            "would alias a pinned_host input buffer onto a device-memory "
            "output and the runtime rejects the memory-kind mismatch; "
            "donation must stay off (auto_accelerate resolves this "
            "automatically when donate is unset)",
            rule="with ('optimizer_offload', ...) donation is OFF")]
    return []


def resolve_donation(strategy_extra: Dict[str, Any],
                     donate: Optional[bool]) -> bool:
    """The donation flag a train step may actually use.

    ``donate=None`` auto-resolves (off under optimizer_offload); an
    explicit ``donate=True`` that conflicts raises ``ValueError`` at
    resolve time, before any parameter init — the repo's strategy-matrix
    convention for impossible combinations.
    """
    findings = check_donation_alias(strategy_extra, donate)
    if findings:
        raise ValueError(f"graftlint[donation-alias]: "
                         f"{findings[0].message}")
    if donate is None:
        return not strategy_extra.get("optimizer_offload")
    return bool(donate)


# ----------------------------------------------- host-kind-out-shardings


def _is_explicit_host_kind(sharding, kind: Optional[str]) -> bool:
    """True when `kind` means 'deliberately placed off-device'.

    pinned_host is always explicit (the optimizer_offload trees).  On
    the CPU backend the DEFAULT memory kind is literally
    'unpinned_host', so that name only counts as host placement on a
    non-CPU platform.  Deliberately judged from `device.platform` alone:
    querying the memories API (`default_memory()`/`addressable_
    memories()`) on a fresh CPU backend pins its memory-space list
    before pinned_host is registered and every later pinned_host
    NamedSharding construction in the process fails — the checker must
    not perturb what it checks.
    """
    if kind == "pinned_host":
        return True
    if kind in _HOST_MEMORY_KINDS:
        try:
            platform = next(iter(sharding.device_set)).platform
        except Exception:  # noqa: BLE001 — fakes/abstract shardings
            return False
        return platform != "cpu"
    return False


def check_host_out_shardings(tree: Any) -> List[Finding]:
    """Shardings destined for jit out_shardings must be device-kind.

    A leaf is flagged when its memory kind is an explicit host placement
    (see `_is_explicit_host_kind`) — the optimizer_offload pinned_host
    trees, not plain CPU defaults.
    """
    import jax

    findings: List[Finding] = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(
            tree, is_leaf=lambda x: hasattr(x, "memory_kind"))[0]:
        kind = getattr(leaf, "memory_kind", None)
        if _is_explicit_host_kind(leaf, kind):
            findings.append(Finding(
                "host-kind-out-shardings",
                f"out_shardings leaf {jax.tree_util.keystr(path)} carries "
                f"memory_kind={kind!r} — jit-init onto host memory trips "
                f"the SPMD partitioner ('Side-effect HLO must have "
                f"sharding'); init on device shardings, then "
                f"jax.device_put to the host-kind tree",
                rule="jit out_shardings with a host memory kind trips "
                     "the SPMD partitioner"))
    return findings


def assert_no_host_out_shardings(tree: Any, where: str = "jit init"
                                 ) -> None:
    findings = check_host_out_shardings(tree)
    if findings:
        raise ValueError(
            f"graftlint[host-kind-out-shardings] at {where}: "
            f"{findings[0].message}")


# ---------------------------------------------------------- step audits


def audit_step(fn: Callable, *abstract_args) -> List[Finding]:
    """Trace `fn` (abstract args ok — ShapeDtypeStructs) and run both
    jaxpr checkers.  Never dispatches device computation."""
    import jax

    closed = jax.make_jaxpr(fn)(*abstract_args)
    return (check_collective_in_cond(closed)
            + check_remat_noop(closed))


def self_audit(n_devices: int = 8) -> List[Finding]:
    """Trace the repo's own canonical train steps and lint the jaxprs.

    Covers the strategy corners where the deadlock/remat rules actually
    bite: ring-SP (ppermute inside shard_map, where-masked — must be
    clean), pipeline gpipe (masked schedule collectives), and the remat'd
    fsdp+tp step.  Uses materialize=False abstract state: tracing only.
    """
    import jax
    import jax.numpy as jnp

    from ..auto.accelerate import auto_accelerate
    from ..models.gpt import GPT, GPTConfig

    devices = list(jax.devices("cpu"))[:n_devices]
    if len(devices) < 4:
        return [Finding(
            "self-audit",
            f"need >= 4 cpu devices for the audit meshes, have "
            f"{len(devices)} — run under "
            f"XLA_FLAGS=--xla_force_host_platform_device_count=8")]
    cfg = GPTConfig(vocab_size=256, n_layer=2, n_head=4, n_embd=64,
                    block_size=32, dtype=jnp.float32)
    cases = [
        ("fsdp-tp-remat", cfg,
         [("tensor_parallel", {"size": 2}), ("fsdp", {}),
          ("checkpoint", {"policy": "dots"})], 1),
        ("ring-sp", cfg,
         [("sequence_parallel", {"size": 2, "impl": "ring"}),
          ("fsdp", {})], 1),
        ("accum", cfg, [("fsdp", {}), ("grad_accum", {"steps": 2})], 2),
    ]
    import dataclasses as _dc

    pp_cfg = _dc.replace(cfg, n_layer=2)
    cases.append(("pp-gpipe", pp_cfg,
                  [("pipeline_parallel", {"size": 2, "microbatches": 2}),
                   ("fsdp", {})], 1))
    findings: List[Finding] = []
    skipped: List[str] = []
    for tag, mcfg, strategy, accum in cases:
        try:
            res = auto_accelerate(GPT(mcfg), strategy=strategy,
                                  devices=devices, materialize=False)
            shape = (4, mcfg.block_size) if accum == 1 else \
                (accum, 4, mcfg.block_size)
            batch = {"input_ids": jax.ShapeDtypeStruct(shape, jnp.int32),
                     "labels": jax.ShapeDtypeStruct(shape, jnp.int32)}
            case = audit_step(res.train_step, res.state, batch)
        except RuntimeError as e:
            # environment gap (e.g. pipeline shard_map needs jax >= 0.6)
            # — report the skip loudly rather than claiming coverage
            skipped.append(f"{tag}: {e}")
            continue
        for f in case:
            f.message = f"[{tag}] {f.message}"
            findings.append(f)
    if skipped:
        from ..common.log import get_logger

        get_logger("graftlint").warning(
            "self-audit skipped %d case(s): %s", len(skipped),
            "; ".join(skipped))
    return findings

"""graftlint concurrency engine: lock discipline + shared-state races.

Parity: no reference counterpart — reference dlrover's concurrency
discipline (elastic_agent/torch/training.py thread lifecycles,
common/multi_process.py SharedLock protocol) exists only as runtime
behavior, and its failure mode is the chaos-drill wedge.  This repo's two
worst historical outages were exactly that class (CLAUDE.md):

- **PR 1 wedge**: a SIGKILLed SharedLock holder stalled the next worker
  generation's first shm staging for the full 600s SAVE_TIMEOUT — a
  blocking wait reachable while a cross-process lock was held.
- **PR 4 wedge**: the replica backup dialed a dead peer socket *inside*
  the shm staging-lock span, burning a 150s RPC floor per call with the
  lock held (the fix hoisted the dial out of ``_segment_bytes``;
  checkpoint/replica.py documents the shape).

Both are visible in the source: a blocking operation (socket dial, RPC,
``retry_call``, ``fsync``, ``sleep``, subprocess spawn) transitively
reachable from a lock-held region.  This engine makes that whole class a
lint failure instead of a chaos-drill discovery.  It reuses the protocol
engine's per-module call graph and transitive-effect closure
(protocol_engine.ModuleGraph) and, like it, imports no jax — it runs in
the ``__graft_entry__.py`` pre-flight before any backend exists.

Rules (catalog + severities in findings.RULE_CATALOG):

- ``blocking-under-lock``: a blocking call (BLOCKING table: socket dial /
  ``retry_call`` / frame IO / ``fsync`` / ``time.sleep`` / subprocess
  spawn / bulk socket IO) lexically inside a ``with lock:`` body or an
  ``acquire()``-to-``release()`` span, directly or transitively through
  local calls.  Cross-process SharedLocks make this a *generation* wedge
  (the lock outlives the holder's death), in-process locks make it a
  convoy; both shapes are flagged.  The lock/IPC implementation itself
  (LOCK_IMPL_FILES — its client lock exists to serialize the socket) is
  sanctioned.
- ``lock-order-cycle``: lock A held when lock B is acquired (directly or
  through local calls) adds ordering edge A→B; a cycle in the per-module
  edge graph is a potential ABBA deadlock.  Lock identities are resolved
  per class (``self._lock`` in two classes are two locks) so the graph
  never aliases unrelated locks.
- ``unguarded-shared-state``: a ``self.X`` attribute mutated inside a
  ``threading.Thread(target=self._run)``-style worker method while
  another method of the same class mutates it with no common lock
  guarding both sites (write-write race), or accesses it under a lock
  the worker write does not hold (inconsistent guard — the lock protects
  nothing).  Lock-/event-/queue-typed attributes are exempt (their
  methods are thread-safe); plain loads racing a GIL-atomic flag write
  are NOT flagged (idiomatic stop-flag passing).  Worker targets are
  resolved from ``target=self.<method>`` bound-method references;
  nested-closure targets are out of scope (separate function scopes).
- ``thread-lifecycle``: a non-daemon ``threading.Thread`` started with
  no ``join()`` reachable on any shutdown path (``self.X`` threads:
  anywhere in the class; local threads: in the same function) and no
  ``daemon=True``/``.daemon = True`` mark — the interpreter hangs at
  exit waiting on it, which is exactly how a "finished" job keeps its
  pod alive.  Tests are exempt from this rule and from
  unguarded-shared-state (short-lived scaffolding, not services);
  the two wedge rules run everywhere, tests included — a deadlocked
  test wedges CI just as hard.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .findings import Finding, is_suppressed
from .protocol_engine import FuncInfo, ModuleGraph, _dotted, _terminal

# --------------------------------------------------------------- tables
# The tables ARE the spec, like the protocol engine's verb tables: a new
# blocking primitive or lock constructor gets added here in the same PR
# that introduces it.

#: dotted call names that block the calling thread unconditionally.
BLOCKING_DOTTED = {"time.sleep", "_time.sleep"}

#: terminal callee names that block regardless of receiver.
BLOCKING_TERMINALS = {
    "create_connection",   # socket dial (the PR 4 wedge primitive)
    "retry_call",          # the shared RPC policy: bounded but LONG
    "_send_frame", "_recv_frame",   # frame-level control-plane IO
    "fsync",               # storage durability barrier
    "urlopen",             # http fetch
    "sendall",             # bulk socket IO (replica blob transfers)
}

#: subprocess spawn: ``subprocess.run(...)``, ``subprocess.Popen(...)``…
SUBPROCESS_TERMINALS = {"run", "call", "check_output", "check_call",
                        "Popen"}

#: receiver fragments that mark ``.connect()``/``.recv()`` as socket IO.
SOCKET_RECEIVER_HINTS = ("sock", "conn", "request")

#: constructors whose result is a lock (attr-type resolution).
LOCK_CONSTRUCTORS = {"Lock", "RLock", "Condition", "SharedLock",
                     "Semaphore", "BoundedSemaphore"}

#: constructors whose result is internally synchronized — attributes of
#: these types are exempt from unguarded-shared-state (their methods are
#: thread-safe; rebinding them post-init is the bug the rule would still
#: catch via the write-write arm if both writes are bare).
THREADSAFE_CONSTRUCTORS = LOCK_CONSTRUCTORS | {
    "Event", "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
    "deque", "Barrier", "local",
}

#: the SharedLock/IPC and RPC transport implementations: their client
#: locks exist to SERIALIZE the client socket — the exchange IS the
#: critical section (LocalSocketComm._client_lock, RpcClient._lock) —
#: and the lock server's poll loop sleeps by design.  Callers above the
#: transport still get checked.
LOCK_IMPL_FILES = ("common/multi_process.py", "common/comm.py")


def _is_test_path(path: str) -> bool:
    parts = path.replace(os.sep, "/").split("/")
    return "tests" in parts or parts[-1].startswith("test_")


# ---------------------------------------------------------- lock naming


def _class_attr_types(tree: ast.Module) -> Dict[str, Dict[str, str]]:
    """class -> {attr -> constructor terminal} for ``self.X = Ctor(...)``
    assignments anywhere in the class (``__init__`` and helpers alike)."""
    out: Dict[str, Dict[str, str]] = {}
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        attrs: Dict[str, str] = {}
        for child in ast.walk(node):
            if not isinstance(child, ast.Assign):
                continue
            value = child.value
            # unwrap `X() if cond else None` (the master=True idiom)
            if isinstance(value, ast.IfExp):
                value = value.body
            if not isinstance(value, ast.Call):
                continue
            ctor = _terminal(value.func)
            if ctor not in THREADSAFE_CONSTRUCTORS:
                continue
            for t in child.targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    attrs[t.attr] = ctor
        if attrs:
            out[node.name] = attrs
    return out


class LockNamer:
    """Resolves AST expressions to canonical per-module lock identities.

    ``self._lock`` inside class C -> ``C._lock`` (two classes never
    alias); anything else keeps its dotted text.  An expression is a
    lock when its attr is lock-TYPED (assigned from a LOCK_CONSTRUCTORS
    call in the class) or lock-NAMED ("lock"/"mutex" in the dotted
    text — covers parameters and cross-object handles the type pass
    cannot see).
    """

    def __init__(self, attr_types: Dict[str, Dict[str, str]]):
        self._attr_types = attr_types

    def lock_id(self, expr: ast.AST, cls: Optional[str]) -> Optional[str]:
        dotted = _dotted(expr)
        if not dotted:
            return None
        parts = dotted.split(".")
        if parts[0] == "self" and cls:
            canon = f"{cls}.{'.'.join(parts[1:])}"
            attr = parts[1] if len(parts) > 1 else ""
            ctor = self._attr_types.get(cls, {}).get(attr)
            if ctor in LOCK_CONSTRUCTORS:
                return canon
            if self._looks_locky(dotted):
                return canon
            return None
        if self._looks_locky(dotted):
            return dotted
        return None

    @staticmethod
    def _looks_locky(dotted: str) -> bool:
        low = dotted.lower()
        return "lock" in low or "mutex" in low

    def attr_ctor(self, cls: Optional[str], attr: str) -> Optional[str]:
        return self._attr_types.get(cls or "", {}).get(attr)


# ------------------------------------------------------------ regions


class LockRegion:
    """One lock-held span inside a function, as a closed line interval."""

    __slots__ = ("lock_id", "start", "end", "via", "lineno")

    def __init__(self, lock_id: str, start: int, end: int, via: str,
                 lineno: int):
        self.lock_id = lock_id
        self.start = start      # first line INSIDE the held span
        self.end = end          # last line of the held span
        self.via = via          # "with" | "acquire"
        self.lineno = lineno    # the with/acquire line (for messages)

    def contains(self, line: int) -> bool:
        return self.start <= line <= self.end


def _node_end(node: ast.AST) -> int:
    return max((getattr(n, "end_lineno", None) or
                getattr(n, "lineno", 0) for n in ast.walk(node)),
               default=getattr(node, "lineno", 0))


def lock_regions(info: FuncInfo, namer: LockNamer) -> List[LockRegion]:
    """All lock-held line spans in one function.

    ``with lock:`` bodies are exact; ``x.acquire()`` spans run to the
    first subsequent ``x.release()`` line in the same function (the
    in-tree ``acquire; try: ... finally: release`` idiom keeps the
    finally's release line AFTER the guarded body, so line intervals are
    faithful), else to the function's end — matching the protocol
    engine's lock-leak view of an unreleased acquire.
    """
    regions: List[LockRegion] = []
    releases: Dict[str, List[int]] = {}
    acquires: List[Tuple[str, int]] = []
    for node in ast.walk(info.node):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                lid = namer.lock_id(item.context_expr, info.cls)
                if lid and node.body:
                    regions.append(LockRegion(
                        lid, node.body[0].lineno, _node_end(node),
                        "with", node.lineno))
        elif isinstance(node, ast.Call):
            term = _terminal(node.func)
            if term in ("acquire", "release") and \
                    isinstance(node.func, ast.Attribute):
                lid = namer.lock_id(node.func.value, info.cls)
                if lid is None:
                    continue
                if term == "acquire":
                    acquires.append((lid, node.lineno))
                else:
                    releases.setdefault(lid, []).append(node.lineno)
    fn_end = _node_end(info.node)
    for lid, line in acquires:
        later = sorted(r for r in releases.get(lid, []) if r >= line)
        end = later[0] if later else fn_end
        regions.append(LockRegion(lid, line + 1, end - 1 if later else end,
                                  "acquire", line))
    return [r for r in regions if r.start <= r.end]


# ------------------------------------------------------ blocking calls


def blocking_reason(call: ast.Call) -> Optional[str]:
    """Why `call` blocks the calling thread, or None."""
    dotted = _dotted(call.func) or ""
    term = _terminal(call.func) or ""
    if dotted in BLOCKING_DOTTED or \
            (term == "sleep" and dotted.split(".")[0] in ("time", "_time",
                                                          "gevent")):
        return "time.sleep"
    if term in BLOCKING_TERMINALS:
        return {"create_connection": "socket dial",
                "retry_call": "retry_call RPC",
                "fsync": "fsync",
                "sendall": "bulk socket send",
                "urlopen": "http fetch"}.get(term, f"{term} frame IO")
    if term in ("connect", "recv", "accept") and \
            isinstance(call.func, ast.Attribute):
        recv = (_dotted(call.func.value) or "").lower()
        if any(h in recv for h in SOCKET_RECEIVER_HINTS):
            return f"socket {term}"
    if term in SUBPROCESS_TERMINALS:
        root = dotted.split(".")[0]
        if root in ("subprocess", "sp") or term == "Popen":
            return "subprocess spawn"
    if term == "_request":
        # LocalSocketComm RPC: a unix-socket round trip (plus the 150s
        # dial floor when the resource master is gone)
        return "cross-process IPC round trip"
    return None


# ------------------------------------------------------ effect marking


def mark_concurrency_effects(graph: ModuleGraph, namer: LockNamer) -> None:
    """Stamp 'blocking' / 'acquires:<lock>' direct effects per function,
    pre-closure.  The protocol engine's transitive_effects then answers
    "does anything reachable from f block / take lock L"."""
    for info in graph.funcs.values():
        for node in ast.walk(info.node):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lid = namer.lock_id(item.context_expr, info.cls)
                    if lid:
                        info.effects.add(f"acquires:{lid}")
            elif isinstance(node, ast.Call):
                if blocking_reason(node):
                    info.effects.add("blocking")
                term = _terminal(node.func)
                if term == "acquire" and \
                        isinstance(node.func, ast.Attribute):
                    lid = namer.lock_id(node.func.value, info.cls)
                    if lid:
                        info.effects.add(f"acquires:{lid}")


def _calls_in_span(info: FuncInfo, region: LockRegion) -> List[ast.Call]:
    out = []
    for node in ast.walk(info.node):
        if isinstance(node, ast.Call) and region.contains(node.lineno):
            out.append(node)
    return out


# ------------------------------------------- rule: blocking-under-lock


def check_blocking_under_lock(path: str, tree: ast.Module,
                              source_lines: Sequence[str],
                              graph: ModuleGraph,
                              namer: LockNamer) -> List[Finding]:
    norm = path.replace(os.sep, "/")
    if any(norm.endswith(f) for f in LOCK_IMPL_FILES):
        return []
    findings: List[Finding] = []
    for info in graph.funcs.values():
        for region in lock_regions(info, namer):
            reported: Set[int] = set()
            for call in _calls_in_span(info, region):
                term = _terminal(call.func)
                if term in ("acquire", "release"):
                    continue  # nested lock ops are lock-order's domain
                reason = blocking_reason(call)
                via = ""
                if reason is None:
                    target = graph.resolve(call, info.cls)
                    if target and "blocking" in \
                            graph.transitive_effects(target):
                        reason = "a transitively blocking call"
                        via = f" via {target}()"
                if reason is None:
                    continue
                if call.lineno in reported:
                    continue
                if is_suppressed(source_lines, call.lineno,
                                 "blocking-under-lock"):
                    continue
                reported.add(call.lineno)
                findings.append(Finding(
                    "blocking-under-lock",
                    f"{info.qualname} reaches {reason}{via} while holding "
                    f"{region.lock_id} ({region.via} at line "
                    f"{region.lineno}) — a slow/dead peer turns the lock "
                    f"into a wedge for every waiter (and a SIGKILL here "
                    f"wedges the next worker generation for the full "
                    f"timeout); move the blocking work outside the lock "
                    f"span (copy under the lock, send after release)",
                    path, call.lineno))
    return findings


# --------------------------------------------- rule: lock-order-cycle


def _lock_edges(graph: ModuleGraph, namer: LockNamer
                ) -> List[Tuple[str, str, str, int]]:
    """(held, acquired, qualname, line) ordering edges across the module."""
    edges: List[Tuple[str, str, str, int]] = []
    for info in graph.funcs.values():
        for region in lock_regions(info, namer):
            inner: Set[Tuple[str, int]] = set()
            for node in ast.walk(info.node):
                if isinstance(node, (ast.With, ast.AsyncWith)) and \
                        region.contains(node.lineno) and \
                        node.lineno != region.lineno:
                    for item in node.items:
                        lid = namer.lock_id(item.context_expr, info.cls)
                        if lid:
                            inner.add((lid, node.lineno))
                elif isinstance(node, ast.Call) and \
                        region.contains(node.lineno):
                    term = _terminal(node.func)
                    if term == "acquire" and \
                            isinstance(node.func, ast.Attribute) and \
                            node.lineno != region.lineno:
                        lid = namer.lock_id(node.func.value, info.cls)
                        if lid:
                            inner.add((lid, node.lineno))
                    target = graph.resolve(node, info.cls) \
                        if isinstance(node, ast.Call) else None
                    if target:
                        for eff in graph.transitive_effects(target):
                            if eff.startswith("acquires:"):
                                inner.add((eff.split(":", 1)[1],
                                           node.lineno))
            for lid, line in inner:
                if lid != region.lock_id:
                    edges.append((region.lock_id, lid, info.qualname,
                                  line))
    return edges


def check_lock_order_cycle(path: str, tree: ast.Module,
                           source_lines: Sequence[str],
                           graph: ModuleGraph,
                           namer: LockNamer) -> List[Finding]:
    edges = _lock_edges(graph, namer)
    adj: Dict[str, Set[str]] = {}
    where: Dict[Tuple[str, str], Tuple[str, int]] = {}
    for a, b, qual, line in edges:
        adj.setdefault(a, set()).add(b)
        where.setdefault((a, b), (qual, line))
    findings: List[Finding] = []
    seen_cycles: Set[Tuple[str, ...]] = set()

    def dfs(start: str, node: str, trail: List[str]):
        for nxt in sorted(adj.get(node, ())):
            if nxt == start:
                cycle = trail + [start]
                key = tuple(sorted(cycle[:-1]))
                if key in seen_cycles:
                    continue
                seen_cycles.add(key)
                qual, line = where[(cycle[0], cycle[1])]
                if is_suppressed(source_lines, line, "lock-order-cycle"):
                    continue
                findings.append(Finding(
                    "lock-order-cycle",
                    f"lock ordering cycle {' -> '.join(cycle)} (edge "
                    f"{cycle[0]} -> {cycle[1]} in {qual}) — two threads "
                    f"entering from opposite ends deadlock; impose one "
                    f"global acquisition order or collapse to one lock",
                    path, line))
            elif nxt not in trail:
                dfs(start, nxt, trail + [nxt])

    for start in sorted(adj):
        dfs(start, start, [start])
    return findings


# ------------------------------------- rule: unguarded-shared-state


def _worker_methods(tree: ast.Module) -> Dict[str, Set[str]]:
    """class -> method names used as ``Thread(target=self.<m>)``."""
    out: Dict[str, Set[str]] = {}
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        targets: Set[str] = set()
        for child in ast.walk(node):
            if not (isinstance(child, ast.Call)
                    and _terminal(child.func) == "Thread"):
                continue
            for kw in child.keywords:
                if kw.arg == "target" and \
                        isinstance(kw.value, ast.Attribute) and \
                        isinstance(kw.value.value, ast.Name) and \
                        kw.value.value.id == "self":
                    targets.add(kw.value.attr)
        if targets:
            out[node.name] = targets
    return out


class _AttrSites:
    """Guard sets per self-attribute access site within one method."""

    def __init__(self):
        self.writes: Dict[str, List[Tuple[int, frozenset]]] = {}
        self.reads: Dict[str, List[Tuple[int, frozenset]]] = {}
        self.first_join: Optional[int] = None  # line of first .join() call


def _attr_sites(info: FuncInfo, namer: LockNamer) -> _AttrSites:
    regions = lock_regions(info, namer)

    def guards(line: int) -> frozenset:
        return frozenset(r.lock_id for r in regions if r.contains(line))

    sites = _AttrSites()
    for node in ast.walk(info.node):
        if isinstance(node, ast.Call) and _terminal(node.func) == "join" \
                and isinstance(node.func, ast.Attribute):
            if sites.first_join is None or node.lineno < sites.first_join:
                sites.first_join = node.lineno
        if not (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            continue
        entry = (node.lineno, guards(node.lineno))
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            sites.writes.setdefault(node.attr, []).append(entry)
        else:
            sites.reads.setdefault(node.attr, []).append(entry)
    return sites


def _expand_worker_set(graph: ModuleGraph, cls: str,
                       targets: Set[str]) -> Set[str]:
    """Worker-CONFINED closure: a private method whose every in-class
    caller is already in the worker set runs only on the worker thread
    (the ``_sync_shm_to_storage -> _update_shard_num`` shape) — its
    writes are same-thread, not races.  Public methods stay out (other
    modules may call them from any thread)."""
    members = {i.qualname.split(".")[-1]: i for i in graph.funcs.values()
               if i.cls == cls}
    callers: Dict[str, Set[str]] = {}
    for name, info in members.items():
        for callee in info.calls:
            if callee.startswith(f"{cls}."):
                callers.setdefault(callee.split(".")[-1], set()).add(name)
    out = set(targets)
    changed = True
    while changed:
        changed = False
        for name in members:
            if name in out or not name.startswith("_") or \
                    name.startswith("__"):
                continue
            who = callers.get(name)
            if who and who <= out:
                out.add(name)
                changed = True
    return out


def check_unguarded_shared_state(path: str, tree: ast.Module,
                                 source_lines: Sequence[str],
                                 graph: ModuleGraph,
                                 namer: LockNamer) -> List[Finding]:
    if _is_test_path(path):
        return []
    workers = _worker_methods(tree)
    if not workers:
        return []
    findings: List[Finding] = []
    for cls, methods in workers.items():
        methods = _expand_worker_set(graph, cls, methods)
        worker_infos = [i for i in graph.funcs.values()
                        if i.cls == cls and
                        i.qualname.split(".")[-1] in methods]
        other_infos = [i for i in graph.funcs.values()
                       if i.cls == cls and
                       i.qualname.split(".")[-1] not in methods and
                       i.qualname.split(".")[-1] != "__init__"]
        other_sites = [(i, _attr_sites(i, namer)) for i in other_infos]
        flagged: Set[str] = set()
        for winfo in worker_infos:
            wsites = _attr_sites(winfo, namer)
            for attr, wwrites in sorted(wsites.writes.items()):
                if attr in flagged:
                    continue
                if namer.attr_ctor(cls, attr) in THREADSAFE_CONSTRUCTORS:
                    continue
                for oinfo, osites in other_sites:
                    owrites = osites.writes.get(attr, [])
                    oreads = osites.reads.get(attr, [])
                    if osites.first_join is not None:
                        # accesses after a .join() are synchronized with
                        # worker termination (happens-before) — the
                        # _wait_drain error-handoff shape, not a race
                        owrites = [(ln, g) for ln, g in owrites
                                   if ln < osites.first_join]
                        oreads = [(ln, g) for ln, g in oreads
                                  if ln < osites.first_join]
                    hit: Optional[Tuple[int, str]] = None
                    # (a) write-write with no common lock
                    for wline, wguard in wwrites:
                        for oline, oguard in owrites:
                            if not (wguard & oguard):
                                hit = (wline,
                                       f"also written in {oinfo.qualname} "
                                       f"(line {oline}) with no common "
                                       f"lock")
                                break
                        if hit:
                            break
                    # (b) worker writes bare while another site is guarded
                    if hit is None:
                        for wline, wguard in wwrites:
                            if wguard:
                                continue
                            guarded = [(ln, g) for ln, g in
                                       (owrites + oreads) if g]
                            if guarded:
                                oline, og = guarded[0]
                                hit = (wline,
                                       f"accessed in {oinfo.qualname} "
                                       f"(line {oline}) under "
                                       f"{sorted(og)[0]}, which this "
                                       f"write does not hold")
                                break
                    if hit is None:
                        continue
                    line, detail = hit
                    if is_suppressed(source_lines, line,
                                     "unguarded-shared-state"):
                        continue
                    flagged.add(attr)
                    findings.append(Finding(
                        "unguarded-shared-state",
                        f"self.{attr} is mutated in thread worker "
                        f"{winfo.qualname} and {detail} — the interleaving "
                        f"is a data race; guard both sites with one lock "
                        f"(or confine the attribute to one thread)",
                        path, line))
                    break
    return findings


# -------------------------------------------- rule: thread-lifecycle


def _daemon_true(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
            return bool(kw.value.value)
    return False


def check_thread_lifecycle(path: str, tree: ast.Module,
                           source_lines: Sequence[str],
                           graph: ModuleGraph,
                           namer: LockNamer) -> List[Finding]:
    if _is_test_path(path):
        return []
    findings: List[Finding] = []
    # joins/daemon-marks per scope: class name -> names; plus per function
    class_joined: Dict[str, Set[str]] = {}
    class_daemoned: Dict[str, Set[str]] = {}
    for info in graph.funcs.values():
        scope = info.cls or ""
        for node in ast.walk(info.node):
            if isinstance(node, ast.Call) and \
                    _terminal(node.func) == "join" and \
                    isinstance(node.func, ast.Attribute):
                d = _dotted(node.func.value)
                if d:
                    class_joined.setdefault(scope, set()).add(d)
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and \
                            t.attr == "daemon" and \
                            isinstance(node.value, ast.Constant) and \
                            node.value.value:
                        d = _dotted(t.value)
                        if d:
                            class_daemoned.setdefault(scope,
                                                      set()).add(d)
            if isinstance(node, ast.Call) and \
                    _terminal(node.func) == "setDaemon" and \
                    isinstance(node.func, ast.Attribute):
                d = _dotted(node.func.value)
                if d:
                    class_daemoned.setdefault(scope, set()).add(d)

    for info in graph.funcs.values():
        scope = info.cls or ""
        for node in ast.walk(info.node):
            if not (isinstance(node, ast.Call)
                    and _terminal(node.func) == "Thread"):
                continue
            root = _dotted(node.func) or ""
            if root and root.split(".")[0] not in ("threading", "Thread"):
                # SomeModule.Thread lookalikes: only the stdlib class
                if "." in root:
                    continue
            if _daemon_true(node):
                continue
            # name(s) the constructed thread is bound to
            bound: List[str] = []
            parent_assign = None
            for fn_node in ast.walk(info.node):
                if isinstance(fn_node, ast.Assign) and any(
                        node is c for c in ast.walk(fn_node.value)):
                    parent_assign = fn_node
                    break
            if parent_assign is not None:
                for t in parent_assign.targets:
                    d = _dotted(t)
                    if d:
                        bound.append(d)
            joined = class_joined.get(scope, set())
            daemoned = class_daemoned.get(scope, set())
            if not info.cls:
                # module-level function: joins only visible in-function
                joined = {d for d in joined}
            if any(b in joined for b in bound):
                continue
            if any(b in daemoned for b in bound):
                continue
            if is_suppressed(source_lines, node.lineno,
                             "thread-lifecycle"):
                continue
            what = (f"bound to {bound[0]}" if bound
                    else "started fire-and-forget")
            findings.append(Finding(
                "thread-lifecycle",
                f"{info.qualname} creates a non-daemon Thread ({what}) "
                f"with no join() on any shutdown path and no daemon=True "
                f"— process exit hangs waiting for it; mark it daemon or "
                f"join it from stop()/close()",
                path, node.lineno))
    return findings


# ------------------------------------------------------------- driver


CHECKS = (
    check_blocking_under_lock,
    check_lock_order_cycle,
    check_unguarded_shared_state,
    check_thread_lifecycle,
)


def run_paths(paths: Sequence[str],
              checkers: Optional[Sequence[str]] = None
              ) -> Tuple[List[Finding], int]:
    """Run the concurrency engine over files/dirs; (findings, files).

    Same contract as the ast/protocol engines' run_paths; `checkers`
    filters by rule id.
    """
    from .ast_engine import iter_python_files

    wanted = set(checkers) if checkers else None
    files = iter_python_files(paths)
    findings: List[Finding] = []
    for fpath in files:
        try:
            source = open(fpath).read()
            tree = ast.parse(source)
        except (OSError, SyntaxError) as e:
            findings.append(Finding("parse-error", str(e), fpath, 0))
            continue
        lines = source.splitlines()
        rel = os.path.relpath(fpath)
        graph = ModuleGraph(tree)
        namer = LockNamer(_class_attr_types(tree))
        mark_concurrency_effects(graph, namer)
        for check in CHECKS:
            got = check(rel, tree, lines, graph, namer)
            if wanted is not None:
                got = [f for f in got if f.checker in wanted]
            findings.extend(got)
    return findings, len(files)

"""graftlint protocol engine: interprocedural control-plane invariants.

Parity: no single reference counterpart — reference dlrover encodes its
control-plane protocol (journal-then-ack in `master/servicer.py`,
atomic checkpoint publishes in `common/storage.py`) purely as runtime
behavior; regressions surface as flaky chaos drills.  Here the PR 4/5
invariants that so far existed only as CLAUDE.md prose become statically
checked rules that span FUNCTIONS, not lines: the engine builds a
per-module call graph over the AST (methods resolved within their class,
bare names within their module), computes each function's transitive
*effects* (journal-append, manifest-publish, commit-evidence, ...), and
then checks ordering/dataflow invariants against those effects.

Like the AST engine this imports no jax — it runs in the
`__graft_entry__.py` pre-flight before any backend exists.

Rules (catalog + severities in findings.RULE_CATALOG):

- ``journal-before-ack``: in a servicer class (one that defines a
  ``_journal`` helper), every isinstance-branch handling a verb in
  JOURNALED_VERBS must reach a journal append, and that append must
  precede the branch's final (success) return in statement order —
  acked mutations must be durable ones.  Early returns are the
  no-mutation paths by construction (task-exhausted, not-created) and
  are tolerated; the regression this catches is a new mutating verb
  acked without any append, or an append moved below the ack.
  **Group-commit shape**: an ack gated on the journal's durable
  watermark counts as the append reaching the ack — a branch (or its
  helper, transitively) that calls ``journal.append_nowait`` must also
  reach ``journal.wait_durable`` before the final return; an async
  enqueue with NO durable-wait gate is flagged (the ack would race the
  batch leader's fsync, un-doing journal-before-ack under a crash).
  **Failover shape** (ISSUE 20): an ``append_nowait("failover", ...)``
  in a function that never reaches ``wait_durable`` is flagged under
  the same id — the fencing handoff's "ack" is the epoch bump itself,
  and promoting on an un-fsynced fence frame lets the old epoch
  reappear after a crash (the sanctioned shape is the synchronous
  ``journal.append``, master/master.py promote_to_leader).
- ``idem-key-required``: verbs in IDEM_VERBS are retried across master
  restarts and must thread an idempotency key end to end — the servicer
  branch's journal call must carry ``idem=``, and the MasterClient
  method building that payload must pass ``idem=`` into its transport
  call.
- ``commit-order``: a write naming ``COMMIT_MARKER`` must be preceded
  (in the same function, transitively through local calls) by a
  manifest publish; a write naming ``TRACKER_FILE`` by a manifest
  publish OR commit evidence (a manifest/marker read-and-verify) — the
  tracker may legally repoint to an already-committed generation, but
  never publish a generation no one verified.
- ``atomic-publish``: raw ``open(path, "w"/"wb")`` on a published
  control file (manifest/tracker/marker/spec/inflight/...) tears under
  crash; route through storage.write (write-tmp + fsync + rename) or a
  local tmp with an os.replace.  The helper itself
  (ATOMIC_HELPER_FILES) is sanctioned.
- ``lock-leak``: an ``<x>.acquire(...)`` on a lock-named object whose
  matching ``<x>.release()`` is not inside a ``finally`` block of the
  same function leaks the cross-process SharedLock when this process
  dies mid-section (the lock outlives hard kills — CLAUDE.md).  The
  lock service implementation itself (LOCK_IMPL_FILES) is sanctioned,
  as are ``with``-statement acquisitions.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .findings import Finding, is_suppressed

# --------------------------------------------------------------- protocol
# The protocol tables ARE the spec: a new mutating verb must be added
# here (and to the servicer) in the same PR, exactly like v1's
# DONATING_CALLS / FRAME_IO_CALLS tables.

#: message payload types whose servicer branch mutates durable master
#: state and therefore must journal before acking (master/servicer.py).
JOURNALED_VERBS = {
    "TaskRequest", "KVStoreAddRequest", "JoinRendezvousRequest",
    "TaskResult", "DatasetShardParams", "NodeMeta", "NodeFailure",
    "KVStoreSetRequest", "ShardCheckpoint", "PolicyDecisionReport",
    "ServeSubmitRequest", "ServeLeaseRequest", "ServeResultReport",
    "MeshTransitionPhaseReport",
}

#: verbs that are NOT naturally idempotent across a master restart: the
#: idem key + journaled response make their retries at-most-once.
IDEM_VERBS = {
    "TaskRequest", "KVStoreAddRequest", "JoinRendezvousRequest",
    "TaskResult", "PolicyDecisionReport",
    "ServeSubmitRequest", "ServeLeaseRequest", "ServeResultReport",
    "MeshTransitionPhaseReport",
}

#: names whose (transitive) call means "a manifest was published".
MANIFEST_PUBLISHERS = {"write_manifest", "_write_step_manifest"}

#: names whose (transitive) call means "commit state was read/verified"
#: — a tracker repoint after these targets an already-committed step.
COMMIT_EVIDENCE = {"read_manifest", "read_last_step"}

#: constants naming the two published commit files (common/constants.py).
MARKER_CONSTS = {"COMMIT_MARKER"}
TRACKER_CONSTS = {"TRACKER_FILE"}

#: path-text fragments that mark a file as a *published* control file
#: for atomic-publish (read by another process / a later generation).
PUBLISHED_HINTS = (
    "manifest", "tracker", ".commit", ".done", ".spec", ".inflight",
    "snapshot", "latest_checkpointed",
)

#: the blessed write-tmp+fsync+rename implementations themselves.
ATOMIC_HELPER_FILES = ("common/storage.py",)

#: the SharedLock/socket service implementation (its internal
#: threading.Lock bookkeeping is the mechanism, not a client).
LOCK_IMPL_FILES = ("common/multi_process.py",)

#: transport senders a client verb may thread its idem key into.
CLIENT_TRANSPORT_CALLS = {"_call", "_call_critical"}


# ------------------------------------------------------------- call graph


class FuncInfo:
    """One function/method: AST node + resolution context."""

    __slots__ = ("qualname", "node", "cls", "calls", "effects")

    def __init__(self, qualname: str, node: ast.AST, cls: Optional[str]):
        self.qualname = qualname
        self.node = node
        self.cls = cls
        self.calls: Set[str] = set()     # resolved local qualnames
        self.effects: Set[str] = set()   # direct effects, pre-closure


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _terminal(node: ast.AST) -> Optional[str]:
    """Last attribute/name of a callee: `self.storage.write` -> 'write'."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _names_in(node: ast.AST) -> Set[str]:
    """All Name ids and Attribute attrs under `node` (constant spotting)."""
    out: Set[str] = set()
    for child in ast.walk(node):
        if isinstance(child, ast.Name):
            out.add(child.id)
        elif isinstance(child, ast.Attribute):
            out.add(child.attr)
    return out


class ModuleGraph:
    """Per-module call graph with transitive effect closure.

    Calls are resolved conservatively: ``self.foo(...)``/``cls.foo(...)``
    to a method of the enclosing class, bare ``foo(...)`` to a module
    function (imported names resolve by terminal name when a module
    function of that name exists — good enough for the in-repo
    ``from .integrity import write_manifest`` idiom).
    """

    def __init__(self, tree: ast.Module):
        self.funcs: Dict[str, FuncInfo] = {}
        self.by_class: Dict[str, Set[str]] = {}
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add(node, None)
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        self._add(sub, node.name)
        for info in self.funcs.values():
            self._collect_calls(info)

    def _add(self, node, cls: Optional[str]):
        qual = f"{cls}.{node.name}" if cls else node.name
        self.funcs[qual] = FuncInfo(qual, node, cls)
        if cls:
            self.by_class.setdefault(cls, set()).add(node.name)

    def resolve(self, call: ast.Call, cls: Optional[str]) -> Optional[str]:
        """Local qualname a call resolves to, or None (external)."""
        fn = call.func
        if isinstance(fn, ast.Attribute) and \
                isinstance(fn.value, ast.Name) and \
                fn.value.id in ("self", "cls") and cls and \
                fn.attr in self.by_class.get(cls, ()):
            return f"{cls}.{fn.attr}"
        if isinstance(fn, ast.Name) and fn.id in self.funcs:
            return fn.id
        return None

    def _collect_calls(self, info: FuncInfo):
        for child in ast.walk(info.node):
            if isinstance(child, ast.Call):
                target = self.resolve(child, info.cls)
                if target:
                    info.calls.add(target)

    def transitive_effects(self, qual: str,
                           _seen: Optional[Set[str]] = None) -> Set[str]:
        if _seen is None:
            _seen = set()
        if qual in _seen or qual not in self.funcs:
            return set()
        _seen.add(qual)
        info = self.funcs[qual]
        out = set(info.effects)
        for callee in info.calls:
            out |= self.transitive_effects(callee, _seen)
        return out


def _mark_effects(graph: ModuleGraph):
    """Stamp direct effects onto every function, pre-closure."""
    for info in graph.funcs.values():
        name = info.qualname.rsplit(".", 1)[-1]
        if name in MANIFEST_PUBLISHERS:
            info.effects.add("manifest-publish")
        if name in COMMIT_EVIDENCE:
            info.effects.add("commit-evidence")
        # a function that references a commit/manifest constant anywhere
        # AND reads storage is consulting commit state (the constant may
        # live in a path assignment, not the read call itself —
        # engine.committed_steps builds `marker` then storage.exists(it))
        fn_names = _names_in(info.node)
        if fn_names & (MARKER_CONSTS | {"MANIFEST_NAME"}):
            for child in ast.walk(info.node):
                if isinstance(child, ast.Call) and \
                        _terminal(child.func) in ("exists", "read",
                                                  "listdir"):
                    info.effects.add("commit-evidence")
                    break
        for child in ast.walk(info.node):
            if not isinstance(child, ast.Call):
                continue
            term = _terminal(child.func)
            if term == "append" and _dotted(child.func) and \
                    "journal" in _dotted(child.func):
                info.effects.add("journal-append")
            # group-commit split shape: enqueue + durable-watermark gate
            # are separate effects; only their CONJUNCTION equals a
            # synchronous journal append (check_servicer_protocol)
            if term == "append_nowait" and _dotted(child.func) and \
                    "journal" in _dotted(child.func):
                info.effects.add("journal-append-async")
            if term == "wait_durable" and _dotted(child.func) and \
                    "journal" in _dotted(child.func):
                info.effects.add("journal-durable-wait")
            if term in MANIFEST_PUBLISHERS:
                info.effects.add("manifest-publish")
            if term in COMMIT_EVIDENCE:
                info.effects.add("commit-evidence")
            names = _names_in(child)
            if term in ("write", "open", "write_fileobj", "replace") \
                    and names & MARKER_CONSTS:
                info.effects.add("marker-write")
            if term in ("exists", "read") and \
                    names & (MARKER_CONSTS | {"MANIFEST_NAME"}):
                info.effects.add("commit-evidence")


# ------------------------------------------------------- rule: servicer


def _isinstance_verb(test: ast.AST) -> Set[str]:
    """Message type names from `isinstance(payload, msg.X)` tests."""
    out: Set[str] = set()
    if isinstance(test, ast.Call) and \
            isinstance(test.func, ast.Name) and \
            test.func.id == "isinstance" and len(test.args) == 2:
        types = test.args[1]
        cands = types.elts if isinstance(types, ast.Tuple) else [types]
        for t in cands:
            term = _terminal(t)
            if term:
                out.add(term)
    return out


def _branch_journal_calls(
        branch: List[ast.stmt], graph: ModuleGraph, cls: Optional[str]
) -> Tuple[List[ast.Call], List[ast.Call], List[ast.Call]]:
    """Journal-reaching calls inside `branch`, by durability shape.

    Returns ``(complete, async_only, wait_only)``: *complete* calls
    transitively reach a synchronous append OR both halves of the
    group-commit pair (append_nowait + wait_durable — self._journal);
    *async_only* reach just the enqueue (ack would race the batch
    leader's fsync); *wait_only* reach just the durable-watermark gate
    (pairs an earlier async enqueue into a complete shape).
    """
    complete: List[ast.Call] = []
    async_only: List[ast.Call] = []
    wait_only: List[ast.Call] = []
    for stmt in branch:
        for child in ast.walk(stmt):
            if not isinstance(child, ast.Call):
                continue
            target = graph.resolve(child, cls)
            if not target:
                continue
            effs = graph.transitive_effects(target)
            has_async = "journal-append-async" in effs
            has_wait = "journal-durable-wait" in effs
            if "journal-append" in effs or (has_async and has_wait):
                complete.append(child)
            elif has_async:
                async_only.append(child)
            elif has_wait:
                wait_only.append(child)
    return complete, async_only, wait_only


def _stmt_index_of(branch: List[ast.stmt], node: ast.AST) -> int:
    """Index of the top-level branch statement containing `node`."""
    for i, stmt in enumerate(branch):
        for child in ast.walk(stmt):
            if child is node:
                return i
    return -1


def check_servicer_protocol(path: str, tree: ast.Module,
                            source_lines: Sequence[str],
                            graph: ModuleGraph) -> List[Finding]:
    """journal-before-ack + servicer half of idem-key-required."""
    findings: List[Finding] = []
    servicer_classes = {info.cls for info in graph.funcs.values()
                        if info.cls and
                        info.qualname.endswith("._journal")}
    if not servicer_classes:
        return findings
    for info in graph.funcs.values():
        if info.cls not in servicer_classes or \
                info.qualname.endswith("._journal"):
            continue
        for node in ast.walk(info.node):
            if not isinstance(node, ast.If):
                continue
            verbs = _isinstance_verb(node.test)
            journaled = verbs & JOURNALED_VERBS
            if not journaled:
                continue
            verb = sorted(journaled)[0]
            branch = node.body
            complete, async_only, wait_only = _branch_journal_calls(
                branch, graph, info.cls)
            if async_only and wait_only:
                # split group-commit shape assembled IN the branch: the
                # enqueue and the watermark gate are separate helpers —
                # the wait calls are the durability completion points
                complete = complete + wait_only
            elif async_only and not complete:
                if not is_suppressed(source_lines, node.lineno,
                                     "journal-before-ack"):
                    findings.append(Finding(
                        "journal-before-ack",
                        f"servicer branch for {verb} enqueues a journal "
                        f"frame (append_nowait) but never gates the ack "
                        f"on journal.wait_durable — under group commit "
                        f"the response can leave before the batch "
                        f"leader's fsync, losing journal-before-ack",
                        path, node.lineno))
                continue
            if not complete:
                if not is_suppressed(source_lines, node.lineno,
                                     "journal-before-ack"):
                    findings.append(Finding(
                        "journal-before-ack",
                        f"servicer branch for mutating verb {verb} "
                        f"returns a response without any journal append "
                        f"— a master restart silently loses the acked "
                        f"mutation (route through self._journal)",
                        path, node.lineno))
                continue
            # ordering: the last journal call must precede the branch's
            # final return in top-level statement order
            returns = [s for s in branch if isinstance(s, ast.Return)]
            if returns:
                last_ret = returns[-1]
                j_idx = max(_stmt_index_of(branch, c) for c in complete)
                r_idx = _stmt_index_of(branch, last_ret)
                if 0 <= r_idx < j_idx and not is_suppressed(
                        source_lines, last_ret.lineno,
                        "journal-before-ack"):
                    findings.append(Finding(
                        "journal-before-ack",
                        f"servicer branch for {verb} acks (line "
                        f"{last_ret.lineno}) BEFORE its journal append — "
                        f"append must precede the response frame",
                        path, last_ret.lineno))
            if verb in IDEM_VERBS:
                # the idem key rides the APPEND call (sync or async half)
                carries = any(
                    any(kw.arg == "idem" and not (
                        isinstance(kw.value, ast.Constant)
                        and kw.value.value is None)
                        for kw in c.keywords)
                    for c in complete + async_only)
                if not carries and not is_suppressed(
                        source_lines, node.lineno, "idem-key-required"):
                    findings.append(Finding(
                        "idem-key-required",
                        f"servicer branch for {verb} journals without "
                        f"idem= — a retry crossing a master restart "
                        f"re-applies instead of replaying the recorded "
                        f"response",
                        path, node.lineno))
    return findings


# ------------------------------------------------- rule: client idem keys


def check_client_idem(path: str, tree: ast.Module,
                      source_lines: Sequence[str],
                      graph: ModuleGraph) -> List[Finding]:
    """Client half of idem-key-required: a method that ships an IDEM_VERB
    payload must pass idem= into its transport call."""
    findings: List[Finding] = []
    for info in graph.funcs.values():
        built_verbs: Set[str] = set()
        for child in ast.walk(info.node):
            if isinstance(child, ast.Call):
                term = _terminal(child.func)
                if term in IDEM_VERBS:
                    built_verbs.add(term)
        if not built_verbs:
            continue
        transport_calls = [
            c for c in ast.walk(info.node)
            if isinstance(c, ast.Call)
            and _terminal(c.func) in CLIENT_TRANSPORT_CALLS]
        if not transport_calls:
            continue  # constructing a payload without sending is not ours
        ok = any(any(kw.arg == "idem" and not (
            isinstance(kw.value, ast.Constant) and kw.value.value is None)
            for kw in c.keywords) for c in transport_calls)
        if not ok:
            line = transport_calls[0].lineno
            if not is_suppressed(source_lines, line, "idem-key-required"):
                findings.append(Finding(
                    "idem-key-required",
                    f"{info.qualname} sends mutating verb(s) "
                    f"{sorted(built_verbs)} without idem= on the "
                    f"transport call — pass idem=self._next_idem()",
                    path, line))
    return findings


# ------------------------------------------------------ rule: commit-order


def _writes_const(call: ast.Call, consts: Set[str]) -> bool:
    term = _terminal(call.func)
    if term not in ("write", "open", "write_fileobj"):
        return False
    # reads share the same callee names on storage objects — require a
    # write-mode literal for open()
    if term == "open":
        mode = ""
        if len(call.args) > 1 and isinstance(call.args[1], ast.Constant):
            mode = str(call.args[1].value)
        for kw in call.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                mode = str(kw.value.value)
        if "w" not in mode and "a" not in mode:
            return False
    return bool(_names_in(call) & consts)


def check_commit_order(path: str, tree: ast.Module,
                       source_lines: Sequence[str],
                       graph: ModuleGraph) -> List[Finding]:
    findings: List[Finding] = []
    for info in graph.funcs.values():
        body = info.node.body
        for child in ast.walk(info.node):
            if not isinstance(child, ast.Call):
                continue
            is_marker = _writes_const(child, MARKER_CONSTS)
            is_tracker = _writes_const(child, TRACKER_CONSTS)
            if not (is_marker or is_tracker):
                continue
            # effects reachable from statements BEFORE this write
            idx = _stmt_index_of(body, child)
            prior: Set[str] = set()
            for stmt in body[:idx + 1]:
                for c in ast.walk(stmt):
                    if isinstance(c, ast.Call):
                        if c is child:
                            continue
                        if c.lineno > child.lineno:
                            continue
                        target = graph.resolve(c, info.cls)
                        if target:
                            prior |= graph.transitive_effects(target)
                        term = _terminal(c.func)
                        if term in MANIFEST_PUBLISHERS:
                            prior.add("manifest-publish")
                        if term in COMMIT_EVIDENCE:
                            prior.add("commit-evidence")
                        if term in ("exists", "read") and \
                                _names_in(c) & (MARKER_CONSTS
                                                | {"MANIFEST_NAME"}):
                            prior.add("commit-evidence")
                        if _writes_const(c, MARKER_CONSTS):
                            prior.add("marker-write")
            if is_marker and "manifest-publish" not in prior:
                if not is_suppressed(source_lines, child.lineno,
                                     "commit-order"):
                    findings.append(Finding(
                        "commit-order",
                        f"{info.qualname} writes the .commit marker with "
                        f"no preceding manifest publish — the commit "
                        f"order is done-files -> manifest -> marker -> "
                        f"tracker",
                        path, child.lineno))
            if is_tracker and not prior & {"manifest-publish",
                                           "commit-evidence",
                                           "marker-write"}:
                if not is_suppressed(source_lines, child.lineno,
                                     "commit-order"):
                    findings.append(Finding(
                        "commit-order",
                        f"{info.qualname} publishes the tracker with no "
                        f"preceding manifest publish or commit evidence "
                        f"— it may point at an unverifiable generation",
                        path, child.lineno))
    return findings


# ---------------------------------------------------- rule: atomic-publish


def _resolved_path_text(call: ast.Call, info: FuncInfo) -> str:
    """Source-ish text of open()'s path arg, chasing one local assign."""
    if not call.args:
        return ""
    arg = call.args[0]
    texts = [ast.dump(arg)]
    if isinstance(arg, ast.Name):
        for child in ast.walk(info.node):
            if isinstance(child, ast.Assign):
                for t in child.targets:
                    if isinstance(t, ast.Name) and t.id == arg.id:
                        texts.append(ast.dump(child.value))
            elif isinstance(child, ast.AugAssign):
                t = child.target
                if isinstance(t, ast.Name) and t.id == arg.id:
                    texts.append(ast.dump(child.value))
    return " ".join(texts)


def check_atomic_publish(path: str, tree: ast.Module,
                         source_lines: Sequence[str],
                         graph: ModuleGraph) -> List[Finding]:
    norm = path.replace(os.sep, "/")
    if any(norm.endswith(f) for f in ATOMIC_HELPER_FILES):
        return []
    findings: List[Finding] = []
    for info in graph.funcs.values():
        for child in ast.walk(info.node):
            if not (isinstance(child, ast.Call)
                    and isinstance(child.func, ast.Name)
                    and child.func.id == "open"):
                continue
            mode = ""
            if len(child.args) > 1 and isinstance(child.args[1],
                                                  ast.Constant):
                mode = str(child.args[1].value)
            for kw in child.keywords:
                if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                    mode = str(kw.value.value)
            if "w" not in mode and "a" not in mode:
                continue
            text = _resolved_path_text(child, info).lower()
            if "tmp" in text:
                continue  # write-tmp half of the sanctioned dance
            if not any(h in text for h in PUBLISHED_HINTS):
                continue
            if is_suppressed(source_lines, child.lineno, "atomic-publish"):
                continue
            findings.append(Finding(
                "atomic-publish",
                f"{info.qualname} writes a published control file with a "
                f"raw open(..., {mode!r}) — a crash mid-write publishes "
                f"a torn file; route through storage.write (write-tmp + "
                f"fsync + rename) or write a .tmp and os.replace it",
                path, child.lineno))
    return findings


# -------------------------------------------------------- rule: lock-leak


def check_lock_leak(path: str, tree: ast.Module,
                    source_lines: Sequence[str],
                    graph: ModuleGraph) -> List[Finding]:
    norm = path.replace(os.sep, "/")
    if any(norm.endswith(f) for f in LOCK_IMPL_FILES):
        return []
    findings: List[Finding] = []
    for info in graph.funcs.values():
        acquires: List[Tuple[str, ast.Call]] = []
        released_in_finally: Set[str] = set()
        for child in ast.walk(info.node):
            if isinstance(child, ast.Call):
                term = _terminal(child.func)
                obj = _dotted(child.func)
                if term == "acquire" and obj and \
                        "lock" in obj.lower():
                    acquires.append((obj.rsplit(".", 1)[0], child))
            if isinstance(child, ast.Try):
                for stmt in child.finalbody:
                    for c in ast.walk(stmt):
                        if isinstance(c, ast.Call) and \
                                _terminal(c.func) == "release":
                            obj = _dotted(c.func)
                            if obj:
                                released_in_finally.add(
                                    obj.rsplit(".", 1)[0])
        for obj, call in acquires:
            if obj in released_in_finally:
                continue
            if is_suppressed(source_lines, call.lineno, "lock-leak"):
                continue
            findings.append(Finding(
                "lock-leak",
                f"{info.qualname} acquires {obj} without a release in a "
                f"finally — a crash mid-section leaves the cross-process "
                f"lock held until the dead-pid reaper notices (pattern: "
                f"acquire, then try: ... finally: release)",
                path, call.lineno))
    return findings


# ------------------------------------ rule: failover-frame durability


def check_failover_durability(path: str, tree: ast.Module,
                              source_lines: Sequence[str],
                              graph: ModuleGraph) -> List[Finding]:
    """The ``failover`` journal frame IS the fencing handoff (ISSUE 20):
    its "ack" is the epoch bump the promoting standby performs next, so
    it must be durable first.  Flags ``append_nowait("failover", ...)``
    in a function that never gates on ``wait_durable`` — emitted under
    the existing journal-before-ack id (same invariant, different ack
    shape)."""
    findings: List[Finding] = []
    for info in graph.funcs.values():
        async_failover: List[ast.Call] = []
        gated = False
        for child in ast.walk(info.node):
            if not isinstance(child, ast.Call):
                continue
            term = _terminal(child.func)
            if term == "wait_durable":
                gated = True
            elif term == "append_nowait" and child.args and \
                    isinstance(child.args[0], ast.Constant) and \
                    child.args[0].value == "failover":
                async_failover.append(child)
        if gated:
            continue
        for call in async_failover:
            if is_suppressed(source_lines, call.lineno,
                             "journal-before-ack"):
                continue
            findings.append(Finding(
                "journal-before-ack",
                f"{info.qualname} enqueues the failover frame with "
                f"append_nowait but never gates on wait_durable — "
                f"promoting on an un-fsynced fence frame can lose the "
                f"epoch bump across a crash and resurrect the old "
                f"leader's epoch; use the synchronous journal.append "
                f"for the failover frame",
                path, call.lineno))
    return findings


# ------------------------------------------------------------- entry point


CHECKS = (
    check_servicer_protocol,
    check_client_idem,
    check_commit_order,
    check_atomic_publish,
    check_lock_leak,
    check_failover_durability,
)


def run_paths(paths: Sequence[str],
              checkers: Optional[Sequence[str]] = None
              ) -> Tuple[List[Finding], int]:
    """Run the protocol engine over files/dirs; (findings, files_scanned).

    Same contract as ast_engine.run_paths; `checkers` filters by rule id
    (a check function contributes when ANY of its rule ids is selected —
    check_servicer_protocol emits two ids).
    """
    from .ast_engine import iter_python_files

    wanted = set(checkers) if checkers else None
    files = iter_python_files(paths)
    findings: List[Finding] = []
    for fpath in files:
        try:
            source = open(fpath).read()
            tree = ast.parse(source)
        except (OSError, SyntaxError) as e:
            findings.append(Finding("parse-error", str(e), fpath, 0))
            continue
        lines = source.splitlines()
        rel = os.path.relpath(fpath)
        graph = ModuleGraph(tree)
        _mark_effects(graph)
        for check in CHECKS:
            got = check(rel, tree, lines, graph)
            if wanted is not None:
                got = [f for f in got if f.checker in wanted]
            findings.extend(got)
    return findings, len(files)

"""graftlint CLI — ``python -m dlrover_wuqiong_tpu.analysis``.

Parity: reference `dlrover/python/elastic_agent/diagnosis/
diagnosis_agent.py:1` runs its checks inside the agent loop; here the
same contract is a standalone gate shaped like bench.py: ONE JSON line
on stdout (machine-readable for CI/driver), human findings on stderr,
exit code 1 when any rule is violated.

Engine selection: ``--engine ast`` needs no jax at all; ``--engine
jaxpr`` self-provisions a virtual CPU platform (the audit meshes need 8
devices) BEFORE jax initializes any backend, so running it on a machine
with a live TPU tunnel never touches a chip.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional


def _default_paths() -> List[str]:
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    root = os.path.dirname(pkg)
    cand = [pkg] + [os.path.join(root, p)
                    for p in ("tests", "examples", "tools", "bench.py",
                              "__graft_entry__.py")]
    return [p for p in cand if os.path.exists(p)]


def _provision_cpu(n_devices: int) -> None:
    """Force a CPU backend with enough virtual devices, pre-init.

    Mirrors tests/conftest.py: the env vars must be set before the
    backend exists, and the axon sitecustomize's jax_platforms config
    beats JAX_PLATFORMS in-process, so the explicit config update wins.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m dlrover_wuqiong_tpu.analysis",
        description="graftlint: static SPMD-correctness checks")
    parser.add_argument("--engine", choices=("jaxpr", "ast", "all"),
                        default="all")
    parser.add_argument("--devices", type=int, default=8,
                        help="virtual CPU devices for the jaxpr audit")
    parser.add_argument("--max-report", type=int, default=50,
                        help="cap on stderr finding lines")
    parser.add_argument("paths", nargs="*",
                        help="files/dirs for the AST engine "
                             "(default: the repo)")
    args = parser.parse_args(argv)

    from .findings import render_report, summarize

    t0 = time.time()
    findings = []
    engines = []
    files_scanned = 0
    if args.engine in ("ast", "all"):
        from .ast_engine import run_paths

        ast_findings, files_scanned = run_paths(
            args.paths or _default_paths())
        findings.extend(ast_findings)
        engines.append("ast")
    if args.engine in ("jaxpr", "all"):
        _provision_cpu(args.devices)
        from .jaxpr_engine import self_audit

        findings.extend(self_audit(args.devices))
        engines.append("jaxpr")

    if findings:
        print(render_report(findings, limit=args.max_report),
              file=sys.stderr)
    # bench.py contract: exactly one JSON line on stdout
    print(json.dumps({
        "graftlint": {
            "engines": engines,
            "files_scanned": files_scanned,
            "findings": len(findings),
            "by_checker": summarize(findings),
            "elapsed_s": round(time.time() - t0, 2),
            "ok": not findings,
        }
    }))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())

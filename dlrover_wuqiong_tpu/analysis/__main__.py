"""graftlint CLI — ``python -m dlrover_wuqiong_tpu.analysis``.

Parity: reference `dlrover/python/elastic_agent/diagnosis/
diagnosis_agent.py:1` runs its checks inside the agent loop; here the
same contract is a standalone gate shaped like bench.py: ONE JSON line
on stdout (machine-readable for CI/driver), human findings on stderr,
exit code 1 when any rule is violated.

Engine selection: ``--engine ast`` / ``--engine protocol`` /
``--engine concurrency`` / ``--engine schema`` need no jax at all (the
`__graft_entry__.py` pre-flight runs all four); ``--engine jaxpr`` /
``--engine hlo`` self-provision a virtual CPU platform (the
audit/budget meshes need 8 devices) BEFORE jax initializes any
backend, so running them on a machine with a live TPU tunnel never
touches a chip.  ``--changed`` restricts the file-scanning engines to
the git diff (fast CI mode; the whole-program jaxpr/hlo engines are
skipped — schema still runs: its fixed-file extraction is pure AST and
cheap).  ``--catalog`` prints the rule catalog as the one JSON line
and exits 0.  ``--format sarif`` swaps the stdout line for a SARIF
2.1.0 document (still exactly one line) so CI annotates findings in
place; exit code semantics are unchanged.  ``--update-lock``
regenerates ``analysis/schema.lock.json`` from the extracted wire
surface (forces the schema engine on) instead of diffing against it —
internal-consistency errors still gate.

The JSON schema is a compatibility contract (tests/test_analysis.py
pins it): keys are only ever ADDED to the ``graftlint`` object.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from typing import List, Optional


def _default_paths() -> List[str]:
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    root = os.path.dirname(pkg)
    cand = [pkg] + [os.path.join(root, p)
                    for p in ("tests", "examples", "tools", "bench.py",
                              "__graft_entry__.py")]
    return [p for p in cand if os.path.exists(p)]


def _changed_paths() -> List[str]:
    """Python files touched in the working tree (diff vs HEAD plus
    untracked) — the ``--changed`` fast mode's scan set."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    out: List[str] = []
    for args in (("git", "diff", "--name-only", "HEAD"),
                 ("git", "ls-files", "--others", "--exclude-standard")):
        try:
            text = subprocess.run(
                args, cwd=root, capture_output=True, text=True,
                timeout=30, check=False).stdout
        except (OSError, subprocess.TimeoutExpired):
            continue
        for rel in text.splitlines():
            if rel.endswith(".py"):
                p = os.path.join(root, rel)
                if os.path.exists(p):
                    out.append(p)
    return sorted(set(out))


def _provision_cpu(n_devices: int) -> None:
    """Force a CPU backend with enough virtual devices, pre-init.

    Mirrors tests/conftest.py: the env vars must be set before the
    backend exists, and the axon sitecustomize's jax_platforms config
    beats JAX_PLATFORMS in-process, so the explicit config update wins.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m dlrover_wuqiong_tpu.analysis",
        description="graftlint: static SPMD-correctness and "
                    "control-plane-protocol checks")
    parser.add_argument("--engine",
                        choices=("jaxpr", "ast", "protocol", "concurrency",
                                 "schema", "hlo", "all"),
                        default="all")
    parser.add_argument("--format", choices=("json", "sarif"),
                        default="json",
                        help="stdout format: the graftlint JSON line "
                             "(default) or a SARIF 2.1.0 document for CI "
                             "annotation (still one line)")
    parser.add_argument("--devices", type=int, default=8,
                        help="virtual CPU devices for the jaxpr/hlo "
                             "audits")
    parser.add_argument("--max-report", type=int, default=50,
                        help="cap on stderr finding lines")
    parser.add_argument("--changed", action="store_true",
                        help="fast mode: scan only git-diff'd .py files "
                             "with the ast+protocol+concurrency+schema "
                             "engines (jaxpr/hlo are whole-program and "
                             "are skipped)")
    parser.add_argument("--update-lock", action="store_true",
                        help="regenerate analysis/schema.lock.json from "
                             "the extracted wire surface (forces the "
                             "schema engine; deterministic sorted-keys "
                             "JSON, atomic tmp+rename) instead of "
                             "diffing against it")
    parser.add_argument("--catalog", action="store_true",
                        help="print the rule catalog as the one JSON "
                             "line and exit")
    parser.add_argument("paths", nargs="*",
                        help="files/dirs for the ast/protocol engines "
                             "(default: the repo)")
    args = parser.parse_args(argv)

    from .findings import (catalog_json, render_report, summarize,
                           summarize_severity, to_sarif)

    if args.catalog:
        print(json.dumps({"graftlint_catalog": catalog_json()}))
        return 0

    t0 = time.monotonic()
    findings = []
    engines = []
    files_scanned = 0
    hlo_measured = {}
    if args.changed:
        scan_paths = args.paths or _changed_paths()
        run_file_engines = bool(scan_paths)
        run_trace_engines = False
    else:
        scan_paths = args.paths or _default_paths()
        run_file_engines = True
        run_trace_engines = True
    if args.engine in ("ast", "all") and run_file_engines:
        from .ast_engine import run_paths

        ast_findings, files_scanned = run_paths(scan_paths)
        findings.extend(ast_findings)
        engines.append("ast")
    if args.engine in ("protocol", "all") and run_file_engines:
        from .protocol_engine import run_paths as run_protocol

        proto_findings, n_files = run_protocol(scan_paths)
        files_scanned = max(files_scanned, n_files)
        findings.extend(proto_findings)
        engines.append("protocol")
    if args.engine in ("concurrency", "all") and run_file_engines:
        from .concurrency_engine import run_paths as run_concurrency

        conc_findings, n_files = run_concurrency(scan_paths)
        files_scanned = max(files_scanned, n_files)
        findings.extend(conc_findings)
        engines.append("concurrency")
    schema_summary = None
    if (args.engine in ("schema", "all") and run_file_engines) \
            or args.update_lock:
        from .schema_engine import run_schema

        schema_findings, schema_summary = run_schema(
            update_lock=args.update_lock)
        findings.extend(schema_findings)
        engines.append("schema")
    if args.engine in ("jaxpr", "all") and run_trace_engines:
        _provision_cpu(args.devices)
        from .jaxpr_engine import self_audit

        findings.extend(self_audit(args.devices))
        engines.append("jaxpr")
    if args.engine in ("hlo", "all") and run_trace_engines:
        _provision_cpu(args.devices)
        from .hlo_budget import budget_audit

        hlo_findings, hlo_measured = budget_audit(args.devices)
        findings.extend(hlo_findings)
        engines.append("hlo")

    if findings:
        print(render_report(findings, limit=args.max_report),
              file=sys.stderr)
    gating = [f for f in findings if f.severity != "warning"]
    if args.format == "sarif":
        # one-line SARIF 2.1.0 document instead of the graftlint object;
        # same exit-code semantics so CI gates identically.
        print(json.dumps(to_sarif(findings)))
        return 1 if gating else 0
    # bench.py contract: exactly one JSON line on stdout.  Schema
    # evolution is ADD-ONLY (tests/test_analysis.py pins it); the
    # ``schema`` section only appears when the schema engine ran.
    record = {
        "engines": engines,
        "files_scanned": files_scanned,
        "findings": len(findings),
        "by_checker": summarize(findings),
        "by_severity": summarize_severity(findings),
        "hlo_collectives": {
            tag: {op: dict(v) for op, v in sorted(ops.items())}
            for tag, ops in sorted(hlo_measured.items())},
        "elapsed_s": round(time.monotonic() - t0, 2),
        "ok": not gating,
    }
    if schema_summary is not None:
        record["schema"] = schema_summary
    print(json.dumps({"graftlint": record}))
    return 1 if gating else 0


if __name__ == "__main__":
    sys.exit(main())

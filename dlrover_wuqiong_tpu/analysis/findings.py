"""Finding model and rule catalog shared by all graftlint engines.

Parity: reference `dlrover/python/diagnosis/common/diagnosis_action.py`
style typed results (the runtime diagnosis stack reports observations as
structured objects, `diagnosis/diagnostician.py:1` here) — graftlint moves
the same idea BEFORE execution: each hard-won SPMD rule from CLAUDE.md
becomes a checker that emits `Finding`s from a trace or an AST instead of
from a crashed job.  Dependency-free on purpose: the AST and protocol
engines must be importable without initializing jax
(`__graft_entry__.py` pre-flight).

v2 additions: severity levels (``error`` gates, ``warning`` reports),
the machine-readable RULE_CATALOG (one entry per rule id — the README
rule-catalog section and ``--catalog`` both render from it), and the
suppression grammar: an inline ``# graftlint: disable=<ids> -- <reason>``
must carry a reason string after ``--`` or the suppression itself is a
finding (`suppression-no-reason`).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Set

SEVERITIES = ("error", "warning")


@dataclasses.dataclass
class Finding:
    """One rule violation, anchored to a file:line when known."""

    checker: str          # e.g. "env-at-trace"
    message: str          # human-readable, names the offending symbol
    path: str = ""        # repo-relative when possible
    line: int = 0         # 1-based; 0 = not file-anchored (jaxpr findings)
    rule: str = ""        # the CLAUDE.md rule this enforces, one line
    severity: str = ""    # "error" | "warning"; "" = look up the catalog

    def __post_init__(self):
        if not self.severity:
            entry = RULE_CATALOG.get(self.checker)
            self.severity = entry["severity"] if entry else "error"

    def location(self) -> str:
        if self.path and self.line:
            return f"{self.path}:{self.line}"
        return self.path or "<trace>"

    def format(self) -> str:
        return (f"{self.location()}: {self.severity}: "
                f"[{self.checker}] {self.message}")


def summarize(findings: List[Finding]) -> Dict[str, int]:
    """Per-checker counts for the single-line JSON summary."""
    out: Dict[str, int] = {}
    for f in findings:
        out[f.checker] = out.get(f.checker, 0) + 1
    return dict(sorted(out.items()))


def summarize_severity(findings: List[Finding]) -> Dict[str, int]:
    """Per-severity counts ({"error": n, "warning": m}) for the JSON line."""
    out: Dict[str, int] = {}
    for f in findings:
        sev = f.severity if f.severity in SEVERITIES else "error"
        out[sev] = out.get(sev, 0) + 1
    return dict(sorted(out.items()))


def render_report(findings: List[Finding],
                  limit: Optional[int] = None) -> str:
    lines = [f.format() for f in findings[:limit]]
    if limit is not None and len(findings) > limit:
        lines.append(f"... and {len(findings) - limit} more")
    return "\n".join(lines)


# ------------------------------------------------------------ suppressions

#: ``# graftlint: disable=rule-a,rule-b -- why this is sanctioned here``
#: The reason after ``--`` is REQUIRED: a reason-less disable still
#: suppresses (so the fix is additive) but emits `suppression-no-reason`.
DISABLE_RE = re.compile(
    r"graftlint:\s*disable=([\w,-]+)(?:\s*--\s*(\S.*))?")


def suppressed_checkers(line_text: str) -> Set[str]:
    """Rule ids disabled by an inline comment on `line_text` ('' = none)."""
    m = DISABLE_RE.search(line_text)
    if not m:
        return set()
    return {c.strip() for c in m.group(1).split(",") if c.strip()}


def is_suppressed(source_lines: Sequence[str], line: int,
                  checker: str) -> bool:
    """True when the 1-based `line` carries a disable for `checker`."""
    if not (1 <= line <= len(source_lines)):
        return False
    return checker in suppressed_checkers(source_lines[line - 1])


def check_suppression_reasons(path: str,
                              source_lines: Sequence[str]) -> List[Finding]:
    """Every inline disable must carry a ``-- reason`` tail.

    Run by the AST engine only (one pass per file) so `--engine all`
    does not double-report files both engines scan.
    """
    findings: List[Finding] = []
    for i, text in enumerate(source_lines, start=1):
        m = DISABLE_RE.search(text)
        if m and not m.group(2):
            findings.append(Finding(
                "suppression-no-reason",
                f"inline suppression of {m.group(1)!r} has no reason — "
                f"write '# graftlint: disable={m.group(1)} -- <why this "
                f"is sanctioned here>'",
                path=path, line=i,
                rule=RULE_CATALOG["suppression-no-reason"]["rationale"]))
    return findings


# ------------------------------------------------------------ rule catalog

#: id -> {engine, severity, rationale}.  The single source of truth the
#: README catalog, ``--catalog`` and Finding.severity defaults render
#: from; tests assert README and catalog stay in sync.
RULE_CATALOG: Dict[str, Dict[str, str]] = {
    # ---- ast engine (intra-file pattern rules, jax-free)
    "env-at-trace": {
        "engine": "ast", "severity": "error",
        "rationale": "os.getenv of a trace-time toggle (DWT_FA_*) inside "
                     "jitted code bakes one process's env into shared HLO; "
                     "read toggles at module scope and close over them",
    },
    "env-flip-outside-tuner": {
        "engine": "ast", "severity": "error",
        "rationale": "raw os.environ writes of TRACE_ENV_VARS names skip "
                     "the tuner's save-restore and compile-cache re-key — "
                     "flip variants only through auto/tuner.py "
                     "variant_env/apply_variant",
    },
    "donated-reuse": {
        "engine": "ast", "severity": "error",
        "rationale": "train_step/apply_sparse_update DONATE their inputs — "
                     "reusing an argument you passed in reads freed memory",
    },
    "blocking-readback": {
        "engine": "ast", "severity": "error",
        "rationale": "unconditional float()/np.asarray() on step outputs in "
                     "a train loop defeats fused dispatch — sync once per "
                     "fusion via the metrics readback",
    },
    "raw-rpc-call": {
        "engine": "ast", "severity": "error",
        "rationale": "every control-plane socket touch routes through "
                     "retry_call (ONE retry policy); raw dials outside "
                     "common/comm.py bypass backoff, jitter and deadlines",
    },
    "fork-after-jax": {
        "engine": "ast", "severity": "error",
        "rationale": "fork from a JAX-initialized process deadlocks XLA "
                     "runtime threads; spawn, never fork",
    },
    "cache-key-env": {
        "engine": "ast", "severity": "error",
        "rationale": "a framework cache key over a jitted step must fold in "
                     "the trace-time env toggles or warm entries are claimed "
                     "for HLO the XLA layer then misses",
    },
    "unverified-restore": {
        "engine": "ast", "severity": "error",
        "rationale": "restore paths must digest-verify storage/shm/replica "
                     "bytes before device_put/restore_pytree — the "
                     "sanctioned route is engine.load",
    },
    "suppression-no-reason": {
        "engine": "ast", "severity": "error",
        "rationale": "inline disables must record WHY the rule is "
                     "sanctioned at that line, or the suppression outlives "
                     "its justification",
    },
    "control-plane-hygiene": {
        "engine": "ast", "severity": "error",
        "rationale": "typed JSON frames only on the agent-master path (no "
                     "pickle), and spawn, never fork, from JAX-initialized "
                     "processes",
    },
    "docstring-citation": {
        "engine": "ast", "severity": "error",
        "rationale": "every package module docstring cites the reference "
                     "file:line it matches so behavior parity stays "
                     "auditable",
    },
    "wall-clock-duration": {
        "engine": "ast", "severity": "warning",
        "rationale": "time.time() in elapsed/deadline arithmetic drifts "
                     "under NTP slew and host suspend — duration math runs "
                     "on time.monotonic(); wall clock is only for "
                     "persisted or cross-process timestamps",
    },
    # ---- protocol engine (interprocedural, per-module call graph)
    "journal-before-ack": {
        "engine": "protocol", "severity": "error",
        "rationale": "a mutating servicer verb acked before its journal "
                     "append is a mutation a master restart silently loses; "
                     "append must dominate the success return",
    },
    "idem-key-required": {
        "engine": "protocol", "severity": "error",
        "rationale": "mutating client verbs retried across a master restart "
                     "re-apply unless an idempotency key rides the frame "
                     "end to end (client call AND servicer journal)",
    },
    "commit-order": {
        "engine": "protocol", "severity": "error",
        "rationale": "checkpoint commit is atomic BY ORDER (done-files -> "
                     "manifest -> marker -> tracker); a marker/tracker "
                     "write with no preceding manifest publish (or commit "
                     "evidence) publishes an unverifiable generation",
    },
    "atomic-publish": {
        "engine": "protocol", "severity": "error",
        "rationale": "published control files (manifest/tracker/marker/"
                     "spec/...) must go through write-tmp+fsync+rename "
                     "(storage.write); a raw open(path, 'w') can tear",
    },
    "lock-leak": {
        "engine": "protocol", "severity": "error",
        "rationale": "a SharedLock acquire whose release is not in a "
                     "finally wedges the next worker generation for the "
                     "full timeout when this process dies mid-section",
    },
    # ---- concurrency engine (lock discipline + shared-state races)
    "blocking-under-lock": {
        "engine": "concurrency", "severity": "error",
        "rationale": "a socket dial/RPC/retry_call/fsync/sleep/subprocess "
                     "spawn reachable inside a lock-held span turns a slow "
                     "or dead peer into a wedge for every waiter — the PR 1 "
                     "(SIGKILLed SharedLock holder, 600s SAVE_TIMEOUT "
                     "stall) and PR 4 (replica dial-under-lock, 150s RPC "
                     "floor) outage shape; copy under the lock, send after "
                     "release",
    },
    "lock-order-cycle": {
        "engine": "concurrency", "severity": "error",
        "rationale": "lock A held while acquiring B adds ordering edge "
                     "A->B; a cycle in the per-module edge graph means two "
                     "threads entering from opposite ends deadlock — "
                     "impose one global acquisition order",
    },
    "unguarded-shared-state": {
        "engine": "concurrency", "severity": "error",
        "rationale": "a self.X mutated in a Thread(target=self._run) "
                     "worker and also written elsewhere with no common "
                     "lock (or read under a lock the worker write does "
                     "not hold) is a data race the GIL does not save you "
                     "from",
    },
    "thread-lifecycle": {
        "engine": "concurrency", "severity": "warning",
        "rationale": "a non-daemon Thread started with no join() on any "
                     "shutdown path hangs process exit — exactly how a "
                     "'finished' job keeps its pod alive; mark it daemon "
                     "or join it from stop()",
    },
    # ---- schema engine (wire-schema compatibility vs the lockfile)
    "schema-removed": {
        "engine": "schema", "severity": "error",
        "rationale": "a wire message/field/registry member/verb/replayed "
                     "journal kind present in schema.lock.json is gone — "
                     "old-generation peers still send it and old journals "
                     "still hold it; ADD-ONLY schemas never remove",
    },
    "schema-renamed": {
        "engine": "schema", "severity": "error",
        "rationale": "a locked name was replaced by a new one at the "
                     "same ordinal slot — a rename is a remove+add on "
                     "the wire; add the new name alongside and keep the "
                     "old one decoding",
    },
    "schema-default-changed": {
        "engine": "schema", "severity": "error",
        "rationale": "frames from old peers OMIT defaulted fields — "
                     "changing the default silently changes what those "
                     "frames mean on decode (sentinels like 0/-1/'' are "
                     "part of the wire contract)",
    },
    "schema-field-no-sentinel": {
        "engine": "schema", "severity": "error",
        "rationale": "the codec drops unknown fields on decode, so "
                     "mixed-generation decode only works when every "
                     "message field has a no-change default; a "
                     "sentinel-less field breaks rolling upgrades",
    },
    "schema-lock-stale": {
        "engine": "schema", "severity": "error",
        "rationale": "the extracted wire surface differs from the "
                     "committed schema.lock.json — additions are legal "
                     "but must be locked in the same PR (--update-lock) "
                     "so the schema delta is a reviewed diff",
    },
    "schema-lock-corrupt": {
        "engine": "schema", "severity": "warning",
        "rationale": "schema.lock.json is unreadable — the engine "
                     "re-extracts and skips the diff rather than "
                     "failing the gate on a torn artifact; regenerate "
                     "with --update-lock",
    },
    "journal-kind-unreplayed": {
        "engine": "schema", "severity": "error",
        "rationale": "a journal kind the servicer/master appends with "
                     "no replay branch in _apply_entry is silent state "
                     "loss at the next failover — every acked mutation "
                     "of that kind vanishes on restart",
    },
    "snapshot-asymmetric": {
        "engine": "schema", "severity": "warning",
        "rationale": "a snapshot key exported by _journal_state but "
                     "never read by _restore_snapshot (or vice versa) "
                     "means compaction silently drops state — the "
                     "export/restore key sets must stay symmetric",
    },
    # ---- jaxpr engine (trace-level)
    "collective-in-cond": {
        "engine": "jaxpr", "severity": "error",
        "rationale": "collectives under lax.cond with a shard-varying "
                     "predicate deadlock the rendezvous; compute "
                     "unconditionally and mask with jnp.where",
    },
    "remat-noop": {
        "engine": "jaxpr", "severity": "error",
        "rationale": "remat with prevent_cse=False under a python layer "
                     "loop is silently undone by XLA CSE",
    },
    "donation-alias": {
        "engine": "jaxpr", "severity": "error",
        "rationale": "donating a pinned_host input onto a device output is "
                     "rejected by the runtime; optimizer_offload must "
                     "disable donation",
    },
    "host-kind-out-shardings": {
        "engine": "jaxpr", "severity": "error",
        "rationale": "jit out_shardings with a host memory kind trips the "
                     "SPMD partitioner; init on device then device_put",
    },
    "self-audit": {
        "engine": "jaxpr", "severity": "warning",
        "rationale": "the self-audit harness could not build its meshes — "
                     "coverage gap, not a rule violation",
    },
    # ---- hlo budget engine (lowered-HLO communication budgets)
    "collective-budget": {
        "engine": "hlo", "severity": "error",
        "rationale": "an extra all-gather/reduce-scatter/all-reduce/"
                     "collective-permute in the lowered step vs the "
                     "checked-in analytic budget is the classic silent "
                     "GSPMD perf regression (ROADMAP item 5 gate)",
    },
    "budget-coverage": {
        "engine": "hlo", "severity": "warning",
        "rationale": "a budgeted strategy could not be lowered in this "
                     "environment — the budget was not checked, which is "
                     "a coverage gap, not a regression",
    },
}


def catalog_json() -> Dict[str, Dict[str, str]]:
    """Stable-ordered catalog for ``--catalog`` and the schema test."""
    return {k: dict(RULE_CATALOG[k]) for k in sorted(RULE_CATALOG)}


# ------------------------------------------------------------------ sarif


def to_sarif(findings: List[Finding]) -> Dict:
    """Serialize findings as a SARIF 2.1.0 document (``--format sarif``).

    Rules render from RULE_CATALOG (the same single source of truth as
    ``--catalog``/README) so CI annotations carry the rationale; findings
    with no file anchor (jaxpr trace findings) omit the location.  Only
    rules that actually fired are listed, keeping the document — and the
    one-line stdout contract — small.
    """
    fired = sorted({f.checker for f in findings})
    rules = []
    for rid in fired:
        entry = RULE_CATALOG.get(rid, {})
        rules.append({
            "id": rid,
            "shortDescription": {"text": entry.get("rationale", rid)},
            "properties": {"engine": entry.get("engine", "unknown")},
            "defaultConfiguration": {
                "level": entry.get("severity", "error")},
        })
    results = []
    for f in findings:
        res = {
            "ruleId": f.checker,
            "level": f.severity if f.severity in SEVERITIES else "error",
            "message": {"text": f.message},
        }
        if f.path:
            region = {"startLine": f.line} if f.line else {}
            loc = {"physicalLocation": {
                "artifactLocation": {"uri": f.path.replace("\\", "/")}}}
            if region:
                loc["physicalLocation"]["region"] = region
            res["locations"] = [loc]
        results.append(res)
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "graftlint",
                "informationUri":
                    "https://github.com/intelligent-machine-learning/"
                    "dlrover",
                "rules": rules,
            }},
            "results": results,
        }],
    }

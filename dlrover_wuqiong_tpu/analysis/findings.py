"""Finding model shared by both graftlint engines.

Parity: reference `dlrover/python/diagnosis/common/diagnosis_action.py`
style typed results (the runtime diagnosis stack reports observations as
structured objects, `diagnosis/diagnostician.py:1` here) — graftlint moves
the same idea BEFORE execution: each hard-won SPMD rule from CLAUDE.md
becomes a checker that emits `Finding`s from a trace or an AST instead of
from a crashed job.  Dependency-free on purpose: the AST engine must be
importable without initializing jax (`__graft_entry__.py` pre-flight).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional


@dataclasses.dataclass
class Finding:
    """One rule violation, anchored to a file:line when known."""

    checker: str          # e.g. "env-at-trace"
    message: str          # human-readable, names the offending symbol
    path: str = ""        # repo-relative when possible
    line: int = 0         # 1-based; 0 = not file-anchored (jaxpr findings)
    rule: str = ""        # the CLAUDE.md rule this enforces, one line

    def location(self) -> str:
        if self.path and self.line:
            return f"{self.path}:{self.line}"
        return self.path or "<trace>"

    def format(self) -> str:
        return f"{self.location()}: [{self.checker}] {self.message}"


def summarize(findings: List[Finding]) -> Dict[str, int]:
    """Per-checker counts for the single-line JSON summary."""
    out: Dict[str, int] = {}
    for f in findings:
        out[f.checker] = out.get(f.checker, 0) + 1
    return dict(sorted(out.items()))


def render_report(findings: List[Finding],
                  limit: Optional[int] = None) -> str:
    lines = [f.format() for f in findings[:limit]]
    if limit is not None and len(findings) > limit:
        lines.append(f"... and {len(findings) - limit} more")
    return "\n".join(lines)

"""graftlint — static analysis enforcing the repo's hard-won SPMD rules.

Parity: reference `dlrover/python/diagnosis/` + `elastic_agent/monitor/`
(error_monitor.py:1, node_check.py:1) diagnose distributed failures at
RUNTIME; graftlint moves the TPU-costly bug classes to a pre-execution
contract.  Six engines share one finding model + rule catalog
(findings.RULE_CATALOG):

- `ast_engine` scans source text: trace-time ``DWT_*`` env reads
  missing from the compile-cache key, donated-buffer reuse,
  control-plane pickle/fork hygiene, module docstring citations.
- `protocol_engine` checks interprocedural control-plane invariants
  over a per-module call graph: journal-before-ack, idem keys,
  commit ordering, atomic publishes, lock leaks.
- `concurrency_engine` checks lock discipline on the same call-graph
  machinery: blocking-under-lock, lock-order cycles, unguarded
  shared state across threads, thread lifecycles.
- `schema_engine` extracts the full wire surface (message dataclasses,
  ADD-ONLY registries, verb classes, journal kinds vs replay branches,
  snapshot export/restore keys) and diffs it against the committed
  `analysis/schema.lock.json` — removals/renames/default changes are
  errors; additions require ``--update-lock``.
- `jaxpr_engine` inspects traced train steps without executing them:
  collective-in-cond deadlocks, CSE-undone remat, donation vs
  optimizer_offload aliasing, host-kind out_shardings.
- `hlo_budget` AOT-lowers the real train step per strategy and audits
  collective-op counts against checked-in analytic budgets.

CLI: ``python -m dlrover_wuqiong_tpu.analysis [--engine
jaxpr|ast|protocol|concurrency|schema|hlo|all] [--format json|sarif]
[--update-lock] [path...]`` — single-line JSON (or SARIF) summary on
stdout (bench.py contract), file:line findings on stderr, exit 1 on
any non-warning finding.  This module and the
ast/protocol/concurrency/schema engines import no jax so
``__graft_entry__.py`` can pre-flight them before any backend
initialization; jaxpr/hlo are imported lazily.
"""

from .ast_engine import run_paths as run_ast_engine  # noqa: F401
from .findings import Finding, render_report, summarize  # noqa: F401


def run_jaxpr_engine(n_devices: int = 8):
    """Lazy Engine A entry — imports jax on first use."""
    from .jaxpr_engine import self_audit

    return self_audit(n_devices)

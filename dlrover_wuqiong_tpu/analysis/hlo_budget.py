"""graftlint HLO budget engine: collective-communication regression gate.

Parity: no reference counterpart — reference dlrover treats communication
volume as a runtime observable (profiler dashboards); a sneaked-in extra
all-gather shows up as a throughput dip nobody attributes.  Here the
classic silent GSPMD regression — a model/step change that makes the
partitioner insert an extra collective or re-replicate a sharded tensor —
is caught at lint time: the engine lowers the repo's REAL
`make_train_step` per strategy on the self-provisioned CPU mesh (the
jaxpr engine's self-audit harness, same tiny GPTConfig), compiles it,
counts the collective ops and their payload bytes in the optimized HLO,
and compares against the checked-in analytic budgets below.  ROADMAP
item 5's perf-gap work gets a gate: a strategy exceeding its budget is a
`collective-budget` finding.

Backend note: XLA:CPU's SPMD expansion lowers all-gather/reduce-scatter
into all-reduce-based patterns, so the op MIX here is backend-specific —
budgets are keyed to this harness (same jax, same mesh, same model) and
are exact-count pins, not TPU predictions.  What IS transferable: the
count deltas.  An edit that adds one all-gather per layer on TPU adds
the same +N ops here.  Bytes budgets carry ~5% headroom (layout padding
may shift with XLA point releases); counts are pinned exactly.

Budget provenance (GPTConfig vocab=256, n_layer=2, n_head=4, n_embd=64,
block=32, 118,528 params, f32, 8 virtual CPU devices):

- ``fsdp`` (mesh fsdp8): every param (13 leaves) is gathered for fwd and
  for bwd and every grad reduce-scattered, each lowered to all-reduce on
  CPU, plus the loss/grad-norm scalar reductions — 65 all-reduce,
  ~2.78 MB/step measured.
- ``dp-tp`` (mesh dp4xtp2): grads all-reduce over dp (13 leaves) + tp
  activation reductions + scalar reductions = 28 all-reduce; the tp=2
  attention/mlp boundary contributes 12 collective-permutes (CPU's
  expansion of the tp all-gathers), ~1.4 MB/step total measured.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Tuple

from .findings import Finding

#: collective op names counted in the optimized HLO (async `-start`
#: halves count once; `-done` is ignored).
COLLECTIVE_OPS = ("all-gather", "all-reduce", "reduce-scatter",
                  "collective-permute", "all-to-all")

_OP_RE = re.compile(
    r"=\s*((?:\([^)]*\)|[a-z0-9]+\[[^\]]*\](?:\{[^}]*\})?))\s+"
    r"(all-gather|all-reduce|reduce-scatter|collective-permute|"
    r"all-to-all)(?:-start)?\(")
_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
_DTYPE_BYTES = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "f8e4m3fn": 1,
                "f8e5m2": 1, "s64": 8, "u64": 8, "s32": 4, "u32": 4,
                "s16": 2, "u16": 2, "s8": 1, "u8": 1, "pred": 1}


def count_collectives(hlo_text: str) -> Dict[str, Dict[str, int]]:
    """{op: {"count": n, "bytes": b}} over an optimized-HLO dump.

    Bytes are the op's OUTPUT payload (tuple outputs summed) — a proxy
    for wire traffic that is exact for all-reduce/permute and a lower
    bound for gathers.
    """
    out: Dict[str, Dict[str, int]] = {}
    for m in _OP_RE.finditer(hlo_text):
        shape_txt, op = m.group(1), m.group(2)
        nbytes = 0
        for dt, dims in _SHAPE_RE.findall(shape_txt):
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            nbytes += n * _DTYPE_BYTES.get(dt, 4)
        ent = out.setdefault(op, {"count": 0, "bytes": 0})
        ent["count"] += 1
        ent["bytes"] += nbytes
    return out


#: checked-in analytic budgets (see module docstring for provenance).
#: "max_count" is an exact pin of the measured lowering; "max_bytes"
#: carries ~5% layout-padding headroom.  An op kind that appears in the
#: lowering but not in the budget is ALWAYS a finding (an unexpected
#: collective kind is exactly the regression this gate exists for).
BUDGETS: Dict[str, Dict] = {
    "fsdp": {
        "strategy": [("fsdp", {})],
        "accum": 1,
        "ops": {
            "all-reduce": {"max_count": 65, "max_bytes": 2_920_000},
        },
    },
    "dp-tp": {
        "strategy": [("data_parallel", {"size": 4}),
                     ("tensor_parallel", {"size": 2})],
        "accum": 1,
        "ops": {
            "all-reduce": {"max_count": 28, "max_bytes": 830_000},
            "collective-permute": {"max_count": 12, "max_bytes": 690_000},
        },
    },
}


def lower_case_hlo(strategy: Sequence, accum: int,
                   n_devices: int = 8) -> str:
    """Optimized HLO text of the repo's real train step for `strategy`.

    Mirrors jaxpr_engine.self_audit: tiny GPTConfig, materialize=False
    (abstract ShapeDtypeStruct state — AOT lower+compile only, no
    parameter materialization, no dispatch)."""
    import jax
    import jax.numpy as jnp

    from ..auto.accelerate import auto_accelerate
    from ..models.gpt import GPT, GPTConfig

    devices = list(jax.devices("cpu"))[:n_devices]
    if len(devices) < n_devices:
        raise RuntimeError(
            f"need {n_devices} cpu devices for the budget meshes, have "
            f"{len(devices)} — run under "
            f"XLA_FLAGS=--xla_force_host_platform_device_count=8")
    cfg = GPTConfig(vocab_size=256, n_layer=2, n_head=4, n_embd=64,
                    block_size=32, dtype=jnp.float32)
    res = auto_accelerate(GPT(cfg), strategy=list(strategy),
                          devices=devices, materialize=False)
    shape = (8, cfg.block_size) if accum == 1 else \
        (accum, 8, cfg.block_size)
    batch = {"input_ids": jax.ShapeDtypeStruct(shape, jnp.int32),
             "labels": jax.ShapeDtypeStruct(shape, jnp.int32)}
    return res.train_step.lower(res.state, batch).compile().as_text()


def check_budget(tag: str, counts: Dict[str, Dict[str, int]],
                 budget: Dict) -> List[Finding]:
    """Compare measured collective counts/bytes against one budget."""
    findings: List[Finding] = []
    ops = budget["ops"]
    for op, got in sorted(counts.items()):
        allowed = ops.get(op)
        if allowed is None:
            findings.append(Finding(
                "collective-budget",
                f"[{tag}] unexpected collective kind {op} x{got['count']} "
                f"({got['bytes']} B) — not in the checked-in budget; a "
                f"code change made the partitioner insert new "
                f"communication",
                path="hlo:" + tag))
            continue
        if got["count"] > allowed["max_count"]:
            findings.append(Finding(
                "collective-budget",
                f"[{tag}] {op} count {got['count']} exceeds budget "
                f"{allowed['max_count']} — an extra collective sneaked "
                f"into the lowered step (bytes {got['bytes']})",
                path="hlo:" + tag))
        if got["bytes"] > allowed["max_bytes"]:
            findings.append(Finding(
                "collective-budget",
                f"[{tag}] {op} payload {got['bytes']} B exceeds budget "
                f"{allowed['max_bytes']} B at count {got['count']} — "
                f"same op count moving more data usually means a "
                f"re-replicated operand",
                path="hlo:" + tag))
    return findings


def budget_audit(n_devices: int = 8,
                 budgets: Optional[Dict[str, Dict]] = None
                 ) -> Tuple[List[Finding], Dict[str, Dict]]:
    """Lower+compile every budgeted strategy and gate on the budgets.

    Returns (findings, measured) — `measured` maps tag -> per-op counts
    so the CLI can surface the numbers even when the gate passes.
    An environment that cannot build a case (e.g. too few devices)
    yields a `budget-coverage` WARNING, not silent skippage.
    """
    budgets = BUDGETS if budgets is None else budgets
    findings: List[Finding] = []
    measured: Dict[str, Dict] = {}
    for tag, budget in sorted(budgets.items()):
        try:
            text = lower_case_hlo(budget["strategy"], budget.get(
                "accum", 1), n_devices=n_devices)
        except RuntimeError as e:
            findings.append(Finding(
                "budget-coverage",
                f"[{tag}] budget not checked: {e}",
                path="hlo:" + tag))
            continue
        counts = count_collectives(text)
        measured[tag] = counts
        findings.extend(check_budget(tag, counts, budget))
    return findings, measured
